"""Recursive-descent MySQL parser (ref: pkg/parser/parser.y — 16.5k-line
goyacc grammar; this covers the dialect subset the engine executes: full
TPC-H SELECT shape, DML, DDL, txn control, SHOW/SET/EXPLAIN/ANALYZE/ADMIN,
prepared statements, BACKUP/RESTORE).

Expression precedence mirrors MySQL (ref: parser.y precedence decls):
  OR < XOR < AND < NOT < comparison/IS/IN/LIKE/BETWEEN < | < & < shifts
  < +- < */%  < ^ < unary < collate.
"""

from __future__ import annotations

from . import ast as A
from .lexer import LexError, T, Token, tokenize


class ParseError(ValueError):
    pass


# Keywords that stop an alias from being swallowed.
_RESERVED_AFTER_EXPR = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "UNION", "JOIN",
    "INNER", "LEFT", "RIGHT", "CROSS", "ON", "USING", "AND", "OR", "XOR",
    "NOT", "AS", "ASC", "DESC", "INTO", "FOR", "SET", "WHEN", "THEN",
    "ELSE", "END", "BETWEEN", "LIKE", "IN", "IS", "EXISTS", "CASE",
    "STRAIGHT_JOIN", "NATURAL", "OFFSET", "LOCK", "VALUES", "WITH",
    "INTERVAL", "REGEXP", "RLIKE", "DIV", "MOD", "COLLATE", "DUPLICATE",
    "EXCEPT", "INTERSECT", "TABLESAMPLE",
    "KEY", "UPDATE", "ALL", "ANY", "SOME", "ESCAPE", "OVER", "WINDOW",
}

_TABLE_OPTION_KWS = {
    "ENGINE", "AUTO_INCREMENT", "CHARSET", "CHARACTER", "COLLATE", "COMMENT",
    "DEFAULT", "TTL", "TTL_ENABLE", "TTL_JOB_INTERVAL", "AUTO_ID_CACHE",
    "AUTO_RANDOM_BASE", "SHARD_ROW_ID_BITS", "PRE_SPLIT_REGIONS",
    "KEY_BLOCK_SIZE", "STATS_PERSISTENT", "STATS_AUTO_RECALC",
    "STATS_SAMPLE_PAGES", "MAX_ROWS", "MIN_ROWS", "AVG_ROW_LENGTH",
    "CHECKSUM", "DELAY_KEY_WRITE", "ROW_FORMAT", "COMPRESSION", "CONNECTION",
    "PACK_KEYS", "STATS_BUCKETS", "STATS_TOPN", "STATS_COL_CHOICE",
    "STATS_COL_LIST", "STATS_SAMPLE_RATE", "INSERT_METHOD",
    "SECONDARY_ENGINE", "PLACEMENT", "AUTOEXTEND_SIZE", "ENCRYPTION",
}

_AGG_FUNCS = {
    "count", "sum", "avg", "min", "max", "group_concat", "bit_and",
    "bit_or", "bit_xor", "std", "stddev", "stddev_pop", "stddev_samp",
    "var_pop", "var_samp", "variance", "approx_count_distinct",
}

_TYPE_NAMES = {
    "tinyint", "smallint", "mediumint", "int", "integer", "bigint",
    "float", "double", "real", "decimal", "numeric", "dec", "fixed",
    "char", "varchar", "binary", "varbinary", "text", "tinytext",
    "mediumtext", "longtext", "blob", "tinyblob", "mediumblob", "longblob",
    "date", "datetime", "timestamp", "time", "year", "bit", "bool",
    "boolean", "enum", "set", "json", "signed", "unsigned",
}


def parse(sql: str) -> list:
    """Parse one or more ;-separated statements."""
    return Parser(sql).parse_statements()


def parse_one(sql: str):
    stmts = parse(sql)
    if len(stmts) != 1:
        raise ParseError(f"expected one statement, got {len(stmts)}")
    return stmts[0]


def parse_expr(text: str) -> A.ExprNode:
    p = Parser(f"SELECT {text}")
    stmt = p.parse_statements()[0]
    return stmt.fields[0].expr


def _parse_hints(text: str) -> list:
    """/*+ NAME(args), NAME2() */ body -> [(name_lower, [arg strings])]
    (ref: pkg/util/hint hintparser — the subset the planner consumes;
    unknown hints pass through and are ignored there)."""
    import re as _re

    out = []
    for m in _re.finditer(r"([A-Za-z_][A-Za-z0-9_]*)\s*(?:\(([^()]*)\))?", text):
        name = m.group(1).lower()
        raw = (m.group(2) or "").strip()
        args = [a.strip().strip("`'\"") for a in _re.split(r"[,\s]+", raw) if a.strip()] if raw else []
        out.append((name, args))
    return out


class Parser:
    def __init__(self, sql: str):
        self._named_window_refs: list = []
        self.sql = sql
        try:
            self.toks = tokenize(sql)
        except LexError as e:
            raise ParseError(str(e)) from e
        self.i = 0
        self.n_params = 0

    # ---- token helpers ----
    def peek(self, ahead: int = 0) -> Token:
        j = min(self.i + ahead, len(self.toks) - 1)
        return self.toks[j]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind is not T.EOF:
            self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind is T.IDENT and t.upper in kws

    def eat_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.i += 1
            return True
        return False

    def expect_kw(self, kw: str):
        if not self.eat_kw(kw):
            raise ParseError(f"expected {kw} at {self._where()}")

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind is T.OP and t.text in ops

    def eat_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.i += 1
            return True
        return False

    def expect_op(self, op: str):
        if not self.eat_op(op):
            raise ParseError(f"expected {op!r} at {self._where()}")

    def _where(self) -> str:
        t = self.peek()
        frag = self.sql[max(0, t.pos - 20) : t.pos + 20]
        return f"token {t.text!r} (…{frag}…)"

    def ident(self) -> str:
        t = self.peek()
        if t.kind in (T.IDENT, T.QIDENT):
            self.i += 1
            return t.text
        raise ParseError(f"expected identifier at {self._where()}")

    def expect_number(self) -> int:
        t = self.peek()
        if t.kind is T.NUMBER:
            self.i += 1
            return int(t.text)
        raise ParseError(f"expected number at {self._where()}")

    # ---- statements ----
    def parse_statements(self) -> list:
        out = []
        while self.peek().kind is not T.EOF:
            if self.eat_op(";"):
                continue
            out.append(self.statement())
            if self.peek().kind is not T.EOF:
                self.expect_op(";")
        return out

    def statement(self):
        t = self.peek()
        if t.kind is not T.IDENT:
            if t.kind is T.OP and t.text == "(":
                return self.select_or_union()
            raise ParseError(f"unexpected {self._where()}")
        kw = t.upper
        if kw in ("SELECT", "WITH"):
            return self.select_or_union()
        if kw == "INSERT" or kw == "REPLACE":
            return self.insert_stmt(replace=kw == "REPLACE")
        if kw == "UPDATE":
            return self.update_stmt()
        if kw == "DELETE":
            return self.delete_stmt()
        if kw == "GRANT":
            return self.grant_stmt(revoke=False)
        if kw == "REVOKE":
            return self.grant_stmt(revoke=True)
        if kw == "CREATE":
            return self.create_stmt()
        if kw == "DROP":
            return self.drop_stmt()
        if kw == "ALTER":
            return self.alter_stmt()
        if kw == "RENAME":
            return self.rename_stmt()
        if kw == "TRUNCATE":
            self.next()
            self.eat_kw("TABLE")
            return A.TruncateTableStmt(self.table_name())
        if kw == "SET":
            return self.set_stmt()
        if kw == "USE":
            self.next()
            return A.UseStmt(self.ident())
        if kw == "SHOW":
            return self.show_stmt()
        if kw in ("EXPLAIN", "DESC", "DESCRIBE"):
            return self.explain_stmt()
        if kw == "ANALYZE":
            return self.analyze_stmt()
        if kw in ("BEGIN", "START"):
            self.next()
            self.eat_kw("TRANSACTION")
            self.eat_kw("PESSIMISTIC") or self.eat_kw("OPTIMISTIC")
            if self.eat_kw("WITH"):
                self.expect_kw("CONSISTENT")
                self.expect_kw("SNAPSHOT")
            if self.eat_kw("READ"):
                self.eat_kw("ONLY") or self.eat_kw("WRITE")
                if self.eat_kw("AS"):  # AS OF TIMESTAMP ... (stale read)
                    self.expect_kw("OF")
                    self.expect_kw("TIMESTAMP")
                    self.expr()
            return A.BeginStmt()
        if kw == "SAVEPOINT":
            self.next()
            return A.SavepointStmt("set", self.ident().lower())
        if kw == "RELEASE":
            self.next()
            self.expect_kw("SAVEPOINT")
            return A.SavepointStmt("release", self.ident().lower())
        if kw == "COMMIT":
            self.next()
            return A.CommitStmt()
        if kw == "ROLLBACK":
            self.next()
            if self.eat_kw("TO"):
                self.eat_kw("SAVEPOINT")
                return A.SavepointStmt("rollback", self.ident().lower())
            return A.RollbackStmt()
        if kw == "PREPARE":
            self.next()
            name = self.ident()
            self.expect_kw("FROM")
            s = self.next()
            if s.kind is not T.STRING:
                raise ParseError("PREPARE ... FROM expects a string")
            return A.PrepareStmt(name, s.text)
        if kw == "EXECUTE":
            self.next()
            name = self.ident()
            using = []
            if self.eat_kw("USING"):
                while True:
                    self.expect_op("@")
                    using.append(self.ident())
                    if not self.eat_op(","):
                        break
            return A.ExecuteStmt(name, using)
        if kw == "DEALLOCATE":
            self.next()
            self.eat_kw("PREPARE")
            return A.DeallocateStmt(self.ident())
        if kw == "ADMIN":
            return self.admin_stmt()
        if kw == "KILL":
            # KILL [TIDB] [CONNECTION|QUERY] id (ref: parser.y KillStmt)
            self.next()
            self.eat_kw("TIDB")
            q = self.eat_kw("QUERY")
            if not q:
                self.eat_kw("CONNECTION")
            return A.KillStmt(self.expect_number(), q)
        if kw == "LOAD":
            if self.peek(1).kind is T.IDENT and self.peek(1).upper == "STATS":
                self.next()
                self.next()
                return A.LoadStatsStmt(self.next().text)
            return self.load_data_stmt()
        if kw == "IMPORT":
            self.next()
            self.expect_kw("INTO")
            table = self.table_name()
            cols = []
            if self.at_op("("):
                self.expect_op("(")
                while not self.at_op(")"):
                    cols.append(self.next().text)
                    self.eat_op(",")
                self.expect_op(")")
            self.expect_kw("FROM")
            path = self.next().text
            opts = {}
            if self.eat_kw("FORMAT"):
                opts["format"] = self.next().text
            if self.eat_kw("WITH"):
                while True:
                    k = self.ident()
                    v = True
                    if self.eat_op("="):
                        v = self.next().text
                    opts[k] = v
                    if not self.eat_op(","):
                        break
            return A.ImportIntoStmt(table, cols, path, opts)
        if kw == "BATCH":
            # BATCH [ON col] LIMIT n <dml> (non-transactional DML)
            self.next()
            col_name = ""
            if self.eat_kw("ON"):
                col_name = self.ident()
                while self.eat_op("."):
                    col_name = self.ident()
            self.expect_kw("LIMIT")
            n = self.expect_number()
            return A.BatchStmt(col_name, n, self.statement())
        if kw == "SPLIT":
            return self.split_stmt()
        if kw in ("BACKUP", "RESTORE"):
            return self.brie_stmt(kw.lower())
        if kw == "STOP":
            # STOP BACKUP LOG TO 'file://dir' (ISSUE 20; ref: `br log
            # stop`): detach the log backup attached at that destination
            self.next()
            self.expect_kw("BACKUP")
            if not self.eat_kw("LOG", "LOGS"):
                raise ParseError(f"expected LOG at {self._where()}")
            self.expect_kw("TO")
            return A.BRIEStmt("stop_backup_log", self.next().text)
        if kw == "TRACE":
            self.next()
            fmt = "row"
            if self.eat_kw("FORMAT"):
                self.eat_op("=")
                fmt = self.next().text.lower()
                if fmt not in ("row", "json"):
                    raise ParseError(f"TRACE FORMAT {fmt!r} not supported (row|json)")
            return A.TraceStmt(self.statement(), fmt)
        if kw in ("PAUSE", "RESUME"):
            # PAUSE/RESUME CHANGEFEED name (ref: TiCDC changefeed
            # pause/resume, SQL-ified like BACKUP/RESTORE)
            self.next()
            self.expect_kw("CHANGEFEED")
            return A.ChangefeedStmt(kw.lower(), self.ident())
        if kw == "FLASHBACK":
            self.next()
            self.expect_kw("TABLE")
            tbl = self.table_name()
            new = ""
            if self.eat_kw("TO"):
                new = self.ident()
            return A.FlashbackStmt(tbl, new)
        raise ParseError(f"unsupported statement start {kw} at {self._where()}")

    # ---- SELECT / UNION ----
    def select_or_union(self):
        ctes = self.with_clause() if self.at_kw("WITH") else []
        paren = self.at_op("(")
        selects = [self.single_select()]
        paren_flags = [paren]
        all_flags = []
        ops = []
        while self.at_kw("UNION", "EXCEPT", "INTERSECT"):
            ops.append(self.next().upper.lower())
            all_flags.append(self.eat_kw("ALL") or (self.eat_kw("DISTINCT") and False))
            paren_flags.append(self.at_op("("))
            selects.append(self.single_select())
        if len(selects) == 1:
            s = selects[0]
            if ctes:
                s.ctes = ctes + getattr(s, "ctes", [])
            # (SELECT ...) ORDER BY ... LIMIT ...: a parenthesized branch does
            # not swallow trailing clauses. If the branch already has its own
            # ORDER/LIMIT the outer ones apply AFTER it (MySQL derived-result
            # semantics) — represent that as a single-branch SetOprStmt so
            # neither clause set is lost.
            if paren_flags[0] and (self.at_kw("ORDER") or self.at_kw("LIMIT")):
                order_by, limit = [], None
                if self.eat_kw("ORDER"):
                    self.expect_kw("BY")
                    order_by = self.by_list()
                if self.at_kw("LIMIT"):
                    limit = self.limit_clause()
                if getattr(s, "order_by", None) or getattr(s, "limit", None):
                    return A.SetOprStmt([s], [], order_by, limit, ops=[], ctes=ctes)
                s.order_by, s.limit = order_by, limit
            return s
        order_by, limit = [], None
        if self.eat_kw("ORDER"):
            self.expect_kw("BY")
            order_by = self.by_list()
        if self.at_kw("LIMIT"):
            limit = self.limit_clause()
        # MySQL binds a trailing ORDER BY/LIMIT to the whole union; the last
        # branch will have swallowed it — hoist it up, but only when the
        # branch was NOT parenthesized (a parenthesized branch's ORDER/LIMIT
        # is branch-local).
        last = selects[-1]
        if not order_by and not limit and not paren_flags[-1] and isinstance(last, A.SelectStmt):
            order_by, limit = last.order_by, last.limit
            last.order_by, last.limit = [], None
        return A.SetOprStmt(selects, all_flags, order_by, limit, ops=ops, ctes=ctes)

    def with_clause(self) -> list:
        """WITH [RECURSIVE] name [(cols)] AS (subquery), ...
        (ref: parser.y WithClause; ast.CommonTableExpression)."""
        self.expect_kw("WITH")
        recursive = self.eat_kw("RECURSIVE")
        ctes = []
        while True:
            name = self.ident()
            cols = []
            if self.eat_op("("):
                while True:
                    cols.append(self.ident())
                    if not self.eat_op(","):
                        break
                self.expect_op(")")
            self.expect_kw("AS")
            self.expect_op("(")
            sub = self.select_or_union()
            self.expect_op(")")
            ctes.append(A.CTE(name, cols, sub, recursive))
            if not self.eat_op(","):
                break
        return ctes

    def single_select(self) -> A.SelectStmt:
        _win_refs_start = len(self._named_window_refs)
        if self.eat_op("("):
            s = self.select_or_union()
            self.expect_op(")")
            return s
        self.expect_kw("SELECT")
        hints = []
        if self.peek().kind is T.HINT:
            hints = _parse_hints(self.next().text)
        distinct = False
        while True:
            if self.eat_kw("DISTINCT", "DISTINCTROW"):
                distinct = True
            elif self.eat_kw("ALL", "SQL_CALC_FOUND_ROWS", "STRAIGHT_JOIN", "SQL_NO_CACHE", "HIGH_PRIORITY"):
                pass
            else:
                break
        fields = [self.select_field()]
        while self.eat_op(","):
            fields.append(self.select_field())
        frm = None
        if self.eat_kw("FROM"):
            frm = self.table_refs()
        where = self.expr() if self.eat_kw("WHERE") else None
        group_by, having = [], None
        if self.eat_kw("GROUP"):
            self.expect_kw("BY")
            group_by = self.by_list()
            self.eat_kw("WITH") and self.expect_kw("ROLLUP")
        if self.eat_kw("HAVING"):
            having = self.expr()
        named = {}
        if self.eat_kw("WINDOW"):
            # named windows: WINDOW w AS (spec)[, ...]
            while True:
                wname = self.ident().lower()
                self.expect_kw("AS")
                named[wname] = self.window_spec()
                if not self.eat_op(","):
                    break
        order_by = []
        if self.eat_kw("ORDER"):
            self.expect_kw("BY")
            order_by = self.by_list()
        limit = self.limit_clause() if self.at_kw("LIMIT") else None
        # resolve OVER w references only AFTER ORDER BY/LIMIT parse: a
        # window function in ORDER BY may legally name a WINDOW-clause
        # window (MySQL window resolution is per query block, clause order
        # notwithstanding)
        if named:
            # only THIS query block's refs (index >= _win_refs_start):
            # a subquery inside ORDER BY parses while the outer refs are
            # still pending, and windows are block-scoped in MySQL
            mine = self._named_window_refs[_win_refs_start:]
            for wf, ref in mine:
                if ref in named:
                    part, order, frame = named[ref]
                    wf.partition_by, wf.order_by, wf.has_frame = part, order, frame
            self._named_window_refs = self._named_window_refs[:_win_refs_start] + [
                (wf, ref) for wf, ref in mine if ref not in named
            ]
        if len(self._named_window_refs) > _win_refs_start:
            _, missing = self._named_window_refs[-1]
            raise ParseError(f"Window {missing!r} is not defined")
        for_update = False
        if self.eat_kw("FOR"):
            self.expect_kw("UPDATE")
            for_update = True
            if self.eat_kw("OF"):
                self.ident()
            self.eat_kw("NOWAIT") or (self.eat_kw("SKIP") and self.expect_kw("LOCKED"))
        elif self.eat_kw("LOCK"):
            self.expect_kw("IN")
            self.expect_kw("SHARE")
            self.expect_kw("MODE")
        return A.SelectStmt(fields, frm, where, group_by, having, order_by, limit, distinct, for_update, hints=hints)

    def select_field(self):
        if self.at_op("*"):
            self.next()
            return A.SelectField(A.Star(), "")
        # t.* / db.t.*
        if self.peek().kind in (T.IDENT, T.QIDENT):
            j = self.i
            name = self.ident()
            if self.at_op(".") and self.peek(1).kind in (T.IDENT, T.QIDENT) and self.peek(2).kind is T.OP and self.peek(2).text == "." and self.peek(3).kind is T.OP and self.peek(3).text == "*":
                self.next()
                tbl = self.ident()
                self.next()
                self.next()
                return A.SelectField(A.Star(table=tbl, db=name), "")
            if self.at_op(".") and self.peek(1).kind is T.OP and self.peek(1).text == "*":
                self.next()
                self.next()
                return A.SelectField(A.Star(table=name), "")
            self.i = j
        src_start = self.peek().pos
        e = self.expr()
        src_end = self.peek().pos if self.peek().kind is not T.EOF else len(self.sql)
        source = self.sql[src_start:src_end].strip()
        alias = ""
        if self.eat_kw("AS"):
            t = self.next()
            if t.kind in (T.IDENT, T.QIDENT, T.STRING):
                alias = t.text
            else:
                raise ParseError(f"bad alias at {self._where()}")
        elif self.peek().kind in (T.IDENT, T.QIDENT) and self.peek().upper not in _RESERVED_AFTER_EXPR:
            alias = self.next().text
        return A.SelectField(e, alias, source)

    def by_list(self) -> list:
        out = []
        while True:
            e = self.expr()
            desc = False
            if self.eat_kw("DESC"):
                desc = True
            else:
                self.eat_kw("ASC")
            out.append(A.ByItem(e, desc))
            if not self.eat_op(","):
                break
        return out

    def limit_clause(self) -> A.Limit:
        self.expect_kw("LIMIT")
        a = self.simple_limit_value()
        if self.eat_op(","):
            return A.Limit(self.simple_limit_value(), a)
        if self.eat_kw("OFFSET"):
            return A.Limit(a, self.simple_limit_value())
        return A.Limit(a)

    def simple_limit_value(self):
        t = self.peek()
        if t.kind is T.NUMBER:
            self.next()
            return A.Literal(int(t.text), "int", pos=t.pos)
        if t.kind is T.PARAM:
            self.next()
            p = A.ParamMarker(self.n_params, pos=t.pos)
            self.n_params += 1
            return p
        raise ParseError(f"expected LIMIT count at {self._where()}")

    # ---- table refs ----
    def table_refs(self):
        left = self.table_factor()
        while True:
            natural = False
            if self.at_kw("NATURAL"):
                natural = True
                self.next()
            if self.eat_op(","):
                right = self.table_factor()
                left = A.Join(left, right, "cross")
                continue
            if self.eat_kw("STRAIGHT_JOIN"):
                right = self.table_factor()
                on, using = None, []
                if self.eat_kw("ON"):
                    on = self.expr()
                elif self.eat_kw("USING"):
                    self.expect_op("(")
                    while True:
                        using.append(self.ident())
                        if not self.eat_op(","):
                            break
                    self.expect_op(")")
                left = A.Join(left, right, "inner", on, using)
                continue
            kind = None
            if self.at_kw("JOIN", "INNER", "CROSS"):
                if self.eat_kw("INNER") or self.eat_kw("CROSS"):
                    pass
                self.expect_kw("JOIN")
                kind = "inner"
            elif self.at_kw("LEFT", "RIGHT"):
                kind = "left" if self.eat_kw("LEFT") else (self.eat_kw("RIGHT") and "right")
                self.eat_kw("OUTER")
                self.expect_kw("JOIN")
            else:
                break
            right = self.table_factor()
            on, using = None, []
            if not natural:
                if self.eat_kw("ON"):
                    on = self.expr()
                elif self.eat_kw("USING"):
                    self.expect_op("(")
                    while True:
                        using.append(self.ident())
                        if not self.eat_op(","):
                            break
                    self.expect_op(")")
            left = A.Join(left, right, kind, on, using)
        return left

    def table_factor(self):
        if self.eat_op("("):
            if self.at_kw("SELECT", "WITH") or self.at_op("("):
                sub = self.select_or_union()
                self.expect_op(")")
                self.eat_kw("AS")
                alias = self.ident()
                return A.SubqueryTable(sub, alias)
            refs = self.table_refs()
            self.expect_op(")")
            return refs
        return self.table_name(allow_alias=True)

    def table_name(self, allow_alias: bool = False) -> A.TableName:
        name = self.ident()
        db = ""
        if self.eat_op("."):
            db, name = name, self.ident()
        alias = ""
        hints = []
        if allow_alias and self.at_kw("PARTITION"):
            self.next()
            self.expect_op("(")
            parts = [self._partition_name()]
            while self.eat_op(","):
                parts.append(self._partition_name())
            self.expect_op(")")
            hints.append(("partition", parts))
        if allow_alias:
            if self.eat_kw("AS"):
                alias = self.ident()
            elif self.peek().kind in (T.IDENT, T.QIDENT) and self.peek().upper not in _RESERVED_AFTER_EXPR and self.peek().upper not in ("USE", "IGNORE", "FORCE", "PARTITION", "TABLESAMPLE"):
                alias = self.next().text
            while self.at_kw("USE", "IGNORE", "FORCE"):
                kind = self.next().upper.lower()
                self.expect_kw("INDEX") if self.at_kw("INDEX") else self.expect_kw("KEY")
                if self.eat_kw("FOR"):
                    if self.eat_kw("ORDER") or self.eat_kw("GROUP"):
                        self.expect_kw("BY")
                    else:
                        self.expect_kw("JOIN")
                self.expect_op("(")
                idxs = []
                if not self.at_op(")"):
                    while True:
                        idxs.append(self.ident())
                        if not self.eat_op(","):
                            break
                self.expect_op(")")
                hints.append((kind, idxs))
            if self.eat_kw("TABLESAMPLE"):
                self.expect_kw("REGIONS")
                self.expect_op("(")
                self.expect_op(")")
                hints.append(("tablesample", ["regions"]))
        return A.TableName(name, db, alias, hints)

    # ---- expressions: precedence climbing ----
    def expr(self) -> A.ExprNode:
        return self.or_expr()

    def or_expr(self):
        left = self.xor_expr()
        while True:
            if self.eat_kw("OR") or self.eat_op("||"):
                left = A.BinaryOp("or", left, self.xor_expr())
            else:
                return left

    def xor_expr(self):
        left = self.and_expr()
        while self.eat_kw("XOR"):
            left = A.BinaryOp("xor", left, self.and_expr())
        return left

    def and_expr(self):
        left = self.not_expr()
        while True:
            if self.eat_kw("AND") or self.eat_op("&&"):
                left = A.BinaryOp("and", left, self.not_expr())
            else:
                return left

    def not_expr(self):
        if self.eat_kw("NOT"):
            return A.UnaryOp("not", self.not_expr())
        return self.predicate()

    _CMP = {"=": "eq", "<=>": "nulleq", "<": "lt", "<=": "le", ">": "gt", ">=": "ge", "<>": "ne", "!=": "ne"}

    def predicate(self):
        left = self.bit_or_expr()
        while True:
            t = self.peek()
            if t.kind is T.OP and t.text in self._CMP:
                op = self._CMP[self.next().text]
                if self.at_kw("ANY", "SOME", "ALL"):
                    is_all = self.next().upper == "ALL"
                    self.expect_op("(")
                    sub = self.select_or_union()
                    self.expect_op(")")
                    left = A.CompareSubquery(left, op, sub, is_all)
                else:
                    left = A.BinaryOp(op, left, self.bit_or_expr())
                continue
            if self.at_kw("MEMBER"):
                self.next()
                self.expect_kw("OF")
                self.expect_op("(")
                arr = self.expr()
                self.expect_op(")")
                left = A.FuncCall("json_member_of", [left, arr])
                continue
            negated = False
            j = self.i
            if self.at_kw("NOT"):
                if self.peek(1).kind is T.IDENT and self.peek(1).upper in ("IN", "LIKE", "BETWEEN", "REGEXP", "RLIKE"):
                    self.next()
                    negated = True
                else:
                    self.i = j
                    return left
            if self.eat_kw("IS"):
                neg = self.eat_kw("NOT")
                if self.eat_kw("NULL"):
                    left = A.IsNull(left, neg)
                elif self.eat_kw("TRUE"):
                    left = A.IsTruth(left, True, neg)
                elif self.eat_kw("FALSE"):
                    left = A.IsTruth(left, False, neg)
                else:
                    raise ParseError(f"IS what? at {self._where()}")
                continue
            if self.eat_kw("IN"):
                self.expect_op("(")
                if self.at_kw("SELECT", "WITH"):
                    sub = self.select_or_union()
                    self.expect_op(")")
                    left = A.InSubquery(left, sub, negated)
                else:
                    items = [self.expr()]
                    while self.eat_op(","):
                        items.append(self.expr())
                    self.expect_op(")")
                    left = A.InList(left, items, negated)
                continue
            if self.eat_kw("BETWEEN"):
                lo = self.bit_or_expr()
                self.expect_kw("AND")
                hi = self.bit_or_expr()
                left = A.Between(left, lo, hi, negated)
                continue
            if self.eat_kw("LIKE"):
                pat = self.bit_or_expr()
                esc = "\\"
                if self.eat_kw("ESCAPE"):
                    esc_t = self.next()
                    esc = esc_t.text
                left = A.Like(left, pat, esc, negated)
                continue
            if self.eat_kw("REGEXP", "RLIKE"):
                left = A.Regexp(left, self.bit_or_expr(), negated)
                continue
            return left

    def bit_or_expr(self):
        left = self.bit_and_expr()
        while self.at_op("|") and not self.at_op("||"):
            self.next()
            left = A.BinaryOp("bitor", left, self.bit_and_expr())
        return left

    def bit_and_expr(self):
        left = self.shift_expr()
        while self.at_op("&"):
            self.next()
            left = A.BinaryOp("bitand", left, self.shift_expr())
        return left

    def shift_expr(self):
        left = self.add_expr()
        while self.at_op("<<", ">>"):
            op = "shiftleft" if self.next().text == "<<" else "shiftright"
            left = A.BinaryOp(op, left, self.add_expr())
        return left

    def add_expr(self):
        left = self.mul_expr()
        while True:
            if self.at_op("+"):
                self.next()
                right = self.mul_expr()
                # date + INTERVAL n unit
                if isinstance(right, A.Interval):
                    left = A.FuncCall("date_add", [left, right])
                else:
                    left = A.BinaryOp("plus", left, right)
            elif self.at_op("-"):
                self.next()
                right = self.mul_expr()
                if isinstance(right, A.Interval):
                    left = A.FuncCall("date_sub", [left, right])
                else:
                    left = A.BinaryOp("minus", left, right)
            else:
                return left

    def mul_expr(self):
        left = self.xor_bit_expr()
        while True:
            if self.at_op("*"):
                self.next()
                left = A.BinaryOp("mul", left, self.xor_bit_expr())
            elif self.at_op("/"):
                self.next()
                left = A.BinaryOp("div", left, self.xor_bit_expr())
            elif self.at_op("%") or self.at_kw("MOD"):
                self.next()
                left = A.BinaryOp("mod", left, self.xor_bit_expr())
            elif self.at_kw("DIV"):
                self.next()
                left = A.BinaryOp("intdiv", left, self.xor_bit_expr())
            else:
                return left

    def xor_bit_expr(self):
        left = self.unary_expr()
        while self.at_op("^"):
            self.next()
            left = A.BinaryOp("bitxor", left, self.unary_expr())
        return left

    def unary_expr(self):
        if self.at_op("-"):
            self.next()
            return A.UnaryOp("unaryminus", self.unary_expr())
        if self.at_op("+"):
            self.next()
            return self.unary_expr()
        if self.at_op("~"):
            self.next()
            return A.UnaryOp("bitneg", self.unary_expr())
        if self.at_op("!"):
            # '!' binds at unary precedence (above comparison/IN/LIKE),
            # unlike NOT (ref: parser.y precedence: '!' ~ NEG level)
            self.next()
            return A.UnaryOp("not", self.unary_expr())
        if self.at_kw("BINARY"):
            # BINARY expr — treat as cast to binary string (collation change)
            j = self.i
            self.next()
            if self.peek().kind in (T.IDENT, T.QIDENT, T.STRING, T.NUMBER) or self.at_op("("):
                return A.Cast(self.unary_expr(), A.TypeSpec("binary"))
            self.i = j
        return self._collate_tail(self.primary())

    def _collate_tail(self, node):
        while True:
            if self.eat_kw("COLLATE"):
                node = A.CollateExpr(node, self.ident().lower())
            elif self.at_op("->") or self.at_op("->>"):
                # JSON path operators (ref: parser.y: col->path ==
                # json_extract, ->> wraps json_unquote)
                unq = self.next().text == "->>"
                ptok = self.next()
                if ptok.kind is not T.STRING:
                    raise ParseError(f"expected JSON path string at {self._where()}")
                node = A.FuncCall("json_extract", [node, A.Literal(ptok.text, "str", pos=ptok.pos)])
                if unq:
                    node = A.FuncCall("json_unquote", [node])
            else:
                return node

    def primary(self) -> A.ExprNode:
        t = self.peek()
        if (
            t.kind is T.IDENT
            and t.text.startswith("_")
            and t.text.lower() in ("_utf8", "_utf8mb4", "_binary", "_latin1", "_ascii", "_gbk")
            and self.peek(1).kind is T.STRING
        ):
            self.next()
            s = self.next()
            return A.Literal(s.text, "str", pos=s.pos)
        # hex/bit literals: X'1A2B', B'1010' (ref: parser.y HexLiteral/BitLiteral)
        if t.kind is T.IDENT and t.upper == "N" and self.peek(1).kind is T.STRING:
            self.next()
            s = self.next()
            return A.Literal(s.text, "str", pos=s.pos)
        if (
            t.kind is T.IDENT
            and t.upper in ("X", "B")
            and self.peek(1).kind is T.STRING
        ):
            self.next()
            raw = self.next().text
            try:
                v = int(raw, 16 if t.upper == "X" else 2) if raw else 0
            except ValueError:
                raise ParseError(f"bad {t.upper}-literal at {self._where()}")
            return A.Literal(v, "int", pos=-2)  # value != token text: not slot-bindable
        if t.kind is T.NUMBER:
            self.next()
            if "." in t.text or "e" in t.text.lower():
                kind = "float" if ("e" in t.text.lower()) else "decimal"
                return A.Literal(t.text, kind, pos=t.pos)
            return A.Literal(int(t.text), "int", pos=t.pos)
        if t.kind is T.STRING:
            self.next()
            # adjacent string literal concat 'a' 'b' (a multi-token literal
            # cannot bind by slot position: pos sentinel -2)
            text, pos = t.text, t.pos
            while self.peek().kind is T.STRING:
                text += self.next().text
                pos = -2
            return A.Literal(text, "str", pos=pos)
        if t.kind is T.HEX:
            self.next()
            h = t.text[2:]
            if len(h) % 2:
                h = "0" + h
            return A.Literal(bytes.fromhex(h), "hex")
        if t.kind is T.PARAM:
            self.next()
            p = A.ParamMarker(self.n_params, pos=t.pos)
            self.n_params += 1
            return p
        if t.kind is T.OP and t.text == "(":
            self.next()
            if self.at_kw("SELECT", "WITH"):
                sub = self.select_or_union()
                self.expect_op(")")
                return A.SubqueryExpr(sub)
            e = self.expr()
            if self.eat_op(","):
                items = [e, self.expr()]
                while self.eat_op(","):
                    items.append(self.expr())
                self.expect_op(")")
                return A.RowExpr(items)
            self.expect_op(")")
            return e
        if t.kind is T.OP and t.text == "@":
            self.next()
            if self.eat_op("@"):
                scope = ""
                name = self.ident()
                if name.lower() in ("global", "session") and self.eat_op("."):
                    scope = name.lower()
                    name = self.ident()
                return A.Variable(name.lower(), True, scope)
            return A.Variable(self.ident().lower(), False)
        if t.kind is T.QIDENT:
            return self.column_or_func()
        if t.kind is T.IDENT:
            kw = t.upper
            if kw == "NULL":
                self.next()
                return A.Literal(None, "null")
            if kw == "TRUE":
                self.next()
                return A.Literal(1, "bool")
            if kw == "FALSE":
                self.next()
                return A.Literal(0, "bool")
            if kw == "CASE":
                return self.case_expr()
            if kw == "CAST" or kw == "CONVERT":
                return self.cast_expr(kw)
            if kw == "EXISTS":
                self.next()
                self.expect_op("(")
                sub = self.select_or_union()
                self.expect_op(")")
                return A.Exists(sub)
            if kw == "NOT":
                self.next()
                return A.UnaryOp("not", self.not_expr())
            if kw == "INTERVAL":
                self.next()
                v = self.bit_or_expr()
                unit = self.ident().lower()
                return A.Interval(v, unit)
            if kw == "DEFAULT" and not (self.peek(1).kind is T.OP and self.peek(1).text == "("):
                self.next()
                return A.Default()
            if kw in ("DATE", "TIME", "TIMESTAMP") and self.peek(1).kind is T.STRING:
                self.next()
                s = self.next()
                return A.FuncCall("cast_literal_" + kw.lower(), [A.Literal(s.text, "str", pos=s.pos)])
            return self.column_or_func()
        raise ParseError(f"unexpected {self._where()}")

    def case_expr(self):
        self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.expr()
        whens = []
        while self.eat_kw("WHEN"):
            cond = self.expr()
            self.expect_kw("THEN")
            whens.append((cond, self.expr()))
        els = self.expr() if self.eat_kw("ELSE") else None
        self.expect_kw("END")
        return A.Case(operand, whens, els)

    def cast_expr(self, kw: str):
        self.next()
        self.expect_op("(")
        e = self.expr()
        if kw == "CAST":
            self.expect_kw("AS")
            ts = self.type_spec()
        elif self.eat_kw("USING"):  # CONVERT(expr USING charset)
            cs = self.ident().lower()
            self.expect_op(")")
            return A.FuncCall("convert_using", [e, A.Literal(cs, "str")])
        else:  # CONVERT(expr, type)
            self.expect_op(",")
            ts = self.type_spec()
        self.expect_op(")")
        return A.Cast(e, ts)

    _EXTRACT_UNITS = {
        "MICROSECOND", "SECOND", "MINUTE", "HOUR", "DAY", "WEEK", "MONTH",
        "QUARTER", "YEAR", "SECOND_MICROSECOND", "MINUTE_MICROSECOND",
        "MINUTE_SECOND", "HOUR_MICROSECOND", "HOUR_SECOND", "HOUR_MINUTE",
        "DAY_MICROSECOND", "DAY_SECOND", "DAY_MINUTE", "DAY_HOUR",
        "YEAR_MONTH",
    }

    def column_or_func(self) -> A.ExprNode:
        quoted = self.peek().kind is T.QIDENT  # `max`(x) is never a call
        name = self.ident()
        # function call?
        if self.at_op("(") and not quoted:
            lname = name.lower()
            self.next()
            if lname in ("substring", "substr", "mid") and not self.at_op(")"):
                # SUBSTRING(str FROM pos [FOR len]) (ref: parser.y
                # SubstringExpr); the comma form reuses the generic
                # argument loop below
                e = self.expr()
                if self.eat_kw("FROM"):
                    pos = self.expr()
                    args = [e, pos]
                    if self.eat_kw("FOR"):
                        args.append(self.expr())
                    self.expect_op(")")
                    return A.FuncCall("substr", args)
                args = [e]
                while self.eat_op(","):
                    args.append(self.expr())
                self.expect_op(")")
                return A.FuncCall(lname, args)
            if lname == "extract" and self.peek().upper in self._EXTRACT_UNITS:
                # EXTRACT(unit FROM expr) (ref: parser.y ExtractExpr)
                unit = self.next().upper.lower()
                self.expect_kw("FROM")
                e = self.expr()
                self.expect_op(")")
                return A.FuncCall("extract", [A.Literal(unit, "str"), e])
            distinct = False
            if lname in _AGG_FUNCS and self.eat_kw("DISTINCT"):
                distinct = True
            args: list = []
            if self.at_op("*"):
                self.next()
                args = [A.Star()]
            elif not self.at_op(")"):
                args.append(self.func_arg())
                while self.eat_op(","):
                    args.append(self.func_arg())
            gc_order, gc_sep = [], None
            if lname == "group_concat":
                # GROUP_CONCAT(expr [ORDER BY ...] [SEPARATOR str]) — the
                # trailing clauses follow the arg without a comma
                if self.eat_kw("ORDER"):
                    self.expect_kw("BY")
                    gc_order = self.by_list()
                if self.eat_kw("SEPARATOR"):
                    gc_sep = self.next().text
            self.expect_op(")")
            if self.at_kw("OVER"):
                self.next()
                if distinct:
                    raise ParseError(f"DISTINCT is not allowed in window function {lname!r}")
                if self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_op("("):
                    # OVER w — named window, resolved after the WINDOW clause
                    wf = A.WindowFunc(lname, args, [], [], False)
                    self._named_window_refs.append((wf, self.ident().lower()))
                    return wf
                part, order, frame = self.window_spec()
                return A.WindowFunc(lname, args, part, order, frame)
            if lname in _AGG_FUNCS:
                return A.AggFunc(lname, args, distinct, gc_order, gc_sep)
            return A.FuncCall(lname, args)
        # qualified column
        table = db = ""
        if self.eat_op("."):
            table, name = name, self.ident()
            if self.eat_op("."):
                db, table, name = table, name, self.ident()
        return A.ColumnName(name, table, db)

    def func_arg(self):
        return self.expr()

    def _frame_bound(self):
        if self.eat_kw("UNBOUNDED"):
            self.eat_kw("PRECEDING") or self.eat_kw("FOLLOWING")
        elif self.eat_kw("CURRENT"):
            self.expect_kw("ROW")
        else:
            if self.at_kw("INTERVAL"):
                self.expr()
            else:
                self.next()  # numeric offset
            self.eat_kw("PRECEDING") or self.eat_kw("FOLLOWING")

    def _frame_clause(self):
        """ROWS/RANGE [BETWEEN a AND b | bound] — parsed into the window
        spec; explicit frames route to the oracle (ops/window.py)."""
        self.next()  # ROWS | RANGE
        if self.eat_kw("BETWEEN"):
            self._frame_bound()
            self.expect_kw("AND")
            self._frame_bound()
        else:
            self._frame_bound()

    def window_spec(self):
        """OVER ( [PARTITION BY exprs] [ORDER BY items] [frame] ) —
        explicit ROWS/RANGE frames parse (corpus coverage) and flag the
        WindowFunc; the planner rejects non-default frames at lowering."""
        self.expect_op("(")
        part: list = []
        order: list = []
        frame = False
        if self.eat_kw("PARTITION"):
            self.expect_kw("BY")
            part.append(self.expr())
            while self.eat_op(","):
                part.append(self.expr())
        if self.eat_kw("ORDER"):
            self.expect_kw("BY")
            order = self.by_list()
        if self.at_kw("ROWS", "RANGE", "GROUPS"):
            self._frame_clause()
            frame = True
        self.expect_op(")")
        return part, order, frame

    # ---- type spec ----
    def type_spec(self) -> A.TypeSpec:
        name = self.ident().lower()
        if name == "national":
            name = self.ident().lower()
        if name not in _TYPE_NAMES:
            raise ParseError(f"unknown type {name!r} at {self._where()}")
        if name in ("signed", "unsigned"):
            # CAST(x AS UNSIGNED [INT|INTEGER]) — eat the optional keyword
            self.eat_kw("INT", "INTEGER")
        if name in ("integer",):
            name = "int"
        if name in ("numeric", "dec", "fixed"):
            name = "decimal"
        if name in ("bool", "boolean"):
            name = "tinyint"
        if name == "real":
            name = "double"
        length = dec = -1
        if self.eat_op("("):
            if name in ("enum", "set"):
                elems = []
                while True:
                    s = self.next()
                    elems.append(s.text)
                    if not self.eat_op(","):
                        break
                self.expect_op(")")
                ts = A.TypeSpec(name, elems=tuple(elems))
                return self._type_attrs(ts)
            length = self.expect_number()
            if self.eat_op(","):
                dec = self.expect_number()
            self.expect_op(")")
        ts = A.TypeSpec(name, length, dec)
        return self._type_attrs(ts)

    def _type_attrs(self, ts: A.TypeSpec) -> A.TypeSpec:
        if self.eat_kw("ARRAY"):
            pass  # CAST(... AS t ARRAY) — multi-valued index form
        while True:
            if self.eat_kw("UNSIGNED"):
                ts.unsigned = True
            elif self.eat_kw("SIGNED"):
                pass
            elif self.eat_kw("ZEROFILL"):
                ts.zerofill = True
            elif self.eat_kw("CHARACTER"):
                self.expect_kw("SET")
                ts.charset = self.ident().lower()
            elif self.eat_kw("CHARSET"):
                ts.charset = self.ident().lower()
            elif self.eat_kw("COLLATE"):
                ts.collate = self.ident().lower()
            else:
                return ts

    # ---- DML ----
    def insert_stmt(self, replace: bool) -> A.InsertStmt:
        self.next()
        self.eat_kw("LOW_PRIORITY") or self.eat_kw("DELAYED") or self.eat_kw("HIGH_PRIORITY")
        ignore = self.eat_kw("IGNORE")
        self.eat_kw("INTO")
        table = self.table_name()
        if self.eat_kw("PARTITION"):
            self.expect_op("(")
            self._partition_name()
            while self.eat_op(","):
                self._partition_name()
            self.expect_op(")")
        columns = []
        if self.at_op("(") and not self._paren_is_select():
            self.next()
            while True:
                columns.append(self.ident())
                if not self.eat_op(","):
                    break
            self.expect_op(")")
        values, select = [], None
        if self.eat_kw("VALUES", "VALUE"):
            while True:
                self.expect_op("(")
                row = []
                if not self.at_op(")"):
                    row.append(self.expr())
                    while self.eat_op(","):
                        row.append(self.expr())
                self.expect_op(")")
                values.append(row)
                if not self.eat_op(","):
                    break
        elif self.at_kw("SELECT", "WITH") or self.at_op("("):
            select = self.select_or_union()
        elif self.eat_kw("SET"):
            cols, row = [], []
            while True:
                cols.append(self.ident())
                self.expect_op("=")
                row.append(self.expr())
                if not self.eat_op(","):
                    break
            columns, values = cols, [row]
        on_dup = []
        if self.eat_kw("ON"):
            self.expect_kw("DUPLICATE")
            self.expect_kw("KEY")
            self.expect_kw("UPDATE")
            while True:
                c = self.column_name_simple()
                self.expect_op("=")
                on_dup.append(A.Assignment(c, self.expr()))
                if not self.eat_op(","):
                    break
        return A.InsertStmt(table, columns, values, select, on_dup, replace, ignore)

    def _paren_is_select(self) -> bool:
        return self.at_op("(") and self.peek(1).kind is T.IDENT and self.peek(1).upper in ("SELECT", "WITH")

    def column_name_simple(self) -> A.ColumnName:
        name = self.ident()
        table = db = ""
        if self.eat_op("."):
            table, name = name, self.ident()
            if self.eat_op("."):
                db, table, name = table, name, self.ident()
        return A.ColumnName(name, table, db)

    def update_stmt(self) -> A.UpdateStmt:
        self.next()
        self.eat_kw("IGNORE")
        table = self.table_refs()
        self.expect_kw("SET")
        assigns = []
        while True:
            c = self.column_name_simple()
            self.expect_op("=")
            assigns.append(A.Assignment(c, self.expr()))
            if not self.eat_op(","):
                break
        where = self.expr() if self.eat_kw("WHERE") else None
        order_by = []
        if self.eat_kw("ORDER"):
            self.expect_kw("BY")
            order_by = self.by_list()
        limit = self.limit_clause() if self.at_kw("LIMIT") else None
        return A.UpdateStmt(table, assigns, where, order_by, limit)

    def delete_stmt(self) -> A.DeleteStmt:
        self.next()
        self.eat_kw("LOW_PRIORITY")
        self.eat_kw("QUICK")
        self.eat_kw("IGNORE")
        if not self.at_kw("FROM"):
            # multi-table form: DELETE t1, t2 FROM <joined tables> WHERE ..
            # (ref: parser.y DeleteFromStmt multi-table) — parsed; the
            # executor deletes from the FIRST named table
            def target():
                t = self.table_name()
                if self.eat_op("."):
                    self.expect_op("*")
                return t

            targets = [target()]
            while self.eat_op(","):
                targets.append(target())
            self.expect_kw("FROM")
            self.table_refs()
            where = self.expr() if self.eat_kw("WHERE") else None
            return A.DeleteStmt(targets[0], where, [], None, multi_table=True)
        self.expect_kw("FROM")
        table = self.table_name(allow_alias=True)
        if self.eat_op(","):
            # multi-table USING form
            while True:
                self.table_name(allow_alias=True)
                if not self.eat_op(","):
                    break
            if self.eat_kw("USING"):
                self.table_refs()
            where = self.expr() if self.eat_kw("WHERE") else None
            return A.DeleteStmt(table, where, [], None, multi_table=True)
        if self.eat_kw("USING"):
            self.table_refs()
        where = self.expr() if self.eat_kw("WHERE") else None
        order_by = []
        if self.eat_kw("ORDER"):
            self.expect_kw("BY")
            order_by = self.by_list()
        limit = self.limit_clause() if self.at_kw("LIMIT") else None
        return A.DeleteStmt(table, where, order_by, limit)

    def load_data_stmt(self) -> A.LoadDataStmt:
        self.next()
        self.expect_kw("DATA")
        self.eat_kw("LOCAL")
        self.expect_kw("INFILE")
        path = self.next().text
        self.eat_kw("IGNORE") or self.eat_kw("REPLACE")
        self.expect_kw("INTO")
        self.expect_kw("TABLE")
        table = self.table_name()
        stmt = A.LoadDataStmt(path, table)
        if self.eat_kw("FIELDS", "COLUMNS"):
            while True:
                if self.eat_kw("TERMINATED"):
                    self.expect_kw("BY")
                    stmt.fields_terminated = self.next().text
                elif self.eat_kw("ENCLOSED"):
                    self.expect_kw("BY")
                    stmt.fields_enclosed = self.next().text
                elif self.eat_kw("OPTIONALLY"):
                    self.expect_kw("ENCLOSED")
                    self.expect_kw("BY")
                    stmt.fields_enclosed = self.next().text
                elif self.eat_kw("ESCAPED"):
                    self.expect_kw("BY")
                    self.next()
                else:
                    break
        if self.eat_kw("LINES"):
            self.expect_kw("TERMINATED")
            self.expect_kw("BY")
            stmt.lines_terminated = self.next().text
        if self.eat_kw("IGNORE"):
            stmt.ignore_lines = self.expect_number()
            self.expect_kw("LINES") if self.at_kw("LINES") else self.expect_kw("ROWS")
        if self.eat_op("("):
            while True:
                stmt.columns.append(self.ident())
                if not self.eat_op(","):
                    break
            self.expect_op(")")
        return stmt

    # ---- DDL ----
    def split_stmt(self) -> A.SplitTableStmt:
        """SPLIT [REGION FOR] TABLE t [INDEX i] BETWEEN (..) AND (..)
        REGIONS n | BY (..)[, (..)] (ref: parser.y SplitRegionStmt)."""
        self.next()
        self.eat_kw("REGION") and self.eat_kw("FOR")
        self.eat_kw("PARTITION")
        self.expect_kw("TABLE")
        table = self.table_name()
        if self.eat_kw("PARTITION"):
            self.expect_op("(")
            while not self.at_op(")"):
                self.next()
            self.expect_op(")")
        index = ""
        if self.eat_kw("INDEX"):
            index = self.ident()
        between = None
        points = []

        def row():
            self.expect_op("(")
            vals = [self.expr()]
            while self.eat_op(","):
                vals.append(self.expr())
            self.expect_op(")")
            return vals

        if self.eat_kw("BETWEEN"):
            lo = row()
            self.expect_kw("AND")
            hi = row()
            self.expect_kw("REGIONS")
            between = (lo, hi, self.expect_number())
        elif self.eat_kw("BY"):
            points.append(row())
            while self.eat_op(","):
                points.append(row())
        return A.SplitTableStmt(table, index, between, points)

    def create_stmt(self):
        self.next()
        or_replace = False
        if self.eat_kw("OR"):
            self.expect_kw("REPLACE")
            or_replace = True
        definer = False
        if self.eat_kw("DEFINER"):
            self.expect_op("=")
            self.next()
            if self.eat_op("@"):
                self.next()
            definer = True
        if self.eat_kw("ALGORITHM"):
            self.expect_op("=")
            self.next()
            definer = True
        if self.eat_kw("SQL"):
            self.expect_kw("SECURITY")
            self.next()
            definer = True
        if self.at_kw("VIEW"):
            self.next()
            ine = False
            if self.eat_kw("IF"):
                self.expect_kw("NOT")
                self.expect_kw("EXISTS")
                ine = True
            name = self.table_name()
            cols = []
            if self.at_op("("):
                self.expect_op("(")
                while not self.at_op(")"):
                    cols.append(self.ident())
                    self.eat_op(",")
                self.expect_op(")")
            self.expect_kw("AS")
            sel_start = self.peek().pos
            sel = self.select_or_union()
            sel_end = self.peek().pos if self.peek().kind is not T.EOF else len(self.sql)
            source = self.sql[sel_start:sel_end].strip().rstrip(";").strip()
            if self.eat_kw("WITH"):
                self.eat_kw("CASCADED") or self.eat_kw("LOCAL")
                self.expect_kw("CHECK")
                self.expect_kw("OPTION")
            return A.CreateViewStmt(name, cols, sel, or_replace, source)
        if self.eat_kw("SEQUENCE"):
            ine = False
            if self.eat_kw("IF"):
                self.expect_kw("NOT")
                self.expect_kw("EXISTS")
                ine = True
            name = self.table_name()
            opts = {}
            while self.peek().kind in (T.IDENT, T.QIDENT):
                k = self.next().upper.lower()
                if k in ("start", "increment"):
                    self.eat_kw("WITH") or self.eat_kw("BY")
                    self.eat_op("=")
                    t = self.next()
                    neg = t.text == "-"
                    opts[k] = -self.expect_number() if neg else int(t.text)
                elif k in ("minvalue", "maxvalue", "cache"):
                    self.eat_op("=")
                    t = self.next()
                    neg = t.text == "-"
                    opts[k] = -self.expect_number() if neg else int(t.text)
                # nominvalue/nomaxvalue/nocache/cycle/nocycle: flags
            return A.CreateSequenceStmt(name, ine, opts)
        if self.at_kw("GLOBAL", "SESSION") and self.peek(1).upper == "BINDING":
            scope = self.next().upper.lower()
            self.next()
            self.expect_kw("FOR")
            t0 = self.peek().pos
            target = self.statement()
            t1 = self.peek().pos
            self.expect_kw("USING")
            h0 = self.peek().pos
            hinted = self.statement()
            h1 = self.peek().pos if self.peek().kind is not T.EOF else len(self.sql)
            st = A.BindingStmt("create", scope, target, hinted)
            st.target_sql = self.sql[t0:t1].strip().rstrip(";")
            st.hinted_sql = self.sql[h0:h1].strip().rstrip(";")
            return st
        if self.eat_kw("BINDING"):
            self.expect_kw("FOR")
            t0 = self.peek().pos
            target = self.statement()
            t1 = self.peek().pos
            self.expect_kw("USING")
            h0 = self.peek().pos
            hinted = self.statement()
            h1 = self.peek().pos if self.peek().kind is not T.EOF else len(self.sql)
            st = A.BindingStmt("create", "session", target, hinted)
            st.target_sql = self.sql[t0:t1].strip().rstrip(";")
            st.hinted_sql = self.sql[h0:h1].strip().rstrip(";")
            return st
        self.eat_kw("GLOBAL")  # global temporary table
        self.eat_kw("TEMPORARY")
        if self.eat_kw("ROLE"):
            ine = False
            if self.eat_kw("IF"):
                self.expect_kw("NOT")
                self.expect_kw("EXISTS")
                ine = True
            users = [self.user_spec(with_password=True)]
            while self.eat_op(","):
                users.append(self.user_spec(with_password=True))
            return A.CreateUserStmt(users, ine)
        if self.eat_kw("CHANGEFEED"):
            # CREATE CHANGEFEED name INTO 'sink-uri'
            #   [FOR TABLE t1, t2] [WITH start_ts = N, ...]
            name = self.ident()
            self.expect_kw("INTO")
            uri_tok = self.next()
            if uri_tok.kind is not T.STRING:
                raise ParseError("CREATE CHANGEFEED ... INTO expects a sink-uri string")
            tables = []
            if self.eat_kw("FOR"):
                self.expect_kw("TABLE")
                tables.append(self.table_name())
                while self.eat_op(","):
                    tables.append(self.table_name())
            opts = {}
            if self.eat_kw("WITH"):
                while True:
                    k = self.ident().lower()
                    v = True
                    if self.eat_op("="):
                        t = self.next()
                        # only INTEGRAL numbers coerce; '1.5' stays a
                        # string so the session rejects it with a typed
                        # SQLError instead of a raw int() ValueError
                        v = (int(t.text)
                             if t.kind is T.NUMBER and t.text.lstrip("-").isdigit()
                             else t.text)
                    opts[k] = v
                    if not self.eat_op(","):
                        break
            return A.ChangefeedStmt("create", name, uri_tok.text, tables, opts)
        if self.eat_kw("PLACEMENT"):
            self.expect_kw("POLICY")
            if self.eat_kw("IF"):
                self.expect_kw("NOT")
                self.expect_kw("EXISTS")
            self.ident()
            while self.peek().kind in (T.IDENT, T.QIDENT):
                self.next()
                self.eat_op("=")
                self.next()
            return A.SetStmt([])
        if self.eat_kw("RESOURCE"):
            self.expect_kw("GROUP")
            if self.eat_kw("IF"):
                self.expect_kw("NOT")
                self.expect_kw("EXISTS")
            self.ident()
            while self.peek().kind in (T.IDENT, T.QIDENT, T.NUMBER, T.STRING):
                self.next()
            return A.SetStmt([])
        if self.eat_kw("USER"):
            ine = False
            if self.eat_kw("IF"):
                self.expect_kw("NOT")
                self.expect_kw("EXISTS")
                ine = True
            users = [self.user_spec(with_password=True)]
            while self.eat_op(","):
                users.append(self.user_spec(with_password=True))
            return A.CreateUserStmt(users, ine)
        if self.eat_kw("DATABASE", "SCHEMA"):
            ine = False
            if self.eat_kw("IF"):
                self.expect_kw("NOT")
                self.expect_kw("EXISTS")
                ine = True
            name = self.ident()
            while self.at_kw("DEFAULT", "CHARACTER", "CHARSET", "COLLATE"):
                self.eat_kw("DEFAULT")
                if self.eat_kw("CHARACTER"):
                    self.expect_kw("SET")
                    self.eat_op("=")
                    self.ident()
                elif self.eat_kw("CHARSET"):
                    self.eat_op("=")
                    self.ident()
                elif self.eat_kw("COLLATE"):
                    self.eat_op("=")
                    self.ident()
            return A.CreateDatabaseStmt(name, ine)
        if self.eat_kw("UNIQUE"):
            self.expect_kw("INDEX")
            return self._create_index(unique=True)
        if self.eat_kw("INDEX"):
            return self._create_index(unique=False)
        self.expect_kw("TABLE")
        ine = False
        if self.eat_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            ine = True
        table = self.table_name()
        if self.eat_kw("LIKE"):
            return A.CreateTableStmt(table, [], if_not_exists=ine, like=self.table_name())
        columns, indexes, fks = [], [], []
        self.expect_op("(")
        while True:
            if self.at_kw("PRIMARY"):
                self.next()
                self.expect_kw("KEY")
                if self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_op("("):
                    self.ident()  # MySQL ignores the PK's given name
                idx = A.IndexDef("primary", self._index_cols(), unique=True, primary=True)
                self._index_opts()
                indexes.append(idx)
            elif self.at_kw("CHECK"):
                self.next()
                self.expect_op("(")
                self.expr()  # table CHECK constraint: parsed, not enforced
                self.expect_op(")")
                if self.eat_kw("NOT"):
                    self.expect_kw("ENFORCED")
                else:
                    self.eat_kw("ENFORCED")
            elif self.at_kw("UNIQUE"):
                self.next()
                self.eat_kw("KEY") or self.eat_kw("INDEX")
                name = ""
                if self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_op("("):
                    name = self.ident()
                indexes.append(A.IndexDef(name, self._index_cols(), unique=True))
                self._index_opts()
            elif self.at_kw("KEY", "INDEX", "FULLTEXT"):
                if self.eat_kw("FULLTEXT"):
                    self.eat_kw("KEY") or self.eat_kw("INDEX")
                else:
                    self.next()
                name = ""
                if self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_op("("):
                    name = self.ident()
                indexes.append(A.IndexDef(name, self._index_cols()))
                self._index_opts()
            elif self.at_kw("CONSTRAINT", "FOREIGN"):
                fk_name = ""
                if self.eat_kw("CONSTRAINT"):
                    if not self.at_kw("FOREIGN", "UNIQUE", "PRIMARY", "CHECK"):
                        fk_name = self.ident()
                if self.at_kw("CHECK"):
                    self.next()
                    self.expect_op("(")
                    self.expr()
                    self.expect_op(")")
                    if self.eat_kw("NOT"):
                        self.expect_kw("ENFORCED")
                    else:
                        self.eat_kw("ENFORCED")
                    if not self.eat_op(","):
                        break
                    continue
                if self.eat_kw("FOREIGN"):
                    self.expect_kw("KEY")
                    if self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_op("("):
                        self.ident()
                    cols = self._index_cols()
                    self.expect_kw("REFERENCES")
                    rt = self.table_name()
                    rcols = self._index_cols()
                    on_delete = on_update = "restrict"
                    while self.eat_kw("ON"):
                        which = "delete" if self.eat_kw("DELETE") else ("update" if self.eat_kw("UPDATE") else "")
                        if self.eat_kw("CASCADE"):
                            act = "cascade"
                        elif self.eat_kw("RESTRICT"):
                            act = "restrict"
                        elif self.eat_kw("SET") and self.eat_kw("NULL"):
                            act = "set_null"
                        elif self.eat_kw("NO") and self.eat_kw("ACTION"):
                            act = "no_action"
                        else:
                            act = "restrict"
                        if which == "delete":
                            on_delete = act
                        elif which == "update":
                            on_update = act
                    fks.append(A.ForeignKeyDef(fk_name, [c for c, _ in cols], rt, [c for c, _ in rcols], on_delete, on_update))
                elif self.eat_kw("UNIQUE"):
                    self.eat_kw("KEY") or self.eat_kw("INDEX")
                    name = fk_name
                    if self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_op("("):
                        name = self.ident()
                    indexes.append(A.IndexDef(name, self._index_cols(), unique=True))
                elif self.eat_kw("PRIMARY"):
                    self.expect_kw("KEY")
                    indexes.append(A.IndexDef("primary", self._index_cols(), unique=True, primary=True))
                    self._index_opts()
            else:
                columns.append(self.column_def())
            if not self.eat_op(","):
                break
        self.expect_op(")")
        options = self._table_options()
        while self.at_op(",") and self.peek(1).kind is T.IDENT and self.peek(1).upper in _TABLE_OPTION_KWS:
            self.next()  # CREATE TABLE options may be comma-separated
            options.update(self._table_options())
        if self.at_kw("PARTITION"):
            options["partition_by"] = self._partition_clause()
            # trailing options may follow the partition list
            options.update(self._table_options())
        if self.eat_kw("ON"):
            self.expect_kw("COMMIT")
            self.expect_kw("DELETE")
            self.expect_kw("ROWS")
        select = None
        if self.eat_kw("AS") or self.at_kw("SELECT"):
            select = self.select_or_union()
        return A.CreateTableStmt(table, columns, indexes, fks, ine, options, None, select)

    def _create_index(self, unique: bool) -> A.CreateIndexStmt:
        name = self.ident()
        self.expect_kw("ON")
        table = self.table_name()
        cols = self._index_cols()
        return A.CreateIndexStmt(name, table, cols, unique)

    def _index_opts(self):
        """Swallow index tail options: USING BTREE/HASH, COMMENT, invisible,
        clustered attrs (ref: parser.y IndexOptionList)."""
        while True:
            if self.eat_kw("USING"):
                self.ident()
            elif self.eat_kw("COMMENT"):
                self.next()
            elif self.at_kw("VISIBLE", "INVISIBLE", "CLUSTERED", "NONCLUSTERED", "GLOBAL", "LOCAL"):
                self.next()
            elif self.eat_kw("KEY_BLOCK_SIZE"):
                self.eat_op("=")
                self.expect_number()
            else:
                return

    def _partition_name(self) -> str:
        """Partition names may start with a digit (2023p1) — the lexer
        splits that into NUMBER+IDENT; rejoin them."""
        if self.peek().kind is T.NUMBER and self.peek(1).kind is T.IDENT:
            n = self.next().text
            return n + self.next().text
        if self.peek().kind is T.NUMBER:
            return self.next().text
        return self.ident()

    def _partition_clause(self) -> dict:
        """PARTITION BY RANGE/LIST/HASH/KEY ... — parsed into a plan-visible
        dict; execution treats partitioned tables as one keyspace for now
        (ref: parser.y PartitionOpt; rule_partition_processor.go prunes)."""
        self.expect_kw("PARTITION")
        self.expect_kw("BY")
        method = self.next().upper  # RANGE | LIST | HASH | KEY | LINEAR?
        if method == "LINEAR":
            method = self.next().upper
        columns = False
        if self.eat_kw("COLUMNS"):
            columns = True
        exprs = []
        if self.at_op("("):
            self.expect_op("(")
            if not self.at_op(")"):
                while True:
                    exprs.append(self.expr())
                    if not self.eat_op(","):
                        break
            self.expect_op(")")
        n_parts = None
        if self.eat_kw("PARTITIONS"):
            n_parts = self.expect_number()
        parts = []
        part_exprs = exprs
        if self.eat_op("("):
            while True:
                self.expect_kw("PARTITION")
                pname = self.ident()
                pdef = {"name": pname}
                if self.eat_kw("VALUES"):
                    if self.eat_kw("LESS"):
                        self.expect_kw("THAN")
                        if self.eat_kw("MAXVALUE"):
                            pdef["less_than"] = "MAXVALUE"
                        else:
                            self.expect_op("(")
                            vals = []
                            while True:
                                vals.append("MAXVALUE" if self.eat_kw("MAXVALUE") else self.expr())
                                if not self.eat_op(","):
                                    break
                            self.expect_op(")")
                            pdef["less_than"] = vals
                    elif self.eat_kw("IN"):
                        self.expect_op("(")
                        vals = []
                        while True:
                            if self.eat_op("("):
                                row = []
                                while True:
                                    row.append(self.expr())
                                    if not self.eat_op(","):
                                        break
                                self.expect_op(")")
                                vals.append(row)
                            else:
                                vals.append(self.expr())
                            if not self.eat_op(","):
                                break
                        self.expect_op(")")
                        pdef["in"] = vals
                while self.at_kw("COMMENT", "ENGINE", "PLACEMENT", "TABLESPACE",
                                 "MAX_ROWS", "MIN_ROWS", "DATA", "INDEX"):
                    kw2 = self.next().upper
                    if kw2 == "PLACEMENT":
                        self.expect_kw("POLICY")
                    elif kw2 in ("DATA", "INDEX"):
                        self.expect_kw("DIRECTORY")
                    self.eat_op("=")
                    self.next()
                parts.append(pdef)
                if not self.eat_op(","):
                    break
            self.expect_op(")")
        return {"method": method, "columns": columns, "n": n_parts, "parts": parts, "exprs": part_exprs}

    def _index_cols(self) -> list:
        self.expect_op("(")
        out = []
        while True:
            if self.at_op("("):
                # expression index element ((expr)): parsed and marked —
                # creation sites drop the element, and a UNIQUE index that
                # lost one must ALSO drop uniqueness (the remaining columns
                # would otherwise enforce a STRICTER constraint). ref:
                # pkg/ddl/index.go buildIndexColumns expression columns
                self.next()
                self.expr()
                self.expect_op(")")
                self.eat_kw("ASC") or self.eat_kw("DESC")
                out.append(("__expr__", -2))
            else:
                c = self.ident()
                plen = -1
                if self.eat_op("("):
                    plen = self.expect_number()
                    self.expect_op(")")
                self.eat_kw("ASC") or self.eat_kw("DESC")
                out.append((c, plen))
            if not self.eat_op(","):
                break
        self.expect_op(")")
        return out

    def column_def(self) -> A.ColumnDef:
        name = self.ident()
        ts = self.type_spec()
        cd = A.ColumnDef(name, ts)
        while True:
            if self.eat_kw("NOT"):
                self.expect_kw("NULL")
                cd.not_null = True
            elif self.eat_kw("NULL"):
                pass
            elif self.eat_kw("DEFAULT"):
                cd.default = self.default_value()
            elif self.eat_kw("AUTO_INCREMENT"):
                cd.auto_increment = True
            elif self.eat_kw("PRIMARY"):
                self.expect_kw("KEY")
                cd.primary_key = True
            elif self.eat_kw("KEY"):
                cd.primary_key = True
            elif self.eat_kw("UNIQUE"):
                self.eat_kw("KEY")
                cd.unique = True
            elif self.eat_kw("COMMENT"):
                cd.comment = self.next().text
            elif self.eat_kw("COLLATE"):
                cd.type.collate = self.ident().lower()
            elif self.eat_kw("CHARACTER"):
                self.expect_kw("SET")
                cd.type.charset = self.ident().lower()
            elif self.eat_kw("ON"):
                self.expect_kw("UPDATE")
                fn = self.ident()
                if self.eat_op("("):
                    if self.peek().kind is T.NUMBER:
                        self.expect_number()
                    self.expect_op(")")
                cd.on_update_now = fn.lower() in ("current_timestamp", "now")
            elif self.eat_kw("REFERENCES"):
                self.table_name()
                self._index_cols()
            elif self.at_kw("GENERATED", "AS"):
                # [GENERATED ALWAYS] AS (expr) [VIRTUAL|STORED]
                if self.eat_kw("GENERATED"):
                    self.expect_kw("ALWAYS")
                self.expect_kw("AS")
                self.expect_op("(")
                cd.generated = self.expr()
                self.expect_op(")")
                if self.eat_kw("STORED"):
                    cd.generated_stored = True
                else:
                    self.eat_kw("VIRTUAL")
            elif self.eat_kw("CHECK") or (self.at_kw("CONSTRAINT") and self.eat_kw("CONSTRAINT")):
                if not self.at_op("("):
                    if not self.at_kw("CHECK"):
                        self.ident()  # constraint name
                    self.eat_kw("CHECK")
                self.expect_op("(")
                cd.check = self.expr()
                self.expect_op(")")
                if self.eat_kw("NOT"):
                    self.expect_kw("ENFORCED")
                else:
                    self.eat_kw("ENFORCED")
            elif self.eat_kw("BINARY"):
                pass  # char(n) BINARY -> binary collation attribute
            elif self.at_kw("CLUSTERED", "NONCLUSTERED"):
                self.next()  # TiDB clustered-index attribute on the PK
            elif self.eat_kw("SERIAL"):
                self.expect_kw("DEFAULT")
                self.expect_kw("VALUE")
                cd.auto_increment = True
            elif self.eat_kw("AUTO_RANDOM"):
                if self.eat_op("("):
                    self.expect_number()
                    self.expect_op(")")
            else:
                return cd

    def default_value(self):
        t = self.peek()
        if t.kind is T.IDENT and t.upper in ("CURRENT_TIMESTAMP", "NOW"):
            self.next()
            if self.eat_op("("):
                if self.peek().kind is T.NUMBER:
                    self.expect_number()  # fsp
                self.expect_op(")")
            return A.FuncCall("now", [])
        if t.kind is T.IDENT and t.upper == "NEXT":
            self.next()
            self.expect_kw("VALUE")
            self.expect_kw("FOR")
            seq = self.table_name()
            return A.FuncCall("nextval", [A.Literal(seq.name, "str")])
        if self.at_op("("):
            self.next()
            e = self.expr()
            self.expect_op(")")
            return e
        return self.unary_expr()

    def _table_options(self) -> dict:
        opts = {}
        while True:
            if self.eat_kw("ENGINE"):
                self.eat_op("=")
                opts["engine"] = self.ident()
            elif self.eat_kw("AUTO_INCREMENT"):
                self.eat_op("=")
                opts["auto_increment"] = self.expect_number()
            elif self.eat_kw("DEFAULT"):
                continue
            elif self.eat_kw("CHARSET"):
                self.eat_op("=")
                opts["charset"] = self.ident().lower()
            elif self.eat_kw("CHARACTER"):
                self.expect_kw("SET")
                self.eat_op("=")
                opts["charset"] = self.ident().lower()
            elif self.eat_kw("COLLATE"):
                self.eat_op("=")
                opts["collate"] = self.ident().lower()
            elif self.eat_kw("COMMENT"):
                self.eat_op("=")
                opts["comment"] = self.next().text
            elif self.eat_kw("TTL"):
                self.eat_op("=")
                opts["ttl"] = self.expr()  # col + INTERVAL n UNIT
            elif self.at_kw(
                "AUTO_ID_CACHE", "AUTO_RANDOM_BASE", "SHARD_ROW_ID_BITS",
                "PRE_SPLIT_REGIONS", "KEY_BLOCK_SIZE", "STATS_PERSISTENT",
                "STATS_AUTO_RECALC", "STATS_SAMPLE_PAGES", "MAX_ROWS",
                "MIN_ROWS", "AVG_ROW_LENGTH", "CHECKSUM", "DELAY_KEY_WRITE",
                "ROW_FORMAT", "COMPRESSION", "CONNECTION", "PACK_KEYS",
                "STATS_BUCKETS", "STATS_TOPN", "STATS_COL_CHOICE",
                "STATS_COL_LIST", "STATS_SAMPLE_RATE", "INSERT_METHOD",
                "SECONDARY_ENGINE", "TTL_ENABLE", "TTL_JOB_INTERVAL",
                "PLACEMENT", "AUTOEXTEND_SIZE", "ENCRYPTION",
            ):
                name = self.next().upper.lower()
                self.eat_kw("POLICY")  # PLACEMENT POLICY [=] x
                self.eat_op("=")
                opts[name] = self.next().text  # number / ident / string
            else:
                return opts

    def drop_stmt(self):
        self.next()
        if self.eat_kw("USER"):
            ie = False
            if self.eat_kw("IF"):
                self.expect_kw("EXISTS")
                ie = True
            users = [self.user_spec()[:2]]
            while self.eat_op(","):
                users.append(self.user_spec()[:2])
            return A.DropUserStmt(users, ie)
        if self.eat_kw("DATABASE", "SCHEMA"):
            ie = False
            if self.eat_kw("IF"):
                self.expect_kw("EXISTS")
                ie = True
            return A.DropDatabaseStmt(self.ident(), ie)
        if self.eat_kw("INDEX"):
            name = self.ident()
            self.expect_kw("ON")
            return A.DropIndexStmt(name, self.table_name())
        if self.eat_kw("VIEW"):
            ie = False
            if self.eat_kw("IF"):
                self.expect_kw("EXISTS")
                ie = True
            names = [self.table_name()]
            while self.eat_op(","):
                names.append(self.table_name())
            return A.DropViewStmt(names, ie)
        if self.eat_kw("ROLE"):
            users = [self.user_spec()[:2]]
            while self.eat_op(","):
                users.append(self.user_spec()[:2])
            return A.DropUserStmt(users, True)
        if self.eat_kw("CHANGEFEED"):
            return A.ChangefeedStmt("drop", self.ident())
        if self.eat_kw("PLACEMENT"):
            self.expect_kw("POLICY")
            if self.eat_kw("IF"):
                self.expect_kw("EXISTS")
            self.ident()
            return A.SetStmt([])
        if self.eat_kw("RESOURCE"):
            self.expect_kw("GROUP")
            if self.eat_kw("IF"):
                self.expect_kw("EXISTS")
            self.ident()
            return A.SetStmt([])
        if self.eat_kw("STATS"):
            while self.peek().kind in (T.IDENT, T.QIDENT):
                self.next()
                self.eat_op(",")
            return A.SetStmt([])
        if self.at_kw("GLOBAL", "SESSION") and self.peek(1).upper == "BINDING":
            scope = self.next().upper.lower()
            self.next()
            self.expect_kw("FOR")
            target = self.statement()
            hinted = self.statement() if self.eat_kw("USING") else None
            return A.BindingStmt("drop", scope, target, hinted)
        self.eat_kw("GLOBAL")
        self.eat_kw("TEMPORARY")
        if self.eat_kw("SEQUENCE"):
            ie = False
            if self.eat_kw("IF"):
                self.expect_kw("EXISTS")
                ie = True
            names = [self.table_name()]
            while self.eat_op(","):
                names.append(self.table_name())
            return A.DropSequenceStmt(names, ie)
        if self.eat_kw("BINDING"):
            self.expect_kw("FOR")
            target = self.statement()
            hinted = self.statement() if self.eat_kw("USING") else None
            return A.BindingStmt("drop", "session", target, hinted)
        self.eat_kw("TEMPORARY")
        self.expect_kw("TABLE")
        ie = False
        if self.eat_kw("IF"):
            self.expect_kw("EXISTS")
            ie = True
        tables = [self.table_name()]
        while self.eat_op(","):
            tables.append(self.table_name())
        return A.DropTableStmt(tables, ie)

    def alter_stmt(self):
        self.next()
        if self.eat_kw("USER"):
            ie = False
            if self.eat_kw("IF"):
                self.expect_kw("EXISTS")
                ie = True
            users = [self.user_spec(with_password=True)]
            while self.eat_op(","):
                users.append(self.user_spec(with_password=True))
            return A.AlterUserStmt(users, ie)
        if self.eat_kw("SEQUENCE"):
            name = self.table_name()
            while self.peek().kind in (T.IDENT, T.QIDENT, T.NUMBER):
                self.next()
            return A.CreateSequenceStmt(name, True, {})
        if self.eat_kw("DATABASE", "SCHEMA"):
            if self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_kw("DEFAULT", "CHARACTER", "CHARSET", "COLLATE"):
                self.ident()
            while self.at_kw("DEFAULT", "CHARACTER", "CHARSET", "COLLATE"):
                self.eat_kw("DEFAULT")
                if self.eat_kw("CHARACTER"):
                    self.expect_kw("SET")
                elif not (self.eat_kw("CHARSET") or self.eat_kw("COLLATE")):
                    break
                self.eat_op("=")
                self.ident()
            return A.SetStmt([])
        if self.eat_kw("INSTANCE") or self.eat_kw("RANGE"):
            while self.peek().kind in (T.IDENT, T.QIDENT, T.NUMBER, T.STRING):
                self.next()
            return A.SetStmt([])
        self.expect_kw("TABLE")
        table = self.table_name()
        specs = []
        while True:
            if self.eat_kw("ADD"):
                if self.eat_kw("COLUMN"):
                    cd = self.column_def()
                    pos = ""
                    if self.eat_kw("FIRST"):
                        pos = "first"
                    elif self.eat_kw("AFTER"):
                        pos = "after:" + self.ident()
                    specs.append(A.AlterTableSpec("add_column", column=cd, position=pos))
                elif self.eat_kw("INDEX", "KEY"):
                    name = ""
                    if self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_op("("):
                        name = self.ident()
                    specs.append(A.AlterTableSpec("add_index", index=A.IndexDef(name, self._index_cols())))
                elif self.eat_kw("UNIQUE"):
                    self.eat_kw("INDEX") or self.eat_kw("KEY")
                    name = ""
                    if self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_op("("):
                        name = self.ident()
                    specs.append(A.AlterTableSpec("add_index", index=A.IndexDef(name, self._index_cols(), unique=True)))
                elif self.eat_kw("PRIMARY"):
                    self.expect_kw("KEY")
                    specs.append(A.AlterTableSpec("add_index", index=A.IndexDef("primary", self._index_cols(), unique=True, primary=True)))
                    self._index_opts()
                elif self.eat_kw("STATS_EXTENDED"):
                    self.ident()
                    self.ident()  # correlation | dependency
                    self._index_cols()
                    specs.append(A.AlterTableSpec("noop_option"))
                elif self.eat_kw("PARTITION"):
                    if self.at_op("("):
                        self._partition_def_list()
                    else:
                        self.eat_kw("PARTITIONS") and self.expect_number()
                    specs.append(A.AlterTableSpec("add_partition"))
                elif self.at_kw("CONSTRAINT", "CHECK", "FOREIGN"):
                    if self.eat_kw("CONSTRAINT"):
                        if not self.at_kw("CHECK", "FOREIGN", "UNIQUE", "PRIMARY"):
                            self.ident()
                    if self.eat_kw("CHECK"):
                        self.expect_op("(")
                        self.expr()
                        self.expect_op(")")
                        if self.eat_kw("NOT"):
                            self.expect_kw("ENFORCED")
                        else:
                            self.eat_kw("ENFORCED")
                        specs.append(A.AlterTableSpec("add_check"))
                    elif self.eat_kw("FOREIGN"):
                        self.expect_kw("KEY")
                        if self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_op("("):
                            self.ident()
                        self._index_cols()
                        self.expect_kw("REFERENCES")
                        self.table_name()
                        self._index_cols()
                        while self.eat_kw("ON"):
                            self.eat_kw("DELETE") or self.eat_kw("UPDATE")
                            self.eat_kw("CASCADE") or self.eat_kw("RESTRICT") or (self.eat_kw("SET") and self.eat_kw("NULL")) or (self.eat_kw("NO") and self.eat_kw("ACTION"))
                        specs.append(A.AlterTableSpec("add_foreign_key"))
                    elif self.eat_kw("UNIQUE"):
                        self.eat_kw("INDEX") or self.eat_kw("KEY")
                        name = ""
                        if self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_op("("):
                            name = self.ident()
                        specs.append(A.AlterTableSpec("add_index", index=A.IndexDef(name, self._index_cols(), unique=True)))
                    elif self.eat_kw("PRIMARY"):
                        self.expect_kw("KEY")
                        specs.append(A.AlterTableSpec("add_index", index=A.IndexDef("primary", self._index_cols(), unique=True, primary=True)))
                else:
                    cd = self.column_def()
                    pos = ""
                    if self.eat_kw("FIRST"):
                        pos = "first"
                    elif self.eat_kw("AFTER"):
                        pos = "after:" + self.ident()
                    specs.append(A.AlterTableSpec("add_column", column=cd, position=pos))
            elif self.eat_kw("DROP"):
                if self.eat_kw("COLUMN"):
                    specs.append(A.AlterTableSpec("drop_column", name=self.ident()))
                elif self.eat_kw("INDEX", "KEY"):
                    specs.append(A.AlterTableSpec("drop_index", name=self.ident()))
                elif self.eat_kw("PRIMARY"):
                    self.expect_kw("KEY")
                    specs.append(A.AlterTableSpec("drop_index", name="primary"))
                elif self.eat_kw("PARTITION"):
                    self._name_list_or_all()
                    specs.append(A.AlterTableSpec("drop_partition"))
                elif self.eat_kw("FOREIGN"):
                    self.expect_kw("KEY")
                    specs.append(A.AlterTableSpec("drop_foreign_key", name=self.ident()))
                elif self.eat_kw("CHECK") or self.eat_kw("CONSTRAINT"):
                    specs.append(A.AlterTableSpec("drop_check", name=self.ident()))
                else:
                    specs.append(A.AlterTableSpec("drop_column", name=self.ident()))
            elif self.eat_kw("MODIFY"):
                self.eat_kw("COLUMN")
                cd = self.column_def()
                specs.append(A.AlterTableSpec("modify_column", column=cd))
            elif self.eat_kw("CHANGE"):
                self.eat_kw("COLUMN")
                old = self.ident()
                cd = self.column_def()
                specs.append(A.AlterTableSpec("change_column", column=cd, name=old))
            elif self.eat_kw("RENAME"):
                if self.eat_kw("INDEX"):
                    old = self.ident()
                    self.expect_kw("TO")
                    specs.append(A.AlterTableSpec("rename_index", name=old, new_name=self.ident()))
                else:
                    self.eat_kw("TO") or self.eat_kw("AS")
                    specs.append(A.AlterTableSpec("rename", new_name=self.ident()))
            elif self.at_kw("SET"):
                # ALTER TABLE t SET {COLUMNAR | TIFLASH} REPLICA n (ref:
                # TiDB's `SET TIFLASH REPLICA` DDL — ours attaches the
                # changefeed-fed columnar replica tier, ISSUE 12)
                self.next()
                if not self.eat_kw("COLUMNAR", "TIFLASH"):
                    raise ParseError(f"expected COLUMNAR or TIFLASH after SET at {self._where()}")
                self.expect_kw("REPLICA")
                n = int(self.expect_number())
                specs.append(A.AlterTableSpec("set_columnar_replica", options={"count": n}))
            elif self.at_kw("ATTRIBUTES"):
                self.next()
                self.eat_op("=")
                self.next()
                specs.append(A.AlterTableSpec("noop_option"))
            elif self.at_kw("FIRST", "LAST"):
                # FIRST/LAST PARTITION LESS THAN (...) (TiDB interval mgmt)
                self.next()
                self.expect_kw("PARTITION")
                self.eat_kw("LESS") and self.expect_kw("THAN")
                if self.eat_op("("):
                    self.expr()
                    self.expect_op(")")
                specs.append(A.AlterTableSpec("noop_option"))
            elif self.at_kw("EXCHANGE"):
                self.next()
                self.expect_kw("PARTITION")
                pname = self.ident()
                self.expect_kw("WITH")
                self.expect_kw("TABLE")
                other = self.table_name()
                if self.eat_kw("WITH") or self.eat_kw("WITHOUT"):
                    self.expect_kw("VALIDATION")
                specs.append(A.AlterTableSpec("exchange_partition", name=pname, new_name=other.name))
            elif self.at_kw("REORGANIZE"):
                self.next()
                self.expect_kw("PARTITION")
                while self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_kw("INTO"):
                    self.ident()
                    if not self.eat_op(","):
                        break
                self.expect_kw("INTO")
                self._partition_def_list()
                specs.append(A.AlterTableSpec("reorganize_partition"))
            elif self.at_kw("COALESCE"):
                self.next()
                self.expect_kw("PARTITION")
                self.expect_number()
                specs.append(A.AlterTableSpec("coalesce_partition"))
            elif self.at_kw("TRUNCATE"):
                self.next()
                self.expect_kw("PARTITION")
                self._name_list_or_all()
                specs.append(A.AlterTableSpec("truncate_partition"))
            elif self.at_kw("PARTITION"):
                if self.peek(1).upper == "BY":
                    specs.append(A.AlterTableSpec("repartition", options=self._partition_clause()))
                else:
                    self.next()
                    self._partition_name()
                    while self.peek().kind in (T.IDENT, T.QIDENT, T.NUMBER, T.STRING):
                        self.next()
                        self.eat_op("=")
                    specs.append(A.AlterTableSpec("noop_option"))
            elif self.at_kw("REMOVE"):
                self.next()
                self.expect_kw("PARTITIONING")
                specs.append(A.AlterTableSpec("remove_partitioning"))
            elif self.at_kw("ALTER"):
                self.next()
                if self.eat_kw("CONSTRAINT"):
                    self.ident()
                    if self.eat_kw("NOT"):
                        self.expect_kw("ENFORCED")
                    else:
                        self.eat_kw("ENFORCED")
                    specs.append(A.AlterTableSpec("alter_constraint"))
                elif self.eat_kw("INDEX"):
                    self.ident()
                    self.next()  # VISIBLE | INVISIBLE
                    specs.append(A.AlterTableSpec("alter_index_visibility"))
                else:
                    self.eat_kw("COLUMN")
                    cname = self.ident()
                    if self.eat_kw("SET"):
                        self.expect_kw("DEFAULT")
                        d = self.default_value()
                        specs.append(A.AlterTableSpec("set_default", name=cname, default=d))
                    else:
                        self.expect_kw("DROP")
                        self.expect_kw("DEFAULT")
                        specs.append(A.AlterTableSpec("set_default", name=cname, default=None))
            elif self.at_kw(
                "ENGINE", "AUTO_INCREMENT", "CHARSET", "CHARACTER", "COLLATE",
                "COMMENT", "DEFAULT", "CONVERT", "TTL", "TTL_ENABLE",
                "AUTO_ID_CACHE", "SHARD_ROW_ID_BITS", "ROW_FORMAT",
                "PLACEMENT", "COMPRESSION", "KEY_BLOCK_SIZE", "REMOVE_TTL",
                "STATS_BUCKETS", "STATS_TOPN", "STATS_COL_CHOICE",
                "STATS_SAMPLE_RATE", "STATS_PERSISTENT", "CACHE", "NOCACHE",
                "FORCE", "ORDER",
            ):
                if self.eat_kw("CONVERT"):
                    self.expect_kw("TO")
                    self.eat_kw("CHARACTER") and self.expect_kw("SET") or self.eat_kw("CHARSET")
                    self.ident()
                    if self.eat_kw("COLLATE"):
                        self.ident()
                    specs.append(A.AlterTableSpec("charset"))
                elif self.eat_kw("CACHE") or self.eat_kw("NOCACHE") or self.eat_kw("FORCE"):
                    specs.append(A.AlterTableSpec("noop_option"))
                elif self.eat_kw("ORDER"):
                    self.expect_kw("BY")
                    self.by_list()
                    specs.append(A.AlterTableSpec("noop_option"))
                elif self.eat_kw("REMOVE_TTL"):
                    specs.append(A.AlterTableSpec("table_option", options={"remove_ttl": True}))
                else:
                    o = self._table_options()
                    if not o and not self.at_op(",") and not self.at_kw(";"):
                        raise ParseError(f"unsupported ALTER option at {self._where()}")
                    specs.append(A.AlterTableSpec("table_option", options=o))
            else:
                raise ParseError(f"unsupported ALTER action at {self._where()}")
            if not self.eat_op(","):
                break
        return A.AlterTableStmt(table, specs)

    def _partition_def_list(self):
        self.expect_op("(")
        depth = 1
        while depth and self.peek().kind is not T.EOF:
            if self.at_op("("):
                depth += 1
            elif self.at_op(")"):
                depth -= 1
            self.next()

    def _name_list_or_all(self):
        if self.eat_kw("ALL"):
            return
        while True:
            self.ident()
            if not self.eat_op(","):
                break

    def rename_stmt(self):
        self.next()
        if self.eat_kw("USER"):
            while True:
                self.user_spec()
                self.expect_kw("TO")
                self.user_spec()
                if not self.eat_op(","):
                    break
            return A.SetStmt([])
        self.expect_kw("TABLE")
        pairs = []
        while True:
            old = self.table_name()
            self.expect_kw("TO")
            pairs.append((old, self.table_name()))
            if not self.eat_op(","):
                break
        return A.RenameTableStmt(pairs)

    # ---- SET / SHOW / EXPLAIN / ANALYZE / ADMIN / BRIE ----
    def set_stmt(self) -> A.SetStmt:
        self.next()
        if self.eat_kw("PASSWORD"):
            if self.eat_kw("FOR"):
                self.user_spec()
            self.expect_op("=")
            self.next()
            return A.SetStmt([])
        if self.eat_kw("RESOURCE"):
            self.expect_kw("GROUP")
            self.ident()
            return A.SetStmt([])
        if self.at_kw("ROLE", "DEFAULT"):
            # SET [DEFAULT] ROLE ... TO ...
            while self.peek().kind is not T.EOF and not self.at_op(";"):
                self.next()
            return A.SetStmt([])
        if self.eat_kw("NAMES"):
            cs = self.next().text.lower()
            if cs == "default":
                cs = "utf8mb4"
            coll = ""
            if self.eat_kw("COLLATE"):
                coll = self.next().text.lower()
            # expanded by the session (pkg/executor/set.go setCharset needs
            # @@default_collation_for_utf8mb4, which the parser can't read)
            return A.SetStmt([("session", "__set_names__",
                               A.Literal(f"{cs}|{coll}", "str"))])
        assigns = []
        while True:
            scope = "session"
            if self.eat_kw("GLOBAL"):
                scope = "global"
            elif self.eat_kw("SESSION", "LOCAL"):
                scope = "session"
            if self.at_op("@"):
                self.next()
                if self.eat_op("@"):
                    name = self.ident()
                    if name.lower() in ("global", "session") and self.eat_op("."):
                        scope = name.lower()
                        name = self.ident()
                else:
                    scope = "user"
                    name = self.ident()
            else:
                name = self.ident()
            if not (self.eat_op("=") or self.eat_op(":=")):
                raise ParseError(f"expected = at {self._where()}")
            if self.at_kw("ON", "OFF") and self.peek(1).kind in (T.OP, T.EOF) and (self.peek(1).text in (",", ";", "")):
                v = A.Literal(self.next().text, "str")
            else:
                v = self.expr()
            assigns.append((scope, name.lower(), v))
            if not self.eat_op(","):
                break
        return A.SetStmt(assigns)

    def show_stmt(self) -> A.ShowStmt:
        self.next()
        full = self.eat_kw("FULL")
        glob = self.eat_kw("GLOBAL")
        self.eat_kw("SESSION")
        s = A.ShowStmt("", full=full, global_scope=glob)
        if self.eat_kw("DATABASES", "SCHEMAS"):
            s.kind = "databases"
        elif self.eat_kw("TABLES"):
            s.kind = "tables"
            if self.eat_kw("FROM", "IN"):
                s.db = self.ident()
        elif self.eat_kw("COLUMNS", "FIELDS"):
            s.kind = "columns"
            self.expect_kw("FROM") if self.at_kw("FROM") else self.expect_kw("IN")
            s.table = self.table_name()
        elif self.eat_kw("CREATE"):
            if self.eat_kw("TABLE"):
                s.kind = "create_table"
                s.table = self.table_name()
            elif self.eat_kw("DATABASE"):
                s.kind = "create_database"
                s.db = self.ident()
            elif self.eat_kw("VIEW"):
                s.kind = "create_view"
                s.table = self.table_name()
            elif self.eat_kw("SEQUENCE"):
                s.kind = "create_sequence"
                s.table = self.table_name()
            elif self.eat_kw("USER"):
                s.kind = "create_user"
                self.user_spec()
        elif self.eat_kw("INDEX", "INDEXES", "KEYS"):
            s.kind = "index"
            self.eat_kw("FROM") or self.eat_kw("IN")
            s.table = self.table_name()
        elif self.eat_kw("GRANTS"):
            s.kind = "grants"
            if self.eat_kw("FOR"):
                self.user_spec()
                if self.eat_kw("USING"):
                    self.user_spec()
        elif self.eat_kw("BINDINGS"):
            s.kind = "bindings"
        elif self.eat_kw("VARIABLES"):
            s.kind = "variables"
        elif self.eat_kw("STATUS"):
            s.kind = "status"
        elif self.eat_kw("WARNINGS"):
            s.kind = "warnings"
        elif self.eat_kw("ERRORS"):
            s.kind = "errors"
        elif self.eat_kw("PROCESSLIST"):
            s.kind = "processlist"
        elif self.eat_kw("ENGINES"):
            s.kind = "engines"
        elif self.eat_kw("COLLATION"):
            s.kind = "collation"
        elif self.eat_kw("CHARSET", "CHARACTER"):
            self.eat_kw("SET")
            s.kind = "charset"
        elif self.eat_kw("STATS_META"):
            s.kind = "stats_meta"
        elif self.eat_kw("STATS_HISTOGRAMS"):
            s.kind = "stats_histograms"
        elif self.eat_kw("BACKUP"):
            # SHOW BACKUP LOGS (ISSUE 20; ref: `br log status`): one row
            # per attached log backup with its durable checkpoint
            if not self.eat_kw("LOGS", "LOG"):
                raise ParseError(f"expected LOGS at {self._where()}")
            s.kind = "backup_logs"
        elif self.eat_kw("CHANGEFEEDS", "CHANGEFEED"):
            # SHOW CHANGEFEEDS (ref: TiCDC `changefeed list`); the
            # singular form with a name filters to exactly that feed —
            # LIKE metacharacters in the name are escaped so `my_feed`
            # never wildcard-matches `myxfeed` (review finding)
            s.kind = "changefeeds"
            if self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_kw("LIKE", "WHERE"):
                name = self.ident()
                s.pattern = (name.replace("\\", "\\\\")
                             .replace("%", "\\%").replace("_", "\\_"))
        elif self.eat_kw("PLACEMENT"):
            # SHOW PLACEMENT [LABELS] (ref: the reference's SHOW PLACEMENT;
            # ours reports the PD's region->store map + scheduling state)
            self.eat_kw("LABELS")
            s.kind = "placement"
        elif self.eat_kw("COLUMNAR"):
            # SHOW COLUMNAR TABLES (ISSUE 12; ref: information_schema
            # .tiflash_replica): per-table delta rows, stable chunks, and
            # the applied resolved-ts frontier of the columnar replica
            self.expect_kw("TABLES")
            s.kind = "columnar"
        elif self.eat_kw("TABLE"):
            self.expect_kw("STATUS")
            s.kind = "table_status"
            if self.eat_kw("FROM", "IN"):
                s.db = self.ident()
        elif self.eat_kw("GRANTS"):
            s.kind = "grants"
        elif self.eat_kw("PLUGINS"):
            s.kind = "plugins"
        else:
            # tolerant catch-all (ref: the reference's ~60 SHOW forms):
            # swallow the remaining tokens; execution reports the kind
            words = []
            while self.peek().kind is not T.EOF and not self.at_op(";"):
                words.append(self.next().text)
            s.kind = "other:" + " ".join(words[:4]).lower()
            return s
        if self.eat_kw("LIKE"):
            s.pattern = self.next().text
        elif self.eat_kw("WHERE"):
            s.where = self.expr()
        return s

    def explain_stmt(self):
        self.next()
        analyze = self.eat_kw("ANALYZE")
        fmt = "row"
        if self.eat_kw("FORMAT"):
            self.eat_op("=")
            fmt = self.next().text.lower()  # 'brief' | tidb_json | ...
        # DESC table shorthand
        if not analyze and self.peek().kind in (T.IDENT, T.QIDENT) and self.peek().upper not in (
            "SELECT", "INSERT", "UPDATE", "DELETE", "REPLACE", "WITH",
        ):
            t = self.table_name()
            return A.ShowStmt("columns", table=t)
        return A.ExplainStmt(self.statement(), analyze, fmt)

    def user_spec(self, with_password: bool = False):
        """'name'[@'host'] [IDENTIFIED BY 'pw'] -> (name, host[, password])."""
        t = self.next()
        name = t.text
        host = "%"
        if self.eat_op("@"):
            host = self.next().text
        if not with_password:
            return (name, host, None)
        pw = ""
        while True:
            if self.eat_kw("IDENTIFIED"):
                if self.eat_kw("WITH"):
                    self.next()  # auth plugin name
                    if self.eat_kw("BY") or self.eat_kw("AS"):
                        pw = self.next().text
                else:
                    self.expect_kw("BY")
                    pw = self.next().text
            elif self.eat_kw("RESOURCE"):
                self.expect_kw("GROUP")
                self.ident()
            elif self.eat_kw("REQUIRE"):
                while True:
                    t = self.next().upper  # SSL|X509|NONE|ISSUER|SUBJECT|CIPHER|SAN
                    if t in ("ISSUER", "SUBJECT", "CIPHER", "SAN"):
                        self.next()  # the quoted value
                    if not self.eat_kw("AND"):
                        break
            elif self.eat_kw("ATTRIBUTE"):
                self.next()
            elif self.eat_kw("COMMENT"):
                self.next()
            elif self.eat_kw("ACCOUNT"):
                self.next()  # LOCK | UNLOCK
            elif self.eat_kw("PASSWORD"):
                if self.eat_kw("EXPIRE"):
                    if self.eat_kw("INTERVAL"):
                        self.expect_number()
                        self.next()  # DAY
                    else:
                        self.eat_kw("NEVER") or self.eat_kw("DEFAULT")
                elif self.eat_kw("HISTORY") or self.eat_kw("REUSE"):
                    self.eat_kw("INTERVAL")
                    self.eat_kw("DEFAULT") or (self.expect_number() and self.eat_kw("DAY"))
            elif self.at_kw("FAILED_LOGIN_ATTEMPTS", "PASSWORD_LOCK_TIME"):
                self.next()
                self.eat_kw("UNBOUNDED") or self.expect_number()
            else:
                break
        return (name, host, pw)

    def grant_stmt(self, revoke: bool):
        """GRANT/REVOKE priv[, priv] ON [db.]tbl TO/FROM user[, user]
        (ref: parser.y GrantStmt — the subset privilege checks use)."""
        self.next()
        privs = []
        while True:
            if self.eat_kw("ALL"):
                self.eat_kw("PRIVILEGES")
                privs.append("all")
            else:
                kw = self.next().text.lower()
                # multi-word privileges (ref: mysql/privs): CREATE VIEW,
                # SHOW VIEW, CREATE USER/ROLE, ALTER ROUTINE, SHOW DATABASES,
                # LOCK TABLES, EVENT, REPLICATION SLAVE/CLIENT ...
                while self.peek().kind is T.IDENT and self.peek().upper in (
                    "VIEW", "USER", "ROLE", "ROUTINE", "DATABASES", "TABLES",
                    "TEMPORARY", "SLAVE", "CLIENT", "OPTION", "ADMIN",
                ):
                    kw += "_" + self.next().text.lower()
                privs.append(kw)
            if not self.eat_op(","):
                break
        self.expect_kw("ON")
        db = table = "*"
        if self.at_op("*"):
            self.next()
            if self.eat_op("."):
                self.expect_op("*")
        else:
            first = self.ident()
            if self.eat_op("."):
                db = first
                if self.at_op("*"):
                    self.next()
                else:
                    table = self.ident()
            else:
                table = first
        self.expect_kw("FROM" if revoke else "TO")
        users = [self.user_spec()[:2]]
        while self.eat_op(","):
            users.append(self.user_spec()[:2])
        node = A.RevokeStmt if revoke else A.GrantStmt
        return node(privs, db, table, users)

    def analyze_stmt(self) -> A.AnalyzeTableStmt:
        self.next()
        self.expect_kw("TABLE")
        tables = [self.table_name()]
        while self.eat_op(","):
            tables.append(self.table_name())
        cols = []
        while True:
            if self.eat_kw("ALL"):
                self.expect_kw("COLUMNS")
            elif self.eat_kw("PREDICATE"):
                self.expect_kw("COLUMNS")
            elif self.eat_kw("COLUMNS"):
                while True:
                    cols.append(self.ident())
                    if not self.eat_op(","):
                        break
            elif self.eat_kw("INDEX"):
                while self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_kw("WITH"):
                    self.ident()
                    if not self.eat_op(","):
                        break
            elif self.eat_kw("PARTITION"):
                while True:
                    self.ident()
                    if not self.eat_op(","):
                        break
            elif self.eat_kw("WITH"):
                self.expect_number()
                self.next()  # BUCKETS | TOPN | SAMPLES | CMSKETCH ... 
                if self.eat_kw("WIDTH") or self.eat_kw("DEPTH"):
                    pass
            else:
                break
        return A.AnalyzeTableStmt(tables, cols)

    def admin_stmt(self) -> A.AdminStmt:
        self.next()
        if self.eat_kw("CHECK"):
            if self.eat_kw("INDEX"):
                t = self.table_name()
                self.ident()
                return A.AdminStmt("check_table", [t])
            self.expect_kw("TABLE")
            tables = [self.table_name()]
            while self.eat_op(","):
                tables.append(self.table_name())
            return A.AdminStmt("check_table", tables)
        if self.eat_kw("CHECKSUM"):
            self.expect_kw("TABLE")
            tables = [self.table_name()]
            while self.eat_op(","):
                tables.append(self.table_name())
            return A.AdminStmt("checksum_table", tables)
        if self.eat_kw("SHOW"):
            if self.eat_kw("DDL"):
                if self.eat_kw("JOBS"):
                    if self.at_kw("WHERE"):
                        self.next()
                        self.expr()
                    return A.AdminStmt("show_ddl_jobs")
                return A.AdminStmt("show_ddl")
            # ADMIN SHOW t NEXT_ROW_ID / SLOW / BDR ROLE ...
            while self.peek().kind in (T.IDENT, T.QIDENT, T.NUMBER) and not self.at_op(";"):
                self.next()
            return A.AdminStmt("show_other")
        if self.eat_kw("CANCEL"):
            self.expect_kw("DDL")
            self.expect_kw("JOBS")
            ids = [self.expect_number()]
            while self.eat_op(","):
                ids.append(self.expect_number())
            return A.AdminStmt("cancel_ddl_jobs", job_ids=ids)
        if self.eat_kw("SET"):
            # ADMIN SET BDR ROLE PRIMARY/SECONDARY ...
            while self.peek().kind in (T.IDENT, T.QIDENT, T.NUMBER, T.STRING):
                self.next()
            return A.AdminStmt("set")
        if self.eat_kw("UNSET"):
            while self.peek().kind in (T.IDENT, T.QIDENT):
                self.next()
            return A.AdminStmt("unset")
        if self.eat_kw("RELOAD") or self.eat_kw("FLUSH"):
            while self.peek().kind in (T.IDENT, T.QIDENT):
                self.next()
            return A.AdminStmt("reload")
        if self.eat_kw("RECOVER") or self.eat_kw("CLEANUP"):
            while self.peek().kind in (T.IDENT, T.QIDENT):
                self.next()
            return A.AdminStmt("cleanup")
        raise ParseError(f"unsupported ADMIN at {self._where()}")

    def brie_stmt(self, kind: str) -> A.BRIEStmt:
        self.next()
        if kind == "backup" and self.eat_kw("LOG", "LOGS"):
            # BACKUP LOG TO 'file://dir' (ISSUE 20; ref: `br log start`):
            # attach the durable log backup changefeed
            self.expect_kw("TO")
            return A.BRIEStmt("backup_log", self.next().text)
        tables = []
        if self.eat_kw("TABLE"):
            tables.append(self.table_name())
            while self.eat_op(","):
                tables.append(self.table_name())
        elif self.eat_kw("DATABASE", "SCHEMA"):
            if self.eat_op("*"):
                pass  # BACKUP DATABASE * = full backup
            elif not self.at_kw("TO", "FROM"):
                db = self.ident()
                tables.append(A.TableName("*", db))
        if kind == "backup":
            self.expect_kw("TO")
        else:
            self.expect_kw("FROM")
        storage = self.next().text
        until_ts = None
        if kind == "restore" and self.eat_kw("UNTIL"):
            # RESTORE FROM 'file://dir' UNTIL TS = n (ISSUE 20: PITR —
            # full backup + log replay to exactly ts n)
            self.expect_kw("TS")
            self.eat_op("=")
            until_ts = self.expect_number()
        return A.BRIEStmt(kind, storage, tables, until_ts=until_ts)
