"""SQL front end: lexer, AST, recursive-descent MySQL parser
(ref: pkg/parser — goyacc grammar parser.y + ast/)."""

from . import ast
from .lexer import LexError, tokenize
from .parser import ParseError, parse, parse_expr, parse_one

__all__ = ["ast", "tokenize", "LexError", "ParseError", "parse", "parse_one", "parse_expr"]
