"""Device-resident columnar batches — the HBM representation.

Design (SURVEY.md §7 layer 1): static shapes everywhere. A region batch is
padded to a fixed capacity and carries a `row_valid` mask; NULLs are a
separate per-column mask. XLA then sees one shape per (schema, capacity)
pair and compiles one fused program per DAG fingerprint.

Type mapping onto device dtypes:

  int / uint       int64  (uint64 bit-cast; unsigned compare via sign-flip)
  double / float   float64 / float32
  decimal(p,s)     int64 scaled by 10^s  — exact, VPU-friendly
  datetime/date    int64  (order-preserving packed layout, types/mytime.py)
  duration         int64 nanoseconds
  string/bytes     uint8 [N, W] padded + int32 lengths; W static per batch.
                   Lexicographic compare/sort/group uses big-endian packed
                   int64 words (pack_string_words) so strings become a small
                   tuple of sortable int64 columns.

Reference seam: these batches are what the unistore coprocessor decodes rows
into (ref: cophandler/mpp_exec.go:110-244 tableScanExec -> chunk.Chunk); we
decode straight to numpy then ship whole columns to HBM in one transfer.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..types import FieldType, TypeCode
from .chunk import Chunk
from .column import Column, numpy_dtype_for

# max packed words used for on-device string compare/group keys (8 bytes each)
STRING_WORDS = 4


@jax.tree_util.register_pytree_node_class
@dataclass
class DeviceColumn:
    """One column on device. `data` is [N] for fixed-width, [N, W] for varlen."""

    data: jax.Array
    null: jax.Array  # bool [N]; True = NULL
    length: jax.Array | None  # int32 [N] for varlen, else None
    ft: FieldType  # static

    def tree_flatten(self):
        children = (self.data, self.null, self.length)
        return children, self.ft

    @classmethod
    def tree_unflatten(cls, ft, children):
        return cls(children[0], children[1], children[2], ft)

    def is_varlen(self) -> bool:
        return self.data.ndim == 2

    @property
    def capacity(self) -> int:
        return self.data.shape[0]


@jax.tree_util.register_pytree_node_class
@dataclass
class DeviceBatch:
    """A capacity-padded batch of rows on device."""

    cols: list[DeviceColumn]
    row_valid: jax.Array  # bool [N]; False = padding
    n_rows: jax.Array  # int32 scalar (actual row count)

    def tree_flatten(self):
        return (self.cols, self.row_valid, self.n_rows), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def capacity(self) -> int:
        return self.row_valid.shape[0]


def _pad(arr: np.ndarray, capacity: int, fill=0) -> np.ndarray:
    n = len(arr)
    if n == capacity:
        return arr
    out = np.full((capacity,) + arr.shape[1:], fill, arr.dtype)
    out[:n] = arr
    return out


def host_column_arrays(col: Column, capacity: int, str_width: int | None = None):
    """Column -> (data, null, length|None) numpy arrays padded to capacity."""
    n = len(col)
    null = _pad(col.null.astype(bool), capacity, True)
    if not col.is_varlen():
        data = col.data
        if data.dtype == np.uint64:
            data = data.view(np.int64)
        return _pad(data, capacity), null, None
    lens = (col.offsets[1:] - col.offsets[:-1]).astype(np.int32)
    max_len = int(lens.max()) if n else 0
    w = int(str_width) if str_width else max(1, max_len)
    if max_len > w:
        raise ValueError(f"varlen column has a {max_len}-byte value but str_width={w}")
    data = np.zeros((capacity, w), np.uint8)
    for i in range(n):
        ln = min(int(lens[i]), w)
        data[i, :ln] = col.blob[col.offsets[i]: col.offsets[i] + ln]
    return data, null, _pad(lens, capacity)


def to_device_batch(chunk: Chunk, capacity: int | None = None, str_widths: dict[int, int] | None = None) -> DeviceBatch:
    n = chunk.num_rows()
    cap = capacity or max(1, n)
    cols = []
    for ci, col in enumerate(chunk.columns):
        _check_ci_ascii(col)
        w = (str_widths or {}).get(ci)
        data, null, length = host_column_arrays(col, cap, w)
        cols.append(
            DeviceColumn(
                jnp.asarray(data),
                jnp.asarray(null),
                jnp.asarray(length) if length is not None else None,
                col.ft,
            )
        )
    row_valid = np.zeros(cap, bool)
    row_valid[:n] = True
    return DeviceBatch(cols, jnp.asarray(row_valid), jnp.int32(n))


def shared_str_widths(chunks: list[Chunk]) -> dict[int, int]:
    """Per-column max byte width across a batch of same-schema chunks — the
    shared varlen layout a region-stacked batch must agree on (each region's
    own max would give ragged [N, W] planes that cannot stack)."""
    widths: dict[int, int] = {}
    for ch in chunks:
        for ci, col in enumerate(ch.columns):
            if not col.is_varlen():
                continue
            w = 1
            if len(col):
                w = max(int((col.offsets[1:] - col.offsets[:-1]).max()), 1)
            widths[ci] = max(widths.get(ci, 1), w)
    return widths


def _check_ci_ascii(col: Column) -> None:
    """The device CI kernels fold ASCII only; any non-ASCII byte in a
    case/accent-insensitive column routes the whole plan to the
    weight-based oracle (executor.py's NotImplementedError fallback)
    rather than comparing wrongly (VERDICT r4 weak #6). THE one routing
    check — both the single-region and the stacked batch builders call
    it, so batched and per-region dispatch can never route differently."""
    if col.ft.is_string() and col.ft.is_ci() and col.is_varlen() and len(col):
        if col.blob is not None and col.blob.size and int(col.blob.max()) >= 0x80:
            raise NotImplementedError(
                "non-ASCII data under a CI collation is oracle-evaluated"
            )


def to_stacked_device_batch(chunks: list[Chunk], capacity: int) -> DeviceBatch:
    """Stack same-schema chunks into ONE region-batched DeviceBatch whose
    every leaf carries a leading region axis: data [B, cap, ...], null/
    row_valid [B, cap], n_rows [B]. This is the input shape of the vmapped
    fused program (the batch-coprocessor analog of stacking per-region
    fragments for one launch); `jax.vmap(program, in_axes=0)` maps each
    region lane back to the single-region program unchanged.

    All chunks must share a schema; varlen columns are padded to the
    batch-wide max width (shared_str_widths). Stacking happens host-side so
    the whole batch ships to HBM in one transfer per column."""
    assert chunks, "cannot stack an empty region batch"
    widths = shared_str_widths(chunks)
    n_cols = chunks[0].num_cols()
    cols: list[DeviceColumn] = []
    for ci in range(n_cols):
        datas, nulls, lengths = [], [], []
        for ch in chunks:
            col = ch.columns[ci]
            _check_ci_ascii(col)
            data, null, length = host_column_arrays(col, capacity, widths.get(ci))
            datas.append(data)
            nulls.append(null)
            lengths.append(length)
        ft = chunks[0].columns[ci].ft
        has_len = lengths[0] is not None
        cols.append(
            DeviceColumn(
                jnp.asarray(np.stack(datas)),
                jnp.asarray(np.stack(nulls)),
                jnp.asarray(np.stack(lengths)) if has_len else None,
                ft,
            )
        )
    row_valid = np.zeros((len(chunks), capacity), bool)
    for b, ch in enumerate(chunks):
        row_valid[b, : ch.num_rows()] = True
    n_rows = np.array([ch.num_rows() for ch in chunks], np.int32)
    return DeviceBatch(cols, jnp.asarray(row_valid), jnp.asarray(n_rows))


def pack_string_words(data: jax.Array, length: jax.Array, n_words: int = STRING_WORDS) -> jax.Array:
    """[N, W] uint8 + lengths -> [N, n_words + 1] int64, big-endian packed.

    Bytes beyond each row's length are zeroed and the byte length is appended
    as a final tiebreaker word, so comparing rows as tuples of these words ==
    bytes.Compare on the originals truncated to 8*n_words bytes (the length
    word distinguishes b"a" from b"a\\x00", which zero-padding alone cannot).
    Strings differing only beyond 8*n_words bytes still tie — callers that
    need exact semantics on longer strings must fall back to the host path.
    """
    nbytes = n_words * 8
    w = data.shape[1]
    if w < nbytes:
        data = jnp.pad(data, ((0, 0), (0, nbytes - w)))
    else:
        data = data[:, :nbytes]
    pos = jnp.arange(nbytes, dtype=jnp.int32)
    data = jnp.where(pos[None, :] < length[:, None], data, 0)
    words = data.reshape(data.shape[0], n_words, 8).astype(jnp.int64)
    shifts = jnp.array([56, 48, 40, 32, 24, 16, 8, 0], jnp.int64)
    packed = (words << shifts[None, None, :]).sum(axis=-1)
    # flip sign bit so unsigned byte order == signed int64 order
    packed = packed ^ jnp.int64(-0x8000000000000000)
    return jnp.concatenate([packed, length[:, None].astype(jnp.int64)], axis=1)


def device_dtype_for(ft: FieldType):
    dt = numpy_dtype_for(ft)
    if dt is None:
        return jnp.uint8
    if dt == np.uint64:
        return jnp.int64
    return jnp.dtype(dt)
