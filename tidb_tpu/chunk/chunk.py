"""Host columnar batch (ref: pkg/util/chunk/chunk.go:35)."""

from __future__ import annotations

import numpy as np

from ..types import Datum, FieldType
from .column import Column


class Chunk:
    # _device_token: lazily-assigned monotonic identity used by the store's
    # device-batch caches (id() is reused after GC; a token never is)
    __slots__ = ("columns", "_device_token")

    def __init__(self, columns: list[Column]):
        self.columns = columns

    @classmethod
    def empty(cls, fts: list[FieldType]) -> "Chunk":
        return cls([Column.empty(ft) for ft in fts])

    @classmethod
    def from_rows(cls, fts: list[FieldType], rows: list[list[Datum]]) -> "Chunk":
        cols = []
        for ci, ft in enumerate(fts):
            cols.append(Column.from_datums(ft, [r[ci] for r in rows]))
        return cls(cols)

    def nbytes(self) -> int:
        """Host bytes held by this chunk (memory-tracker accounting)."""
        total = 0
        for c in self.columns:
            for arr in (c.data, c.null, c.offsets, c.blob):
                if arr is not None and hasattr(arr, "nbytes"):
                    total += arr.nbytes
        return total

    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    def num_cols(self) -> int:
        return len(self.columns)

    def field_types(self) -> list[FieldType]:
        return [c.ft for c in self.columns]

    def row(self, i: int) -> list[Datum]:
        return [c.get_datum(i) for c in self.columns]

    def rows(self) -> list[list[Datum]]:
        return [self.row(i) for i in range(self.num_rows())]

    def take(self, idx: np.ndarray) -> "Chunk":
        return Chunk([c.take(idx) for c in self.columns])

    def slice(self, start: int, stop: int) -> "Chunk":
        return self.take(np.arange(start, min(stop, self.num_rows())))

    @classmethod
    def concat(cls, chunks: list["Chunk"]) -> "Chunk":
        if not chunks:
            raise ValueError("concat of no chunks")
        return cls([Column.concat([ch.columns[i] for ch in chunks]) for i in range(chunks[0].num_cols())])

    def __repr__(self):
        return f"Chunk({self.num_rows()} rows × {self.num_cols()} cols)"
