"""Host columnar column (ref: pkg/util/chunk/column.go:73).

The reference Column is Arrow-flavored: nullBitmap + offsets + data + elemBuf.
Here the host form is numpy-native:

  - fixed-width types: `data` is a numpy array (int64/uint64/float64/float32),
    one slot per row; NULL rows hold a zero value and are flagged in `null`.
  - varlen types (strings/bytes/json): `offsets` (int64, n+1) into a `blob`
    uint8 buffer — same layout the reference uses, which also makes the
    chunk wire codec (codec.py) a couple of memcpys.

Decimals are held as *scaled int64* (value * 10^ft.decimal) — the device
representation — with the scale carried by the FieldType. MyDecimal objects
appear only at the edges (types/mydecimal.py).
"""

from __future__ import annotations

import numpy as np

from ..types import FieldType, TypeCode, Datum, DatumKind, MyDecimal, MyTime


def numpy_dtype_for(ft: FieldType):
    if ft.is_int():
        return np.uint64 if ft.is_unsigned() else np.int64
    if ft.tp == TypeCode.Float:
        return np.float32
    if ft.tp == TypeCode.Double:
        return np.float64
    if ft.is_decimal():
        return np.int64  # scaled by 10^ft.decimal
    if ft.is_time():
        return np.uint64  # packed datetime (mytime.py)
    if ft.is_duration():
        return np.int64  # nanoseconds
    if ft.tp in (TypeCode.Enum, TypeCode.Set, TypeCode.Bit):
        return np.uint64
    return None  # varlen


class Column:
    __slots__ = ("ft", "data", "null", "offsets", "blob")

    def __init__(self, ft: FieldType, data=None, null=None, offsets=None, blob=None):
        self.ft = ft
        self.data = data
        self.null = null
        self.offsets = offsets
        self.blob = blob

    # ---- construction -----------------------------------------------------
    @classmethod
    def empty(cls, ft: FieldType) -> "Column":
        dt = numpy_dtype_for(ft)
        if dt is None:
            return cls(ft, None, np.zeros(0, bool), np.zeros(1, np.int64), np.zeros(0, np.uint8))
        return cls(ft, np.zeros(0, dt), np.zeros(0, bool))

    @classmethod
    def from_numpy(cls, ft: FieldType, data: np.ndarray, null: np.ndarray | None = None) -> "Column":
        if null is None:
            null = np.zeros(len(data), bool)
        return cls(ft, data, null)

    @classmethod
    def from_datums(cls, ft: FieldType, datums: list[Datum]) -> "Column":
        n = len(datums)
        null = np.array([d.is_null() for d in datums], bool)
        dt = numpy_dtype_for(ft)
        if dt is None:
            parts, offs = [], np.zeros(n + 1, np.int64)
            for i, d in enumerate(datums):
                b = b""
                if not d.is_null():
                    b = d.val.encode() if isinstance(d.val, str) else bytes(d.val)
                parts.append(b)
                offs[i + 1] = offs[i] + len(b)
            blob = np.frombuffer(b"".join(parts), np.uint8).copy() if offs[-1] else np.zeros(0, np.uint8)
            return cls(ft, None, null, offs, blob)
        vals = np.zeros(n, dt)
        for i, d in enumerate(datums):
            if d.is_null():
                continue
            if ft.is_decimal():
                dec = d.val if isinstance(d.val, MyDecimal) else MyDecimal(d.val)
                vals[i] = dec.to_scaled_int(max(ft.decimal, 0))
            elif ft.is_time():
                vals[i] = d.val.packed if isinstance(d.val, MyTime) else int(d.val)
            else:
                vals[i] = d.val
        return cls(ft, vals, null)

    # ---- access ------------------------------------------------------------
    def __len__(self) -> int:
        if self.data is not None:
            return len(self.data)
        return len(self.offsets) - 1

    def is_varlen(self) -> bool:
        return self.data is None

    def get_bytes(self, i: int) -> bytes:
        return self.blob[self.offsets[i]: self.offsets[i + 1]].tobytes()

    def get_datum(self, i: int) -> Datum:
        if self.null[i]:
            return Datum.NULL
        ft = self.ft
        if self.is_varlen():
            b = self.get_bytes(i)
            if ft.tp == TypeCode.JSON:
                return Datum(DatumKind.MysqlJSON, b)
            if ft.charset == "binary":
                return Datum.bytes_(b)
            return Datum.string(b.decode("utf-8", "surrogateescape"))
        v = self.data[i]
        if ft.is_int():
            return Datum.u64(int(v)) if ft.is_unsigned() else Datum.i64(int(v))
        if ft.is_float():
            return Datum.f64(float(v)) if ft.tp == TypeCode.Double else Datum(DatumKind.Float32, float(v))
        if ft.is_decimal():
            return Datum.dec(MyDecimal.from_scaled_int(int(v), max(ft.decimal, 0)))
        if ft.is_time():
            return Datum.time(MyTime(int(v), max(ft.decimal, 0)))
        if ft.is_duration():
            return Datum.duration(int(v))
        if ft.tp == TypeCode.Enum:
            return Datum.enum_from(ft.elems, int(v))
        if ft.tp == TypeCode.Set:
            return Datum.set_from(ft.elems, int(v))
        return Datum.u64(int(v))

    def take(self, idx: np.ndarray) -> "Column":
        null = self.null[idx]
        if not self.is_varlen():
            return Column(self.ft, self.data[idx], null)
        lens = (self.offsets[1:] - self.offsets[:-1])[idx]
        offs = np.zeros(len(idx) + 1, np.int64)
        np.cumsum(lens, out=offs[1:])
        blob = np.zeros(int(offs[-1]), np.uint8)
        for j, i in enumerate(idx):
            blob[offs[j]: offs[j + 1]] = self.blob[self.offsets[i]: self.offsets[i + 1]]
        return Column(self.ft, None, null, offs, blob)

    @classmethod
    def concat(cls, cols: list["Column"]) -> "Column":
        ft = cols[0].ft
        null = np.concatenate([c.null for c in cols])
        if not cols[0].is_varlen():
            return cls(ft, np.concatenate([c.data for c in cols]), null)
        blobs = [c.blob for c in cols]
        sizes = np.array([0] + [len(c.blob) for c in cols], np.int64).cumsum()
        offs_parts = [cols[0].offsets]
        for k, c in enumerate(cols[1:], 1):
            offs_parts.append(c.offsets[1:] + sizes[k])
        return cls(ft, None, null, np.concatenate(offs_parts), np.concatenate(blobs) if blobs else np.zeros(0, np.uint8))
