from .column import Column
from .chunk import Chunk
from .device import DeviceColumn, DeviceBatch, to_device_batch, STRING_WORDS

__all__ = ["Column", "Chunk", "DeviceColumn", "DeviceBatch", "to_device_batch", "STRING_WORDS"]
