"""Root executor: split a logical DAG into a per-region pushdown plan and a
root merge plan, dispatch, and merge — the component the reference spreads
over physical-plan task splitting and the root executors
(ref: pkg/planner/core finishCopTask / PhysicalHashAgg partial-final split;
root merge pkg/executor/aggregate/agg_hash_executor.go:430; ordered result
merge pkg/distsql/select_result.go:63).

Split rules (first merge point wins; everything before it is row-local and
pushes verbatim — scans, selections, projections, broadcast joins):

  Aggregation  push Partial1, root runs the Final merge re-group; DISTINCT
               aggregates are not decomposable -> whole agg stays at root
               (ref: AggregationPushDownSolver skips distinct)
  TopN         pushed per region AND re-applied at root (global top-k is
               contained in the union of per-region top-k)
  Limit        pushed per region and re-applied at root

Executors after the merge point run at root unchanged: the Final merge
reproduces the Complete aggregation's output schema, so HAVING selections,
root TopN/Limit and output offsets apply as written.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from ..chunk import Chunk
from ..exec.builder import DEFAULT_GROUP_CAPACITY, ProgramCache
from ..exec.dag import Aggregation, ColumnInfo, DAGRequest, IndexScan, Join, Limit, Projection, Selection, Sort, TableScan, TopN, Window, current_schema_fts
from ..exec.executor import run_dag_on_chunks
from ..expr.agg import AggDesc, AggMode
from ..expr.ir import col
from .dispatch import KVRequest, SelectResult, select


@dataclass
class RootPlan:
    """The two halves of a split plan. root_dag is None when the pushdown
    result needs no root computation (plain scan shapes) — the per-region
    chunks concatenate in task (range) order, which also serves keep_order."""

    push_dag: DAGRequest
    root_dag: DAGRequest | None


def _merge_aggregation(agg: Aggregation) -> Aggregation:
    """Build the root Final-merge Aggregation over the Partial1 output
    schema [agg states..., group cols...]."""
    merge_descs = []
    idx = 0
    for d in agg.aggs:
        pf = d.partial_fts()
        args = tuple(col(idx + i, pf[i]) for i in range(len(pf)))
        idx += len(pf)
        merge_descs.append(AggDesc(d.name, args, mode=AggMode.Final, distinct=d.distinct, ft=d.ft, extra=d.extra))
    group_refs = tuple(col(idx + i, g.ft) for i, g in enumerate(agg.group_by))
    return Aggregation(group_by=group_refs, aggs=tuple(merge_descs), merge=True)


def host_only_exprs(exprs) -> bool:
    """True if any expression uses an op the device whitelist excludes (the
    runtime-blocklist analog of infer_pushdown.go IsPushDownEnabled)."""
    from ..expr.ir import EXTENSION_OPS, ScalarFunc

    HOST_ONLY = {
        "replace",
        # JSON + regexp evaluate on the host oracle (ref: the per-store
        # pushdown whitelists, infer_pushdown.go scalarExprSupportedByTiKV)
        "json_extract", "json_unquote", "json_type", "json_valid",
        "json_length", "json_keys", "json_contains", "json_member_of",
        "json_array", "json_object", "json_quote", "regexp", "regexp_like",
        "convert_using",
    }

    def walk(e):
        if isinstance(e, ScalarFunc):
            if e.op in HOST_ONLY or e.op in EXTENSION_OPS:
                return True
            return any(walk(a) for a in e.args)
        return False

    return any(walk(e) for e in exprs)


def _has_host_only_op(ex) -> bool:
    """Executor-level screen: keep any executor whose expressions use
    host-only ops at root where the oracle fallback can evaluate them
    (extension functions — incl. the subquery Apply fallback — and the
    JSON/regexp set)."""
    exprs: list = []
    if isinstance(ex, Selection):
        exprs = list(ex.conditions)
    elif isinstance(ex, Projection):
        exprs = list(ex.exprs)
    elif isinstance(ex, Aggregation):
        exprs = list(ex.group_by)
        for d in ex.aggs:
            exprs.extend(d.args)
    elif isinstance(ex, (TopN, Sort)):
        exprs = [e for e, _ in ex.order_by]
    elif isinstance(ex, Join):
        exprs = list(ex.probe_keys) + list(ex.build_keys)
        if any(_has_host_only_op(b) for b in ex.build):
            return True
    elif isinstance(ex, Window):
        exprs = list(ex.partition_by) + [e for e, _ in ex.order_by]
        for w in ex.funcs:
            exprs.extend(w.args)
    return host_only_exprs(exprs)


def split_dag(dag: DAGRequest) -> RootPlan:
    executors = dag.executors
    push: list = []
    root: list = []
    i = 0
    while i < len(executors):
        ex = executors[i]
        if not isinstance(ex, (TableScan, IndexScan)) and _has_host_only_op(ex):
            root = list(executors[i:])
            break
        if isinstance(ex, (TableScan, IndexScan, Selection, Projection, Join)):
            push.append(ex)
            i += 1
            continue
        if isinstance(ex, Aggregation):
            if any(d.distinct or d.name == "group_concat" for d in ex.aggs):
                # not decomposable: aggregate wholly at root
                root = list(executors[i:])
            else:
                push.append(replace(ex, partial=True))
                root = [_merge_aggregation(ex)] + list(executors[i + 1 :])
            break
        if isinstance(ex, (TopN, Limit)):
            push.append(ex)  # per-region pre-prune
            root = list(executors[i:])  # re-apply globally, then the rest
            break
        if isinstance(ex, Sort):
            # the root sorts the full concatenation, so a per-region
            # pre-sort would be pure wasted work (no k-way merge yet) —
            # cut here like Window and keep paging usable for the
            # row-local scan half (ref: sortexec/sort.go)
            root = list(executors[i:])
            break
        if isinstance(ex, Window):
            # window functions need the full partition: never per-region
            # (the reference runs Window at root or over whole-data TiFlash,
            # plan_to_pb.go:663 / exhaust_physical_plans window enforcement)
            root = list(executors[i:])
            break
        raise TypeError(f"unknown executor {ex}")
    push_fts = current_schema_fts(push)
    push_dag = DAGRequest(tuple(push), output_offsets=tuple(range(len(push_fts))), time_zone=dag.time_zone, flags=dag.flags)
    if not root:
        # fully pushable: apply the original offsets region-side
        return RootPlan(replace(push_dag, output_offsets=dag.output_offsets), None)
    virtual_scan = TableScan(0, tuple(ColumnInfo(-100 - i, ft) for i, ft in enumerate(push_fts)))
    root_dag = DAGRequest((virtual_scan, *root), output_offsets=dag.output_offsets, time_zone=dag.time_zone, flags=dag.flags)
    return RootPlan(push_dag, root_dag)


def execute_root(
    store,
    dag: DAGRequest,
    ranges: list,
    start_ts: int,
    aux_chunks: list | None = None,
    concurrency: int = 4,
    cache: ProgramCache | None = None,
    group_capacity: int = DEFAULT_GROUP_CAPACITY,
    paging_size: int | None = None,
    batch_cop: bool = False,
    summary_sink: list | None = None,
    tracker=None,
    low_memory: bool = False,
    small_groups: int | None = None,
    checker=None,
    backoff_weight: int = 2,
    replica_read: str = "leader",
    mesh: bool | None = None,
    mesh_min_rows: int = 0,
    isolation_engines: tuple = ("tpu",),
) -> Chunk:
    """Run a logical (Complete-mode) DAG over the store: split, dispatch the
    pushdown half per region, merge at root. The caller-visible result is
    identical to running the whole DAG over all rows at once.

    isolation_engines (tidb_isolation_read_engines) is the engine-routing
    consult (ref: kv.StoreType{TiKV,TiFlash} selection): when it includes
    `columnar` and the plan is an eligible analytical scan, the WHOLE DAG
    runs over the columnar replica's device-resident chunks at the same
    snapshot — no split, no per-region dispatch — with a typed-staleness
    fallback to the row store when the replica's frontier lags.

    mesh (tidb_enable_tpu_mesh) lets the dispatch planner shard eligible
    partial-agg/TopN pushdowns over the device mesh and merge the partial
    states ON DEVICE (psum over the region axis) — the root's Final merge
    then consumes ONE state per store instead of R per-region partials.

    paging_size applies only when the pushdown half is row-local (the store
    rejects paged aggregation/TopN/Limit); otherwise it is ignored here.
    tracker accounts per-region result bytes; low_memory switches to a
    sequential dispatch with an INCREMENTAL Partial2 fold of per-region agg
    states, so the working set stays O(one region + the group table)
    instead of O(all regions) (the spill-degradation action of the
    query MemTracker chain — VERDICT r2 weak/next #10; ref: util/memory
    action chain + agg_spill.go's bounded-memory intent)."""
    from ..util import tracing

    with tracing.span("distsql.execute_root", n_ranges=len(ranges),
                      start_ts=start_ts, low_memory=low_memory) as sp:
        out = _execute_root(
            store, dag, ranges, start_ts, aux_chunks, concurrency, cache,
            group_capacity, paging_size, batch_cop, summary_sink, tracker,
            low_memory, small_groups, checker, backoff_weight, replica_read,
            mesh, mesh_min_rows, isolation_engines,
        )
        if sp is not None:
            sp.set("rows", out.num_rows())
        return out


def _execute_root(
    store, dag, ranges, start_ts, aux_chunks, concurrency, cache,
    group_capacity, paging_size, batch_cop, summary_sink, tracker,
    low_memory, small_groups, checker, backoff_weight=2,
    replica_read="leader", mesh=None, mesh_min_rows=0,
    isolation_engines=("tpu",),
) -> Chunk:
    if "columnar" in isolation_engines:
        # engine routing (ISSUE 12): eligible analytical scans ride the
        # columnar replica; None = not ours / frontier lagged after the
        # data_not_ready wait — the row store serves as if never routed
        from ..columnar.route import try_columnar_select

        served = try_columnar_select(
            store, dag, ranges, start_ts, aux_chunks or [], cache=cache,
            group_capacity=group_capacity, small_groups=small_groups,
            backoff_weight=backoff_weight, checker=checker,
        )
        if served is not None:
            if summary_sink is not None:
                # dict entries are dispatch attribution, filtered from the
                # per-task summary lists by EXPLAIN ANALYZE (same contract
                # as batch_stats)
                summary_sink.append({"columnar": {"rows": served.num_rows()}})
            return served
    plan = split_dag(dag)
    if low_memory and plan.root_dag is not None:
        folded = _execute_root_lowmem(store, plan, ranges, start_ts, aux_chunks or [], cache, group_capacity, tracker)
        if folded is not None:
            return folded
    if paging_size is not None:
        from ..exec.dag import Aggregation as _A, Limit as _L, Sort as _S, TopN as _T, executor_walk

        if any(isinstance(e, (_A, _T, _L, _S)) for e in executor_walk(plan.push_dag.executors)):
            paging_size = None
    res: SelectResult = select(
        store,
        KVRequest(
            plan.push_dag, ranges, start_ts, concurrency=concurrency,
            aux_chunks=aux_chunks or [], paging_size=paging_size,
            batch_cop=batch_cop, small_groups=small_groups, checker=checker,
            backoff_weight=backoff_weight, replica_read=replica_read,
            mesh=mesh, mesh_min_rows=mesh_min_rows,
        ),
    )
    if summary_sink is not None:
        # per-task ExecutorExecutionSummary lists (ref: tipb exec summaries
        # consumed by EXPLAIN ANALYZE, select_result.go:499)
        summary_sink.extend(res.exec_summaries)
        if res.batch_stats is not None:
            # dict entry = batched-dispatch attribution; _explain_analyze
            # filters it from the per-task summary lists
            summary_sink.append(res.batch_stats)
    if tracker is not None:
        for c in res.chunks:
            if c is not None:
                tracker.consume(c.nbytes())
    merged = res.merged()
    if merged is None:
        merged = Chunk.empty(plan.push_dag.output_fts())
    out = merged
    if plan.root_dag is not None:
        from ..util import tracing

        # run_dag_on_chunks has the oracle fallback — a root merge whose
        # group count outgrows every capacity retry degrades, not crashes
        with tracing.span("distsql.root_merge", in_rows=merged.num_rows()):
            out = run_dag_on_chunks(plan.root_dag, [merged], cache=cache, group_capacity=group_capacity,
                                    small_groups=small_groups)
    if tracker is not None:
        for c in res.chunks:
            if c is not None:
                tracker.consume(-c.nbytes())
    return out


def _partial2_dag(plan: RootPlan) -> DAGRequest | None:
    """Fold DAG for the incremental low-memory merge: over the push half's
    partial-state schema, re-aggregate in merge mode EMITTING partial
    states again (Partial2 — associative, so region results fold pairwise;
    ref: pkg/expression/aggregation AggFunctionMode Partial2Mode)."""
    if plan.root_dag is None or len(plan.root_dag.executors) < 2:
        return None
    merge_agg = plan.root_dag.executors[1]
    if not isinstance(merge_agg, Aggregation) or not merge_agg.merge:
        return None
    p2 = replace(merge_agg, partial=True)
    scan = plan.root_dag.executors[0]
    n_out = len(p2.output_fts())
    return DAGRequest((scan, p2), output_offsets=tuple(range(n_out)))


def _execute_root_lowmem(store, plan: RootPlan, ranges, start_ts, aux_chunks, cache, group_capacity, tracker) -> Chunk | None:
    """Sequential region dispatch + pairwise Partial2 fold; None when the
    plan has no foldable merge point (caller uses the normal path)."""
    from .dispatch import select_stream

    p2 = _partial2_dag(plan)
    if p2 is None:
        return None
    # mesh=False: the whole point here is ONE region's result live at a
    # time — a mesh batch would stack every region back into memory
    req = KVRequest(plan.push_dag, ranges, start_ts, concurrency=1,
                    aux_chunks=aux_chunks, mesh=False)
    acc: Chunk | None = None
    for chunk, _sums in select_stream(store, req):
        if tracker is not None:
            tracker.consume(chunk.nbytes())
        if acc is None:
            acc = chunk
        else:
            both = Chunk.concat([acc, chunk])
            folded = run_dag_on_chunks(p2, [both], cache=cache, group_capacity=group_capacity)
            if tracker is not None:
                tracker.consume(-acc.nbytes())
                tracker.consume(-chunk.nbytes())
                tracker.consume(folded.nbytes())
            acc = folded
    if acc is None:
        acc = Chunk.empty(plan.push_dag.output_fts())
    return run_dag_on_chunks(plan.root_dag, [acc], cache=cache, group_capacity=group_capacity)
