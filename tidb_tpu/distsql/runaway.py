"""Runaway-query control (ref: pkg/resourcegroup/runaway/checker.go — the
RunawayChecker whose BeforeCopRequest hook the coprocessor client calls
before every request, checker.go:27; TiDB's own MAX_EXECUTION_TIME
enforcement rides the same mechanism).

A checker is created per statement from `max_execution_time` (ms, 0 =
unlimited) plus an explicit kill flag (KILL QUERY). The dispatch loop asks
it before every coprocessor task AND every paging round, so a scan that
fans out over many regions dies at the first boundary past the deadline —
the same granularity the reference gets from its per-request hook."""

from __future__ import annotations

import time


class QueryKilledError(Exception):
    """Surfaced as MySQL error 3024 (ER_QUERY_TIMEOUT, `timeout=True`)
    or 1317 (ER_QUERY_INTERRUPTED, explicit KILL) by the session — the
    flag is typed here at the raise site, never parsed from the text."""

    def __init__(self, message: str, timeout: bool = False):
        super().__init__(message)
        self.timeout = timeout


class RunawayChecker:
    def __init__(self, max_execution_ms: int = 0, now_fn=time.monotonic):
        self._now = now_fn
        self._deadline = (
            self._now() + max_execution_ms / 1000.0 if max_execution_ms > 0 else None
        )
        self._killed = False

    def kill(self):
        """KILL QUERY: the next dispatch boundary aborts the statement."""
        self._killed = True

    @property
    def deadline(self) -> float | None:
        """Absolute monotonic deadline (None = unlimited) — the Backoffer
        clamps its sleeps so a statement never sleeps past its own
        MAX_EXECUTION_TIME (it would only wake up to die)."""
        return self._deadline

    def before_cop_request(self):
        """The BeforeCopRequest hook: raise when over budget or killed."""
        if self._killed:
            raise QueryKilledError("Query execution was interrupted")
        if self._deadline is not None and self._now() > self._deadline:
            raise QueryKilledError(
                "Query execution was interrupted, maximum statement execution time exceeded",
                timeout=True,
            )
