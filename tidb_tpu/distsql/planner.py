"""The ONE execution planner: pick each request's execution tier by data
size and topology (ROADMAP "unify the dispatch path onto the mesh").

Three tiers, one routing seam (ref: the reference picking cop tasks vs
batch-cop vs MPP in planner/core's task-type decision, mpp_gather.go:40):

  single  one region task (or a paging request): the per-task launch path
          with its capacity ladder, retry classification and failpoints.
  pool    N region tasks over the dispatch thread pool, one XLA launch
          per region (the pre-batching shape; also the paging path).
  batch   N tasks grouped per store, stacked on a leading region axis and
          served by ONE vmapped XLA launch per (store, DAG, capacity)
          (PR 4's batch coprocessor).
  mesh    like batch, but the stacked batch is sharded over the device
          mesh under `shard_map` and the per-region PARTIAL AGGREGATE
          STATES are merged ON DEVICE — `jax.lax.psum` over the region
          axis for sum/count/avg states, pmin/pmax for extremes,
          all_gather+local-reduce for bit/first states, a device-side
          merge re-group for GROUP BY tables and a device-side re-top-k
          for TopN — so a store answers with ONE merged state instead of
          R per-region partials for the host to fold (SURVEY §3.1/§5:
          partial/final agg -> psum).

The mesh tier is the paper's north star collective on the STANDARD
`distsql.select` path; `parallel/sql.py`'s mesh_select plans (grouped
exchange, shuffle joins) ride their own shard_map programs above this
seam. Every tier shares the same up-front epoch checks, typed region
errors, breakers and replica routing — a task can fall from mesh to
batch to single without changing semantics, only launch shape.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..exec.dag import Aggregation, IndexScan, Join, Projection, Selection, TableScan, TopN

# aggregates whose Partial1 states merge with mesh collectives
# (parallel/mesh.py partial_merge_plan: additive states psum, min/max
# pmin/pmax in the right domain, bit/first via all_gather)
MESH_MERGEABLE_AGGS = frozenset({
    "count", "sum", "avg", "min", "max", "first_row",
    "bit_and", "bit_or", "bit_xor",
    "stddev_pop", "stddev_samp", "var_pop", "var_samp",
})

@dataclass(frozen=True)
class TierDecision:
    # per-request tiers: "single" | "pool" | "batch" | "mesh"
    # statement-level tiers (choose_statement_tier): "root" | "mesh" | "mpp"
    tier: str
    # mesh merge kind ("scalar" | "group" | "topn") for the request tiers;
    # exchange plan kind ("agg" | "join") for the statement tiers
    kind: str | None = None


def mesh_merge_kind(dag) -> str | None:
    """Shape gate for the mesh tier: is this pushdown DAG's result
    mergeable ON DEVICE across regions? Returns the merge kind:

      "scalar"  [scan, Sel/Proj/Join*, Aggregation(partial, no GROUP BY)]
                — flat psum/pmin/pmax of the state columns.
      "group"   same with GROUP BY — per-region group tables all_gather
                and re-aggregate in merge mode on device (HashAgg and
                StreamAgg both land here; the merge is always hash).
      "topn"    [scan, Sel/Proj/Join*, TopN] — per-region top-k
                candidates all_gather and re-top-k on device.
      None      ineligible (Complete/Final mode, DISTINCT, group_concat,
                string-valued scalar gather states, Limit/Sort tails,
                reordered output offsets).
    """
    exs = dag.executors
    if len(exs) < 2 or not isinstance(exs[0], (TableScan, IndexScan)):
        return None
    from ..exec.dag import current_schema_fts

    n_out = len(current_schema_fts(exs))
    if tuple(dag.output_offsets) != tuple(range(n_out)):
        # the merge stages index state columns positionally; split_dag's
        # push DAGs always carry identity offsets (root applies the
        # statement's), so anything else is a hand-built DAG — skip
        return None
    if not all(isinstance(e, (Selection, Projection, Join)) for e in exs[1:-1]):
        return None
    last = exs[-1]
    if isinstance(last, TopN):
        return "topn"
    if not isinstance(last, Aggregation) or not last.partial or last.merge:
        return None
    for d in last.aggs:
        if d.distinct or d.name not in MESH_MERGEABLE_AGGS:
            return None
    if last.group_by:
        return "group"
    for d in last.aggs:
        # scalar states ride flat psum lanes; a string-valued gather
        # state (first_row/min/max over varchar) has no lane to ride
        if d.name in ("min", "max", "first_row") and d.ft.is_string():
            return None
    return "scalar"


def _n_devices() -> int:
    import jax

    return len(jax.devices())


def estimated_rows(store) -> int:
    """Coarse data-size signal for the tier decision: the store's live
    key count (MemKV tracks it under its own lock). The authoritative
    check happens store-side on the actually-decoded chunks — this client
    estimate only gates the mesh ATTEMPT, the way the reference's planner
    consults stats before picking an MPP task type."""
    try:
        return len(store.kv)
    except Exception:  # noqa: BLE001 — a stats miss must never fail dispatch
        return 0


def choose_statement_tier(dag, *, allow_mpp: bool, allow_mesh: bool,
                          columnar_routed) -> TierDecision:
    """Statement-level tier pick ABOVE execute_root's per-request tiers
    (ref: mpp_gather.go:40 useMPPExecution — the reference asks "MPP?"
    once per statement before task planning). Returns:

      "mpp"   plan the statement as an exchange-linked fragment graph
              (mpp/dispatch.py): fragment planner + wire seam + columnar
              replica probe sourcing. Joins take this tier even when the
              columnar replica covers the plan — the fragments SOURCE from
              the replica instead of ceding the whole statement to it.
      "mesh"  the whole-plan mesh shortcut (parallel/sql.try_mesh_select)
              without the fragment/dispatch layer (tidb_allow_mpp=OFF).
      "root"  no statement-level shortcut: execute_root owns dispatch
              (its own per-request tiers + columnar engine routing).

    `columnar_routed` is a thunk so the engine-routing walk only runs when
    a shortcut is actually on the table (review finding on the original
    mesh gate: no double walk when mesh is off)."""
    if not allow_mesh or _n_devices() < 2:
        return TierDecision("root")
    from ..parallel.sql import mesh_eligible

    kind = mesh_eligible(dag)
    if kind is None:
        return TierDecision("root")
    if allow_mpp and kind == "join":
        # shuffle joins are the mpp tier's raison d'être: the replica
        # serves the probe scan INSIDE the fragment plan, so columnar
        # engine routing must not preempt the statement
        return TierDecision("mpp", kind)
    if columnar_routed():
        # the columnar replica owns this plan (engine routing, ISSUE 12):
        # the whole-statement shortcut must not preempt it
        return TierDecision("root")
    return TierDecision("mpp" if allow_mpp else "mesh", kind)


def choose_tier(store, req, tasks) -> TierDecision:
    """One tier per request (ref: copr task-type selection): paging and
    single-task requests stay on the per-task path; eligible partial-agg /
    TopN shapes with >= 2 devices and enough data ride the mesh; batch_cop
    requests ride the vmapped store batch; everything else the pool."""
    n = len(tasks)
    if n <= 1 or req.paging_size is not None:
        return TierDecision("pool" if (req.concurrency > 1 and n > 1) else "single")
    if req.mesh is not False:
        kind = mesh_merge_kind(req.dag)
        if (
            kind is not None
            and _n_devices() >= 2
            and estimated_rows(store) >= (req.mesh_min_rows or 0)
        ):
            return TierDecision("mesh", kind)
    if req.batch_cop:
        return TierDecision("batch")
    return TierDecision("pool" if req.concurrency > 1 else "single")
