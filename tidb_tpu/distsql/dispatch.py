"""DistSQL dispatch: split a request into per-region cop tasks, send, merge
(ref: pkg/distsql/distsql.go:56 Select + RequestBuilder request_builder.go:56;
task split copr/coprocessor.go:331 buildCopTasks; retry-on-region-error
coprocessor.go:1424).

Concurrency mirrors `tidb_distsql_scan_concurrency` (sysvar.go:1956) with a
thread pool; device execution itself serializes on the single JAX stream,
but scan-decode and host encode overlap.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from .. import topsql
from ..chunk import Chunk
from ..codec import tablecodec
from ..codec.number import encode_int_cmp
from ..exec.dag import DAGRequest
from ..store import CopRequest, KeyRange, TPUStore

I64_MIN = -(1 << 63)
I64_MAX = (1 << 63) - 1
MAX_RETRY = 8


class RegionUnavailableError(RuntimeError):
    """Every retry budget for a region is spent — MySQL error 9005
    "Region is unavailable" (ref: tidb errno.ErrRegionUnavailable; raised
    when client-go's Backoffer times out on region errors)."""


class CopInternalError(RuntimeError):
    """The coprocessor answered `other_error` — a non-retryable execution
    failure, MySQL error 1105 (ref: copr handleCopResponse returning
    errors.Errorf for OtherError)."""


# ------------------------------------------------------------ circuit breaker

class CircuitBreaker:
    """Per-store breaker (ref: client-go's store slow-score / liveness
    state machine, and the classic closed -> open -> half-open breaker).
    N consecutive failures open it; an open breaker rejects requests (the
    dispatch layer fails the store's tasks over through a PD re-placement
    instead of paying the timeout again); after `probe_after` seconds one
    probe request is let through — success closes, failure re-opens."""

    __slots__ = ("store_id", "state", "fails", "opened_at", "last_probe",
                 "threshold", "probe_after", "_now", "_lock")

    def __init__(self, store_id: int, threshold: int = 3,
                 probe_after: float = 0.05, now_fn=time.monotonic):
        self.store_id = store_id
        self.state = "closed"  # guarded_by: _lock
        self.fails = 0  # guarded_by: _lock
        self.opened_at = 0.0  # guarded_by: _lock
        self.last_probe = 0.0  # guarded_by: _lock
        self.threshold = threshold
        self.probe_after = probe_after
        self._now = now_fn
        self._lock = threading.Lock()

    def _gauge(self):  # requires: _lock
        from ..util import metrics

        metrics.BREAKER_STATE.labels(str(self.store_id)).set(
            {"closed": 0, "half-open": 1, "open": 2}[self.state])

    def allow_request(self) -> bool:
        """The probe admission is RATE-LIMITED, not a single token: a
        probe whose outcome never reaches record_success/record_failure
        (the request died on an unrelated error, the task re-split away,
        the statement was killed mid-probe) must not wedge the breaker —
        the next window simply admits another probe."""
        now = self._now()
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if now - self.opened_at < self.probe_after:
                    return False
                self.state = "half-open"  # time served: admit a probe
            elif now - self.last_probe < self.probe_after:
                return False  # a probe was admitted this window
            self.last_probe = now
            self._gauge()
            return True

    def record_success(self) -> None:
        with self._lock:
            changed = self.state != "closed" or self.fails
            self.state, self.fails = "closed", 0
            if changed:
                self._gauge()

    def state_view(self) -> str:
        """Locked state snapshot — the board's views read THROUGH this
        (vet finding: they used to read `b.state` under the board lock
        only, racing every transition made under the breaker's own)."""
        with self._lock:
            return self.state

    def probe_ready(self) -> bool:
        """Non-consuming routability check: closed, or an open/half-open
        breaker whose probe window has arrived. The replica selector
        avoids stores that return False (no point grouping lanes onto a
        tripped follower) but MUST keep offering ones that return True —
        otherwise a follower nobody routes to can never half-open-probe
        back closed (allow_request still gates the actual admission)."""
        now = self._now()
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                return now - self.opened_at >= self.probe_after
            return now - self.last_probe >= self.probe_after

    def record_failure(self) -> bool:
        """Returns True when THIS failure opened (or re-opened) the
        breaker — the caller's cue to fail the task over."""
        from ..util import metrics

        with self._lock:
            self.fails += 1
            if self.state == "half-open" or (
                self.state == "closed" and self.fails >= self.threshold
            ):
                self.state, self.opened_at = "open", self._now()
                metrics.BREAKER_TRIPS.labels(str(self.store_id)).inc()
                self._gauge()
                return True
            return self.state == "open"


class BreakerBoard:
    """All of a TPUStore's per-store breakers (client-side shared state:
    every session and dispatch thread on the store consults one board)."""

    def __init__(self, threshold: int = 3, probe_after: float = 0.05,
                 now_fn=time.monotonic):
        self.threshold = threshold
        self.probe_after = probe_after
        self._now = now_fn
        self._breakers: dict[int, CircuitBreaker] = {}  # guarded_by: _lock
        self._lock = threading.Lock()

    def get(self, store_id: int) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(store_id)
            if b is None:
                b = self._breakers[store_id] = CircuitBreaker(
                    store_id, self.threshold, self.probe_after, self._now)
            return b

    def allow_request(self, store_id: int) -> bool:
        return self.get(store_id).allow_request()

    def record_success(self, store_id: int) -> None:
        self.get(store_id).record_success()

    def record_failure(self, store_id: int) -> bool:
        return self.get(store_id).record_failure()

    def _snapshot(self) -> list:
        with self._lock:
            return list(self._breakers.items())

    def open_stores(self) -> set:
        # per-breaker states are read under each breaker's own lock, with
        # the board lock already released (board -> breaker never nests)
        return {sid for sid, b in self._snapshot() if b.state_view() == "open"}

    def unroutable_stores(self) -> set:
        """Stores the replica selector should route around right now:
        tripped breakers still inside their probe-silence window."""
        return {sid for sid, b in self._snapshot() if not b.probe_ready()}

    def states(self) -> dict:
        return {sid: b.state_view() for sid, b in self._snapshot()}

    def all_closed(self) -> bool:
        return all(b.state_view() == "closed" for sid, b in self._snapshot())


def full_table_ranges(table_id: int) -> list[KeyRange]:
    start = tablecodec.encode_row_key(table_id, I64_MIN)
    end = tablecodec.encode_row_key(table_id, I64_MAX) + b"\x00"
    return [KeyRange(start, end)]


def handle_ranges(table_id: int, pairs: list[tuple[int, int]]) -> list[KeyRange]:
    """[lo, hi] handle intervals -> key ranges (ref: ranger -> kv ranges)."""
    out = []
    for lo, hi in pairs:
        out.append(KeyRange(tablecodec.encode_row_key(table_id, lo), tablecodec.encode_row_key(table_id, hi) + b"\x00"))
    return out


@dataclass
class KVRequest:
    """(ref: kv.Request kv.go:528 — the slice the executor hands to distsql).

    aux_chunks: join build-side operands broadcast to every region task
    (resolved by the root executor from prior scans; ref: TiFlash broadcast
    join, mpp_exec.go:669)."""

    dag: DAGRequest
    ranges: list
    start_ts: int
    concurrency: int = 4
    keep_order: bool = False
    aux_chunks: list = field(default_factory=list)
    paging_size: int | None = None  # per-page row budget (ref: kv.Request Paging)
    use_wire: bool = False  # route every cop request through the serialized
    # bytes seam (coprocessor_bytes) instead of in-process objects
    batch_cop: bool = False  # group region tasks per store/chip into one
    # worker's batch (ref: copr/batch_coprocessor.go — all regions of a
    # TiFlash store travel in one request)
    small_groups: int | None = None  # planner NDV hint -> dense agg kernel
    checker: object = None  # RunawayChecker — before_cop_request() raises
    # past the deadline / after KILL (ref: resourcegroup checker.go:27)
    backoff_weight: int = 2  # tidb_backoff_weight: scales every retry
    # budget (ref: sessionctx BackOffWeight -> copr backoffer construction)
    replica_read: str = "leader"  # tidb_replica_read: leader / follower /
    # closest-replica — which peer of each region serves the cop task
    # (ref: sessionctx ReplicaRead -> kvrpcpb.Context.replica_read)
    mesh: bool | None = None  # mesh dispatch tier (tidb_enable_tpu_mesh):
    # None/True lets the planner shard eligible partial-agg/TopN shapes
    # over the device mesh and psum-merge partial states ON DEVICE; False
    # pins the request to the vmap/pool tiers (distsql/planner.py)
    mesh_min_rows: int = 0  # tidb_tpu_mesh_min_rows: data-size floor the
    # planner applies before attempting the mesh tier


@dataclass
class CopTask:
    region_id: int
    epoch: int
    ranges: list


@dataclass
class SelectResult:
    """(ref: distsql.SelectResult select_result.go:63).

    exec_summaries: one entry per cop response, flattened in TASK order
    (deterministic across runs — pool completion order never leaks into
    EXPLAIN ANALYZE attribution, honoring keep_order). batch_stats carries
    the batched-dispatch attribution ({"batches","regions","launches_saved"})
    when the batch-cop path ran, for EXPLAIN ANALYZE / TRACE surfacing."""

    chunks: list
    exec_summaries: list = field(default_factory=list)
    batch_stats: dict | None = None

    def merged(self) -> Chunk:
        return Chunk.concat(self.chunks) if self.chunks else None


def _build_tasks(store: TPUStore, ranges: list) -> list[CopTask]:
    tasks = []
    for rng in ranges:
        for region in store.cluster.regions_in_range(rng.start, rng.end):
            start = max(rng.start, region.start_key)
            end = min(rng.end, region.end_key)
            if start < end:
                tasks.append(CopTask(region.region_id, region.epoch, [KeyRange(start, end)]))
    # merge tasks per region (ref: buildCopTasks per-region aggregation)
    by_region: dict[int, CopTask] = {}
    ordered = []
    for t in tasks:
        ex = by_region.get(t.region_id)
        if ex is None:
            by_region[t.region_id] = t
            ordered.append(t)
        else:
            ex.ranges.extend(t.ranges)
    return ordered


def select_stream(store: TPUStore, req: KVRequest):
    """Sequential per-task chunk generator — the bounded-memory dispatch
    the degraded OOM path uses (one region's result live at a time;
    ref: copr worker pool degraded to a single in-order worker).

    The mesh tier applies here too (the planner's call): eligible
    partial-agg shapes run one store batch at a time, each merged on
    device, and the stream yields the per-store merged chunks — still
    bounded by one store's stacked batch. The low-memory degrade path
    pins `mesh=False` and keeps the strict one-region-at-a-time shape."""
    from .planner import choose_tier

    scan_kind = _scan_kind(req)
    with _admission_guard(store):
        pass  # saturation answered before any task is built (ISSUE 15)
    tasks = _build_tasks(store, req.ranges)
    if choose_tier(store, req, tasks).tier == "mesh":
        results: list = [None] * len(tasks)
        summaries_by_task: list = [[] for _ in tasks]
        ctx = _route_ctx(store) if req.replica_read != "leader" else None
        by_store: dict[int, list] = {}
        for i, t in enumerate(tasks):
            by_store.setdefault(_route_task(store, req, t, ctx=ctx),
                                []).append((i, t))
        for sid, entries in by_store.items():
            _run_store_batch(store, req, sid, entries, results,
                             summaries_by_task, None, scan_kind, mesh=True)
            for i, _t in entries:
                for c in results[i] or []:
                    if c is not None:
                        yield c, summaries_by_task[i]
        return
    for task in tasks:
        summaries: list = []
        for c in _run_one_task(store, req, task, summaries, scan_kind=scan_kind):
            if c is not None:
                yield c, summaries


def _scan_kind(req) -> str:
    from ..exec.dag import IndexScan

    return "index" if isinstance(req.dag.scan(), IndexScan) else "table"


def _route_ctx(store) -> tuple:
    """One (bad-store set, read-load map) snapshot for a whole routing
    pass — the batch grouping loop calls _route_task once per lane, and
    these inputs are loop-invariant there (re-snapshotting per lane
    would take the board/down/replica locks O(lanes) times)."""
    return (store.down_stores() | store.breakers.unroutable_stores(),
            store.replication.read_counts())


def _route_task(store, req, task, avoid=frozenset(), leader_only=False,
                ctx=None) -> int:
    """Pick the peer that serves this cop task (ref: client-go's replica
    selector honoring tidb_replica_read). `leader` routes to the leader;
    `follower` prefers the least-read-loaded healthy follower; `closest-
    replica` picks the least-read-loaded healthy peer, leader included
    (the in-process analog of same-AZ proximity: the least-busy chip is
    'closest'). The client does NOT pre-filter on safe_ts — the store's
    gate answers DataIsNotReady and the retry loop falls back to the
    leader, exactly the reference's wire protocol. `ctx` is an optional
    `_route_ctx` snapshot; the retry loop omits it (a retry wants fresh
    health state)."""
    cluster = store.cluster
    leader = cluster.leader_of(task.region_id)
    if leader_only or req.replica_read == "leader":
        return leader
    peers = cluster.peers_of(task.region_id)
    # skip peers the client already knows are sick: down switches AND
    # breakers inside their probe-silence window (else min-by-load keeps
    # re-picking a tripped follower — its frozen read count looks
    # attractively idle — and every batch degrades to the single path).
    # A breaker whose probe window arrived is offered again: someone has
    # to send the half-open probe that re-closes it.
    bad, loads = ctx if ctx is not None else _route_ctx(store)
    healthy = [p for p in peers if p not in avoid and p not in bad]
    if not healthy:
        return leader
    if req.replica_read == "follower":
        followers = [p for p in healthy if p != leader]
        if not followers:
            return leader
        return min(followers, key=lambda p: (loads.get(p, 0), p))
    return min(healthy, key=lambda p: (loads.get(p, 0), p))


def _failover(store, region_id: int, bad_store: int, boff) -> int | None:
    """Ask the PD to fail a region over off its sick LEADER store (ref:
    client-go marking a store unreachable): a leader transfer among the
    live peers, or a re-placement when quorum is lost. When nothing can
    serve (or the transfer timed out), backs off on the
    store_unavailable budget — maybe the store comes back or a breaker
    probe succeeds — and returns None."""
    from ..util.backoff import BackoffExhausted

    pd = getattr(store, "pd", None)
    avoid = store.breakers.open_stores() | store.down_stores()
    target = pd.failover_region(region_id, bad_store, avoid=avoid) if pd else None
    if target is None:
        try:
            boff.backoff("store_unavailable",
                         f"no healthy store for region {region_id}")
        except BackoffExhausted as exc:
            raise RegionUnavailableError(str(exc)) from exc
    return target


def _run_one_task(store, req, task, summaries, retries=MAX_RETRY,
                  dispatch_span=None, scan_kind="table", boff=None):
    """One cop task; drives the paging loop when paging is on (ref:
    copr/coprocessor.go:1393 handleCopPagingResult — each page's lastRange
    seeds the next request until the task drains). Shared by select()'s
    pool workers and the sequential select_stream path so metrics, spans,
    failpoints, wire routing AND the typed error contract cannot drift
    apart. Returns the task's chunks (retry subtasks included); summaries
    accumulate in place.

    Region errors are CLASSIFIED (ref: copr/coprocessor.go:1424
    handleCopResponse): each kind retries on its own Backoffer budget.
    store_unavailable from the LEADER feeds the store's circuit breaker
    and — once the breaker opens — fails the task over via the PD (a
    leader transfer among live peers; placement move only on quorum
    loss); from a FOLLOWER it just routes around the bad replica.
    not_leader with a usable hint switches peers immediately (one shot,
    no backoff); data_not_ready waits once on its own budget, retries
    the follower, then latches the task onto the leader."""
    import time as _time

    from ..store.errors import parse_region_error
    from ..util import failpoint as _fp
    from ..util import metrics, tracing
    from ..util.backoff import Backoffer, BackoffExhausted

    if boff is None:
        # one budget per TASK, shared with its re-split subtasks (the
        # reference allocates one Backoffer per request chain)
        boff = Backoffer(weight=req.backoff_weight, checker=req.checker)
    board = store.breakers
    t_task = _time.monotonic()
    with tracing.span(
        "distsql.cop_task",
        parent=None if tracing.current_span() is not None else dispatch_span,
        region_id=task.region_id, epoch=task.epoch,
    ) as sp:
        out_chunks: list = []
        ranges = task.ranges
        pages = 0
        local_avoid: set = set()  # follower peers this task routes around
        leader_only = False  # DataIsNotReady latch: fall back to the leader
        forced_sid: int | None = None  # NotLeader hint: one-shot target
        hint_used = False
        dnr_waits = 0  # DataIsNotReady waits before the leader fallback
        while True:
            if req.checker is not None:
                req.checker.before_cop_request()
            _fp.eval("distsql.before_task")
            if forced_sid is not None:
                sid, forced_sid = forced_sid, None
            else:
                sid = _route_task(store, req, task, avoid=local_avoid,
                                  leader_only=leader_only)
            leader = store.cluster.leader_of(task.region_id)
            if not board.allow_request(sid):
                if sid != leader:
                    # a sick FOLLOWER never fails the region over — the
                    # leader is fine; just route around the bad replica
                    local_avoid.add(sid)
                    continue
                # leader breaker open: do NOT pay the sick store's failure
                # again — fail over through the PD (leader transfer among
                # live peers, placement move only on quorum loss) or wait
                # for a probe window on the store_unavailable budget
                _failover(store, task.region_id, sid, boff)
                continue
            metrics.DISTSQL_TASKS.inc()
            # authoritative placement lookup (a miss routes through the
            # PD, never a modulo guess) — the per-store counts are what
            # bench.py's skew scenario reads before/after PD balancing
            metrics.DISTSQL_STORE_TASKS.labels(str(sid)).inc()
            creq = CopRequest(
                req.dag, ranges, req.start_ts, task.region_id, task.epoch,
                aux_chunks=req.aux_chunks, paging_size=req.paging_size,
                small_groups=req.small_groups, peer_store=sid,
                replica_read=req.replica_read != "leader" and sid != leader,
            )
            if req.use_wire:
                from ..codec.wire import decode_cop_response, encode_cop_request

                resp = decode_cop_response(store.coprocessor_bytes(encode_cop_request(creq)))
            else:
                resp = store.coprocessor(creq)
            if resp.region_error is not None:
                err = parse_region_error(resp.region_error)
                metrics.DISTSQL_RETRIES.inc()
                metrics.REGION_ERRORS.labels(err.kind).inc()
                if sp is not None:
                    sp.set("region_error", resp.region_error)
                if retries <= 0:
                    raise RegionUnavailableError(
                        f"region retries exhausted: {resp.region_error}")
                try:
                    if err.kind == "store_unavailable":
                        opened = board.record_failure(sid)
                        pd = getattr(store, "pd", None)
                        if pd is not None:
                            pd.note_store_down(sid)
                        if sid != leader:
                            # a dead follower costs a re-route, not a
                            # failover: the leader still serves (client-go
                            # trying the next peer in the selector)
                            local_avoid.add(sid)
                        elif opened:
                            _failover(store, task.region_id, sid, boff)
                        else:
                            boff.backoff("store_unavailable", resp.region_error)
                        continue  # same task, fresh routing decision
                    if err.kind == "server_busy":
                        board.record_failure(sid)
                        boff.backoff("server_busy", resp.region_error,
                                     suggested_ms=getattr(err, "backoff_ms", 0))
                        continue
                    if err.kind == "not_leader":
                        hint = getattr(err, "leader_store", -1)
                        if hint >= 0 and hint != sid and not hint_used:
                            # a usable leader hint: switch peers NOW — one
                            # immediate retry, no backoff round burned
                            # (ref: client-go updating the region cache
                            # from errorpb.NotLeader.leader and retrying)
                            hint_used = True
                            forced_sid = hint
                            continue
                        boff.backoff("not_leader", resp.region_error)
                        hint_used = False  # a fresh hint may follow the election
                        continue
                    if err.kind == "data_not_ready":
                        # the follower's safe_ts trails start_ts: one short
                        # wait and a follower retry (maybe the apply loop
                        # catches up), then the leader serves the rest of
                        # this task (ref: client-go's DataIsNotReady ->
                        # leader fallback on the maxDataNotReady budget)
                        dnr_waits += 1
                        if dnr_waits > 1:
                            leader_only = True
                        else:
                            boff.backoff("data_not_ready", resp.region_error)
                        continue
                    # epoch_not_match / region_not_found / generic miss:
                    # brief backoff, then re-split the REMAINING ranges
                    # against the fresh region view; subtask spans nest
                    # under this one (ambient)
                    boff.backoff(err.kind, resp.region_error)
                except BackoffExhausted as exc:
                    raise RegionUnavailableError(str(exc)) from exc
                for s2 in _build_tasks(store, ranges):
                    out_chunks.extend(_run_one_task(
                        store, req, s2, summaries, retries - 1,
                        scan_kind=scan_kind, boff=boff,
                    ))
                return out_chunks
            if resp.other_error is not None:
                raise CopInternalError(resp.other_error)
            board.record_success(sid)
            pd = getattr(store, "pd", None)
            if pd is not None:
                pd.note_store_up(sid)
            summaries.append(resp.exec_summaries)
            out_chunks.append(resp.chunk)
            pages += 1
            if resp.last_range is None:
                if sp is not None:
                    sp.set("pages", pages)
                    sp.set("rows", sum(c.num_rows() for c in out_chunks if c is not None))
                metrics.DISTSQL_TASK_DURATION.labels(scan_kind).observe(
                    _time.monotonic() - t_task
                )
                return out_chunks
            ranges = resp.last_range


def _run_store_batch(store, req, sid, entries, results, summaries_by_task,
                     dispatch_span, scan_kind, mesh: bool = False) -> dict:
    """ONE batched dispatch for all of a store's region tasks (ref:
    copr/batch_coprocessor.go — a TiFlash store's regions travel in one
    request): the store stacks the regions and drives one vmapped launch —
    or, when the planner chose the MESH tier (`mesh`), shards the stacked
    lanes over the device mesh and merges the partial states on device
    (the store degrades mesh -> vmap on ineligibility/overflow, so the
    contract here is identical either way).
    `sid` is the ROUTED target peer (the leader for every lane under
    tidb_replica_read='leader'; a follower group otherwise). A region
    that comes back with a region_error (stale epoch after a concurrent
    split, region folded by a merge, a follower's safe_ts gate) falls out
    of the batch into the standard _run_one_task retry path — the rest of
    the batch's results stand. Returns this batch's attribution stats."""
    import time as _time

    from ..util import failpoint as _fp
    from ..util import metrics, tracing

    if not store.breakers.allow_request(sid):
        # the store's circuit breaker is open: skip the batched dispatch
        # entirely — every lane falls out to the single-task path, which
        # owns the failover-through-PD decision (exactly like stale-epoch
        # lanes, just before the launch instead of after)
        for i, t in entries:
            results[i] = _run_one_task(
                store, req, t, summaries_by_task[i],
                dispatch_span=dispatch_span, scan_kind=scan_kind,
            )
        return {"batches": 0, "regions": 0, "launches_saved": 0,
                "mesh_batches": 0, "mesh_lanes": 0}
    creqs = []
    for i, t in entries:
        if req.checker is not None:
            req.checker.before_cop_request()
        _fp.eval("distsql.before_task")
        metrics.DISTSQL_TASKS.inc()
        metrics.DISTSQL_STORE_TASKS.labels(str(sid)).inc()
        creqs.append(CopRequest(
            req.dag, t.ranges, req.start_ts, t.region_id, t.epoch,
            aux_chunks=req.aux_chunks, small_groups=req.small_groups,
            peer_store=sid,
            replica_read=(req.replica_read != "leader"
                          and sid != store.cluster.leader_of(t.region_id)),
            mesh=mesh, mesh_min_rows=req.mesh_min_rows,
        ))
    t_batch = _time.monotonic()
    stats = {"batches": 0, "regions": 0, "launches_saved": 0,
             "mesh_batches": 0, "mesh_lanes": 0}
    batch_ids: set = set()
    mesh_ids: set = set()
    with tracing.span("distsql.batch_cop", parent=dispatch_span,
                      batch_size=len(entries),
                      tier="mesh" if mesh else "batch") as bsp:
        if req.use_wire:
            from ..codec.wire import decode_batch_cop_response, encode_batch_cop_request

            resps = decode_batch_cop_response(
                store.batch_coprocessor_bytes(encode_batch_cop_request(creqs)))
        else:
            resps = store.batch_coprocessor(creqs)
        served_ok = 0
        for (i, t), resp in zip(entries, resps):
            sums = summaries_by_task[i]
            if resp.region_error is not None:
                from ..store.errors import parse_region_error

                metrics.DISTSQL_RETRIES.inc()
                metrics.REGION_ERRORS.labels(parse_region_error(resp.region_error).kind).inc()
                # faulted lane (stale epoch, folded region, down store):
                # re-split its ranges against the fresh region view and
                # retry ONLY it through the single-task path, which owns
                # classification, backoff, breakers and failover (spans
                # nest under the batch span, ambient)
                chunks: list = []
                for s2 in _build_tasks(store, t.ranges):
                    chunks.extend(_run_one_task(
                        store, req, s2, sums, MAX_RETRY - 1, scan_kind=scan_kind,
                    ))
                results[i] = chunks
                continue
            if resp.other_error is not None:
                raise CopInternalError(resp.other_error)
            served_ok += 1
            # only lanes a vmapped launch actually served count toward
            # batch attribution — cop-cache hits, overflow fall-outs and
            # single-path degrades did not ride one (resp.batched == 0);
            # distinct ids count distinct launches (capacity buckets), so
            # launches_saved equals the store's served-per-launch-minus-one
            if resp.batched:
                stats["regions"] += 1
                batch_ids.add(resp.batched)
                if resp.mesh_merged:
                    # this lane's partial state rode the on-device psum —
                    # one merged state per store, no per-region host merge
                    stats["mesh_lanes"] += 1
                    mesh_ids.add(resp.batched)
            sums.append(resp.exec_summaries)
            results[i] = [resp.chunk]
            with tracing.span("distsql.cop_task", region_id=t.region_id,
                              epoch=t.epoch, batched=bool(resp.batched)) as sp:
                if sp is not None and resp.chunk is not None:
                    sp.set("rows", resp.chunk.num_rows())
        if served_ok:
            # at least one lane answered cleanly: the store is reachable
            # (closes a half-open probe; resets the consecutive-fail count)
            store.breakers.record_success(sid)
        stats["batches"] = len(batch_ids)
        stats["launches_saved"] = max(stats["regions"] - len(batch_ids), 0)
        stats["mesh_batches"] = len(mesh_ids)
        if bsp is not None:
            bsp.set("launches_saved", stats["launches_saved"])
            if stats["mesh_lanes"]:
                bsp.set("mesh_lanes_merged", stats["mesh_lanes"])
        metrics.DISTSQL_TASK_DURATION.labels(scan_kind).observe(
            _time.monotonic() - t_batch
        )
    return stats


def _admission_guard(store):
    """Dispatch-tier admission (ISSUE 15): when the gate's dispatch lane
    is saturated (or the server/admission-full failpoint is armed), the
    request is refused with a typed ServerIsBusy-style shed BEFORE any
    cop task is built — the store never starts work it would drop. The
    returned token is a context manager releasing the dispatch slot."""
    from contextlib import nullcontext

    gate = getattr(store, "admission", None)
    return gate.before_dispatch() if gate is not None else nullcontext()


def select(store: TPUStore, req: KVRequest) -> SelectResult:
    from ..util import tracing
    from .planner import choose_tier

    with _admission_guard(store):
        return _select_admitted(store, req)


def _select_admitted(store: TPUStore, req: KVRequest) -> SelectResult:
    from ..util import tracing
    from .planner import choose_tier

    tasks = _build_tasks(store, req.ranges)
    results: list = [None] * len(tasks)
    # per-task summary buckets, flattened in task order below: pool workers
    # finish in arbitrary order, and a shared append list would make
    # EXPLAIN ANALYZE region attribution nondeterministic across runs
    summaries_by_task: list = [[] for _ in tasks]
    # cross-thread span handoff: pool workers don't inherit contextvars,
    # so capture the dispatching thread's span here and parent the
    # per-task spans on it explicitly (pkg/util/tracing's SpanFromContext
    # handover at the copIterator worker boundary). The Top SQL resource
    # tag rides the SAME seam: workers adopt the statement's tag so the
    # store/backoff sinks attribute from pool threads.
    dispatch_span = tracing.current_span()
    stmt_tag = topsql.current_tag()
    scan_kind = _scan_kind(req)
    batch_stats: dict | None = None

    def run_task(i: int, task: CopTask):
        with topsql.adopt(stmt_tag):
            return _run_one_task(store, req, task, summaries_by_task[i],
                                 dispatch_span=dispatch_span, scan_kind=scan_kind)

    # ONE execution planner picks the tier by data size and topology
    # (distsql/planner.py): single launch -> vmapped store batch -> mesh
    # shard_map with on-device psum of the partial states. batch and mesh
    # share the per-store grouping below; mesh marks its cop requests so
    # the store merges on device.
    decision = choose_tier(store, req, tasks)
    if decision.tier in ("batch", "mesh"):
        # batched dispatch: ONE launch per STORE — the store stacks its
        # regions and runs one vmapped XLA launch (the mesh tier further
        # shards those lanes over the device mesh) instead of N serialized
        # per-region launches (ref: batch_coprocessor.go grouping regions
        # per TiFlash store, balanced by the PD's authoritative placement
        # map). Paging requests never batch: the per-page resume cursor is
        # inherently per-region sequential state.
        by_store: dict[int, list] = {}
        ctx = _route_ctx(store) if req.replica_read != "leader" else None
        for i, t in enumerate(tasks):
            # group lanes by their ROUTED peer (leader view by default;
            # follower/closest targets under tidb_replica_read) — each
            # target store still gets exactly one batched dispatch
            by_store.setdefault(_route_task(store, req, t, ctx=ctx),
                                []).append((i, t))

        def run_batch(sid, entries):
            with topsql.adopt(stmt_tag):
                return _run_store_batch(store, req, sid, entries, results,
                                        summaries_by_task, dispatch_span, scan_kind,
                                        mesh=decision.tier == "mesh")

        with ThreadPoolExecutor(max_workers=max(len(by_store), 1)) as pool:
            futs = [pool.submit(run_batch, sid, entries)
                    for sid, entries in by_store.items()]
            per_store = [f.result() for f in futs]
        batch_stats = {
            "batches": sum(s["batches"] for s in per_store),
            "regions": sum(s["regions"] for s in per_store),
            "launches_saved": sum(s["launches_saved"] for s in per_store),
            "mesh_batches": sum(s["mesh_batches"] for s in per_store),
            "mesh_lanes": sum(s["mesh_lanes"] for s in per_store),
        }
    elif req.concurrency > 1 and len(tasks) > 1:
        with ThreadPoolExecutor(max_workers=req.concurrency) as pool:
            futs = [pool.submit(run_task, i, t) for i, t in enumerate(tasks)]
            for i, f in enumerate(futs):
                results[i] = f.result()
    else:
        for i, t in enumerate(tasks):
            results[i] = run_task(i, t)

    chunks = [c for sub in results for c in sub if c is not None]
    summaries = [s for per_task in summaries_by_task for s in per_task]
    return SelectResult(chunks=chunks, exec_summaries=summaries,
                        batch_stats=batch_stats)
