from .dispatch import (
    BreakerBoard,
    CircuitBreaker,
    CopInternalError,
    KVRequest,
    RegionUnavailableError,
    SelectResult,
    select,
    full_table_ranges,
    handle_ranges,
)
from .root import RootPlan, execute_root, split_dag

__all__ = [
    "KVRequest",
    "SelectResult",
    "select",
    "full_table_ranges",
    "handle_ranges",
    "RootPlan",
    "execute_root",
    "split_dag",
    "BreakerBoard",
    "CircuitBreaker",
    "RegionUnavailableError",
    "CopInternalError",
]
