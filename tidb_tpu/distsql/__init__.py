from .dispatch import KVRequest, SelectResult, select, full_table_ranges

__all__ = ["KVRequest", "SelectResult", "select", "full_table_ranges"]
