"""Top SQL — per-digest resource attribution (ref: pkg/util/topsql).

The reference samples CPU on a timer and attributes samples to the SQL /
plan digest stored in goroutine labels, then a reporter aggregates the
samples into fixed windows of top-N digests. In-process we can do better
than statistical sampling: every layer that already measures (thread CPU
deltas at the session boundary, the fused-program clock in the store,
the Backoffer's slept intervals, the admission gate's queue wait)
records its EXACT measurement onto an ambient per-statement resource
tag, and the reporter folds finished statements into windows.

Three pieces:

  tag.py      the contextvar resource tag `(sql_digest, plan_digest)` +
              the attribution sinks layers call (no-ops when no tag is
              ambient, so untagged/background work costs one dict read)
  reporter.py the windowed top-K collector (bounded ring, "others"
              fold), per-digest EWMA cost classes (point/small/scan/
              heavy) the admission gate weighs in-flight statements by

`COLLECTOR` is the process singleton, the same shape as
`util.metrics.REGISTRY`: every session/store of the process reports
into one ledger, exactly like the reference's single topsql reporter
per tidb-server.
"""

from __future__ import annotations

from .reporter import (  # noqa: F401
    CLASS_WEIGHTS,
    COLLECTOR,
    DEFAULT_CLASS,
    OTHERS_DIGEST,
    TopSQLCollector,
    split_by_rows,
)
from .tag import (  # noqa: F401
    ResourceTag,
    activate,
    adopt,
    current_tag,
    deactivate,
    record_backoff,
    record_cop_cache_hit,
    record_device,
    record_queue_wait,
)
