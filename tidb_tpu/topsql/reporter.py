"""Windowed top-K reporter + EWMA cost classes (ref:
pkg/util/topsql/reporter — the pubsub reporter collects per-digest
records into one-minute windows, keeps the top `MaxStatementCount`
digests per metric and folds the rest into an `others` row, retaining a
bounded history).

Statements flush their finished resource tag here; the live window
auto-seals when its span elapses (checked on every record and read, so
idle processes without a PD still rotate) and the PD tick's
`topsql.report` phase forces the check on a clock. Sealed windows keep
the union of top-K digests BY EACH metric — a digest that dominates
backoff but not CPU still surfaces — and fold the remainder into one
`(others)` entry so window totals stay conservation-exact.

Cost classes: a per-digest EWMA of (cpu_ns + device_ns) per execution
buckets digests into point/small/scan/heavy. The admission gate's
measured-cost mode weighs in-flight statements by class — the EWMA is
the "measured, not guessed" half of the ROADMAP item. Classes are
re-learned continuously: a digest whose plan changes migrates as soon
as the EWMA crosses a boundary, never pinned to its first-seen cost.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..util import metrics

OTHERS_DIGEST = "(others)"

# additive per-statement metrics a window row carries; ranking considers
# each independently when picking a window's top-K survivors
WINDOW_METRICS = ("cpu_ns", "device_ns", "compile_ns", "backoff_ms", "queue_ms")

# EWMA(cpu_ns + device_ns) upper bounds per class; above the last bound
# is "heavy". Scaled to this engine's in-process latencies (a point-get
# is ~100µs of host time; a mesh aggregate is tens of ms of device time).
CLASS_BOUNDS_NS = (("point", 1_500_000), ("small", 8_000_000), ("scan", 40_000_000))
CLASS_WEIGHTS = {"point": 1, "small": 1, "scan": 2, "heavy": 4}
DEFAULT_CLASS = "small"  # unmeasured digests: neither fast-tracked nor shed

_EWMA_ALPHA = 0.4  # fast re-learn: ~3 executions cross a class boundary
_MAX_EWMAS = 4096  # cost map bound; least-recently-updated evicts


def split_by_rows(total_ns: int, rows: list) -> list:
    """Split one launch's elapsed across its lanes proportionally to
    each lane's decoded rows (the ex_rows attribution the batched tiers
    need), EXACTLY: shares always sum to `total_ns`, largest-remainder
    rounding, deterministic. All-zero row counts degrade to equal split."""
    n = len(rows)
    if n == 0:
        return []
    w = [max(int(r), 0) for r in rows]
    s = sum(w)
    if s == 0:
        w = [1] * n
        s = n
    shares = [total_ns * wi // s for wi in w]
    rem = total_ns - sum(shares)
    if rem:
        order = sorted(range(n), key=lambda i: (-(total_ns * w[i] % s), i))
        for j in range(rem):  # rem < n by floor arithmetic
            shares[order[j]] += 1
    return shares


class DigestStats:
    """One digest's additive totals inside one window (or the live one)."""

    __slots__ = ("digest", "plan_digest", "sample_sql", "exec_count", "errors",
                 "cpu_ns", "device_ns", "compile_ns", "backoff_ms", "queue_ms",
                 "bytes_to_device", "cop_cache_hits", "plan_cache_hits")

    def __init__(self, digest: str):
        self.digest = digest
        self.plan_digest = ""
        self.sample_sql = ""
        self.exec_count = 0
        self.errors = 0
        self.cpu_ns = 0
        self.device_ns = 0
        self.compile_ns = 0
        self.backoff_ms = 0.0
        self.queue_ms = 0.0
        self.bytes_to_device = 0
        self.cop_cache_hits = 0
        self.plan_cache_hits = 0

    def merge(self, other: "DigestStats") -> None:
        self.exec_count += other.exec_count
        self.errors += other.errors
        self.cpu_ns += other.cpu_ns
        self.device_ns += other.device_ns
        self.compile_ns += other.compile_ns
        self.backoff_ms += other.backoff_ms
        self.queue_ms += other.queue_ms
        self.bytes_to_device += other.bytes_to_device
        self.cop_cache_hits += other.cop_cache_hits
        self.plan_cache_hits += other.plan_cache_hits

    def as_dict(self) -> dict:
        return {
            "digest": self.digest,
            "plan_digest": self.plan_digest,
            "sample_sql": self.sample_sql,
            "exec_count": self.exec_count,
            "errors": self.errors,
            "cpu_ns": self.cpu_ns,
            "device_ns": self.device_ns,
            "compile_ns": self.compile_ns,
            "backoff_ms": self.backoff_ms,
            "queue_ms": self.queue_ms,
            "bytes_to_device": self.bytes_to_device,
            "cop_cache_hits": self.cop_cache_hits,
            "plan_cache_hits": self.plan_cache_hits,
        }


class _Window:
    __slots__ = ("start", "end", "top", "others")

    def __init__(self, start: float, end: float, top: dict,
                 others: DigestStats | None):
        self.start = start
        self.end = end
        self.top = top  # digest -> DigestStats, ranked survivors
        self.others = others


class _Ewma:
    __slots__ = ("value", "n")

    def __init__(self):
        self.value = 0.0
        self.n = 0

    def update(self, x: float) -> None:
        self.value = x if self.n == 0 else _EWMA_ALPHA * x + (1.0 - _EWMA_ALPHA) * self.value
        self.n += 1


class TopSQLCollector:
    """The process-wide ledger. One leaf lock (`_mu`) guards the live
    window, the ring and the cost map; statements flush under it once
    per execution and readers snapshot under it — no other lock is ever
    taken while holding it, so it can never participate in a cycle."""

    def __init__(self, window_s: float = 1.0, top_k: int = 30,
                 ring: int = 60, now_fn=time.time):
        self._mu = threading.Lock()
        self._now = now_fn
        self.enabled = True
        self.window_s = window_s
        self.top_k = top_k
        self._live: dict[str, DigestStats] = {}  # guarded_by: _mu
        self._live_start: float = now_fn()  # guarded_by: _mu
        self._ring: deque = deque(maxlen=ring)  # guarded_by: _mu
        self._cost: dict[str, _Ewma] = {}  # guarded_by: _mu
        # all-time totals: incremented with EXACTLY the values the live
        # window absorbs, so API/infoschema sums reconcile against the
        # tidb_tpu_topsql_* counters byte-for-byte
        self.totals: dict[str, float] = {m: 0 for m in WINDOW_METRICS}  # guarded_by: _mu
        self.totals["exec_count"] = 0
        self.launch_device_ns = 0  # guarded_by: _mu — conservation ledger

    # ------------------------------------------------------------ config
    def configure(self, top_k: int | None = None, window_s: float | None = None,
                  ring: int | None = None, enabled: bool | None = None):
        with self._mu:
            if top_k is not None:
                self.top_k = max(1, int(top_k))
            if window_s is not None:
                self.window_s = max(0.001, float(window_s))
            if ring is not None:
                self._ring = deque(self._ring, maxlen=max(1, int(ring)))
            if enabled is not None:
                self.enabled = bool(enabled)

    def reset(self):
        with self._mu:
            self._live = {}
            self._live_start = self._now()
            self._ring.clear()
            self._cost = {}
            self.totals = {m: 0 for m in WINDOW_METRICS}
            self.totals["exec_count"] = 0
            self.launch_device_ns = 0

    # ------------------------------------------------------------- sinks
    def note_launch(self, ns: int) -> None:
        """One fused-program launch's total device time, recorded at the
        store while a statement tag is ambient — the right-hand side of
        the attribution-conservation equation."""
        with self._mu:
            self.launch_device_ns += ns
        metrics.TOPSQL_LAUNCH_DEVICE_NS.inc(ns)

    def record_statement(self, snap: dict, success: bool = True,
                         plan_cache_hit: bool = False) -> None:
        """Fold one finished statement's tag snapshot into the live
        window and its digest's cost EWMA."""
        if not self.enabled:
            return
        digest = snap.get("sql_digest") or ""
        if not digest:
            return
        now = self._now()
        with self._mu:
            self._maybe_seal_locked(now)
            d = self._live.get(digest)
            fresh = d is None
            if fresh:
                d = self._live[digest] = DigestStats(digest)
            d.exec_count += 1
            d.errors += 0 if success else 1
            d.cpu_ns += snap["cpu_ns"]
            d.device_ns += snap["device_ns"]
            d.compile_ns += snap["compile_ns"]
            d.backoff_ms += snap["backoff_ms"]
            d.queue_ms += snap["queue_ms"]
            d.bytes_to_device += snap["bytes_to_device"]
            d.cop_cache_hits += snap["cop_cache_hits"]
            d.plan_cache_hits += 1 if plan_cache_hit else 0
            if snap.get("plan_digest"):
                d.plan_digest = snap["plan_digest"]
            if not d.sample_sql and snap.get("sample_sql"):
                d.sample_sql = snap["sample_sql"]
            t = self.totals
            t["exec_count"] += 1
            t["cpu_ns"] += snap["cpu_ns"]
            t["device_ns"] += snap["device_ns"]
            t["compile_ns"] += snap["compile_ns"]
            t["backoff_ms"] += snap["backoff_ms"]
            t["queue_ms"] += snap["queue_ms"]
            ew = self._cost.get(digest)
            if ew is None:
                if len(self._cost) >= _MAX_EWMAS:
                    self._cost.pop(next(iter(self._cost)))
                ew = self._cost[digest] = _Ewma()
            else:
                self._cost[digest] = self._cost.pop(digest)  # LRU refresh
            ew.update(float(snap["cpu_ns"] + snap["device_ns"]))
            live_n = len(self._live)
        # the counter mirror is BATCHED at seal time (_seal_locked): one
        # metric-lock round-trip per window instead of five per statement
        # — after any rotate the counters equal the sealed-window sums
        # exactly, which is when the byte-consistency reconciliation reads
        # them. Only the live-digest gauge moves here, and only when a
        # digest first appears (steady-state hot path: zero metric locks).
        if fresh:
            metrics.TOPSQL_LIVE_DIGESTS.set(live_n)

    # ----------------------------------------------------------- windows
    def _maybe_seal_locked(self, now: float) -> int:  # requires: _mu
        """Seal the live window if its span elapsed. Empty spans advance
        the start without minting empty windows."""
        sealed = 0
        if now - self._live_start < self.window_s:
            return 0
        if self._live:
            sealed = self._seal_locked(now)
        self._live_start = now
        return sealed

    def _seal_locked(self, now: float) -> int:  # requires: _mu
        end = min(now, self._live_start + self.window_s)
        keep: set = set()
        rows = list(self._live.values())
        # deferred counter mirror: the whole window's sums land in one
        # round-trip per family (record_statement stays metric-lock-free)
        recs = cpu = dev = comp = 0
        back = qms = 0.0
        for st in rows:
            recs += st.exec_count
            cpu += st.cpu_ns
            dev += st.device_ns
            comp += st.compile_ns
            back += st.backoff_ms
            qms += st.queue_ms
        metrics.TOPSQL_RECORDS.inc(recs)
        if cpu:
            metrics.TOPSQL_CPU_NS.inc(cpu)
        if dev:
            metrics.TOPSQL_DEVICE_NS.inc(dev)
        if comp:
            metrics.TOPSQL_COMPILE_NS.inc(comp)
        if back:
            metrics.TOPSQL_BACKOFF_MS.inc(back)
        if qms:
            metrics.TOPSQL_QUEUE_MS.inc(qms)
        for m in WINDOW_METRICS:
            ranked = sorted(rows, key=lambda d, m=m: (-getattr(d, m), d.digest))
            keep.update(d.digest for d in ranked[: self.top_k])
        top = {dg: st for dg, st in self._live.items() if dg in keep}
        others = None
        folded = [st for dg, st in self._live.items() if dg not in keep]
        if folded:
            others = DigestStats(OTHERS_DIGEST)
            for st in folded:
                others.merge(st)
            metrics.TOPSQL_OTHERS_FOLDED.inc(len(folded))
        self._ring.append(_Window(self._live_start, end, top, others))
        self._live = {}
        metrics.TOPSQL_WINDOWS_SEALED.inc()
        metrics.TOPSQL_LIVE_DIGESTS.set(0)
        return 1

    def rotate(self, force: bool = False) -> int:
        """Seal the live window when due (`force` seals a non-empty live
        window regardless of age — tests and shutdown flushes). The PD
        tick's `topsql.report` phase calls this on a clock so windows
        rotate even on an idle SQL front end."""
        now = self._now()
        with self._mu:
            if force and self._live:
                n = self._seal_locked(now)
                self._live_start = now
                return n
            return self._maybe_seal_locked(now)

    # ------------------------------------------------------------- views
    def windows_view(self, include_live: bool = True) -> list[dict]:
        """JSON-able window list, oldest first, live window (if any and
        requested) last with `"live": true`. The information_schema
        memtable, the HTTP API and the tests all consume THIS — one
        serializer, so the surfaces cannot drift."""
        now = self._now()
        with self._mu:
            self._maybe_seal_locked(now)
            out = []
            for w in self._ring:
                rows = sorted(
                    w.top.values(),
                    key=lambda d: (-(d.cpu_ns + d.device_ns), d.digest),
                )
                out.append({
                    "start": w.start,
                    "end": w.end,
                    "live": False,
                    "digests": [d.as_dict() for d in rows],
                    "others": w.others.as_dict() if w.others is not None else None,
                })
            if include_live and self._live:
                rows = sorted(
                    self._live.values(),
                    key=lambda d: (-(d.cpu_ns + d.device_ns), d.digest),
                )
                out.append({
                    "start": self._live_start,
                    "end": now,
                    "live": True,
                    "digests": [d.as_dict() for d in rows],
                    "others": None,
                })
            return out

    def digest_view(self, digest: str) -> dict:
        """One digest across the retained windows + its cost state."""
        windows = []
        for w in self.windows_view():
            for row in w["digests"]:
                if row["digest"] == digest:
                    windows.append(dict(row, window_start=w["start"],
                                        window_end=w["end"], live=w["live"]))
        with self._mu:
            ew = self._cost.get(digest)
            ewma = ew.value if ew is not None else None
            n = ew.n if ew is not None else 0
        return {
            "digest": digest,
            "cost_class": self._class_of(ewma),
            "ewma_cost_ns": ewma,
            "measured_executions": n,
            "windows": windows,
        }

    # -------------------------------------------------------- cost model
    @staticmethod
    def _class_of(ewma_ns: float | None) -> str:
        if ewma_ns is None:
            return DEFAULT_CLASS
        for name, bound in CLASS_BOUNDS_NS:
            if ewma_ns < bound:
                return name
        return "heavy"

    def cost_class(self, digest: str | None) -> str:
        """Measured cost class for the digest; DEFAULT_CLASS until the
        first execution lands (never guessed from the statement text)."""
        if not digest:
            return DEFAULT_CLASS
        with self._mu:
            ew = self._cost.get(digest)
            return self._class_of(ew.value if ew is not None else None)

    def weight(self, digest: str | None) -> int:
        return CLASS_WEIGHTS[self.cost_class(digest)]


COLLECTOR = TopSQLCollector()
