"""The per-statement resource tag and its attribution sinks (ref:
pkg/util/topsql/state — the reference carries `sql_digest, plan_digest`
in goroutine pprof labels; here the tag is a contextvar, the same
ambient mechanism util/tracing uses for spans).

The tag is set ONCE per statement at the session boundary, riding the
digest the plan-cache probe already computed in its one lexer pass. The
dispatch pool's workers do NOT inherit contextvars (the PR-2 tracing
seam has the same property), so `select()` captures the tag on the
session thread and each worker `adopt()`s it explicitly — one tag
object shared by every thread of the statement, its counters guarded by
a leaf lock no other lock is ever taken under.

Sinks are free when no tag is ambient: one contextvar read, no lock.
"""

from __future__ import annotations

import contextvars
import threading
from contextlib import contextmanager

from .reporter import COLLECTOR

_tag: contextvars.ContextVar = contextvars.ContextVar("topsql_tag", default=None)


class ResourceTag:
    """Mutable per-statement attribution target. `sql_digest` is the
    plan-cache probe's literal-masked digest (EXECUTE re-points it at
    the underlying prepared statement's, the same join the stmt log
    does); `plan_digest` lands when the planner picks an access path.
    Counter fields accumulate under `_mu` — sinks run on dispatch pool
    threads concurrently with each other."""

    __slots__ = (
        "sql_digest", "plan_digest", "sample_sql", "_mu",
        "cpu_ns", "device_ns", "compile_ns", "backoff_ms", "queue_ms",
        "bytes_to_device", "cop_cache_hits",
    )

    def __init__(self, sql_digest: str, sample_sql: str = ""):
        self.sql_digest = sql_digest
        self.plan_digest = ""
        self.sample_sql = sample_sql
        self._mu = threading.Lock()
        with self._mu:  # tags churn per-statement: even init writes lock
            self.cpu_ns = 0  # guarded_by: _mu
            self.device_ns = 0  # guarded_by: _mu
            self.compile_ns = 0  # guarded_by: _mu
            self.backoff_ms = 0.0  # guarded_by: _mu
            self.queue_ms = 0.0  # guarded_by: _mu
            self.bytes_to_device = 0  # guarded_by: _mu
            self.cop_cache_hits = 0  # guarded_by: _mu

    def add(self, device_ns: int = 0, compile_ns: int = 0,
            bytes_to_device: int = 0, backoff_ms: float = 0.0,
            queue_ms: float = 0.0, cop_cache_hits: int = 0):
        with self._mu:
            self.device_ns += device_ns
            self.compile_ns += compile_ns
            self.bytes_to_device += bytes_to_device
            self.backoff_ms += backoff_ms
            self.queue_ms += queue_ms
            self.cop_cache_hits += cop_cache_hits

    def finish(self, cpu_ns: int) -> dict:
        """Statement end: the session lands its exact thread-CPU delta
        and takes the flush snapshot in one locked step."""
        with self._mu:
            self.cpu_ns = cpu_ns
        return self.snapshot()

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "sql_digest": self.sql_digest,
                "plan_digest": self.plan_digest,
                "sample_sql": self.sample_sql,
                "cpu_ns": self.cpu_ns,
                "device_ns": self.device_ns,
                "compile_ns": self.compile_ns,
                "backoff_ms": self.backoff_ms,
                "queue_ms": self.queue_ms,
                "bytes_to_device": self.bytes_to_device,
                "cop_cache_hits": self.cop_cache_hits,
            }


def current_tag() -> ResourceTag | None:
    return _tag.get()


def activate(tag: ResourceTag | None):
    """Install `tag` as the statement's ambient attribution target.
    Returns the token `deactivate` needs; None tags install nothing
    (Top SQL off, or an unlexable statement with no probe digest)."""
    if tag is None:
        return None
    return _tag.set(tag)


def deactivate(token) -> None:
    if token is not None:
        _tag.reset(token)


@contextmanager
def adopt(tag: ResourceTag | None):
    """Cross-thread handoff: a dispatch pool worker adopts the session
    thread's tag for the duration of its task (contextvars do not cross
    ThreadPoolExecutor, exactly like the dispatch_span handoff)."""
    if tag is None:
        yield
        return
    token = _tag.set(tag)
    try:
        yield
    finally:
        _tag.reset(token)


# ------------------------------------------------------------------ sinks
def record_device(launch_ns: int, compile_ns: int = 0,
                  bytes_to_device: int = 0) -> None:
    """One fused-program launch's device attribution: the whole launch
    elapsed lands on the ambient statement (per-lane ExecSummary shares
    are display attribution; the statement owns the full launch), plus
    the launch total into the collector's conservation ledger — so
    `sum(per-digest device_ns) == sum(launch totals)` is checkable."""
    t = _tag.get()
    if t is None:
        return
    t.add(device_ns=launch_ns, compile_ns=compile_ns,
          bytes_to_device=bytes_to_device)
    COLLECTOR.note_launch(launch_ns)


def record_backoff(ms: float) -> None:
    """A Backoffer slept interval attributed to the ambient statement."""
    t = _tag.get()
    if t is not None:
        t.add(backoff_ms=ms)


def record_queue_wait(ms: float) -> None:
    """Admission-gate queue wait attributed to the ambient statement."""
    t = _tag.get()
    if t is not None:
        t.add(queue_ms=ms)


def record_cop_cache_hit() -> None:
    """A region served from the coprocessor cache: zero device time by
    construction (no launch ran) — the hit count keeps the conservation
    story honest instead of looking like lost attribution."""
    t = _tag.get()
    if t is not None:
        t.add(cop_cache_hits=1)
