"""Placement Driver — the control plane over the region data plane
(ref: tikv/pd — server/cluster coordinator, statistics/hot_peer_cache.go,
schedule/operator + checker/split_checker, merge_checker, and the
balance-region / hot-region schedulers; mock seam: unistore/pd.go).

The seed kept placement inside `store/region.py` as a static round-robin
`Cluster.scatter()`; this package replaces that with the reference's
feedback loop:

  flow.py        per-region read/write flow recorded by the store's
                 coprocessor and txn write paths, drained as heartbeat
                 snapshots (ref: pdpb.RegionHeartbeatRequest fields
                 bytes_read/bytes_written/keys_read/keys_written,
                 approximate_size/approximate_keys)
  core.py        the PD itself: decaying hot-peer caches, a bounded
                 operator queue, the Timer-driven tick loop, and the
                 views behind /pd/api/v1/* and SHOW PLACEMENT
  schedulers.py  split-checker, merge-checker, balance-region and
                 hot-region schedulers proposing operators each tick

Placement is authoritative here: `Cluster.store_of()` misses route through
`PlacementDriver.place_region()` instead of the seed's silent
`region_id % n_stores` fallback.
"""

from .core import Operator, OperatorQueue, PDConfig, PlacementDriver
from .flow import FlowRecorder, RegionFlow, RegionHeartbeat

__all__ = [
    "FlowRecorder",
    "Operator",
    "OperatorQueue",
    "PDConfig",
    "PlacementDriver",
    "RegionFlow",
    "RegionHeartbeat",
]
