"""PD checkers and schedulers — each proposes operators from the current
statistics; the PD tick owns admission (bounded queue, one operator per
region) and execution (ref: pd schedule/checker/{split,merge}_checker.go
and schedulers/{balance_region,hot_region}.go; each scheduler's
Schedule() returns a small batch of operators per round)."""

from __future__ import annotations

from .core import Operator


class SplitChecker:
    """Regions whose approximate size or key count exceed the limits get
    a split operator (ref: checker/split_checker + TiKV's size-based
    split check). The split bumps the region epoch, so in-flight cop
    tasks surface EpochNotMatch and re-split through the distsql retry
    path — exactly the data-plane contract the seed already honors."""

    name = "split-checker"

    def schedule(self, pd) -> list[Operator]:
        ops = []
        stats = pd.flow.stats()
        for r in pd.cluster.regions():
            size, keys = stats.get(r.region_id, (0, 0))
            if size > pd.conf.max_region_size or keys > pd.conf.max_region_keys:
                ops.append(pd.new_operator(
                    "split", r.region_id,
                    note=f"size={size} keys={keys}",
                ))
        return ops


class MergeChecker:
    """Adjacent tiny/empty regions fold into one (ref:
    checker/merge_checker.go — both peers must be below the merge bounds;
    the survivor keeps the left region's placement). The first region is
    never absorbed, mirroring the reference's new-region protection."""

    name = "merge-checker"

    def schedule(self, pd) -> list[Operator]:
        ops = []
        stats = pd.flow.stats()
        regions = pd.cluster.regions()
        i = 0
        while i + 1 < len(regions):
            left, right = regions[i], regions[i + 1]
            lsize, lkeys = stats.get(left.region_id, (0, 0))
            rsize, rkeys = stats.get(right.region_id, (0, 0))
            if (lsize <= pd.conf.merge_region_size and lkeys <= pd.conf.merge_region_keys
                    and rsize <= pd.conf.merge_region_size and rkeys <= pd.conf.merge_region_keys):
                ops.append(pd.new_operator(
                    "merge", left.region_id, peer_region=right.region_id,
                    note=f"keys={lkeys}+{rkeys}",
                ))
                i += 2  # the pair is spoken for this round
            else:
                i += 1
        return ops


class BalanceRegionScheduler:
    """Even the region count across stores by moving the coldest regions
    off the most loaded store (ref: schedulers/balance_region.go — the
    reference balances a size score; region count is our size analog
    since regions are the TPU work unit). Proposes a batch per tick
    against a simulated count map so one tick can close a large gap."""

    name = "balance-region-scheduler"

    def schedule(self, pd) -> list[Operator]:
        cluster = pd.cluster
        regions = cluster.regions()
        if cluster.n_stores < 2 or not regions:
            return []
        counts = {s: 0 for s in range(cluster.n_stores)}
        by_store: dict[int, list] = {s: [] for s in range(cluster.n_stores)}
        for r in regions:
            sid = cluster.store_of(r.region_id)
            counts[sid] = counts.get(sid, 0) + 1
            by_store.setdefault(sid, []).append(r)
        # coldest first within each store: moving quiet regions is cheap
        heat = pd.hot_read.rates()
        for rid, rate in pd.hot_write.rates().items():
            heat[rid] = heat.get(rid, 0.0) + rate
        for lst in by_store.values():
            lst.sort(key=lambda r: heat.get(r.region_id, 0.0))
        ops = []
        while len(ops) < pd.conf.ops_per_tick:
            src = max(counts, key=counts.get)
            dst = min(counts, key=counts.get)
            if counts[src] - counts[dst] <= pd.conf.balance_tolerance or not by_store[src]:
                break
            region = by_store[src].pop(0)
            ops.append(pd.new_operator(
                "move-region", region.region_id, source=src, target=dst,
                note=f"count {counts[src]}->{counts[dst]}",
            ))
            counts[src] -= 1
            counts[dst] += 1
        return ops


class LeaderBalanceScheduler:
    """Even LEADER counts across stores by transferring leadership to
    follower peers on leader-light stores (ref: schedulers/
    balance_leader.go — leadership moves are cheap, no data moves, so
    this runs before region moves get considered). Only regions with a
    follower peer on the destination store are candidates: a transfer
    must stay within the peer set."""

    name = "leader-balance-scheduler"

    def schedule(self, pd) -> list[Operator]:
        from ..replication import QUORUM_SAFE_TS_MAX

        cluster = pd.cluster
        regions = cluster.regions()
        if cluster.n_stores < 2 or not regions:
            return []
        # never balance ONTO a dead store: a down store's leaders failed
        # over away, so its zero count would otherwise make it the
        # destination every round and every proposal would cancel at the
        # apply-time ping (same rationale as _apply_move's guard)
        live = [s for s in range(cluster.n_stores) if pd.store.ping_store(s)]
        if len(live) < 2:
            return []
        repl = getattr(pd.store, "replication", None)
        counts = {s: 0 for s in live}
        by_leader: dict[int, list] = {s: [] for s in live}
        for r in regions:
            sid = cluster.leader_of(r.region_id)
            if sid in counts:
                counts[sid] = counts.get(sid, 0) + 1
                by_leader.setdefault(sid, []).append(r)
        ops = []
        while len(ops) < pd.conf.ops_per_tick:
            src = max(counts, key=counts.get)
            dst = min(counts, key=counts.get)
            if counts[src] - counts[dst] <= pd.conf.balance_tolerance:
                break
            movable = [r for r in by_leader[src]
                       if dst in cluster.peers_of(r.region_id)
                       and (repl is None or repl.safe_ts(
                           r.region_id, dst) == QUORUM_SAFE_TS_MAX)]
            if not movable:
                break  # no caught-up peer on the light store
            region = movable[0]
            by_leader[src].remove(region)
            ops.append(pd.new_operator(
                "transfer-leader", region.region_id, source=src, target=dst,
                note=f"leaders {counts[src]}->{counts[dst]}",
            ))
            counts[src] -= 1
            counts[dst] += 1
        return ops


class HotRegionScheduler:
    """Move the hottest peer off the most flow-loaded store (ref:
    schedulers/hot_region.go — byte-rate dominant dimension). One
    operator per tick: hot placement oscillates if moved greedily, so the
    2x source/destination guard plus the hot-degree hysteresis in the
    cache keep it damped."""

    name = "hot-region-scheduler"

    def schedule(self, pd) -> list[Operator]:
        cluster = pd.cluster
        if cluster.n_stores < 2:
            return []
        peers = pd.hot_write.hot_peers() + pd.hot_read.hot_peers()
        if not peers:
            return []
        load = {s: 0.0 for s in range(cluster.n_stores)}
        by_store: dict[int, list] = {s: [] for s in range(cluster.n_stores)}
        seen = set()
        for p in peers:
            if p.region_id in seen or cluster.region_by_id(p.region_id) is None:
                continue
            seen.add(p.region_id)
            sid = cluster.store_of(p.region_id)
            load[sid] = load.get(sid, 0.0) + p.byte_rate
            by_store.setdefault(sid, []).append(p)
        src = max(load, key=load.get)
        dst = min(load, key=load.get)
        movable = by_store.get(src, [])
        if len(movable) < len(by_store.get(dst, [])) + 2:
            # moving the only hot peer just relocates the hotspot — only
            # move when the source actually has peers to spare (damping)
            return []
        hottest = movable[0]
        return [pd.new_operator(
            "move-hot-region", hottest.region_id, source=src, target=dst,
            note=f"byte_rate={hottest.byte_rate:.0f}",
        )]
