"""The Placement Driver core: hot-peer statistics, the bounded operator
queue, and the tick loop that turns heartbeats into placement actions
(ref: pd server/cluster/coordinator.go runs checkers+schedulers per
region; statistics/hot_peer_cache.go keeps decaying flow averages with a
hot-degree counter; schedule/operator has the bounded operator controller
with TTL expiry).

One tick = one PD scheduling round:

  heartbeat   drain the FlowRecorder (failpoint `pd/heartbeat-lost` drops
              the interval on the floor, like a lost heartbeat stream)
  statistics  feed the read/write hot-peer caches, refresh region stats
  checkers    split-checker + merge-checker propose structural operators
  schedulers  balance-region + hot-region propose movement operators
  dispatch    execute up to `ops_per_tick` queued operators against the
              cluster (split/merge bump epochs, so in-flight cop tasks
              take the existing EpochNotMatch re-split retry path);
              stale operators expire (failpoint `pd/operator-timeout`
              expires every pending operator immediately)

Everything is observable: `pd_operator_total{type=}` counts proposals,
`pd_hot_region{store=}` gauges hot peers per store, and each tick emits a
`pd.tick` trace with per-phase child spans."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from .flow import FlowRecorder, RegionHeartbeat

KV_MAX_TS = (1 << 62)  # "latest" snapshot for PD-side key sampling


@dataclass
class PDConfig:
    """Scheduling knobs (ref: pd config ScheduleConfig; sizes scaled down
    from the reference's 96MiB/960k-key region defaults to the in-process
    scale)."""

    tick_interval: float = 10.0  # seconds between Timer ticks
    max_region_size: int = 1 << 22  # bytes; split-checker threshold
    max_region_keys: int = 1 << 16  # keys; split-checker threshold
    merge_region_size: int = 1 << 10  # bytes; merge-checker "tiny" bound
    merge_region_keys: int = 16  # keys; merge-checker "tiny" bound
    balance_tolerance: int = 1  # allowed max-min region-count gap
    hot_decay: float = 0.8  # EWMA weight on the previous average
    hot_byte_rate: float = 1024.0  # bytes/tick considered hot
    hot_min_degree: int = 2  # ticks above threshold before "hot"
    operator_limit: int = 64  # queue bound (excess proposals dropped)
    operator_ttl_ticks: int = 16  # pending longer than this -> timeout
    ops_per_tick: int = 8  # operators dispatched per tick


# ---------------------------------------------------------------- hot peers

@dataclass
class HotPeer:
    """Decayed flow average of one region (ref: statistics/hot_peer_cache
    HotPeerStat: rolling byte/key rates + HotDegree/AntiCount)."""

    region_id: int
    byte_rate: float = 0.0
    key_rate: float = 0.0
    degree: int = 0


class HotPeerCache:
    """One cache per flow kind (read / write). Each heartbeat updates the
    EWMA rate; sustained rate above `hot_byte_rate` grows the hot degree,
    quiet intervals shrink it — a region must stay hot for
    `hot_min_degree` ticks before the scheduler believes it (the
    reference's HotDegree/AntiCount hysteresis)."""

    def __init__(self, kind: str, conf: PDConfig):
        self.kind = kind
        self.conf = conf
        self.peers: dict[int, HotPeer] = {}  # guarded_by: _mu
        # the PD timer thread updates while session/HTTP threads read
        # (SHOW PLACEMENT, /pd/api/v1/hotspot) — snapshot under the lock
        self._mu = threading.Lock()

    def update(self, region_id: int, byte_delta: int, key_delta: int) -> None:
        with self._mu:
            p = self.peers.get(region_id)
            if p is None:
                p = self.peers[region_id] = HotPeer(region_id)
            a = self.conf.hot_decay
            p.byte_rate = a * p.byte_rate + (1.0 - a) * float(byte_delta)
            p.key_rate = a * p.key_rate + (1.0 - a) * float(key_delta)
            if p.byte_rate >= self.conf.hot_byte_rate:
                p.degree += 1
            else:
                p.degree -= 1
            if p.degree <= 0 and p.byte_rate < self.conf.hot_byte_rate / 4:
                del self.peers[region_id]
            else:
                p.degree = max(p.degree, 0)

    def prune(self, live: set) -> None:
        with self._mu:
            for rid in [rid for rid in self.peers if rid not in live]:
                del self.peers[rid]

    def hot_peers(self) -> list[HotPeer]:
        """Peers past the degree hysteresis, hottest first (copies — the
        cache keeps mutating under its own lock)."""
        with self._mu:
            out = [
                HotPeer(p.region_id, p.byte_rate, p.key_rate, p.degree)
                for p in self.peers.values()
                if p.degree >= self.conf.hot_min_degree
            ]
        out.sort(key=lambda p: -p.byte_rate)
        return out

    def rates(self) -> dict[int, float]:
        """region_id -> decayed byte rate, every tracked peer (the
        balance scheduler's coldness key)."""
        with self._mu:
            return {rid: p.byte_rate for rid, p in self.peers.items()}


# ---------------------------------------------------------------- operators

@dataclass
class Operator:
    """One placement action (ref: schedule/operator.Operator). `kind` is
    the pd_operator_total label: split / merge / move-region (balance) /
    move-hot-region."""

    op_id: int
    kind: str
    region_id: int
    source: int = -1  # store id (moves)
    target: int = -1  # store id (moves)
    peer_region: int = -1  # the absorbed region (merge)
    state: str = "pending"  # pending -> finished | cancelled | timeout
    created_tick: int = 0
    note: str = ""


class OperatorQueue:
    """Bounded FIFO with one-operator-per-region admission (ref: the
    operator controller's region lock: a region with a pending operator
    does not accept another)."""

    def __init__(self, limit: int):
        self.limit = limit
        self._mu = threading.Lock()
        self._pending: list[Operator] = []  # guarded_by: _mu
        self.history: list[Operator] = []  # finished/cancelled/timeout ring; guarded_by: _mu
        self._history_max = 128

    def add(self, op: Operator) -> bool:
        with self._mu:
            if len(self._pending) >= self.limit:
                return False
            busy = {o.region_id for o in self._pending} | {
                o.peer_region for o in self._pending if o.peer_region >= 0
            }
            if op.region_id in busy or (op.peer_region >= 0 and op.peer_region in busy):
                return False
            self._pending.append(op)
            return True

    def pop_batch(self, n: int) -> list[Operator]:
        with self._mu:
            batch, self._pending = self._pending[:n], self._pending[n:]
            return batch

    def pending(self) -> list[Operator]:
        with self._mu:
            return list(self._pending)

    def history_view(self) -> list[Operator]:
        """Locked snapshot of the retired-operator ring (vet finding:
        /pd/api/v1/operators used to iterate `history` raw while retire()
        appends from the tick thread)."""
        with self._mu:
            return list(self.history)

    def retire(self, op: Operator, state: str, note: str = "") -> None:
        op.state = state
        if note:
            op.note = note
        with self._mu:
            self.history.append(op)
            del self.history[: -self._history_max]

    def expire(self, now_tick: int, ttl: int, force: bool = False) -> list[Operator]:
        """Time out pending operators older than `ttl` ticks (all of them
        when `force`, the pd/operator-timeout failpoint's behavior)."""
        with self._mu:
            expired = [
                o for o in self._pending
                if force or (now_tick - o.created_tick) > ttl
            ]
            self._pending = [o for o in self._pending if o not in expired]
        for o in expired:
            self.retire(o, "timeout")
        return expired


# ---------------------------------------------------------------- the PD

class PlacementDriver:
    """The control plane of one TPUStore: consumes region flow, keeps hot
    statistics, and schedules split/merge/move operators over the
    cluster's placement map (which it owns — Cluster.store_of misses
    route back here)."""

    def __init__(self, store, conf: PDConfig | None = None):
        from .schedulers import (
            BalanceRegionScheduler,
            HotRegionScheduler,
            LeaderBalanceScheduler,
            MergeChecker,
            SplitChecker,
        )

        self.store = store
        self.cluster = store.cluster
        self.conf = conf or PDConfig()
        self.flow = FlowRecorder(self.cluster)
        self.hot_read = HotPeerCache("read", self.conf)
        self.hot_write = HotPeerCache("write", self.conf)
        self.queue = OperatorQueue(self.conf.operator_limit)
        self.checkers = [SplitChecker(), MergeChecker()]
        self.schedulers = [LeaderBalanceScheduler(), BalanceRegionScheduler(),
                           HotRegionScheduler()]
        self.ticks = 0  # guarded_by: _mu
        self.heartbeats_seen = 0  # guarded_by: _mu
        self._next_op_id = 1  # guarded_by: _mu
        self._mu = threading.Lock()  # id/counter bumps
        self._tick_mu = threading.RLock()  # serializes whole ticks
        # (timer-driven + manual tick() must not interleave: each tick
        # drains ONE heartbeat interval and owns the scheduling round)
        self._timer = None
        self.last_tick_root = None  # last pd.tick trace (TRACE/debug view); guarded_by: _mu
        # store health as dispatch reported it + the tick's own probes
        # (ref: PD's store state machine Up/Disconnected/Down driven by
        # store heartbeats); surfaced in /pd/api/v1/stores
        self.store_health: dict[int, str] = {}  # guarded_by: _mu
        self.cluster.pd = self  # placement authority hookup

    # -- placement authority ------------------------------------------------
    def place_region(self, region_id: int) -> int:
        """Authoritative placement for a region the map does not know —
        the PR-3 fix for the seed's silent `region_id % n_stores`
        fallback: a miss is a placement DECISION (least-loaded store),
        recorded so every later lookup agrees (ref: pd's operator-driven
        AddPeer on new regions)."""
        from ..util import metrics

        metrics.PD_PLACEMENT_DECISIONS.inc()
        return self.cluster.place_least_loaded(region_id)

    # -- store health + failover --------------------------------------------
    def note_store_down(self, store_id: int) -> None:
        """Dispatch-reported store failure (ref: client-go feeding store
        liveness back; PD flips the store Disconnected)."""
        with self._mu:
            self.store_health[store_id] = "down"

    def note_store_up(self, store_id: int) -> None:
        # dispatch calls this after every successful cop response; the
        # old unlocked fast-path read raced the tick thread's probe
        # writes (vet: lock-discipline) — one uncontended lock is cheap
        with self._mu:
            if self.store_health.get(store_id) == "down":
                self.store_health[store_id] = "up"

    def store_state(self, store_id: int) -> str:
        with self._mu:
            return self.store_health.get(store_id, "up")

    def failover_region(self, region_id: int, bad_store: int,
                        avoid=frozenset()) -> int | None:
        """Fail one region over off a sick leader store — the dispatch
        layer's escape hatch once the leader's circuit breaker opens.
        Since ISSUE 8 the first choice is a LEADER TRANSFER among live
        peers (ref: raft leadership election after a leader dies: the
        data is already replicated, no bytes move); a placement move —
        re-placing the whole peer set, a fresh-snapshot bootstrap — only
        happens when QUORUM is lost (majority of peers unreachable, or
        the last proposal failed its quorum ack). Both shapes record an
        operator so /pd/api/v1/operators shows the storm, and both count
        `pd_failover_total`; transfers additionally count
        `pd_transfer_leader_total`. Returns the new leader store, or None
        when nothing can serve (caller backs off and retries — e.g. the
        `store/transfer-leader-timeout` failpoint eating the transfer)."""
        from ..util import failpoint, metrics

        if self.cluster.region_by_id(region_id) is None:
            return None
        peers = self.cluster.peers_of(region_id)
        down = self.store.down_stores()
        live = [
            p for p in peers
            if p != bad_store and p not in avoid and p not in down
            and self.store.ping_store(p)
        ]
        quorum = len(peers) // 2 + 1
        counts = self.cluster.counts_per_store()
        if len(live) >= quorum and self.store.replication.quorum_ok(region_id):
            if failpoint.eval("store/transfer-leader-timeout"):
                op = self.new_operator("transfer-leader", region_id,
                                       source=bad_store, target=live[0])
                self.queue.retire(op, "timeout", "transfer-leader timed out")
                metrics.PD_OPERATOR_TIMEOUTS.inc()
                return None  # caller backs off; a later attempt may land
            # raft: only an up-to-date peer may win the election — prefer
            # fully-applied live peers, then least-loaded among them
            target = self.store.replication.best_transfer_target(
                region_id, live, counts)
            if self.cluster.transfer_leader(region_id, target):
                self.note_store_down(bad_store)
                op = self.new_operator("transfer-leader", region_id,
                                       source=bad_store, target=target)
                self.queue.retire(op, "finished", "breaker failover: leader transfer")
                metrics.PD_OPERATORS.labels("transfer-leader").inc()
                metrics.PD_TRANSFER_LEADER.inc()
                metrics.PD_FAILOVERS.inc()
                return target
            # the transfer lost a race (another thread moved leadership
            # already, or the peer set changed under us): quorum is NOT
            # lost — let the caller re-route against the fresh topology
            return None
        # quorum lost: re-place the whole group on healthy stores
        candidates = [
            s for s in range(self.cluster.n_stores)
            if s != bad_store and s not in avoid and not self.store.store_down(s)
        ]
        if not candidates:
            return None
        target = min(candidates, key=lambda s: counts.get(s, 0))
        self.cluster.re_place(region_id, target,
                              avoid=set(avoid) | down | {bad_store})
        self.note_store_down(bad_store)
        op = self.new_operator("failover", region_id, source=bad_store, target=target)
        self.queue.retire(op, "finished", "quorum lost: placement move")
        metrics.PD_OPERATORS.labels("failover").inc()
        metrics.PD_FAILOVERS.inc()
        return target

    def new_operator(self, kind: str, region_id: int, **kw) -> Operator:
        with self._mu:
            op_id = self._next_op_id
            self._next_op_id += 1
            tick = self.ticks
        return Operator(op_id, kind, region_id, created_tick=tick, **kw)

    # -- the tick loop ------------------------------------------------------
    def timer(self, interval: float | None = None):
        from ..background import Timer

        return Timer("pd", interval or self.conf.tick_interval, self.tick)

    def start_background(self, interval: float | None = None):
        if self._timer is None:
            self._timer = self.timer(interval).start()
        return self

    def stop(self):
        if self._timer is not None:
            self._timer.stop()
            self._timer = None

    def tick(self) -> list[Operator]:
        """One scheduling round; returns the operators dispatched."""
        from ..util import failpoint, metrics, tracing

        with self._tick_mu:
            return self._tick(failpoint, metrics, tracing)

    def _tick(self, failpoint, metrics, tracing) -> list[Operator]:
        with self._mu:
            self.ticks += 1
            tick_no = self.ticks
        t0 = time.monotonic()
        dispatched: list[Operator] = []
        with tracing.trace("pd.tick", tick=tick_no) as root:
            with self._mu:
                self.last_tick_root = root
            with tracing.span("pd.heartbeat") as hsp:
                beats = self.flow.heartbeat()
                if failpoint.eval("pd/heartbeat-lost"):
                    beats = []  # the interval's heartbeat stream was lost
                self._absorb(beats)
                if hsp is not None:
                    hsp.set("heartbeats", len(beats))
            with tracing.span("pd.health") as psp:
                down = self._probe_stores()
                if psp is not None:
                    psp.set("down_stores", down)
            with tracing.span("pd.replication") as rsp:
                # the resolved-ts worker analog: unwedged followers catch
                # up to their leader's committed watermark here, and the
                # per-store safe_ts lag gauges refresh
                repl = getattr(self.store, "replication", None)
                advanced = repl.catch_up() if repl is not None else 0
                if rsp is not None:
                    rsp.set("followers_advanced", advanced)
            with tracing.span("pd.cdc") as csp:
                # the changefeed frontier driver (ISSUE 10): each feed
                # recovers lost spans, advances its resolved-ts, drains
                # the sorter up to the frontier, and flushes its sink
                hub = getattr(self.store, "cdc", None)
                emitted = hub.tick() if hub is not None else 0
                if csp is not None:
                    csp.set("events_emitted", emitted)
            with tracing.span("pd.columnar") as osp:
                # the columnar replica's compaction driver (ISSUE 12):
                # fold each table's delta into its device-resident stable
                # chunks and refresh the freshness gauges — AFTER pd.cdc
                # so this tick's flushed frontier is foldable immediately
                rep = getattr(self.store, "columnar", None)
                folded = rep.compact_tick() if rep is not None else 0
                if osp is not None:
                    osp.set("rows_folded", folded)
            with tracing.span("pd.pitr") as pitr_sp:
                # point-in-time recovery upkeep (ISSUE 20): refresh each
                # log backup's durable-checkpoint gauges and trim the
                # schema journal below the floor every feed has passed —
                # AFTER pd.cdc so this tick's checkpoint slide is visible
                from ..br import pitr_tick

                pitr_tick(self.store)
                if pitr_sp is not None:
                    pitr_sp.set("log_backups",
                                len(getattr(self.store, "log_backups", ())))
            with tracing.span("topsql.report") as tsp:
                # Top SQL window rotation (ISSUE 17): the reporter seals
                # its live window on a clock even when no statement lands
                # to trigger the lazy rotation — the PD tick is the
                # process's background heartbeat, same as cdc/columnar
                from .. import topsql

                sealed = topsql.COLLECTOR.rotate()
                if tsp is not None:
                    tsp.set("windows_sealed", sealed)
            with tracing.span("pd.schedule") as ssp:
                proposed = 0
                for sched in self.checkers + self.schedulers:
                    for op in sched.schedule(self):
                        if self.queue.add(op):
                            metrics.PD_OPERATORS.labels(op.kind).inc()
                            proposed += 1
                if ssp is not None:
                    ssp.set("proposed", proposed)
            with tracing.span("pd.dispatch") as dsp:
                forced = bool(failpoint.eval("pd/operator-timeout"))
                for op in self.queue.expire(tick_no, self.conf.operator_ttl_ticks, force=forced):
                    metrics.PD_OPERATOR_TIMEOUTS.inc()
                for op in self.queue.pop_batch(self.conf.ops_per_tick):
                    self._apply(op)
                    dispatched.append(op)
                if dsp is not None:
                    dsp.set("dispatched", len(dispatched))
            self._refresh_gauges()
            root.set("operators", len(dispatched))
        metrics.PD_TICK_DURATION.observe(time.monotonic() - t0)
        return dispatched

    def _probe_stores(self) -> int:
        """Liveness-probe every store (ref: PD's store heartbeat watchdog):
        refresh the health view, and close a tripped circuit breaker whose
        store answers again — but ONLY for stores with no regions placed
        (their traffic failed over away, so no request would ever run the
        breaker's own half-open probe). A store still holding regions —
        e.g. one opened by a server-busy storm the liveness ping cannot
        see — keeps its probe discipline: dispatch traffic decides.
        Returns the down-store count."""
        board = getattr(self.store, "breakers", None)
        counts = self.cluster.counts_per_store()
        down = 0
        for sid in range(self.cluster.n_stores):
            up = self.store.ping_store(sid)
            with self._mu:
                self.store_health[sid] = "up" if up else "down"
            if not up:
                down += 1
            elif (
                board is not None
                and counts.get(sid, 0) == 0
                and board.states().get(sid) not in (None, "closed")
            ):
                board.record_success(sid)
        return down

    def _absorb(self, beats: list[RegionHeartbeat]) -> None:
        from ..util import metrics

        live = {r.region_id for r in self.cluster.regions()}
        with self._mu:
            self.heartbeats_seen += len(beats)
        for b in beats:
            metrics.PD_REGION_HEARTBEATS.inc()
            self.hot_read.update(b.region_id, b.read_bytes, b.read_keys)
            self.hot_write.update(b.region_id, b.write_bytes, b.write_keys)
        self.hot_read.prune(live)
        self.hot_write.prune(live)

    # -- operator execution -------------------------------------------------
    def _apply(self, op: Operator) -> None:
        try:
            if op.kind == "split":
                self._apply_split(op)
            elif op.kind == "merge":
                self._apply_merge(op)
            elif op.kind == "transfer-leader":
                self._apply_transfer_leader(op)
            elif op.kind in ("move-region", "move-hot-region"):
                self._apply_move(op)
            else:
                self.queue.retire(op, "cancelled", f"unknown kind {op.kind!r}")
        except Exception as exc:  # noqa: BLE001 — a bad operator must not kill the tick
            self.queue.retire(op, "cancelled", str(exc))

    def _split_key(self, region) -> bytes | None:
        """Median live key of the region — the split point (ref: TiKV's
        size-based SplitCheck picking the approximate middle key).

        The KV_MAX_TS scan is a deliberate latest-version read: split
        points should reflect CURRENT data, not any statement snapshot.
        Control-plane only — the dataflow-snapshot vet pass polices
        latest-version reads on the request path, and this function is
        outside that cone (tests/test_vet.py pins that)."""
        keys = [k for k, _ in self.store.kv.scan(region.start_key, region.end_key, KV_MAX_TS)]
        if len(keys) < 2:
            return None
        mid = keys[len(keys) // 2]
        return mid if mid != region.start_key else None

    def _apply_split(self, op: Operator) -> None:
        region = self.cluster.region_by_id(op.region_id)
        if region is None:
            self.queue.retire(op, "cancelled", "region gone")
            return
        key = self._split_key(region)
        if key is None:
            self.queue.retire(op, "cancelled", "no split point")
            return
        child = self.cluster.split(key)  # cluster notifies flow.on_split
        self.queue.retire(op, "finished", f"child={child.region_id}")

    def _apply_merge(self, op: Operator) -> None:
        merged = self.cluster.merge(op.region_id, op.peer_region)
        if merged is None:  # cluster notifies flow.on_merge on success
            self.queue.retire(op, "cancelled", "neighbor gone")
            return
        self.queue.retire(op, "finished", f"absorbed={op.peer_region}")

    def _apply_transfer_leader(self, op: Operator) -> None:
        """Move a region's leadership to a follower peer (ref: pd's
        transfer-leader operator -> raft TransferLeader). No epoch bump;
        in-flight cop tasks at the old leader get NotLeader with a hint."""
        from ..util import failpoint, metrics

        if self.cluster.region_by_id(op.region_id) is None:
            self.queue.retire(op, "cancelled", "region gone")
            return
        if failpoint.eval("store/transfer-leader-timeout"):
            self.queue.retire(op, "timeout", "transfer-leader timed out")
            metrics.PD_OPERATOR_TIMEOUTS.inc()
            return
        if not self.store.ping_store(op.target):
            self.queue.retire(op, "cancelled", f"target store {op.target} down")
            return
        from ..replication import QUORUM_SAFE_TS_MAX

        repl = getattr(self.store, "replication", None)
        if repl is not None and repl.safe_ts(
                op.region_id, op.target) != QUORUM_SAFE_TS_MAX:
            # raft refuses to elect a peer that has not applied the full
            # log; retry after the catch-up phase closes the gap
            self.queue.retire(op, "cancelled", "target apply lags")
            return
        if self.cluster.transfer_leader(op.region_id, op.target):
            metrics.PD_TRANSFER_LEADER.inc()
            self.queue.retire(op, "finished")
        else:
            self.queue.retire(op, "cancelled", "target no longer a follower peer")

    def _apply_move(self, op: Operator) -> None:
        if self.cluster.region_by_id(op.region_id) is None:
            self.queue.retire(op, "cancelled", "region gone")
            return
        if not self.store.ping_store(op.target):
            # a balance/hot-region proposal computed before the outage (or
            # during it — the schedulers see the empty store as the least
            # loaded) must not ping-pong regions back ONTO a down store
            self.queue.retire(op, "cancelled", f"target store {op.target} down")
            return
        self.cluster.set_store(op.region_id, op.target)
        self.queue.retire(op, "finished")

    # -- observability ------------------------------------------------------
    def _refresh_gauges(self) -> None:
        from ..util import metrics

        regions = self.cluster.regions()
        metrics.PD_REGIONS.set(len(regions))
        hot_by_store: dict[int, int] = {s: 0 for s in range(self.cluster.n_stores)}
        count_by_store: dict[int, int] = {s: 0 for s in range(self.cluster.n_stores)}
        hot = {p.region_id for p in self.hot_read.hot_peers()} | {
            p.region_id for p in self.hot_write.hot_peers()
        }
        for r in regions:
            sid = self.cluster.store_of(r.region_id)
            count_by_store[sid] = count_by_store.get(sid, 0) + 1
            if r.region_id in hot:
                hot_by_store[sid] = hot_by_store.get(sid, 0) + 1
        for sid, n in hot_by_store.items():
            metrics.PD_HOT_REGION.labels(str(sid)).set(n)
        for sid, n in count_by_store.items():
            metrics.PD_STORE_REGIONS.labels(str(sid)).set(n)
        metrics.PD_OPERATOR_PENDING.set(len(self.queue.pending()))

    def regions_view(self) -> list[dict]:
        stats = self.flow.stats()
        out = []
        for r in self.cluster.regions():
            size, keys = stats.get(r.region_id, (0, 0))
            out.append({
                "region_id": r.region_id,
                "start_key": r.start_key.hex(),
                "end_key": r.end_key.hex(),
                "epoch": r.epoch,
                "store": self.cluster.store_of(r.region_id),
                "leader": self.cluster.leader_of(r.region_id),
                "peers": self.cluster.peers_of(r.region_id),
                "approximate_size": size,
                "approximate_keys": keys,
            })
        return out

    def stores_view(self) -> list[dict]:
        stats = self.flow.stats()
        breaker_states = {}
        board = getattr(self.store, "breakers", None)
        if board is not None:
            breaker_states = board.states()
        repl = getattr(self.store, "replication", None)
        lag = repl.lag_view() if repl is not None else {}
        peer_counts = self.cluster.peer_counts_per_store()
        by_store: dict[int, dict] = {
            s: {"store_id": s, "region_count": 0, "region_size": 0, "region_keys": 0,
                "hot_read_regions": 0, "hot_write_regions": 0,
                "leader_count": 0, "peer_count": peer_counts.get(s, 0),
                "safe_ts_lag": lag.get(s, 0),
                "state": self.store_state(s),
                "breaker": breaker_states.get(s, "closed")}
            for s in range(self.cluster.n_stores)
        }
        hot_r = {p.region_id for p in self.hot_read.hot_peers()}
        hot_w = {p.region_id for p in self.hot_write.hot_peers()}
        for r in self.cluster.regions():
            sid = self.cluster.store_of(r.region_id)
            st = by_store.setdefault(sid, {"store_id": sid, "region_count": 0, "region_size": 0,
                                           "region_keys": 0, "hot_read_regions": 0, "hot_write_regions": 0,
                                           "leader_count": 0, "peer_count": 0, "safe_ts_lag": 0})
            size, keys = stats.get(r.region_id, (0, 0))
            # region_count IS the leader view ("a region lives where it
            # leads"); leader_count is kept as the replication-explicit
            # ALIAS below so the two can never diverge
            st["region_count"] += 1
            st["region_size"] += size
            st["region_keys"] += keys
            st["hot_read_regions"] += 1 if r.region_id in hot_r else 0
            st["hot_write_regions"] += 1 if r.region_id in hot_w else 0
        for st in by_store.values():
            st["leader_count"] = st["region_count"]
        return [by_store[s] for s in sorted(by_store)]

    def hotspot_view(self) -> dict:
        def peers(cache: HotPeerCache) -> list[dict]:
            return [
                {"region_id": p.region_id, "store": self.cluster.store_of(p.region_id),
                 "byte_rate": round(p.byte_rate, 1), "key_rate": round(p.key_rate, 1),
                 "degree": p.degree}
                for p in cache.hot_peers()
            ]

        with self._mu:
            tick = self.ticks
        return {"as_of_tick": tick, "read": peers(self.hot_read), "write": peers(self.hot_write)}

    def operators_view(self) -> dict:
        def row(o: Operator) -> dict:
            return {"op_id": o.op_id, "kind": o.kind, "region_id": o.region_id,
                    "source": o.source, "target": o.target, "state": o.state,
                    "created_tick": o.created_tick, "note": o.note}

        return {"pending": [row(o) for o in self.queue.pending()],
                "history": [row(o) for o in self.queue.history_view()]}

    def scheduling_state(self, region_id: int) -> str:
        """SHOW PLACEMENT's Scheduling_State column for one region."""
        for o in self.queue.pending():
            if o.region_id == region_id or o.peer_region == region_id:
                return f"pending-{o.kind}"
        states = []
        if any(p.region_id == region_id for p in self.hot_read.hot_peers()):
            states.append("hot-read")
        if any(p.region_id == region_id for p in self.hot_write.hot_peers()):
            states.append("hot-write")
        return ",".join(states) if states else "scheduled"
