"""Region flow collection — what the data plane tells the PD
(ref: pdpb.RegionHeartbeatRequest: bytes_written/bytes_read,
keys_written/keys_read, approximate_size/approximate_keys; TiKV fills
these from its flow observer, store/worker/pd_worker collects them into
the heartbeat stream).

In one process there is no heartbeat RPC: the store's coprocessor path
calls `record_read` per served region task and the write paths (direct
puts, 2PC commit apply, bulk ingest) call `record_write` per key. The PD
tick drains the interval deltas with `heartbeat()` — the snapshot IS the
heartbeat — while the approximate size/keys totals persist as the
region's running stats (the split/merge checkers' input)."""

from __future__ import annotations

import threading
from dataclasses import dataclass


@dataclass
class RegionFlow:
    """Per-region counters: interval deltas (reset by each heartbeat
    drain) plus running approximate totals (never reset; redistributed on
    split/merge like the reference's approximate_size bookkeeping)."""

    region_id: int
    read_bytes: int = 0
    read_keys: int = 0
    write_bytes: int = 0
    write_keys: int = 0
    approx_size: int = 0  # logical live-data bytes (overwrites replace,
    # deletes shrink by the mean entry size) — approximate
    approx_keys: int = 0  # live-key estimate (tombstones decrement)


@dataclass(frozen=True)
class RegionHeartbeat:
    """One region's heartbeat snapshot (ref: pdpb.RegionHeartbeatRequest,
    the flow subset the schedulers consume)."""

    region_id: int
    read_bytes: int
    read_keys: int
    write_bytes: int
    write_keys: int
    approx_size: int
    approx_keys: int


class FlowRecorder:
    """Thread-safe flow sink shared by the cop pool workers and the txn
    commit path; key->region attribution goes through the cluster's
    locate (the region the key lives in NOW, matching how TiKV's
    flow observer attributes to the serving peer)."""

    def __init__(self, cluster):
        self.cluster = cluster
        self._mu = threading.Lock()
        self._flows: dict[int, RegionFlow] = {}  # guarded_by: _mu

    def _flow(self, region_id: int) -> RegionFlow:  # requires: _mu
        f = self._flows.get(region_id)
        if f is None:
            f = self._flows[region_id] = RegionFlow(region_id)
        return f

    # -- data-plane hooks ---------------------------------------------------
    def record_read(self, region_id: int, nbytes: int, keys: int) -> None:
        """One served cop task: decoded bytes + rows scanned."""
        with self._mu:
            f = self._flow(region_id)
            f.read_bytes += nbytes
            f.read_keys += keys

    def record_write(self, key: bytes, nbytes: int, prev_live: bool = False,
                     delete: bool = False) -> None:
        """One applied KV mutation (put_row / commit apply / ingest).
        `prev_live` (from MemKV.put) discriminates insert / overwrite /
        delete so the approximate totals track LOGICAL size: an overwrite
        is traffic but not growth, a delete of a live key shrinks by the
        region's mean entry size."""
        region_id = self.cluster.locate(key).region_id
        with self._mu:
            self._apply_write(region_id, key, nbytes, prev_live, delete)

    def record_writes(self, items) -> None:
        """Batch form for commit/ingest appliers: items of
        (key, nbytes, prev_live, delete). Region attribution resolves
        first (cluster lock), then one flow-lock pass applies — callers
        invoke this AFTER releasing the kv critical section so readers
        never wait on flow bookkeeping."""
        located = [
            (self.cluster.locate(k).region_id, k, n, p, d)
            for k, n, p, d in items
        ]
        with self._mu:
            for rid, k, n, p, d in located:
                self._apply_write(rid, k, n, p, d)

    def _apply_write(self, region_id: int, key: bytes, nbytes: int,  # requires: _mu
                     prev_live: bool, delete: bool) -> None:
        f = self._flow(region_id)
        f.write_bytes += nbytes + len(key)
        f.write_keys += 1
        if delete:
            if prev_live:
                mean = f.approx_size // max(f.approx_keys, 1)
                f.approx_size = max(f.approx_size - mean, 0)
                f.approx_keys = max(f.approx_keys - 1, 0)
        elif not prev_live:
            f.approx_size += nbytes + len(key)
            f.approx_keys += 1
        # overwrite of a live key: the new version logically replaces the
        # old (GC reclaims it), so approximate totals stay put

    # -- PD-side consumption ------------------------------------------------
    def heartbeat(self) -> list[RegionHeartbeat]:
        """Drain interval deltas into heartbeat snapshots, one per LIVE
        region (merged-away regions are pruned here; zero-traffic regions
        still report, which is what lets the hot caches decay them)."""
        live = {r.region_id for r in self.cluster.regions()}
        with self._mu:
            for rid in [rid for rid in self._flows if rid not in live]:
                del self._flows[rid]
            for rid in live:
                self._flow(rid)  # a region with no traffic yet still beats
            beats = [
                RegionHeartbeat(
                    f.region_id, f.read_bytes, f.read_keys,
                    f.write_bytes, f.write_keys, f.approx_size, f.approx_keys,
                )
                for f in self._flows.values()
            ]
            for f in self._flows.values():
                f.read_bytes = f.read_keys = f.write_bytes = f.write_keys = 0
        return beats

    def stats(self) -> dict[int, tuple[int, int]]:
        """region_id -> (approx_size, approx_keys) running totals."""
        with self._mu:
            return {rid: (f.approx_size, f.approx_keys) for rid, f in self._flows.items()}

    # -- topology-change bookkeeping ----------------------------------------
    def on_split(self, parent_id: int, child_id: int) -> None:
        """A split halves the parent's approximate totals into the child
        (ref: the approximate redistribution PD applies until the next
        real heartbeat corrects it)."""
        with self._mu:
            p = self._flow(parent_id)
            c = self._flow(child_id)
            c.approx_size, p.approx_size = p.approx_size // 2, p.approx_size - p.approx_size // 2
            c.approx_keys, p.approx_keys = p.approx_keys // 2, p.approx_keys - p.approx_keys // 2

    def on_merge(self, left_id: int, right_id: int) -> None:
        """A merge folds the absorbed region's totals AND pending deltas
        into the survivor."""
        with self._mu:
            right = self._flows.pop(right_id, None)
            if right is None:
                return
            left = self._flow(left_id)
            left.read_bytes += right.read_bytes
            left.read_keys += right.read_keys
            left.write_bytes += right.write_bytes
            left.write_keys += right.write_keys
            left.approx_size += right.approx_size
            left.approx_keys += right.approx_keys
