from . import number, datum_codec, rowcodec, tablecodec
from .rowcodec import RowEncoder, decode_row_to_datum_map
from .tablecodec import encode_row_key, decode_row_key, encode_index_key, record_prefix

__all__ = [
    "number",
    "datum_codec",
    "rowcodec",
    "tablecodec",
    "RowEncoder",
    "decode_row_to_datum_map",
    "encode_row_key",
    "decode_row_key",
    "encode_index_key",
    "record_prefix",
]
