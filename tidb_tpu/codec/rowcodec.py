"""Row format v2 (ref: pkg/util/rowcodec/row.go:36-70 layout diagram).

    [VER=128][FLAGS][NOT_NULL_CNT u16][NULL_CNT u16]
    [not-null col ids][null col ids][not-null value end-offsets][values]

small row: ids u8, offsets u16; large row (max col id > 255 or data > 64KiB):
ids u32, offsets u32. Ids sorted ascending within each group. Value encodings
per rowcodec/encoder.go encodeValueDatum: compact LE ints/uints, comparable
float64, raw bytes for strings, packed uint for times, EncodeDecimal for
decimals, int64 nanos for durations.
"""

from __future__ import annotations

import struct

from ..types import Datum, DatumKind, FieldType, MyDecimal, MyTime, TypeCode
from . import number
from .decimal_bin import decode_decimal, encode_decimal

CODEC_VER = 128
FLAG_LARGE = 1


class RowEncoder:
    """Encode (col_id -> Datum) into row format v2."""

    def encode(self, col_ids: list[int], datums: list[Datum]) -> bytes:
        pairs = sorted(zip(col_ids, datums), key=lambda p: p[0])
        notnull = [(cid, d) for cid, d in pairs if not d.is_null()]
        null_ids = [cid for cid, d in pairs if d.is_null()]
        values = [encode_row_value(d) for _, d in notnull]
        data = b"".join(values)
        large = (max(col_ids) if col_ids else 0) > 255 or len(data) > 0xFFFF
        flags = FLAG_LARGE if large else 0
        out = bytearray([CODEC_VER, flags])
        out += struct.pack("<HH", len(notnull), len(null_ids))
        id_fmt, off_fmt = ("<I", "<I") if large else ("<B", "<H")
        for cid, _ in notnull:
            out += struct.pack(id_fmt, cid)
        for cid in null_ids:
            out += struct.pack(id_fmt, cid)
        off = 0
        for v in values:
            off += len(v)
            out += struct.pack(off_fmt, off)
        out += data
        return bytes(out)


def encode_row_value(d: Datum) -> bytes:
    """(ref: rowcodec/encoder.go:173 encodeValueDatum)."""
    k = d.kind
    if k == DatumKind.Int64:
        return number.encode_int_value(d.val)
    if k in (DatumKind.Uint64, DatumKind.MysqlEnum, DatumKind.MysqlSet, DatumKind.MysqlBit):
        return number.encode_uint_value(int(d.val))
    if k in (DatumKind.String, DatumKind.Bytes):
        return d.val.encode() if isinstance(d.val, str) else bytes(d.val)
    if k == DatumKind.MysqlTime:
        packed = d.val.packed if isinstance(d.val, MyTime) else int(d.val)
        return number.encode_uint_value(packed)
    if k == DatumKind.MysqlDuration:
        return number.encode_int_value(d.val)
    if k in (DatumKind.Float32, DatumKind.Float64):
        return number.encode_float_cmp(float(d.val))
    if k == DatumKind.MysqlDecimal:
        return encode_decimal(d.val)
    if k == DatumKind.MysqlJSON:
        return bytes(d.val)
    raise ValueError(f"unsupported row value kind {k}")


def decode_row_value(b: bytes, ft: FieldType) -> Datum:
    """Inverse of encode_row_value, driven by the column's FieldType
    (ref: rowcodec/decoder.go decodeColData)."""
    if ft.is_int():
        if ft.is_unsigned():
            return Datum.u64(number.decode_uint_value(b))
        return Datum.i64(number.decode_int_value(b))
    if ft.is_float():
        v, _ = number.decode_float_cmp(b)
        return Datum.f64(v) if ft.tp.name == "Double" else Datum(DatumKind.Float32, v)
    if ft.is_string():
        if ft.charset == "binary":
            return Datum.bytes_(bytes(b))
        return Datum.string(bytes(b).decode("utf-8", "surrogateescape"))
    if ft.is_decimal():
        v, _ = decode_decimal(b)
        return Datum.dec(v)
    if ft.is_time():
        return Datum.time(MyTime(number.decode_uint_value(b), max(ft.decimal, 0)))
    if ft.is_duration():
        return Datum.duration(number.decode_int_value(b))
    if ft.tp == TypeCode.JSON:
        return Datum.json(bytes(b))
    if ft.tp == TypeCode.Enum:
        return Datum.enum_from(ft.elems, number.decode_uint_value(b))
    if ft.tp == TypeCode.Set:
        return Datum.set_from(ft.elems, number.decode_uint_value(b))
    # Bit lands as uint
    return Datum.u64(number.decode_uint_value(b))


class RowReader:
    """Zero-copy view over an encoded row."""

    __slots__ = ("b", "large", "n_notnull", "n_null", "ids_off", "offs_off", "data_off")

    def __init__(self, b: bytes):
        if b[0] != CODEC_VER:
            raise ValueError(f"invalid rowcodec version {b[0]}")
        self.b = b
        self.large = bool(b[1] & FLAG_LARGE)
        self.n_notnull, self.n_null = struct.unpack_from("<HH", b, 2)
        id_sz = 4 if self.large else 1
        off_sz = 4 if self.large else 2
        self.ids_off = 6
        self.offs_off = self.ids_off + (self.n_notnull + self.n_null) * id_sz
        self.data_off = self.offs_off + self.n_notnull * off_sz

    def _id_at(self, i: int) -> int:
        if self.large:
            return struct.unpack_from("<I", self.b, self.ids_off + 4 * i)[0]
        return self.b[self.ids_off + i]

    def _end_off(self, i: int) -> int:
        if self.large:
            return struct.unpack_from("<I", self.b, self.offs_off + 4 * i)[0]
        return struct.unpack_from("<H", self.b, self.offs_off + 2 * i)[0]

    def value_bytes(self, col_id: int) -> bytes | None:
        """Raw value bytes for col_id; None if the column is NULL or absent.

        Returns b"" only for genuinely empty values (empty string).
        """
        lo, hi = 0, self.n_notnull
        while lo < hi:
            mid = (lo + hi) // 2
            cid = self._id_at(mid)
            if cid < col_id:
                lo = mid + 1
            elif cid > col_id:
                hi = mid
            else:
                start = self._end_off(mid - 1) if mid else 0
                return self.b[self.data_off + start : self.data_off + self._end_off(mid)]
        return None

    def is_null(self, col_id: int) -> bool:
        lo, hi = self.n_notnull, self.n_notnull + self.n_null
        while lo < hi:
            mid = (lo + hi) // 2
            cid = self._id_at(mid)
            if cid < col_id:
                lo = mid + 1
            elif cid > col_id:
                hi = mid
            else:
                return True
        return False


def fill_origin_default(row_bytes: bytes, col_id: int, default, decoded: Datum) -> Datum:
    """Pre-ADD-COLUMN rows carry no bytes for the column: fill the origin
    default unless the row explicitly stored NULL (ref: rowcodec
    ChunkDecoder default fill; shared by the scan and point-read paths)."""
    if default is None or not decoded.is_null():
        return decoded
    if RowReader(row_bytes).is_null(col_id):
        return decoded
    return default


def decode_row_to_datum_map(b: bytes, fts_by_id: dict[int, FieldType]) -> dict[int, Datum]:
    r = RowReader(b)
    out = {}
    for cid, ft in fts_by_id.items():
        vb = r.value_bytes(cid)
        if vb is None:
            out[cid] = Datum.NULL
        else:
            out[cid] = decode_row_value(vb, ft)
    return out
