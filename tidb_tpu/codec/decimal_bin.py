"""MySQL decimal binary (memcomparable) format.

(ref: pkg/types/mydecimal.go WriteBin/FromBin and pkg/util/codec/decimal.go
EncodeDecimal — precision byte + frac byte + packed base-10^9 words with the
sign bit of the first byte flipped, all bytes inverted for negatives, making
the encoding lexicographically ordered.)
"""

from __future__ import annotations

from ..types import MyDecimal

DIGITS_PER_WORD = 9
WORD_SIZE = 4
# bytes needed for a partial word of n leading/trailing digits
DIG2BYTES = [0, 1, 1, 2, 2, 3, 3, 4, 4, 4]


def _digits_of(d: MyDecimal, prec: int, frac: int) -> tuple[bool, str, str]:
    neg = d.d < 0
    q = d.round(frac)  # enforce target scale
    s = format(abs(q.d), "f")
    if "." in s:
        int_part, frac_part = s.split(".")
    else:
        int_part, frac_part = s, ""
    frac_part = frac_part.ljust(frac, "0")[:frac]
    int_digits = prec - frac
    int_part = int_part.lstrip("0") or ""
    if len(int_part) > int_digits:
        raise ValueError(f"decimal overflow: {s} does not fit precision {prec},{frac}")
    int_part = int_part.rjust(int_digits, "0")
    return neg, int_part, frac_part


def encode_bin(d: MyDecimal, prec: int, frac: int) -> bytes:
    neg, int_part, frac_part = _digits_of(d, prec, frac)
    int_digits = prec - frac
    leading = int_digits % DIGITS_PER_WORD
    trailing = frac % DIGITS_PER_WORD
    out = bytearray()

    def put_word(digit_str: str, nbytes: int):
        v = int(digit_str) if digit_str else 0
        out.extend(v.to_bytes(nbytes, "big"))

    pos = 0
    if leading:
        put_word(int_part[:leading], DIG2BYTES[leading])
        pos = leading
    while pos < int_digits:
        put_word(int_part[pos : pos + DIGITS_PER_WORD], WORD_SIZE)
        pos += DIGITS_PER_WORD
    pos = 0
    while pos + DIGITS_PER_WORD <= frac:
        put_word(frac_part[pos : pos + DIGITS_PER_WORD], WORD_SIZE)
        pos += DIGITS_PER_WORD
    if trailing:
        put_word(frac_part[pos:], DIG2BYTES[trailing])

    if neg:
        for i in range(len(out)):
            out[i] ^= 0xFF
    out[0] ^= 0x80
    return bytes(out)


def decode_bin(b: bytes, prec: int, frac: int, pos: int = 0) -> tuple[MyDecimal, int]:
    int_digits = prec - frac
    leading = int_digits % DIGITS_PER_WORD
    trailing = frac % DIGITS_PER_WORD
    size = (
        DIG2BYTES[leading]
        + (int_digits // DIGITS_PER_WORD) * WORD_SIZE
        + (frac // DIGITS_PER_WORD) * WORD_SIZE
        + DIG2BYTES[trailing]
    )
    buf = bytearray(b[pos : pos + size])
    neg = not (buf[0] & 0x80)
    buf[0] ^= 0x80
    if neg:
        for i in range(len(buf)):
            buf[i] ^= 0xFF

    digits = []
    cur = 0
    if leading:
        n = DIG2BYTES[leading]
        digits.append(str(int.from_bytes(buf[cur : cur + n], "big")).rjust(leading, "0"))
        cur += n
    for _ in range(int_digits // DIGITS_PER_WORD):
        digits.append(str(int.from_bytes(buf[cur : cur + WORD_SIZE], "big")).rjust(9, "0"))
        cur += WORD_SIZE
    int_str = "".join(digits) or "0"
    digits = []
    for _ in range(frac // DIGITS_PER_WORD):
        digits.append(str(int.from_bytes(buf[cur : cur + WORD_SIZE], "big")).rjust(9, "0"))
        cur += WORD_SIZE
    if trailing:
        n = DIG2BYTES[trailing]
        digits.append(str(int.from_bytes(buf[cur : cur + n], "big")).rjust(trailing, "0"))
        cur += n
    frac_str = "".join(digits)
    s = (("-" if neg else "") + (int_str.lstrip("0") or "0") + ("." + frac_str if frac_str else ""))
    return MyDecimal(s, frac), pos + size


def encode_decimal(d: MyDecimal, prec: int | None = None, frac: int | None = None) -> bytes:
    """(ref: codec/decimal.go EncodeDecimal: [prec][frac][bin])."""
    if prec is None or prec < 0:
        frac = d.scale
        digits = len(format(abs(d.d), "f").replace(".", "").lstrip("0")) or 1
        prec = max(digits, frac + 1)
    return bytes([prec, frac]) + encode_bin(d, prec, frac)


def decode_decimal(b: bytes, pos: int = 0) -> tuple[MyDecimal, int]:
    prec, frac = b[pos], b[pos + 1]
    return decode_bin(b, prec, frac, pos + 2)
