"""Wire serialization for the coprocessor seam — this framework's tipb.

The reference crosses its store boundary with protobuf: `tipb.DAGRequest`
in, `tipb.SelectResponse` (datum rows or raw columnar chunk buffers) out
(ref: cophandler/cop_handler.go:249-267 encode paths, pkg/util/chunk/
codec.go:37 raw-column wire layout, negotiated at distsql.SetEncodeType
distsql.go:201-235). Here the same contract is a compact tagged binary
format over the ir.Expr/DAG dataclasses plus the Chunk's raw buffers —
little-endian, alignment-free, so a sidecar process (or another host) can
serve cop requests without sharing Python objects.

Layout conventions: u8 tags, little-endian fixed-width ints, length-prefixed
byte strings, numpy buffers verbatim (the chunk columns go on the wire as
their raw data — the reference's TypeChunk encoding does exactly this)."""

from __future__ import annotations

import struct

import numpy as np

from ..chunk import Chunk
from ..chunk.column import Column, numpy_dtype_for
from ..expr.agg import AggDesc, AggMode
from ..expr.ir import ColumnRef, Const, Expr, ScalarFunc
from ..types import Collation, Datum, DatumKind, FieldType, Flag, MyDecimal, MyTime, TypeCode


class Writer:
    def __init__(self):
        self.buf = bytearray()

    def u8(self, v: int):
        self.buf.append(v & 0xFF)

    def i32(self, v: int):
        self.buf += struct.pack("<i", v)

    def i64(self, v: int):
        self.buf += struct.pack("<q", v)

    def u64(self, v: int):
        self.buf += struct.pack("<Q", v & ((1 << 64) - 1))

    def f64(self, v: float):
        self.buf += struct.pack("<d", v)

    def blob(self, b: bytes):
        self.i32(len(b))
        self.buf += b

    def s(self, v: str):
        self.blob(v.encode("utf-8"))

    def bool_(self, v: bool):
        self.u8(1 if v else 0)

    def done(self) -> bytes:
        return bytes(self.buf)


class Reader:
    def __init__(self, b: bytes):
        self.b = memoryview(b)
        self.i = 0

    def u8(self) -> int:
        v = self.b[self.i]
        self.i += 1
        return v

    def i32(self) -> int:
        v = struct.unpack_from("<i", self.b, self.i)[0]
        self.i += 4
        return v

    def i64(self) -> int:
        v = struct.unpack_from("<q", self.b, self.i)[0]
        self.i += 8
        return v

    def u64(self) -> int:
        v = struct.unpack_from("<Q", self.b, self.i)[0]
        self.i += 8
        return v

    def f64(self) -> float:
        v = struct.unpack_from("<d", self.b, self.i)[0]
        self.i += 8
        return v

    def blob(self) -> bytes:
        n = self.i32()
        v = bytes(self.b[self.i : self.i + n])
        self.i += n
        return v

    def s(self) -> str:
        return self.blob().decode("utf-8")

    def bool_(self) -> bool:
        return self.u8() != 0


# -------------------------------------------------------------- field types

def w_ft(w: Writer, ft: FieldType):
    w.u8(int(ft.tp))
    w.i32(int(ft.flag))
    w.i32(ft.flen)
    w.i32(ft.decimal)
    w.s(ft.charset)
    w.i32(int(ft.collate))
    w.i32(len(ft.elems))
    for e in ft.elems:
        w.s(e)


def r_ft(r: Reader) -> FieldType:
    tp = TypeCode(r.u8())
    flag = Flag(r.i32())
    flen = r.i32()
    dec = r.i32()
    charset = r.s()
    collate = Collation(r.i32())
    elems = tuple(r.s() for _ in range(r.i32()))
    return FieldType(tp, flag, flen, dec, charset, collate, elems)


# -------------------------------------------------------------- datums

def w_datum(w: Writer, d: Datum):
    w.u8(int(d.kind))
    k = d.kind
    if k == DatumKind.Null:
        return
    if k in (DatumKind.Int64, DatumKind.MysqlDuration):
        w.i64(int(d.val))
    elif k == DatumKind.Uint64:
        w.u64(int(d.val))
    elif k in (DatumKind.Float64, DatumKind.Float32):
        w.f64(float(d.val))
    elif k in (DatumKind.String, DatumKind.Bytes, DatumKind.MysqlJSON):
        v = d.val
        w.blob(v.encode("utf-8") if isinstance(v, str) else bytes(v))
    elif k == DatumKind.MysqlDecimal:
        w.s(str(d.val))
    elif k == DatumKind.MysqlTime:
        w.u64(d.val.packed)
        w.u8(d.val.fsp)
    else:
        raise NotImplementedError(f"wire datum kind {k}")


def w_opt_datum(w: Writer, d):
    """Optional datum (ColumnInfo.default — tipb default_val analog)."""
    if d is None:
        w.u8(0)
    else:
        w.u8(1)
        w_datum(w, d)


def r_opt_datum(r: Reader):
    return r_datum(r) if r.u8() else None


def r_datum(r: Reader) -> Datum:
    k = DatumKind(r.u8())
    if k == DatumKind.Null:
        return Datum.NULL
    if k == DatumKind.Int64:
        return Datum.i64(r.i64())
    if k == DatumKind.MysqlDuration:
        return Datum(DatumKind.MysqlDuration, r.i64())
    if k == DatumKind.Uint64:
        return Datum.u64(r.u64())
    if k == DatumKind.Float64:
        return Datum.f64(r.f64())
    if k == DatumKind.Float32:
        return Datum(DatumKind.Float32, r.f64())
    if k == DatumKind.String:
        return Datum.string(r.blob().decode("utf-8", "surrogateescape"))
    if k in (DatumKind.Bytes, DatumKind.MysqlJSON):
        return Datum(k, r.blob())
    if k == DatumKind.MysqlDecimal:
        return Datum.dec(MyDecimal(r.s()))
    if k == DatumKind.MysqlTime:
        packed = r.u64()
        fsp = r.u8()
        return Datum.time(MyTime(packed, fsp))
    raise NotImplementedError(f"wire datum kind {k}")


# -------------------------------------------------------------- expressions

_EXPR_COL, _EXPR_CONST, _EXPR_FUNC = 1, 2, 3


def w_expr(w: Writer, e: Expr):
    if isinstance(e, ColumnRef):
        w.u8(_EXPR_COL)
        w.i32(e.index)
        w_ft(w, e.ft)
    elif isinstance(e, Const):
        w.u8(_EXPR_CONST)
        w_datum(w, e.datum)
        w_ft(w, e.ft)
    elif isinstance(e, ScalarFunc):
        w.u8(_EXPR_FUNC)
        w.s(e.op)
        w.i32(len(e.args))
        for a in e.args:
            w_expr(w, a)
        w_ft(w, e.ft)
    else:
        raise NotImplementedError(f"wire expr {type(e).__name__}")


def r_expr(r: Reader) -> Expr:
    tag = r.u8()
    if tag == _EXPR_COL:
        idx = r.i32()
        return ColumnRef(idx, r_ft(r))
    if tag == _EXPR_CONST:
        d = r_datum(r)
        return Const(d, r_ft(r))
    if tag == _EXPR_FUNC:
        op = r.s()
        args = tuple(r_expr(r) for _ in range(r.i32()))
        return ScalarFunc(op, args, r_ft(r))
    raise ValueError(f"bad expr tag {tag}")


def w_agg_desc(w: Writer, d: AggDesc):
    w.s(d.name)
    w.u8(int(d.mode))
    w.bool_(d.distinct)
    w.bool_(d.extra is not None)
    if d.extra is not None:
        w.s(d.extra)
    w.i32(len(d.args))
    for a in d.args:
        w_expr(w, a)
    w_ft(w, d.ft)


def r_agg_desc(r: Reader) -> AggDesc:
    name = r.s()
    mode = AggMode(r.u8())
    distinct = r.bool_()
    extra = r.s() if r.bool_() else None
    args = tuple(r_expr(r) for _ in range(r.i32()))
    ft = r_ft(r)
    return AggDesc(name, args, mode=mode, distinct=distinct, ft=ft, extra=extra)


# -------------------------------------------------------------- executors

_EX_SCAN, _EX_SEL, _EX_PROJ, _EX_AGG, _EX_TOPN, _EX_LIMIT, _EX_JOIN, _EX_ISCAN, _EX_SORT = range(1, 10)


def w_executor(w: Writer, ex):
    from ..exec.dag import Aggregation, ColumnInfo, IndexScan, Join, Limit, Projection, Selection, Sort, TableScan, TopN

    if isinstance(ex, IndexScan):
        w.u8(_EX_ISCAN)
        w.i64(ex.table_id)
        w.i64(ex.index_id)
        w.bool_(ex.desc)
        w.i32(len(ex.columns))
        for c in ex.columns:
            w.i64(c.col_id)
            w_ft(w, c.ft)
            w_opt_datum(w, c.default)
    elif isinstance(ex, TableScan):
        w.u8(_EX_SCAN)
        w.i64(ex.table_id)
        w.bool_(ex.desc)
        w.i32(len(ex.columns))
        for c in ex.columns:
            w.i64(c.col_id)
            w_ft(w, c.ft)
            w_opt_datum(w, c.default)
    elif isinstance(ex, Selection):
        w.u8(_EX_SEL)
        w.i32(len(ex.conditions))
        for c in ex.conditions:
            w_expr(w, c)
    elif isinstance(ex, Projection):
        w.u8(_EX_PROJ)
        w.i32(len(ex.exprs))
        for e in ex.exprs:
            w_expr(w, e)
    elif isinstance(ex, Aggregation):
        w.u8(_EX_AGG)
        w.bool_(ex.stream)
        w.bool_(ex.partial)
        w.bool_(ex.merge)
        w.i32(len(ex.group_by))
        for g in ex.group_by:
            w_expr(w, g)
        w.i32(len(ex.aggs))
        for a in ex.aggs:
            w_agg_desc(w, a)
    elif isinstance(ex, TopN):
        w.u8(_EX_TOPN)
        w.i64(ex.limit)
        w.i32(len(ex.order_by))
        for e, desc in ex.order_by:
            w_expr(w, e)
            w.bool_(desc)
    elif isinstance(ex, Limit):
        w.u8(_EX_LIMIT)
        w.i64(ex.limit)
    elif isinstance(ex, Sort):
        w.u8(_EX_SORT)
        w.i32(len(ex.order_by))
        for e, desc in ex.order_by:
            w_expr(w, e)
            w.bool_(desc)
    elif isinstance(ex, Join):
        w.u8(_EX_JOIN)
        w.s(ex.join_type)
        w.bool_(ex.build_unique)
        w.i32(len(ex.build))
        for b in ex.build:
            w_executor(w, b)
        w.i32(len(ex.probe_keys))
        for k in ex.probe_keys:
            w_expr(w, k)
        for k in ex.build_keys:
            w_expr(w, k)
    else:
        raise NotImplementedError(f"wire executor {type(ex).__name__}")


def r_executor(r: Reader):
    from ..exec.dag import Aggregation, ColumnInfo, IndexScan, Join, Limit, Projection, Selection, Sort, TableScan, TopN

    tag = r.u8()
    if tag == _EX_ISCAN:
        tid = r.i64()
        iid = r.i64()
        desc = r.bool_()
        cols = tuple(ColumnInfo(r.i64(), r_ft(r), r_opt_datum(r)) for _ in range(r.i32()))
        return IndexScan(tid, iid, cols, desc)
    if tag == _EX_SCAN:
        tid = r.i64()
        desc = r.bool_()
        cols = tuple(ColumnInfo(r.i64(), r_ft(r), r_opt_datum(r)) for _ in range(r.i32()))
        return TableScan(tid, cols, desc)
    if tag == _EX_SEL:
        return Selection(tuple(r_expr(r) for _ in range(r.i32())))
    if tag == _EX_PROJ:
        return Projection(tuple(r_expr(r) for _ in range(r.i32())))
    if tag == _EX_AGG:
        stream = r.bool_()
        partial = r.bool_()
        merge = r.bool_()
        group_by = tuple(r_expr(r) for _ in range(r.i32()))
        aggs = tuple(r_agg_desc(r) for _ in range(r.i32()))
        return Aggregation(group_by, aggs, stream, partial, merge)
    if tag == _EX_TOPN:
        limit = r.i64()
        order = tuple((r_expr(r), r.bool_()) for _ in range(r.i32()))
        return TopN(order, limit)
    if tag == _EX_LIMIT:
        return Limit(r.i64())
    if tag == _EX_SORT:
        return Sort(tuple((r_expr(r), r.bool_()) for _ in range(r.i32())))
    if tag == _EX_JOIN:
        jt = r.s()
        bu = r.bool_()
        build = tuple(r_executor(r) for _ in range(r.i32()))
        nk = r.i32()
        pks = tuple(r_expr(r) for _ in range(nk))
        bks = tuple(r_expr(r) for _ in range(nk))
        return Join(build, pks, bks, jt, build_unique=bu)
    raise ValueError(f"bad executor tag {tag}")


def encode_dag(dag) -> bytes:
    """DAGRequest -> bytes (the tipb.DAGRequest analog)."""
    w = Writer()
    w.i32(len(dag.executors))
    for ex in dag.executors:
        w_executor(w, ex)
    w.i32(len(dag.output_offsets))
    for o in dag.output_offsets:
        w.i32(o)
    w.s(dag.time_zone)
    w.i64(dag.flags)
    return w.done()


def decode_dag(b: bytes):
    from ..exec.dag import DAGRequest

    r = Reader(b)
    executors = tuple(r_executor(r) for _ in range(r.i32()))
    offsets = tuple(r.i32() for _ in range(r.i32()))
    tz = r.s()
    flags = r.i64()
    return DAGRequest(executors, offsets, tz, flags)


# ------------------------------------------------------- mpp fragment frames

# exchange partition-mode tags (ref: tipb.ExchangeType — PassThrough /
# Broadcast / Hash; mpp/fragment.py mirrors the same three modes)
_EXCH_MODES = ("hash", "broadcast", "passthrough")


def w_exchange_sender(w: Writer, s):
    w.u8(_EXCH_MODES.index(s.exchange_type))
    w.i32(s.target_fragment)
    w.i32(len(s.partition_keys))
    for k in s.partition_keys:
        w_expr(w, k)


def r_exchange_sender(r: Reader):
    from ..mpp.fragment import ExchangeSender

    mode = _EXCH_MODES[r.u8()]
    target = r.i32()
    keys = tuple(r_expr(r) for _ in range(r.i32()))
    return ExchangeSender(mode, keys, target)


def encode_fragment_plan(fplan) -> bytes:
    """FragmentPlan -> bytes — the per-query ExchangeSender wire seam (the
    tipb.DispatchTaskRequest analog: fragment topology + per-fragment plan
    slices). mpp/dispatch.py round-trips every dispatched plan through
    this frame, so the fragment graph is proven wire-clean per query, the
    way use_wire proves the cop DAG."""
    w = Writer()
    w.i32(fplan.n_tasks)
    w.i32(fplan.root)
    w.i32(len(fplan.fragments))
    for f in fplan.fragments:
        w.i32(f.idx)
        w.i32(len(f.executors))
        for ex in f.executors:
            w_executor(w, ex)
        w.i32(len(f.receivers))
        for rcv in f.receivers:
            w.i32(rcv.source_fragment)
        w_exchange_sender(w, f.sender)
    return w.done()


def decode_fragment_plan(b: bytes):
    from ..mpp.fragment import ExchangeReceiver, Fragment, FragmentPlan

    r = Reader(b)
    n_tasks = r.i32()
    root = r.i32()
    frags = []
    for _ in range(r.i32()):
        idx = r.i32()
        executors = tuple(r_executor(r) for _ in range(r.i32()))
        receivers = tuple(ExchangeReceiver(r.i32()) for _ in range(r.i32()))
        sender = r_exchange_sender(r)
        frags.append(Fragment(idx, executors, receivers, sender))
    return FragmentPlan(tuple(frags), n_tasks, root)


# -------------------------------------------------------------- chunks

def encode_chunk(ch: Chunk) -> bytes:
    """Chunk -> bytes: per column, FieldType + null bitmap + raw buffers —
    the TypeChunk idea (ref: pkg/util/chunk/codec.go:37 — raw little-endian
    column buffers on the wire, no per-datum encoding)."""
    w = Writer()
    w.i32(len(ch.columns))
    w.i32(ch.num_rows())
    for col in ch.columns:
        w_ft(w, col.ft)
        w.blob(np.packbits(np.asarray(col.null, bool)).tobytes())
        if col.is_varlen():
            w.u8(1)
            w.blob(np.asarray(col.offsets, np.int64).tobytes())
            w.blob(np.asarray(col.blob, np.uint8).tobytes())
        else:
            w.u8(0)
            data = col.data
            w.s(data.dtype.str)
            w.blob(data.tobytes())
    return w.done()


def decode_chunk(b: bytes) -> Chunk:
    r = Reader(b)
    n_cols = r.i32()
    n_rows = r.i32()
    cols = []
    for _ in range(n_cols):
        ft = r_ft(r)
        null = np.unpackbits(np.frombuffer(r.blob(), np.uint8), count=n_rows).astype(bool)
        if r.u8():
            offsets = np.frombuffer(r.blob(), np.int64).copy()
            blob = np.frombuffer(r.blob(), np.uint8).copy()
            cols.append(Column(ft, None, null, offsets, blob))
        else:
            dt = np.dtype(r.s())
            data = np.frombuffer(r.blob(), dt).copy()
            cols.append(Column(ft, data, null))
    return Chunk(cols)


# -------------------------------------------------------------- cop seam

def encode_cop_request(req, _aux_index=None) -> bytes:
    """_aux_index (chunk -> table index) switches the aux section to
    back-references into a frame-level chunk table: a batch frame carries
    each distinct broadcast build side ONCE instead of once per region
    request (N regions x one 64MB build side must not make an N*64MB
    frame). None keeps the self-contained single-request layout."""
    w = Writer()
    b = encode_dag(req.dag)
    w.blob(b)
    w.i32(len(req.ranges))
    for rg in req.ranges:
        w.blob(rg.start)
        w.blob(rg.end)
    w.i64(req.start_ts)
    w.i64(req.region_id)
    w.i64(req.region_epoch)
    w.i32(len(req.aux_chunks))
    for c in req.aux_chunks:
        if _aux_index is None:
            w.blob(encode_chunk(c))
        else:
            w.i32(_aux_index(c))
    w.i32(-1 if req.paging_size is None else req.paging_size)
    w.i32(-1 if req.small_groups is None else req.small_groups)
    w.i32(req.peer_store)
    w.bool_(req.replica_read)
    w.bool_(req.mesh)
    # i64: the tidb_tpu_mesh_min_rows sysvar range (up to 1<<40) exceeds i32
    w.i64(req.mesh_min_rows)
    return w.done()


def decode_cop_request(b: bytes, _aux_table: list | None = None):
    """_aux_table is the batch frame's shared chunk table: every region
    task of a broadcast join references the SAME decoded build side, which
    restores the object identity the store's batch grouping and aux-upload
    cache key on — without it, wire-mode batching would decode N distinct
    copies and every group would collapse to a singleton."""
    from ..store.store import CopRequest, KeyRange

    r = Reader(b)
    dag = decode_dag(r.blob())
    ranges = [KeyRange(r.blob(), r.blob()) for _ in range(r.i32())]
    start_ts = r.i64()
    region_id = r.i64()
    epoch = r.i64()
    n_aux = r.i32()
    if _aux_table is None:
        aux = [decode_chunk(r.blob()) for _ in range(n_aux)]
    else:
        aux = [_aux_table[r.i32()] for _ in range(n_aux)]
    paging = r.i32()
    smg = r.i32()
    peer_store = r.i32()
    replica_read = r.bool_()
    mesh = r.bool_() if r.i < len(r.b) else False
    mesh_min_rows = r.i64() if r.i < len(r.b) else 0
    return CopRequest(dag, ranges, start_ts, region_id, epoch, aux,
                      None if paging < 0 else paging,
                      None if smg < 0 else smg,
                      peer_store=peer_store, replica_read=replica_read,
                      mesh=mesh, mesh_min_rows=mesh_min_rows)


def encode_cop_response(resp) -> bytes:
    w = Writer()
    w.bool_(resp.chunk is not None)
    if resp.chunk is not None:
        w.blob(encode_chunk(resp.chunk))
    w.s(resp.region_error or "")
    w.s(resp.other_error or "")
    w.i32(len(resp.exec_summaries))
    for sm in resp.exec_summaries:
        w.i64(sm.time_processed_ns)
        w.i64(sm.num_produced_rows)
        w.i64(sm.num_iterations)
        w.i64(sm.time_compile_ns)
        w.bool_(sm.cache_hit)
        w.i64(sm.num_bytes)
        w.i64(sm.radix_partitions)
        w.i64(sm.radix_rung)
        w.i64(sm.radix_escapes)
    w.bool_(resp.last_range is not None)
    if resp.last_range is not None:
        w.i32(len(resp.last_range))
        for rg in resp.last_range:
            w.blob(rg.start)
            w.blob(rg.end)
    w.i32(int(getattr(resp, "batched", 0)))
    w.i32(int(getattr(resp, "mesh_merged", 0)))
    return w.done()


def decode_cop_response(b: bytes):
    from ..store.store import CopResponse, ExecSummary, KeyRange

    r = Reader(b)
    chunk = decode_chunk(r.blob()) if r.bool_() else None
    region_error = r.s() or None
    other_error = r.s() or None
    summaries = [
        ExecSummary(r.i64(), r.i64(), r.i64(), r.i64(), r.bool_(), r.i64(),
                    r.i64(), r.i64(), r.i64())
        for _ in range(r.i32())
    ]
    last_range = None
    if r.bool_():
        last_range = [KeyRange(r.blob(), r.blob()) for _ in range(r.i32())]
    batched = r.i32() if r.i < len(r.b) else 0
    mesh_merged = r.i32() if r.i < len(r.b) else 0
    return CopResponse(chunk, region_error, other_error, summaries, last_range, batched,
                       mesh_merged)


# ----------------------------------------------------- batched cop frames

def encode_batch_cop_request(reqs) -> bytes:
    """N cop requests in one frame — the batch-coprocessor wire shape (ref:
    copr/batch_coprocessor.go batching all of a store's region tasks into
    one RPC). Layout: request frames with aux back-references, then the
    shared chunk table — each DISTINCT broadcast build side travels once
    per frame, however many region requests carry it."""
    w = Writer()
    w.i32(len(reqs))
    table: list = []
    index: dict[int, int] = {}

    def aux_index(c) -> int:
        k = id(c)  # objects stay alive for the duration of this call
        if k not in index:
            index[k] = len(table)
            table.append(c)
        return index[k]

    for req in reqs:
        w.blob(encode_cop_request(req, _aux_index=aux_index))
    w.i32(len(table))
    for c in table:
        w.blob(encode_chunk(c))
    return w.done()


def decode_batch_cop_request(b: bytes) -> list:
    r = Reader(b)
    blobs = [r.blob() for _ in range(r.i32())]
    table = [decode_chunk(r.blob()) for _ in range(r.i32())]
    return [decode_cop_request(bb, _aux_table=table) for bb in blobs]


def encode_batch_cop_response(resps) -> bytes:
    """N cop responses in one frame, request order preserved."""
    w = Writer()
    w.i32(len(resps))
    for resp in resps:
        w.blob(encode_cop_response(resp))
    return w.done()


def decode_batch_cop_response(b: bytes) -> list:
    r = Reader(b)
    return [decode_cop_response(r.blob()) for _ in range(r.i32())]
