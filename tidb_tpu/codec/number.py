"""Low-level number/bytes codecs (ref: pkg/util/codec/{number,bytes,float}.go).

Two families:
  - *comparable* encodings (big-endian, sign-flipped) used in keys, where
    lexicographic byte order must equal value order;
  - *compact* little-endian / varint encodings used inside row values.
"""

from __future__ import annotations

import struct

SIGN_MASK = 0x8000000000000000
U64 = (1 << 64) - 1


# ---- comparable (key) encodings -------------------------------------------

def encode_int_cmp(v: int) -> bytes:
    """int64 -> 8 bytes, order-preserving (ref: number.go EncodeIntToCmpUint)."""
    return struct.pack(">Q", (v & U64) ^ SIGN_MASK)


def decode_int_cmp(b: bytes, pos: int = 0) -> tuple[int, int]:
    u = struct.unpack_from(">Q", b, pos)[0] ^ SIGN_MASK
    return (u - (1 << 64)) if u & SIGN_MASK else u, pos + 8


def encode_uint_cmp(v: int) -> bytes:
    return struct.pack(">Q", v & U64)


def decode_uint_cmp(b: bytes, pos: int = 0) -> tuple[int, int]:
    return struct.unpack_from(">Q", b, pos)[0], pos + 8


def encode_float_cmp(v: float) -> bytes:
    """(ref: float.go encodeFloatToCmpUint64)."""
    u = struct.unpack(">Q", struct.pack(">d", v))[0]
    if u & SIGN_MASK:
        u = (~u) & U64
    else:
        u |= SIGN_MASK
    return struct.pack(">Q", u)


def decode_float_cmp(b: bytes, pos: int = 0) -> tuple[float, int]:
    u = struct.unpack_from(">Q", b, pos)[0]
    if u & SIGN_MASK:
        u &= ~SIGN_MASK & U64
    else:
        u = (~u) & U64
    return struct.unpack(">d", struct.pack(">Q", u))[0], pos + 8


ENC_GROUP_SIZE = 8
ENC_MARKER = 0xFF
ENC_PAD = 0x00


def encode_bytes_cmp(data: bytes) -> bytes:
    """Memcomparable bytes: 8-byte groups + pad-count marker
    (ref: bytes.go EncodeBytes)."""
    out = bytearray()
    for i in range(0, len(data) + 1, ENC_GROUP_SIZE):
        group = data[i : i + ENC_GROUP_SIZE]
        pad = ENC_GROUP_SIZE - len(group)
        out += group + bytes([ENC_PAD]) * pad
        out.append(ENC_MARKER - pad)
    return bytes(out)


def decode_bytes_cmp(b: bytes, pos: int = 0) -> tuple[bytes, int]:
    out = bytearray()
    while True:
        group = b[pos : pos + ENC_GROUP_SIZE]
        marker = b[pos + ENC_GROUP_SIZE]
        pos += ENC_GROUP_SIZE + 1
        pad = ENC_MARKER - marker
        if pad == 0:
            out += group
        else:
            out += group[: ENC_GROUP_SIZE - pad]
            break
    return bytes(out), pos


# ---- compact (value) encodings --------------------------------------------

def encode_varint(v: int) -> bytes:
    """Zigzag varint (ref: binary.PutVarint)."""
    u = ((v << 1) ^ (v >> 63)) & U64  # python >> is arithmetic for negatives
    return encode_uvarint(u)


def decode_varint(b: bytes, pos: int = 0) -> tuple[int, int]:
    u, pos = decode_uvarint(b, pos)
    v = u >> 1
    if u & 1:
        v = ~v
    return v, pos


def encode_uvarint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def decode_uvarint(b: bytes, pos: int = 0) -> tuple[int, int]:
    v = shift = 0
    while True:
        x = b[pos]
        pos += 1
        v |= (x & 0x7F) << shift
        if x < 0x80:
            return v, pos
        shift += 7


def encode_compact_bytes(data: bytes) -> bytes:
    """(ref: bytes.go EncodeCompactBytes: varint length + raw)."""
    return encode_varint(len(data)) + data


def decode_compact_bytes(b: bytes, pos: int = 0) -> tuple[bytes, int]:
    n, pos = decode_varint(b, pos)
    return b[pos : pos + n], pos + n


def encode_int_value(v: int) -> bytes:
    """Variable-width little-endian int used inside rowcodec values
    (ref: rowcodec/common.go encodeInt)."""
    if -(1 << 7) <= v < (1 << 7):
        return struct.pack("<b", v)
    if -(1 << 15) <= v < (1 << 15):
        return struct.pack("<h", v)
    if -(1 << 31) <= v < (1 << 31):
        return struct.pack("<i", v)
    return struct.pack("<q", v)


def decode_int_value(b: bytes) -> int:
    n = len(b)
    if n == 1:
        return struct.unpack("<b", b)[0]
    if n == 2:
        return struct.unpack("<h", b)[0]
    if n == 4:
        return struct.unpack("<i", b)[0]
    return struct.unpack("<q", b)[0]


def encode_uint_value(v: int) -> bytes:
    if v < (1 << 8):
        return struct.pack("<B", v)
    if v < (1 << 16):
        return struct.pack("<H", v)
    if v < (1 << 32):
        return struct.pack("<I", v)
    return struct.pack("<Q", v)


def decode_uint_value(b: bytes) -> int:
    n = len(b)
    if n == 1:
        return b[0]
    if n == 2:
        return struct.unpack("<H", b)[0]
    if n == 4:
        return struct.unpack("<I", b)[0]
    return struct.unpack("<Q", b)[0]
