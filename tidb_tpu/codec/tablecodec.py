"""Table key layout (ref: pkg/tablecodec/tablecodec.go:50-51,103).

    record key: t{tableID}_r{handle}   -> 't' + cmp-int64 + "_r" + cmp-int64
    index  key: t{tableID}_i{indexID}{encoded index datums}

tableID/handle/indexID use the comparable int64 encoding without a flag byte,
so keys sort by (tableID, handle).
"""

from __future__ import annotations

from ..types import Datum
from .datum_codec import encode_datums
from .number import decode_int_cmp, encode_int_cmp

TABLE_PREFIX = b"t"
RECORD_SEP = b"_r"
INDEX_SEP = b"_i"
RECORD_ROW_KEY_LEN = 1 + 8 + 2 + 8


def record_prefix(table_id: int) -> bytes:
    return TABLE_PREFIX + encode_int_cmp(table_id) + RECORD_SEP


def table_prefix(table_id: int) -> bytes:
    return TABLE_PREFIX + encode_int_cmp(table_id)


def encode_row_key(table_id: int, handle: int) -> bytes:
    return record_prefix(table_id) + encode_int_cmp(handle)


def decode_row_key(key: bytes) -> tuple[int, int]:
    if len(key) < RECORD_ROW_KEY_LEN or key[:1] != TABLE_PREFIX or key[9:11] != RECORD_SEP:
        raise ValueError(f"not a record key: {key!r}")
    tid, _ = decode_int_cmp(key, 1)
    handle, _ = decode_int_cmp(key, 11)
    return tid, handle


def encode_index_key(table_id: int, index_id: int, values: list[Datum]) -> bytes:
    return (
        TABLE_PREFIX
        + encode_int_cmp(table_id)
        + INDEX_SEP
        + encode_int_cmp(index_id)
        + encode_datums(values, comparable=True)
    )


def decode_key_table_id(key: bytes) -> int:
    tid, _ = decode_int_cmp(key, 1)
    return tid
