"""Flagged datum codec (ref: pkg/util/codec/codec.go EncodeValue/EncodeKey).

Keys use comparable encodings (flag + big-endian/memcomparable payload) so
byte order == datum order; values may use compact varint/compact-bytes forms.
Flags per codec.go:41-53 / rowcodec/common.go:42-53.
"""

from __future__ import annotations

from ..types import Datum, DatumKind, FieldType, MyDecimal, MyTime
from . import number
from .decimal_bin import decode_decimal, encode_decimal

NIL_FLAG = 0
BYTES_FLAG = 1
COMPACT_BYTES_FLAG = 2
INT_FLAG = 3
UINT_FLAG = 4
FLOAT_FLAG = 5
DECIMAL_FLAG = 6
DURATION_FLAG = 7
VARINT_FLAG = 8
VARUINT_FLAG = 9
JSON_FLAG = 10
MAX_FLAG = 250


def encode_datum(d: Datum, comparable: bool = True) -> bytes:
    """Encode one datum (ref: codec.go encode)."""
    k = d.kind
    if k == DatumKind.Null:
        return bytes([NIL_FLAG])
    if k == DatumKind.Int64:
        if comparable:
            return bytes([INT_FLAG]) + number.encode_int_cmp(d.val)
        return bytes([VARINT_FLAG]) + number.encode_varint(d.val)
    if k in (DatumKind.Uint64, DatumKind.MysqlEnum, DatumKind.MysqlSet, DatumKind.MysqlBit):
        if comparable:
            return bytes([UINT_FLAG]) + number.encode_uint_cmp(d.val)
        return bytes([VARUINT_FLAG]) + number.encode_uvarint(d.val)
    if k in (DatumKind.Float32, DatumKind.Float64):
        return bytes([FLOAT_FLAG]) + number.encode_float_cmp(float(d.val))
    if k in (DatumKind.String, DatumKind.Bytes):
        b = d.val.encode() if isinstance(d.val, str) else bytes(d.val)
        if comparable:
            return bytes([BYTES_FLAG]) + number.encode_bytes_cmp(b)
        return bytes([COMPACT_BYTES_FLAG]) + number.encode_compact_bytes(b)
    if k == DatumKind.MysqlDecimal:
        return bytes([DECIMAL_FLAG]) + encode_decimal(d.val)
    if k == DatumKind.MysqlTime:
        packed = d.val.packed if isinstance(d.val, MyTime) else int(d.val)
        if comparable:
            return bytes([UINT_FLAG]) + number.encode_uint_cmp(packed)
        return bytes([VARUINT_FLAG]) + number.encode_uvarint(packed)
    if k == DatumKind.MysqlDuration:
        return bytes([DURATION_FLAG]) + number.encode_int_cmp(d.val)
    if k == DatumKind.MaxValue:
        return bytes([MAX_FLAG])
    raise ValueError(f"cannot encode datum kind {k}")


def encode_datums(ds: list[Datum], comparable: bool = True) -> bytes:
    return b"".join(encode_datum(d, comparable) for d in ds)


def decode_datum(b: bytes, pos: int = 0, ft: FieldType | None = None) -> tuple[Datum, int]:
    """Decode one datum; ft refines time/duration interpretation."""
    flag = b[pos]
    pos += 1
    if flag == NIL_FLAG:
        return Datum.NULL, pos
    if flag == INT_FLAG:
        v, pos = number.decode_int_cmp(b, pos)
        return Datum.i64(v), pos
    if flag == UINT_FLAG:
        v, pos = number.decode_uint_cmp(b, pos)
        if ft is not None and ft.is_time():
            return Datum.time(MyTime(v, max(ft.decimal, 0))), pos
        return Datum.u64(v), pos
    if flag == VARINT_FLAG:
        v, pos = number.decode_varint(b, pos)
        return Datum.i64(v), pos
    if flag == VARUINT_FLAG:
        v, pos = number.decode_uvarint(b, pos)
        if ft is not None and ft.is_time():
            return Datum.time(MyTime(v, max(ft.decimal, 0))), pos
        return Datum.u64(v), pos
    if flag == FLOAT_FLAG:
        v, pos = number.decode_float_cmp(b, pos)
        return Datum.f64(v), pos
    if flag == BYTES_FLAG:
        v, pos = number.decode_bytes_cmp(b, pos)
        return _bytes_datum(v, ft), pos
    if flag == COMPACT_BYTES_FLAG:
        v, pos = number.decode_compact_bytes(b, pos)
        return _bytes_datum(v, ft), pos
    if flag == DECIMAL_FLAG:
        v, pos = decode_decimal(b, pos)
        return Datum.dec(v), pos
    if flag == DURATION_FLAG:
        v, pos = number.decode_int_cmp(b, pos)
        return Datum.duration(v), pos
    if flag == MAX_FLAG:
        return Datum(DatumKind.MaxValue), pos
    raise ValueError(f"invalid encoded datum flag {flag}")


def _bytes_datum(v: bytes, ft: FieldType | None) -> Datum:
    if ft is not None and ft.is_string() and ft.charset != "binary":
        return Datum.string(v.decode("utf-8", "surrogateescape"))
    return Datum.bytes_(v)


def decode_datums(b: bytes, fts: list[FieldType] | None = None) -> list[Datum]:
    out, pos, i = [], 0, 0
    while pos < len(b):
        ft = fts[i] if fts and i < len(fts) else None
        d, pos = decode_datum(b, pos, ft)
        out.append(d)
        i += 1
    return out
