"""The TPU coprocessor store — this framework's unistore.

Implements the coprocessor contract end to end
(ref: unistore/tikv/server.go:625 Coprocessor ->
cophandler/cop_handler.go:89 HandleCopRequest): a CopRequest carries the DAG,
key ranges and snapshot ts; the store materializes the region's rows as a
columnar chunk (rowcodec decode happens ONCE per region version, then the
chunk — host and device — is cached), runs the fused device program, and
returns the result chunk plus execution summaries.

Region errors (epoch mismatch after a split) surface exactly like TiKV's so
the distsql layer exercises the same retry/re-split path as the reference
(ref: copr/coprocessor.go:1424).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from dataclasses import dataclass, field, replace

from ..chunk import Chunk, to_device_batch
from ..chunk.device import DeviceBatch, to_stacked_device_batch
from ..codec import tablecodec
from ..codec.rowcodec import RowEncoder, decode_row_to_datum_map, fill_origin_default
from ..exec.builder import DEFAULT_GROUP_CAPACITY, ProgramCache
from ..exec.dag import DAGRequest
from ..exec.executor import OverflowRetryError, drive_batched_program_info, drive_program_info, run_dag_reference, _pow2
from ..types import Datum
from .kv import MemKV
from .region import Cluster, Region


@dataclass(frozen=True)
class KeyRange:
    """(ref: coprocessor.KeyRange)."""

    start: bytes
    end: bytes


@dataclass
class CopRequest:
    """(ref: coprocessor.Request: tp=DAG, data, ranges, start_ts).

    aux_chunks: broadcast operands for the DAG's join build sides, one per
    non-probe scan in canonical order (the TiFlash broadcast-exchange analog
    — ref: mpp_exec.go:669 Broadcast partition mode). Every region task of a
    broadcast join carries the same chunks; the device upload is shared.

    paging_size: when set, the scan stops after at most this many rows and
    the response carries `last_range`, the resume cursor for the next page
    (ref: copr/coprocessor.go:1393 handleCopPagingResult; store side
    cop_handler.go:210 lastRange). Row-local DAGs only — aggregations
    cannot produce correct partials from a partial scan."""

    dag: DAGRequest
    ranges: list
    start_ts: int
    region_id: int = 0
    region_epoch: int = 0
    aux_chunks: list = field(default_factory=list)
    paging_size: int | None = None
    small_groups: int | None = None  # planner NDV hint (stats-driven)
    peer_store: int = -1  # the peer the client routed to (-1 = whoever
    # leads at serve time); a non-leader peer answers NotLeader unless
    # replica_read (ref: kvrpcpb.Context.peer)
    replica_read: bool = False  # follower read: a non-leader peer may
    # serve IF its safe_ts covers start_ts, else DataIsNotReady
    # (ref: kvrpcpb.Context.replica_read)
    mesh: bool = False  # the dispatch planner chose the MESH tier for this
    # store batch: shard the stacked lanes over the device mesh and merge
    # the per-region partial states on device (psum over the region axis)
    # instead of returning R per-region partials (distsql/planner.py)
    mesh_min_rows: int = 0  # tidb_tpu_mesh_min_rows carried to the store:
    # the AUTHORITATIVE data-size floor, applied to the group's actually
    # decoded row total (the client's estimate only gated the attempt)


@dataclass
class ExecSummary:
    """(ref: tipb.ExecutorExecutionSummary, cop_handler.go:518). Extended
    with device-time attribution: where the task's wall time went —
    XLA compile (vs. a program-cache hit) and the bytes the executor
    moved (scan row: decoded region bytes; final row: result bytes)."""

    time_processed_ns: int = 0
    num_produced_rows: int = 0
    num_iterations: int = 1
    time_compile_ns: int = 0  # 0 on a cache hit
    cache_hit: bool = False  # the fused program came from the cache
    num_bytes: int = 0
    # radix-join attribution (ISSUE 13): set on Join executors whose task
    # rode the radix-partitioned kernel — partition count, the join
    # capacity RUNG the program compiled at, and the skew-escape row
    # count; 0/0/0 = monolithic kernel (EXPLAIN ANALYZE `join_radix` row)
    radix_partitions: int = 0
    radix_rung: int = 0
    radix_escapes: int = 0


@dataclass
class CopResponse:
    chunk: Chunk | None = None
    region_error: str | None = None
    other_error: str | None = None
    exec_summaries: list = field(default_factory=list)
    last_range: list | None = None  # [KeyRange] resume cursor; None = drained
    batched: int = 0  # nonzero = served by a vmapped batch launch (NOT by
    # the cop cache, an overflow fall-out, or a single-path degrade); the
    # value identifies the launch within its batch_coprocessor call, so the
    # dispatch layer can count distinct launches for launches_saved
    mesh_merged: int = 0  # nonzero = this lane's partial state was merged
    # ON DEVICE with its group's other lanes (psum over the region axis);
    # the value is the number of lanes the one merged state covers — the
    # group's FIRST lane carries the merged chunk, the rest answer empty


def _apply_radix_attribution(summaries: list, walk, info) -> None:
    """Fold the driver's `join_radix` attribution (exec/executor.py
    _radix_attribution: partitions / capacity rung / skew escapes) onto
    the FIRST Join executor's summary — the triple is PROGRAM-level (one
    escape total, one plan per compiled program), so stamping every Join
    would multiply it in EXPLAIN ANALYZE's cross-summary sum; the summary
    indexes align with the executor walk, same as the row counts."""
    ri = info.get("radix") if isinstance(info, dict) else None
    if not ri:
        return
    from ..exec.dag import Join as _Join

    for i, ex in enumerate(walk):
        if isinstance(ex, _Join) and i < len(summaries):
            summaries[i].radix_partitions = int(ri.get("partitions") or 0)
            summaries[i].radix_rung = int(ri.get("rung") or 0)
            summaries[i].radix_escapes = int(ri.get("escapes") or 0)
            return


def _fault_matches(value, store_id: int) -> bool:
    """Per-store failpoint arming: True fires for every store; a
    set/list/tuple of ids fires for those stores; a dict
    `{"stores": ids-or-None, ...}` fires for the listed stores (None =
    all) and may carry extra payload (`backoff_ms` for server-busy); a
    ZERO-arg callable returns any of those shapes per hit (custom
    fire-N-times logic — `failpoint.eval` already invokes callables with
    no arguments, so this is the only callable arity that exists; a
    value arriving un-invoked via `failpoint.peek` is asked here).
    None/falsy never fires."""
    if not value:
        return False
    if callable(value):  # peek path hands over the raw callable
        return _fault_matches(value(), store_id)
    if value is True or isinstance(value, int):
        return True
    if isinstance(value, (set, frozenset, list, tuple)):
        return store_id in value
    if isinstance(value, dict):
        stores = value.get("stores")
        return stores is None or store_id in stores
    return True


class TPUStore:
    """KV + regions + TPU coprocessor, one process (ref: mockstore
    EmbedUnistore, mockstore.go:86)."""

    def __init__(self):
        from ..pd.core import PlacementDriver
        from ..replication import ReplicaManager
        from .txn import TxnEngine

        self.kv = MemKV()
        self.cluster = Cluster()
        self.programs = ProgramCache()
        # the control plane: flow stats always record (cheap increments);
        # the schedulers only act when tick()/timer runs (ref: every
        # TiKV store heartbeats PD whether or not PD is scheduling)
        self.pd = PlacementDriver(self)
        # the replication overlay: peer sets live on the cluster, per-peer
        # applied watermarks (safe_ts) live here; every committed write
        # proposes through it (ISSUE 8)
        self.replication = ReplicaManager(self)
        # change data capture (ISSUE 10): the hub subscribes to every
        # replication proposal; its WriteGuard brackets the write paths
        # so the resolved-ts frontier can prove quiescence
        from ..cdc import ChangefeedHub

        self.cdc = ChangefeedHub(self)
        # the HTAP columnar replica tier (ISSUE 12): per-table delta+stable
        # column stores fed by changefeeds, compacted by the pd.columnar
        # tick phase, routed to by tidb_isolation_read_engines
        from ..columnar import ColumnarReplica

        self.columnar = ColumnarReplica(self)
        self.txn = TxnEngine(self.kv, on_commit=self._bump_write_ver,
                             on_apply=self.record_applied_writes,
                             pre_apply=self._check_write_quorum,
                             write_guard=self.cdc.guard.writing,
                             on_apply_group=self.record_applied_writes_grouped)
        self._tso = itertools.count(100)  # guarded_by: _tso_lock
        self._tso_lock = threading.Lock()
        self._active_snapshots: dict[int, int] = {}  # guarded_by: _tso_lock
        self._write_ver = 0  # guarded_by: _cop_lock
        self._chunk_cache: dict = {}
        self._batch_cache: dict = {}
        self._aux_batch_cache: dict = {}  # token -> (chunk, DeviceBatch); guarded_by: _aux_lock
        self._aux_lock = threading.Lock()  # select() fans tasks over threads
        self._chunk_tokens = itertools.count(1)  # monotonic chunk identity; guarded_by: _aux_lock
        # coprocessor RESULT cache (ref: pkg/store/copr/coprocessor_cache.go):
        # a whole region response keyed by the region's data version
        self._cop_cache: dict = {}  # guarded_by: _cop_lock
        self._cop_lock = threading.Lock()
        self._row_encoder = RowEncoder()
        # fault switches: logical placement stores marked down answer every
        # cop request with a typed StoreUnavailable region error (the
        # in-process analog of a TiKV store dropping off the network)
        self._down_stores: set[int] = set()  # guarded_by: _down_lock
        self._down_lock = threading.Lock()
        # per-store circuit breakers — client-side state, but shared by
        # every session/dispatch thread on this store (runtime import:
        # the distsql layer imports this module at load time)
        from ..distsql.dispatch import BreakerBoard

        self.breakers = BreakerBoard()
        # admission control (ISSUE 15): one gate per store — every session
        # and the dispatch layer of a server consult it; fully open by
        # default (0 = unlimited), configured by server config / tests
        # (runtime import: server/__init__ lazily re-exports, no cycle)
        from ..server.admission import AdmissionGate

        self.admission = AdmissionGate()
        # cross-session fused execution (ISSUE 19): one coalescer per
        # store — concurrent plan-cache-hit point-gets park in a
        # micro-batch window and ship as ONE batch-cop launch; concurrent
        # autocommit single-row writes fold into group commit (runtime
        # import for the same no-cycle reason as the gate)
        from ..server.coalesce import SessionCoalescer

        self.coalescer = SessionCoalescer(self)
        # point-in-time recovery (ISSUE 20): the ordered store-level log
        # of schema-change entries (the changefeed recovery source — they
        # are synthetic, never in KV) and the attached log backups
        # (dest uri -> br.pitr.LogBackup; GIL-atomic dict ops, written by
        # BACKUP LOG / stop, read by the pd.pitr tick)
        from ..cdc.schema import SchemaJournal

        self.schema_journal = SchemaJournal()
        self.log_backups: dict = {}

    # -- store fault switches (chaos/testing; ref: failpoint-driven store
    # outages in the reference's integration suites) ------------------------
    def set_down(self, store_id: int) -> None:
        """Take one logical placement store down: every cop request whose
        region is placed there answers `store_unavailable` until set_up."""
        with self._down_lock:
            self._down_stores.add(store_id)

    def set_up(self, store_id: int) -> None:
        with self._down_lock:
            self._down_stores.discard(store_id)

    def store_down(self, store_id: int) -> bool:
        with self._down_lock:
            return store_id in self._down_stores

    def down_stores(self) -> set:
        with self._down_lock:
            return set(self._down_stores)

    def ping_store(self, store_id: int) -> bool:
        """Store liveness probe (ref: client-go store liveness check /
        PD's store heartbeat watchdog): False when the store is switched
        down OR the unreachable failpoint is armed for it. Non-consuming —
        a probe must never eat a fire-N-times count."""
        from ..util import failpoint

        if self.store_down(store_id):
            return False
        return not _fault_matches(failpoint.peek("store/unreachable"), store_id)

    def evict_caches(self) -> int:
        """Drop the decoded-chunk and device-batch caches — the first OOM
        action in the chain (ref: pkg/util/memory ActionOnExceed
        SoftLimit/spill ordering: free reclaimable buffers before killing
        the query). Returns an approximate byte count freed."""
        freed = 0
        for c in self._chunk_cache.values():
            freed += c.nbytes()
        with self._cop_lock:
            for resp, _ts, _flow in self._cop_cache.values():
                if resp.chunk is not None:
                    freed += resp.chunk.nbytes()
            self._cop_cache.clear()
        self._chunk_cache.clear()
        self._batch_cache.clear()
        with self._aux_lock:  # select() uploads aux batches from pool threads
            self._aux_batch_cache.clear()
        return freed

    def next_ts(self) -> int:
        """Store-global TSO (ref: PD timestamp oracle; mock unistore/pd.go).
        Sessions sharing a store draw from one clock so snapshots and
        commit timestamps totally order across sessions."""
        with self._tso_lock:
            return next(self._tso)

    def advance_tso(self, ts: int) -> None:
        """Fast-forward the TSO past `ts` (the CDC replay sink's
        downstream clock sync: a mirror snapshot at a fresh TSO must see
        every replayed version at or below the source's resolved
        frontier). A no-op when the clock is already ahead."""
        with self._tso_lock:
            self._tso = itertools.count(max(next(self._tso), ts + 1))

    def register_snapshot(self, start_ts: int) -> None:
        """An open transaction pins its snapshot: GC never collects at or
        above the oldest registered start_ts (ref: the reference's
        min-start-ts reporting into PD's safepoint calculation,
        gc_worker.go calcSafePointByMinStartTS)."""
        with self._tso_lock:
            self._active_snapshots[start_ts] = self._active_snapshots.get(start_ts, 0) + 1

    def unregister_snapshot(self, start_ts: int) -> None:
        with self._tso_lock:
            n = self._active_snapshots.get(start_ts, 0) - 1
            if n <= 0:
                self._active_snapshots.pop(start_ts, None)
            else:
                self._active_snapshots[start_ts] = n

    def run_gc(self, safepoint: int | None = None) -> int:
        """MVCC GC pass (ref: gc_worker.go): the effective safepoint is
        clamped strictly below every active transaction — both registered
        snapshots (read-only txns included) and lock holders — so no
        in-flight snapshot loses its read view and no write-conflict check
        loses the tombstone it compares against. Default safepoint = the
        current TSO (keep only the latest committed version per key).
        Returns versions removed."""
        sp = safepoint if safepoint is not None else self.next_ts()
        with self._tso_lock:
            for ts in self._active_snapshots:
                sp = min(sp, ts - 1)
        with self.txn._mu:
            for l in self.txn.locks.values():
                sp = min(sp, l.start_ts - 1)
        self.gc_safepoint = max(getattr(self, "gc_safepoint", -1), sp)
        return self.kv.gc(sp)

    def _bump_write_ver(self):
        # the bump rides the cache's own lock (vet finding: the unlocked
        # `+= 1` could lose an increment between two racing writers, and
        # the TOCTOU guard in _cop_cache_put compares EXACT versions).
        # every cop-cache key embeds the old write version, so entries can
        # never serve stale data — the clear just stops dead weight from
        # crowding live entries out of the LRU window
        with self._cop_lock:
            self._write_ver += 1
            self._cop_cache.clear()

    def _snapshot_write_ver(self) -> int:
        """Locked read of the store write version — the pre-read snapshot
        every cache key embeds."""
        with self._cop_lock:
            return self._write_ver

    def _record_write_flow(self, key: bytes, value: bytes | None, prev_live: bool,
                           ts: int, placement: tuple | None = None):
        """Per-key write flow into the PD heartbeat snapshot (ref: TiKV's
        flow observer feeding pdpb.RegionHeartbeat bytes/keys_written) +
        a replication proposal carrying the change entry: the write rides
        the region's raft-lite log, commits on quorum ack, advances
        follower safe_ts, and feeds any subscribed changefeed."""
        self.pd.flow.record_write(key, 0 if value is None else len(value),
                                  prev_live=prev_live, delete=value is None)
        if placement is None:
            placement = self.cluster.locate_placement(key)
        rid, leader, peers = placement
        self.replication.propose(rid, ts, placement=(leader, peers),
                                 entries=[(key, value)])

    def record_applied_writes(self, items, ts: int | None = None):
        """Batch write flow for appliers that land many keys at once (2PC
        commit, bulk ingest, LOAD DATA): items of (key, value|None,
        prev_live). Called AFTER the kv critical section so the flow
        bookkeeping never extends the reader-blocking window. Each touched
        region gets ONE replication proposal at the batch's commit ts
        (a raft batch-proposal, not per-key entries) carrying exactly its
        own keys' changes — the CDC puller sees the log sharded the way
        the raft log is. `ts` defaults to the store commit watermark for
        legacy callers; batch appliers pass their actual commit_ts so
        events never wear a concurrent commit's timestamp."""
        self.pd.flow.record_writes(
            [(k, 0 if v is None else len(v), prev, v is None) for k, v, prev in items]
        )
        if ts is None:
            ts = self.kv.max_committed()
        values = {k: v for k, v, _prev in items}
        for rid, keys in self.cluster.group_keys_by_region(list(values)).items():
            self.replication.propose(rid, ts,
                                     entries=[(k, values[k]) for k in keys])

    def record_applied_writes_grouped(self, lanes):
        """Group-commit write flow (ISSUE 19): lanes of (applied items,
        commit_ts) from ONE coalesced window, ascending commit ts. One
        flow-stats batch for the whole window, then ONE replication
        proposal per touched region carrying every lane's entries at its
        own commit ts (ReplicaManager.propose_group) — N sessions cost
        one quorum round per region instead of N."""
        from ..util import metrics

        flow_items = []
        per_region: dict[int, list] = {}
        pairs = 0
        for applied, ts in lanes:
            flow_items.extend(
                (k, 0 if v is None else len(v), prev, v is None)
                for k, v, prev in applied
            )
            values = {k: v for k, v, _prev in applied}
            for rid, keys in self.cluster.group_keys_by_region(list(values)).items():
                per_region.setdefault(rid, []).append(
                    (ts, [(k, values[k]) for k in keys])
                )
                pairs += 1
        self.pd.flow.record_writes(flow_items)
        for rid, groups in per_region.items():
            self.replication.propose_group(rid, groups)
        if pairs > len(per_region):
            metrics.COALESCE_GROUP_PROPOSALS_SAVED.inc(pairs - len(per_region))

    def _check_write_quorum(self, keys) -> None:
        """The pre-apply write gate (ROADMAP PR-8 follow-on): every
        region a write touches must hold quorum, else the whole write is
        refused with a typed QuorumLostError (MySQL 9005 at the session
        boundary) BEFORE anything turns durable on the shared KV. One
        cluster-lock acquisition fetches every placement."""
        for rid, placement in self.cluster.placements_of_keys(keys).items():
            self.replication.check_write_quorum(rid, placement=placement)

    # -- write path (ref: table.AddRecord -> memdb -> prewrite/commit) ------
    def put_row(self, table_id: int, handle: int, col_ids: list[int], datums: list[Datum], ts: int):
        key = tablecodec.encode_row_key(table_id, handle)
        val = self._row_encoder.encode(col_ids, datums)
        placement = self.cluster.locate_placement(key)
        self.replication.check_write_quorum(placement[0], placement=placement[1:])
        with self.cdc.guard.writing():
            prev = self.kv.put(key, val, ts)
            self._record_write_flow(key, val, prev, ts, placement=placement)
        self._bump_write_ver()

    def delete_row(self, table_id: int, handle: int, ts: int):
        key = tablecodec.encode_row_key(table_id, handle)
        placement = self.cluster.locate_placement(key)
        self.replication.check_write_quorum(placement[0], placement=placement[1:])
        with self.cdc.guard.writing():
            prev = self.kv.put(key, None, ts)
            self._record_write_flow(key, None, prev, ts, placement=placement)
        self._bump_write_ver()

    def put_index(self, key: bytes, value: bytes, ts: int):
        placement = self.cluster.locate_placement(key)
        self.replication.check_write_quorum(placement[0], placement=placement[1:])
        with self.cdc.guard.writing():
            prev = self.kv.put(key, value, ts)
            self._record_write_flow(key, value, prev, ts, placement=placement)
        self._bump_write_ver()

    def propose_schema_change(self, meta, op: str, query: str) -> int:
        """One committed row-shape DDL -> one schema-change entry riding
        `ReplicaManager.propose` (ISSUE 20: DDL through the feed). The
        key is synthetic (`m_schema_<tid>_<ver>`, never in KV); the ts
        draws INSIDE the CDC WriteGuard so no resolved-ts candidate can
        prove quiescence past an undelivered schema change — exactly the
        row write paths' ordering guarantee. The journal records it
        first: a feed that misses the live delivery (paused, born later,
        puller-drop) re-injects from the journal on its next tick."""
        from ..cdc.schema import encode_schema_key, schema_payload
        import json as _json

        key = encode_schema_key(meta.table_id, meta.schema_version)
        value = _json.dumps(schema_payload(meta, op, query)).encode()
        with self.cdc.guard.writing():
            ts = self.next_ts()
            self.schema_journal.append(ts, meta.table_id, key, value)
            rid = self.cluster.locate_placement(
                tablecodec.table_prefix(meta.table_id))[0]
            self.replication.propose(rid, ts, entries=[(key, value)])
        return ts

    # -- scan/decode with caching -------------------------------------------
    def region_chunk(self, region: Region, ranges: list, dag: DAGRequest, start_ts: int) -> Chunk:
        """Rows of `region` ∩ `ranges` decoded to a columnar chunk.

        Cache key includes the store write version: any write invalidates
        (coarse, but correct; per-region versions later)."""
        scan = dag.scan()
        col_ids = tuple(c.col_id for c in scan.columns)
        rkey = (
            region.region_id,
            region.epoch,
            self._snapshot_write_ver(),
            start_ts,
            scan.table_id,
            col_ids,
            tuple((r.start, r.end) for r in ranges),
        )
        cached = self._chunk_cache.get(rkey)
        if cached is not None:
            return cached
        fts = [c.ft for c in scan.columns]
        fts_by_id = {c.col_id: c.ft for c in scan.columns}
        ch = None
        from ..exec.dag import IndexScan

        if not isinstance(scan, IndexScan):
            ch = self._native_region_chunk(region, ranges, scan, start_ts)
        if ch is None:
            rows = []
            for key, val in self._scan_region_kvs(region, ranges, start_ts):
                row = self._decode_row(key, val, scan, fts_by_id)
                if row is not None:
                    rows.append(row)
            ch = Chunk.from_rows(fts, rows)
        self._chunk_cache[rkey] = ch
        return ch

    def _scan_region_kvs(self, region: Region, ranges: list, start_ts: int):
        """(key, value) pairs of region ∩ ranges at the snapshot — the one
        range-clamping loop both decode paths consume."""
        for rng in ranges:
            start = max(rng.start, region.start_key)
            end = min(rng.end, region.end_key)
            if start >= end:
                continue
            yield from self.kv.scan(start, end, start_ts)

    def _native_region_chunk(self, region: Region, ranges: list, scan, start_ts: int) -> Chunk | None:
        """C++ scan decode (tidb_tpu/native): rowcodec values -> columns in
        one call. None on any unsupported shape or decode error — the
        caller runs the row-at-a-time Python decoder instead."""
        from .. import native

        if not native.available():
            return None
        if any(c.default is not None for c in scan.columns):
            return None  # origin-default fill is python-side only
        values: list[bytes] = []
        handles: list[int] = []
        for rng in ranges:
            start = max(rng.start, region.start_key)
            end = min(rng.end, region.end_key)
            if start >= end:
                continue
            for key, val in self.kv.scan(start, end, start_ts):
                try:
                    _, handle = tablecodec.decode_row_key(key)
                except ValueError:
                    continue
                values.append(val)
                handles.append(handle)
        cols = native.decode_rows_columnar(values, handles, scan.columns)
        if cols is None:
            return None
        from ..util import metrics

        metrics.NATIVE_DECODES.inc()
        return Chunk(cols)

    def _decode_row(self, key: bytes, val: bytes, scan, fts_by_id: dict):
        from ..exec.dag import IndexScan

        if isinstance(scan, IndexScan):
            return self._decode_index_entry(key, scan)
        try:
            _, handle = tablecodec.decode_row_key(key)
        except ValueError:
            return None
        dmap = decode_row_to_datum_map(val, fts_by_id)
        row = []
        for c in scan.columns:
            if c.col_id == -1:  # handle column (_tidb_rowid)
                row.append(Datum.i64(handle))
                continue
            row.append(fill_origin_default(val, c.col_id, c.default, dmap[c.col_id]))
        return row

    def _decode_index_entry(self, key: bytes, scan):
        """Index key `t{tid}_i{iid}{vals...}{handle}` -> one row of the
        IndexScan schema (index cols then handle; ref: indexScanExec
        mpp_exec.go:255 decoding index entries back to datums)."""
        from ..codec.datum_codec import decode_datums

        prefix_len = 1 + 8 + 2 + 8  # 't' + tid + '_i' + iid
        if len(key) <= prefix_len:
            return None
        fts = [c.ft for c in scan.columns]
        try:
            datums = decode_datums(key[prefix_len:], fts)
        except (ValueError, IndexError):
            return None
        if len(datums) != len(scan.columns):
            return None
        return datums

    def _paged_region_chunk(self, region: Region, ranges: list, dag: DAGRequest, start_ts: int, limit: int):
        """Scan at most `limit` rows of region ∩ ranges; returns
        (chunk, resume_ranges | None). The resume cursor is the first
        unscanned key, exactly the reference's lastRange contract
        (ref: cop_handler.go:210-224)."""
        scan = dag.scan()
        fts = [c.ft for c in scan.columns]
        fts_by_id = {c.col_id: c.ft for c in scan.columns}
        rows: list = []
        for ri, rng in enumerate(ranges):
            start = max(rng.start, region.start_key)
            end = min(rng.end, region.end_key)
            if start >= end:
                continue
            for key, val in self.kv.scan(start, end, start_ts):
                if len(rows) >= limit:
                    resume = [KeyRange(key, rng.end)] + list(ranges[ri + 1 :])
                    return Chunk.from_rows(fts, rows), resume
                row = self._decode_row(key, val, scan, fts_by_id)
                if row is not None:
                    rows.append(row)
        return Chunk.from_rows(fts, rows), None

    def region_device_batch(self, region: Region, ranges, dag: DAGRequest, start_ts: int, capacity: int | None = None) -> DeviceBatch:
        ch = self.region_chunk(region, ranges, dag, start_ts)
        cap = capacity or _pow2(max(ch.num_rows(), 1))
        scan = dag.scan()
        bkey = (
            region.region_id,
            region.epoch,
            self._snapshot_write_ver(),
            start_ts,
            scan.table_id,
            tuple(c.col_id for c in scan.columns),
            tuple((r.start, r.end) for r in ranges),
            cap,
        )
        cached = self._batch_cache.get(bkey)
        if cached is not None:
            return cached
        batch = to_device_batch(ch, capacity=cap)
        self._batch_cache[bkey] = batch
        return batch

    _AUX_CACHE_MAX = 16

    def _chunk_token(self, chunk: Chunk) -> int:
        """Monotonic identity for a chunk object. id() is reused after GC —
        a dead build side's cache entry could alias a brand-new chunk at
        the same address; a token handed out once per object never can."""
        tok = getattr(chunk, "_device_token", None)
        if tok is None:
            with self._aux_lock:
                tok = getattr(chunk, "_device_token", None)
                if tok is None:
                    tok = next(self._chunk_tokens)
                    chunk._device_token = tok
        return tok

    def _aux_batch(self, chunk: Chunk) -> DeviceBatch:
        """Broadcast build-side chunk -> DeviceBatch, uploaded once per
        chunk object (all region tasks of a join share the operand).

        Bounded LRU keyed by the chunk token (never-reused identity); the
        entry pins the chunk so the device batch and its source live and
        die together."""
        key = self._chunk_token(chunk)
        with self._aux_lock:
            cached = self._aux_batch_cache.get(key)
            if cached is not None:
                self._aux_batch_cache.pop(key)  # refresh LRU position
                self._aux_batch_cache[key] = cached
                return cached[1]
        batch = to_device_batch(chunk, capacity=_pow2(max(chunk.num_rows(), 1)))
        with self._aux_lock:
            self._aux_batch_cache[key] = (chunk, batch)
            while len(self._aux_batch_cache) > self._AUX_CACHE_MAX:
                self._aux_batch_cache.pop(next(iter(self._aux_batch_cache)))
        return batch

    # -- coprocessor result cache (ref: copr/coprocessor_cache.go) ----------
    _COP_CACHE_MAX = 128

    def _cop_cache_key(self, req: CopRequest, write_ver: int):
        return (
            req.region_id,
            req.region_epoch,
            write_ver,
            req.dag.fingerprint(),
            tuple((r.start, r.end) for r in req.ranges),
            req.small_groups,
        )

    def _cop_cacheable(self, req: CopRequest) -> bool:
        # paging responses carry per-page cursors; aux chunks (join build
        # sides) are statement-local operands with no data version to key on
        return req.paging_size is None and not req.aux_chunks

    def _cop_cache_get(self, req: CopRequest) -> CopResponse | None:
        """Serve a whole region response from the result cache when the
        region's data version — (epoch, store write version) — and the DAG
        fingerprint match (ref: coprocessor_cache.go keying responses by
        region data version). Entries are only CREATED for snapshots that
        already see every committed version (start_ts >= kv.max_version at
        put time), so with the write version unchanged any request at
        start_ts >= the entry's sees byte-identical data; an OLDER snapshot
        might predate a version the entry includes and must miss. A hit
        still records read flow — the region logically served the rows, and
        hiding cached traffic from the PD would blind the hot-region
        scheduler to exactly the hottest (most re-read) regions."""
        if not self._cop_cacheable(req):
            return None
        with self._cop_lock:
            key = self._cop_cache_key(req, self._write_ver)
            ent = self._cop_cache.get(key)
            if ent is None:
                return None
            resp, entry_ts, flow = ent
            if req.start_ts < entry_ts:
                return None
            self._cop_cache.pop(key)  # refresh LRU position
            self._cop_cache[key] = ent
        from ..topsql import record_cop_cache_hit
        from ..util import metrics

        metrics.COP_CACHE_HITS.inc()
        record_cop_cache_hit()  # zero device time by construction: no launch ran
        self.pd.flow.record_read(req.region_id, flow[0], flow[1])
        summaries = [replace(s, cache_hit=True, time_compile_ns=0) for s in resp.exec_summaries]
        return CopResponse(chunk=resp.chunk, exec_summaries=summaries)

    def _cop_cache_put(self, req: CopRequest, resp: CopResponse,
                       flow: tuple = (0, 0), write_ver: int | None = None) -> None:
        """flow = (decoded bytes, rows) of the region read — replayed into
        the PD heartbeat on every hit so flow stats see cached traffic.

        write_ver is the caller's snapshot of _write_ver taken BEFORE it
        read the region: the insert is refused under _cop_lock if a write
        landed since (version moved, or a half-applied commit already
        raised kv.max_version) — otherwise a pre-write response could be
        filed under the post-write key and serve stale rows."""
        if (
            not self._cop_cacheable(req)
            or resp.chunk is None
            or resp.region_error is not None
            or resp.other_error is not None
            or resp.last_range is not None
        ):
            return
        with self._cop_lock:
            ver = self._write_ver if write_ver is None else write_ver
            key = self._cop_cache_key(req, ver)
            if ver != self._write_ver:
                return  # a write raced the read: the response may predate it
            # a snapshot that predates some committed version would cache a
            # view NEWER snapshots must not inherit (MVCC: same write_ver,
            # different visibility) — only the all-seeing snapshot caches
            # (max_committed takes kv.lock INSIDE _cop_lock; that order is
            # one-way — nothing holding kv.lock ever takes _cop_lock)
            if req.start_ts < self.kv.max_committed():
                return
            self._cop_cache[key] = (resp, req.start_ts, flow)
            while len(self._cop_cache) > self._COP_CACHE_MAX:
                self._cop_cache.pop(next(iter(self._cop_cache)))

    def _count_replica_read(self, req: CopRequest) -> None:
        """tidb_tpu_replica_read_total{target=} — one count per routed
        request (req.peer_store >= 0), marker-deduped because a batch lane
        can be re-served by the single-request path (singleton groups,
        overflow fall-outs) after the batch already admitted it. Also
        feeds the closest-replica router's per-store read load."""
        if req.peer_store < 0 or getattr(req, "_replica_counted", False):
            return
        req._replica_counted = True
        from ..util import metrics

        target = ("follower"
                  if req.peer_store != self.cluster.leader_of(req.region_id)
                  else "leader")
        metrics.REPLICA_READS.labels(target).inc()
        self.replication.note_read(req.peer_store)

    def _region_fault(self, region_id: int, peer_store: int = -1,
                      replica_read: bool = False, start_ts: int = 0):
        """The typed fault ladder for the peer a request was routed to
        (`peer_store`; -1 = whoever leads at serve time): the set_down
        switch and the three per-store-armable failpoints
        (`store/unreachable`, `store/not-leader`, `store/server-busy`) —
        each returns a typed RegionError the dispatch client classifies
        onto its own backoff budget — then the replication checks: a
        non-leader peer answers NotLeader WITH the current leader as the
        hint unless the request is a replica read, and a replica read is
        gated on the peer's applied watermark (`safe_ts >= start_ts`,
        else DataIsNotReady — ref: TiKV replica read's resolved-ts
        check). None = this peer serves."""
        from ..util import failpoint
        from .errors import DataIsNotReady, NotLeader, ServerIsBusy, StoreUnavailable

        leader = self.cluster.leader_of(region_id)
        sid = peer_store if peer_store >= 0 else leader
        if self.store_down(sid):
            return StoreUnavailable.make(sid)
        if _fault_matches(failpoint.eval("store/unreachable"), sid):
            return StoreUnavailable.make(sid)
        if _fault_matches(failpoint.eval("store/not-leader"), sid):
            # injected leadership wobble: the hint is whatever the cluster
            # currently believes — pointing at the armed store itself
            # means "election in flight", no usable hint
            return NotLeader.make(region_id, sid, leader)
        busy = failpoint.eval("store/server-busy")
        if _fault_matches(busy, sid):
            ms = busy.get("backoff_ms", 0) if isinstance(busy, dict) else 0
            return ServerIsBusy.make(sid, ms)
        if sid != leader:
            if not replica_read:
                return NotLeader.make(region_id, sid, leader)
            safe = self.replication.safe_ts(region_id, sid)
            if safe < start_ts:
                return DataIsNotReady.make(region_id, sid, safe)
        return None

    # -- the serialized endpoint (the sidecar seam) -------------------------
    def coprocessor_bytes(self, req_bytes: bytes) -> bytes:
        """Serve one cop request from wire bytes to wire bytes — the
        process-boundary shape of the coprocessor endpoint (ref:
        unistore/rpc.go:260 CmdCop dispatch over serialized protos). A
        sidecar server loop is exactly `recv -> coprocessor_bytes -> send`."""
        from ..codec.wire import decode_cop_request, encode_cop_response

        try:
            req = decode_cop_request(req_bytes)
        except Exception as exc:  # malformed bytes must not kill the server
            return encode_cop_response(CopResponse(other_error=f"bad request: {exc}"))
        return encode_cop_response(self.coprocessor(req))

    # -- the coprocessor endpoint -------------------------------------------
    def coprocessor(self, req: CopRequest, group_capacity: int = DEFAULT_GROUP_CAPACITY) -> CopResponse:
        from ..util import failpoint, metrics

        metrics.COP_REQUESTS.inc()
        t_start = time.monotonic()
        resp = self._coprocessor(req, group_capacity)
        metrics.COP_DURATION.observe(time.monotonic() - t_start)
        if resp.region_error is not None or resp.other_error is not None:
            metrics.COP_ERRORS.inc()
        return resp

    def _coprocessor(self, req: CopRequest, group_capacity: int) -> CopResponse:
        from ..exec.dag import executor_walk
        from ..util import failpoint, metrics, tracing

        if failpoint.eval("cop-region-error"):
            # fault injection at the RPC seam (ref: unistore/rpc.go:265-271)
            return CopResponse(region_error="injected epoch_not_match")
        if failpoint.eval("cop-other-error"):
            return CopResponse(other_error="injected coprocessor error")
        region = self.cluster.region_by_id(req.region_id)
        if region is None:
            return CopResponse(region_error=f"region {req.region_id} not found")
        err = self._region_fault(req.region_id, req.peer_store,
                                 req.replica_read, req.start_ts)
        if err is not None:
            return CopResponse(region_error=str(err))
        if req.region_epoch != region.epoch:
            return CopResponse(region_error=f"epoch_not_match: have {region.epoch}, got {req.region_epoch}")
        self._count_replica_read(req)
        cached = self._cop_cache_get(req)
        if cached is not None:
            return cached
        ver = self._snapshot_write_ver()  # pre-read snapshot: gates the cache insert
        t0 = time.monotonic_ns()
        last_range = None
        page = None
        in_bytes, in_rows = 0, 0
        info = {"cache_hit": False, "compile_ns": 0}
        try:
            with tracing.span("cop.decode", region_id=req.region_id) as dsp:
                if req.paging_size is not None:
                    from ..exec.dag import Aggregation as _Agg, Limit as _Limit, Sort as _Sort, TopN as _TopN

                    if req.paging_size <= 0:
                        return CopResponse(other_error=f"invalid paging_size {req.paging_size}")
                    if any(isinstance(e, (_Agg, _TopN, _Limit, _Sort)) for e in executor_walk(req.dag.executors)):
                        # per-page agg/top-k/limit results are not mergeable by
                        # concatenation — row-local DAGs only (scan/sel/proj/join)
                        return CopResponse(other_error="paging requires a row-local DAG (no aggregation/TopN/Limit)")
                    page, last_range = self._paged_region_chunk(
                        region, req.ranges, req.dag, req.start_ts, req.paging_size
                    )
                    in_bytes, in_rows = page.nbytes(), page.num_rows()
                    batch = to_device_batch(page, capacity=_pow2(max(page.num_rows(), 1)))
                else:
                    rc = self.region_chunk(region, req.ranges, req.dag, req.start_ts)
                    in_bytes, in_rows = rc.nbytes(), rc.num_rows()
                    batch = self.region_device_batch(region, req.ranges, req.dag, req.start_ts)
                # read flow into the PD heartbeat (ref: TiKV flow observer
                # -> pdpb.RegionHeartbeat bytes/keys_read)
                self.pd.flow.record_read(region.region_id, in_bytes, in_rows)
                if dsp is not None:
                    dsp.set("bytes_to_device", in_bytes)
            batches = [batch] + [self._aux_batch(c) for c in req.aux_chunks]
            with tracing.span("cop.execute", region_id=req.region_id) as xsp:
                chunk, ex_rows, info = drive_program_info(self.programs, req.dag, batches, group_capacity,
                                                          small_groups=req.small_groups)
                if xsp is not None:
                    xsp.set("rows", chunk.num_rows())
                    xsp.set("cache_hit", info["cache_hit"])
        except (OverflowRetryError, NotImplementedError):
            # degenerate fan-out OR an op the device cannot express (JSON,
            # regexp, host-only funcs reaching a pushed executor): fall back
            # to the row-at-a-time oracle (SURVEY §7 / exec/builder.py)
            from ..util import metrics as _m

            _m.COP_FALLBACKS.inc()
            try:
                with tracing.span("cop.oracle_fallback", region_id=req.region_id):
                    region_chunk = page if page is not None else self.region_chunk(region, req.ranges, req.dag, req.start_ts)
                    rows = run_dag_reference(req.dag, [region_chunk] + list(req.aux_chunks))
                    chunk = Chunk.from_rows(req.dag.output_fts(), rows)
                # fallback summaries: aligned with the device path's
                # per-executor walk (build pipelines included); counts are
                # the final row count
                ex_rows = [chunk.num_rows()] * len(executor_walk(req.dag.executors))
                info = {"cache_hit": False, "compile_ns": 0}
            except (RuntimeError, TypeError, NotImplementedError, ValueError) as exc:
                if failpoint.eval("cop-debug-raise"):
                    raise  # loud-failure gate (VERDICT r2 weak #10)
                return CopResponse(other_error=f"oracle fallback failed: {exc}")
        except (RuntimeError, TypeError) as exc:
            if failpoint.eval("cop-debug-raise"):
                raise  # surface kernel bugs with a stack when armed
            return CopResponse(other_error=str(exc))
        elapsed = time.monotonic_ns() - t0
        from ..topsql import record_device

        record_device(elapsed, compile_ns=info["compile_ns"], bytes_to_device=in_bytes)
        # per-executor produced-row counts are real (measured inside the
        # fused program); the time is the whole fused program's — XLA fuses
        # the pipeline into one kernel, so per-operator time does not exist
        # (ref: cop_handler.go:518-531 fills per-executor summaries).
        # compile/cache attribution is likewise per-program: every summary
        # of the task carries it; bytes attribute to the data movers (the
        # scan's decoded region bytes in, the final executor's result out).
        walk = executor_walk(req.dag.executors)
        out_bytes = chunk.nbytes()
        summaries = [
            ExecSummary(
                time_processed_ns=elapsed, num_produced_rows=r,
                time_compile_ns=info["compile_ns"], cache_hit=info["cache_hit"],
                num_bytes=in_bytes if i == 0 else (out_bytes if i == len(ex_rows) - 1 else 0),
            )
            for i, r in enumerate(ex_rows)
        ]
        _apply_radix_attribution(summaries, walk, info)
        for ex, r in zip(walk, ex_rows):
            metrics.COP_EXECUTOR_ROWS.labels(type(ex).__name__.lower()).inc(r)
        resp = CopResponse(chunk=chunk, exec_summaries=summaries, last_range=last_range)
        self._cop_cache_put(req, resp, flow=(in_bytes, in_rows), write_ver=ver)
        return resp

    # -- the batched coprocessor endpoint -----------------------------------
    def batch_coprocessor(self, reqs: list[CopRequest], group_capacity: int = DEFAULT_GROUP_CAPACITY) -> list[CopResponse]:
        """Serve a store's worth of region tasks from ONE vmapped XLA
        launch per (DAG fingerprint, snapshot) group (ref:
        copr/batch_coprocessor.go — all regions of a TiFlash store travel
        in one request). Every region's rows decode as usual, pad to the
        group's shared power-of-two capacity, stack along a leading region
        axis, and execute as a single vmapped program; per-region partial
        results slice back out, so the root-side merge is unchanged.

        Per-request validation happens UP FRONT: a stale epoch, missing
        region or cache hit answers immediately and falls out of the batch
        — the rest of the batch still executes. Paging requests and armed
        cop failpoints route through the single-request path (resume
        cursors and injection sites live there). Responses come back in
        request order."""
        from ..util import failpoint, metrics

        responses: list = [None] * len(reqs)
        groups: dict = {}
        for i, req in enumerate(reqs):
            if (
                req.paging_size is not None
                or failpoint.is_armed("cop-region-error")
                or failpoint.is_armed("cop-other-error")
            ):
                responses[i] = self.coprocessor(req, group_capacity)
                continue
            region = self.cluster.region_by_id(req.region_id)
            if region is None:
                metrics.COP_REQUESTS.inc()
                metrics.COP_ERRORS.inc()
                responses[i] = CopResponse(region_error=f"region {req.region_id} not found")
                continue
            err = self._region_fault(req.region_id, req.peer_store,
                                     req.replica_read, req.start_ts)
            if err is not None:
                # typed store faults fall out of the batch exactly like a
                # stale epoch: the lane answers immediately, the rest of
                # the batch stands (region errors survive the batch frame
                # as strings, same as the single-request seam)
                metrics.COP_REQUESTS.inc()
                metrics.COP_ERRORS.inc()
                responses[i] = CopResponse(region_error=str(err))
                continue
            if req.region_epoch != region.epoch:
                metrics.COP_REQUESTS.inc()
                metrics.COP_ERRORS.inc()
                responses[i] = CopResponse(
                    region_error=f"epoch_not_match: have {region.epoch}, got {req.region_epoch}"
                )
                continue
            self._count_replica_read(req)
            cached = self._cop_cache_get(req)
            if cached is not None:
                metrics.COP_REQUESTS.inc()
                responses[i] = cached
                continue
            key = (
                req.dag.fingerprint(),
                req.start_ts,
                req.small_groups,
                bool(req.mesh),
                tuple(self._chunk_token(c) for c in req.aux_chunks),
            )
            groups.setdefault(key, []).append((i, req, region))
        for entries in groups.values():
            if len(entries) == 1:  # nothing to amortize: the plain path
                i, req, _region = entries[0]
                responses[i] = self.coprocessor(req, group_capacity)
                continue
            if entries[0][1].mesh and self._run_cop_mesh(entries, responses, group_capacity):
                continue  # merged on device; else degrade to the vmap tier
            self._run_cop_batch(entries, responses, group_capacity)
        return responses

    # data-size floor for the mesh tier on ACTUAL decoded rows (the
    # client's estimate only gated the attempt): below it the vmapped
    # batch tier serves — a shard_map launch is not worth its compile
    # for a handful of rows. Env-tunable for benches.
    MESH_MIN_GROUP_ROWS = int(os.environ.get("TIDB_TPU_MESH_MIN_ROWS", "0"))

    def _run_cop_mesh(self, entries, responses, group_capacity: int) -> bool:
        """ONE shard_map launch for a same-DAG group of region tasks (the
        dispatch planner's MESH tier): decode every lane, stack to the
        group's max pow2 capacity, pad the region axis onto the device
        mesh, and merge the per-region partial states ON DEVICE — psum
        over the region axis for additive aggregate states, pmin/pmax for
        extremes, a merge-mode re-group for GROUP BY tables, a re-top-k
        for TopN. The group's first lane answers with the ONE merged
        chunk; the rest answer empty with the same mesh_merged marker, so
        the root-side merge consumes a single state per store.

        Returns True when every lane was answered; False degrades the
        whole group to the vmapped batch tier (ineligible DAG, too few
        rows, overflow, or any trace/launch failure) — which owns the
        per-lane capacity ladder and the oracle fallback."""
        import jax

        from ..distsql.planner import mesh_merge_kind
        from ..exec.dag import executor_walk
        from ..exec.executor import drive_mesh_program_info
        from ..util import metrics, tracing

        req0 = entries[0][1]
        dag = req0.dag
        kind = mesh_merge_kind(dag)
        if kind is None:
            return False
        t0 = time.monotonic_ns()
        try:
            with tracing.span("cop.mesh_decode", regions=len(entries)) as dsp:
                chunks = [
                    self.region_chunk(region, req.ranges, dag, req.start_ts)
                    for (_i, req, region) in entries
                ]
                if dsp is not None:
                    dsp.set("bytes_to_device", sum(ch.nbytes() for ch in chunks))
                aux_batches = [self._aux_batch(c) for c in req0.aux_chunks]
        except Exception:  # noqa: BLE001 — degrade, never lose the group
            return False
        floor = max(self.MESH_MIN_GROUP_ROWS, req0.mesh_min_rows)
        if sum(ch.num_rows() for ch in chunks) < floor:
            # data-size tier rule: small groups ride vmap (counted like
            # every other mesh decline so dashboards can tell "declined"
            # from "never attempted")
            metrics.MESH_COP_FALLBACKS.inc()
            return False
        caps = [_pow2(max(ch.num_rows(), 1)) for ch in chunks]
        cap = max(caps)
        # skew guard (#review): every lane pads to the group MAX capacity,
        # so one post-split giant among small regions would inflate the
        # stacked footprint toward lanes*max — the exact hazard the vmap
        # tier's pow2 BUCKETING exists for. When padding would waste >4x
        # the honest per-lane footprint, degrade to the bucketed tier.
        if cap * len(caps) > 4 * sum(caps):
            metrics.MESH_COP_FALLBACKS.inc()
            return False
        n_devs = len(jax.devices())
        D = min(n_devs, len(chunks))
        R_pad = -(-len(chunks) // D) * D  # empty lanes pad the region axis
        fts = chunks[0].field_types()
        lanes = list(chunks) + [Chunk.empty(fts) for _ in range(R_pad - len(chunks))]
        try:
            with tracing.span("cop.mesh_execute", regions=len(entries),
                              devices=D, kind=kind) as xsp:
                stacked = to_stacked_device_batch(lanes, cap)
                merged, lane_counts, info = drive_mesh_program_info(
                    self.programs, dag, stacked, aux_batches, group_capacity,
                    kind, D, small_groups=req0.small_groups,
                )
                if xsp is not None:
                    xsp.set("cache_hit", info["cache_hit"])
        except Exception:  # noqa: BLE001 — degrade, never lose the group
            metrics.MESH_COP_FALLBACKS.inc()
            return False
        if merged is None:
            # global overflow flag: the vmapped tier's PER-LANE ladder
            # isolates the overflowing region instead
            metrics.MESH_COP_FALLBACKS.inc()
            return False
        elapsed = time.monotonic_ns() - t0
        from ..topsql import record_device, split_by_rows

        # one launch served every lane: attribution splits by each lane's
        # decoded rows (not an equal share — a 10k-row lane did the work a
        # 10-row lane did not), and the shares sum EXACTLY to the launch
        # total so EXPLAIN/Top SQL conservation holds
        shares = split_by_rows(elapsed, [ch.num_rows() for ch in chunks])
        record_device(elapsed, compile_ns=info["compile_ns"],
                      bytes_to_device=sum(ch.nbytes() for ch in chunks))
        walk = executor_walk(dag.executors)
        out_fts = merged.field_types()
        metrics.MESH_COP_BATCHES.inc()
        for k, (i, req, region) in enumerate(entries):
            metrics.MESH_COP_LANES.inc()
            # the first lane carries the one merged state; the rest answer
            # empty — concatenation at root sees exactly one row block per
            # store, the "no per-region host merge" contract
            out_chunk = merged if k == 0 else Chunk.empty(out_fts)
            summaries = self._lane_attribution(
                region, chunks[k], out_chunk.nbytes() if k == 0 else 0,
                lane_counts[k], shares[k],
                compile_ns=info["compile_ns"] if k == 0 else 0,
                cache_hit=info["cache_hit"] if k == 0 else True, walk=walk,
                # the carrier lane owns the merged result — it carries the
                # group-total join_radix attribution too
                radix_info=info if k == 0 else None,
            )
            # NOT cop-cached: the merged state covers the whole group, not
            # one region's data version — a later request with a different
            # lane set must not inherit it
            responses[i] = CopResponse(
                chunk=out_chunk, exec_summaries=summaries, batched=1,
                mesh_merged=len(entries),
            )
        return True

    def _lane_attribution(self, region, in_chunk, out_bytes: int, counts,
                          share: int, compile_ns: int, cache_hit: bool,
                          walk, radix_info=None) -> list:
        """Shared per-lane attribution for the vmapped-bucket and mesh
        launch loops: PD read flow, cop metrics, and the ExecSummary list
        (the fused program's time shared across the lane's executors;
        bytes attribute to the data movers — scan in, final executor
        out). Keeping ONE copy means EXPLAIN ANALYZE / flow accounting
        changes cannot drift between the two batched tiers."""
        from ..util import metrics

        self.pd.flow.record_read(region.region_id, in_chunk.nbytes(),
                                 in_chunk.num_rows())
        metrics.COP_REQUESTS.inc()
        metrics.COP_DURATION.observe(share / 1e9)
        in_b = in_chunk.nbytes()
        summaries = [
            ExecSummary(
                time_processed_ns=share, num_produced_rows=r,
                time_compile_ns=compile_ns, cache_hit=cache_hit,
                num_bytes=in_b if j == 0 else (out_bytes if j == len(counts) - 1 else 0),
            )
            for j, r in enumerate(counts)
        ]
        if radix_info:
            _apply_radix_attribution(summaries, walk, radix_info)
        for ex, r in zip(walk, counts):
            metrics.COP_EXECUTOR_ROWS.labels(type(ex).__name__.lower()).inc(r)
        return summaries

    def _run_cop_batch(self, entries, responses, group_capacity: int) -> None:
        """Decode a same-DAG group of region tasks, bucket by shared pow2
        capacity, and launch one vmapped program per bucket — the
        documented (store, DAG-fingerprint, capacity) launch unit. Without
        the bucketing, one skewed region would pad EVERY lane to its size
        and a 16-region batch could cost ~16x the per-region footprint.
        Lanes whose overflow flag fired — and a whole bucket on any
        batched-trace failure — degrade to the single-request path, which
        owns the capacity ladder and the oracle fallback."""
        from ..util import tracing

        req0 = entries[0][1]
        dag = req0.dag
        ver = self._snapshot_write_ver()  # pre-read snapshot: gates the cache inserts
        try:
            with tracing.span("cop.batch_decode", regions=len(entries)) as dsp:
                chunks = [
                    self.region_chunk(region, req.ranges, dag, req.start_ts)
                    for (_i, req, region) in entries
                ]
                if dsp is not None:
                    dsp.set("bytes_to_device", sum(ch.nbytes() for ch in chunks))
                aux_batches = [self._aux_batch(c) for c in req0.aux_chunks]
        except Exception:  # noqa: BLE001 — degrade, never lose the batch
            for i, req, _region in entries:
                responses[i] = self.coprocessor(req, group_capacity)
            return
        buckets: dict[int, list] = {}
        for k, ch in enumerate(chunks):
            buckets.setdefault(_pow2(max(ch.num_rows(), 1)), []).append(k)
        batch_id = 0
        for cap, idxs in buckets.items():
            if len(idxs) == 1:  # nothing to amortize at this capacity
                i, req, _region = entries[idxs[0]]
                responses[i] = self.coprocessor(req, group_capacity)
                continue
            batch_id += 1
            self._launch_cop_bucket(
                [entries[k] for k in idxs], [chunks[k] for k in idxs], cap,
                aux_batches, responses, group_capacity, ver, batch_id,
            )

    def _launch_cop_bucket(self, entries, chunks, cap: int, aux_batches,
                           responses, group_capacity: int, write_ver: int,
                           batch_id: int) -> None:
        """ONE vmapped launch for a capacity bucket of decoded regions."""
        from ..exec.dag import executor_walk
        from ..util import metrics, tracing

        req0 = entries[0][1]
        dag = req0.dag
        # per-bucket clock: a later bucket's lanes must not be billed for
        # earlier buckets' launches (decode is cached and near-free here)
        t0 = time.monotonic_ns()
        try:
            with tracing.span("cop.batch_execute", regions=len(entries),
                              capacity=cap) as xsp:
                # pow2 lane axis: vmap_batch rides the ProgramCache key,
                # so an unpadded lane count would compile a fresh program
                # per batch size — coalesced windows (ISSUE 19) arrive at
                # every size. Empty pad lanes cost rows=0 decode, same as
                # the mesh tier's region-axis padding.
                B_pad = _pow2(len(chunks))
                lanes = list(chunks)
                if B_pad > len(lanes):
                    fts = chunks[0].field_types()
                    lanes += [Chunk.empty(fts) for _ in range(B_pad - len(lanes))]
                stacked = to_stacked_device_batch(lanes, cap)
                per_region, info = drive_batched_program_info(
                    self.programs, dag, stacked, aux_batches, group_capacity,
                    small_groups=req0.small_groups,
                )
                if xsp is not None:
                    xsp.set("cache_hit", info["cache_hit"])
        except Exception:  # noqa: BLE001 — degrade, never lose the bucket
            # oracle-only ops, CI non-ASCII routing, vmap-ineligible shapes:
            # the single path reproduces the error handling contract
            # (other_error / oracle fallback / cop-debug-raise) per region
            for i, req, _region in entries:
                responses[i] = self.coprocessor(req, group_capacity)
            return
        elapsed = time.monotonic_ns() - t0
        from ..topsql import record_device, split_by_rows

        # per-lane attribution by decoded rows (exact: shares sum to the
        # launch total); overflow fall-out lanes keep their share here —
        # the launch still spent it — and bill their retry separately
        shares = split_by_rows(elapsed, [ch.num_rows() for ch in chunks])
        record_device(elapsed, compile_ns=info["compile_ns"],
                      bytes_to_device=sum(ch.nbytes() for ch in chunks))
        walk = executor_walk(dag.executors)
        metrics.BATCH_COP_BATCHES.inc()
        served = 0
        for lane, ((i, req, region), ch, res) in enumerate(zip(entries, chunks, per_region)):
            if res is None:
                # this lane's group/join/topn capacity overflowed: only it
                # rides the single-request retry ladder
                responses[i] = self.coprocessor(req, group_capacity)
                continue
            chunk, ex_rows = res
            metrics.BATCH_COP_REGIONS.inc()
            # read flow ONLY for lanes the batch actually served — fall-out
            # lanes (and whole-bucket degrades) record theirs inside the
            # single path, so the PD never sees a region's read twice.
            # compile time belongs to the ONE shared program: the first lane
            # carries it, the rest are cache hits by construction
            lane_info = info
            if info.get("radix"):
                # each lane's summaries carry its OWN escape count (the
                # batch total would multiply across EXPLAIN's summary sum)
                by_lane = info["radix"].get("escapes_by_lane") or []
                lane_info = {"radix": dict(
                    info["radix"],
                    escapes=by_lane[lane] if lane < len(by_lane) else 0,
                )}
            summaries = self._lane_attribution(
                region, ch, chunk.nbytes(), ex_rows, shares[lane],
                compile_ns=info["compile_ns"] if served == 0 else 0,
                cache_hit=info["cache_hit"] if served == 0 else True, walk=walk,
                radix_info=lane_info,
            )
            served += 1
            resp = CopResponse(chunk=chunk, exec_summaries=summaries, batched=batch_id)
            self._cop_cache_put(req, resp, flow=(ch.nbytes(), ch.num_rows()), write_ver=write_ver)
            responses[i] = resp
        if served > 1:
            metrics.BATCH_COP_LAUNCHES_SAVED.inc(served - 1)

    def batch_coprocessor_bytes(self, req_bytes: bytes) -> bytes:
        """The sidecar seam of the batched endpoint: one frame of N cop
        requests in, one frame of N responses out (ref: the BatchCommands /
        BatchCop stream framing over serialized protos)."""
        from ..codec.wire import decode_batch_cop_request, encode_batch_cop_response

        try:
            reqs = decode_batch_cop_request(req_bytes)
        except Exception as exc:  # malformed bytes must not kill the server
            return encode_batch_cop_response([CopResponse(other_error=f"bad batch request: {exc}")])
        return encode_batch_cop_response(self.batch_coprocessor(reqs))
