"""Range-sharded regions (ref: unistore cluster.go:45 Cluster, mockstore
region splitting).

Regions are the unit of data parallelism: the distsql layer splits a scan
into per-region tasks (ref: copr/coprocessor.go:331 buildCopTasks) and the
mesh layer maps regions onto TPU devices (SURVEY.md §2.5). Epochs support
the region-error/retry path: a split bumps the epoch, in-flight tasks with
the stale epoch get EpochNotMatch and re-split, mirroring
copr/coprocessor.go:1424 handleCopResponse.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field

KEY_MAX = b"\xff" * 32


@dataclass
class Region:
    region_id: int
    start_key: bytes
    end_key: bytes
    epoch: int = 1

    def contains(self, key: bytes) -> bool:
        return self.start_key <= key < (self.end_key or KEY_MAX)


class Cluster:
    """All regions, sorted by start key, covering [b'', KEY_MAX).

    Also plays the mock PD: regions are assigned to stores (the TPU-chip
    analog of TiKV/TiFlash stores), `scatter()` rebalances round-robin
    (ref: PD scatter; unistore/pd.go + cluster.go), and the store-global
    TSO lives on TPUStore."""

    def __init__(self, n_stores: int = 1):
        self._regions: list[Region] = [Region(1, b"", KEY_MAX)]
        self._next_id = 2
        self.n_stores = max(n_stores, 1)
        self._store_of: dict[int, int] = {1: 0}

    def set_stores(self, n: int):
        self.n_stores = max(n, 1)
        self.scatter()

    def store_of(self, region_id: int) -> int:
        return self._store_of.get(region_id, region_id % self.n_stores)

    def scatter(self):
        """Round-robin region->store placement (ref: PD scatter-region)."""
        for i, r in enumerate(self._regions):
            self._store_of[r.region_id] = i % self.n_stores

    def regions(self) -> list[Region]:
        return list(self._regions)

    def region_by_id(self, rid: int) -> Region | None:
        for r in self._regions:
            if r.region_id == rid:
                return r
        return None

    def split(self, key: bytes) -> Region:
        """Split the region containing `key` at `key`; bumps both epochs
        (ref: mockstore SplitKeys)."""
        i = self._locate(key)
        r = self._regions[i]
        if r.start_key == key:
            return r
        new = Region(self._next_id, key, r.end_key, epoch=r.epoch + 1)
        self._next_id += 1
        r.end_key = key
        r.epoch += 1
        self._regions.insert(i + 1, new)
        self._store_of[new.region_id] = new.region_id % self.n_stores
        return new

    def split_n(self, start: bytes, end: bytes, n: int, keyfn):
        """Split [start, end) into n regions using keyfn(i) boundaries."""
        for i in range(1, n):
            self.split(keyfn(i))

    def _locate(self, key: bytes) -> int:
        starts = [r.start_key for r in self._regions]
        i = bisect.bisect_right(starts, key) - 1
        return max(i, 0)

    def locate(self, key: bytes) -> Region:
        return self._regions[self._locate(key)]

    def regions_in_range(self, start: bytes, end: bytes) -> list[Region]:
        out = []
        for r in self._regions:
            if (r.end_key or KEY_MAX) <= start:
                continue
            if r.start_key >= end:
                break
            out.append(r)
        return out
