"""Range-sharded regions (ref: unistore cluster.go:45 Cluster, mockstore
region splitting).

Regions are the unit of data parallelism: the distsql layer splits a scan
into per-region tasks (ref: copr/coprocessor.go:331 buildCopTasks) and the
mesh layer maps regions onto TPU devices (SURVEY.md §2.5). Epochs support
the region-error/retry path: a split bumps the epoch, in-flight tasks with
the stale epoch get EpochNotMatch and re-split, mirroring
copr/coprocessor.go:1424 handleCopResponse. Merges (PR 3) bump the
surviving epoch and delete the absorbed region, so stale tasks surface
either EpochNotMatch or region-not-found — both re-split cleanly.

Placement (region -> store) lives in an authoritative map owned by the
placement driver (`tidb_tpu/pd`): a split child inherits its parent's
store (peers stay put, like TiKV), and a lookup miss is routed through
`PlacementDriver.place_region()` — a recorded least-loaded decision, not
the seed's silent `region_id % n_stores` guess. All cluster state is
lock-protected: the PD tick mutates topology from a background Timer
thread while cop dispatch reads it.

Since ISSUE 8 every region also carries a PEER SET (ref: metapb.Region's
peers — one leader + up to `max_replicas - 1` followers): `_store_of`
remains the LEADER view (back-compat: `store_of == leader_of`), `_peers`
holds the full set, and every placement decision — bootstrap, scatter,
split inheritance, miss placement, moves — routes through ONE shared
helper (`_assign_locked`/`_inherit_locked`) so leader map and peer sets
can never drift apart. `transfer_leader` moves leadership WITHIN the peer
set without an epoch bump (raft leadership is not a topology change;
in-flight tasks get NotLeader with a usable hint instead of a re-split).
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass, field

KEY_MAX = b"\xff" * 32


@dataclass
class Region:
    region_id: int
    start_key: bytes
    end_key: bytes
    epoch: int = 1

    def contains(self, key: bytes) -> bool:
        return self.start_key <= key < (self.end_key or KEY_MAX)


class Cluster:
    """All regions, sorted by start key, covering [b'', KEY_MAX).

    Region->store placement (stores are the TPU-chip analog of
    TiKV/TiFlash stores) is authoritative: `scatter()` is the bootstrap
    round-robin (ref: PD scatter-region), after which the PD's
    schedulers own every change via `set_store`/`split`/`merge`."""

    def __init__(self, n_stores: int = 1, max_replicas: int = 3):
        self._regions: list[Region] = [Region(1, b"", KEY_MAX)]  # guarded_by: _mu
        self._next_id = 2  # guarded_by: _mu
        self.n_stores = max(n_stores, 1)
        self.max_replicas = max(max_replicas, 1)  # replication.max_replicas
        self._store_of: dict[int, int] = {}  # LEADER view; guarded_by: _mu
        self._peers: dict[int, list[int]] = {}  # full peer sets; guarded_by: _mu
        self._mu = threading.RLock()
        self.pd = None  # PlacementDriver; owns placement misses when attached
        self.replica = None  # ReplicaManager; tracks per-peer safe_ts
        self.cdc = None  # ChangefeedHub; resolved-ts watermarks follow
        # splits/merges the same way flow stats and replica watermarks do
        with self._mu:
            self._assign_locked(1, 0)

    def set_stores(self, n: int):
        with self._mu:
            self.n_stores = max(n, 1)
        self.scatter()

    # -- the ONE placement primitive -----------------------------------------
    def _replica_count(self) -> int:  # requires: _mu
        return min(self.max_replicas, self.n_stores)

    def _assign_locked(self, region_id: int, leader: int) -> None:  # requires: _mu
        """THE shared placement helper: record `leader` and derive the
        peer set (leader + the next replica-count-1 stores round-robin,
        the scatter-time peer layout). Bootstrap (`__init__`), `scatter`,
        miss placement and moves all route through here so the leader map
        and the peer sets cannot drift apart (ISSUE 8 satellite: the seed
        hard-coded `region->store` in three places)."""
        leader = leader % self.n_stores
        self._store_of[region_id] = leader
        r = self._replica_count()
        self._peers[region_id] = [(leader + k) % self.n_stores for k in range(r)]
        if self.replica is not None:
            self.replica.on_assign(region_id, self._peers[region_id], leader)

    def _inherit_locked(self, parent_id: int, child_id: int) -> None:  # requires: _mu
        """Split inheritance: the child keeps the parent's leader AND peer
        set verbatim — peers stay put on a split; rebalancing is a
        separate PD decision."""
        self._store_of[child_id] = self._store_of.get(parent_id, 0)
        self._peers[child_id] = list(self._peers.get(
            parent_id, [self._store_of.get(parent_id, 0)]))

    def store_of(self, region_id: int) -> int:
        """Authoritative placement lookup — the LEADER view (back-compat
        alias of `leader_of`). A miss is NOT answered with a modulo
        guess: it routes through the PD (recorded least-loaded placement)
        so every subsequent lookup agrees."""
        with self._mu:
            sid = self._store_of.get(region_id)
        if sid is not None:
            return sid
        if self.pd is not None:
            return self.pd.place_region(region_id)
        return self.place_least_loaded(region_id)

    def leader_of(self, region_id: int) -> int:
        """The region's leader store (what `store_of` has always meant)."""
        return self.store_of(region_id)

    def peers_of(self, region_id: int) -> list[int]:
        """The region's full peer set, leader included (ref:
        metapb.Region peers). A miss places first (same authority chain
        as `store_of`)."""
        with self._mu:
            peers = self._peers.get(region_id)
            if peers is not None:
                return list(peers)
        self.store_of(region_id)  # drives the placement decision
        with self._mu:
            return list(self._peers.get(region_id, [self._store_of.get(region_id, 0)]))

    def followers_of(self, region_id: int) -> list[int]:
        leader = self.leader_of(region_id)
        return [p for p in self.peers_of(region_id) if p != leader]

    def locate_placement(self, key: bytes) -> tuple[int, int, list[int]]:
        """(region_id, leader, peers) of the region holding `key` in ONE
        lock acquisition — the per-key write path's lookup (locate +
        leader_of + peers_of would take the lock three times per put)."""
        with self._mu:
            rid = self._regions[self._locate(key)].region_id
            leader = self._store_of.get(rid, 0)
            return rid, leader, list(self._peers.get(rid, [leader]))

    def placement_of(self, region_id: int) -> tuple[int, list[int]]:
        """(leader, peers) of one region in ONE lock acquisition (the
        safe_ts gate's lookup). Falls back to (0, [0]) for an unknown
        region WITHOUT driving a placement decision — gate queries must
        stay read-only."""
        with self._mu:
            leader = self._store_of.get(region_id, 0)
            return leader, list(self._peers.get(region_id, [leader]))

    def regions_of_keys(self, keys) -> set:
        """Region ids covering `keys` in ONE lock acquisition — the bulk
        commit path's replication-proposal grouping (a locate() per key
        would take the lock N times)."""
        with self._mu:
            return {self._regions[self._locate(k)].region_id for k in keys}

    def group_keys_by_region(self, keys) -> dict:
        """region_id -> [keys] in ONE lock acquisition — the bulk commit
        path's per-region change batching (each region's replication
        proposal carries exactly its own keys, so the CDC puller sees the
        log sharded the way the raft log is)."""
        out: dict[int, list] = {}
        with self._mu:
            for k in keys:
                out.setdefault(self._regions[self._locate(k)].region_id, []).append(k)
        return out

    def placements_of_keys(self, keys) -> dict:
        """region_id -> (leader, peers) for every region covering `keys`
        in ONE lock acquisition — the write-quorum gate's lookup (a
        placement_of() per touched region would re-take the lock N
        times on the hot commit path, the round-trip pattern PR 8's
        review collapsed)."""
        out: dict[int, tuple] = {}
        with self._mu:
            for k in keys:
                rid = self._regions[self._locate(k)].region_id
                if rid not in out:
                    leader = self._store_of.get(rid, 0)
                    out[rid] = (leader, list(self._peers.get(rid, [leader])))
        return out

    def place_least_loaded(self, region_id: int) -> int:
        """Place one region on the store with the fewest leaders and
        record the decision (the PD's placement primitive; also the
        standalone-Cluster fallback when no PD is attached)."""
        with self._mu:
            counts = {i: 0 for i in range(self.n_stores)}
            for r in self._regions:
                sid = self._store_of.get(r.region_id)
                if sid is not None:
                    counts[sid] = counts.get(sid, 0) + 1
            target = min(range(self.n_stores), key=lambda i: counts.get(i, 0))
            if any(r.region_id == region_id for r in self._regions):
                self._assign_locked(region_id, target)
            return target

    def set_store(self, region_id: int, store_id: int) -> None:
        """Move a region's leader placement (the PD move-operator
        primitive). A move to an existing peer is a leader change within
        the set; a move elsewhere swaps the old leader peer out for the
        target (the add-then-remove peer dance collapsed to one step)."""
        with self._mu:
            old = self._store_of.get(region_id)
            self._store_of[region_id] = store_id
            peers = self._peers.get(region_id)
            if peers is None:
                self._assign_locked(region_id, store_id)
            else:
                if store_id not in peers:
                    self._peers[region_id] = [
                        store_id if p == old else p for p in peers
                    ] if old in peers else [store_id] + peers[1:]
                if self.replica is not None and store_id != old:
                    # the new leader's follower watermark must not linger
                    # (it would read as phantom safe_ts lag forever) and
                    # the old leader joins as a follower
                    self.replica.on_assign(region_id, self._peers[region_id],
                                           store_id)

    def transfer_leader(self, region_id: int, store_id: int) -> bool:
        """Move leadership WITHIN the peer set (ref: raft TransferLeader
        via pd's transfer-leader operator). No epoch bump — leadership is
        not a topology change; in-flight tasks at the old leader get
        NotLeader with the new leader as a usable hint. Returns False
        when `store_id` is not a peer (or already leads)."""
        with self._mu:
            peers = self._peers.get(region_id)
            old = self._store_of.get(region_id)
            if peers is None or store_id not in peers or old == store_id:
                return False
            self._store_of[region_id] = store_id
            if self.replica is not None:
                self.replica.on_transfer(region_id, old, store_id)
            return True

    def re_place(self, region_id: int, leader: int, avoid=frozenset()) -> None:
        """Rebuild a region's peer set from scratch around `leader`,
        avoiding `avoid` stores — the quorum-loss escape hatch (majority
        of peers dead: no leader transfer can win, so the PD re-places
        the whole group on healthy stores, a fresh-snapshot bootstrap)."""
        with self._mu:
            healthy = [s for s in range(self.n_stores)
                       if s != leader and s not in avoid]
            r = self._replica_count()
            peers = [leader] + healthy[: max(r - 1, 0)]
            self._store_of[region_id] = leader
            self._peers[region_id] = peers
            if self.replica is not None:
                self.replica.on_replace(region_id, peers, leader)

    def counts_per_store(self) -> dict[int, int]:
        """Leaders per store (the historical region count — a region
        'lives' where it leads)."""
        with self._mu:
            counts = {i: 0 for i in range(self.n_stores)}
            for r in self._regions:
                sid = self._store_of.get(r.region_id)
                if sid is not None:
                    counts[sid] = counts.get(sid, 0) + 1
            return counts

    def peer_counts_per_store(self) -> dict[int, int]:
        """Peers (leader + follower replicas) per store."""
        with self._mu:
            counts = {i: 0 for i in range(self.n_stores)}
            for r in self._regions:
                for p in self._peers.get(r.region_id, ()):
                    counts[p] = counts.get(p, 0) + 1
            return counts

    def scatter(self):
        """Round-robin region->store placement (ref: PD scatter-region;
        bootstrap-time only — steady state belongs to the schedulers).
        Routes through the shared helper, so peer sets scatter with the
        leaders."""
        with self._mu:
            for i, r in enumerate(self._regions):
                self._assign_locked(r.region_id, i % self.n_stores)

    def regions(self) -> list[Region]:
        with self._mu:
            return list(self._regions)

    def region_by_id(self, rid: int) -> Region | None:
        with self._mu:
            for r in self._regions:
                if r.region_id == rid:
                    return r
            return None

    def split(self, key: bytes) -> Region:
        """Split the region containing `key` at `key`; bumps both epochs
        (ref: mockstore SplitKeys). The child inherits the parent's store
        — a split keeps peers in place; rebalancing is a separate PD
        decision (ref: TiKV split + balance-region)."""
        with self._mu:
            i = self._locate(key)
            r = self._regions[i]
            if r.start_key == key:
                return r
            new = Region(self._next_id, key, r.end_key, epoch=r.epoch + 1)
            self._next_id += 1
            r.end_key = key
            r.epoch += 1
            self._regions.insert(i + 1, new)
            self._inherit_locked(r.region_id, new.region_id)
            if self.pd is not None:  # stats follow the topology, whoever
                # initiated the split (PD operator, DDL pre-split, tests)
                self.pd.flow.on_split(r.region_id, new.region_id)
            if self.replica is not None:  # watermarks follow peers
                self.replica.on_split(r.region_id, new.region_id)
            if self.cdc is not None:  # the child's resolved watermark
                # inherits the parent's (the sorter hand-off on a split)
                self.cdc.on_split(r.region_id, new.region_id)
            return new

    def merge(self, left_id: int, right_id: int | None = None) -> Region | None:
        """Fold the region right of `left_id` into it (ref: pd
        merge-checker -> TiKV PrepareMerge/CommitMerge collapsed to one
        step). The survivor keeps the left placement and bumps its epoch
        past both inputs; the absorbed region disappears, so stale tasks
        on it get region-not-found and re-split. When `right_id` is
        given, the merge only proceeds if it still names the immediate
        right neighbor (operator-staleness guard). Returns the merged
        region, or None if the merge cannot happen."""
        with self._mu:
            for i, r in enumerate(self._regions):
                if r.region_id == left_id:
                    break
            else:
                return None
            if i + 1 >= len(self._regions):
                return None  # rightmost region has no merge partner
            right = self._regions[i + 1]
            if right_id is not None and right.region_id != right_id:
                return None
            r.end_key = right.end_key
            r.epoch = max(r.epoch, right.epoch) + 1
            del self._regions[i + 1]
            self._store_of.pop(right.region_id, None)
            self._peers.pop(right.region_id, None)
            if self.pd is not None:
                self.pd.flow.on_merge(r.region_id, right.region_id)
            if self.replica is not None:  # survivor watermark = min of both
                self.replica.on_merge(
                    r.region_id, right.region_id,
                    peers=list(self._peers.get(r.region_id, ())),
                    leader=self._store_of.get(r.region_id, -1))
            if self.cdc is not None:  # survivor resolved watermark covers
                # BOTH inputs — min of the two (the sorter hand-off)
                self.cdc.on_merge(r.region_id, right.region_id)
            return r

    def split_n(self, start: bytes, end: bytes, n: int, keyfn):
        """Split [start, end) into n regions using keyfn(i) boundaries."""
        for i in range(1, n):
            self.split(keyfn(i))

    def _locate(self, key: bytes) -> int:  # requires: _mu
        starts = [r.start_key for r in self._regions]
        i = bisect.bisect_right(starts, key) - 1
        return max(i, 0)

    def locate(self, key: bytes) -> Region:
        with self._mu:
            return self._regions[self._locate(key)]

    def regions_in_range(self, start: bytes, end: bytes) -> list[Region]:
        out = []
        with self._mu:
            for r in self._regions:
                if (r.end_key or KEY_MAX) <= start:
                    continue
                if r.start_key >= end:
                    break
                out.append(r)
        return out
