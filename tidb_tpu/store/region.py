"""Range-sharded regions (ref: unistore cluster.go:45 Cluster, mockstore
region splitting).

Regions are the unit of data parallelism: the distsql layer splits a scan
into per-region tasks (ref: copr/coprocessor.go:331 buildCopTasks) and the
mesh layer maps regions onto TPU devices (SURVEY.md §2.5). Epochs support
the region-error/retry path: a split bumps the epoch, in-flight tasks with
the stale epoch get EpochNotMatch and re-split, mirroring
copr/coprocessor.go:1424 handleCopResponse. Merges (PR 3) bump the
surviving epoch and delete the absorbed region, so stale tasks surface
either EpochNotMatch or region-not-found — both re-split cleanly.

Placement (region -> store) lives in an authoritative map owned by the
placement driver (`tidb_tpu/pd`): a split child inherits its parent's
store (peers stay put, like TiKV), and a lookup miss is routed through
`PlacementDriver.place_region()` — a recorded least-loaded decision, not
the seed's silent `region_id % n_stores` guess. All cluster state is
lock-protected: the PD tick mutates topology from a background Timer
thread while cop dispatch reads it.
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass, field

KEY_MAX = b"\xff" * 32


@dataclass
class Region:
    region_id: int
    start_key: bytes
    end_key: bytes
    epoch: int = 1

    def contains(self, key: bytes) -> bool:
        return self.start_key <= key < (self.end_key or KEY_MAX)


class Cluster:
    """All regions, sorted by start key, covering [b'', KEY_MAX).

    Region->store placement (stores are the TPU-chip analog of
    TiKV/TiFlash stores) is authoritative: `scatter()` is the bootstrap
    round-robin (ref: PD scatter-region), after which the PD's
    schedulers own every change via `set_store`/`split`/`merge`."""

    def __init__(self, n_stores: int = 1):
        self._regions: list[Region] = [Region(1, b"", KEY_MAX)]  # guarded_by: _mu
        self._next_id = 2  # guarded_by: _mu
        self.n_stores = max(n_stores, 1)
        self._store_of: dict[int, int] = {1: 0}  # guarded_by: _mu
        self._mu = threading.RLock()
        self.pd = None  # PlacementDriver; owns placement misses when attached

    def set_stores(self, n: int):
        with self._mu:
            self.n_stores = max(n, 1)
        self.scatter()

    def store_of(self, region_id: int) -> int:
        """Authoritative placement lookup. A miss is NOT answered with a
        modulo guess: it routes through the PD (recorded least-loaded
        placement) so every subsequent lookup agrees."""
        with self._mu:
            sid = self._store_of.get(region_id)
        if sid is not None:
            return sid
        if self.pd is not None:
            return self.pd.place_region(region_id)
        return self.place_least_loaded(region_id)

    def place_least_loaded(self, region_id: int) -> int:
        """Place one region on the store with the fewest regions and
        record the decision (the PD's placement primitive; also the
        standalone-Cluster fallback when no PD is attached)."""
        with self._mu:
            counts = {i: 0 for i in range(self.n_stores)}
            for r in self._regions:
                sid = self._store_of.get(r.region_id)
                if sid is not None:
                    counts[sid] = counts.get(sid, 0) + 1
            target = min(range(self.n_stores), key=lambda i: counts.get(i, 0))
            if any(r.region_id == region_id for r in self._regions):
                self._store_of[region_id] = target
            return target

    def set_store(self, region_id: int, store_id: int) -> None:
        """Move a region's placement (the PD move-operator primitive)."""
        with self._mu:
            self._store_of[region_id] = store_id

    def counts_per_store(self) -> dict[int, int]:
        with self._mu:
            counts = {i: 0 for i in range(self.n_stores)}
            for r in self._regions:
                sid = self._store_of.get(r.region_id)
                if sid is not None:
                    counts[sid] = counts.get(sid, 0) + 1
            return counts

    def scatter(self):
        """Round-robin region->store placement (ref: PD scatter-region;
        bootstrap-time only — steady state belongs to the schedulers)."""
        with self._mu:
            for i, r in enumerate(self._regions):
                self._store_of[r.region_id] = i % self.n_stores

    def regions(self) -> list[Region]:
        with self._mu:
            return list(self._regions)

    def region_by_id(self, rid: int) -> Region | None:
        with self._mu:
            for r in self._regions:
                if r.region_id == rid:
                    return r
            return None

    def split(self, key: bytes) -> Region:
        """Split the region containing `key` at `key`; bumps both epochs
        (ref: mockstore SplitKeys). The child inherits the parent's store
        — a split keeps peers in place; rebalancing is a separate PD
        decision (ref: TiKV split + balance-region)."""
        with self._mu:
            i = self._locate(key)
            r = self._regions[i]
            if r.start_key == key:
                return r
            new = Region(self._next_id, key, r.end_key, epoch=r.epoch + 1)
            self._next_id += 1
            r.end_key = key
            r.epoch += 1
            self._regions.insert(i + 1, new)
            self._store_of[new.region_id] = self._store_of.get(r.region_id, 0)
            if self.pd is not None:  # stats follow the topology, whoever
                # initiated the split (PD operator, DDL pre-split, tests)
                self.pd.flow.on_split(r.region_id, new.region_id)
            return new

    def merge(self, left_id: int, right_id: int | None = None) -> Region | None:
        """Fold the region right of `left_id` into it (ref: pd
        merge-checker -> TiKV PrepareMerge/CommitMerge collapsed to one
        step). The survivor keeps the left placement and bumps its epoch
        past both inputs; the absorbed region disappears, so stale tasks
        on it get region-not-found and re-split. When `right_id` is
        given, the merge only proceeds if it still names the immediate
        right neighbor (operator-staleness guard). Returns the merged
        region, or None if the merge cannot happen."""
        with self._mu:
            for i, r in enumerate(self._regions):
                if r.region_id == left_id:
                    break
            else:
                return None
            if i + 1 >= len(self._regions):
                return None  # rightmost region has no merge partner
            right = self._regions[i + 1]
            if right_id is not None and right.region_id != right_id:
                return None
            r.end_key = right.end_key
            r.epoch = max(r.epoch, right.epoch) + 1
            del self._regions[i + 1]
            self._store_of.pop(right.region_id, None)
            if self.pd is not None:
                self.pd.flow.on_merge(r.region_id, right.region_id)
            return r

    def split_n(self, start: bytes, end: bytes, n: int, keyfn):
        """Split [start, end) into n regions using keyfn(i) boundaries."""
        for i in range(1, n):
            self.split(keyfn(i))

    def _locate(self, key: bytes) -> int:  # requires: _mu
        starts = [r.start_key for r in self._regions]
        i = bisect.bisect_right(starts, key) - 1
        return max(i, 0)

    def locate(self, key: bytes) -> Region:
        with self._mu:
            return self._regions[self._locate(key)]

    def regions_in_range(self, start: bytes, end: bytes) -> list[Region]:
        out = []
        with self._mu:
            for r in self._regions:
                if (r.end_key or KEY_MAX) <= start:
                    continue
                if r.start_key >= end:
                    break
                out.append(r)
        return out
