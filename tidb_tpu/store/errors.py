"""Typed region errors — the classification layer the dispatch client
retries on (ref: kvproto errorpb.Error: NotLeader / EpochNotMatch /
ServerIsBusy / StoreNotMatch, and client-go's per-kind Backoffer budgets,
tikv/client-go retry/backoff.go + copr/coprocessor.go:1424 handleCopResponse).

The wire seam carries `CopResponse.region_error` as a string (exactly like
the reference carries errorpb inside the cop response proto), so every
typed error ENCODES to a stable `kind`-prefixed string and PARSES back on
the client side — region errors survive both the single-request bytes seam
and the batched frames without a codec change. `parse_region_error` is
total: an unrecognized string still classifies (as `region_miss`, the
catch-all retry kind) so an old peer can never wedge a new client.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class RegionError:
    """Base: a retryable region-level failure. `kind` selects the
    Backoffer budget; `message` is the wire string it round-trips to."""

    message: str
    kind: str = "region_miss"

    def __str__(self) -> str:
        return self.message


@dataclass(frozen=True)
class NotLeader(RegionError):
    """The peer asked is not the region's leader (ref: errorpb.NotLeader,
    whose `leader` field names the peer to go to instead; the client
    switches peers IMMEDIATELY on a usable hint and only burns the
    updateLeader backoff budget without one). store_id is the store that
    rejected the request; leader_store the hinted current leader (-1 =
    unknown/no hint — e.g. an election in flight)."""

    store_id: int = -1
    leader_store: int = -1
    kind: str = "not_leader"

    @staticmethod
    def make(region_id: int, store_id: int, leader_store: int = -1) -> "NotLeader":
        # leader_store rides the kind-prefixed wire string BEFORE the
        # rejecting store so `_int_after`'s rfind("store") still finds the
        # standalone trailing token (old hint-less strings parse as -1)
        return NotLeader(
            f"not_leader: region {region_id} leader_store={leader_store} "
            f"store {store_id}",
            store_id=store_id, leader_store=leader_store,
        )


@dataclass(frozen=True)
class EpochNotMatch(RegionError):
    """Stale region epoch after a split/merge — the client re-splits its
    ranges against the fresh region view (ref: errorpb.EpochNotMatch)."""

    kind: str = "epoch_not_match"


@dataclass(frozen=True)
class RegionNotFound(RegionError):
    """The region id no longer exists (absorbed by a merge) — re-split,
    same as a stale epoch (ref: errorpb.RegionNotFound)."""

    kind: str = "region_not_found"


@dataclass(frozen=True)
class ServerIsBusy(RegionError):
    """The store is overloaded and suggests how long to wait (ref:
    errorpb.ServerIsBusy.backoff_ms; client-go honors the suggestion as a
    floor on its serverBusy backoff)."""

    backoff_ms: int = 0
    kind: str = "server_busy"

    @staticmethod
    def make(store_id: int, backoff_ms: int = 0) -> "ServerIsBusy":
        return ServerIsBusy(
            f"server_is_busy: store {store_id} backoff_ms={backoff_ms}",
            backoff_ms=backoff_ms,
        )


@dataclass(frozen=True)
class DataIsNotReady(RegionError):
    """A replica read asked a follower whose applied watermark trails the
    request's snapshot (ref: errorpb.DataIsNotReady raised by TiKV's
    replica read when `safe_ts < start_ts`; client-go backs off on the
    maxDataNotReady budget and falls back to the leader)."""

    store_id: int = -1
    safe_ts: int = -1
    kind: str = "data_not_ready"

    @staticmethod
    def make(region_id: int, store_id: int, safe_ts: int) -> "DataIsNotReady":
        return DataIsNotReady(
            f"data_is_not_ready: region {region_id} safe_ts={safe_ts} "
            f"store {store_id}",
            store_id=store_id, safe_ts=safe_ts,
        )


@dataclass(frozen=True)
class StoreUnavailable(RegionError):
    """The placement store is down/unreachable — the breaker-counting
    kind: repeated hits open the store's circuit breaker and the task
    fails over through a PD re-placement (ref: client-go's store
    liveness/slow-score marking a store unreachable)."""

    store_id: int = -1
    kind: str = "store_unavailable"

    @staticmethod
    def make(store_id: int) -> "StoreUnavailable":
        return StoreUnavailable(f"store_unavailable: store {store_id}",
                                store_id=store_id)


@dataclass(frozen=True)
class QuorumLost(RegionError):
    """The region's write quorum is gone — a majority of peers cannot ack
    (ref: a raft group without a quorum accepts no proposals; TiKV answers
    Propose errors until a majority returns). Unlike the read-side errors
    above this one is raised on the WRITE path: the store refuses the
    write instead of letting it stay silently durable on the shared KV
    (ROADMAP PR-8 follow-on)."""

    store_id: int = -1
    kind: str = "quorum_lost"

    @staticmethod
    def make(region_id: int, acks: int, needed: int) -> "QuorumLost":
        return QuorumLost(
            f"quorum_lost: region {region_id} acks={acks} needed={needed}",
        )


class QuorumLostError(RuntimeError):
    """Exception shape of QuorumLost for the write path (the read path
    carries region errors as response values; writes raise). The session
    boundary maps it to MySQL 9005 ErrRegionUnavailable."""

    def __init__(self, region_id: int, acks: int, needed: int):
        super().__init__(str(QuorumLost.make(region_id, acks, needed)))
        self.region_id, self.acks, self.needed = region_id, acks, needed


def _int_after(s: str, token: str, default: int = -1) -> int:
    i = s.rfind(token)
    if i < 0:
        return default
    tail = s[i + len(token):].lstrip()
    digits = ""
    for c in tail:
        if c.isdigit() or (c == "-" and not digits):
            digits += c
        else:
            break
    try:
        return int(digits)
    except ValueError:
        return default


def parse_region_error(message: str | None) -> RegionError | None:
    """Classify a wire region-error string into its typed form. Total:
    anything unrecognized is a generic `region_miss` (retry + re-split,
    the safe default — exactly how the seed treated every region error)."""
    if message is None:
        return None
    m = message.strip()
    low = m.lower()
    if "data_is_not_ready" in low or "data is not ready" in low:
        return DataIsNotReady(m, store_id=_int_after(low, "store"),
                              safe_ts=_int_after(low, "safe_ts="))
    if "not_leader" in low or "not leader" in low:
        return NotLeader(m, store_id=_int_after(low, "store"),
                         leader_store=_int_after(low, "leader_store="))
    if "server_is_busy" in low or "server is busy" in low:
        return ServerIsBusy(m, backoff_ms=max(_int_after(low, "backoff_ms="), 0))
    if "store_unavailable" in low or "store unavailable" in low:
        return StoreUnavailable(m, store_id=_int_after(low, "store"))
    if "quorum_lost" in low or "quorum lost" in low:
        return QuorumLost(m)
    if "epoch_not_match" in low or "epoch not match" in low:
        return EpochNotMatch(m)
    if "not found" in low:
        return RegionNotFound(m)
    return RegionError(m)
