from .kv import MemKV
from .region import Region, Cluster
from .store import TPUStore, CopRequest, CopResponse, KeyRange

__all__ = ["MemKV", "Region", "Cluster", "TPUStore", "CopRequest", "CopResponse", "KeyRange"]
