from .kv import MemKV
from .region import Region, Cluster
from .store import TPUStore, CopRequest, CopResponse, KeyRange
from .errors import (
    RegionError,
    NotLeader,
    DataIsNotReady,
    EpochNotMatch,
    RegionNotFound,
    QuorumLost,
    QuorumLostError,
    ServerIsBusy,
    StoreUnavailable,
    parse_region_error,
)

__all__ = [
    "MemKV", "Region", "Cluster", "TPUStore", "CopRequest", "CopResponse", "KeyRange",
    "RegionError", "NotLeader", "DataIsNotReady", "EpochNotMatch", "RegionNotFound",
    "QuorumLost", "QuorumLostError", "ServerIsBusy", "StoreUnavailable",
    "parse_region_error",
]
