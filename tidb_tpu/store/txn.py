"""Percolator transaction engine over MemKV (ref: unistore/tikv/mvcc.go
MVCCStore prewrite/commit + lockstore; client-go 2PC driver;
pkg/store/driver/txn/txn_driver.go).

The reference splits 2PC across the client (primary selection, parallel
prewrite, commit point) and the store (lock CF, write CF, conflict checks).
In one process both halves collapse into this engine:

  prewrite   lock every mutated key after write-conflict + lock checks
  commit     apply buffered values at commit_ts, release locks (atomic
             under the engine mutex — readers never observe a partial
             commit, which is why snapshot reads here do not need the
             reference's lock-wait/resolve path)
  rollback   drop this txn's locks
  pessimistic lock
             conflict-checked intention locks taken at DML time
             (ref: acquire pessimistic lock, mvcc.go; lock converts to a
             prewrite lock at commit)

Failure semantics match Percolator where observable in-process:
  KeyIsLocked    another live txn holds the key (no wait queue — the
                 caller surfaces a lock-conflict error immediately)
  WriteConflict  a commit landed after this txn's snapshot/for_update ts
"""

from __future__ import annotations

import threading
from contextlib import contextmanager, nullcontext
from dataclasses import dataclass

from .kv import MemKV


class TxnError(Exception):
    pass


class KeyIsLocked(TxnError):
    def __init__(self, key: bytes, holder_ts: int):
        super().__init__(f"key is locked by txn {holder_ts}")
        self.key, self.holder_ts = key, holder_ts


class WriteConflict(TxnError):
    def __init__(self, key: bytes, conflict_ts: int, start_ts: int):
        super().__init__(
            f"write conflict: key committed at {conflict_ts} > txn start {start_ts}"
        )
        self.key, self.conflict_ts, self.start_ts = key, conflict_ts, start_ts


@dataclass
class Lock:
    """(ref: lockstore entry / kvrpcpb.LockInfo)."""

    primary: bytes
    start_ts: int
    op: str  # "prewrite" | "pessimistic"
    value: bytes | None = None  # buffered write (prewrite only)
    is_delete: bool = False
    for_update_ts: int = 0


class TxnEngine:
    def __init__(self, kv: MemKV, on_commit=None, on_apply=None,
                 pre_apply=None, write_guard=None, on_apply_group=None):
        self.kv = kv
        self.locks: dict[bytes, Lock] = {}  # guarded_by: _mu
        self._mu = threading.RLock()
        self._on_commit = on_commit  # store cache-invalidation hook
        self._on_apply = on_apply  # batch hook: ([(key, value|None,
        # prev_live)], commit_ts) called AFTER the kv critical section
        # (PD write flow + replication proposal + CDC delivery)
        self._on_apply_group = on_apply_group  # group-commit hook:
        # ([(applied, commit_ts)]) for a whole coalesced window at once,
        # so the store can fold every lane's changes into ONE replication
        # proposal per region (falls back to per-lane _on_apply when unset)
        self._pre_apply = pre_apply  # keys hook BEFORE any apply: may raise
        # (the store's write-quorum gate — a refused commit applies nothing)
        self._write_guard = write_guard  # zero-arg ctx factory wrapping
        # [commit-ts draw .. change delivery]: the CDC resolved-ts sampler
        # treats the window as an in-flight write (cdc/hub.py WriteGuard)

    def _guard(self):
        return self._write_guard() if self._write_guard is not None else nullcontext()

    # ------------------------------------------------------------------
    def acquire_pessimistic(self, keys: list, primary: bytes, start_ts: int, for_update_ts: int):
        """Intention locks for pessimistic DML (ref: mvcc.go pessimistic
        lock path): conflict-checked against commits newer than
        for_update_ts, held until commit/rollback."""
        with self._mu:
            for k in keys:
                l = self.locks.get(k)
                if l is not None and l.start_ts != start_ts:
                    raise KeyIsLocked(k, l.start_ts)
            for k in keys:
                cts = self.kv.latest_ts(k)
                if cts > for_update_ts:
                    raise WriteConflict(k, cts, for_update_ts)
            for k in keys:
                if k not in self.locks:
                    self.locks[k] = Lock(primary, start_ts, "pessimistic", for_update_ts=for_update_ts)

    def prewrite(self, mutations: dict, primary: bytes, start_ts: int):
        """mutations: key -> value bytes (None = delete tombstone)."""
        with self._mu:
            for k in mutations:
                l = self.locks.get(k)
                if l is not None and l.start_ts != start_ts:
                    raise KeyIsLocked(k, l.start_ts)
            for k in mutations:
                l = self.locks.get(k)
                if l is not None and l.op == "pessimistic":
                    continue  # conflict already checked at for_update_ts
                cts = self.kv.latest_ts(k)
                if cts > start_ts:
                    raise WriteConflict(k, cts, start_ts)
            for k, v in mutations.items():
                self.locks[k] = Lock(primary, start_ts, "prewrite", v, v is None)

    def commit(self, keys: list, start_ts: int, commit_ts):
        """commit_ts: an int, or a callable TSO source. When callable, the
        timestamp is drawn INSIDE the kv critical section: with a monotone
        TSO, no reader can have obtained read_ts >= commit_ts before the
        whole apply is visible — snapshot isolation without the reference's
        lock-wait/resolve read path. Returns the commit_ts used."""
        applied = []
        with self._guard():  # entered BEFORE the commit ts is drawn
            with self._mu:
                staged = []
                for k in keys:
                    l = self.locks.get(k)
                    if l is None or l.start_ts != start_ts:
                        raise TxnError(f"lock not found for commit (txn {start_ts})")
                    if l.op != "prewrite":
                        raise TxnError("commit before prewrite (pessimistic lock not converted)")
                    staged.append((k, l))
                if self._pre_apply is not None and staged:
                    # the write-quorum gate: raises BEFORE anything applies,
                    # so a quorum-lost region refuses the whole commit (the
                    # caller's locks stay put for its rollback path)
                    self._pre_apply([k for k, _ in staged])
                with self.kv.lock:  # readers see all of the commit or none
                    if callable(commit_ts):
                        commit_ts = commit_ts()
                    for k, l in staged:
                        v = None if l.is_delete else l.value
                        prev = self.kv.put(k, v, commit_ts)
                        del self.locks[k]
                        applied.append((k, v, prev))
            if self._on_apply is not None and applied:
                self._on_apply(applied, commit_ts)  # outside the locks —
                # flow bookkeeping must never extend the window in which
                # readers are blocked
        if self._on_commit is not None and staged:
            self._on_commit()
        return commit_ts

    def rollback(self, keys: list, start_ts: int):
        with self._mu:
            for k in keys:
                l = self.locks.get(k)
                if l is not None and l.start_ts == start_ts:
                    del self.locks[k]

    def release_all(self, start_ts: int):
        """Drop every lock a txn holds (rollback convenience)."""
        with self._mu:
            for k in [k for k, l in self.locks.items() if l.start_ts == start_ts]:
                del self.locks[k]

    # ------------------------------------------------------------------
    def commit_txn(self, mutations: dict, start_ts: int, commit_ts):
        """Full 2PC for an in-process txn: prewrite everything (primary =
        first key), then commit. Raises without side effects on conflict;
        pessimistic locks this txn already holds are converted.
        commit_ts may be a callable TSO source (see commit)."""
        if not mutations:
            return None
        keys = list(mutations)
        primary = keys[0]
        try:
            self.prewrite(mutations, primary, start_ts)
        except TxnError:
            self.release_all(start_ts)
            raise
        return self.commit(keys, start_ts, commit_ts)

    def commit_group(self, reqs: list, tso) -> list:
        """Group commit (ISSUE 19): 2PC several independent autocommit
        transactions in ONE write-guard window and ONE kv critical
        section, each lane committing at its OWN timestamp drawn from
        `tso` in lane order. reqs: [(mutations dict, start_ts)]. Returns
        one result per lane: the commit_ts on success, or the exception
        instance for a lane that fell out (conflict / refused quorum —
        its locks are released; the window stands for the other lanes).
        The per-lane sequence is exactly commit_txn's — prewrite, quorum
        gate, apply, release — so a group of one is byte-equivalent to
        the single path."""
        results: list = [None] * len(reqs)
        staged_lanes: list = []  # (idx, keys, start_ts)
        applied_lanes: list = []  # (applied, commit_ts)
        with self._guard():  # entered BEFORE any commit ts is drawn
            with self._mu:
                for i, (mutations, start_ts) in enumerate(reqs):
                    if not mutations:
                        continue
                    keys = list(mutations)
                    try:
                        self.prewrite(mutations, keys[0], start_ts)
                        if self._pre_apply is not None:
                            self._pre_apply(keys)
                    except Exception as exc:  # TxnError | QuorumLostError
                        self.release_all(start_ts)
                        results[i] = exc
                        continue
                    staged_lanes.append((i, keys, start_ts))
                with self.kv.lock:  # readers see all of a lane or none
                    for i, keys, start_ts in staged_lanes:
                        cts = tso()
                        applied = []
                        for k in keys:
                            l = self.locks[k]
                            v = None if l.is_delete else l.value
                            prev = self.kv.put(k, v, cts)
                            del self.locks[k]
                            applied.append((k, v, prev))
                        results[i] = cts
                        applied_lanes.append((applied, cts))
            if applied_lanes:  # outside the locks, inside the guard —
                # same bracket as the single path's _on_apply
                if self._on_apply_group is not None:
                    self._on_apply_group(applied_lanes)
                elif self._on_apply is not None:
                    for applied, cts in applied_lanes:
                        self._on_apply(applied, cts)
        if applied_lanes and self._on_commit is not None:
            self._on_commit()
        return results

    def check_unlocked(self, keys, start_ts: int = 0):
        """Raise KeyIsLocked if any key is held by another transaction —
        the guard bulk ingest (LOAD DATA, BR restore) runs before writing
        around the lock table (ref: Lightning conflict with live txns)."""
        with self._mu:
            for k in keys:
                l = self.locks.get(k)
                if l is not None and l.start_ts != start_ts:
                    raise KeyIsLocked(k, l.start_ts)

    @contextmanager
    def ingest_guard(self):
        """One critical section for a whole bulk-import batch: the caller
        draws its read/write timestamps, re-runs its duplicate checks, and
        applies the writes all inside — no committed write or prewrite can
        interleave (LOAD DATA / BR restore vs in-flight 2PC; lock order
        engine _mu -> kv.lock matches commit())."""
        with self._mu:
            with self.kv.lock:
                yield

    def bulk_ingest(self, items, ts: int):
        """Atomically verify-and-apply (key, value) pairs (BR restore —
        no value-level duplicate checks needed; LOAD DATA wraps its whole
        check+apply in ingest_guard instead)."""
        applied = []
        with self._guard():
            with self.ingest_guard():
                self.check_unlocked([k for k, _ in items])
                if self._pre_apply is not None and items:
                    self._pre_apply([k for k, _ in items])
                for k, v in items:
                    applied.append((k, v, self.kv.put(k, v, ts)))
            if self._on_apply is not None and applied:
                self._on_apply(applied, ts)
