"""In-memory MVCC key-value engine (ref: unistore/tikv/mvcc.go MVCCStore on
badger + lockstore).

A sorted-array store with timestamped versions: enough Percolator surface
for snapshot reads and the write path (put at commit_ts, delete as
tombstone), without the lock column family — single-process writes are
serialized by the session layer for now (2PC lands with the txn layer).
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass, field


class MemKV:
    __slots__ = ("_data", "_keys", "_dirty", "lock", "max_version")

    def __init__(self):
        self._data: dict[bytes, list[tuple[int, bytes | None]]] = {}  # guarded_by: lock
        self._keys: list[bytes] = []  # guarded_by: lock
        self._dirty = False  # guarded_by: lock
        # largest commit_ts ever written: a snapshot at start_ts >=
        # max_version sees EVERY committed version, which is what makes a
        # coprocessor response reusable across snapshots (store cop cache)
        self.max_version = 0  # guarded_by: lock
        # structural lock: every read/write takes it, and TxnEngine.commit
        # holds it across the WHOLE apply loop, so a concurrent snapshot
        # read can never observe half a commit (the docstring invariant of
        # store/txn.py); RLock so the engine can nest puts under it
        self.lock = threading.RLock()

    def put(self, key: bytes, value: bytes | None, ts: int) -> bool:
        """value None = tombstone. Returns whether the key had a LIVE
        (non-tombstone) latest version before this put — the flow
        recorder's insert/update/delete discriminator."""
        with self.lock:
            versions = self._data.get(key)
            prev_live = bool(versions) and versions[-1][1] is not None
            if versions is None:
                self._data[key] = [(ts, value)]
                self._dirty = True
            else:
                versions.append((ts, value))
                if len(versions) > 1 and versions[-2][0] > ts:
                    versions.sort(key=lambda v: v[0])
            if ts > self.max_version:
                self.max_version = ts
            return prev_live

    def _ensure_sorted(self):  # requires: lock
        if self._dirty:
            self._keys = sorted(self._data.keys())
            self._dirty = False

    def get(self, key: bytes, ts: int) -> bytes | None:
        with self.lock:
            versions = self._data.get(key)
            if not versions:
                return None
            # newest version with commit_ts <= ts
            for vts, val in reversed(versions):
                if vts <= ts:
                    return val
            return None

    def scan(self, start: bytes, end: bytes, ts: int, limit: int | None = None):
        """Yield (key, value) with start <= key < end visible at ts.
        The result set is materialized under the lock — one consistent cut."""
        with self.lock:
            self._ensure_sorted()
            i = bisect.bisect_left(self._keys, start)
            out = []
            while i < len(self._keys):
                k = self._keys[i]
                if k >= end:
                    break
                v = self.get(k, ts)
                if v is not None:
                    out.append((k, v))
                    if limit is not None and len(out) >= limit:
                        break
                i += 1
        return iter(out)

    def scan_versions(self, start: bytes, end: bytes, lo_ts: int, hi_ts: int):
        """Every committed version of keys in [start, end) with
        lo_ts < commit_ts <= hi_ts, as (key, commit_ts, value|None) in key
        order — the CDC incremental scan (ref: TiCDC's kv client scanning
        the range from checkpoint-ts when a region subscription (re)opens;
        tombstones ride along so deletes replay downstream). One
        consistent cut: materialized under the lock."""
        out = []
        with self.lock:
            self._ensure_sorted()
            i = bisect.bisect_left(self._keys, start)
            while i < len(self._keys):
                k = self._keys[i]
                if k >= end:
                    break
                for vts, val in self._data.get(k, ()):
                    if lo_ts < vts <= hi_ts:
                        out.append((k, vts, val))
                i += 1
        return out

    def gc(self, safepoint: int) -> int:
        """MVCC garbage collection at `safepoint`: per key, keep every
        version newer than the safepoint plus the newest one at-or-below
        it (the version a safepoint-old snapshot still reads); if that
        survivor is a tombstone nothing can ever read, drop it too
        (ref: pkg/store/gcworker/gc_worker.go resolve + delete-versions).
        Returns the number of versions removed."""
        removed = 0
        with self.lock:
            for key in list(self._data):
                versions = self._data[key]  # ascending commit_ts
                newest_le = None
                keep = []
                for vts, val in versions:
                    if vts <= safepoint:
                        newest_le = (vts, val)
                    else:
                        keep.append((vts, val))
                if newest_le is not None and newest_le[1] is not None:
                    keep.insert(0, newest_le)
                removed += len(versions) - len(keep)
                if keep:
                    self._data[key] = keep
                else:
                    del self._data[key]
                    self._dirty = True
        return removed

    def latest_ts(self, key: bytes) -> int:
        """Commit ts of the newest version of `key` (0 if none) — the
        write-conflict check input (ref: mvcc.go checkConflict)."""
        with self.lock:
            versions = self._data.get(key)
            return versions[-1][0] if versions else 0

    def max_ts(self) -> int:
        # vet(lock-discipline) finding: this walked _data with no lock —
        # a concurrent put resizing the dict mid-iteration raises
        ts = 0
        with self.lock:
            for versions in self._data.values():
                if versions:
                    ts = max(ts, versions[-1][0])
        return ts

    def max_committed(self) -> int:
        """Locked snapshot of max_version (for callers that must not
        take `lock` around their own critical sections)."""
        with self.lock:
            return self.max_version

    def __len__(self):
        with self.lock:
            return len(self._data)
