"""tidb_tpu — a TPU-native analytical execution framework.

A from-scratch reimplementation of TiDB's query-processing capabilities
(reference: YangKeao/tidb) designed TPU-first: columnar region batches live as
HBM-resident device arrays, `tipb.Expr`-shaped expression trees compile to fused
XLA programs, and the coprocessor operator set (Selection, HashAgg, StreamAgg,
TopN, HashJoin, Limit, Projection) runs as vmapped/shard_mapped kernels over a
`jax.sharding.Mesh`, with per-region partial aggregates psum-reduced over ICI.

Package map (mirrors reference layers, SURVEY.md §1):
  types/     MySQL type system: FieldType, Datum, MyDecimal, Time
             (ref: pkg/types, pkg/parser/types)
  chunk/     Columnar batches, host (numpy) + device (jax) forms
             (ref: pkg/util/chunk)
  codec/     Memcomparable datum codec, row format v2, table key layout
             (ref: pkg/util/codec, pkg/util/rowcodec, pkg/tablecodec)
  expr/      Expression IR, JAX compiler, aggregation descriptors
             (ref: pkg/expression)
  ops/       Device kernels for the coprocessor operator set
             (ref: pkg/store/mockstore/unistore/cophandler/mpp_exec.go)
  exec/      DAG executor: DAGRequest -> fused compiled program
             (ref: unistore/cophandler/cop_handler.go)
  store/     In-process region-sharded MVCC store (unistore analog)
             (ref: pkg/store/mockstore/unistore)
  distsql/   Request building, per-region task split, result merge
             (ref: pkg/distsql, pkg/store/copr)
  parallel/  Mesh sharding, psum partial-agg merge, all_to_all exchange
             (ref: MPP — pkg/planner/core/fragment.go, cophandler/mpp_exec.go)
  parser/    Standalone MySQL-dialect lexer + recursive-descent parser -> AST
             (ref: pkg/parser — a leaf package, like the reference's)
  sql/       SQL front end: catalog, AST->DAG planner, session, subquery
             decorrelation, sysvars (ref: pkg/infoschema+pkg/meta,
             pkg/planner, pkg/session, pkg/sessionctx)
  server/    MySQL wire protocol server + minimal client
             (ref: pkg/server)
  native/    C++ runtime components (scan-decode kernel) via ctypes
             (ref: TiKV's native decode; rowcodec ChunkDecoder)
  tools/     dump / LOAD DATA bulk import / BACKUP-RESTORE
             (ref: dumpling/, pkg/lightning, br/)
  background/ timer, TTL, dist-task, auto-analyze workers
             (ref: pkg/timer, pkg/ttl, pkg/disttask, statistics/handle)
  util/      failpoints, metrics, memory tracking
             (ref: pkg/util, pingcap/failpoint, pkg/metrics)
"""

import jax as _jax

# MySQL semantics need 64-bit ints (BIGINT, packed datetimes, scaled
# decimals) and float64 DOUBLE; the engine is written for x64 throughout.
_jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
