"""DAG -> one fused XLA program.

The reference interprets a DAG as a pull-based iterator chain per batch
(ref: cophandler mppExecute pull loop, cop_handler.go:228). Here the whole
executor list traces into a *single* jitted function: scan columns in HBM ->
masked selection -> sort-based aggregation / topn / projection — XLA fuses
the lot, which is the TPU analog of the legacy fused closure executor
(ref: unistore/cophandler/closure_exec.go:165 buildClosureExecutor).

Programs cache by (DAG fingerprint, capacity, group capacity) — the XLA
compile is the expensive part, amortized exactly like the reference's
coprocessor cache (ref: pkg/store/copr/coprocessor_cache.go).

A program returns per-output-column (value, null[, raw bytes + lengths]),
plus row validity, row count and an overflow flag; on overflow (group/join
capacity exceeded) the host driver re-plans with a larger capacity or falls
back to the reference evaluator (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..chunk.device import DeviceBatch
from ..expr.compile import CompVal, ExprCompiler, normalize_device_column
from ..ops import apply_selection, group_aggregate, hash_join, scalar_aggregate, topn
from ..ops import dense_pallas as _eager_dense_pallas  # noqa: F401
from ..ops import joinagg as _eager_joinagg  # noqa: F401
from ..ops import joinscan as _eager_joinscan  # noqa: F401

# ^ the packed-join modules are imported lazily on the hot path below, but
# MUST already be loaded before any jit trace starts: their module-level
# jnp constants (_PIN_HAY, I64_MAX, ...) would be staged as tracers if the
# first import happened inside the traced program, leaking into every
# later trace (jax UnexpectedTracerError, order-dependent).
from ..ops.aggregate import GatherState, finalize_agg
from ..types import FieldType
from .dag import Aggregation, DAGRequest, IndexScan, Join, Limit, Projection, Selection, Sort, TableScan, TopN, Window, collect_scans, current_schema_fts

DEFAULT_GROUP_CAPACITY = 4096


def _gather(cols: list[CompVal], idx) -> list[CompVal]:
    out = []
    for c in cols:
        raw = None
        if c.raw is not None:
            raw = (c.raw[0][idx], c.raw[1][idx])
        out.append(CompVal(c.value[idx], c.null[idx], c.ft, raw=raw))
    return out


@dataclass
class CompiledDAG:
    fn: object  # jitted (DeviceBatch, ...) -> (outputs, valid, n_rows, overflow, ex_rows)
    out_fts: list[FieldType]
    capacities: tuple  # one per scan, canonical order (dag.collect_scans)
    group_capacity: int
    join_capacity: int
    # radix-join attribution, filled AT TRACE TIME (first execution): the
    # partition count / per-partition build capacity the plan chose; empty
    # when no Join rode the radix kernel.  The drivers read it after the
    # call to emit the `join_radix` span/summary (partitions, rung,
    # escapes) — see exec/executor.py.
    radix_info: dict = None  # type: ignore[assignment]


class _TraceState:
    """Mutable trace-time accumulators shared across nested pipelines.

    Group and join overflow are SEPARATE flags so the retry driver grows
    only the capacity that actually overflowed (a 4x-per-retry growth on
    the wrong knob wastes HBM and compile time).

    summaries=False drops the per-executor produced-row counts: each one
    is a full-array reduce with a ~1.5-3ms dispatch floor on the tunneled
    v5e, which for a 9-executor join plan is more than the sorts cost —
    the bench path runs without them, production keeps them (EXPLAIN
    ANALYZE needs the numbers)."""

    def __init__(self, summaries: bool = True):
        self.group_overflow = jnp.bool_(False)
        self.join_overflow = jnp.bool_(False)
        self.topn_overflow = jnp.bool_(False)
        # capacity NEED hints riding next to the flags (exec/ladder.py):
        # the true group count / join fan-out when a kernel knows it, so
        # the retry driver re-dispatches the exact (precompiled) rung in
        # the SAME device fetch that read the overflow flag
        self.group_need = jnp.int64(0)
        self.join_need = jnp.int64(0)
        # radix-join attribution: escaped-row count (EXPLAIN/TRACE)
        self.radix_escapes = jnp.int64(0)
        self.radix_meta: dict = {}  # filled at trace time (partitions)
        self.radix_joins = True  # builder knob: False = monolithic only
        self.summaries = summaries
        self.ex_rows: list = []

    def note_group(self, need):
        if need is not None:
            self.group_need = jnp.maximum(self.group_need, need.astype(jnp.int64))

    def note_join(self, need):
        if need is not None:
            self.join_need = jnp.maximum(self.join_need, need.astype(jnp.int64))

    def rows(self, arr_or_scalar):
        """Record a produced-row count (lazy: no-op when summaries off).
        Accepts a precomputed scalar or a bool/int mask to sum."""
        if not self.summaries:
            return
        v = arr_or_scalar
        if getattr(v, "ndim", 0) > 0:
            v = v.sum()
        self.ex_rows.append(v.astype(jnp.int64))


def _used_cols_after(rest, width: int, out_offsets):
    """Column indexes < width referenced by the remaining executors (or by
    the DAG outputs when the schema survives to the end) — the builder's
    column-pruning analog of the reference's columnPruner rule
    (pkg/planner/core/rule_column_pruning.go), applied at join output where
    every live column costs a ~16ns/row random gather on TPU.

    Schema-REPLACING executors (Projection/Aggregation) consume their
    inputs and cut the walk; schema-EXTENDING ones (Join, Window) preserve
    the prefix, so later references < width still mean these columns."""
    from ..expr.ir import ColumnRef, ScalarFunc

    used: set = set()

    def collect(e):
        if isinstance(e, ColumnRef):
            if e.index < width:
                used.add(e.index)
        elif isinstance(e, ScalarFunc):
            for a in e.args:
                collect(a)

    for ex in rest:
        if isinstance(ex, Selection):
            for c in ex.conditions:
                collect(c)
        elif isinstance(ex, (TopN, Sort)):
            for e, _ in ex.order_by:
                collect(e)
        elif isinstance(ex, Limit):
            pass
        elif isinstance(ex, Window):
            for e in ex.partition_by:
                collect(e)
            for e, _ in ex.order_by:
                collect(e)
            for w in ex.funcs:
                for a in w.args:
                    collect(a)
                if w.default is not None:
                    collect(w.default)
        elif isinstance(ex, Join):
            for e in ex.probe_keys:
                collect(e)
        elif isinstance(ex, Projection):
            for e in ex.exprs:
                collect(e)
            return used
        elif isinstance(ex, Aggregation):
            for e in ex.group_by:
                collect(e)
            for d in ex.aggs:
                for a in d.args:
                    collect(a)
            return used
    if out_offsets is None:
        return set(range(width))
    used.update(o for o in out_offsets if o < width)
    return used


def _gather_pruned(cols: list, idx, used: set, base: int) -> list:
    """Gather only the live columns; dead slots get an all-NULL zero column
    (schema positions preserved, no HBM traffic)."""
    n = idx.shape[0]
    out = []
    for j, c in enumerate(cols):
        if (base + j) in used:
            out.append(_gather([c], idx)[0])
        else:
            v = jnp.zeros((n,) + c.value.shape[1:], c.value.dtype)
            out.append(CompVal(v, jnp.ones(n, bool), c.ft))
    return out


def _run_pipeline(executors, batches, cursor, group_capacity, join_capacity, state: _TraceState, topn_full: bool = False, small_groups: int | None = None, unique_joins: bool = True, out_offsets=None):
    """Trace one executor pipeline; recursion handles Join build sides.

    batches are consumed in canonical scan order (dag.collect_scans);
    `cursor` is the trace-time index of the next unconsumed batch."""
    scan = executors[0]
    assert isinstance(scan, (TableScan, IndexScan)), "pipeline must start with a scan"
    batch = batches[cursor[0]]
    cursor[0] += 1
    fts = [c.ft for c in scan.columns]
    cols = [normalize_device_column(c) for c in batch.cols]
    valid = batch.row_valid
    # per-executor produced-row counts, scan first (real numbers for the
    # exec summaries — ref: tipb.ExecutorExecutionSummary NumProducedRows)
    state.rows(batch.n_rows)

    ei = 1
    while ei < len(executors):
        ex = executors[ei]
        comp = ExprCompiler(fts)
        if isinstance(ex, Selection):
            conds = comp.run(list(ex.conditions), cols)
            valid = apply_selection(valid, conds)
        elif isinstance(ex, Projection):
            cols = comp.run(list(ex.exprs), cols)
            fts = [e.ft for e in ex.exprs]
        elif isinstance(ex, Limit):
            keep = jnp.cumsum(valid.astype(jnp.int32)) <= ex.limit
            valid = valid & keep
        elif isinstance(ex, TopN):
            order_vals = comp.run([e for e, _ in ex.order_by], cols)
            by = list(zip(order_vals, [d for _, d in ex.order_by]))
            idx, out_valid, t_ovf = topn(by, valid, ex.limit, full_sort=topn_full)
            state.topn_overflow = state.topn_overflow | t_ovf
            cols = _gather(cols, idx)
            valid = out_valid
        elif isinstance(ex, Sort):
            from ..ops.topn import sort_all

            order_vals = comp.run([e for e, _ in ex.order_by], cols)
            by = list(zip(order_vals, [d for _, d in ex.order_by]))
            idx, out_valid = sort_all(by, valid)
            cols = _gather(cols, idx)
            valid = out_valid
        elif isinstance(ex, Join):
            nxt = executors[ei + 1] if ei + 1 < len(executors) else None
            fused_ok = isinstance(nxt, Aggregation) and _joinagg_pattern(ex, nxt, len(fts), unique_joins)
            if fused_ok:
                fused = _trace_packed_chain(
                    ex, nxt, comp, cols, valid, batches, cursor,
                    group_capacity, join_capacity, state, topn_full,
                    small_groups, unique_joins,
                )
                if fused is not None:
                    cols, valid, fts = fused
                    state.rows(valid)
                    ei += 2
                    continue
            bcols, bvalid, bfts = _run_pipeline(ex.build, batches, cursor, group_capacity, join_capacity, state, topn_full, small_groups, unique_joins)
            bcomp = ExprCompiler(bfts)
            bkeys = bcomp.run(list(ex.build_keys), bcols)
            pkeys = comp.run(list(ex.probe_keys), cols)
            _check_join_key_types(pkeys, bkeys)
            if fused_ok and _single_word(pkeys[0]) and _single_word(bkeys[0]):
                fused = _trace_joinagg(
                    nxt, comp, cols, bkeys, pkeys, bvalid, valid,
                    group_capacity, state,
                )
                if fused is not None:
                    cols, valid, fts = fused
                    state.rows(valid)
                    ei += 2
                    continue
            res = _trace_radix_join(ex, bkeys, pkeys, bvalid, valid,
                                    join_capacity, state, unique_joins)
            if res is None:
                res = hash_join(bkeys, pkeys, bvalid, valid, join_capacity, ex.join_type,
                                build_unique=ex.build_unique and unique_joins)
            state.join_overflow = state.join_overflow | res.overflow
            state.note_join(res.need)
            if ex.join_type in ("semi", "anti"):
                # probe schema preserved, rows filtered by match-existence
                valid = res.out_valid
            else:
                nb = bvalid.shape[0]
                used = _used_cols_after(executors[ei + 1:], len(fts) + len(bfts), out_offsets)
                if res.probe_identity:
                    p_g = cols  # unique-build layout: slot j == probe row j
                else:
                    p_g = _gather_pruned(cols, res.probe_idx, used, 0)
                b_g = _gather_pruned(bcols, jnp.clip(res.build_idx, 0, nb - 1), used, len(fts))
                b_g = [CompVal(c.value, c.null | res.build_null, c.ft, raw=c.raw) for c in b_g]
                cols = p_g + b_g
                valid = res.out_valid
                if ex.join_type == "left_outer":
                    bfts = [f.clone_nullable() for f in bfts]
                fts = fts + bfts
        elif isinstance(ex, Window):
            from ..ops.window import window_cols

            part_vals = comp.run(list(ex.partition_by), cols) if ex.partition_by else []
            order_vals = comp.run([e for e, _ in ex.order_by], cols) if ex.order_by else []
            order_pairs = list(zip(order_vals, [d for _, d in ex.order_by]))
            funcs = []
            for w in ex.funcs:
                argv = comp.run(list(w.args), cols) if w.args else []
                if w.default is not None:
                    argv = argv + comp.run([w.default], cols)
                funcs.append((w, argv))
            cols = cols + window_cols(part_vals, order_pairs, funcs, valid)
            fts = fts + [w.ft for w in ex.funcs]
        elif isinstance(ex, Aggregation):
            garg_exprs = []
            for a in ex.aggs:
                garg_exprs.extend(a.args)
            gvals = comp.run(list(ex.group_by), cols) if ex.group_by else []
            avals = comp.run(list(garg_exprs), cols) if garg_exprs else []
            aggs = []
            k = 0
            for a in ex.aggs:
                aggs.append((a, avals[k : k + len(a.args)]))
                k += len(a.args)
            new_cols: list[CompVal] = []
            if ex.group_by:
                res = group_aggregate(gvals, aggs, valid, group_capacity, merge=ex.merge, small_groups=small_groups, stream=ex.stream)
                state.group_overflow = state.group_overflow | res.overflow
                state.note_group(res.need)
                for (a, av), st in zip(aggs, res.states):
                    new_cols.extend(_agg_result_cols(a, av, st, res.group_valid, ex.partial))
                new_cols.extend(_gather(gvals, res.group_rep))
                valid = res.group_valid
            else:
                states, s_ovf = scalar_aggregate(aggs, valid, merge=ex.merge, salt=group_capacity)
                state.group_overflow = state.group_overflow | s_ovf
                ones = jnp.ones(1, bool)
                for (a, av), st in zip(aggs, states):
                    new_cols.extend(_agg_result_cols(a, av, st, ones, ex.partial))
                valid = ones
            cols = new_cols
            fts = ex.output_fts()
        else:
            raise TypeError(f"unsupported executor {ex}")
        state.rows(valid)
        ei += 1

    return cols, valid, fts


def _trace_radix_join(ex, bkeys, pkeys, bvalid, valid, join_capacity, state: _TraceState, unique_joins: bool):
    """Route an eligible Join through the radix-partitioned kernel
    (ops/radix_join.py); None = take the monolithic kernel.  Eligibility
    is decided SHAPE-ONLY — join shape, planner-proven unique build,
    single int-class key word, build/probe capacity ratio — before any
    value work, mirroring the packed-chain gate's contract."""
    from ..ops.radix_join import radix_hash_join, radix_plan

    if not (state.radix_joins and ex.build_unique and unique_joins):
        return None
    if ex.join_type not in ("inner", "left_outer", "semi", "anti"):
        return None
    if len(bkeys) != 1 or len(pkeys) != 1:
        return None
    if not (_single_word(bkeys[0]) and _single_word(pkeys[0])):
        return None
    if bkeys[0].eval_type == "real" or pkeys[0].eval_type == "real":
        return None  # float keys: NaN/-0.0 classes stay on the sort kernel
    plan = radix_plan(bvalid.shape[0], valid.shape[0], join_capacity)
    if plan is None:
        return None
    from ..ops.radix_join import probe_strategy

    mode = probe_strategy(*plan[:3])
    res, escapes = radix_hash_join(
        bkeys, pkeys, bvalid, valid, ex.join_type, join_capacity, plan,
        strategy=mode,
    )
    state.radix_escapes = state.radix_escapes + escapes
    # attribution reports what EXECUTED: the search strategy probes one
    # un-partitioned sorted build table (partitions=1, no escape hatch).
    # Program-level, first-radix-join-wins — the escape counter above
    # still totals across every radix join in the program
    state.radix_meta.setdefault("partitions", 1 if mode == "search" else plan[0])
    state.radix_meta.setdefault("part_cap", plan[1])
    state.radix_meta.setdefault("strategy", mode)
    return res


def _single_word(k: CompVal) -> bool:
    """True when the key normalizes to exactly one sort word (ops/keys.py
    layout: [null_flag, word]) — the joinagg kernel's key contract."""
    from ..ops.keys import sort_key_arrays

    return len(sort_key_arrays(k)) == 2


def _joinagg_pattern(ex, agg, n_probe_cols: int, unique_joins: bool) -> bool:
    """Join(unique build, inner) immediately under GROUP BY probe-key with
    probe-only aggregate arguments — the shape ops/joinagg.py fuses."""
    from ..expr.ir import ColumnRef, ScalarFunc
    from ..ops.joinagg import FUSABLE_AGGS

    if not (ex.join_type == "inner" and ex.build_unique and unique_joins):
        return False
    if len(ex.probe_keys) != 1 or len(ex.build_keys) != 1:
        return False
    if len(agg.group_by) != 1 or agg.group_by[0] != ex.probe_keys[0]:
        return False
    if agg.merge:
        return False

    def probe_only(e) -> bool:
        if isinstance(e, ColumnRef):
            return e.index < n_probe_cols
        if isinstance(e, ScalarFunc):
            return all(probe_only(a) for a in e.args)
        return True

    for d in agg.aggs:
        if d.distinct or d.name not in FUSABLE_AGGS:
            return False
        if not all(probe_only(a) for a in d.args):
            return False
    return True


def _chain_shape(build):
    """[scan, Sel*, Join(inner, unique, single-key, build=[scan, Sel*])]
    -> (outer_execs, inner_join) or None — the 3-table membership shape
    ops/joinagg.py's packed chain collapses (TPC-H Q3)."""
    if not build or not isinstance(build[0], (TableScan, IndexScan)):
        return None
    i = 1
    while i < len(build) and isinstance(build[i], Selection):
        i += 1
    if i != len(build) - 1 or not isinstance(build[i], Join):
        return None
    j = build[i]
    if j.join_type != "inner" or not j.build_unique:
        return None
    if len(j.probe_keys) != 1 or len(j.build_keys) != 1:
        return None
    inner = j.build
    if not inner or not isinstance(inner[0], (TableScan, IndexScan)):
        return None
    if not all(isinstance(e, Selection) for e in inner[1:]):
        return None
    return list(build[:i]), j


def _int_expr(e) -> bool:
    return e.ft.eval_type() == "int"


def _trace_packed_chain(ex, agg, comp, cols, valid, batches, cursor, group_capacity, join_capacity, state: _TraceState, topn_full, small_groups, unique_joins):
    """Packed-int fast path (ops/joinagg.py packed_join_groupsum): all
    eligibility is checked STATICALLY (expr FieldTypes) before any batch is
    consumed, so returning None never double-consumes a scan."""
    from ..ops.joinagg import _PACKED_AGGS, membership_chain, packed_join_groupsum

    for d in agg.aggs:
        if d.name not in _PACKED_AGGS or d.distinct:
            return None
        for a in d.args:
            if a.ft.eval_type() not in ("int", "decimal"):
                return None
    pk_e, bk_e = ex.probe_keys[0], ex.build_keys[0]
    if not _int_expr(pk_e) or not _int_expr(bk_e):
        return None
    if pk_e.ft.is_unsigned() != bk_e.ft.is_unsigned():
        raise TypeError("join key signedness mismatch (insert casts)")
    chain = _chain_shape(ex.build)
    simple = all(isinstance(e, Selection) for e in ex.build[1:]) and isinstance(ex.build[0], (TableScan, IndexScan))
    if chain is not None:
        outer_execs, ij = chain
        if not (_int_expr(ij.probe_keys[0]) and _int_expr(ij.build_keys[0])):
            return None
        if ij.probe_keys[0].ft.is_unsigned() != ij.build_keys[0].ft.is_unsigned():
            raise TypeError("join key signedness mismatch (insert casts)")
        # the next join's key must come from the OUTER scan's schema
        from ..expr.ir import ColumnRef, ScalarFunc

        outer_w = len(outer_execs[0].columns)

        def within(e, w):
            if isinstance(e, ColumnRef):
                return e.index < w
            if isinstance(e, ScalarFunc):
                return all(within(x, w) for x in e.args)
            return True

        if not within(bk_e, outer_w) or not within(ij.probe_keys[0], outer_w):
            return None
    elif not simple:
        return None

    # compile probe-side agg args (probe cols only — no consumption)
    garg_exprs = []
    for a in agg.aggs:
        garg_exprs.extend(a.args)
    avals = comp.run(list(garg_exprs), cols) if garg_exprs else []
    if any(a.value.ndim != 1 or a.raw is not None for a in avals):
        return None
    if len({id(a.null) for a in avals}) > 8:
        return None
    pkv = comp.run([pk_e], cols)[0]
    probe_ok = valid & ~pkv.null

    if chain is not None:
        outer_execs, ij = chain
        ocols, ovalid, ofts = _run_pipeline(outer_execs, batches, cursor, group_capacity, join_capacity, state, topn_full, small_groups, unique_joins)
        icols, ivalid, ifts = _run_pipeline(list(ij.build), batches, cursor, group_capacity, join_capacity, state, topn_full, small_groups, unique_joins)
        ocomp, icomp = ExprCompiler(ofts), ExprCompiler(ifts)
        okey = ocomp.run([ij.probe_keys[0]], ocols)[0]
        ckey = icomp.run([ij.build_keys[0]], icols)[0]
        payload = ocomp.run([bk_e], ocols)[0]
        o_ok = ovalid & ~okey.null & ~payload.null
        i_ok = ivalid & ~ckey.null
        hay_key, hay_ok, ovf = membership_chain(
            okey.value, o_ok, ckey.value, i_ok, payload.value,
        )
        state.join_overflow = state.join_overflow | ovf
        state.rows(hay_ok)  # inner join rows
    else:
        bcols, bvalid, bfts = _run_pipeline(list(ex.build), batches, cursor, group_capacity, join_capacity, state, topn_full, small_groups, unique_joins)
        bcomp = ExprCompiler(bfts)
        bkv = bcomp.run([bk_e], bcols)[0]
        hay_key = bkv.value
        hay_ok = bvalid & ~bkv.null

    aggs = []
    k = 0
    for a in agg.aggs:
        aggs.append((a, avals[k : k + len(a.args)]))
        k += len(a.args)
    states, group_valid, key_out, ovf, extent_cnt = packed_join_groupsum(
        hay_key, hay_ok, pkv, probe_ok, aggs,
    )
    state.join_overflow = state.join_overflow | ovf
    state.rows(jnp.where(group_valid, extent_cnt, jnp.int64(0)))
    new_cols: list[CompVal] = []
    for (a, av), st in zip(aggs, states):
        new_cols.extend(_agg_result_cols(a, av, st, group_valid, agg.partial))
    new_cols.append(key_out)
    return new_cols, group_valid, agg.output_fts()


def _trace_joinagg(agg, comp, cols, bkeys, pkeys, bvalid, valid, group_capacity, state: _TraceState):
    """Trace the fused join+agg kernel; None when a compiled arg shape is
    ineligible (multi-word value or raw string bytes riding the column)."""
    from ..ops.joinagg import join_stream_agg

    garg_exprs = []
    for a in agg.aggs:
        garg_exprs.extend(a.args)
    avals = comp.run(list(garg_exprs), cols) if garg_exprs else []
    if any(a.value.ndim != 1 or a.raw is not None for a in avals):
        return None
    aggs = []
    k = 0
    for a in agg.aggs:
        aggs.append((a, avals[k : k + len(a.args)]))
        k += len(a.args)
    res, sorted_aggs, group_out, j_ovf, join_rows = join_stream_agg(
        bkeys, pkeys, bvalid, valid, aggs, group_capacity,
    )
    state.join_overflow = state.join_overflow | j_ovf
    state.group_overflow = state.group_overflow | res.overflow
    state.rows(join_rows)
    new_cols: list[CompVal] = []
    for (a, av_s), st in zip(sorted_aggs, res.states):
        new_cols.extend(_agg_result_cols(a, av_s, st, res.group_valid, agg.partial))
    new_cols.extend(_gather([group_out], res.group_rep))
    return new_cols, res.group_valid, agg.output_fts()


def _check_join_key_types(pkeys: list[CompVal], bkeys: list[CompVal]):
    """Join keys must normalize to identical sort-key layouts; the planner
    is responsible for inserting casts (ref: hash join key unification in
    pkg/planner/core — e.g. decimal keys are brought to one scale)."""
    assert len(pkeys) == len(bkeys), "join key arity mismatch"
    for p, b in zip(pkeys, bkeys):
        pe, be = p.eval_type, b.eval_type
        if pe != be:
            raise TypeError(f"join key class mismatch: {pe} vs {be} (insert casts)")
        if pe == "decimal" and max(p.ft.decimal, 0) != max(b.ft.decimal, 0):
            raise TypeError("join key decimal scale mismatch (insert casts)")
        if pe == "int" and p.ft.is_unsigned() != b.ft.is_unsigned():
            raise TypeError("join key signedness mismatch (insert casts)")


def _pack_cols(cols: list[CompVal]) -> list[tuple]:
    """CompVals -> the program's packed output tuples: (value, null) per
    column, raw string bytes + lengths riding along when present."""
    packed = []
    for c in cols:
        if c.raw is not None:
            packed.append((c.value, c.null, c.raw[0], c.raw[1]))
        else:
            packed.append((c.value, c.null))
    return packed


def build_program(
    dag: DAGRequest,
    capacities,
    group_capacity: int = DEFAULT_GROUP_CAPACITY,
    join_capacity: int | None = None,
    topn_full: bool = False,
    small_groups: int | None = None,
    unique_joins: bool = True,
    summaries: bool = True,
    vmap_batch: int | None = None,
    mesh_lanes: int | None = None,
    mesh_devices: int | None = None,
    mesh_kind: str | None = None,
    radix_joins: bool = True,
) -> CompiledDAG:
    """Compile the whole DAG tree (probe pipeline + all join build
    pipelines) into one fused XLA program over a tuple of device batches.

    vmap_batch=B builds the REGION-BATCHED variant: the first (probe) batch
    carries a leading region axis of size B (chunk.device
    to_stacked_device_batch) and the program vmaps over it, so B regions
    execute in ONE XLA launch — the batch-coprocessor analog of TiFlash
    serving all of a store's regions from one request
    (ref: copr/batch_coprocessor.go). Join build sides arriving as broadcast
    aux batches are shared across regions (in_axes=None), exactly like the
    broadcast join operand every region task carries. All outputs (packed
    columns, valid, n_rows, the overflow flags, ex_rows) gain a leading
    region axis; overflow is therefore PER REGION and the driver can retry
    only the lanes that overflowed.

    mesh_lanes=R builds the MESH variant (the dispatch planner's top tier):
    the region-stacked batch additionally SHARDS its leading axis over a
    `mesh_devices`-wide 1-D device mesh under shard_map, each device vmaps
    the per-region program over its local lanes, and the per-region results
    merge ON DEVICE per `mesh_kind` — partial aggregate states psum/pmin/
    pmax-reduced over the region axis ("scalar"), group-state tables
    all_gathered and re-aggregated in merge mode ("group"), or top-k
    candidates all_gathered and re-topped ("topn") — so the program returns
    ONE merged result instead of R per-region partials (SURVEY §3.1/§5).
    Mesh outputs: (merged packed cols, merged valid, per-lane ex_rows
    [R, n_exec], overflow scalar); overflow is GLOBAL — the driver falls
    back to the vmapped tier, whose per-lane ladder takes over."""
    if isinstance(capacities, int):
        capacities = (capacities,)
    capacities = tuple(capacities)
    n_scans = len(collect_scans(dag.executors))
    assert len(capacities) == n_scans, f"need {n_scans} batch capacities, got {len(capacities)}"
    join_capacity = join_capacity or max(capacities)

    radix_info: dict = {}

    def program(*batches):
        state = _TraceState(summaries)
        state.radix_joins = radix_joins
        cursor = [0]
        cols, valid, _ = _run_pipeline(dag.executors, batches, cursor, group_capacity, join_capacity, state, topn_full, small_groups, unique_joins, out_offsets=dag.output_offsets)
        packed = _pack_cols([cols[i] for i in dag.output_offsets])
        n_out = valid.sum()
        # summaries off: no constant/empty-shaped stand-in — both a
        # 0-length output and a folded-constant output have SIGSEGV'd the
        # tunneled TPU compiler; reuse the (data-dependent) row count
        ex = jnp.stack(state.ex_rows) if state.ex_rows else n_out[None].astype(jnp.int64)
        radix_info.update(state.radix_meta)  # trace-time side channel
        # the flag tuple carries the capacity NEED hints and the radix
        # escape count so the retry driver / attribution read them in the
        # SAME device fetch as the overflow flags (no extra round-trip)
        ovfs = (state.group_overflow, state.join_overflow, state.topn_overflow,
                state.group_need, state.join_need, state.radix_escapes)
        return packed, valid, n_out, ovfs, ex

    if mesh_lanes is not None:
        jit_fn = _build_mesh_fn(dag, program, n_scans, mesh_lanes,
                                mesh_devices or 1, mesh_kind, group_capacity)
    elif vmap_batch is not None:
        # region axis on the probe batch only; aux/build batches broadcast
        jit_fn = jax.jit(jax.vmap(program, in_axes=(0,) + (None,) * (n_scans - 1)))
    else:
        jit_fn = jax.jit(program)
    return CompiledDAG(jit_fn, dag.output_fts(), capacities, group_capacity, join_capacity,
                       radix_info=radix_info)


def _build_mesh_fn(dag: DAGRequest, program, n_scans: int, lanes: int,
                   n_devices: int, kind: str, group_capacity: int):
    """shard_map wrapper: vmap the per-region program over each device's
    local lanes, then merge the per-region results on device (psum of
    partial states / merge-mode re-group / re-top-k) — the mesh tier's
    program body. `lanes` must divide over `n_devices` (the store pads the
    region axis with empty lanes)."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.compat import shard_map
    from ..parallel.mesh import REGION_AXIS, merge_packed_states, region_mesh

    assert kind in ("scalar", "group", "topn"), f"unknown mesh kind {kind!r}"
    assert lanes % n_devices == 0, "mesh lanes must divide over the devices"
    mesh = region_mesh(n_devices)
    last = dag.executors[-1]
    out_fts = dag.output_fts()

    def device_fn(local, *aux):
        packed, valid, _n, ovfs, ex = jax.vmap(lambda b: program(b, *aux))(local)
        local_ovf = ovfs[0].any() | ovfs[1].any() | ovfs[2].any()
        # radix escape total over the region axis (join_radix attribution
        # — the mesh tier reports it like the other tiers)
        radix_esc = jax.lax.psum(ovfs[5].sum(), REGION_AXIS)
        if kind == "scalar":
            # the north-star collective: partial states psum/pmin/pmax-
            # reduced over the region axis (parallel/mesh.py merge seam)
            merged = [tuple(t) for t in merge_packed_states(list(last.aggs), packed)]
            mvalid = jnp.ones(1, bool)
            m_ovf = jnp.bool_(False)
        else:
            cols, gvalid = _gather_mesh_outputs(packed, valid, out_fts)
            if kind == "group":
                out_cols, mvalid, m_ovf = _mesh_merge_group(
                    last, out_fts, cols, gvalid, group_capacity)
            else:
                out_cols, mvalid, m_ovf = _mesh_merge_topn(last, out_fts, cols, gvalid)
            merged = _pack_cols(out_cols)
        ovf = jax.lax.pmax((local_ovf | m_ovf).astype(jnp.int32), REGION_AXIS) > 0
        return merged, mvalid, ex, ovf, radix_esc

    fn = shard_map(
        device_fn,
        mesh=mesh,
        # prefix specs: the whole stacked probe batch shards its leading
        # region axis; aux (join build) batches replicate to every device
        in_specs=(P(REGION_AXIS),) + (P(),) * (n_scans - 1),
        # merged cols / valid / overflow / escape count are replicated in
        # fact (psum / all_gather-then-identical-local-work) but not
        # statically inferrable by the vma check; ex_rows keep their
        # region axis
        out_specs=(P(), P(), P(REGION_AXIS), P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def _gather_mesh_outputs(packed, valid, out_fts):
    """Flatten the vmapped per-lane outputs [R_local, L, ...] to rows and
    all_gather them over the mesh: every device ends with the SAME
    [R_total*L] row block (device-major == region stack == task order), so
    the merge stage below computes a replicated result with no further
    communication. Raw string bytes ride whole — byte-exact, no packed-word
    truncation."""
    from ..parallel.mesh import REGION_AXIS

    cols = []
    for out, ft in zip(packed, out_fts):
        flat = []
        for a in out:
            rows = a.reshape((-1,) + a.shape[2:])
            g = jax.lax.all_gather(rows, REGION_AXIS)
            flat.append(g.reshape((-1,) + g.shape[2:]))
        if len(out) == 4:
            cols.append(CompVal(flat[0], flat[1], ft, raw=(flat[2], flat[3])))
        else:
            cols.append(CompVal(flat[0], flat[1], ft))
    gvalid = jax.lax.all_gather(valid.reshape(-1), REGION_AXIS).reshape(-1)
    return cols, gvalid


def _mesh_merge_group(agg, state_fts, cols, valid, group_capacity: int):
    """Device-side merge of the gathered per-region group tables: the root
    Final merge's Partial2 re-group (root.py _merge_aggregation, partial
    output) traced INTO the mesh program — the output schema is the push
    DAG's partial schema again, so one merged table per store replaces R
    per-region tables while the root's Final pass runs unchanged."""
    from dataclasses import replace as _replace

    from ..distsql.root import _merge_aggregation

    p2 = _replace(_merge_aggregation(agg), partial=True)
    comp = ExprCompiler(state_fts)
    gvals = comp.run(list(p2.group_by), cols)
    garg_exprs = [a for d in p2.aggs for a in d.args]
    avals = comp.run(garg_exprs, cols) if garg_exprs else []
    aggs = []
    k = 0
    for d in p2.aggs:
        aggs.append((d, avals[k: k + len(d.args)]))
        k += len(d.args)
    res = group_aggregate(gvals, aggs, valid, group_capacity, merge=True)
    new_cols: list[CompVal] = []
    for (d, av), st in zip(aggs, res.states):
        new_cols.extend(_agg_result_cols(d, av, st, res.group_valid, True))
    new_cols.extend(_gather(gvals, res.group_rep))
    return new_cols, res.group_valid, res.overflow


def _mesh_merge_topn(ex, fts, cols, valid):
    """Device-side re-top-k over the gathered per-region candidates
    (global top-k ⊆ union of per-region top-k): the order expressions
    recompute over the candidate rows — TopN preserves its input schema,
    so the same exprs apply. full_sort: the candidate block is tiny
    (R*k rows) and the exact variant never overflows."""
    comp = ExprCompiler(fts)
    order_vals = comp.run([e for e, _ in ex.order_by], cols)
    by = list(zip(order_vals, [d for _, d in ex.order_by]))
    idx, out_valid, _ovf = topn(by, valid, ex.limit, full_sort=True)
    return _gather(cols, idx), out_valid, jnp.bool_(False)


def _agg_result_cols(a, av: list[CompVal], st, group_valid, partial: bool) -> list[CompVal]:
    """One aggregate's output columns from its states.

    GatherState (first_row any mode, string min/max): gather the value
    column — raw string bytes ride along — from the original rows; the wire
    state for partial first_row is [has, value] (expr/agg.py schema)."""
    if isinstance(st, GatherState):
        has = st.has & group_valid
        g = _gather([av[-1]], st.idx)[0]
        null = g.null | ~has
        out = []
        if a.name == "first_row" and partial:
            out.append(CompVal(has.astype(jnp.int64), jnp.zeros(has.shape, bool), a.partial_fts()[0]))
        out.append(CompVal(g.value, null, a.ft, raw=g.raw))
        return out
    fts = a.partial_fts()
    if partial:
        return [CompVal(v, nl, ft) for (v, nl), ft in zip(st, fts)]
    v, nl = finalize_agg(a, st, group_valid)
    return [CompVal(v, nl, a.ft)]


class ProgramCache:
    """Fingerprint -> CompiledDAG (ref: coprocessor cache keying).

    The key includes the region-batch size (`vmap_batch`): a vmapped
    program is specialized to its leading axis, so a new batch shape is an
    honest recompile, not a hit — `stats()` exposes per-instance
    compiles/hits so tests can assert "one compile + N hits per batch
    shape" (the launch-count regression guard).

    Compiles are single-flight per key: pool-tier region tasks all need
    the same push program on a cold cache, and without coordination each
    thread that misses compiles its own copy (correct but N× the compile
    cost, and the compiles/hits counters — the regression guard — become
    timing-dependent). The first thread to miss claims the key; racers
    wait on its event and land as hits."""

    def __init__(self):
        import threading

        # _cache is deliberately unguarded: dict get/set are GIL-atomic
        self._cache: dict = {}
        self._stats_mu = threading.Lock()  # pool threads share one cache
        self.compiles = 0  # guarded_by: _stats_mu
        self.hits = 0  # guarded_by: _stats_mu
        self._inflight: dict = {}  # key -> Event, guarded_by: _stats_mu

    def get(
        self,
        dag: DAGRequest,
        capacities,
        group_capacity: int = DEFAULT_GROUP_CAPACITY,
        join_capacity: int | None = None,
        topn_full: bool = False,
        small_groups: int | None = None,
        unique_joins: bool = True,
        vmap_batch: int | None = None,
        mesh_lanes: int | None = None,
        mesh_devices: int | None = None,
        mesh_kind: str | None = None,
        radix_joins: bool = True,
    ) -> CompiledDAG:
        return self.get_info(dag, capacities, group_capacity, join_capacity,
                             topn_full, small_groups, unique_joins, vmap_batch,
                             mesh_lanes, mesh_devices, mesh_kind, radix_joins)[0]

    def get_info(
        self,
        dag: DAGRequest,
        capacities,
        group_capacity: int = DEFAULT_GROUP_CAPACITY,
        join_capacity: int | None = None,
        topn_full: bool = False,
        small_groups: int | None = None,
        unique_joins: bool = True,
        vmap_batch: int | None = None,
        mesh_lanes: int | None = None,
        mesh_devices: int | None = None,
        mesh_kind: str | None = None,
        radix_joins: bool = True,
    ) -> tuple:
        """(program, cache_hit, compile_ns) — the attribution triple the
        exec summaries and the TRACE span tree surface (ref: the
        coprocessor-cache hit flag in copr responses)."""
        import time as _t

        if isinstance(capacities, int):
            capacities = (capacities,)
        capacities = tuple(capacities)
        from ..ops.dense_pallas import pallas_mode
        from ..util import metrics, tracing

        # pallas mode is read at TRACE time (env + backend): a program
        # traced under one mode must not serve another (mismatched
        # buffer counts at execution)
        # mesh programs are specialized to their lane count AND device
        # count (shard_map shapes both into the trace); mesh_kind is
        # derivable from the fingerprint but cheap to carry explicitly
        key = (dag.fingerprint(), capacities, group_capacity, join_capacity, topn_full, small_groups, unique_joins, vmap_batch, pallas_mode(), mesh_lanes, mesh_devices, mesh_kind, radix_joins)
        import threading

        while True:
            prog = self._cache.get(key)
            if prog is not None:
                with self._stats_mu:
                    self.hits += 1
                metrics.PROGRAM_CACHE_HITS.inc()
                with tracing.span("exec.program", cache_hit=True):
                    pass
                return prog, True, 0
            with self._stats_mu:
                ev = self._inflight.get(key)
                if ev is None:
                    self._inflight[key] = threading.Event()
                    break  # this thread owns the compile
            # another thread is compiling this key: wait, then re-read the
            # cache (if its compile raised, the next waiter claims the key)
            ev.wait()
        try:
            with tracing.span("exec.program", cache_hit=False) as sp:
                with self._stats_mu:
                    self.compiles += 1
                metrics.PROGRAM_COMPILES.inc()
                t0 = _t.perf_counter_ns()
                prog = build_program(dag, capacities, group_capacity, join_capacity, topn_full, small_groups, unique_joins, vmap_batch=vmap_batch,
                                     mesh_lanes=mesh_lanes, mesh_devices=mesh_devices, mesh_kind=mesh_kind, radix_joins=radix_joins)
                compile_ns = _t.perf_counter_ns() - t0
                metrics.PROGRAM_COMPILE_DURATION.observe(compile_ns / 1e9)
                if sp is not None:
                    sp.set("compile_ns", compile_ns)
                    if vmap_batch is not None:
                        sp.set("batch_size", vmap_batch)
                    if mesh_lanes is not None:
                        sp.set("mesh_lanes", mesh_lanes)
            self._cache[key] = prog
            metrics.PROGRAM_CACHE_ENTRIES.set(len(self._cache))
        finally:
            with self._stats_mu:
                self._inflight.pop(key).set()
        return prog, False, compile_ns

    def stats(self):
        with self._stats_mu:
            return {"entries": len(self._cache), "compiles": self.compiles, "hits": self.hits}
