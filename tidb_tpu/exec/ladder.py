"""Shape-stable capacity ladder (ISSUE 13 tentpole #2).

Every data-dependent capacity knob (group table, join out-capacity /
radix escape buffer) used to grow multiplicatively from a per-query
seed — `max(n // 4, 128)`-style — so two queries of slightly different
sizes, or one query's overflow retry, each traced and compiled a brand
new XLA program.  Sort-heavy join programs compile in minutes on the
tunneled TPU backend, which made the retry ladder the dominant cost of
the first q3-class join (ROADMAP: 131s compile, overflow assert in
round 3).

The fix is a SMALL geometric rung set: every requested capacity snaps UP
to the nearest power-of-two rung >= RUNG_BASE, and overflow retries move
rung to rung instead of multiplying the seed.  Capacities then take a
handful of distinct values per batch shape, so ProgramCache keys
collapse onto a precompilable set and a retry re-dispatches an
already-compiled program (asserted via ProgramCache stats in
tests/test_radix_join.py).  The executor pairs the ladder with the
programs' NEED HINTS (exec/builder.py: true group count / join fan-out
riding next to the overflow flags) so a retry jumps straight to the
correct rung — one recompile-free re-dispatch instead of a 4x-growth
walk (the "no host round-trip wasted" half of the contract: the need
travels in the same device fetch as the overflow flag).
"""

from __future__ import annotations

RUNG_BASE = 64  # smallest rung; DEFAULT_GROUP_CAPACITY (4096) is on-ladder
RUNG_MAX = 1 << 30  # sanity ceiling — beyond this the spill path owns it


def rung_for(n: int) -> int:
    """Smallest power-of-two rung >= max(n, RUNG_BASE)."""
    c = RUNG_BASE
    while c < n and c < RUNG_MAX:
        c *= 2
    return c


def next_rung(c: int, factor: int = 4) -> int:
    """The retry rung when no need hint is available: one geometric step
    (x4 keeps the historical growth rate, expressed in rungs)."""
    return rung_for(max(c, RUNG_BASE) * factor)


def overflow_step(gc: int, jc: int, g_ovf: bool, j_ovf: bool,
                  g_need: int, j_need: int) -> tuple:
    """ONE overflow-retry policy step — shared by the executor driver and
    both bench loops so the bench certifies the policy production runs
    (BENCH_JOIN's retry_recompiles_after_warm number is only meaningful
    if the loops agree).  Returns (gc, jc, drop_join_hints):

      * a need hint ABOVE the current rung is a pure capacity miss — jump
        straight to its rung and keep every fast-path hint;
      * otherwise (violated unique-build hint, hash collision, dense-table
        stop) capacity growth alone cannot help: step the ladder — which
        also re-salts — and, for the join knob, tell the caller to drop
        the unique-build/radix hints in the same retry.
    """
    if g_ovf:
        # at the RUNG_MAX ceiling this no longer moves and the retries
        # exhaust into OverflowRetryError — the spill path owns it there
        gc = rung_for(g_need) if g_need > gc else next_rung(gc)
    drop_join_hints = False
    if j_ovf:
        hinted = rung_for(j_need) if j_need > jc else 0
        if hinted > jc:
            jc = hinted
        else:
            # no rung can move (hintless, hint <= rung, or the RUNG_MAX
            # ceiling saturated the jump): the retry must still CHANGE
            # the program — drop the hints and step (re-salt)
            drop_join_hints = True
            jc = next_rung(jc)
    return gc, jc, drop_join_hints


def rungs_up_to(n: int) -> list[int]:
    """Every rung from RUNG_BASE through rung_for(n) — the precompile set
    bench.py warms so overflow retries never trace a new program."""
    out = [RUNG_BASE]
    while out[-1] < n and out[-1] < RUNG_MAX:
        out.append(out[-1] * 2)
    return out
