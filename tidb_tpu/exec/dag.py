"""The DAG request IR — this framework's `tipb.DAGRequest`.

Mirrors the executor-list shape of the reference wire format
(ref: pingcap/tipb DAGRequest; built by pkg/planner/core/plan_to_pb.go and
consumed by unistore/cophandler/cop_handler.go:319 buildDAG): a scan-first
pipeline of executors plus output offsets and encode options. Everything is
immutable and fingerprintable so compiled XLA programs cache per plan shape
(ref: the coprocessor-cache keying idea, pkg/store/copr/coprocessor_cache.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..expr.agg import AggDesc
from ..expr.ir import Expr
from ..types import FieldType


@dataclass(frozen=True)
class ColumnInfo:
    """(ref: tipb.ColumnInfo — column id + type as the scan emits it;
    `default` mirrors tipb's default_val: rows written before an ADD
    COLUMN have no bytes for the column, and the scan fills this origin
    default instead of NULL)."""

    col_id: int
    ft: FieldType
    default: object = None  # Datum | None

    def fingerprint(self):
        d = None if self.default is None else repr(self.default)
        return (self.col_id, self.ft.tp, int(self.ft.flag), self.ft.flen, self.ft.decimal, d)


@dataclass(frozen=True)
class TableScan:
    """(ref: tipb.TableScan; executor mpp_exec.go:110 tableScanExec)."""

    table_id: int
    columns: tuple  # tuple[ColumnInfo, ...]
    desc: bool = False

    def fingerprint(self):
        return ("scan", self.table_id, self.desc) + tuple(c.fingerprint() for c in self.columns)


@dataclass(frozen=True)
class IndexScan:
    """(ref: tipb.IndexScan; executor mpp_exec.go:255 indexScanExec).

    Reads index entries `t{tid}_i{iid}{vals...}{handle}` instead of rows;
    output schema is the stored entry layout: the indexed columns in index
    order, then the int64 handle (col_id -1). A covering query runs
    entirely off this scan; an index lookup uses it to produce handles for
    a second table read."""

    table_id: int
    index_id: int
    columns: tuple  # tuple[ColumnInfo, ...] — index cols then handle(-1)
    desc: bool = False

    def fingerprint(self):
        return ("iscan", self.table_id, self.index_id, self.desc) + tuple(
            c.fingerprint() for c in self.columns
        )


@dataclass(frozen=True)
class Selection:
    """(ref: tipb.Selection; mpp_exec.go:1121 selExec)."""

    conditions: tuple  # tuple[Expr, ...]

    def fingerprint(self):
        return ("sel",) + tuple(c.fingerprint() for c in self.conditions)


@dataclass(frozen=True)
class Projection:
    """(ref: tipb.Projection; mpp_exec.go:1157 projExec)."""

    exprs: tuple

    def fingerprint(self):
        return ("proj",) + tuple(e.fingerprint() for e in self.exprs)


@dataclass(frozen=True)
class Aggregation:
    """(ref: tipb.Aggregation; mpp_exec.go:999 aggExec). Output schema is
    [agg results..., group-by keys...] matching the reference's layout.

    `stream` marks input already sorted by group keys (StreamAgg): the
    boundary-scan kernel runs — no sort, no hash (ops/aggregate.py
    _group_aggregate_stream; ref: agg_stream_executor.go).
    `partial` True emits partial states instead of finalized values.
    """

    group_by: tuple  # tuple[Expr, ...]
    aggs: tuple  # tuple[AggDesc, ...]
    stream: bool = False
    partial: bool = False
    merge: bool = False  # input rows are partial states (Final/Partial2)

    def fingerprint(self):
        return (
            ("agg", self.stream, self.partial, self.merge)
            + tuple(g.fingerprint() for g in self.group_by)
            + tuple(a.fingerprint() for a in self.aggs)
        )

    def output_fts(self) -> list[FieldType]:
        out = []
        for a in self.aggs:
            if self.partial:
                out.extend(a.partial_fts())
            else:
                out.append(a.ft)
        out.extend(g.ft for g in self.group_by)
        return out


@dataclass(frozen=True)
class Join:
    """Equi hash join (ref: tipb.Join; unistore/cophandler/mpp_exec.go:844
    joinExec; root-side design pkg/executor/join/hash_join_v2.go:658).

    The enclosing pipeline is the PROBE side (preserved by left_outer, like
    the reference's probe stream); `build` is a scan-first sub-pipeline for
    the build side — its scans consume the request's broadcast aux batches
    (the TiFlash broadcast-exchange analog, mpp_exec.go:669 Broadcast mode).
    Output schema: probe columns ++ build columns (semi/anti: probe only).

    Key expressions must agree in eval class/scale/signedness between the
    two sides — the planner inserts casts, as the reference's hash join
    requires identical key types (join key normalization in planner core).
    """

    build: tuple  # tuple[executor, ...] — scan-first build pipeline
    probe_keys: tuple  # tuple[Expr, ...] over the probe schema
    build_keys: tuple  # tuple[Expr, ...] over the build schema
    join_type: str = "inner"  # inner | left_outer | semi | anti
    # planner-proven: build keys are unique per build row (PK handle or a
    # unique index covering exactly the key columns). The kernel then skips
    # the fan-out expansion pass (output keeps the probe layout); runtime-
    # verified — a fan-out > 1 raises join overflow and the driver retries
    # with the general kernel (ref: hash_join_v2.go one-row-per-key layout).
    build_unique: bool = False

    def __post_init__(self):
        if self.join_type not in ("inner", "left_outer", "semi", "anti"):
            raise ValueError(f"unknown join type {self.join_type!r}")
        if len(self.probe_keys) != len(self.build_keys):
            raise ValueError("join key arity mismatch")

    def fingerprint(self):
        return (
            ("join", self.join_type, self.build_unique)
            + tuple(e.fingerprint() for e in self.build)
            + ("pk",) + tuple(k.fingerprint() for k in self.probe_keys)
            + ("bk",) + tuple(k.fingerprint() for k in self.build_keys)
        )


@dataclass(frozen=True)
class WinDesc:
    """One window function (ref: tipb.WindowFunc within tipb.Window;
    semantics pkg/executor/aggfuncs/func_{rank,row_number,lead_lag,...}.go).

    `offset` carries the static integer parameter: LEAD/LAG offset,
    NTILE bucket count, NTH_VALUE position. `default` is the lowered
    LEAD/LAG default expression (a Const) or None (NULL)."""

    name: str
    args: tuple  # tuple[Expr, ...] — value argument(s)
    ft: FieldType
    offset: int = 1
    default: object = None  # Expr | None

    def fingerprint(self):
        d = self.default.fingerprint() if self.default is not None else None
        return ("win", self.name, self.offset, d) + tuple(a.fingerprint() for a in self.args)


@dataclass(frozen=True)
class Window:
    """(ref: tipb.Window; pkg/executor/window.go WindowExec). Output schema:
    input columns ++ one result column per function — matching the
    reference's appended window result columns (plan_to_pb.go:663)."""

    partition_by: tuple  # tuple[Expr, ...]
    order_by: tuple  # tuple[(Expr, desc: bool), ...]
    funcs: tuple  # tuple[WinDesc, ...]

    def fingerprint(self):
        return (
            ("window",)
            + tuple(e.fingerprint() for e in self.partition_by)
            + ("ord",) + tuple((e.fingerprint(), d) for e, d in self.order_by)
            + ("fn",) + tuple(f.fingerprint() for f in self.funcs)
        )


@dataclass(frozen=True)
class TopN:
    """(ref: tipb.TopN; mpp_exec.go:526 topNExec)."""

    order_by: tuple  # tuple[(Expr, desc: bool), ...]
    limit: int

    def fingerprint(self):
        return ("topn", self.limit) + tuple((e.fingerprint(), d) for e, d in self.order_by)


@dataclass(frozen=True)
class Sort:
    """Full sort, no bound (ref: tipb.Sort with IsPartialSort=false;
    root executor pkg/executor/sortexec/sort.go — the external merge sort).
    Split shape: each region sorts its rows, the root re-sorts the
    concatenation (the k-way merge specialization can land later —
    correctness first: EVERY row comes back, in order)."""

    order_by: tuple  # tuple[(Expr, desc: bool), ...]

    def fingerprint(self):
        return ("sort",) + tuple((e.fingerprint(), d) for e, d in self.order_by)


@dataclass(frozen=True)
class Limit:
    """(ref: tipb.Limit; mpp_exec.go:397 limitExec)."""

    limit: int

    def fingerprint(self):
        return ("limit", self.limit)


@dataclass(frozen=True)
class DAGRequest:
    """Executor pipeline, scan first (ref: tipb.DAGRequest.Executors).

    output_offsets selects/permutes the final executor's columns
    (ref: cop_handler.go output offsets handling :249-267).
    """

    executors: tuple
    output_offsets: tuple
    time_zone: str = "UTC"
    flags: int = 0

    def fingerprint(self):
        return tuple(e.fingerprint() for e in self.executors) + ("out",) + tuple(self.output_offsets)

    def scan(self):
        assert isinstance(self.executors[0], (TableScan, IndexScan))
        return self.executors[0]

    def output_fts(self) -> list[FieldType]:
        fts = current_schema_fts(self.executors)
        return [fts[i] for i in self.output_offsets]


def current_schema_fts(executors) -> list[FieldType]:
    """Schema of the last executor's output."""
    fts: list[FieldType] = []
    for ex in executors:
        if isinstance(ex, (TableScan, IndexScan)):
            fts = [c.ft for c in ex.columns]
        elif isinstance(ex, (Selection, Limit, TopN, Sort)):
            pass  # schema unchanged
        elif isinstance(ex, Projection):
            fts = [e.ft for e in ex.exprs]
        elif isinstance(ex, Aggregation):
            fts = ex.output_fts()
        elif isinstance(ex, Window):
            fts = fts + [f.ft for f in ex.funcs]
        elif isinstance(ex, Join):
            if ex.join_type in ("semi", "anti"):
                pass  # probe schema unchanged
            else:
                build_fts = current_schema_fts(ex.build)
                if ex.join_type == "left_outer":
                    build_fts = [f.clone_nullable() for f in build_fts]
                fts = fts + build_fts
        else:
            raise TypeError(f"unknown executor {ex}")
    return fts


def executor_walk(executors) -> list:
    """Executors flattened in execution-summary order: scan first, a Join's
    build pipeline entries before the Join itself — exactly the order the
    fused program appends per-executor row counts."""
    out = [executors[0]]
    for ex in executors[1:]:
        if isinstance(ex, Join):
            out.extend(executor_walk(ex.build))
        out.append(ex)
    return out


def collect_scans(executors) -> list[TableScan]:
    """All TableScans in canonical order: pipeline order, recursing into a
    Join's build side at the Join's position. Device batches (and oracle
    chunks) are supplied in exactly this order."""
    out: list[TableScan] = []
    for ex in executors:
        if isinstance(ex, (TableScan, IndexScan)):
            out.append(ex)
        elif isinstance(ex, Join):
            out.extend(collect_scans(ex.build))
    return out
