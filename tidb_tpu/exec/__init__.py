from .dag import (
    Aggregation,
    DAGRequest,
    Limit,
    Projection,
    Selection,
    Sort,
    TableScan,
    TopN,
    ColumnInfo,
    Join,
    collect_scans,
)
from .builder import build_program, ProgramCache, CompiledDAG
from .executor import OverflowRetryError, run_dag_on_chunk, run_dag_on_chunks, run_dag_reference

__all__ = [
    "Aggregation",
    "DAGRequest",
    "Limit",
    "Projection",
    "Selection",
    "Sort",
    "TableScan",
    "TopN",
    "ColumnInfo",
    "Join",
    "collect_scans",
    "build_program",
    "ProgramCache",
    "CompiledDAG",
    "run_dag_on_chunk",
    "run_dag_on_chunks",
    "OverflowRetryError",
    "run_dag_reference",
]
