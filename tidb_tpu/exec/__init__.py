from .dag import (
    Aggregation,
    DAGRequest,
    Limit,
    Projection,
    Selection,
    TableScan,
    TopN,
    ColumnInfo,
)
from .builder import build_program, ProgramCache, CompiledDAG
from .executor import run_dag_on_chunk, run_dag_reference

__all__ = [
    "Aggregation",
    "DAGRequest",
    "Limit",
    "Projection",
    "Selection",
    "TableScan",
    "TopN",
    "ColumnInfo",
    "build_program",
    "ProgramCache",
    "CompiledDAG",
    "run_dag_on_chunk",
    "run_dag_reference",
]
