"""Host-side DAG drivers.

run_dag_on_chunk: the device path — pad a host Chunk into a DeviceBatch, run
the fused program, decode outputs back to a host Chunk. Handles the overflow
contract by retrying with doubled group capacity (recompile, cached).

run_dag_reference: the Go-semantics oracle — interprets the same DAG row by
row with RefEvaluator (ref: unistore/cophandler/mpp_exec.go executors),
used by the parity harness and as the small-data root executor.
"""

from __future__ import annotations

import numpy as np

from ..chunk import Chunk, Column, to_device_batch
from ..expr.agg import AggDesc
from ..expr.eval_ref import RefEvaluator, compare, _truth
from ..types import Datum, DatumKind, FieldType, MyDecimal, MyTime
from .builder import DEFAULT_GROUP_CAPACITY, CompiledDAG, ProgramCache, build_program
from .dag import Aggregation, DAGRequest, Limit, Projection, Selection, TableScan, TopN


def _pow2(n: int) -> int:
    c = 1
    while c < n:
        c *= 2
    return c


def decode_outputs(packed, valid, out_fts) -> Chunk:
    valid = np.asarray(valid)
    idx = np.nonzero(valid)[0]
    cols = []
    for ft, out in zip(out_fts, packed):
        if len(out) == 4:  # string: words, null, raw bytes, lengths
            _, null, data, length = out
            null = np.asarray(null)[idx]
            data = np.asarray(data)[idx]
            length = np.asarray(length)[idx]
            offs = np.zeros(len(idx) + 1, np.int64)
            np.cumsum(np.where(null, 0, length), out=offs[1:])
            blob = np.zeros(int(offs[-1]), np.uint8)
            for j in range(len(idx)):
                if not null[j]:
                    blob[offs[j] : offs[j + 1]] = data[j, : length[j]]
            cols.append(Column(ft, None, null, offs, blob))
        else:
            v, null = out
            v = np.asarray(v)[idx]
            null = np.asarray(null)[idx]
            if ft.is_unsigned() or ft.is_time():
                v = v.view(np.uint64) if v.dtype == np.int64 else v.astype(np.uint64)
            cols.append(Column(ft, v.copy(), null.copy()))
    return Chunk(cols)


# Shared default so repeated executions of the same plan shape reuse the
# compiled XLA program (ref: coprocessor cache amortization).
DEFAULT_PROGRAM_CACHE = ProgramCache()


def drive_program(cache: ProgramCache, dag: DAGRequest, batch, group_capacity: int, max_retries: int = 3):
    """Run the fused program, growing group capacity on overflow
    (the single overflow-retry contract — store and host driver share it).

    Returns (chunk, per-executor produced-row counts, scan first)."""
    gc = group_capacity
    for _ in range(max_retries + 1):
        prog = cache.get(dag, batch.capacity, gc)
        packed, valid, n, overflow, ex_rows = prog.fn(batch)
        if not bool(overflow):
            counts = [int(x) for x in np.asarray(ex_rows)]
            return decode_outputs(packed, valid, prog.out_fts), counts
        gc *= 4  # group/join capacity exceeded: recompile bigger
    raise RuntimeError("DAG overflow not resolved after retries")


def run_dag_on_chunk(
    dag: DAGRequest,
    chunk: Chunk,
    cache: ProgramCache | None = None,
    capacity: int | None = None,
    group_capacity: int = DEFAULT_GROUP_CAPACITY,
    max_retries: int = 3,
) -> Chunk:
    cache = cache or DEFAULT_PROGRAM_CACHE
    cap = capacity or _pow2(max(chunk.num_rows(), 1))
    batch = to_device_batch(chunk, capacity=cap)
    return drive_program(cache, dag, batch, group_capacity, max_retries)[0]


# ---------------------------------------------------------------------------
# Reference interpreter (oracle)
# ---------------------------------------------------------------------------

def datum_group_key(d: Datum):
    if d.is_null():
        return (0, None)
    if d.kind == DatumKind.MysqlDecimal:
        return (1, str(d.val.d.normalize()))
    if d.kind in (DatumKind.String, DatumKind.Bytes):
        v = d.val.encode() if isinstance(d.val, str) else bytes(d.val)
        return (1, v)
    if d.kind == DatumKind.MysqlTime:
        return (1, d.val.packed)
    if d.kind in (DatumKind.Float32, DatumKind.Float64):
        return (1, float(d.val) + 0.0)  # -0.0 -> 0.0
    return (1, d.val)


class _RefAgg:
    """One aggregate's accumulator (Complete mode), incl. DISTINCT via a
    seen-set (ref: executor/aggfuncs distinct wrappers) and the BIT_*
    aggregates (ref: aggfuncs/func_bitfuncs.go)."""

    def __init__(self, desc: AggDesc):
        self.d = desc
        self.count = 0
        self.sum = None
        self.extreme = None
        self.first = None
        self.has_first = False
        self.bits = None
        self.seen = set() if desc.distinct else None

    def update(self, args: list[Datum]):
        name = self.d.name
        if self.seen is not None and name in ("count", "sum", "avg"):
            # DISTINCT: rows with any NULL arg are skipped; each distinct
            # arg tuple contributes once
            if any(a.is_null() for a in args):
                return
            key = tuple(datum_group_key(a) for a in args)
            if key in self.seen:
                return
            self.seen.add(key)
        if name == "count":
            if all(not a.is_null() for a in args):
                self.count += 1
            return
        a = args[0]
        if name == "first_row":
            if not self.has_first:
                self.first, self.has_first = a, True
            return
        if a.is_null():
            return
        if name in ("bit_and", "bit_or", "bit_xor"):
            v = int(a.val) & ((1 << 64) - 1)
            if self.bits is None:
                self.bits = v
            elif name == "bit_and":
                self.bits &= v
            elif name == "bit_or":
                self.bits |= v
            else:
                self.bits ^= v
            return
        self.count += 1
        if name in ("sum", "avg"):
            if self.sum is None:
                if a.kind in (DatumKind.Float64, DatumKind.Float32):
                    self.sum = float(a.val)
                elif a.kind == DatumKind.MysqlDecimal:
                    self.sum = a.val
                else:
                    self.sum = MyDecimal(a.val, 0)
            else:
                if isinstance(self.sum, float):
                    self.sum += float(a.val)
                else:
                    self.sum = self.sum + (a.val if a.kind == DatumKind.MysqlDecimal else MyDecimal(a.val, 0))
        elif name in ("min", "max"):
            if self.extreme is None:
                self.extreme = a
            else:
                c = compare(a, self.extreme)
                if (name == "min" and c < 0) or (name == "max" and c > 0):
                    self.extreme = a
        else:
            raise NotImplementedError(name)

    def result(self) -> Datum:
        name = self.d.name
        ft = self.d.ft
        if name == "count":
            return Datum.i64(self.count)
        if name == "first_row":
            return self.first if self.has_first else Datum.NULL
        if name == "sum":
            if self.sum is None:
                return Datum.NULL
            if isinstance(self.sum, float):
                return Datum.f64(self.sum)
            return Datum.dec(self.sum.round(max(ft.decimal, 0)))
        if name == "avg":
            if self.count == 0:
                return Datum.NULL
            if isinstance(self.sum, float):
                return Datum.f64(self.sum / self.count)
            q = self.sum.div(MyDecimal(self.count, 0))
            return Datum.dec(q.round(max(ft.decimal, 0)))
        if name in ("min", "max"):
            return self.extreme if self.extreme is not None else Datum.NULL
        if name in ("bit_and", "bit_or", "bit_xor"):
            if self.bits is None:  # empty: AND -> all ones, OR/XOR -> 0
                return Datum.u64((1 << 64) - 1 if name == "bit_and" else 0)
            return Datum.u64(self.bits)
        raise NotImplementedError(name)


def run_dag_reference(dag: DAGRequest, chunk: Chunk) -> list[list[Datum]]:
    ev = RefEvaluator()
    rows = chunk.rows()
    for ex in dag.executors[1:]:
        if isinstance(ex, Selection):
            rows = [r for r in rows if all(_truth(ev.eval(c, r)) for c in ex.conditions)]
        elif isinstance(ex, Projection):
            rows = [[ev.eval(e, r) for e in ex.exprs] for r in rows]
        elif isinstance(ex, Limit):
            rows = rows[: ex.limit]
        elif isinstance(ex, TopN):
            import functools

            def cmp_rows(r1, r2):
                for e, desc in ex.order_by:
                    a, b = ev.eval(e, r1), ev.eval(e, r2)
                    if a.is_null() and b.is_null():
                        continue
                    if a.is_null():
                        c = -1
                    elif b.is_null():
                        c = 1
                    else:
                        c = compare(a, b)
                    if c:
                        return -c if desc else c
                return 0

            rows = sorted(rows, key=functools.cmp_to_key(cmp_rows))[: ex.limit]
        elif isinstance(ex, Aggregation):
            assert not ex.partial and not ex.merge, "oracle runs Complete mode"
            groups: dict = {}
            order: list = []
            for r in rows:
                key = tuple(datum_group_key(ev.eval(g, r)) for g in ex.group_by)
                if key not in groups:
                    groups[key] = ([_RefAgg(a) for a in ex.aggs], [ev.eval(g, r) for g in ex.group_by])
                    order.append(key)
                accs, _ = groups[key]
                for acc, a in zip(accs, ex.aggs):
                    acc.update([ev.eval(x, r) for x in a.args])
            if not ex.group_by:
                if not rows:
                    groups[()] = ([_RefAgg(a) for a in ex.aggs], [])
                    order.append(())
            rows = []
            for key in order:
                accs, gvals = groups[key]
                rows.append([acc.result() for acc in accs] + gvals)
        else:
            raise TypeError(f"unsupported executor {ex}")
    return [[r[i] for i in dag.output_offsets] for r in rows]
