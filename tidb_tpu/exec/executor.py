"""Host-side DAG drivers.

run_dag_on_chunk: the device path — pad a host Chunk into a DeviceBatch, run
the fused program, decode outputs back to a host Chunk. Handles the overflow
contract by retrying with doubled group capacity (recompile, cached).

run_dag_reference: the Go-semantics oracle — interprets the same DAG row by
row with RefEvaluator (ref: unistore/cophandler/mpp_exec.go executors),
used by the parity harness and as the small-data root executor.
"""

from __future__ import annotations

import numpy as np

from ..chunk import Chunk, Column, to_device_batch
from ..expr.agg import AggDesc
from ..expr.eval_ref import RefEvaluator, compare, _truth
from ..types import Datum, DatumKind, FieldType, MyDecimal, MyTime
from .builder import DEFAULT_GROUP_CAPACITY, CompiledDAG, ProgramCache, build_program
from .dag import Aggregation, DAGRequest, Join, Limit, Projection, Selection, Sort, TableScan, TopN, Window, current_schema_fts


def _pow2(n: int) -> int:
    c = 1
    while c < n:
        c *= 2
    return c


def decode_outputs(packed, valid, out_fts) -> Chunk:
    valid = np.asarray(valid)
    idx = np.nonzero(valid)[0]
    cols = []
    for ft, out in zip(out_fts, packed):
        if len(out) == 4:  # string: words, null, raw bytes, lengths
            _, null, data, length = out
            null = np.asarray(null)[idx]
            data = np.asarray(data)[idx]
            length = np.asarray(length)[idx]
            offs = np.zeros(len(idx) + 1, np.int64)
            np.cumsum(np.where(null, 0, length), out=offs[1:])
            blob = np.zeros(int(offs[-1]), np.uint8)
            for j in range(len(idx)):
                if not null[j]:
                    blob[offs[j] : offs[j + 1]] = data[j, : length[j]]
            cols.append(Column(ft, None, null, offs, blob))
        elif ft.is_string() and np.asarray(out[0]).ndim == 2:
            # string column without raw bytes (e.g. CASE/IF over string
            # operands): reconstruct from the packed compare words — covers
            # the first STRING_WORDS*8 bytes, the packed-key contract
            words, null = np.asarray(out[0]), np.asarray(out[1])
            words, null = words[idx], null[idx]
            w = words.shape[1] - 1
            payload = (words[:, :w].astype(np.uint64) ^ np.uint64(1 << 63))
            length = np.minimum(np.maximum(words[:, w], 0), w * 8).astype(np.int64)
            length = np.where(null, 0, length)
            byte_mat = np.zeros((len(idx), w * 8), np.uint8)
            for k in range(w):
                for b in range(8):
                    byte_mat[:, k * 8 + b] = ((payload[:, k] >> np.uint64(56 - 8 * b)) & np.uint64(0xFF)).astype(np.uint8)
            offs = np.zeros(len(idx) + 1, np.int64)
            np.cumsum(length, out=offs[1:])
            blob = np.zeros(int(offs[-1]), np.uint8)
            for j in range(len(idx)):
                blob[offs[j] : offs[j + 1]] = byte_mat[j, : length[j]]
            cols.append(Column(ft, None, null.copy(), offs, blob))
        else:
            v, null = out
            v = np.asarray(v)[idx]
            null = np.asarray(null)[idx]
            if ft.is_unsigned() or ft.is_time():
                v = v.view(np.uint64) if v.dtype == np.int64 else v.astype(np.uint64)
            cols.append(Column(ft, v.copy(), null.copy()))
    return Chunk(cols)


# Shared default so repeated executions of the same plan shape reuse the
# compiled XLA program (ref: coprocessor cache amortization).
DEFAULT_PROGRAM_CACHE = ProgramCache()


def drive_program(cache: ProgramCache, dag: DAGRequest, batches, group_capacity: int, max_retries: int = 3, join_capacity: int | None = None, small_groups: int | None = None):
    """Run the fused program, growing group/join capacity on overflow
    (the single overflow-retry contract — store and host driver share it).

    batches: one DeviceBatch per scan in canonical order (dag.collect_scans)
    — a single batch is accepted for single-scan DAGs.
    Returns (chunk, per-executor produced-row counts, scan first)."""
    chunk, counts, _ = drive_program_info(cache, dag, batches, group_capacity, max_retries, join_capacity, small_groups)
    return chunk, counts


def _radix_attribution(prog, jc: int, radix_esc, info: dict):
    """`join_radix` attribution (ISSUE 13 satellite): a TRACE span under
    the ambient cop.execute/session span plus an info entry the store
    folds into the exec summaries for EXPLAIN ANALYZE.  The escape count
    arrived in the same device fetch as the overflow flags."""
    ri = prog.radix_info or {}
    if not ri:
        return
    from ..util import tracing

    esc = int(radix_esc)
    with tracing.span("exec.join_radix", partitions=ri.get("partitions"),
                      rung=jc, escapes=esc, strategy=ri.get("strategy")):
        pass
    info["radix"] = {"partitions": ri.get("partitions", 0), "rung": jc,
                     "escapes": esc, "strategy": ri.get("strategy")}


def drive_program_info(cache: ProgramCache, dag: DAGRequest, batches, group_capacity: int, max_retries: int = 3, join_capacity: int | None = None, small_groups: int | None = None):
    """drive_program plus the compile/cache attribution triple:
    (chunk, counts, {"cache_hit", "compile_ns"}) — jit defers the XLA
    compile to the first call, so a fresh program's first execution time
    counts as compile time (trace+compile dominate it by orders of
    magnitude).

    Capacities snap to the LADDER RUNGS (exec/ladder.py) so programs are
    keyed by a small precompilable capacity set, and an overflow retry
    consults the program's NEED hints — the true group count / join
    fan-out that rode the same device fetch as the flags — to re-dispatch
    the exact rung: a warm ladder makes every retry a ProgramCache hit
    (zero recompiles, pinned in tests/test_radix_join.py)."""
    import time as _time

    from ..util import metrics
    from .ladder import overflow_step, rung_for

    if not isinstance(batches, (list, tuple)):
        batches = [batches]
    caps = tuple(b.capacity for b in batches)
    gc = rung_for(group_capacity)
    jc = rung_for(join_capacity or max(caps))
    tf = False
    smg = small_groups
    uj = True
    rj = True
    info = {"cache_hit": True, "compile_ns": 0}
    for _ in range(max_retries + 1):
        prog, hit, build_ns = cache.get_info(dag, caps, gc, jc, tf, smg, uj, radix_joins=rj)
        t0 = _time.perf_counter_ns()
        metrics.PROGRAM_LAUNCHES.inc()
        packed, valid, n, (g_ovf, j_ovf, t_ovf, g_need, j_need, radix_esc), ex_rows = prog.fn(*batches)
        g_ovf, j_ovf, t_ovf = bool(g_ovf), bool(j_ovf), bool(t_ovf)
        if not hit:
            info["cache_hit"] = False
            # bool() above blocked on the result: first-call = trace+compile
            info["compile_ns"] += build_ns + (_time.perf_counter_ns() - t0)
        if not g_ovf and not j_ovf and not t_ovf:
            counts = [int(x) for x in np.asarray(ex_rows)]
            _radix_attribution(prog, jc, radix_esc, info)
            return decode_outputs(packed, valid, prog.out_fts), counts, info
        if g_ovf:
            # also drop a wrong stats hint in the same retry: the driver
            # cannot tell whether the dense kernel ran (the agg mix may
            # have been ineligible), so doing both never wastes a retry
            # on a byte-identical program
            smg = None
        gc, jc, drop = overflow_step(gc, jc, g_ovf, j_ovf, int(g_need), int(j_need))
        if drop:
            uj = False
            rj = False
        if t_ovf:
            tf = True  # TopN candidate overflow: exact full-sort variant
    raise OverflowRetryError("DAG overflow not resolved after retries")


class OverflowRetryError(RuntimeError):
    """Capacity growth retries exhausted; caller may fall back to the
    row-at-a-time oracle (the host fallback SURVEY §7 promises)."""


def _slice_region(packed, b: int) -> list:
    """Region lane `b` of a vmapped program's packed outputs — each leaf
    loses its leading region axis, recovering the single-region layout
    decode_outputs consumes."""
    return [tuple(np.asarray(a)[b] for a in out) for out in packed]


def drive_batched_program_info(
    cache: ProgramCache,
    dag: DAGRequest,
    stacked,
    aux_batches,
    group_capacity: int,
    join_capacity: int | None = None,
    small_groups: int | None = None,
):
    """ONE vmapped launch over a region-stacked batch (chunk.device
    to_stacked_device_batch) — the device half of the batch coprocessor:
    where the per-region path issues N launches serialized on the single
    JAX stream, this issues one program execution whose leading axis is the
    region, then slices per-region results back out.

    Returns (per_region, info): per_region[b] is (chunk, per-executor row
    counts) for lanes that completed, or None for lanes whose overflow flag
    fired — group/join/topn overflow is data-dependent per region, so only
    the overflowing region falls out of the batch; the caller retries it
    through the single-region capacity ladder (drive_program_info) while
    every other region's result stands. info is the shared
    {"cache_hit", "compile_ns"} attribution of the one batched program."""
    import time as _time

    from ..util import metrics

    from .ladder import rung_for

    B = int(stacked.row_valid.shape[0])
    cap = int(stacked.row_valid.shape[1])
    caps = (cap,) + tuple(b.capacity for b in aux_batches)
    jc = rung_for(join_capacity or max(caps))
    prog, hit, build_ns = cache.get_info(
        dag, caps, rung_for(group_capacity), jc, False, small_groups, True, vmap_batch=B
    )
    t0 = _time.perf_counter_ns()
    metrics.PROGRAM_LAUNCHES.inc()
    packed, valid, n, (g_ovf, j_ovf, t_ovf, _g_need, _j_need, radix_esc), ex_rows = prog.fn(stacked, *aux_batches)
    g_ovf, j_ovf, t_ovf = np.asarray(g_ovf), np.asarray(j_ovf), np.asarray(t_ovf)
    info = {"cache_hit": hit, "compile_ns": 0}
    if not hit:
        # the flag fetch above blocked on the result: first-call time is
        # trace+compile, same attribution as drive_program_info
        info["compile_ns"] = build_ns + (_time.perf_counter_ns() - t0)
    valid_np = np.asarray(valid)
    ex_np = np.asarray(ex_rows)
    per_region: list = []
    esc_np = np.asarray(radix_esc)
    served_esc = 0
    esc_by_lane: list = []
    for b in range(B):
        if bool(g_ovf[b]) or bool(j_ovf[b]) or bool(t_ovf[b]):
            per_region.append(None)
            esc_by_lane.append(0)
            continue
        served_esc += int(esc_np[b])
        esc_by_lane.append(int(esc_np[b]))
        chunk = decode_outputs(_slice_region(packed, b), valid_np[b], prog.out_fts)
        per_region.append((chunk, [int(x) for x in ex_np[b]]))
    _radix_attribution(prog, jc, served_esc, info)
    if "radix" in info:
        # per-lane escape counts, aligned with per_region: the batched
        # store attributes each lane's OWN escapes to its summaries
        # (stamping the batch total per lane would multiply it in
        # EXPLAIN ANALYZE's cross-summary sum)
        info["radix"]["escapes_by_lane"] = esc_by_lane
    return per_region, info


def drive_mesh_program_info(
    cache: ProgramCache,
    dag: DAGRequest,
    stacked,
    aux_batches,
    group_capacity: int,
    kind: str,
    mesh_devices: int,
    join_capacity: int | None = None,
    small_groups: int | None = None,
):
    """ONE shard_map launch over a region-stacked batch — the device half
    of the MESH dispatch tier: the stacked lanes shard over the device
    mesh, each device vmaps the fused program over its local regions, and
    the per-region partial results merge ON DEVICE (psum of partial
    aggregate states over the region axis / merge-mode re-group / re-top-k
    per `kind`) so the caller gets ONE merged chunk instead of R
    per-region partials.

    Returns (chunk, lane_counts, info): `chunk` is the merged result (None
    when the program's global overflow flag fired — the caller degrades to
    the vmapped tier, whose per-lane capacity ladder takes over);
    lane_counts[b] is lane b's per-executor produced-row counts (the same
    honest per-region numbers the vmap tier reports); info is the shared
    {"cache_hit", "compile_ns"} attribution."""
    import time as _time

    from ..util import metrics

    from .ladder import rung_for

    R = int(stacked.row_valid.shape[0])
    cap = int(stacked.row_valid.shape[1])
    caps = (cap,) + tuple(b.capacity for b in aux_batches)
    jc = rung_for(join_capacity or max(caps))
    prog, hit, build_ns = cache.get_info(
        dag, caps, rung_for(group_capacity), jc, False, small_groups, True,
        mesh_lanes=R, mesh_devices=mesh_devices, mesh_kind=kind,
    )
    t0 = _time.perf_counter_ns()
    metrics.PROGRAM_LAUNCHES.inc()
    merged, mvalid, ex_rows, ovf, radix_esc = prog.fn(stacked, *aux_batches)
    overflow = bool(np.asarray(ovf))
    info = {"cache_hit": hit, "compile_ns": 0}
    if not hit:
        # the flag fetch above blocked on the result: first-call time is
        # trace+compile, same attribution as drive_program_info
        info["compile_ns"] = build_ns + (_time.perf_counter_ns() - t0)
    ex_np = np.asarray(ex_rows)
    lane_counts = [[int(x) for x in ex_np[b]] for b in range(R)]
    if overflow:
        return None, lane_counts, info
    _radix_attribution(prog, jc, np.asarray(radix_esc), info)
    chunk = decode_outputs(merged, np.asarray(mvalid), prog.out_fts)
    return chunk, lane_counts, info


def _group_key_partition(chunk: Chunk, key_cols: list[int], n_parts: int, salt: int = 0) -> list[Chunk]:
    """Split rows by a host-side hash of the named columns: equal keys land
    in the same part, so per-part aggregation results are disjoint. `salt`
    varies per recursion depth — an unsalted re-partition of one part maps
    every row back into a single bucket (code-review r4)."""
    import numpy as np

    n = chunk.num_rows()
    h = np.full(n, 1469598103934665603 ^ (salt * 0x9E3779B97F4A7C15 & 0xFFFFFFFFFFFFFFFF), np.uint64)
    prime = np.uint64(1099511628211)
    for ci in key_cols:
        col = chunk.columns[ci]
        if col.is_varlen():
            w = np.fromiter(
                (0 if col.null[i] else hash(col.get_bytes(i)) & 0xFFFFFFFFFFFFFFFF
                 for i in range(n)),
                np.uint64, count=n,
            )
        else:
            w = np.where(col.null, 0, col.data).astype(np.uint64)
        h = (h ^ w) * prime
    part = (h % np.uint64(n_parts)).astype(np.int64)
    return [chunk.take(np.nonzero(part == p)[0]) for p in range(n_parts)]


def _spill_partitioned(dag: DAGRequest, chunks, cache, group_capacity, small_groups, depth=0) -> Chunk:
    """Out-of-capacity execution — the spill analog (ref:
    pkg/executor/aggregate/agg_spill.go, join/hash_join_spill.go,
    sortexec/sort_spill.go): when device capacity retries exhaust, the
    input partitions on the HOST and the same fused program runs once per
    partition — device kernels only, never the row-at-a-time oracle.

      * Partial-mode aggregation: ANY row split works (the downstream
        Final merge combines duplicate groups), so halve the probe chunk.
      * Complete/Final aggregation over bare column group keys: partition
        rows by a host hash of the key columns — per-part group sets are
        disjoint and results concatenate.
      * Join/Selection/Projection-terminal DAGs: halve the probe side
        (each probe row's matches are independent); output order is
        preserved by concatenating slices in order.

    Raises OverflowRetryError when no safe decomposition exists."""
    if depth >= 4:
        raise OverflowRetryError("spill partitioning depth exhausted")
    probe = chunks[0]
    n = probe.num_rows()
    if n < 2:
        raise OverflowRetryError("cannot partition a <2-row input")
    last = dag.executors[-1]

    def run_parts(parts: list) -> Chunk:
        outs = []
        for p in parts:
            if p.num_rows() == 0:
                continue
            outs.append(
                run_dag_on_chunks(
                    dag, [p] + list(chunks[1:]), cache=cache,
                    group_capacity=group_capacity, oracle_fallback=False,
                    small_groups=small_groups, _spill_depth=depth + 1,
                )
            )
        if not outs:
            return Chunk.empty(dag.output_fts())
        return Chunk.concat(outs)

    if isinstance(last, Aggregation):
        simple_pipeline = all(
            isinstance(e, (TableScan, Selection)) for e in dag.executors[:-1]
        )
        if last.partial and simple_pipeline:
            from ..util import metrics

            metrics.SPILL_PARTITIONS.inc()
            return run_parts([probe.slice(0, n // 2), probe.slice(n // 2, n)])
        from ..expr.ir import ColumnRef

        if simple_pipeline and last.group_by and all(
            isinstance(g, ColumnRef) for g in last.group_by
        ):
            from ..util import metrics

            metrics.SPILL_PARTITIONS.inc()
            keys = [g.index for g in last.group_by]
            return run_parts(_group_key_partition(probe, keys, 4, salt=depth + 1))
        raise OverflowRetryError("no safe spill decomposition for this aggregation")
    row_local = all(
        isinstance(e, (TableScan, Selection, Projection, Join)) for e in dag.executors
    )
    if row_local and isinstance(last, (Join, Selection, Projection)):
        # probe-halving is only sound when EVERY main-pipeline executor is
        # row-local: a mid-pipeline Aggregation/TopN/Limit/Window would
        # make per-half results non-concatenable (e.g. the root DAG
        # [scan, Aggregation(merge), Selection] from a HAVING plan)
        from ..util import metrics

        metrics.SPILL_PARTITIONS.inc()
        return run_parts([probe.slice(0, n // 2), probe.slice(n // 2, n)])
    raise OverflowRetryError(f"no spill decomposition for {type(last).__name__}")


def run_dag_on_chunks(
    dag: DAGRequest,
    chunks: list,
    cache: ProgramCache | None = None,
    group_capacity: int = DEFAULT_GROUP_CAPACITY,
    max_retries: int = 3,
    oracle_fallback: bool = True,
    small_groups: int | None = None,
    _spill_depth: int = 0,
) -> Chunk:
    """Device path over one chunk per scan. Capacity-retry exhaustion first
    tries host-partitioned multi-pass device execution (the spill analog);
    the reference evaluator is the last resort (host-only operators)."""
    cache = cache or DEFAULT_PROGRAM_CACHE
    try:
        batches = [to_device_batch(c, capacity=_pow2(max(c.num_rows(), 1))) for c in chunks]
        return drive_program(cache, dag, batches, group_capacity, max_retries, small_groups=small_groups)[0]
    except OverflowRetryError:
        try:
            return _spill_partitioned(dag, chunks, cache, group_capacity, small_groups, _spill_depth)
        except OverflowRetryError:
            if not oracle_fallback:
                raise
        rows = run_dag_reference(dag, chunks)
        return Chunk.from_rows(dag.output_fts(), rows)
    except NotImplementedError:
        # a host-only operator (replace, group_concat): the row-at-a-time
        # oracle is the documented fallback
        if not oracle_fallback:
            raise
        rows = run_dag_reference(dag, chunks)
        return Chunk.from_rows(dag.output_fts(), rows)


def run_dag_on_chunk(
    dag: DAGRequest,
    chunk: Chunk,
    cache: ProgramCache | None = None,
    capacity: int | None = None,
    group_capacity: int = DEFAULT_GROUP_CAPACITY,
    max_retries: int = 3,
) -> Chunk:
    cache = cache or DEFAULT_PROGRAM_CACHE
    cap = capacity or _pow2(max(chunk.num_rows(), 1))
    batch = to_device_batch(chunk, capacity=cap)
    return drive_program(cache, dag, batch, group_capacity, max_retries)[0]


# ---------------------------------------------------------------------------
# Reference interpreter (oracle)
# ---------------------------------------------------------------------------

def datum_group_key(d: Datum, ft: FieldType | None = None):
    if d.is_null():
        return (0, None)
    if d.kind == DatumKind.MysqlJSON:
        return (1, bytes(d.val))
    if d.kind in (DatumKind.MysqlEnum, DatumKind.MysqlSet):
        return (1, int(d.val))
    if d.kind == DatumKind.MysqlDecimal:
        return (1, str(d.val.d.normalize()))
    if d.kind in (DatumKind.String, DatumKind.Bytes):
        if ft is not None and ft.is_ci():
            # one group per collation WEIGHT key (full Unicode,
            # types/collate.py — é and É and e share a unicode_ci group)
            from ..types.collate import weight_bytes

            return (1, weight_bytes(d.val, ft.collate))
        v = d.val.encode() if isinstance(d.val, str) else bytes(d.val)
        return (1, v)
    if d.kind == DatumKind.MysqlTime:
        return (1, d.val.packed)
    if d.kind in (DatumKind.Float32, DatumKind.Float64):
        return (1, float(d.val) + 0.0)  # -0.0 -> 0.0
    return (1, d.val)


class _RefAgg:
    """One aggregate's accumulator (Complete mode), incl. DISTINCT via a
    seen-set (ref: executor/aggfuncs distinct wrappers) and the BIT_*
    aggregates (ref: aggfuncs/func_bitfuncs.go)."""

    def __init__(self, desc: AggDesc):
        self.d = desc
        self.count = 0
        self.sum = None
        self.extreme = None
        self.first = None
        self.has_first = False
        self.bits = None
        self.fsum = 0.0  # float moments for stddev/var
        self.sumsq = 0.0
        self.strs: list = []  # group_concat pieces
        self.seen = set() if desc.distinct else None

    def update(self, args: list[Datum]):
        name = self.d.name
        if self.seen is not None and name in (
            "count", "sum", "avg", "group_concat",
            "stddev_pop", "stddev_samp", "var_pop", "var_samp",
        ):
            # DISTINCT: rows with any NULL arg are skipped; each distinct
            # arg tuple contributes once
            if any(a.is_null() for a in args):
                return
            key = tuple(
                datum_group_key(a, ae.ft)
                for a, ae in zip(args, self.d.args)
            )
            if key in self.seen:
                return
            self.seen.add(key)
        if name == "count":
            if all(not a.is_null() for a in args):
                self.count += 1
            return
        a = args[0]
        if name == "first_row":
            if not self.has_first:
                self.first, self.has_first = a, True
            return
        if a.is_null():
            return
        if name in ("bit_and", "bit_or", "bit_xor"):
            v = int(a.val) & ((1 << 64) - 1)
            if self.bits is None:
                self.bits = v
            elif name == "bit_and":
                self.bits &= v
            elif name == "bit_or":
                self.bits |= v
            else:
                self.bits ^= v
            return
        self.count += 1
        if name in ("sum", "avg"):
            self._add_sum(a)
        elif name in ("stddev_pop", "stddev_samp", "var_pop", "var_samp"):
            v = a.val.to_float() if a.kind == DatumKind.MysqlDecimal else float(a.val)
            self.fsum += v
            self.sumsq += v * v
        elif name == "group_concat":
            v = a.val if isinstance(a.val, str) else (
                bytes(a.val).decode("utf-8", "surrogateescape") if isinstance(a.val, (bytes, bytearray)) else str(a.val)
            )
            self.strs.append(v)
        elif name in ("min", "max"):
            if self.extreme is None:
                self.extreme = a
            else:
                c = compare(a, self.extreme)
                if (name == "min" and c < 0) or (name == "max" and c > 0):
                    self.extreme = a
        else:
            raise NotImplementedError(name)

    def _add_sum(self, a: Datum):
        if self.sum is None:
            if a.kind in (DatumKind.Float64, DatumKind.Float32):
                self.sum = float(a.val)
            elif a.kind == DatumKind.MysqlDecimal:
                self.sum = a.val
            else:
                self.sum = MyDecimal(a.val, 0)
        else:
            if isinstance(self.sum, float):
                self.sum += float(a.val)
            else:
                self.sum = self.sum + (a.val if a.kind == DatumKind.MysqlDecimal else MyDecimal(a.val, 0))

    def merge_update(self, args: list[Datum]):
        """Consume partial-state columns (Partial2/Final modes) — the state
        schemas of expr/agg.py (ref: aggfuncs MergePartialResult)."""
        name = self.d.name
        if self.seen is not None and name not in ("min", "max", "first_row"):
            raise NotImplementedError("DISTINCT partials are not mergeable")
        if name == "count":
            if not args[0].is_null():
                self.count += int(args[0].val)
            return
        if name == "avg":
            c, s = args
            if not c.is_null():
                self.count += int(c.val)
            if not s.is_null():
                self._add_sum(s)
            return
        if name == "sum":
            if not args[0].is_null():
                self.count += 1
                self._add_sum(args[0])
            return
        if name == "first_row":
            has, val = args
            if not has.is_null() and int(has.val) > 0 and not self.has_first:
                self.first, self.has_first = val, True
            return
        if name in ("stddev_pop", "stddev_samp", "var_pop", "var_samp"):
            c, s, q = args
            if not c.is_null():
                self.count += int(c.val)
            if not s.is_null():
                self.fsum += float(s.val)
                self.sumsq += float(q.val)
            return
        if name == "group_concat":
            raise NotImplementedError("group_concat partials are not mergeable (root-only aggregate)")
        # min/max/bit_*: state column == value column, same combine
        self.update(args)

    def partial_result(self) -> list[Datum]:
        """Emit this accumulator's partial-state columns (Partial1 mode)."""
        name = self.d.name
        pf = self.d.partial_fts()
        if name == "count":
            return [Datum.i64(self.count)]
        if name == "sum":
            return [self._sum_datum(pf[0])]
        if name == "avg":
            return [Datum.i64(self.count), self._sum_datum(pf[1])]
        if name in ("min", "max"):
            return [self.extreme if self.extreme is not None else Datum.NULL]
        if name == "first_row":
            return [Datum.i64(1 if self.has_first else 0), self.first if self.has_first else Datum.NULL]
        if name in ("stddev_pop", "stddev_samp", "var_pop", "var_samp"):
            return [Datum.i64(self.count), Datum.f64(self.fsum), Datum.f64(self.sumsq)]
        return [self.result()]  # bit_*: state == result

    def _sum_datum(self, ft: FieldType) -> Datum:
        if self.sum is None:
            return Datum.NULL
        if isinstance(self.sum, float):
            return Datum.f64(self.sum)
        return Datum.dec(self.sum.round(max(ft.decimal, 0)))

    def result(self) -> Datum:
        name = self.d.name
        ft = self.d.ft
        if name == "count":
            return Datum.i64(self.count)
        if name == "first_row":
            return self.first if self.has_first else Datum.NULL
        if name == "sum":
            if self.sum is None:
                return Datum.NULL
            if isinstance(self.sum, float):
                return Datum.f64(self.sum)
            return Datum.dec(self.sum.round(max(ft.decimal, 0)))
        if name == "avg":
            if self.count == 0:
                return Datum.NULL
            if isinstance(self.sum, float):
                return Datum.f64(self.sum / self.count)
            q = self.sum.div(MyDecimal(self.count, 0))
            return Datum.dec(q.round(max(ft.decimal, 0)))
        if name in ("min", "max"):
            return self.extreme if self.extreme is not None else Datum.NULL
        if name in ("bit_and", "bit_or", "bit_xor"):
            if self.bits is None:  # empty: AND -> all ones, OR/XOR -> 0
                return Datum.u64((1 << 64) - 1 if name == "bit_and" else 0)
            return Datum.u64(self.bits)
        if name in ("stddev_pop", "stddev_samp", "var_pop", "var_samp"):
            import math

            n = self.count
            if n == 0 or (name.endswith("samp") and n < 2):
                return Datum.NULL
            mean = self.fsum / n
            if name.endswith("samp"):
                var = max(self.sumsq - n * mean * mean, 0.0) / (n - 1)
            else:
                var = max(self.sumsq / n - mean * mean, 0.0)
            return Datum.f64(math.sqrt(var) if name.startswith("stddev") else var)
        if name == "group_concat":
            if not self.strs:
                return Datum.NULL
            return Datum.string((self.d.extra if self.d.extra is not None else ",").join(self.strs))
        raise NotImplementedError(name)


def run_dag_reference(dag: DAGRequest, chunks) -> list[list[Datum]]:
    """Row-at-a-time oracle over one chunk per scan (canonical order);
    accepts a bare Chunk for single-scan DAGs."""
    if isinstance(chunks, Chunk):
        chunks = [chunks]
    ev = RefEvaluator()
    cursor = [0]
    rows = _ref_pipeline(dag.executors, chunks, cursor, ev)
    return [[r[i] for i in dag.output_offsets] for r in rows]


def _ref_pipeline(executors, chunks, cursor, ev) -> list[list[Datum]]:
    chunk = chunks[cursor[0]]
    cursor[0] += 1
    rows = chunk.rows()
    for ex in executors[1:]:
        if isinstance(ex, Selection):
            rows = [r for r in rows if all(_truth(ev.eval(c, r)) for c in ex.conditions)]
        elif isinstance(ex, Projection):
            rows = [[ev.eval(e, r) for e in ex.exprs] for r in rows]
        elif isinstance(ex, Limit):
            rows = rows[: ex.limit]
        elif isinstance(ex, TopN):
            rows = _order_by_sorted(rows, ex.order_by, ev)[: ex.limit]
        elif isinstance(ex, Sort):
            rows = _order_by_sorted(rows, ex.order_by, ev)
        elif isinstance(ex, Window):
            rows = _ref_window(ex, rows, ev)
        elif isinstance(ex, Join):
            rows = _ref_join(ex, rows, chunks, cursor, ev)
        elif isinstance(ex, Aggregation):
            groups: dict = {}
            order: list = []
            for r in rows:
                key = tuple(datum_group_key(ev.eval(g, r), g.ft) for g in ex.group_by)
                if key not in groups:
                    groups[key] = ([_RefAgg(a) for a in ex.aggs], [ev.eval(g, r) for g in ex.group_by])
                    order.append(key)
                accs, _ = groups[key]
                for acc, a in zip(accs, ex.aggs):
                    args = [ev.eval(x, r) for x in a.args]
                    if ex.merge:
                        acc.merge_update(args)
                    else:
                        acc.update(args)
            if not ex.group_by:
                if not rows:
                    groups[()] = ([_RefAgg(a) for a in ex.aggs], [])
                    order.append(())
            rows = []
            for key in order:
                accs, gvals = groups[key]
                out: list[Datum] = []
                for acc in accs:
                    if ex.partial:
                        out.extend(acc.partial_result())
                    else:
                        out.append(acc.result())
                rows.append(out + gvals)
        else:
            raise TypeError(f"unsupported executor {ex}")
    return rows


def _order_by_sorted(rows, order_by, ev) -> list:
    """Stable ORDER BY sort — THE null-first/desc-flip comparator both TopN
    and Sort (and only they) define order with."""
    import functools

    def cmp_rows(r1, r2):
        for e, desc in order_by:
            a, b = ev.eval(e, r1), ev.eval(e, r2)
            if a.is_null() and b.is_null():
                continue
            ci = e.ft.is_string() and e.ft.is_ci()
            c = -1 if a.is_null() else (
                1 if b.is_null() else compare(a, b, ci=ci, collation=e.ft.collate if ci else None)
            )
            if c:
                return -c if desc else c
        return 0

    return sorted(rows, key=functools.cmp_to_key(cmp_rows))


def _ref_window(ex, rows, ev) -> list[list[Datum]]:
    """Window oracle: partition dict -> stable sort by order keys -> per-row
    frame evaluation with MySQL default frames (RANGE UNBOUNDED
    PRECEDING..CURRENT ROW including peers with ORDER BY; whole partition
    without). Semantics ref: pkg/executor/aggfuncs/func_*.go per function."""
    import functools

    from ..types import MyDecimal

    def okey_cmp(r1, r2):
        for e, desc in ex.order_by:
            a, b = ev.eval(e, r1), ev.eval(e, r2)
            if a.is_null() and b.is_null():
                continue
            ci = e.ft.is_string() and e.ft.is_ci()
            c = -1 if a.is_null() else (
                1 if b.is_null() else compare(a, b, ci=ci, collation=e.ft.collate if ci else None)
            )
            if c:
                return -c if desc else c
        return 0

    parts: dict = {}
    order: list = []
    for i, r in enumerate(rows):
        key = tuple(datum_group_key(ev.eval(g, r), g.ft) for g in ex.partition_by)
        if key not in parts:
            parts[key] = []
            order.append(key)
        parts[key].append(i)

    results: dict = {i: [] for i in range(len(rows))}
    for key in order:
        idxs = parts[key]
        idxs.sort(key=functools.cmp_to_key(lambda a, b: okey_cmp(rows[a], rows[b]) or (a - b)))
        n = len(idxs)
        # peer groups (equal order keys)
        peer_id = [0] * n
        for j in range(1, n):
            peer_id[j] = peer_id[j - 1] + (1 if okey_cmp(rows[idxs[j - 1]], rows[idxs[j]]) else 0)
        peer_end = [0] * n
        end = n - 1
        for j in range(n - 1, -1, -1):
            if j < n - 1 and peer_id[j] != peer_id[j + 1]:
                end = j
            peer_end[j] = end
        has_order = bool(ex.order_by)
        for w in ex.funcs:
            for j, ri in enumerate(idxs):
                frame_hi = (peer_end[j] if has_order else n - 1)
                results[ri].append(_ref_window_value(w, ex, rows, idxs, j, n, frame_hi, peer_id, ev))
    return [r + results[i] for i, r in enumerate(rows)]


def _ref_window_value(w, ex, rows, idxs, j, n, frame_hi, peer_id, ev) -> Datum:
    from ..types import MyDecimal

    name = w.name

    def argval(ri, k=0):
        return ev.eval(w.args[k], rows[ri])

    if name == "row_number":
        return Datum.i64(j + 1)
    if name == "rank":
        first = next(k for k in range(n) if peer_id[k] == peer_id[j])
        return Datum.i64(first + 1)
    if name == "dense_rank":
        return Datum.i64(peer_id[j] + 1)
    if name == "percent_rank":
        if n <= 1:
            return Datum.f64(0.0)
        first = next(k for k in range(n) if peer_id[k] == peer_id[j])
        return Datum.f64(first / (n - 1))
    if name == "cume_dist":
        return Datum.f64((frame_hi + 1) / n) if ex.order_by else Datum.f64(1.0)
    if name == "ntile":
        k = w.offset
        base, rem = n // k, n % k
        cut = rem * (base + 1)
        if j < cut:
            return Datum.i64(j // (base + 1) + 1)
        return Datum.i64(rem + (j - cut) // max(base, 1) + 1)
    if name in ("lead", "lag"):
        off = w.offset if name == "lead" else -w.offset
        t = j + off
        if 0 <= t < n:
            return argval(idxs[t])
        if w.default is not None:
            return ev.eval(w.default, rows[idxs[j]])
        return Datum.NULL
    if name == "first_value":
        return argval(idxs[0])
    if name == "last_value":
        return argval(idxs[frame_hi])
    if name == "nth_value":
        t = w.offset - 1
        if t <= frame_hi:
            return argval(idxs[t])
        return Datum.NULL
    # frame aggregates over rows[0..frame_hi]
    if name == "count" and not w.args:
        return Datum.i64(frame_hi + 1)
    vals = [argval(idxs[k]) for k in range(frame_hi + 1)]
    live = [d for d in vals if not d.is_null()]
    if name == "count":
        return Datum.i64(len(live))
    if not live:
        return Datum.NULL
    if name in ("min", "max"):
        best = live[0]
        for d in live[1:]:
            c = compare(d, best)
            if (name == "max" and c > 0) or (name == "min" and c < 0):
                best = d
        return best
    # sum / avg with MySQL numeric promotion
    et = w.ft.eval_type()
    if et == "real":
        s = sum(float(d.val.to_float() if isinstance(d.val, MyDecimal) else d.val) for d in live)
        return Datum.f64(s if name == "sum" else s / len(live))
    acc = None
    for d in live:
        dv = d.val if isinstance(d.val, MyDecimal) else MyDecimal(str(d.val))
        acc = dv if acc is None else acc + dv
    if name == "sum":
        return Datum.dec(acc)
    return Datum.dec(acc.div(MyDecimal(str(len(live)))))


def _ref_join(ex: Join, probe_rows, chunks, cursor, ev) -> list[list[Datum]]:
    """Hash-join oracle (ref: mpp_exec.go:844 joinExec — build a key map,
    probe row by row; NULL keys never match)."""
    build_rows = _ref_pipeline(ex.build, chunks, cursor, ev)
    nb_cols = len(current_schema_fts(ex.build))

    def key_of(row, exprs):
        ds = [ev.eval(k, row) for k in exprs]
        if any(d.is_null() for d in ds):
            return None
        return tuple(datum_group_key(d, k.ft) for d, k in zip(ds, exprs))

    table: dict = {}
    for br in build_rows:
        k = key_of(br, ex.build_keys)
        if k is not None:
            table.setdefault(k, []).append(br)

    out: list[list[Datum]] = []
    for pr in probe_rows:
        k = key_of(pr, ex.probe_keys)
        matches = table.get(k, []) if k is not None else []
        if ex.join_type == "inner":
            out.extend(pr + br for br in matches)
        elif ex.join_type == "left_outer":
            if matches:
                out.extend(pr + br for br in matches)
            else:
                out.append(pr + [Datum.NULL] * nb_cols)
        elif ex.join_type == "semi":
            if matches:
                out.append(pr)
        elif ex.join_type == "anti":
            if not matches:
                out.append(pr)
        else:
            raise TypeError(f"unknown join type {ex.join_type}")
    return out
