"""Scatter-free segment machinery — the TPU-shaped core of group-by.

XLA scatter (what `jax.ops.segment_*` lowers to) serializes on TPU and
compiles explosively on some backends; multi-operand variadic sorts are the
other compile sink. This module replaces both:

  * group keys hash into ONE int64 word (splitmix64 mix over the normalized
    key words from ops/keys.py), so grouping costs a single single-operand
    sort no matter how many GROUP BY columns there are;
  * segment reductions over the hash-sorted rows are cumsum / segmented
    associative-scan passes plus gathers at segment boundaries — zero
    scatter ops, all bandwidth-bound elementwise work;
  * hash collisions (different keys, equal hash) are DETECTED exactly by
    comparing every row's key words against its segment head, and surface
    as the group-overflow flag; the retry driver grows capacity, and the
    capacity salts the hash, so a retry re-seeds and the collision clears.

Semantics parity target is unchanged: unistore/cophandler/mpp_exec.go:999
aggExec's map-based group-by (a hash table keyed on encoded group datums —
this is the same idea, shaped for the VPU).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

I64_MAX = jnp.int64(0x7FFFFFFFFFFFFFFF)
MAX63 = jnp.int64(0x7FFFFFFFFFFFFFFF)  # top bit clear: valid-hash space

# splitmix64 finalizer constants (public domain; two's-complement int64)
_C1 = jnp.int64(0xBF58476D1CE4E5B9 - (1 << 64))
_C2 = jnp.int64(0x94D049BB133111EB - (1 << 64))
_GOLDEN = jnp.int64(0x9E3779B97F4A7C15 - (1 << 64))


def _lsr(x, k: int):
    """Logical shift right on int64 (arithmetic shift + mask)."""
    return (x >> k) & jnp.int64((1 << (64 - k)) - 1)


def _mix64(x):
    x = (x ^ _lsr(x, 30)) * _C1
    x = (x ^ _lsr(x, 27)) * _C2
    return x ^ _lsr(x, 31)


def _word_as_i64(w: jax.Array) -> jax.Array:
    """Key word -> int64 bit material. Float words (real sort keys stay
    float, see ops/keys.py) are bitcast via int32 halves — a direct 64-bit
    bitcast would break the TPU x64-emulation rewrite."""
    if jnp.issubdtype(w.dtype, jnp.floating):
        iw = jax.lax.bitcast_convert_type(w.astype(jnp.float64), jnp.int32)
        hi = iw[..., 0].astype(jnp.int64)
        lo = iw[..., 1].astype(jnp.int64) & jnp.int64(0xFFFFFFFF)
        return (hi << 32) | lo
    return w.astype(jnp.int64)


def hash_words(words: list[jax.Array], salt: int) -> jax.Array:
    """Mix a list of [N] key words into one well-distributed int64 [N]."""
    h = _mix64(jnp.int64(salt) * _GOLDEN + jnp.int64(1))
    h = jnp.broadcast_to(h, words[0].shape) if words else h
    for w in words:
        h = _mix64(h ^ _word_as_i64(w))
    return h


def group_hash(words: list[jax.Array], valid: jax.Array, salt: int) -> jax.Array:
    """Single sortable grouping word: valid rows get their 63-bit hash
    (top bit clear), invalid rows get I64_MAX — one argsort then clusters
    equal keys and pushes invalid rows to the tail."""
    h = hash_words(words, salt) & MAX63
    return jnp.where(valid, h, I64_MAX)


def sort_by_word(word: jax.Array):
    """(sorted_word, perm int32) via one single-key sort."""
    iota = jnp.arange(word.shape[0], dtype=jnp.int32)
    sw, perm = jax.lax.sort((word, iota), num_keys=1)
    return sw, perm


@dataclass
class SegCtx:
    """Boundary view of sorted segment ids.

    seg: int32 [N] ascending; nseg static; starts/ends int32 [nseg]
    (ends inclusive; empty segment has ends < starts); counts int64 [nseg].
    """

    seg: jax.Array
    nseg: int
    starts: jax.Array
    ends: jax.Array
    counts: jax.Array


def make_segctx(seg: jax.Array, nseg: int) -> SegCtx:
    g = jnp.arange(nseg, dtype=seg.dtype)
    starts = jnp.searchsorted(seg, g, side="left").astype(jnp.int32)
    ends = jnp.searchsorted(seg, g, side="right").astype(jnp.int32) - 1
    counts = jnp.maximum((ends - starts + 1).astype(jnp.int64), 0)
    return SegCtx(seg, nseg, starts, ends, counts)


def seg_head_pos(ctx: SegCtx) -> jax.Array:
    """Per-row sorted position of the row's segment head (int32 [N])."""
    n = ctx.seg.shape[0]
    return jnp.clip(ctx.starts, 0, n - 1)[ctx.seg]


def run_head_pos(diff: jax.Array) -> jax.Array:
    """Per-row position of the start of its equal-key run, given the
    boundary mask (diff[0] must be True). cummax, no gathers."""
    n = diff.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    return jax.lax.cummax(jnp.where(diff, pos, jnp.int32(0)))


def seg_sum(ctx: SegCtx, vals: jax.Array, dtype=None) -> jax.Array:
    """Per-segment sum via cumsum + boundary gathers (empty segments -> 0).
    Callers pre-mask invalid lanes to 0, exactly as with segment_sum."""
    v = vals if dtype is None else vals.astype(dtype)
    if ctx.nseg == 1:
        return jnp.sum(v, axis=0, keepdims=True)
    n = v.shape[0]
    c = jnp.cumsum(v, axis=0)
    lo = jnp.clip(ctx.starts, 0, n - 1)
    hi = jnp.clip(ctx.ends, 0, n - 1)
    out = c[hi] - c[lo] + v[lo]
    zero = jnp.zeros((), v.dtype)
    return jnp.where(ctx.counts > 0, out, zero)


def _seg_scan_reduce(ctx: SegCtx, vals: jax.Array, combine, empty_fill):
    """Per-segment reduce of an arbitrary associative `combine` via a
    segmented associative scan + gather at segment ends."""
    n = vals.shape[0]

    def comb(a, b):
        v1, s1 = a
        v2, s2 = b
        return jnp.where(s1 == s2, combine(v1, v2), v2), s2

    sv, _ = jax.lax.associative_scan(comb, (vals, ctx.seg))
    out = sv[jnp.clip(ctx.ends, 0, n - 1)]
    return jnp.where(ctx.counts > 0, out, empty_fill)


def seg_min(ctx: SegCtx, vals: jax.Array) -> jax.Array:
    if ctx.nseg == 1:
        return jnp.min(vals, axis=0, keepdims=True)
    fill = jnp.inf if jnp.issubdtype(vals.dtype, jnp.floating) else jnp.iinfo(vals.dtype).max
    return _seg_scan_reduce(ctx, vals, jnp.minimum, jnp.asarray(fill, vals.dtype))


def seg_max(ctx: SegCtx, vals: jax.Array) -> jax.Array:
    if ctx.nseg == 1:
        return jnp.max(vals, axis=0, keepdims=True)
    fill = -jnp.inf if jnp.issubdtype(vals.dtype, jnp.floating) else jnp.iinfo(vals.dtype).min
    return _seg_scan_reduce(ctx, vals, jnp.maximum, jnp.asarray(fill, vals.dtype))


def seg_bitreduce(ctx: SegCtx, red, vals: jax.Array, fill) -> jax.Array:
    """Segmented bitwise and/or/xor (no jax.ops.segment_* exists for these;
    callers pre-mask invalid lanes to the identity). The segmented scan
    handles nseg==1 too (one segment == plain scan, last element = total)."""
    return _seg_scan_reduce(ctx, vals, red, jnp.int64(fill))
