"""Scatter-free segment machinery — the TPU-shaped core of group-by.

XLA scatter (what `jax.ops.segment_*` lowers to) serializes on TPU and
compiles explosively on some backends; multi-operand variadic sorts are the
other compile sink. This module replaces both:

  * group keys hash into ONE int64 word (splitmix64 mix over the normalized
    key words from ops/keys.py), so grouping costs a single single-operand
    sort no matter how many GROUP BY columns there are;
  * segment reductions over the hash-sorted rows are cumsum / segmented
    associative-scan passes plus gathers at segment boundaries — zero
    scatter ops, all bandwidth-bound elementwise work;
  * hash collisions (different keys, equal hash) are DETECTED exactly by
    comparing every row's key words against its segment head, and surface
    as the group-overflow flag; the retry driver grows capacity, and the
    capacity salts the hash, so a retry re-seeds and the collision clears.

Semantics parity target is unchanged: unistore/cophandler/mpp_exec.go:999
aggExec's map-based group-by (a hash table keyed on encoded group datums —
this is the same idea, shaped for the VPU).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

# numpy (not jnp) scalars: created at import with no trace/x64-mode
# capture (these feed the x64-world lane construction, never the
# x64-off pallas kernels) — the jit-purity vet pass enforces this
I64_MAX = np.int64(0x7FFFFFFFFFFFFFFF)
# valid-hash space: top bit clear AND low bit clear — a masked hash is even,
# so it can never equal the (odd) I64_MAX invalid sentinel, keeping the
# sorted seg ids monotone even in the astronomically-unlikely near-miss
MAX63 = np.int64(0x7FFFFFFFFFFFFFFE)

# splitmix64 finalizer constants (public domain; two's-complement int64)
_C1 = np.int64(0xBF58476D1CE4E5B9 - (1 << 64))
_C2 = np.int64(0x94D049BB133111EB - (1 << 64))
_GOLDEN = np.int64(0x9E3779B97F4A7C15 - (1 << 64))


def _lsr(x, k: int):
    """Logical shift right on int64 (arithmetic shift + mask)."""
    return (x >> k) & jnp.int64((1 << (64 - k)) - 1)


def _mix64(x):
    x = (x ^ _lsr(x, 30)) * _C1
    x = (x ^ _lsr(x, 27)) * _C2
    return x ^ _lsr(x, 31)


def _word_as_i64(w: jax.Array) -> jax.Array:
    """Key word -> int64 bit material. Float words (real sort keys stay
    float, see ops/keys.py) are bitcast via int32 halves — a direct 64-bit
    bitcast would break the TPU x64-emulation rewrite."""
    if jnp.issubdtype(w.dtype, jnp.floating):
        iw = jax.lax.bitcast_convert_type(w.astype(jnp.float64), jnp.int32)
        hi = iw[..., 0].astype(jnp.int64)
        lo = iw[..., 1].astype(jnp.int64) & jnp.int64(0xFFFFFFFF)
        return (hi << 32) | lo
    return w.astype(jnp.int64)


def hash_words(words: list[jax.Array], salt: int) -> jax.Array:
    """Mix a list of [N] key words into one well-distributed int64 [N]."""
    h = _mix64(jnp.int64(salt) * _GOLDEN + jnp.int64(1))
    h = jnp.broadcast_to(h, words[0].shape) if words else h
    for w in words:
        h = _mix64(h ^ _word_as_i64(w))
    return h


def group_hash(words: list[jax.Array], valid: jax.Array, salt: int) -> jax.Array:
    """Single sortable grouping word: valid rows get their 63-bit hash
    (top bit clear), invalid rows get I64_MAX — one argsort then clusters
    equal keys and pushes invalid rows to the tail."""
    h = hash_words(words, salt) & MAX63
    return jnp.where(valid, h, I64_MAX)


def sort_by_word(word: jax.Array):
    """(sorted_word, perm int32) via one single-key STABLE sort (position is
    the second sort key, so equal words keep input order — segment heads are
    then the earliest original rows, which group_rep reads off for free)."""
    iota = jnp.arange(word.shape[0], dtype=jnp.int32)
    sw, perm = jax.lax.sort((word, iota), num_keys=2)
    return sw, perm


@dataclass
class SegCtx:
    """Boundary view of sorted segment ids.

    seg: int32 [N] ascending; nseg static; starts/ends int32 [nseg]
    (ends inclusive; empty segment has ends < starts); counts int64 [nseg].
    sums: optional SumBatch — when set, seg_sum calls are recorded and later
    resolved as ONE batched [A, N] cumsum instead of A separate ones.
    """

    seg: jax.Array
    nseg: int
    starts: jax.Array
    ends: jax.Array
    counts: jax.Array
    sums: object = None


class SumBatch:
    """Record/replay batcher for seg_sum.

    An aggregation typically needs many per-segment sums (counts, sums,
    moment sums). Each one as its own int64 cumsum costs a separate
    multi-pass op; stacked [A, N] they ride ONE cumsum whose lane dimension
    vectorizes. Protocol: a dry trace pass records every requested array
    (returning dummy zeros), resolve() computes the batched result, then an
    identical replay pass receives the real arrays in the same order (the
    states functions are pure, so the call sequence repeats exactly; any
    non-sum ops traced twice are structurally identical and XLA CSE merges
    them)."""

    def __init__(self, ctx: "SegCtx"):
        self.ctx = ctx
        self.reqs: list = []
        self.results: list | None = None
        self.replay_i = 0

    def add(self, v: jax.Array) -> jax.Array:
        if self.results is None:
            self.reqs.append(v)
            return jnp.zeros((self.ctx.nseg,), v.dtype)
        r = self.results[self.replay_i]
        self.replay_i += 1
        return r

    def resolve(self):
        ctx = self.ctx
        n = ctx.seg.shape[0]
        lo = jnp.clip(ctx.starts, 0, n - 1)
        hi = jnp.clip(ctx.ends, 0, n - 1)
        by_dtype: dict = {}
        for i, v in enumerate(self.reqs):
            by_dtype.setdefault(jnp.dtype(v.dtype), []).append((i, v))
        results: list = [None] * len(self.reqs)
        for dt, items in by_dtype.items():
            s = jnp.stack([v for _, v in items], 0)  # [A, N]
            c = jnp.cumsum(s, axis=1)
            out = c[:, hi] - c[:, lo] + s[:, lo]
            out = jnp.where(ctx.counts[None, :] > 0, out, jnp.zeros((), dt))
            for j, (i, _) in enumerate(items):
                results[i] = out[j]
        self.results = results
        self.replay_i = 0


@dataclass
class DenseCtx:
    """Small-G group context over rows in ORIGINAL order (no sort at all).

    For the classic OLAP shape — huge scan, handful of groups (TPC-H Q1 has
    six) — the grouping sort is pure overhead. Per-row dense ids come from
    g_cap compares against the distinct-hash table, and every segment
    reduction is ONE fused [N, G] broadcast-masked reduction: the
    `gid == iota` mask materializes in VMEM tiles inside the reduce fusion
    (never in HBM), so each reduction streams its value column exactly once
    no matter how many groups there are. Cost scales with g_cap only in
    VPU lanes, so the planner picks this when statistics promise few groups
    (NDV), and the overflow flag falls back to the sort kernel when the
    promise was wrong. Slot nseg-1 = invalid/overflow rows."""

    gid: jax.Array
    nseg: int
    sums: object = None  # DenseSumBatch when armed


class DenseSumBatch:
    """Record/replay batcher for DENSE seg_sum — all integer per-group sums
    ride ONE chunked-exact f32 matmul on the MXU.

    A [N, G] masked VPU reduce costs ~1ms per 4M-row int64 column on v5e
    (the N*G elementwise expansion is inherent); the MXU contracts the
    same one-hot against EVERY value column at once for free. Exactness:
    int64 values split into 4x16-bit limbs (f32-exact), contracted in
    256-row chunks (sums <= 2^24, f32-exact), chunk totals accumulated in
    int64 (exact, wraps mod 2^64 like the plain int64 sum would). Float
    columns keep the masked-reduce path (f32 matmul would round)."""

    def __init__(self, ctx: "DenseCtx"):
        self.ctx = ctx
        self.reqs: list = []
        self.results: list | None = None
        self.replay_i = 0

    def add(self, v: jax.Array) -> jax.Array:
        if self.results is None:
            self.reqs.append(v)
            return jnp.zeros((self.ctx.nseg,), v.dtype)
        r = self.results[self.replay_i]
        self.replay_i += 1
        return r

    def resolve(self):
        ctx = self.ctx
        n = ctx.gid.shape[0]
        C = 256
        ints = [(i, v) for i, v in enumerate(self.reqs)
                if jnp.issubdtype(v.dtype, jnp.integer) and n % C == 0]
        results: list = [None] * len(self.reqs)
        if ints:
            nc = n // C
            oh = (ctx.gid[:, None] == jnp.arange(ctx.nseg, dtype=ctx.gid.dtype)[None, :])
            oh = oh.astype(jnp.float32).reshape(nc, C, ctx.nseg)
            limbs = []
            for _, v in ints:
                v64 = v.astype(jnp.int64)
                for k in range(4):
                    limbs.append(((v64 >> (16 * k)) & jnp.int64(0xFFFF)).astype(jnp.float32))
            lm = jnp.stack(limbs, axis=1).reshape(nc, C, len(limbs))  # [nc, C, L]
            # [nc, G, L] — each chunk's per-group limb sums. Precision
            # HIGHEST is required: the TPU's default bf16 matmul pass
            # would round 16-bit limbs to 8 significand bits (caught by
            # the q1 parity gate); HIGHEST runs the exact-f32 passes
            part = jax.lax.dot_general(
                oh, lm, (((1,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
            # widen BEFORE the cross-chunk sum: nc*2^24 exceeds f32's
            # integer-exact range; int64 accumulation is exact (<= 2^16*n)
            tot = part.astype(jnp.int64).sum(axis=0)  # [G, L]
            for j, (i, v) in enumerate(ints):
                t = tot[:, 4 * j : 4 * j + 4]
                s = (t[:, 0] + (t[:, 1] << 16) + (t[:, 2] << 32) + (t[:, 3] << 48))
                results[i] = s.astype(v.dtype) if v.dtype != jnp.int64 else s
        for i, v in enumerate(self.reqs):
            if results[i] is None:
                zero = jnp.zeros((), v.dtype)
                results[i] = jnp.sum(
                    jnp.where(_dense_mask(ctx), v[:, None], zero), axis=0
                )
        self.results = results
        self.replay_i = 0


def _dense_mask(ctx: DenseCtx):
    """[N, G] slot-membership mask (fuses into the consuming reduce)."""
    iota = jnp.arange(ctx.nseg, dtype=ctx.gid.dtype)
    return ctx.gid[:, None] == iota[None, :]


def dense_first_match(ctx: DenseCtx, mask: jax.Array):
    """Per-group ORIGINAL position of the first mask row + has-any flag.
    (Dense rows are unsorted, so 'first' = min original index directly.)"""
    n = mask.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    m = _dense_mask(ctx) & mask[:, None]
    fi = jnp.min(jnp.where(m, iota[:, None], jnp.int32(n)), axis=0)
    has = fi < n
    return jnp.where(has, fi, 0), has


def sorted_positions(sorted_hay, queries, side: str = "left"):
    """searchsorted with the implementation chosen by query count: few
    queries -> the binary search (log2(N) gather rounds of q elements);
    many -> merge_searchsorted (2 plain sorts). Crossover ~N/64 queries
    (binary costs ~18*q gathers at ~16ns, the merge ~2 sorts at ~1ns/row)."""
    n, q = sorted_hay.shape[0], queries.shape[0]
    if q <= 2048 or q < n // 64:
        return jnp.searchsorted(sorted_hay, queries, side=side).astype(jnp.int32)
    return merge_searchsorted(sorted_hay, queries, side=side).astype(jnp.int32)


def make_segctx(seg: jax.Array, nseg: int) -> SegCtx:
    """seg must be DENSE ascending (consecutive ids 0..K then constant, as
    segments_from_sorted and the stream/cap paths produce): run k then
    starts segment k, so `starts` is ONE stream-compaction sort (boundary
    rows first, stable by position) instead of two merge_searchsorted
    passes (4 sorts of N+nseg each — this function used to be half the
    sort count of a whole group-by program). ends fall out of starts:
    dense ids leave no gaps below the last run. Small nseg keeps the
    sort-free binary searchsorted (log2(N) gather rounds of nseg lanes)."""
    n = seg.shape[0]
    if nseg <= 2048 or nseg < n // 64:
        starts = jnp.searchsorted(seg, jnp.arange(nseg, dtype=seg.dtype)).astype(jnp.int32)
    else:
        one = jnp.ones(1, bool)
        bnd = jnp.concatenate([one, seg[1:] != seg[:-1]])
        iota = jnp.arange(n, dtype=jnp.int32)
        _, pos = jax.lax.sort(((~bnd).astype(jnp.int8), iota), num_keys=2)
        n_runs = seg[-1].astype(jnp.int32) + 1
        if nseg > n:
            pos = jnp.concatenate([pos, jnp.full(nseg - n, n, jnp.int32)])
        g = jnp.arange(nseg, dtype=jnp.int32)
        starts = jnp.where(g < n_runs, pos[:nseg], jnp.int32(n))
    ends = jnp.concatenate([starts[1:], jnp.full(1, n, jnp.int32)]) - 1
    counts = jnp.maximum((ends - starts + 1).astype(jnp.int64), 0)
    return SegCtx(seg, nseg, starts, ends, counts)


def merge_searchsorted(sorted_hay, queries, side: str = "left"):
    """searchsorted as two plain sorts (merge + inverse permutation).

    jnp.searchsorted(method='sort') measures ~4.3ms for 32K hay + 262K
    queries on TPU while a raw 2-operand lax.sort of the same rows is
    0.2ms; this formulation gets the same positions for ~2 raw sorts. The
    default binary search ('scan') is worse still: ~17 serial gather
    rounds. Tie handling: side='left' sorts queries before equal hay
    (count = hay strictly less), side='right' after (count = hay <=)."""
    nh, nq = sorted_hay.shape[0], queries.shape[0]
    vals = jnp.concatenate([sorted_hay, queries])
    hay_rank = 1 if side == "left" else 0
    order = jnp.concatenate([
        jnp.full(nh, hay_rank, jnp.int32), jnp.full(nq, 1 - hay_rank, jnp.int32)
    ])
    qidx = jnp.concatenate([jnp.full(nh, nq, jnp.int32), jnp.arange(nq, dtype=jnp.int32)])
    _, so, sq = jax.lax.sort((vals, order, qidx), num_keys=2)
    cnt = jnp.cumsum((so == hay_rank).astype(jnp.int32))
    # bring query positions back to query order (hay rows carry qidx=nq
    # and sort to the tail)
    _, pos_sorted = jax.lax.sort((sq, cnt), num_keys=1)
    return pos_sorted[:nq]


def run_head_pos(diff: jax.Array) -> jax.Array:
    """Per-row position of the start of its equal-key run, given the
    boundary mask (diff[0] must be True). cummax, no gathers."""
    n = diff.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    return jax.lax.cummax(jnp.where(diff, pos, jnp.int32(0)))


def seg_sum(ctx, vals: jax.Array, dtype=None) -> jax.Array:
    """Per-segment sum via cumsum + boundary gathers (empty segments -> 0).
    Callers pre-mask invalid lanes to 0, exactly as with segment_sum.
    Routed through ctx.sums (one batched cumsum) when a SumBatch is armed;
    DenseCtx does one masked full reduction per group."""
    v = vals if dtype is None else vals.astype(dtype)
    if isinstance(ctx, DenseCtx):
        if ctx.sums is not None:
            return ctx.sums.add(v)
        zero = jnp.zeros((), v.dtype)
        return jnp.sum(jnp.where(_dense_mask(ctx), v[:, None], zero), axis=0)
    if ctx.nseg == 1:
        return jnp.sum(v, axis=0, keepdims=True)
    if ctx.sums is not None:
        return ctx.sums.add(v)
    n = v.shape[0]
    c = jnp.cumsum(v, axis=0)
    lo = jnp.clip(ctx.starts, 0, n - 1)
    hi = jnp.clip(ctx.ends, 0, n - 1)
    out = c[hi] - c[lo] + v[lo]
    zero = jnp.zeros((), v.dtype)
    return jnp.where(ctx.counts > 0, out, zero)


def _seg_scan_reduce(ctx: SegCtx, vals: jax.Array, combine, neutral, empty_fill):
    """Per-segment reduce of an arbitrary associative `combine` via a manual
    Hillis-Steele doubling scan (shift + where, log2(N) unrolled steps).

    NOT lax.associative_scan: its tuple-carry form lowers to variadic
    reduce-window, which on the TPU backend both hangs compilation at
    multi-M row counts and trips a scoped-vmem XLA bug. Plain shifts and
    selects compile as elementwise ops."""
    n = vals.shape[0]
    v = vals
    s = ctx.seg
    neutral_arr = jnp.full((1,), neutral, vals.dtype)
    d = 1
    while d < n:
        pv = jnp.concatenate([jnp.broadcast_to(neutral_arr, (d,)), v[:-d]])
        ps = jnp.concatenate([jnp.full((d,), -1, s.dtype), s[:-d]])
        v = jnp.where(s == ps, combine(v, pv), v)
        d *= 2
    out = v[jnp.clip(ctx.ends, 0, n - 1)]
    return jnp.where(ctx.counts > 0, out, empty_fill)


def seg_first_match(ctx, mask_s: jax.Array):
    """Per-segment sorted position of the FIRST mask row (int32 [nseg]),
    plus a has-any flag. A reverse cummin over (mask ? position : n) gives
    every position its nearest masked position at-or-after; reading it at
    the segment start yields the first masked row IN the segment — or a
    leak into a later segment, rejected by the extent check. No sort, no
    searchsorted.

    With the stable sort_by_word order, the first masked sorted position in
    a segment is also the masked row with the smallest original index.
    (DenseCtx rows are unsorted; positions are original indices.)"""
    if isinstance(ctx, DenseCtx):
        return dense_first_match(ctx, mask_s)
    n = mask_s.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    rcm = jax.lax.cummin(jnp.where(mask_s, iota, jnp.int32(n)), reverse=True)
    first = rcm[jnp.clip(ctx.starts, 0, n - 1)]
    has = (ctx.counts > 0) & (first <= ctx.ends)
    return jnp.where(has, jnp.clip(first, 0, n - 1), 0), has


def seg_min(ctx, vals: jax.Array) -> jax.Array:
    fill = jnp.inf if jnp.issubdtype(vals.dtype, jnp.floating) else jnp.iinfo(vals.dtype).max
    f = jnp.asarray(fill, vals.dtype)
    if isinstance(ctx, DenseCtx):
        return jnp.min(jnp.where(_dense_mask(ctx), vals[:, None], f), axis=0)
    if ctx.nseg == 1:
        return jnp.min(vals, axis=0, keepdims=True)
    return _seg_scan_reduce(ctx, vals, jnp.minimum, f, f)


def seg_max(ctx, vals: jax.Array) -> jax.Array:
    fill = -jnp.inf if jnp.issubdtype(vals.dtype, jnp.floating) else jnp.iinfo(vals.dtype).min
    f = jnp.asarray(fill, vals.dtype)
    if isinstance(ctx, DenseCtx):
        return jnp.max(jnp.where(_dense_mask(ctx), vals[:, None], f), axis=0)
    if ctx.nseg == 1:
        return jnp.max(vals, axis=0, keepdims=True)
    return _seg_scan_reduce(ctx, vals, jnp.maximum, f, f)


def seg_bitreduce(ctx, red, vals: jax.Array, fill) -> jax.Array:
    """Segmented bitwise and/or/xor (no jax.ops.segment_* exists for these;
    callers pre-mask invalid lanes to the identity). The doubling scan
    handles nseg==1 too (one segment == plain scan, last element = total)."""
    f = jnp.int64(fill)
    if isinstance(ctx, DenseCtx):
        mv = jnp.where(_dense_mask(ctx), vals[:, None], f)
        return jax.lax.reduce(mv, f, lambda a, b: red(a, b), (0,))
    return _seg_scan_reduce(ctx, vals, red, f, f)
