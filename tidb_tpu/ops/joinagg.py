"""Fused sort-merge join + stream aggregation — the TPC-H Q3 shape.

When a unique-build inner join feeds a GROUP BY on exactly the probe-side
join key, the join's merge sort already clusters rows by the group key, so
ONE variadic sort (build and probe key words interleaved, agg arguments
riding as payload operands) performs the probe AND the grouping. The
general pipeline pays three more full-size sorts on top of that one — the
inverse permutation back to probe order, the aggregation's hash-cluster
sort, and the segment-boundary construction — and this kernel skips all of
them: a stream-agg boundary scan runs directly on the merge order.

On TPU the sort IS the unit of cost for join/group plans (every other pass
is a cumsum-class scan), so sharing one sort between the two operators is
the whole win — the analog of the reference handing hash-join output
straight to a stream aggregate when orders match (ref:
pkg/executor/join/hash_join_v2.go build/probe,
pkg/executor/aggregate/agg_stream_executor.go sorted-input contract).

Matching mirrors ops/join.py's unique-build inner-join semantics exactly:
NULL keys never match, a build fan-out > 1 raises the join-overflow flag
(the driver retries on the general kernel), and group capacity overflow
raises the group flag. Output group order is the oracle's first-encounter
order (earliest contributing probe row), recovered by riding the original
probe index through the sort.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..expr.compile import CompVal
from .aggregate import GatherState, _group_aggregate_stream
from .join import _key_matrix
from .seg import I64_MAX

# aggregate names the stream kernel evaluates without raw-byte payloads or
# the DISTINCT hash machinery (ops/aggregate.py _agg_states_raw coverage)
FUSABLE_AGGS = frozenset({
    "count", "sum", "avg", "min", "max", "first_row",
    "bit_and", "bit_or", "bit_xor",
    "stddev_pop", "stddev_samp", "var_pop", "var_samp",
})


def join_stream_agg(
    build_keys: list[CompVal],
    probe_keys: list[CompVal],
    build_valid,
    probe_valid,
    aggs: list,
    group_capacity: int,
):
    """One-sort unique-build inner join + GROUP BY probe key.

    aggs: list of (AggDesc, [probe-row-order arg CompVals]); every arg must
    be single-word (ndim 1, no raw bytes) — the caller checks eligibility.
    Returns (GroupAggResult, sorted_arg_lists, group_out CompVal,
    join_overflow, join_rows); res.group_rep indexes the SORTED row space,
    aligned with sorted_arg_lists and group_out; join_rows is the joined
    row count for the exec summaries.
    """
    bw_l, b_usable = _key_matrix(build_keys, build_valid)
    pw_l, p_usable = _key_matrix(probe_keys, probe_valid)
    assert len(bw_l) == 1 and len(pw_l) == 1, "joinagg needs single-word keys"
    bw, pw = bw_l[0], pw_l[0]
    nb, np_ = bw.shape[0], pw.shape[0]
    n = nb + np_
    top = jnp.inf if jnp.issubdtype(bw.dtype, jnp.floating) else I64_MAX
    vals = jnp.concatenate([
        jnp.where(b_usable, bw, top), jnp.where(p_usable, pw, top),
    ])
    # sort key 2: build rows first within an equal-key run, so a probe row's
    # cumulative hay count already includes its whole run; lax.sort is
    # stable, so probe rows keep original ascending order inside a run
    side = jnp.concatenate([jnp.zeros(nb, jnp.int8), jnp.ones(np_, jnp.int8)])

    payload: list = []
    slot_of: dict = {}

    def carry(hay_fill, arr) -> int:
        key = (id(arr), repr(hay_fill))
        if key not in slot_of:
            slot_of[key] = len(payload)
            payload.append(jnp.concatenate([
                jnp.full((nb,), hay_fill, arr.dtype), arr,
            ]))
        return slot_of[key]

    # original probe index (first-encounter output order + group_rep remap)
    iota_slot = len(payload)
    payload.append(jnp.concatenate([
        jnp.full(nb, n, jnp.int32), jnp.arange(np_, dtype=jnp.int32),
    ]))
    # group-by output value = the probe key's original value lane
    gkey_slot = carry(0, probe_keys[0].value)

    bool_arrs: list = [jnp.concatenate([b_usable, p_usable])]
    bool_ix: dict = {}

    def carry_bool(hay_fill: bool, arr) -> int:
        key = (id(arr), hay_fill)
        if key not in bool_ix:
            bool_ix[key] = len(bool_arrs)
            bool_arrs.append(jnp.concatenate([
                jnp.full(nb, hay_fill, bool), arr,
            ]))
        return bool_ix[key]

    plans = []  # per agg: [(value_slot, null_bit)] per arg
    for desc, avs in aggs:
        slots = []
        for a in avs:
            slots.append((carry(0, a.value), carry_bool(True, a.null)))
        plans.append(slots)

    nwords = []
    for w0 in range(0, len(bool_arrs), 8):
        grp = bool_arrs[w0 : w0 + 8]
        word = grp[0].astype(jnp.uint8)
        for k, a in enumerate(grp[1:], start=1):
            word = word | (a.astype(jnp.uint8) << k)
        nwords.append(word)

    sorted_ops = jax.lax.sort(tuple([vals, side] + payload + nwords), num_keys=2)
    sv, ss = sorted_ops[0], sorted_ops[1]
    pay_s = list(sorted_ops[2 : 2 + len(payload)])
    nw_s = list(sorted_ops[2 + len(payload) :])
    usable_s = ((nw_s[0] >> 0) & 1).astype(bool)
    is_hay = ss == 0
    hay_u = is_hay & usable_s

    one = jnp.ones(1, bool)
    diff = jnp.concatenate([one, sv[1:] != sv[:-1]])
    hcnt = jnp.cumsum(hay_u.astype(jnp.int32))
    # usable-hay count strictly before my run (run-start propagation; the
    # marked values are nondecreasing, so a forward cummax broadcasts each
    # run head's value across its run — the merge_lo_hi trick)
    base = jax.lax.cummax(jnp.where(diff, hcnt - hay_u, jnp.int32(-1)))
    matched = (hcnt - base) > 0
    # run's total usable hay: hcnt at the run END, propagated backward
    # (ends carry nondecreasing hcnt, so reverse cummin finds MY run's end)
    emark = jnp.concatenate([diff[1:], one])
    endv = jax.lax.cummin(
        jnp.where(emark, hcnt, jnp.iinfo(jnp.int32).max), reverse=True
    )
    run_hay = endv - base
    contrib = (~is_hay) & usable_s & matched
    # unique-build contract: any probe matching a >1-row build run
    join_overflow = jnp.any((run_hay > 1) & contrib)

    def resort(a: CompVal, slots) -> CompVal:
        vslot, nbit = slots
        null = ((nw_s[nbit // 8] >> (nbit % 8)) & 1).astype(bool)
        return CompVal(pay_s[vslot], null, a.ft)

    key_ft = probe_keys[0].ft
    sorted_aggs = [
        (desc, [resort(a, sl) for a, sl in zip(avs, plan)])
        for (desc, avs), plan in zip(aggs, plans)
    ]
    res = _group_aggregate_stream(
        [CompVal(sv, jnp.zeros(n, bool), key_ft)],
        sorted_aggs, contrib, group_capacity, merge=False, compact=False,
    )

    # compact=False: res.group_valid is raw has-flags in key order. ONE
    # argsort on the earliest ORIGINAL probe index (ridden through the
    # sort) both compacts contributing groups to the front and restores
    # the oracle's first-encounter output order.
    orig_s = pay_s[iota_slot]
    gc = res.group_rep.shape[0]
    orig_first = jnp.where(
        res.group_valid, orig_s[jnp.clip(res.group_rep, 0, n - 1)], jnp.int32(n)
    )
    order = jnp.argsort(orig_first)
    res.group_rep = res.group_rep[order]
    gids = jnp.arange(gc, dtype=jnp.int32)
    res.group_valid = gids < res.n_groups
    states2 = []
    for st in res.states:
        if isinstance(st, GatherState):
            states2.append(GatherState(st.idx[order], st.has[order]))
        else:
            states2.append([(v[order], nl[order]) for v, nl in st])
    res.states = states2

    group_out = CompVal(pay_s[gkey_slot], jnp.zeros(n, bool), key_ft)
    join_rows = contrib.sum().astype(jnp.int64)
    return res, sorted_aggs, group_out, join_overflow, join_rows


# --------------------------------------------------------------------------
# packed-key fast path: bounded-range int keys, sum/count/avg only
# --------------------------------------------------------------------------
#
# Measured v5e floors (2026-07-31, tunneled chip): a 2-operand int32
# lax.sort costs ~6ms at 4M rows while adding ONE int64 operand takes it
# to ~16ms and a 3rd int32 operand to ~17.5ms; every scan op has a ~2-3ms
# floor; random gathers are ~16ns/row and scatter-add ~100ns/row
# (useless). The packed path is shaped by those numbers: ONE int32-only
# sort (key+side packed in one word, each agg argument as a SINGLE int32
# lane), match/boundary logic that is pure elementwise neighbor algebra,
# and per-group extents from cumsum + reverse-cummin pairs whose addends
# are statically biased by +2^31 (int32 lanes make the monotonicity
# precondition free — no runtime shift/bound reduce at all, the [2A+1, N]
# min-reduce of the old int64 variant is gone). Outputs live at
# run-boundary positions of the sorted [nb+np] space under a validity
# mask — no group capacity exists, so the overflow-retry ladder never
# fires for group count.
#
# Values outside int32 raise the join-overflow flag and the driver lands
# on the general sort kernel — the same contract key ranges over 2^30
# always had (an opportunistic fast path, never a semantics change).

_PACKED_AGGS = frozenset({"sum", "count", "avg"})
_PK_RANGE = 1 << 30  # packed (key - kmin) must fit 30 bits (plus side bit)
# unusable-row sentinels: above every packed key; hay (even) and probe
# (odd, = _PIN_HAY|1) pins keep is_hay = ~(pk&1) true even for pins
_PIN_HAY = np.int32((1 << 31) - 4)  # numpy: import-time pure (vet: jit-purity)
_PIN_PROBE = np.int32((1 << 31) - 3)
I32_SHIFT = 1 << 31  # static non-negativity bias per addend (plain int:
# a module-level jnp expression would leak a tracer when this module is
# first imported inside a jit trace — the builder imports it lazily)


def _pack_keys(both, ok, side):
    """key << 1 | side as int32; unusable rows pin above all real keys.
    Returns (pk, bad_lane). Keys are packed at their ABSOLUTE value (no
    min-rebase): the old rebasing min-reduce sat on the critical path
    BEFORE the sort (a ~3ms serial dependency on the tunneled v5e), while
    the |key| < 2^30-2 width check is pure elementwise — out-of-range
    usable keys pin AND mark the bad lane, which the caller folds into
    its one batched overflow any() (-> the general-kernel retry, exactly
    as rebased range overflow always did)."""
    k32 = both.astype(jnp.int32)
    # range check in int64: jnp.abs(k32) wraps for INT32_MIN (abs returns
    # INT32_MIN itself, which passes < 2^30-2), so key -2^31 would pack to
    # pk 0 and silently join as phantom key 0 (ADVICE r5 high). `both` is
    # already int64 — |key| in that domain is exact for every int32 value.
    in_range = (both == k32.astype(jnp.int64)) & (jnp.abs(both) < (_PK_RANGE - 2))
    usable = ok & in_range
    pk = jnp.where(
        usable,
        (k32 << 1) | side,
        jnp.where(side == 0, _PIN_HAY, _PIN_PROBE),
    )
    return pk, ok & ~in_range


def membership_chain(outer_key, outer_ok, inner_key, inner_ok, payload):
    """Unique-build membership join whose OUTPUT ORDER is free.

    Outer rows (e.g. orders) probe inner rows (e.g. customers) on an int
    key; returns (payload_out, ok_out, overflow) of length n_inner+n_outer
    where ok_out marks outer rows that matched a usable inner row — in
    inner-key sort order, which packed_join_groupsum accepts as-is, so NO
    inverse permutation sort is ever paid. payload: int64 per-outer-row
    value carried through (the next join's key); inner slots come back
    with ok_out False. Payloads outside int32 overflow (-> general
    kernel), keeping the sort at TWO int32 operands."""
    no, nc = outer_key.shape[0], inner_key.shape[0]
    both = jnp.concatenate([inner_key.astype(jnp.int64), outer_key.astype(jnp.int64)])
    ok = jnp.concatenate([inner_ok, outer_ok])
    side = jnp.concatenate([jnp.zeros(nc, jnp.int32), jnp.ones(no, jnp.int32)])
    pk, kbad = _pack_keys(both, ok, side)
    pay32 = payload.astype(jnp.int32)
    wbad = (outer_ok & (payload.astype(jnp.int64) != pay32.astype(jnp.int64))) | kbad[nc:]
    wbad = jnp.concatenate([kbad[:nc], wbad])
    pay = jnp.concatenate([jnp.zeros(nc, jnp.int32), pay32])
    spk, spay = jax.lax.sort((pk, pay), num_keys=1)

    from .dense_pallas import pallas_mode

    mode = pallas_mode()
    if mode:
        from .joinscan import membership_segscan

        ok_out, overflow = membership_segscan(
            spk, wbad, interpret=(mode == "interpret")
        )
        return spay.astype(jnp.int64), ok_out, overflow
    is_inner = (spk & 1) == 0
    is_real = spk < _PIN_HAY
    # sentinel below every real pk (|key| < 2^30-2 keeps pk > INT32_MIN+4;
    # -2 collided with real key -1 under no-rebase packing)
    prev_pk = jnp.concatenate([jnp.full(1, -(2**31), jnp.int32), spk[:-1]])
    # duplicate usable inner keys (adjacent equal pk on the inner side) and
    # payload width, batched into ONE any() (reduce floors — see below)
    overflow = jnp.any(jnp.stack([
        is_inner & is_real & (spk == prev_pk),
        wbad,
    ]))
    keydiff = (spk | jnp.int32(1)) != (prev_pk | jnp.int32(1))
    # run-head flag ("head is a usable inner row") packed into the LSB of
    # a strictly increasing head marker, so a forward cummax broadcasts
    # THIS run's head flag without scans ever crossing runs
    n = no + nc
    iota = jnp.arange(n, dtype=jnp.int32)
    marker = jnp.where(
        keydiff,
        iota * 2 + (is_inner & is_real).astype(jnp.int32),
        jnp.int32(-1),
    )
    head = jax.lax.cummax(marker)
    ok_out = (~is_inner) & is_real & ((head & 1) == 1)
    return spay.astype(jnp.int64), ok_out, overflow


def packed_join_groupsum(hay_key, hay_ok, probe_key, probe_ok, aggs):
    """Unique-build inner join + GROUP BY probe key (int class), aggregates
    restricted to sum/count/avg over int/decimal args that fit int32.

    aggs: [(AggDesc, [arg CompVals in probe row order])]. Returns
    (states per agg, group_valid, key_out CompVal, overflow, join_rows);
    everything is in the sorted [nb+np] row space: group results live at
    each group's first probe row, group_valid masks exactly those rows.
    overflow (-> driver's join-overflow retry, landing on the general
    kernel) fires on: key range over 2^30, duplicate usable hay keys
    (unique-build violation), or an agg argument outside int32."""
    nb, np_ = hay_key.shape[0], probe_key.value.shape[0]
    n = nb + np_
    both = jnp.concatenate([hay_key.astype(jnp.int64), probe_key.value.astype(jnp.int64)])
    ok = jnp.concatenate([hay_ok, probe_ok])
    side = jnp.concatenate([jnp.zeros(nb, jnp.int32), jnp.ones(np_, jnp.int32)])
    pk, kbad = _pack_keys(both, ok, side)

    # one int32 sort: packed key + ONE int32 lane per distinct agg argument
    # (nulls pre-masked to 0 so only COUNT needs the null-bit word).
    # NOT NULL args (FieldType flag) skip the null machinery entirely.
    from ..types import Flag

    lanes: list = []
    combo_of: dict = {}
    nullbit_of: dict = {}
    nbits: list = []
    width_bad = jnp.zeros(np_, bool)  # batched into the ONE post-sort reduce
    for desc, avs in aggs:
        for a in avs:
            key = (id(a.value), id(a.null))
            if key not in combo_of:
                combo_of[key] = len(lanes)
                v32 = a.value.astype(jnp.int32)
                width_bad = width_bad | (
                    probe_ok & ~a.null
                    & (a.value.astype(jnp.int64) != v32.astype(jnp.int64))
                )
                vm = jnp.where(a.null, jnp.int32(0), v32)
                lanes.append(jnp.concatenate([jnp.zeros(nb, jnp.int32), vm]))
            if bool(a.ft.flag & Flag.NotNull):
                nullbit_of[id(a.null)] = -1  # alias of the contrib mask
            elif id(a.null) not in nullbit_of:
                nullbit_of[id(a.null)] = len(nbits)
                nbits.append(jnp.concatenate([jnp.ones(nb, bool), a.null]))
    nword = jnp.zeros(n, jnp.uint8)
    for k, b in enumerate(nbits):
        nword = nword | (b.astype(jnp.uint8) << k)
    ops = [pk] + lanes + ([nword] if nbits else [])
    sorted_ops = jax.lax.sort(tuple(ops), num_keys=1)
    spk = sorted_ops[0]
    lanes_s = list(sorted_ops[1 : 1 + len(lanes)])
    nw_s = sorted_ops[-1] if nbits else None

    from .dense_pallas import pallas_mode

    mode = pallas_mode()
    if mode and len(lanes) <= 2:
        # TPU fast path: ONE Pallas sweep replaces every post-sort scan
        # and the overflow reduce (ops/joinscan.py)
        from .joinscan import postsort_segscan

        lane_keys = list(combo_of)
        nn_bits = [nullbit_of[k[1]] for k in lane_keys]
        bad_all = kbad | jnp.concatenate([jnp.zeros(nb, bool), width_bad])
        gv, cnt, key32, sums, nns, ovf, _jr = postsort_segscan(
            spk, lanes_s, bad_all, nw_s=nw_s, nn_bits=nn_bits,
            interpret=(mode == "interpret"),
        )
        by_combo = {k: (sums[i], nns[i]) for i, k in enumerate(lane_keys)}
        zeros = jnp.zeros(n, bool)
        states = []
        for desc, avs in aggs:
            if desc.name == "count":
                if avs:
                    _, nn = by_combo[(id(avs[0].value), id(avs[0].null))]
                    states.append([(nn, zeros)])
                else:
                    states.append([(cnt, zeros)])
                continue
            a = avs[0]
            s, nn = by_combo[(id(a.value), id(a.null))]
            empty = nn == 0
            if desc.name == "sum":
                states.append([(s, empty)])
            else:  # avg: [count, sum]
                states.append([(nn, zeros), (s, empty)])
        key_out = CompVal(
            jnp.where(gv, (key32 >> 1).astype(jnp.int64), jnp.int64(0)),
            zeros, probe_key.ft,
        )
        return states, gv, key_out, ovf, cnt

    is_hay = (spk & 1) == 0
    is_real = spk < _PIN_HAY
    # sentinel below every real pk (|key| < 2^30-2 keeps pk > INT32_MIN+4;
    # -2 collided with real key -1 under no-rebase packing)
    prev_pk = jnp.concatenate([jnp.full(1, -(2**31), jnp.int32), spk[:-1]])
    dup_hay = is_hay & is_real & (spk == prev_pk)
    # ONE batched any() for every per-row overflow condition (each
    # standalone reduce costs a ~1.5-3ms dispatch floor on this platform)
    overflow = jnp.any(
        jnp.stack([dup_hay, kbad | jnp.concatenate([jnp.zeros(nb, bool), width_bad])])
    )
    keydiff = (spk | jnp.int32(1)) != (prev_pk | jnp.int32(1))
    # first probe row of its key run (prev is hay, or a different key);
    # matched iff prev row is the hay of MY key - all neighbor algebra
    pbnd = (~is_hay) & is_real & (keydiff | ((prev_pk & 1) == 0))
    matched = pbnd & (prev_pk == spk - 1)
    emark = jnp.concatenate([keydiff[1:], jnp.ones(1, bool)])

    # run extents: the run end POSITION comes from one int32 reverse
    # cummin and positions give the contributing count directly
    iota = jnp.arange(n, dtype=jnp.int32)
    end_pos = jax.lax.cummin(
        jnp.where(emark, iota, jnp.int32(n)), reverse=True
    )
    extent_cnt = (end_pos - iota + 1).astype(jnp.int64)  # rows self..run end
    big = jnp.int64(0x7FFFFFFFFFFFFFFF)

    def _extent(addends):
        """Sum of `addends` (int64, non-negative) over [self..run end]."""
        c = jnp.cumsum(addends)
        ev = jax.lax.cummin(jnp.where(emark, c, big), reverse=True)
        return ev - (c - addends)

    combo_sum: dict = {}
    combo_nn: dict = {}
    for key, li in combo_of.items():
        shifted = lanes_s[li].astype(jnp.int64) + I32_SHIFT
        # every row in the extent carried (vm + 2^31), null rows as 0+2^31
        combo_sum[key] = _extent(shifted) - extent_cnt * I32_SHIFT
    for desc, avs in aggs:
        for a in avs:
            nb_ = nullbit_of[id(a.null)]
            key = (id(a.value), id(a.null))
            if key in combo_nn:
                continue
            if nb_ < 0:
                combo_nn[key] = extent_cnt
            else:
                nn = (((nw_s >> nb_) & 1) == 0).astype(jnp.int64)
                combo_nn[key] = _extent(nn)

    group_valid = pbnd & matched
    zeros = jnp.zeros(n, bool)
    states = []
    for desc, avs in aggs:
        if desc.name == "count":
            if avs:
                cnt = combo_nn[(id(avs[0].value), id(avs[0].null))]
            else:
                cnt = extent_cnt
            states.append([(cnt, zeros)])
            continue
        a = avs[0]
        key = (id(a.value), id(a.null))
        s = combo_sum[key]
        cnt_nn = combo_nn[key]
        empty = cnt_nn == 0
        if desc.name == "sum":
            states.append([(s, empty)])
        else:  # avg: [count, sum] (expr/agg.py partial schema)
            states.append([(cnt_nn, zeros), (s, empty)])

    key_out = CompVal(
        jnp.where(is_real, (spk >> 1).astype(jnp.int64), jnp.int64(0)),
        zeros, probe_key.ft,
    )
    return states, group_valid, key_out, overflow, extent_cnt
