"""Fused sort-merge join + stream aggregation — the TPC-H Q3 shape.

When a unique-build inner join feeds a GROUP BY on exactly the probe-side
join key, the join's merge sort already clusters rows by the group key, so
ONE variadic sort (build and probe key words interleaved, agg arguments
riding as payload operands) performs the probe AND the grouping. The
general pipeline pays three more full-size sorts on top of that one — the
inverse permutation back to probe order, the aggregation's hash-cluster
sort, and the segment-boundary construction — and this kernel skips all of
them: a stream-agg boundary scan runs directly on the merge order.

On TPU the sort IS the unit of cost for join/group plans (every other pass
is a cumsum-class scan), so sharing one sort between the two operators is
the whole win — the analog of the reference handing hash-join output
straight to a stream aggregate when orders match (ref:
pkg/executor/join/hash_join_v2.go build/probe,
pkg/executor/aggregate/agg_stream_executor.go sorted-input contract).

Matching mirrors ops/join.py's unique-build inner-join semantics exactly:
NULL keys never match, a build fan-out > 1 raises the join-overflow flag
(the driver retries on the general kernel), and group capacity overflow
raises the group flag. Output group order is the oracle's first-encounter
order (earliest contributing probe row), recovered by riding the original
probe index through the sort.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..expr.compile import CompVal
from .aggregate import GatherState, _group_aggregate_stream
from .join import _key_matrix
from .seg import I64_MAX

# aggregate names the stream kernel evaluates without raw-byte payloads or
# the DISTINCT hash machinery (ops/aggregate.py _agg_states_raw coverage)
FUSABLE_AGGS = frozenset({
    "count", "sum", "avg", "min", "max", "first_row",
    "bit_and", "bit_or", "bit_xor",
    "stddev_pop", "stddev_samp", "var_pop", "var_samp",
})


def join_stream_agg(
    build_keys: list[CompVal],
    probe_keys: list[CompVal],
    build_valid,
    probe_valid,
    aggs: list,
    group_capacity: int,
):
    """One-sort unique-build inner join + GROUP BY probe key.

    aggs: list of (AggDesc, [probe-row-order arg CompVals]); every arg must
    be single-word (ndim 1, no raw bytes) — the caller checks eligibility.
    Returns (GroupAggResult, sorted_arg_lists, group_out CompVal,
    join_overflow, join_rows); res.group_rep indexes the SORTED row space,
    aligned with sorted_arg_lists and group_out; join_rows is the joined
    row count for the exec summaries.
    """
    bw_l, b_usable = _key_matrix(build_keys, build_valid)
    pw_l, p_usable = _key_matrix(probe_keys, probe_valid)
    assert len(bw_l) == 1 and len(pw_l) == 1, "joinagg needs single-word keys"
    bw, pw = bw_l[0], pw_l[0]
    nb, np_ = bw.shape[0], pw.shape[0]
    n = nb + np_
    top = jnp.inf if jnp.issubdtype(bw.dtype, jnp.floating) else I64_MAX
    vals = jnp.concatenate([
        jnp.where(b_usable, bw, top), jnp.where(p_usable, pw, top),
    ])
    # sort key 2: build rows first within an equal-key run, so a probe row's
    # cumulative hay count already includes its whole run; lax.sort is
    # stable, so probe rows keep original ascending order inside a run
    side = jnp.concatenate([jnp.zeros(nb, jnp.int8), jnp.ones(np_, jnp.int8)])

    payload: list = []
    slot_of: dict = {}

    def carry(hay_fill, arr) -> int:
        key = (id(arr), repr(hay_fill))
        if key not in slot_of:
            slot_of[key] = len(payload)
            payload.append(jnp.concatenate([
                jnp.full((nb,), hay_fill, arr.dtype), arr,
            ]))
        return slot_of[key]

    # original probe index (first-encounter output order + group_rep remap)
    iota_slot = len(payload)
    payload.append(jnp.concatenate([
        jnp.full(nb, n, jnp.int32), jnp.arange(np_, dtype=jnp.int32),
    ]))
    # group-by output value = the probe key's original value lane
    gkey_slot = carry(0, probe_keys[0].value)

    bool_arrs: list = [jnp.concatenate([b_usable, p_usable])]
    bool_ix: dict = {}

    def carry_bool(hay_fill: bool, arr) -> int:
        key = (id(arr), hay_fill)
        if key not in bool_ix:
            bool_ix[key] = len(bool_arrs)
            bool_arrs.append(jnp.concatenate([
                jnp.full(nb, hay_fill, bool), arr,
            ]))
        return bool_ix[key]

    plans = []  # per agg: [(value_slot, null_bit)] per arg
    for desc, avs in aggs:
        slots = []
        for a in avs:
            slots.append((carry(0, a.value), carry_bool(True, a.null)))
        plans.append(slots)

    nwords = []
    for w0 in range(0, len(bool_arrs), 8):
        grp = bool_arrs[w0 : w0 + 8]
        word = grp[0].astype(jnp.uint8)
        for k, a in enumerate(grp[1:], start=1):
            word = word | (a.astype(jnp.uint8) << k)
        nwords.append(word)

    sorted_ops = jax.lax.sort(tuple([vals, side] + payload + nwords), num_keys=2)
    sv, ss = sorted_ops[0], sorted_ops[1]
    pay_s = list(sorted_ops[2 : 2 + len(payload)])
    nw_s = list(sorted_ops[2 + len(payload) :])
    usable_s = ((nw_s[0] >> 0) & 1).astype(bool)
    is_hay = ss == 0
    hay_u = is_hay & usable_s

    one = jnp.ones(1, bool)
    diff = jnp.concatenate([one, sv[1:] != sv[:-1]])
    hcnt = jnp.cumsum(hay_u.astype(jnp.int32))
    # usable-hay count strictly before my run (run-start propagation; the
    # marked values are nondecreasing, so a forward cummax broadcasts each
    # run head's value across its run — the merge_lo_hi trick)
    base = jax.lax.cummax(jnp.where(diff, hcnt - hay_u, jnp.int32(-1)))
    matched = (hcnt - base) > 0
    # run's total usable hay: hcnt at the run END, propagated backward
    # (ends carry nondecreasing hcnt, so reverse cummin finds MY run's end)
    emark = jnp.concatenate([diff[1:], one])
    endv = jax.lax.cummin(
        jnp.where(emark, hcnt, jnp.iinfo(jnp.int32).max), reverse=True
    )
    run_hay = endv - base
    contrib = (~is_hay) & usable_s & matched
    # unique-build contract: any probe matching a >1-row build run
    join_overflow = jnp.any((run_hay > 1) & contrib)

    def resort(a: CompVal, slots) -> CompVal:
        vslot, nbit = slots
        null = ((nw_s[nbit // 8] >> (nbit % 8)) & 1).astype(bool)
        return CompVal(pay_s[vslot], null, a.ft)

    key_ft = probe_keys[0].ft
    sorted_aggs = [
        (desc, [resort(a, sl) for a, sl in zip(avs, plan)])
        for (desc, avs), plan in zip(aggs, plans)
    ]
    res = _group_aggregate_stream(
        [CompVal(sv, jnp.zeros(n, bool), key_ft)],
        sorted_aggs, contrib, group_capacity, merge=False, compact=False,
    )

    # compact=False: res.group_valid is raw has-flags in key order. ONE
    # argsort on the earliest ORIGINAL probe index (ridden through the
    # sort) both compacts contributing groups to the front and restores
    # the oracle's first-encounter output order.
    orig_s = pay_s[iota_slot]
    gc = res.group_rep.shape[0]
    orig_first = jnp.where(
        res.group_valid, orig_s[jnp.clip(res.group_rep, 0, n - 1)], jnp.int32(n)
    )
    order = jnp.argsort(orig_first)
    res.group_rep = res.group_rep[order]
    gids = jnp.arange(gc, dtype=jnp.int32)
    res.group_valid = gids < res.n_groups
    states2 = []
    for st in res.states:
        if isinstance(st, GatherState):
            states2.append(GatherState(st.idx[order], st.has[order]))
        else:
            states2.append([(v[order], nl[order]) for v, nl in st])
    res.states = states2

    group_out = CompVal(pay_s[gkey_slot], jnp.zeros(n, bool), key_ft)
    join_rows = contrib.sum().astype(jnp.int64)
    return res, sorted_aggs, group_out, join_overflow, join_rows
