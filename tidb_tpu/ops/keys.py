"""Key normalization: every sortable/groupable value becomes int64 arrays.

All kernel machinery (sort, segment detection, join probe) then works on a
uniform list of int64 key arrays with lexicographic semantics:

  numeric int/decimal/time  [null_flag, value]
  real                      [null_flag, order-preserving bit trick]
  string                    [null_flag, word0..wordW, length]

NULL ordering follows MySQL: NULLs sort first ascending / last descending;
for GROUP BY, NULLs form one group (ref: aggExec treats NULL keys as equal,
unistore/cophandler/mpp_exec.go:999). The float trick mirrors
codec.EncodeFloat (ref: pkg/util/codec/float.go:23): flip all bits for
negatives, flip the sign bit for positives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..expr.compile import CompVal, I64_MIN


def _float_sortable(v: jax.Array) -> jax.Array:
    """Floats stay float keys — XLA sort/compare gives the right total order
    once -0.0 is canonicalized, and a 64-bit bitcast would break the TPU
    x64-emulation rewrite (s64 is a pair of u32 under the hood there)."""
    return jnp.where(v == 0.0, 0.0, v.astype(jnp.float64))


def sort_key_arrays(v: CompVal, desc: bool = False) -> list[jax.Array]:
    """CompVal -> int64 arrays, most significant first.

    Ascending lexicographic order on the result == SQL ORDER BY order of the
    value with NULLs first; `desc` bit-inverts every word (an order-reversing
    bijection on int64), which also puts NULLs last, matching MySQL DESC.
    NULL rows' value lanes are zeroed so all NULLs compare equal (one group).
    """
    nf = 1 - v.null.astype(jnp.int64)  # null -> 0 (sorts first ascending)
    if v.value.ndim == 2:
        words = v.value
        if v.ft.is_ci():
            # general_ci: fold before keying so 'a' and 'A' share a group /
            # sort slot / join bucket (ref: collate.GetCollator key form)
            from ..expr.compile import fold_words_ci

            words = fold_words_ci(words)
        arrs = [nf] + [words[:, i] for i in range(words.shape[1])]
    elif v.eval_type == "real":
        arrs = [nf, _float_sortable(v.value)]
    elif v.ft.is_unsigned() and v.eval_type == "int":
        arrs = [nf, v.value ^ I64_MIN]
    else:
        arrs = [nf, v.value.astype(jnp.int64)]
    arrs = [arrs[0]] + [jnp.where(v.null, jnp.zeros((), a.dtype), a) for a in arrs[1:]]
    if desc:
        # order-reversing bijection: bit-inverse for ints, negation for floats
        arrs = [-a if jnp.issubdtype(a.dtype, jnp.floating) else ~a for a in arrs]
    return arrs


def lexsort(keys: list[jax.Array], extra_key: jax.Array | None = None):
    """Stable lexicographic argsort, most-significant key first.

    jnp.lexsort treats its *last* key as primary, so reverse. `extra_key`
    (least significant, e.g. original row index) goes first after reversal.
    """
    order = list(reversed(keys))
    if extra_key is not None:
        order = [extra_key] + order
    return jnp.lexsort(tuple(order))


def segments_from_sorted(sorted_keys: list[jax.Array], valid: jax.Array):
    """Given key arrays already in sorted row order plus a validity mask
    (invalid rows sorted to the end), return (segment_ids, n_groups).

    segment_ids: int32 [N], 0-based group index per row; invalid rows get
    segment id == n_groups (one past the last real group) so scatter-based
    reductions can drop them into a spare slot.
    """
    diff = jnp.zeros(valid.shape[0], bool)
    for k in sorted_keys:
        diff = diff | jnp.concatenate([jnp.ones(1, bool), k[1:] != k[:-1]])
    new_seg = diff & valid
    seg = jnp.cumsum(new_seg.astype(jnp.int32)) - 1
    n_groups = jnp.max(jnp.where(valid, seg, -1)) + 1
    seg = jnp.where(valid, seg, n_groups)
    return seg.astype(jnp.int32), n_groups.astype(jnp.int32)
