"""One-pass Pallas small-G aggregation — the TPC-H Q1 shape.

The XLA dense kernel (aggregate.py _group_aggregate_dense) materializes a
stack of [N, G] intermediates through HBM (the gid compare matrix, one
masked lane per aggregate state, the exactness-check lanes); at 4M rows x
16 slots that is ~20 full-size HBM round trips and it measures ~2.7% of
the chip's streaming roofline. This kernel replaces all of them with ONE
HBM sweep: a sequential-grid Pallas kernel keeps the group table, the
first-encounter bookkeeping, and every per-group accumulator in VMEM/SMEM
scratch, so each input row is read exactly once.

Group identity follows the engine's established double-hash contract
(seg.py group_hash / hash_words): rows match a slot on the 62-bit primary
hash and the slot's independently-salted verify hash is checked in-kernel
— a mismatch raises the overflow flag and the retry driver falls back to
the sort kernel; silently-wrong needs both hashes to collide, the same
~2^-124 class the sort kernel already accepts. Multi-word keys are
pre-reduced by two independent linear folds (see _key_words) so each key
costs ONE word of emulated-64-bit mixing per hash instead of five.
Alternatives measured and rejected: full-word compare in the kernel
(string keys pack to 5 words; hauling 2 lanes per word made it slower
than the XLA dense kernel), and int32 multiply-rotate chains (VPU has no
native 32-bit vector multiply; 4 chains x 11 words benched below the XLA
dense kernel too).

New keys are inserted into the SMEM table by a bounded while-loop in
first-encounter row order — which is also the oracle's output order, so
the epilogue needs no reordering pass. More than `g_cap` distinct keys
raises the overflow flag and the retry driver falls back to the sort
kernel (ref: pkg/executor/aggregate/agg_hash_executor.go grows its tables
dynamically; fixed capacity + retry is the TPU analog).

Layout: every input lane is int32 shaped [N/128, 128] (int64 values ride
as bitcast hi/lo pairs — Mosaic has no 64-bit vectors). Exact integer
sums come from 4x12-bit limb accumulation of the biased value (v + 2^46),
and the XLA epilogue reconstructs the int64 totals as
sum(limb_l << 12l) - nn_count * 2^46. Values at or beyond +/-2^46 raise
the overflow flag. The per-lane-column int32 accumulators bound the ROW
count, not just the values: each of the N/128 rows in a lane column can
add up to 2^12-1 per limb, so the accumulator reaches ~N*2^5 and
silently wraps past int32 around N ~ 2^26 (~67M rows). Eligibility is
therefore gated on N < MAX_ROWS (2^26); larger batches ride the XLA
dense/sort kernels, whose int64 accumulation has no such bound
(ADVICE r5 medium — the old docstring claimed safety for any N < 2^31).

The whole pallas_call is traced under jax.enable_x64(False): this
platform's remote Mosaic compiler rejects 64-bit grid/index arithmetic,
and with x64 enabled globally every Python int in the blocked lowering
becomes an i64 (measured: any gridded kernel fails to compile). The
kernel body is pure int32 either way.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..util.jaxcompat import enable_x64 as _enable_x64
from .keys import sort_key_arrays

LANES = 128
MAX_TR = 256          # sublane rows per grid block (32K data rows)
MAX_COMBOS = 6        # distinct (value, null) argument combos
NH = 4                # independent 32-bit hash chains (128-bit identity)
NL = 4                # 12-bit limbs: covers |v| < 2^46 after biasing
BIAS = 1 << 46        # value bias making every in-range addend non-negative
# int32 limb-accumulator row bound: (N/128 rows per lane column) * (2^12-1
# max limb) must stay below 2^31 -> N < ~2^26.06; gate at 2^26 (module
# docstring "Layout" paragraph; ADVICE r5 medium)
MAX_ROWS = 1 << 26
_ALLOWED = frozenset({"count", "sum", "avg"})


def pallas_mode() -> str | None:
    """'tpu' for the compiled kernel, 'interpret' (tests), or None (off)."""
    env = os.environ.get("TIDB_TPU_PALLAS", "auto")
    if env == "off":
        return None
    if env == "interpret":
        return "interpret"
    if jax.default_backend() == "tpu":
        return "tpu"
    return "tpu" if env == "tpu" else None


def _rotl64(x, r: int):
    return (x << r) | jax.lax.shift_right_logical(x, 64 - r)


def _key_words(group_bys):
    """TWO independent word lists for the match / verify hashes.

    Multi-word keys (strings pack to 5 sort words) are first reduced to one
    word per hash by a cheap linear rotate-xor fold — two different
    rotation schedules, so a fold collision in one hash is independent of
    the other: (hp collides, hv differs) is caught by the kernel's verify
    check -> overflow -> sort kernel; silently wrong needs BOTH 64-bit
    folds+mixes to collide, the same ~2^-124 class the engine's sort
    kernel already accepts. Folding cuts the int64 mixing (emulated 64-bit
    multiplies on TPU) from 5 words to 1 per key — measured as the
    difference between this path beating and trailing the XLA dense
    kernel. None = ineligible keys."""
    wa, wb = [], []
    nf = None
    for k, g in enumerate(group_bys):
        if g.value.ndim == 2:
            # multi-word string keys: fold the [N, W] word matrix with
            # per-column rotations broadcast over axis 1, then XOR-reduce —
            # column-slicing it (sort_key_arrays' layout) costs a strided
            # copy per word on this backend
            words = g.value
            if g.ft.is_ci():
                from ..expr.compile import fold_words_ci

                words = fold_words_ci(words)
            words = jnp.where(g.null[:, None], jnp.int64(0), words)
            W = words.shape[1]

            def fold(step: int):
                sh = jnp.asarray(
                    [(step * j) % 63 + (1 if j else 0) for j in range(W)],
                    jnp.int64,
                )[None, :]
                rot = (words << sh) | jax.lax.shift_right_logical(
                    words, (64 - sh) % 64
                )
                return jnp.bitwise_xor.reduce(rot, axis=1)

            fa, fb = fold(7), fold(13)
        else:
            ws = sort_key_arrays(g)
            for w in ws[1:]:
                if jnp.issubdtype(w.dtype, jnp.floating):
                    return None  # NaN: bit-equality != SQL equality
            vals = ws[1:]
            fa, fb = vals[0], vals[0]
            for j, w in enumerate(vals[1:], start=1):
                fa = fa ^ _rotl64(w, (7 * j) % 63 + 1)
                fb = fb ^ _rotl64(w, (13 * j) % 63 + 1)
        wa.append(fa)
        wb.append(fb)
        b = g.null.astype(jnp.int64) << k
        nf = b if nf is None else nf | b
    if not wa or len(group_bys) > 32:
        return None
    return wa + [nf], wb + [nf]


def dense_pallas_eligible(group_bys, aggs, merge: bool) -> bool:
    """Strict subset the one-pass kernel handles; everything else falls to
    the XLA dense/sort kernels. The gate is a performance router, never a
    semantics change."""
    if merge or not group_bys:
        return False
    # row-count bound BEFORE any value work: the 12-bit limb accumulators
    # silently wrap past int32 at ~2^26 rows (see MAX_ROWS) — shape-only
    # check, so ineligible giants never materialize key folds
    n = group_bys[0].null.shape[0]
    if n >= MAX_ROWS:
        return False
    if _key_words(group_bys) is None:
        return False
    combos = set()
    for desc, avs in aggs:
        if desc.name not in _ALLOWED or desc.distinct:
            return False
        if desc.name == "count":
            if len(avs) > 1:
                return False
            if avs:
                # same lane checks as sum/avg: a float or wide-int COUNT
                # argument would ship a value lane that trips the in-kernel
                # range gate even though COUNT never reads the value
                a = avs[0]
                if a.eval_type not in ("int", "decimal") or a.value.ndim != 1:
                    return False
                if a.value.dtype != jnp.int64:
                    return False
                combos.add((id(a.value), id(a.null)))
            continue
        if len(avs) != 1:
            return False
        a = avs[0]
        if a.eval_type not in ("int", "decimal") or a.value.ndim != 1:
            return False
        if a.value.dtype != jnp.int64:
            return False
        combos.add((id(a.value), id(a.null)))
    return len(combos) <= MAX_COMBOS


def _lsr(x, k: int):
    return jax.lax.shift_right_logical(x, jnp.int32(k))


def _split32(v64: jax.Array):
    """int64 [N] -> (hi, lo) int32 [N].

    Arithmetic on the emulated-s64 pair, NOT a bitcast to [N, 2] + column
    slices: a stride-2 slice materializes as a sublane-strided copy on this
    backend and measured ~7ms across the q1 lanes; the shift/mask forms
    fuse into the surrounding elementwise program."""
    lo = (v64 & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32).astype(jnp.int32)
    hi = (v64 >> 32).astype(jnp.int32)
    return hi, lo


def _rotl(x, r: int):
    return (x << r) | _lsr(x, 32 - r)


def _shape_lane(a: jax.Array, np_: int):
    n = a.shape[0]
    if np_ != n:
        a = jnp.concatenate([a, jnp.zeros(np_ - n, a.dtype)])
    return a.reshape(np_ // LANES, LANES)


def group_aggregate_dense_pallas(group_bys, aggs, row_valid, g_cap: int, mode: str):
    """One-pass small-G aggregation; returns aggregate.GroupAggResult.

    aggs: [(AggDesc, [CompVal])] pre-checked by dense_pallas_eligible.
    g_cap: static slot count (the planner's NDV hint, capped by caller).
    """
    from .aggregate import GroupAggResult
    from .seg import group_hash, hash_words

    n = row_valid.shape[0]
    G = int(g_cap)

    # ---- lane construction (x64 world, fuses into the surrounding program)
    wa, wb = _key_words(group_bys)
    hp = group_hash(wa, row_valid, salt=G)        # match identity
    hv = hash_words(wb, G + 0x9E3779B9)           # verify identity
    hashes = list(_split32(hp)) + list(_split32(hv))

    combo_ix: dict = {}
    combo_vals: list = []
    for desc, avs in aggs:
        if desc.name == "count" and not avs:
            continue
        a = avs[0]
        k = (id(a.value), id(a.null))
        if k not in combo_ix:
            combo_ix[k] = len(combo_vals)
            combo_vals.append(a)
    NC = len(combo_vals)

    # nullword bits: 0 = row_valid, 1..NC = combo null
    nword = row_valid.astype(jnp.int32)
    for c, a in enumerate(combo_vals):
        nword = nword | (a.null.astype(jnp.int32) << (1 + c))

    np_ = -(-n // 1024) * 1024  # pad to whole (8,128) tiles
    tr = min(MAX_TR, np_ // LANES)
    while (np_ // LANES) % tr:
        tr //= 2
    nb = (np_ // LANES) // tr

    lanes = [_shape_lane(nword, np_)]
    for h in hashes:
        lanes.append(_shape_lane(h, np_))
    for a in combo_vals:
        hi, lo = _split32(a.value.astype(jnp.int64))
        lanes.append(_shape_lane(hi, np_))
        lanes.append(_shape_lane(lo, np_))

    # ---- accumulator row layout: per-group states, then one flag row
    # (overflow conditions accumulate as a VECTOR row — a scalar
    # jnp.max-to-SMEM per group per block lowers to a serial reduce and
    # measurably drags the whole kernel)
    per_g = 1 + NC * (NL + 1)         # count(*) + per-combo limbs + nn count
    flag_row = G * per_g
    acc_rows = -(-(flag_row + 1) // 8) * 8       # pad to whole sublane tiles
    out_rows = -(-(acc_rows + 2 + G) // 8) * 8   # + nused, flag, rep[g]
    tw = NH + 1                        # table: hash lanes + used marker

    def kern(*refs):
        nw_ref = refs[0]
        h_refs = refs[1 : 1 + NH]
        val_refs = refs[1 + NH : 1 + NH + 2 * NC]
        o_ref = refs[1 + NH + 2 * NC]
        acc, tbl, nused, flg, repm = refs[1 + NH + 2 * NC + 1 :]
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            acc[:] = jnp.zeros_like(acc)
            nused[0] = jnp.int32(0)
            flg[0] = jnp.int32(0)
            for g in range(G):
                repm[g] = jnp.int32(0)
                for w in range(NH):
                    tbl[g * tw + w] = jnp.int32(0)
                # no real row can match an unused slot
                tbl[g * tw + NH] = jnp.int32(0)

        nword_b = nw_ref[:]
        hw = [h_refs[w][:] for w in range(NH)]
        valid = (nword_b & 1) == 1
        BIG = jnp.int32(2**31 - 1)
        lin = (
            jax.lax.broadcasted_iota(jnp.int32, (tr, LANES), 0) * LANES
            + jax.lax.broadcasted_iota(jnp.int32, (tr, LANES), 1)
        )

        def match(g):
            # primary (hp) pair only; the hv pair is verified per slot below
            return (
                (tbl[g * tw + NH] == jnp.int32(1))
                & (hw[0] == tbl[g * tw])
                & (hw[1] == tbl[g * tw + 1])
            )

        def cond(c):
            return c[0]

        def body(c):
            _, it = c
            found = ~valid
            for g in range(G):
                found = found | match(g)
            miss = ~found
            minidx = jnp.min(jnp.where(miss, lin, BIG))
            has_miss = minidx < BIG
            fm = lin == minidx
            # read BEFORE the insert: reading after would flag the legal
            # G-th insert as overflow (capacity off-by-one)
            was_full = nused[0] >= G

            @pl.when(has_miss & ~was_full)
            def _():
                for w in range(NH):
                    tbl[nused[0] * tw + w] = jnp.min(jnp.where(fm, hw[w], BIG))
                tbl[nused[0] * tw + NH] = jnp.int32(1)
                repm[nused[0]] = i * (tr * LANES) + minidx
                nused[0] = nused[0] + 1

            @pl.when(has_miss & was_full)
            def _():
                flg[0] = jnp.int32(1)

            return (has_miss & ~was_full & (it < G), it + 1)

        jax.lax.while_loop(cond, body, (jnp.bool_(True), jnp.int32(0)))

        # value-range gate, combo-wise, group-independent: biased hi word
        # must fit 15 bits for the 4x12-bit limb split to be lossless
        bad = jnp.zeros((tr, LANES), bool)
        limbs_c = []
        for c in range(NC):
            nn_c = valid & (((nword_b >> (1 + c)) & 1) == 0)
            hb = val_refs[2 * c][:] + (1 << 14)
            lo = val_refs[2 * c + 1][:]
            bad = bad | (nn_c & ((hb < 0) | (_lsr(hb, 15) != 0)))
            # group-independent limb extraction, masked per group below
            limbs_c.append((
                lo & 0xFFF,
                _lsr(lo, 12) & 0xFFF,
                (_lsr(lo, 24) | ((hb & 0xF) << 8)) & 0xFFF,
                _lsr(hb, 4) & 0xFFF,
            ))

        for g in range(G):

            @pl.when(g < nused[0])
            def _(g=g):
                m = match(g) & valid
                # exactness: all hp-matches must share the slot's verify
                # hash (true collisions -> overflow -> sort kernel);
                # vector-accumulated into the flag row, never a scalar
                bad_g = m & (
                    (hw[2] != tbl[g * tw + 2]) | (hw[3] != tbl[g * tw + 3])
                )
                acc[flag_row, :] = acc[flag_row, :] + jnp.sum(
                    bad_g.astype(jnp.int32), axis=0, dtype=jnp.int32
                )

                base = g * per_g
                acc[base, :] = acc[base, :] + jnp.sum(
                    m.astype(jnp.int32), axis=0, dtype=jnp.int32
                )
                for c in range(NC):
                    nn = m & (((nword_b >> (1 + c)) & 1) == 0)
                    row = base + 1 + c * (NL + 1)
                    for l in range(NL):
                        acc[row + l, :] = acc[row + l, :] + jnp.sum(
                            jnp.where(nn, limbs_c[c][l], 0), axis=0, dtype=jnp.int32
                        )
                    acc[row + NL, :] = acc[row + NL, :] + jnp.sum(
                        nn.astype(jnp.int32), axis=0, dtype=jnp.int32
                    )

        acc[flag_row, :] = acc[flag_row, :] + jnp.sum(
            bad.astype(jnp.int32), axis=0, dtype=jnp.int32
        )

        @pl.when(i == nb - 1)
        def _():
            o_ref[:acc_rows, :] = acc[:, :]
            o_ref[acc_rows, :] = jnp.full((LANES,), nused[0], jnp.int32)
            o_ref[acc_rows + 1, :] = jnp.full((LANES,), flg[0], jnp.int32)
            for g in range(G):
                o_ref[acc_rows + 2 + g, :] = jnp.full((LANES,), repm[g], jnp.int32)

    with _enable_x64(False):
        in_specs = [
            pl.BlockSpec((tr, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
            for _ in lanes
        ]
        out = pl.pallas_call(
            kern,
            grid=(nb,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (out_rows, LANES), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
            out_shape=jax.ShapeDtypeStruct((out_rows, LANES), jnp.int32),
            scratch_shapes=[
                pltpu.VMEM((acc_rows, LANES), jnp.int32),
                pltpu.SMEM((G * tw,), jnp.int32),
                pltpu.SMEM((1,), jnp.int32),
                pltpu.SMEM((1,), jnp.int32),
                pltpu.SMEM((G,), jnp.int32),
            ],
            interpret=(mode == "interpret"),
        )(*lanes)

    # ---- epilogue (x64 world): reconstruct int64 states per group
    o = out.astype(jnp.int64)
    n_groups = out[acc_rows, 0].astype(jnp.int32)
    overflow = (out[acc_rows + 1, 0] != 0) | (jnp.sum(o[flag_row]) != 0)
    group_rep = out[acc_rows + 2 : acc_rows + 2 + G, 0].astype(jnp.int32)
    gidx = jnp.arange(G)
    group_valid = gidx < n_groups

    counts_star = jnp.sum(o[jnp.arange(G) * per_g], axis=1)
    combo_sums, combo_nn = [], []
    for c in range(NC):
        rows = jnp.arange(G) * per_g + 1 + c * (NL + 1)
        s = jnp.zeros(G, jnp.int64)
        for l in range(NL):
            s = s + (jnp.sum(o[rows + l], axis=1) << (12 * l))
        nn = jnp.sum(o[rows + NL], axis=1)
        combo_sums.append(s - nn * jnp.int64(BIAS))
        combo_nn.append(nn)

    zeros = jnp.zeros(G, bool)
    states = []
    for desc, avs in aggs:
        if desc.name == "count":
            if not avs:
                states.append([(counts_star, zeros)])
            else:
                c = combo_ix[(id(avs[0].value), id(avs[0].null))]
                states.append([(combo_nn[c], zeros)])
            continue
        c = combo_ix[(id(avs[0].value), id(avs[0].null))]
        empty = combo_nn[c] == 0
        if desc.name == "sum":
            states.append([(combo_sums[c], empty)])
        else:  # avg: [count, sum]
            states.append([(combo_nn[c], zeros), (combo_sums[c], empty)])

    return GroupAggResult(group_rep, group_valid, n_groups, overflow, states)
