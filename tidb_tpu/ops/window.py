"""Window function kernel (ref: pkg/executor/window.go + pipelined_window.go;
tipb.Window executor; per-function semantics pkg/executor/aggfuncs/
func_{rank,row_number,lead_lag,first_value,...}.go).

The reference slides a frame over partition-sorted rows with per-function
PartialResult updates. On TPU the whole batch is resident, so one stable
lexsort by (partition keys, order keys) turns every supported window into a
segmented scan / gather in sorted space, scattered back to input order:

  row_number / rank / dense_rank    index arithmetic on segment starts
  percent_rank / cume_dist / ntile  + partition sizes (gathered ends)
  sum / count / avg                 segmented inclusive cumsum, read at the
                                    current row's PEER-GROUP END — exactly
                                    MySQL's default frame (RANGE UNBOUNDED
                                    PRECEDING..CURRENT ROW includes peers);
                                    without ORDER BY the frame is the whole
                                    partition (read at partition end)
  min / max                         segmented scan (associative_scan with a
                                    segment-reset combiner)
  first_value / last_value /        gathers at partition start / peer end /
  nth_value / lead / lag            fixed offsets with partition bounds

Explicit ROWS/RANGE frames are not supported here (the planner routes those
to the row-at-a-time oracle). String-valued MIN/MAX likewise fall back.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..expr.compile import CompVal, I64_MIN
from .aggregate import _round_div
from .keys import lexsort, sort_key_arrays

RANK_FUNCS = frozenset({"row_number", "rank", "dense_rank", "percent_rank", "cume_dist", "ntile"})
GATHER_FUNCS = frozenset({"first_value", "last_value", "nth_value", "lead", "lag"})
AGG_FUNCS = frozenset({"sum", "avg", "count", "min", "max"})
WINDOW_FUNCS = RANK_FUNCS | GATHER_FUNCS | AGG_FUNCS


def _seg_running_sum(x, start, arange):
    """Inclusive running sum within segments; `start` = per-row segment
    start index (monotone)."""
    c = jnp.cumsum(x, axis=0)
    excl = c - x  # exclusive prefix
    return c - jnp.take(excl, start)


def _seg_scan_extreme(x, new_part, is_max: bool):
    """Segmented inclusive cummax/cummin via associative_scan with a
    reset-at-boundary combiner (standard segmented-scan construction)."""

    def comb(a, b):
        av, af = a
        bv, bf = b
        m = jnp.maximum(av, bv) if is_max else jnp.minimum(av, bv)
        return jnp.where(bf, bv, m), af | bf

    v, _ = jax.lax.associative_scan(comb, (x, new_part))
    return v


def _gather_cv(cv: CompVal, idx, extra_null) -> CompVal:
    raw = None
    if cv.raw is not None:
        raw = (cv.raw[0][idx], cv.raw[1][idx])
    return CompVal(cv.value[idx], cv.null[idx] | extra_null, cv.ft, raw=raw)


def window_cols(part_vals: list, order_pairs: list, funcs: list, valid) -> list[CompVal]:
    """Compute window columns in original row order.

    part_vals: [CompVal] partition keys; order_pairs: [(CompVal, desc)];
    funcs: [(WinDesc, [CompVal arg columns])]; valid: row mask.
    Returns one CompVal per WinDesc.
    """
    n = valid.shape[0]
    arange = jnp.arange(n)
    keys = [jnp.where(valid, jnp.int64(0), jnp.int64(1))]
    n_pkey_arrays = 1  # the validity key counts as a partition key: padding
    # rows (sorted last) must never merge into the final valid partition
    # even when their zeroed key lanes equal its keys
    for v in part_vals:
        keys.extend(sort_key_arrays(v))
    n_pkey_arrays = len(keys)
    for v, desc in order_pairs:
        keys.extend(sort_key_arrays(v, desc=desc))
    perm = lexsort(keys, extra_key=arange)

    def diff_of(vals_keys):
        d = jnp.zeros(n, bool).at[0].set(True)
        for k in vals_keys:
            ks = k[perm]
            d = d | jnp.concatenate([jnp.ones(1, bool), ks[1:] != ks[:-1]])
        return d

    pkeys = keys[:n_pkey_arrays]
    okeys = keys[n_pkey_arrays:]
    new_part = diff_of(pkeys)
    new_peer = new_part | (diff_of(okeys) if okeys else jnp.zeros(n, bool))
    has_order = bool(order_pairs)

    part_id = jnp.cumsum(new_part.astype(jnp.int32))
    start = jax.lax.cummax(jnp.where(new_part, arange, 0))
    is_last_part = jnp.concatenate([new_part[1:], jnp.ones(1, bool)])
    part_end = jnp.flip(jax.lax.cummin(jnp.flip(jnp.where(is_last_part, arange, n))))
    is_last_peer = jnp.concatenate([new_peer[1:], jnp.ones(1, bool)])
    peer_end = jnp.flip(jax.lax.cummin(jnp.flip(jnp.where(is_last_peer, arange, n))))
    # the read point of the default frame: last peer with ORDER BY, else
    # the whole partition
    frame_end = peer_end if has_order else part_end
    cnt = (part_end - start + 1).astype(jnp.int64)
    pos0 = (arange - start).astype(jnp.int64)  # 0-based row index in partition

    sv = valid[perm]

    def scatter(v_sorted, null_sorted, ft) -> CompVal:
        value = jnp.zeros(n, v_sorted.dtype).at[perm].set(v_sorted)
        null = jnp.ones(n, bool).at[perm].set(null_sorted)
        return CompVal(value, null, ft)

    def gather_result(cv: CompVal, j_sorted, src_null_sorted) -> CompVal:
        """Sorted-space source index -> original-order gathered CompVal."""
        src_orig = jnp.zeros(n, jnp.int32).at[perm].set(perm[jnp.clip(j_sorted, 0, n - 1)].astype(jnp.int32))
        xnull = jnp.ones(n, bool).at[perm].set(src_null_sorted)
        return _gather_cv(cv, src_orig, xnull)

    out: list[CompVal] = []
    for desc, argvals in funcs:
        name = desc.name
        if name == "row_number":
            out.append(scatter(pos0 + 1, ~sv, desc.ft))
        elif name == "rank":
            peer_start = jax.lax.cummax(jnp.where(new_peer, arange, 0))
            out.append(scatter((peer_start - start + 1).astype(jnp.int64), ~sv, desc.ft))
        elif name == "dense_rank":
            d = jnp.cumsum(new_peer.astype(jnp.int64))
            out.append(scatter(d - jnp.take(d, start) + 1, ~sv, desc.ft))
        elif name == "percent_rank":
            peer_start = jax.lax.cummax(jnp.where(new_peer, arange, 0))
            rank = (peer_start - start).astype(jnp.float64)
            denom = jnp.maximum(cnt - 1, 1).astype(jnp.float64)
            out.append(scatter(jnp.where(cnt <= 1, 0.0, rank / denom), ~sv, desc.ft))
        elif name == "cume_dist":
            covered = (peer_end - start + 1).astype(jnp.float64)
            out.append(scatter(covered / cnt.astype(jnp.float64), ~sv, desc.ft))
        elif name == "ntile":
            k = jnp.int64(desc.offset)
            base, rem = cnt // k, cnt % k
            cut = rem * (base + 1)
            bucket = jnp.where(
                pos0 < cut,
                pos0 // jnp.maximum(base + 1, 1),
                rem + (pos0 - cut) // jnp.maximum(base, 1),
            )
            out.append(scatter(bucket + 1, ~sv, desc.ft))
        elif name == "count":
            if argvals:
                ones = jnp.where(sv & ~argvals[0].null[perm], jnp.int64(1), jnp.int64(0))
            else:
                ones = jnp.where(sv, jnp.int64(1), jnp.int64(0))
            run = _seg_running_sum(ones, start, arange)
            out.append(scatter(jnp.take(run, frame_end), ~sv, desc.ft))
        elif name in ("sum", "avg"):
            a = argvals[0]
            if a.value.ndim == 2:
                raise NotImplementedError("string SUM/AVG windows run on the oracle")
            av, anull = a.value[perm], a.null[perm]
            live = sv & ~anull
            if a.eval_type == "real":
                x = jnp.where(live, av.astype(jnp.float64), 0.0)
            else:
                x = jnp.where(live, av.astype(jnp.int64), jnp.int64(0))
            rsum = jnp.take(_seg_running_sum(x, start, arange), frame_end)
            rcnt = jnp.take(
                _seg_running_sum(live.astype(jnp.int64), start, arange), frame_end
            )
            null = ~sv | (rcnt == 0)
            if name == "sum":
                out.append(scatter(rsum, null, desc.ft))
            elif a.eval_type == "real":
                out.append(scatter(rsum / jnp.maximum(rcnt, 1).astype(jnp.float64), null, desc.ft))
            else:
                # decimal avg: scale(out) = scale(arg) + 4 (div frac incr),
                # round half away from zero — mirrors finalize_agg
                src_scale = max(a.ft.decimal, 0) if a.eval_type == "decimal" else 0
                tgt = max(desc.ft.decimal, 0)
                num = rsum * jnp.int64(10 ** (tgt - src_scale))
                out.append(scatter(_round_div(num, jnp.maximum(rcnt, 1)), null, desc.ft))
        elif name in ("min", "max"):
            a = argvals[0]
            if a.value.ndim == 2:
                raise NotImplementedError("string MIN/MAX windows run on the oracle")
            av, anull = a.value[perm], a.null[perm]
            live = sv & ~anull
            unsigned = a.eval_type == "int" and a.ft.is_unsigned()
            if a.eval_type == "real":
                ident = jnp.float64(-jnp.inf if name == "max" else jnp.inf)
                x = jnp.where(live, av.astype(jnp.float64), ident)
            else:
                # full-range identities: extremes the scan cannot beat, and a
                # value EQUAL to the identity is itself the correct answer.
                # Unsigned values flip the sign bit (order-preserving u64 ->
                # s64 bijection), flipped back after the scan.
                xi = av.astype(jnp.int64)
                if unsigned:
                    xi = xi ^ I64_MIN
                ii = jnp.iinfo(jnp.int64)
                ident = jnp.int64(ii.min if name == "max" else ii.max)
                x = jnp.where(live, xi, ident)
            run = _seg_scan_extreme(x, new_part, name == "max")
            rcnt = jnp.take(_seg_running_sum(live.astype(jnp.int64), start, arange), frame_end)
            v = jnp.take(run, frame_end)
            if unsigned:
                v = v ^ I64_MIN
            out.append(scatter(v, ~sv | (rcnt == 0), desc.ft))
        elif name == "first_value":
            out.append(gather_result(argvals[0], start, ~sv))
        elif name == "last_value":
            out.append(gather_result(argvals[0], frame_end, ~sv))
        elif name == "nth_value":
            j = start + jnp.int64(desc.offset) - 1
            miss = ~sv | (j > frame_end)
            out.append(gather_result(argvals[0], j, miss))
        elif name in ("lead", "lag"):
            off = desc.offset if name == "lead" else -desc.offset
            j = arange + off
            inb = (j >= 0) & (j < n)
            jc = jnp.clip(j, 0, n - 1)
            same = inb & (jnp.take(part_id, jc) == part_id) & sv & jnp.take(sv, jc)
            res = gather_result(argvals[0], jc, ~same)
            if len(argvals) > 1:
                d = argvals[1]
                dnull = jnp.ones(n, bool).at[perm].set(~same)
                value = jnp.where(dnull, d.value, res.value) if res.raw is None else res.value
                if res.raw is None:
                    out.append(CompVal(value, jnp.where(dnull, d.null, res.null), desc.ft))
                else:
                    # string default: keep gather result, patch nulls where
                    # the default applies (defaults are Consts; raw ride-along)
                    raise NotImplementedError("string LEAD/LAG defaults run on the oracle")
            else:
                out.append(res)
        else:
            raise NotImplementedError(f"window function {name!r}")
    return out
