"""Pallas post-sort pass for the packed join+group kernel (TPC-H Q3).

After packed_join_groupsum's ONE int32 sort, the XLA path pays ~10ms of
scan floors at 4.65M rows on the tunneled v5e (an int64 cumsum + int64
reverse cummin per agg combo, an int32 reverse cummin for run extents,
plus a batched overflow reduce — each op carries a 2-4ms dispatch floor).
This kernel replaces ALL of it with one sequential-grid sweep over the
sorted arrays: a flagged Hillis-Steele segmented scan (lane phase by
pltpu.roll along lanes, sublane phase by roll + last-lane broadcast,
block carries in SMEM) computes per-run contributing counts, the matched
flag, and exact sums as three 12/12/8-bit limb lanes of the bias-flipped
value (sv ^ 0x80000000 — every addend non-negative, so in-block partial
sums stay under 2^27 in int32; block-boundary carries re-normalize into
canonical limbs so only the top limb grows, bounded by the run-length cap
below).

Emission shift: element e with a key boundary emits the run that ENDED at
e-1 (sum/count/matched from the rolled inclusive scan, key from the
rolled spk). Downstream consumers only see (group_valid, states, key_out,
extent_cnt) as mutually-aligned [n] lanes, so boundary positions are as
good as first-probe-row positions — and a forward-only formulation needs
no reverse scans at all. The array is padded with probe pins so the last
real run always has a boundary element after it.

Overflow -> the join-overflow retry (general kernel), one flag: duplicate
usable hay keys (unique-build contract), any pre-sort bad lane bit (key
or value outside int32 — the unsorted lane rides as a THIRD input so its
any() costs no standalone XLA reduce), or a single run exceeding 2^23
contributing rows (the limb-carry bound; a group that large implies a
skew the general kernel handles anyway).

Traced under jax.enable_x64(False) like every Pallas kernel here (the
remote Mosaic compiler rejects 64-bit grid arithmetic).
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..util.jaxcompat import enable_x64 as _enable_x64


def _x64_ctx(interpret: bool):
    """x64(False) for the Mosaic (real-TPU) lowering only. In interpret
    mode the kernel is staged into the OUTER x64-on trace but lowered
    later with x64 back on; tracing it under x64(False) desyncs literal
    avals from their lowered constants ('func.call' operand i32/i64
    mismatch). The kernels are explicitly i32-typed, so the flag only
    matters to Mosaic's 64-bit-rewrite pass."""
    return contextlib.nullcontext() if interpret else _enable_x64(False)

LANES = 128
TR = 256
T = TR * LANES
_PIN = (1 << 31) - 4          # joinagg._PIN_HAY as a plain int
_RUN_CAP = 1 << 23            # max contributing rows per run (limb bound)


def _lsr(x, k: int):
    return jax.lax.shift_right_logical(x, jnp.int32(k))


def _make_kernel(nb: int, nc: int, nn_bits):
    nnb = [b for b in nn_bits if b >= 0]
    has_nw = bool(nnb)
    nscan = 1 + 3 * nc + len(nnb)  # cnt|mb, limbs, nullable nn counts

    def kern(*refs):
        k = 0
        spk_ref = refs[k]; k += 1
        bad_ref = refs[k]; k += 1
        sv_refs = refs[k : k + nc]; k += nc
        nw_ref = None
        if has_nw:
            nw_ref = refs[k]; k += 1
        gv_ref = refs[k]; k += 1
        cnt_ref = refs[k]; k += 1
        key_ref = refs[k]; k += 1
        limb_refs = refs[k : k + 3 * nc]; k += 3 * nc
        nn_refs = refs[k : k + len(nnb)]; k += len(nnb)
        meta_ref = refs[k]; k += 1
        carry, macc = refs[k:]
        # carry: [0]=prev_pk, then one slot per scan lane
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            carry[0] = jnp.int32(-(2**31))  # below every real pk
            for j in range(nscan):
                carry[1 + j] = jnp.int32(0)
            macc[:] = jnp.zeros_like(macc)

        spk = spk_ref[:]
        lid = jax.lax.broadcasted_iota(jnp.int32, (TR, LANES), 1)
        sid = jax.lax.broadcasted_iota(jnp.int32, (TR, LANES), 0)

        def prev_of(x, first_fill):
            lanerolled = pltpu.roll(x, 1, 1)
            subrolled = pltpu.roll(lanerolled, 1, 0)
            p = jnp.where(lid == 0, subrolled, lanerolled)
            return jnp.where((lid == 0) & (sid == 0), first_fill, p)

        prev_pk = prev_of(spk, carry[0])
        is_hay = (spk & 1) == 0
        is_real = spk < _PIN
        prev_is_hay = (prev_pk & 1) == 0
        keydiff = (spk | 1) != (prev_pk | 1)
        contrib = (~is_hay) & is_real
        dup = is_hay & is_real & (spk == prev_pk) & prev_is_hay
        mb = contrib & (~keydiff) & prev_is_hay & (prev_pk == spk - 1)

        # scan lanes: cnt|matched packed, 3 limbs per combo, nn counts
        vals = [contrib.astype(jnp.int32) + (mb.astype(jnp.int32) << 24)]
        for c in range(nc):
            vb = sv_refs[c][:] ^ jnp.int32(-2147483648)
            vals.append(jnp.where(contrib, vb & 0xFFF, 0))
            vals.append(jnp.where(contrib, _lsr(vb, 12) & 0xFFF, 0))
            vals.append(jnp.where(contrib, _lsr(vb, 24) & 0xFF, 0))
        for b in nnb:
            nn = contrib & (((nw_ref[:] >> b) & 1) == 0)
            vals.append(nn.astype(jnp.int32))

        fs = keydiff.astype(jnp.int32)
        vs = list(vals)
        for d in (1, 2, 4, 8, 16, 32, 64):
            ok = lid >= d
            rf = pltpu.roll(fs, d, 1)
            rvs = [pltpu.roll(v, d, 1) for v in vs]
            keep = (fs == 0) & ok
            vs = [jnp.where(keep, v + rv, v) for v, rv in zip(vs, rvs)]
            fs = jnp.where(ok, fs | rf, fs)
        for d in (1, 2, 4, 8, 16, 32, 64, 128):
            ok = sid >= d
            rf = pltpu.roll(fs, d, 0)
            rvs = [pltpu.roll(v, d, 0) for v in vs]
            rl = [jnp.broadcast_to(rv[:, LANES - 1 : LANES], (TR, LANES)) for rv in rvs]
            rfl = jnp.broadcast_to(rf[:, LANES - 1 : LANES], (TR, LANES))
            keep = (fs == 0) & ok
            vs = [jnp.where(keep, v + rv, v) for v, rv in zip(vs, rl)]
            fs = jnp.where(ok, fs | rfl, fs)

        nof = fs == 0  # no boundary in [block_start..e]: add the carry-in
        cin = [carry[1 + j] for j in range(nscan)]
        vs = [jnp.where(nof, v + c, v) for v, c in zip(vs, cin)]

        # emit the run ended at e-1
        pvs = [prev_of(v, c) for v, c in zip(vs, cin)]
        pc = pvs[0] & 0xFFFFFF
        pm = _lsr(pvs[0], 24)
        emit = keydiff & (pc > 0) & (pm > 0)
        gv_ref[:] = emit.astype(jnp.int32)
        cnt_ref[:] = jnp.where(emit, pc, 0)
        key_ref[:] = jnp.where(emit, prev_pk, 0)
        for j in range(3 * nc):
            limb_refs[j][:] = jnp.where(emit, pvs[1 + j], 0)
        for j in range(len(nnb)):
            nn_refs[j][:] = jnp.where(emit, pvs[1 + 3 * nc + j], 0)

        # carries for the open run, limb-normalized so only the top limb
        # grows across blocks (bounded by the run cap)
        carry[0] = spk[TR - 1, LANES - 1]
        cl = vs[0][TR - 1, LANES - 1]
        carry[1] = cl
        runcap = (cl & 0xFFFFFF) >= (_RUN_CAP - T)
        for c in range(nc):
            l0 = vs[1 + 3 * c][TR - 1, LANES - 1]
            l1 = vs[2 + 3 * c][TR - 1, LANES - 1] + _lsr(l0, 12)
            carry[2 + 3 * c] = l0 & 0xFFF
            carry[3 + 3 * c] = l1 & 0xFFF
            carry[4 + 3 * c] = vs[3 + 3 * c][TR - 1, LANES - 1] + _lsr(l1, 12)
        for j in range(len(nnb)):
            carry[2 + 3 * nc + j] = vs[1 + 3 * nc + j][TR - 1, LANES - 1]

        macc[0, :] = macc[0, :] | jnp.max(dup.astype(jnp.int32), axis=0)
        macc[1, :] = macc[1, :] + jnp.sum(contrib.astype(jnp.int32), axis=0, dtype=jnp.int32)
        macc[2, :] = macc[2, :] | jnp.max(bad_ref[:], axis=0)
        # run cap: open-run carry or an emitted count crossing the bound
        # (vector OR — Mosaic has no scalar VMEM stores). int32 literals:
        # int-only where() branches default to int64 when tracing with x64
        # on (the interpret path)
        one, zero = jnp.int32(1), jnp.int32(0)
        macc[0, :] = macc[0, :] | jnp.where(runcap, one, zero) | jnp.max(
            jnp.where(emit & (pc >= _RUN_CAP - T), one, zero), axis=0
        )

        @pl.when(i == nb - 1)
        def _():
            meta_ref[:, :] = macc[:, :]

    return kern


def postsort_segscan(spk, lanes_s, bad_lane, nw_s=None, nn_bits=(),
                     interpret: bool = False):
    """spk int32 [n] (sorted packed keys), lanes_s: list of int32 [n]
    (sorted agg lanes), bad_lane bool [n] (UNSORTED pre-sort overflow
    bits), nw_s uint8 [n] sorted null-bit word with nn_bits[c] the bit of
    combo c (-1 = NOT NULL). Returns (group_valid, cnt int64, key_i32,
    [sum int64 per lane], [nn int64 per lane], overflow, join_rows) — all
    [n]-aligned at run-boundary positions."""
    n = spk.shape[0]
    nc = len(lanes_s)
    nnb = [b for b in nn_bits if b >= 0]
    np2 = -(-(n + 1) // T) * T
    pad = np2 - n

    def shape(a, fill):
        if pad:
            a = jnp.concatenate([a, jnp.full(pad, fill, a.dtype)])
        return a.reshape(np2 // LANES, LANES)

    spk2 = shape(spk, jnp.int32(_PIN + 1))  # probe-pin pad: emits last run
    bad2 = shape(bad_lane.astype(jnp.int32), 0)
    svs = [shape(v, 0) for v in lanes_s]
    ins = [spk2, bad2] + svs
    if nnb:
        ins.append(shape(nw_s.astype(jnp.int32), 0))
    R = np2 // LANES
    nb = R // TR

    spec = pl.BlockSpec((TR, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
    mspec = pl.BlockSpec((8, LANES), lambda i: (0, 0), memory_space=pltpu.VMEM)
    n_out = 3 + 3 * nc + len(nnb)
    nscan = 1 + 3 * nc + len(nnb)
    with _x64_ctx(interpret):
        outs = pl.pallas_call(
            _make_kernel(nb, nc, list(nn_bits)),
            grid=(nb,),
            in_specs=[spec] * len(ins),
            out_specs=tuple([spec] * n_out + [mspec]),
            out_shape=tuple(
                [jax.ShapeDtypeStruct((R, LANES), jnp.int32)] * n_out
                + [jax.ShapeDtypeStruct((8, LANES), jnp.int32)]
            ),
            scratch_shapes=[
                pltpu.SMEM((1 + nscan,), jnp.int32),
                pltpu.VMEM((8, LANES), jnp.int32),
            ],
            interpret=interpret,
        )(*ins)

    # Emission happens at e for the run that ended at e-1; shifting every
    # output lane back by one places each emission on its run's LAST
    # element — always inside [0, n), including the FINAL run whose
    # boundary fires on the first pad element (flat index n; a plain [:n]
    # slice dropped the max-key group whenever no pin rows existed).
    def unshape(a):
        return a.reshape(np2)[1 : n + 1]

    gv = unshape(outs[0]) != 0
    cnt = unshape(outs[1]).astype(jnp.int64)
    key = unshape(outs[2])
    meta = outs[3 + 3 * nc + len(nnb)].astype(jnp.int64)
    sums = []
    for c in range(nc):
        l0 = unshape(outs[3 + 3 * c]).astype(jnp.int64)
        l1 = unshape(outs[4 + 3 * c]).astype(jnp.int64)
        l2 = unshape(outs[5 + 3 * c]).astype(jnp.int64)
        biased = l0 + (l1 << 12) + (l2 << 24)
        sums.append(biased - (cnt << 31))
    nns = []
    j = 0
    for b in nn_bits:
        if b < 0:
            nns.append(cnt)
        else:
            nns.append(unshape(outs[3 + 3 * nc + j]).astype(jnp.int64))
            j += 1
    overflow = (jnp.sum(meta[0]) + jnp.sum(meta[2])) != 0
    join_rows = jnp.sum(meta[1])
    return gv, cnt, key, sums, nns, overflow, join_rows


def _make_member_kernel(nb: int):
    def kern(spk_ref, bad_ref, ok_ref, meta_ref, carry, macc):
        # carry: [0]=prev_pk [1]=open-run head flag
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            carry[0] = jnp.int32(-(2**31))  # below every real pk
            carry[1] = jnp.int32(0)
            macc[:] = jnp.zeros_like(macc)

        spk = spk_ref[:]
        lid = jax.lax.broadcasted_iota(jnp.int32, (TR, LANES), 1)
        sid = jax.lax.broadcasted_iota(jnp.int32, (TR, LANES), 0)

        def prev_of(x, first_fill):
            lanerolled = pltpu.roll(x, 1, 1)
            subrolled = pltpu.roll(lanerolled, 1, 0)
            p = jnp.where(lid == 0, subrolled, lanerolled)
            return jnp.where((lid == 0) & (sid == 0), first_fill, p)

        prev_pk = prev_of(spk, carry[0])
        is_inner = (spk & 1) == 0
        is_real = spk < _PIN
        prev_is_inner = (prev_pk & 1) == 0
        keydiff = (spk | 1) != (prev_pk | 1)
        dup = is_inner & is_real & (spk == prev_pk) & prev_is_inner
        # run head is a usable inner row: inner rows sort first in a run
        head = (is_inner & is_real & keydiff).astype(jnp.int32)

        fs = keydiff.astype(jnp.int32)
        v = head
        for d in (1, 2, 4, 8, 16, 32, 64):
            ok = lid >= d
            rf = pltpu.roll(fs, d, 1)
            rv = pltpu.roll(v, d, 1)
            keep = (fs == 0) & ok
            v = jnp.where(keep, v + rv, v)
            fs = jnp.where(ok, fs | rf, fs)
        for d in (1, 2, 4, 8, 16, 32, 64, 128):
            ok = sid >= d
            rf = pltpu.roll(fs, d, 0)
            rv = pltpu.roll(v, d, 0)
            rl = jnp.broadcast_to(rv[:, LANES - 1 : LANES], (TR, LANES))
            rfl = jnp.broadcast_to(rf[:, LANES - 1 : LANES], (TR, LANES))
            keep = (fs == 0) & ok
            v = jnp.where(keep, v + rl, v)
            fs = jnp.where(ok, fs | rfl, fs)
        v = jnp.where(fs == 0, v + carry[1], v)

        ok_out = (~is_inner) & is_real & (v > 0)
        ok_ref[:] = ok_out.astype(jnp.int32)

        carry[0] = spk[TR - 1, LANES - 1]
        carry[1] = v[TR - 1, LANES - 1]
        macc[0, :] = macc[0, :] | jnp.max(dup.astype(jnp.int32), axis=0)
        macc[0, :] = macc[0, :] | jnp.max(bad_ref[:], axis=0)

        @pl.when(i == nb - 1)
        def _():
            meta_ref[:, :] = macc[:, :]

    return kern


def membership_segscan(spk, bad_lane, interpret: bool = False):
    """Post-sort pass for membership_chain: per-element ok_out (outer row
    whose key run starts with a usable inner row) plus the overflow flag
    (duplicate inner keys | any pre-sort bad bit) in one sweep — replaces
    an int32 cummax and a standalone batched any() of the XLA path."""
    n = spk.shape[0]
    np2 = -(-n // T) * T
    pad = np2 - n

    def shape(a, fill):
        if pad:
            a = jnp.concatenate([a, jnp.full(pad, fill, a.dtype)])
        return a.reshape(np2 // LANES, LANES)

    spk2 = shape(spk, jnp.int32(_PIN + 1))
    bad2 = shape(bad_lane.astype(jnp.int32), 0)
    R = np2 // LANES
    nb = R // TR
    spec = pl.BlockSpec((TR, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
    mspec = pl.BlockSpec((8, LANES), lambda i: (0, 0), memory_space=pltpu.VMEM)
    with _x64_ctx(interpret):
        ok2, meta = pl.pallas_call(
            _make_member_kernel(nb),
            grid=(nb,),
            in_specs=[spec, spec],
            out_specs=(spec, mspec),
            out_shape=(
                jax.ShapeDtypeStruct((R, LANES), jnp.int32),
                jax.ShapeDtypeStruct((8, LANES), jnp.int32),
            ),
            scratch_shapes=[
                pltpu.SMEM((2,), jnp.int32),
                pltpu.VMEM((8, LANES), jnp.int32),
            ],
            interpret=interpret,
        )(spk2, bad2)
    ok_out = ok2.reshape(np2)[:n] != 0
    overflow = jnp.sum(meta[0].astype(jnp.int64)) != 0
    return ok_out, overflow
