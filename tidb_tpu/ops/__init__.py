from .keys import sort_key_arrays, lexsort, segments_from_sorted
from .selection import apply_selection
from .aggregate import GatherState, GroupAggResult, group_aggregate, scalar_aggregate
from .topn import topn
from .join import hash_join

__all__ = [
    "sort_key_arrays",
    "lexsort",
    "segments_from_sorted",
    "apply_selection",
    "GatherState",
    "GroupAggResult",
    "group_aggregate",
    "scalar_aggregate",
    "topn",
    "hash_join",
]
