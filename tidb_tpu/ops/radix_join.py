"""Radix-partitioned hash join — the ISSUE 13 tentpole (ref: the
reference's radix-hashjoin design doc, docs/design/2018-09-21-radix-hashjoin.md;
pkg/executor/join/hash_join_v2.go partitioned build).

The monolithic kernel (ops/join.py) pays three full-size multi-operand
sorts per join: the build lexsort, merge_lo_hi's combined 4-operand sort
over nb+np, and the inverse sort back to probe order — at production row
counts the sorts ARE the join, and the single monolithic program is also
the 131s-compile shape the ROADMAP calls out.  This kernel partitions
BOTH sides by radix bits of the salted key hash into P independent
sub-joins, each against a fixed, cache-friendly build table:

  1. partition ids from the key hash's low bits (ops/seg.py hash_words,
     salted by the join-capacity rung so a ladder retry re-shuffles a
     pathological clustering);
  2. placement by ONE cheap 2-operand int32 sort per side (partition id +
     row index) — sorted order is partition-major, so the [P, cap] tables
     are plain clipped-window gathers, no scatter ever touches an
     [N]-sized array;
  3. per-partition probe, strategy-routed at trace time (probe_strategy —
     the backend is in the ProgramCache key via pallas_mode): the Pallas
     probe kernel (ops/join_pallas.py) sweeps each partition's build
     table in VMEM/SMEM when the shape gate passes; the TPU XLA fallback
     is a dense broadcast compare fused into its two reductions
     (first-match slot, match count); CPU-class backends skip the tables
     and binary-search the sorted build side per probe ("search" — the
     ~log(nb) cheap host gathers beat every O(N log N) sort XLA-CPU
     would otherwise pay, and the probe rows never leave original order);
  4. a SKEW ESCAPE HATCH: any partition whose build side outgrows
     part_cap or whose probe side outgrows probe_cap is excluded from the
     tables and its rows are compacted into fixed escape buffers (tiny
     searchsorted over the P+1 partition offsets — no extra sort) that
     the GENERAL sorted-merge kernel (merge_lo_hi) joins at esc_cap size;
     escape overflow raises the join-overflow flag with a NEED hint so
     the retry driver re-dispatches the next precompiled rung.

Only the single-word int-class equi-join shape rides this path
(inner/left_outer/semi/anti), and only when the probe side dominates
(build*8 <= probe capacity — the canonical small-build hash join, TPC-H
Q3's shape); everything else stays on the monolithic kernel.  An
opportunistic fast path, never a semantics change: under the planner's
unique-build hint the contract is runtime-verified per partition (match
fan-out > 1 raises overflow, same as ops/join.py), and NULL keys never
match (pid pins past the last partition).

NON-unique builds (the ISSUE 18 lift, unlocked by the MPP exchange): with
`build_unique=False` the kernel runs the same prefix-sum output expansion
as ops/join.py — match COUNTS per probe row (dense per-partition fan-out
from the broadcast-compare's sum reduction, or searchsorted extents on
CPU-class backends), cumsum to output offsets, and one merge_searchsorted
to assign each static output slot its (probe, nth-match) pair.  The
escape hatch expands too (sorted-merge extents at esc_cap size), and the
out-capacity NEED hint rides the overflow flag so the retry ladder jumps
straight to the clearing rung.  The pallas probe kernel only reduces to
the FIRST matching slot, so non-unique fan-out downgrades pallas to the
dense tables at trace time.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..expr.compile import CompVal
from .join import JoinResult, _key_matrix, merge_lo_hi
from .keys import lexsort
from .seg import I64_MAX, hash_words, merge_searchsorted

# plan knobs (static; every program is keyed by the derived plan via its
# capacities + join-capacity rung, so these never recompile per query)
MAX_PARTS = 1 << 16
PART_CAP_MIN = 128
PROBE_CAP_MIN = 8
ESC_CAP_MIN = 1024
ESC_DIV = 16          # esc_cap = join_capacity // ESC_DIV (rung-scaled)
BUILD_RATIO = 8       # eligible when nb_cap * BUILD_RATIO <= np_cap


def _pow2(n: int) -> int:
    c = 1
    while c < n:
        c *= 2
    return c


def radix_plan(nb_cap: int, np_cap: int, join_capacity: int):
    """(n_parts, part_cap, probe_cap, esc_cap) — all static, derived from
    the batch capacities and the join-capacity RUNG, or None when the
    shape is build-heavy (the monolithic kernel wins there: the dense
    probe's work is probe_rows * part_cap, and a big build side forces
    part_cap past the cache-friendly budget)."""
    if nb_cap * BUILD_RATIO > np_cap:
        return None
    # target ~32 build rows per partition (4x slack under PART_CAP_MIN),
    # bounded so the probe table keeps >= 8 slots per partition
    p_hi = min(MAX_PARTS, max(_pow2(np_cap // PROBE_CAP_MIN + 1) // 2, 2))
    n_parts = min(max(_pow2(max(nb_cap, 1) // 32), 2), p_hi)
    part_cap = max(PART_CAP_MIN, _pow2(-(-4 * nb_cap // n_parts)))
    probe_cap = max(PROBE_CAP_MIN, _pow2(-(-2 * np_cap // n_parts)))
    esc_cap = min(_pow2(max(nb_cap, np_cap)),
                  max(ESC_CAP_MIN, join_capacity // ESC_DIV))
    return n_parts, part_cap, probe_cap, esc_cap


def _partition(pid, n_parts: int, cap: int, n: int):
    """Cluster rows by partition id with one stable 2-operand int32 sort;
    returns (tbl_idx [P, cap] int32 row indices, in_part mask, count [P],
    order_pid, order_idx, start [P]).  Rows with pid == n_parts (unusable)
    sort last and never enter a table."""
    iota = jnp.arange(n, dtype=jnp.int32)
    order_pid, order_idx = jax.lax.sort((pid, iota), num_keys=1)
    start = jnp.searchsorted(
        order_pid, jnp.arange(n_parts + 1, dtype=jnp.int32)
    ).astype(jnp.int32)
    count = start[1:] - start[:-1]
    rows = start[:-1, None] + jnp.arange(cap, dtype=jnp.int32)[None, :]
    in_part = jnp.arange(cap, dtype=jnp.int32)[None, :] < count[:, None]
    tbl_idx = order_idx[jnp.clip(rows, 0, n - 1)]
    return tbl_idx, in_part, count, order_pid, order_idx, start


def _escape_rows(order_idx, start, count, esc_part, n_parts: int, esc_cap: int, n: int):
    """Compact the rows of escaped partitions (contiguous runs in the
    partition-sorted order) into a fixed [esc_cap] buffer: buffer slot k
    maps back through a searchsorted over the P+1 escape offsets — P is
    tiny, so this costs no extra [N] pass.  Returns (buf_idx int32
    original-row indices, slot_ok, n_esc int32)."""
    esc_cnt = jnp.where(esc_part, count, 0).astype(jnp.int32)
    off = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(esc_cnt)])
    n_esc = off[-1]
    k = jnp.arange(esc_cap, dtype=jnp.int32)
    p_of = jnp.clip(
        jnp.searchsorted(off, k, side="right").astype(jnp.int32) - 1,
        0, n_parts - 1,
    )
    pos = start[p_of] + (k - off[p_of])
    slot_ok = k < n_esc
    buf_idx = order_idx[jnp.clip(pos, 0, n - 1)]
    return buf_idx, slot_ok, n_esc


def _probe_tables_xla(b_key_tbl, b_slot_ok, p_key_tbl, p_slot_ok, part_cap: int):
    """Dense per-partition probe: first matching build slot (part_cap =
    none) and the unique-contract fan-out check, as two fused reductions
    over the broadcast compare."""
    eq = (p_key_tbl[:, :, None] == b_key_tbl[:, None, :]) & b_slot_ok[:, None, :]
    slotv = jnp.where(
        eq, jnp.arange(part_cap, dtype=jnp.int32)[None, None, :],
        jnp.int32(part_cap),
    )
    bpos = slotv.min(axis=-1)
    nmatch = eq.sum(axis=-1, dtype=jnp.int32)
    dup = jnp.any((nmatch > 1) & p_slot_ok)
    return bpos, dup


def probe_strategy(n_parts: int, part_cap: int, probe_cap: int) -> str:
    """Trace-time probe-strategy switch, decided shape-only (the same
    routing class as dense_pallas's pallas_mode gate; the backend and
    pallas mode are both in the ProgramCache key):

      "pallas-tpu"/"pallas-interpret"  partitioned VMEM/SMEM probe kernel
      "dense"   partitioned broadcast-compare (TPU XLA fallback: VPU-rate
                elementwise work, zero [N]-sized gathers)
      "search"  sorted-build binary-search probe (CPU-class backends:
                ~log(nb) cheap gathers per probe beat every O(N log N)
                sort XLA-CPU would otherwise pay; TPU never takes this —
                its per-gather cost is the documented ~16ns floor)
    """
    from .join_pallas import pallas_probe_eligible

    mode = pallas_probe_eligible(n_parts, part_cap, probe_cap)
    if mode:
        return f"pallas-{mode}"
    if jax.default_backend() == "tpu":
        return "dense"
    return "search"


def _probe_search(bw, b_usable, pw, p_usable, nb: int):
    """CPU-class probe: sort the SMALL build side once (the monolithic
    kernel pays this too), then binary-search every probe key against it
    — no combined merge sort, no inverse sort, probe rows stay in place.
    Returns (build_idx int32 [np] (-1 = none), dup flag)."""
    top = I64_MAX
    bk_m = jnp.where(b_usable, bw, top)
    perm = lexsort([bk_m], extra_key=(~b_usable).astype(jnp.int64))
    sw = bk_m[perm]
    nb_usable = b_usable.sum().astype(jnp.int32)
    lo = jnp.searchsorted(sw, pw, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(sw, pw, side="right").astype(jnp.int32)
    hi = jnp.minimum(hi, nb_usable)  # unusable tail never matches
    matched = (hi > lo) & p_usable
    dup = jnp.any(((hi - lo) > 1) & matched)
    build_idx = jnp.where(
        matched, perm[jnp.clip(lo, 0, nb - 1)].astype(jnp.int32), jnp.int32(-1)
    )
    return build_idx, dup


def _probe_partitioned(bw, b_usable, pw, p_usable, plan: tuple,
                       join_capacity: int, mode: str):
    """The partitioned-table probe (pallas / dense): radix-cluster both
    sides, probe each partition against its fixed-capacity build table,
    and route over-full partitions through the escape hatch.  Returns
    (build_idx [np] original-order, matched, dup, esc_over, need,
    escapes) — `dup` (fan-out > 1 seen) is reported SEPARATELY from the
    escape-overflow flag: it only violates the unique-build contract, so
    semi/anti under a non-unique build never flag on it."""
    n_parts, part_cap, probe_cap, esc_cap = plan
    nb, np_ = bw.shape[0], pw.shape[0]
    P = n_parts

    # partition ids from the salted hash; unusable rows pin to P (sort last)
    salt = join_capacity
    b_pid = jnp.where(
        b_usable, (hash_words([bw], salt) & jnp.int64(P - 1)).astype(jnp.int32),
        jnp.int32(P),
    )
    p_pid = jnp.where(
        p_usable, (hash_words([pw], salt) & jnp.int64(P - 1)).astype(jnp.int32),
        jnp.int32(P),
    )
    b_tbl_idx, b_in, b_count, _b_opid, b_oidx, b_start = _partition(b_pid, P, part_cap, nb)
    p_tbl_idx, p_in, p_count, p_opid, p_oidx, p_start = _partition(p_pid, P, probe_cap, np_)

    # the skew escape hatch: an over-full partition (either side) leaves
    # the tables entirely and rides the general kernel below
    esc_part = (b_count > part_cap) | (p_count > probe_cap)
    b_slot_ok = b_in & ~esc_part[:, None]
    p_slot_ok = p_in & ~esc_part[:, None]
    b_key_tbl = bw[b_tbl_idx]
    p_key_tbl = pw[p_tbl_idx]

    if mode.startswith("pallas"):
        from .join_pallas import probe_tables_pallas

        bpos, dup = probe_tables_pallas(
            b_key_tbl, b_slot_ok, p_key_tbl, p_slot_ok,
            interpret=(mode == "pallas-interpret"),
        )
    else:
        bpos, dup = _probe_tables_xla(b_key_tbl, b_slot_ok, p_key_tbl, p_slot_ok, part_cap)
    b_orig_tbl = jnp.take_along_axis(
        b_tbl_idx, jnp.clip(bpos, 0, part_cap - 1), axis=1
    )
    matched_tbl = (bpos < part_cap) & p_slot_ok

    # ---- escape sub-join: general sorted-merge at esc_cap size ----------
    b_buf, b_ok_e, nbe = _escape_rows(b_oidx, b_start, b_count, esc_part, P, esc_cap, nb)
    p_buf, p_ok_e, npe = _escape_rows(p_oidx, p_start, p_count, esc_part, P, esc_cap, np_)
    bke = jnp.where(b_ok_e, bw[b_buf], I64_MAX)
    perm = lexsort([bke], extra_key=(~b_ok_e).astype(jnp.int64))
    sw = bke[perm]
    usable_sorted = jnp.arange(esc_cap, dtype=jnp.int32) < jnp.minimum(nbe, esc_cap)
    pke = pw[p_buf]
    lo, hi = merge_lo_hi(sw, usable_sorted, pke)
    m_e = (hi > lo) & p_ok_e
    dup_e = jnp.any(((hi - lo) > 1) & m_e)
    b_orig_e = b_buf[perm[jnp.clip(lo, 0, esc_cap - 1)]]

    esc_over = (nbe > esc_cap) | (npe > esc_cap)
    escapes = (jnp.minimum(nbe, esc_cap) + jnp.minimum(npe, esc_cap)).astype(jnp.int64)
    # the rung that sizes esc_cap past the observed escape count — the
    # retry driver re-dispatches it directly (a precompiled rung when the
    # ladder is warm), instead of stepping blind
    need = jnp.where(
        esc_over,
        jnp.maximum(nbe, npe).astype(jnp.int64) * ESC_DIV,
        jnp.int64(0),
    )

    # ---- back to original probe order -----------------------------------
    # sorted-probe-space results: row at sorted position s sits in table
    # slot (pid, s - start[pid]) unless its partition escaped
    s = jnp.arange(np_, dtype=jnp.int32)
    pid_c = jnp.clip(p_opid, 0, P - 1)
    r = s - p_start[pid_c]
    in_tbl = (p_opid < P) & (r < probe_cap) & ~esc_part[pid_c]
    flat = pid_c * probe_cap + jnp.clip(r, 0, probe_cap - 1)
    res_sorted = jnp.where(
        in_tbl & matched_tbl.reshape(-1)[flat],
        b_orig_tbl.reshape(-1)[flat].astype(jnp.int32),
        jnp.int32(-1),
    )
    # inverse sort (2-operand int32) restores the probe-identity layout
    _, build_idx = jax.lax.sort((p_oidx, res_sorted), num_keys=1)
    # escape overlay: a small fixed-size scatter (esc_cap slots, distinct
    # targets, invalid slots dropped out of range)
    tgt = jnp.where(p_ok_e, p_buf, jnp.int32(np_))
    esc_val = jnp.where(m_e, b_orig_e.astype(jnp.int32), jnp.int32(-1))
    build_idx = build_idx.at[tgt].set(esc_val, mode="drop")

    matched = build_idx >= 0
    return build_idx, matched, dup | dup_e, esc_over, need, escapes


def _expand_counts(counts_match, get_kth, probe_valid, out_capacity: int,
                   join_type: str, base_overflow, base_need):
    """The prefix-sum output expansion, shared by both non-unique probe
    modes — an exact mirror of ops/join.py's general path: match counts ->
    cumsum offsets -> one merge_searchsorted assigns each static output
    slot its (probe row, nth-match) pair, recovered by `get_kth`."""
    np_ = probe_valid.shape[0]
    counts = counts_match
    if join_type == "left_outer":
        counts = jnp.where(probe_valid, jnp.maximum(counts, 1), 0)
    offsets = jnp.cumsum(counts) - counts  # start slot per probe row
    total = counts.sum()
    overflow = base_overflow | (total > out_capacity)
    # out-capacity need: exact (the prefix sum already computed the true
    # fan-out); escape-buffer need from the caller folds in via maximum
    need = jnp.where(total > out_capacity, total.astype(jnp.int64), jnp.int64(0))
    need = jnp.maximum(need, base_need)

    slot = jnp.arange(out_capacity)
    probe_of = merge_searchsorted((offsets + counts).astype(jnp.int64), slot.astype(jnp.int64), side="right")
    probe_of = jnp.minimum(probe_of, np_ - 1)
    nth = (slot - offsets[probe_of]).astype(jnp.int32)
    build_idx = get_kth(probe_of, nth)
    out_valid = slot < total
    real_match = counts_match[probe_of] > 0
    build_null = ~real_match  # only possible under left_outer fill
    build_idx = jnp.where(build_null, -1, build_idx)
    return JoinResult(
        probe_idx=probe_of,
        build_idx=build_idx,
        build_null=build_null & out_valid,
        out_valid=out_valid,
        n_out=total,
        overflow=overflow,
        need=need,
    )


def _expand_search(bw, b_usable, pw, p_usable, probe_valid, join_type: str,
                   out_capacity: int):
    """Non-unique fan-out, CPU-class: sorted-build searchsorted extents
    give the match COUNT per probe (hi-lo), and the k-th match is the
    sorted run's k-th row — no partition tables, probe rows in place."""
    nb = bw.shape[0]
    bk_m = jnp.where(b_usable, bw, I64_MAX)
    perm = lexsort([bk_m], extra_key=(~b_usable).astype(jnp.int64))
    sw = bk_m[perm]
    nb_usable = b_usable.sum().astype(jnp.int32)
    lo = jnp.searchsorted(sw, pw, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(sw, pw, side="right").astype(jnp.int32)
    hi = jnp.minimum(hi, nb_usable)  # unusable tail never matches
    counts_match = jnp.where(p_usable, jnp.maximum(hi - lo, 0), 0)

    def get_kth(probe_of, nth):
        pos = jnp.clip(lo[probe_of] + nth, 0, nb - 1)
        return perm[pos].astype(jnp.int32)

    return _expand_counts(counts_match, get_kth, probe_valid, out_capacity,
                          join_type, jnp.bool_(False), jnp.int64(0))


def _expand_partitioned(bw, b_usable, pw, p_usable, probe_valid, plan: tuple,
                        join_capacity: int, join_type: str, out_capacity: int):
    """Non-unique fan-out over the partitioned tables: the dense
    broadcast-compare's sum reduction IS the per-probe match count, and the
    k-th matching build slot falls out of a cumsum over the probe's compare
    row.  Escaped partitions count and expand through the sorted-merge
    extents at esc_cap size, exactly like the first-match overlay."""
    n_parts, part_cap, probe_cap, esc_cap = plan
    nb, np_ = bw.shape[0], pw.shape[0]
    P = n_parts

    salt = join_capacity
    b_pid = jnp.where(
        b_usable, (hash_words([bw], salt) & jnp.int64(P - 1)).astype(jnp.int32),
        jnp.int32(P),
    )
    p_pid = jnp.where(
        p_usable, (hash_words([pw], salt) & jnp.int64(P - 1)).astype(jnp.int32),
        jnp.int32(P),
    )
    b_tbl_idx, b_in, b_count, _b_opid, b_oidx, b_start = _partition(b_pid, P, part_cap, nb)
    p_tbl_idx, p_in, p_count, p_opid, p_oidx, p_start = _partition(p_pid, P, probe_cap, np_)

    esc_part = (b_count > part_cap) | (p_count > probe_cap)
    b_slot_ok = b_in & ~esc_part[:, None]
    p_slot_ok = p_in & ~esc_part[:, None]
    b_key_tbl = bw[b_tbl_idx]
    p_key_tbl = pw[p_tbl_idx]

    # dense compare (fan-out needs EVERY match, not the first-slot
    # reduction — this is why pallas downgrades to the tables here)
    eq = (p_key_tbl[:, :, None] == b_key_tbl[:, None, :]) & b_slot_ok[:, None, :] & p_slot_ok[:, :, None]
    nmatch_tbl = eq.sum(axis=-1, dtype=jnp.int32)  # [P, probe_cap]

    # ---- escape sub-join extents (general sorted-merge at esc_cap) ------
    b_buf, b_ok_e, nbe = _escape_rows(b_oidx, b_start, b_count, esc_part, P, esc_cap, nb)
    p_buf, p_ok_e, npe = _escape_rows(p_oidx, p_start, p_count, esc_part, P, esc_cap, np_)
    bke = jnp.where(b_ok_e, bw[b_buf], I64_MAX)
    perm_e = lexsort([bke], extra_key=(~b_ok_e).astype(jnp.int64))
    swe = bke[perm_e]
    usable_sorted = jnp.arange(esc_cap, dtype=jnp.int32) < jnp.minimum(nbe, esc_cap)
    pke = pw[p_buf]
    lo_e, hi_e = merge_lo_hi(swe, usable_sorted, pke)
    cnt_e = jnp.where(p_ok_e, jnp.maximum(hi_e - lo_e, 0), 0)  # per esc slot

    esc_over = (nbe > esc_cap) | (npe > esc_cap)
    escapes = (jnp.minimum(nbe, esc_cap) + jnp.minimum(npe, esc_cap)).astype(jnp.int64)
    base_need = jnp.where(
        esc_over,
        jnp.maximum(nbe, npe).astype(jnp.int64) * ESC_DIV,
        jnp.int64(0),
    )

    # ---- per-ORIGINAL-probe-row location ---------------------------------
    # inverse of the partition sort: s_pos[i] = sorted position of row i
    # (2-operand int32 sort, same trick as the first-match inverse)
    iota = jnp.arange(np_, dtype=jnp.int32)
    _, s_pos = jax.lax.sort((p_oidx, iota), num_keys=1)
    pid_c = jnp.clip(p_pid, 0, P - 1)
    r = s_pos - p_start[pid_c]  # slot within the partition's sorted run
    escaped = esc_part[pid_c] & (p_pid < P)
    in_tbl = (p_pid < P) & ~escaped & (r < probe_cap)
    flat = pid_c * probe_cap + jnp.clip(r, 0, probe_cap - 1)
    cnt_tbl_i = nmatch_tbl.reshape(-1)[flat]
    # escape-buffer position of this probe row (same offsets _escape_rows
    # packed by); rows past esc_cap count 0 — esc_over already discards
    esc_cnt_p = jnp.where(esc_part, p_count, 0).astype(jnp.int32)
    p_off = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(esc_cnt_p)])[:-1]
    e_i = p_off[pid_c] + r
    e_ok = escaped & (e_i >= 0) & (e_i < esc_cap)
    e_c = jnp.clip(e_i, 0, esc_cap - 1)
    counts_match = jnp.where(
        in_tbl, cnt_tbl_i, jnp.where(e_ok, cnt_e[e_c], 0)
    )

    def get_kth(probe_of, nth):
        pidj = pid_c[probe_of]
        rj = jnp.clip(r[probe_of], 0, probe_cap - 1)
        eq_rows = eq[pidj, rj]  # [out_cap, part_cap]
        cum = jnp.cumsum(eq_rows.astype(jnp.int32), axis=-1)
        slotv = jnp.where(
            eq_rows & (cum == nth[:, None] + 1),
            jnp.arange(part_cap, dtype=jnp.int32)[None, :],
            jnp.int32(part_cap),
        )
        bslot = jnp.clip(slotv.min(axis=-1), 0, part_cap - 1)
        idx_tbl = b_tbl_idx[pidj, bslot].astype(jnp.int32)
        pos_e = jnp.clip(lo_e[e_c[probe_of]] + nth, 0, esc_cap - 1)
        idx_esc = b_buf[perm_e[pos_e]].astype(jnp.int32)
        return jnp.where(in_tbl[probe_of], idx_tbl, idx_esc)

    res = _expand_counts(counts_match, get_kth, probe_valid, out_capacity,
                         join_type, esc_over, base_need)
    return res, escapes


def radix_hash_join(
    build_keys: list[CompVal],
    probe_keys: list[CompVal],
    build_valid,
    probe_valid,
    join_type: str,
    join_capacity: int,
    plan: tuple,
    strategy: str | None = None,
    build_unique: bool = True,
    out_capacity: int | None = None,
):
    """Equi-join over the radix-partitioned tables.

    build_unique=True (default — the planner-proven shape): same output
    contract as ops/join.py's build_unique branch (probe_identity layout:
    output slot j IS probe row j), so the builder consumes the result
    through the identical code path.  build_unique=False (the mpp tier's
    exchange-fed shape, ISSUE 18): the prefix-sum expansion path, same
    contract as ops/join.py's general path — `out_capacity` sizes the
    static output table and is required.

    Returns (JoinResult, escapes int64) — escapes is the escaped-row count
    the EXPLAIN ANALYZE / TRACE `join_radix` attribution reports.  The
    JoinResult's `need` hint carries the join-capacity rung that would
    clear an escape-buffer or out-capacity overflow (0 = growth will not
    help: a violated unique-build contract — the driver drops the hint)."""
    n_parts, part_cap, probe_cap, esc_cap = plan
    bkeys, b_usable = _key_matrix(build_keys, build_valid)
    pkeys, p_usable = _key_matrix(probe_keys, probe_valid)
    assert len(bkeys) == 1 and len(pkeys) == 1, "radix join needs single-word keys"
    bw, pw = bkeys[0], pkeys[0]
    assert not jnp.issubdtype(bw.dtype, jnp.floating), "radix join is int-class only"
    nb, np_ = bw.shape[0], pw.shape[0]
    P = n_parts
    mode = strategy or probe_strategy(P, part_cap, probe_cap)

    if not build_unique and join_type in ("inner", "left_outer"):
        assert out_capacity is not None, "non-unique radix join needs out_capacity"
        if mode == "search":
            return _expand_search(
                bw, b_usable, pw, p_usable, probe_valid, join_type, out_capacity
            ), jnp.int64(0)
        # pallas's probe kernel reduces to the FIRST match only — fan-out
        # needs every match, so pallas downgrades to the dense tables
        return _expand_partitioned(
            bw, b_usable, pw, p_usable, probe_valid, plan, join_capacity,
            join_type, out_capacity,
        )

    # first-match probe: the unique-contract fast path, and semi/anti
    # (which only consume the matched flag — fan-out never changes it)
    if mode == "search":
        # CPU-class backends: the partition tables buy nothing (no SMEM
        # to localize into) — the sorted-build binary-search probe skips
        # the combined merge sort AND the inverse sort outright
        build_idx, dup = _probe_search(bw, b_usable, pw, p_usable, nb)
        matched = build_idx >= 0
        hard_over = jnp.bool_(False)
        need = jnp.int64(0)
        escapes = jnp.int64(0)
    else:
        build_idx, matched, dup, hard_over, need, escapes = _probe_partitioned(
            bw, b_usable, pw, p_usable, plan, join_capacity, mode,
        )
    # dup only violates the unique-build CONTRACT — under a non-unique
    # build (semi/anti here) it is expected fan-out, not an error
    overflow = (hard_over | dup) if build_unique else hard_over

    if join_type == "semi":
        keep = probe_valid & matched
        return JoinResult(
            probe_idx=jnp.arange(np_, dtype=jnp.int32),
            build_idx=jnp.full(np_, -1, jnp.int32),
            build_null=jnp.ones(np_, bool),
            out_valid=keep, n_out=keep.sum(), overflow=overflow, need=need,
        ), escapes
    if join_type == "anti":
        keep = probe_valid & ~matched
        return JoinResult(
            probe_idx=jnp.arange(np_, dtype=jnp.int32),
            build_idx=jnp.full(np_, -1, jnp.int32),
            build_null=jnp.ones(np_, bool),
            out_valid=keep, n_out=keep.sum(), overflow=overflow, need=need,
        ), escapes

    out_valid = (probe_valid & matched) if join_type == "inner" else probe_valid
    build_null = ~matched
    return JoinResult(
        probe_idx=jnp.arange(np_, dtype=jnp.int32),
        build_idx=build_idx,
        build_null=build_null & out_valid,
        out_valid=out_valid,
        n_out=out_valid.sum(),
        overflow=overflow,
        need=need,
        probe_identity=True,
    ), escapes
