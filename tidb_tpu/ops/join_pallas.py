"""Pallas probe kernel for the radix-partitioned join (ISSUE 13
tentpole #3) — the fused build+probe inner loop of the int-key equi-join
fast path.

The XLA dense probe (ops/radix_join.py _probe_tables_xla) broadcasts a
[P, probe_cap, part_cap] compare and trusts fusion to keep it out of
HBM.  This kernel is the manual-fusion twin: one sequential-grid sweep,
one grid step per partition, the partition's build keys resident in SMEM
(radix partitioning is what made them fit — the "cache-friendly build
table" the reference's radix design doc partitions for), the probe block
in VMEM, and a statically unrolled slot loop doing the probe at VPU
rate.  No intermediate ever leaves the core.

int64 key words ride as hi/lo int32 pairs (Mosaic has no 64-bit
vectors — dense_pallas._split32's layout), so EVERY int-class key joins
exactly, including unsigned keys bit-flipped into the top half of the
domain; there is no value-range gate at all.  Eligibility is therefore
decided SHAPE-ONLY, before any value work:

  * part_cap <= MAX_PART_CAP (the SMEM table + unrolled-loop budget);
  * probe_cap a multiple of 1024 (whole (8, 128) int32 blocks per
    partition grid step);
  * total probe slots < MAX_ROWS = 2^26 — the same int32 per-lane-column
    accumulator class dense_pallas gates on (the meta rows accumulate
    per-block reductions across the whole grid).

Parity with the XLA probe is byte-exact by construction (same
first-match-slot semantics, same fan-out check) and pinned over the full
key-type matrix in tests/test_radix_join.py, interpret mode included.
Traced under jax.enable_x64(False) for the Mosaic lowering like every
Pallas kernel here (ops/joinscan.py _x64_ctx rationale).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .joinscan import _x64_ctx

LANES = 128
MAX_PART_CAP = 256     # SMEM build-table slots per partition (unrolled)
MAX_ROWS = 1 << 26     # probe-slot bound (dense_pallas MAX_ROWS class)


def pallas_probe_eligible(n_parts: int, part_cap: int, probe_cap: int) -> str | None:
    """'tpu' | 'interpret' | None — the shape-only lowering gate, decided
    before any value work (capacities only, never data)."""
    from .dense_pallas import pallas_mode

    mode = pallas_mode()
    if not mode:
        return None
    if part_cap > MAX_PART_CAP:
        return None
    if probe_cap % 1024 != 0:
        return None
    if n_parts * probe_cap >= MAX_ROWS:
        return None
    return mode


def _make_kernel(n_parts: int, part_cap: int, trp: int):
    def kern(bhi_ref, blo_ref, bok_ref, phi_ref, plo_ref, pok_ref,
             bpos_ref, meta_ref, macc):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            macc[:] = jnp.zeros_like(macc)

        phi = phi_ref[:]
        plo = plo_ref[:]
        pok = pok_ref[:] != 0
        cap = jnp.int32(part_cap)
        bpos = jnp.full((trp, LANES), part_cap, jnp.int32)
        nmatch = jnp.zeros((trp, LANES), jnp.int32)
        # statically unrolled probe: slot g's key broadcasts from SMEM;
        # ascending g means the first hit wins, matching the XLA probe's
        # min-slot reduction exactly
        for g in range(part_cap):
            on = bok_ref[0, g] != 0
            m = pok & on & (phi == bhi_ref[0, g]) & (plo == blo_ref[0, g])
            bpos = jnp.where(m & (bpos == cap), jnp.int32(g), bpos)
            nmatch = nmatch + m.astype(jnp.int32)
        bpos_ref[:] = bpos
        # unique-build fan-out check, vector-accumulated (no scalar VMEM
        # stores on Mosaic); int32 literals for the x64-on interpret path
        one, zero = jnp.int32(1), jnp.int32(0)
        macc[0, :] = macc[0, :] | jnp.max(
            jnp.where(nmatch > 1, one, zero), axis=0
        )

        @pl.when(i == n_parts - 1)
        def _():
            meta_ref[:, :] = macc[:, :]

    return kern


def probe_tables_pallas(b_key_tbl, b_slot_ok, p_key_tbl, p_slot_ok,
                        interpret: bool = False):
    """(bpos int32 [P, probe_cap] — part_cap = no match, dup flag): the
    Pallas twin of _probe_tables_xla over int64 key tables."""
    from .dense_pallas import _split32

    P, part_cap = b_key_tbl.shape
    probe_cap = p_key_tbl.shape[1]
    trp = probe_cap // LANES
    bhi, blo = _split32(b_key_tbl.reshape(-1))
    phi, plo = _split32(p_key_tbl.reshape(-1))

    def btab(a):
        return a.reshape(P, part_cap)

    def plane(a):
        return a.reshape(P * trp, LANES)

    ins = [
        btab(bhi), btab(blo), b_slot_ok.astype(jnp.int32),
        plane(phi), plane(plo),
        p_slot_ok.astype(jnp.int32).reshape(P * trp, LANES),
    ]
    sspec = pl.BlockSpec((1, part_cap), lambda i: (i, 0), memory_space=pltpu.SMEM)
    vspec = pl.BlockSpec((trp, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
    mspec = pl.BlockSpec((8, LANES), lambda i: (0, 0), memory_space=pltpu.VMEM)
    with _x64_ctx(interpret):
        bpos2, meta = pl.pallas_call(
            _make_kernel(P, part_cap, trp),
            grid=(P,),
            in_specs=[sspec, sspec, sspec, vspec, vspec, vspec],
            out_specs=(vspec, mspec),
            out_shape=(
                jax.ShapeDtypeStruct((P * trp, LANES), jnp.int32),
                jax.ShapeDtypeStruct((8, LANES), jnp.int32),
            ),
            scratch_shapes=[pltpu.VMEM((8, LANES), jnp.int32)],
            interpret=interpret,
        )(*ins)
    bpos = bpos2.reshape(P, probe_cap)
    dup = jnp.sum(meta[0].astype(jnp.int64)) != 0
    return bpos, dup
