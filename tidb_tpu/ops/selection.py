"""Selection kernel (ref: unistore/cophandler/mpp_exec.go:1121 selExec,
pkg/expression/chunk_executor.go:423 VectorizedFilter).

On TPU a filter is just a mask intersection — no row movement. Downstream
kernels consume `row_valid`; compaction happens only at output encode or
before capacity-sensitive ops (join build sides)."""

from __future__ import annotations

import jax.numpy as jnp

from ..expr.compile import CompVal, parse_f64_prefix, string_bytes


def apply_selection(row_valid, conds: list[CompVal]):
    """AND of condition truthiness; NULL and false both drop the row
    (SQL WHERE keeps rows where every condition is true and non-NULL).

    String conditions follow MySQL truthiness: the numeric prefix cast to
    double must be non-zero (ref: types/convert.go StrToFloat; a bare string
    in WHERE goes through implicit double conversion)."""
    out = row_valid
    for c in conds:
        if c.value.ndim == 2:
            data, length = string_bytes(c)
            t = parse_f64_prefix(data, length) != 0.0
        elif c.eval_type == "real":
            t = c.value != 0.0
        else:
            t = c.value != 0
        out = out & t & ~c.null
    return out
