"""Selection kernel (ref: unistore/cophandler/mpp_exec.go:1121 selExec,
pkg/expression/chunk_executor.go:423 VectorizedFilter).

On TPU a filter is just a mask intersection — no row movement. Downstream
kernels consume `row_valid`; compaction happens only at output encode or
before capacity-sensitive ops (join build sides)."""

from __future__ import annotations

import jax.numpy as jnp

from ..expr.compile import CompVal


def apply_selection(row_valid, conds: list[CompVal]):
    """AND of condition truthiness; NULL and false both drop the row
    (SQL WHERE keeps rows where every condition is true and non-NULL)."""
    out = row_valid
    for c in conds:
        if c.value.ndim == 2:
            raise NotImplementedError("string-typed filter condition")
        if c.eval_type == "real":
            t = c.value != 0.0
        else:
            t = c.value != 0
        out = out & t & ~c.null
    return out
