"""Equi hash-join kernel (ref: unistore/cophandler/mpp_exec.go:844 joinExec,
pkg/executor/join/hash_join_v2.go).

The reference builds a string-keyed hash map then probes row by row. On TPU
that becomes sort + binary search: sort the build side by join key; for each
probe row, lower/upper-bound searchsorted gives the matching run [lo, hi).
Single-word keys (ints, dates, decimals) sort on the key itself — exact.
Multi-word keys (strings, composites) mix into ONE salted 63-bit hash word
(ops/seg.py), so the build sort stays a cheap single-operand sort no matter
the key arity; exactness is restored by two word-level checks — every build
run must be internally uniform, and every hash-hit probe must word-match its
run head — whose failure (hash collision) raises the overflow flag. The
retry driver's capacity growth re-salts the hash, clearing the collision.

Output expansion (dynamic fan-out) lands in a static `out_capacity` table:
a prefix sum over match counts assigns each output slot to a (probe,
nth-match) pair, recovered with one more searchsorted — fully vectorized,
no data-dependent shapes, overflow flagged for host fallback (SURVEY.md §7
hard parts: join fan-out).

NULL join keys never match (SQL equi-join), mirroring the reference's
skip-on-null (mpp_exec.go joinExec null key handling).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..expr.compile import CompVal
from .keys import lexsort, sort_key_arrays
from .seg import I64_MAX, MAX63, hash_words, run_head_pos, sort_by_word


@dataclass
class JoinResult:
    """Index-pair form: gather output columns from both sides.

    build_idx/probe_idx: int32 [out_capacity] row indices into the original
    batches; for outer-join null-extended rows, build_idx slot is -1 and
    build_null True.
    """

    probe_idx: jax.Array
    build_idx: jax.Array
    build_null: jax.Array  # True where no build match (outer fill)
    out_valid: jax.Array
    n_out: jax.Array
    overflow: jax.Array


def _key_matrix(vals: list[CompVal], valid):
    """Normalized key arrays; rows with any NULL key are excluded via the
    returned `usable` mask (NULL never equi-matches)."""
    keys = []
    usable = valid
    for v in vals:
        usable = usable & ~v.null
        keys.extend(sort_key_arrays(v)[1:])  # drop null-flag word; nulls excluded
    return keys, usable


def hash_join(
    build_keys: list[CompVal],
    probe_keys: list[CompVal],
    build_valid,
    probe_valid,
    out_capacity: int,
    join_type: str = "inner",
):
    """join_type: inner | left_outer (probe side preserved) | semi | anti."""
    bkeys, b_usable = _key_matrix(build_keys, build_valid)
    pkeys, p_usable = _key_matrix(probe_keys, probe_valid)
    nb = build_valid.shape[0]
    overflow = jnp.bool_(False)

    if len(bkeys) == 1:
        # exact single-word path: sort on the key itself. Mask unusable
        # (invalid / NULL-key) build rows to +max so the sorted array is
        # globally ordered by the key word alone — searchsorted needs that.
        # A LEGITIMATE +max key (BIGINT max, +inf) collides with the mask
        # value, so an unusable-last tiebreak key forces every masked row
        # behind the usable rows of the max-key run; all unusable rows then
        # occupy exactly the tail positions [nb_usable, nb), which the hi
        # clip below removes.
        bk, pk = bkeys[0], pkeys[0]
        top = jnp.inf if jnp.issubdtype(bk.dtype, jnp.floating) else I64_MAX
        bk_m = jnp.where(b_usable, bk, top)
        bperm = lexsort([bk_m], extra_key=(~b_usable).astype(jnp.int64))
        bk_s = bk_m[bperm]
        nb_usable = b_usable.sum()
        # method='sort': the merge formulation (sort queries with the
        # haystack + cumsum) — the default binary search is ~17 serial
        # gather rounds, ~18ms per 64K queries on TPU; the merge is one
        # cheap variadic sort
        lo = jnp.searchsorted(bk_s, pk, side="left", method="sort").astype(jnp.int32)
        hi = jnp.searchsorted(bk_s, pk, side="right", method="sort").astype(jnp.int32)
        hi = jnp.minimum(hi, nb_usable.astype(jnp.int32))
        lo = jnp.minimum(lo, hi)
    else:
        # multi-word keys: one salted hash word per side; unusable rows pin
        # to the (odd, never-hashable) I64_MAX sentinel and sort last
        salt = out_capacity
        bh = jnp.where(b_usable, hash_words(bkeys, salt) & MAX63, I64_MAX)
        ph = jnp.where(p_usable, hash_words(pkeys, salt) & MAX63, I64_MAX)
        bh_s, bperm = sort_by_word(bh)
        lo = jnp.searchsorted(bh_s, ph, side="left", method="sort").astype(jnp.int32)
        hi = jnp.searchsorted(bh_s, ph, side="right", method="sort").astype(jnp.int32)
        lo = jnp.minimum(lo, hi)
        # exactness check 1: every build hash run is internally uniform
        one = jnp.ones(1, bool)
        diffb = jnp.concatenate([one, bh_s[1:] != bh_s[:-1]])
        headb = run_head_pos(diffb)
        bcoll = jnp.zeros(nb, bool)
        for w in bkeys:
            ws = w[bperm]
            bcoll = bcoll | (ws != ws[headb])
        overflow = overflow | jnp.any(bcoll & b_usable[bperm])
        # exactness check 2: every hash-hit probe word-matches its run head
        head_idx = bperm[jnp.clip(lo, 0, nb - 1)]
        pmism = jnp.zeros(p_usable.shape[0], bool)
        for bw, pw in zip(bkeys, pkeys):
            pmism = pmism | (bw[head_idx] != pw)
        hash_hit = p_usable & (hi > lo)
        overflow = overflow | jnp.any(pmism & hash_hit)

    counts = jnp.where(p_usable, hi - lo, 0)
    matched = counts > 0

    if join_type == "semi":
        return JoinResult(
            probe_idx=jnp.arange(probe_valid.shape[0], dtype=jnp.int32),
            build_idx=jnp.full(probe_valid.shape[0], -1, jnp.int32),
            build_null=jnp.ones(probe_valid.shape[0], bool),
            out_valid=probe_valid & matched,
            n_out=(probe_valid & matched).sum(),
            overflow=overflow,
        )
    if join_type == "anti":
        keep = probe_valid & ~matched
        return JoinResult(
            probe_idx=jnp.arange(probe_valid.shape[0], dtype=jnp.int32),
            build_idx=jnp.full(probe_valid.shape[0], -1, jnp.int32),
            build_null=jnp.ones(probe_valid.shape[0], bool),
            out_valid=keep,
            n_out=keep.sum(),
            overflow=overflow,
        )

    if join_type == "left_outer":
        counts = jnp.where(probe_valid, jnp.maximum(counts, 1), 0)

    offsets = jnp.cumsum(counts) - counts  # start slot per probe row
    total = counts.sum()
    overflow = overflow | (total > out_capacity)

    slot = jnp.arange(out_capacity)
    # which probe row does each output slot belong to
    probe_of = jnp.searchsorted(offsets + counts, slot, side="right", method="sort").astype(jnp.int32)
    probe_of = jnp.minimum(probe_of, probe_valid.shape[0] - 1)
    nth = slot - offsets[probe_of]
    b_sorted_pos = lo[probe_of] + nth.astype(jnp.int32)
    b_sorted_pos = jnp.clip(b_sorted_pos, 0, nb - 1)
    build_idx = bperm[b_sorted_pos].astype(jnp.int32)
    out_valid = slot < total
    real_match = p_usable[probe_of] & ((hi[probe_of] - lo[probe_of]) > 0)
    build_null = ~real_match  # only possible under left_outer fill
    build_idx = jnp.where(build_null, -1, build_idx)

    return JoinResult(
        probe_idx=probe_of,
        build_idx=build_idx,
        build_null=build_null & out_valid,
        out_valid=out_valid,
        n_out=total,
        overflow=overflow,
    )
