"""Equi hash-join kernel (ref: unistore/cophandler/mpp_exec.go:844 joinExec,
pkg/executor/join/hash_join_v2.go).

The reference builds a string-keyed hash map then probes row by row. On TPU
that becomes sort + binary search: sort the build side by join key; for each
probe row, lower/upper-bound searchsorted gives the matching run [lo, hi).
Single-word keys (ints, dates, decimals) sort on the key itself — exact.
Multi-word keys (strings, composites) mix into ONE salted 63-bit hash word
(ops/seg.py), so the build sort stays a cheap single-operand sort no matter
the key arity; exactness is restored by two word-level checks — every build
run must be internally uniform, and every hash-hit probe must word-match its
run head — whose failure (hash collision) raises the overflow flag. The
retry driver's capacity growth re-salts the hash, clearing the collision.

Output expansion (dynamic fan-out) lands in a static `out_capacity` table:
a prefix sum over match counts assigns each output slot to a (probe,
nth-match) pair, recovered with one more searchsorted — fully vectorized,
no data-dependent shapes, overflow flagged for host fallback (SURVEY.md §7
hard parts: join fan-out).

NULL join keys never match (SQL equi-join), mirroring the reference's
skip-on-null (mpp_exec.go joinExec null key handling).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..expr.compile import CompVal
from .keys import lexsort, sort_key_arrays
from .seg import I64_MAX, MAX63, hash_words, merge_searchsorted, run_head_pos, sort_by_word


@dataclass
class JoinResult:
    """Index-pair form: gather output columns from both sides.

    build_idx/probe_idx: int32 [out_capacity] row indices into the original
    batches; for outer-join null-extended rows, build_idx slot is -1 and
    build_null True. probe_identity=True means probe_idx is the identity
    (unique-build layout): the builder skips the probe-side gathers, which
    at ~16ns/row/column are the dominant join cost on TPU.
    """

    probe_idx: jax.Array
    build_idx: jax.Array
    build_null: jax.Array  # True where no build match (outer fill)
    out_valid: jax.Array
    n_out: jax.Array
    overflow: jax.Array
    probe_identity: bool = False
    # capacity NEED hint riding next to the overflow flag (exec/ladder.py):
    # when overflow is a pure out-capacity miss, `need` is the join
    # capacity that clears it and the retry driver jumps straight to that
    # rung; 0 = growth will not help (hash collision / violated
    # unique-build hint) and the driver takes the conservative dual action
    need: jax.Array | None = None


def merge_lo_hi(sorted_hay, hay_counted, queries):
    """(lo, hi) match extents of every query against the counted hay rows
    — the equi-join probe — with ZERO [N]-sized random gathers (each costs
    ~16ns/row on TPU, the dominant join cost before this).

    ONE merged 4-operand sort + cumsum gives the counted-hay prefix; each
    equal-VALUE block's extents broadcast to all its elements by a forward
    cummax (block-start prefix; prefixes are nondecreasing) and a reverse
    cummin (block-end prefix); an inverse 3-operand sort returns (lo, hi)
    in query order. lo..hi-1 index the counted prefix of the hay order.

    hay_counted MUST occupy a prefix of the hay sort order (callers mask
    unusable rows to the top sentinel with an unusable-last tiebreak)."""
    nh, nq = sorted_hay.shape[0], queries.shape[0]
    vals = jnp.concatenate([sorted_hay, queries])
    # ties: queries first — a query's exclusive prefix excludes equal hay
    order = jnp.concatenate([jnp.ones(nh, jnp.int32), jnp.zeros(nq, jnp.int32)])
    cntf = jnp.concatenate([hay_counted.astype(jnp.int32), jnp.zeros(nq, jnp.int32)])
    qidx = jnp.concatenate([jnp.full(nh, nq, jnp.int32), jnp.arange(nq, dtype=jnp.int32)])
    sv, _, scnt, sq = jax.lax.sort((vals, order, cntf, qidx), num_keys=2)
    cum = jnp.cumsum(scnt)  # counted hay at or before position (inclusive)
    one = jnp.ones(1, bool)
    diff = jnp.concatenate([one, sv[1:] != sv[:-1]])
    lo_b = jax.lax.cummax(jnp.where(diff, cum - scnt, jnp.int32(-1)))
    emark = jnp.concatenate([diff[1:], one])
    hi_b = jax.lax.cummin(jnp.where(emark, cum, jnp.int32(nh + nq + 1))[::-1])[::-1]
    # back to query order (hay rows carry qidx=nq and sort to the tail)
    _, lo_q, hi_q = jax.lax.sort((sq, lo_b, hi_b), num_keys=1)
    return lo_q[:nq], hi_q[:nq]


def _key_matrix(vals: list[CompVal], valid):
    """Normalized key arrays; rows with any NULL key are excluded via the
    returned `usable` mask (NULL never equi-matches)."""
    keys = []
    usable = valid
    for v in vals:
        usable = usable & ~v.null
        keys.extend(sort_key_arrays(v)[1:])  # drop null-flag word; nulls excluded
    return keys, usable


def hash_join(
    build_keys: list[CompVal],
    probe_keys: list[CompVal],
    build_valid,
    probe_valid,
    out_capacity: int,
    join_type: str = "inner",
    build_unique: bool = False,
):
    """join_type: inner | left_outer (probe side preserved) | semi | anti.

    build_unique: planner-proven one-match-per-probe (build keys unique);
    the output keeps the probe layout and the expansion pass is skipped.
    Runtime-verified — fan-out > 1 raises the overflow flag."""
    bkeys, b_usable = _key_matrix(build_keys, build_valid)
    pkeys, p_usable = _key_matrix(probe_keys, probe_valid)
    nb = build_valid.shape[0]
    np_ = probe_valid.shape[0]
    overflow = jnp.bool_(False)
    nb_usable = b_usable.sum().astype(jnp.int32)

    if len(bkeys) == 1:
        # exact single-word path: sort on the key itself. Mask unusable
        # (invalid / NULL-key) build rows to +max so the sorted array is
        # globally ordered by the key word alone — searchsorted needs that.
        # A LEGITIMATE +max key (BIGINT max, +inf) collides with the mask
        # value, so an unusable-last tiebreak key forces every masked row
        # behind the usable rows of the max-key run; all unusable rows then
        # occupy exactly the tail positions [nb_usable, nb), which the hi
        # clip below removes.
        bk, pk = bkeys[0], pkeys[0]
        top = jnp.inf if jnp.issubdtype(bk.dtype, jnp.floating) else I64_MAX
        bk_m = jnp.where(b_usable, bk, top)
        bperm = lexsort([bk_m], extra_key=(~b_usable).astype(jnp.int64))
        sorted_word = bk_m[bperm]
        probe_word = pk
    else:
        # multi-word keys: one salted hash word per side; unusable rows pin
        # to the (odd, never-hashable) I64_MAX sentinel and sort last
        salt = out_capacity
        bh = jnp.where(b_usable, hash_words(bkeys, salt) & MAX63, I64_MAX)
        ph = jnp.where(p_usable, hash_words(pkeys, salt) & MAX63, I64_MAX)
        sorted_word, bperm = sort_by_word(bh)
        probe_word = ph

    # usable rows occupy the sorted prefix (top-sentinel masking +
    # unusable-last tiebreak), so the counted flag needs no gather
    usable_sorted = jnp.arange(nb, dtype=jnp.int32) < nb_usable
    lo, hi = merge_lo_hi(sorted_word, usable_sorted, probe_word)
    lo_c = jnp.clip(lo, 0, nb - 1)
    matched = (hi > lo) & p_usable
    hi = jnp.where(matched, hi, lo)

    if len(bkeys) > 1:
        # exactness check 1: every build hash run is internally uniform
        one = jnp.ones(1, bool)
        diffb = jnp.concatenate([one, sorted_word[1:] != sorted_word[:-1]])
        headb = run_head_pos(diffb)
        bcoll = jnp.zeros(nb, bool)
        for w in bkeys:
            ws = w[bperm]
            bcoll = bcoll | (ws != ws[headb])
        overflow = overflow | jnp.any(bcoll & b_usable[bperm])
        # exactness check 2: every hash-hit probe word-matches its run head
        head_idx = bperm[lo_c]
        pmism = jnp.zeros(np_, bool)
        for bw, pw in zip(bkeys, pkeys):
            pmism = pmism | (bw[head_idx] != pw)
        overflow = overflow | jnp.any(pmism & matched)

    counts = jnp.where(p_usable, hi - lo, 0)
    matched = counts > 0

    if join_type == "semi":
        return JoinResult(
            probe_idx=jnp.arange(probe_valid.shape[0], dtype=jnp.int32),
            build_idx=jnp.full(probe_valid.shape[0], -1, jnp.int32),
            build_null=jnp.ones(probe_valid.shape[0], bool),
            out_valid=probe_valid & matched,
            n_out=(probe_valid & matched).sum(),
            overflow=overflow,
        )
    if join_type == "anti":
        keep = probe_valid & ~matched
        return JoinResult(
            probe_idx=jnp.arange(probe_valid.shape[0], dtype=jnp.int32),
            build_idx=jnp.full(probe_valid.shape[0], -1, jnp.int32),
            build_null=jnp.ones(probe_valid.shape[0], bool),
            out_valid=keep,
            n_out=keep.sum(),
            overflow=overflow,
        )

    if build_unique and join_type in ("inner", "left_outer"):
        # one-match-per-probe: output slot j IS probe row j — no prefix-sum
        # expansion, no out-capacity searchsorted pass. Verified here: any
        # run longer than one build row flips overflow and the driver
        # recompiles with the general kernel.
        overflow = overflow | jnp.any(counts > 1)
        build_idx = bperm[lo_c].astype(jnp.int32)
        out_valid = (probe_valid & matched) if join_type == "inner" else probe_valid
        build_null = ~matched
        build_idx = jnp.where(build_null, -1, build_idx)
        return JoinResult(
            probe_idx=jnp.arange(np_, dtype=jnp.int32),
            build_idx=build_idx,
            build_null=build_null & out_valid,
            out_valid=out_valid,
            n_out=out_valid.sum(),
            overflow=overflow,
            probe_identity=True,
        )

    if join_type == "left_outer":
        counts = jnp.where(probe_valid, jnp.maximum(counts, 1), 0)

    offsets = jnp.cumsum(counts) - counts  # start slot per probe row
    total = counts.sum()
    overflow = overflow | (total > out_capacity)
    # out-capacity need: exact (the prefix sum already computed the true
    # fan-out); zero when the overflow came from a collision check above
    need = jnp.where(total > out_capacity, total.astype(jnp.int64), jnp.int64(0))

    slot = jnp.arange(out_capacity)
    # which probe row does each output slot belong to
    probe_of = merge_searchsorted((offsets + counts).astype(jnp.int64), slot.astype(jnp.int64), side="right")
    probe_of = jnp.minimum(probe_of, probe_valid.shape[0] - 1)
    nth = slot - offsets[probe_of]
    b_sorted_pos = lo[probe_of] + nth.astype(jnp.int32)
    b_sorted_pos = jnp.clip(b_sorted_pos, 0, nb - 1)
    build_idx = bperm[b_sorted_pos].astype(jnp.int32)
    out_valid = slot < total
    real_match = p_usable[probe_of] & ((hi[probe_of] - lo[probe_of]) > 0)
    build_null = ~real_match  # only possible under left_outer fill
    build_idx = jnp.where(build_null, -1, build_idx)

    return JoinResult(
        probe_idx=probe_of,
        build_idx=build_idx,
        build_null=build_null & out_valid,
        out_valid=out_valid,
        n_out=total,
        overflow=overflow,
        need=need,
    )
