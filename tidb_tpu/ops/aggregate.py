"""Aggregation kernels (ref: unistore/cophandler/mpp_exec.go:999 aggExec,
pkg/executor/aggregate/agg_hash_executor.go, pkg/executor/aggfuncs).

TPU-native shape: the reference keys a hash table on encoded group datums
and updates per-row (pointer chasing — hostile to the VPU). Here group-by
is hash-cluster based: normalize keys to int64 words (ops/keys.py), mix
them into ONE 63-bit hash word (ops/seg.py), sort by that single word, and
reduce each contiguous hash cluster with scatter-free segment passes
(cumsum + boundary gathers). Every per-row array needed after the sort
(agg args, null masks, a second verification hash) rides the SAME sort as
extra variadic-sort operands: random [N] gathers cost ~20ns/row on TPU
(half the whole kernel budget per column), while an extra sort operand is
~1ms/2M rows. Row validity folds into the hash word itself (invalid rows
pin to I64_MAX), so even the validity mask needs no gather. Collisions
(different keys, equal 62-bit hash) are caught by a neighbor compare on
the independently-salted second hash (miss probability ~2^-124 per pair)
and surface as the overflow flag; the retry driver's larger capacity
re-salts both hashes. Dynamic group counts live behind a static
`group_capacity` plus that flag (SURVEY.md §7 "hard parts").

Two phases mirror the reference's partial/final split
(ref: pkg/expression/aggregation modes):
  raw phase    (Complete/Partial1)  raw rows in
  merge phase  (Partial2/Final)     partial-state columns in, reduced by
                                    state-specific merge (+, +, min, max...)

Partial states (expr/agg.py): count=[n], sum=[s], avg=[n,s], min/max=[v].
The psum across regions of these states is exactly the ICI-mesh merge of the
north star (BASELINE.json): count/sum/avg states add elementwise.

Output groups are ordered by first encounter (earliest contributing input
row), matching the row-at-a-time oracle's insertion order.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..expr.agg import AggDesc
from ..expr.compile import CompVal, _round_div, _scale
from ..types import FieldType, TypeCode
from .keys import segments_from_sorted, sort_key_arrays
from .seg import (
    I64_MAX,
    DenseCtx,
    SegCtx,
    SumBatch,
    group_hash,
    hash_words,
    make_segctx,
    seg_bitreduce,
    seg_first_match,
    seg_max,
    seg_min,
    seg_sum,
)

I64_MIN_ = np.int64(-0x8000000000000000)  # numpy: import-time pure (vet: jit-purity)


@dataclass
class GroupAggResult:
    """Fixed-capacity aggregation output.

    group_rep: int32 [G] earliest original input-row index per group (gather
    group-by output columns from the original batch with it; earliest matches
    the row-at-a-time oracle's first-encountered semantics).
    states: per agg, either a list of (value[G], null[G]) state/result
    columns or a GatherState (the caller gathers the agg's value column —
    and its raw string bytes — from the original batch).
    """

    group_rep: jax.Array
    group_valid: jax.Array
    n_groups: jax.Array
    overflow: jax.Array
    states: list
    # capacity NEED hint (exec/ladder.py): the TRUE distinct-group count
    # when the kernel knows it even past capacity (the sort kernel's
    # segment count), so an overflow retry re-dispatches the exact
    # precompiled rung; None/0 = unknown (dense kernels stop inserting at
    # capacity) and the driver steps the ladder geometrically
    need: jax.Array | None = None


@dataclass
class GatherState:
    """Per-group 'fetch this original row' aggregate state.

    Serves first_row (any mode: the earliest original row of the group — in
    merge mode the earliest partial state with has>0) and min/max over
    strings (segmented lexicographic arg-extreme). Gathering from the
    *original* batch lets string aggregates carry their raw bytes, which the
    packed compare words alone cannot (ref: aggfuncs/func_first_row.go,
    func_max_min.go — the reference keeps whole datums in its partial
    results; here the row index plays that role)."""

    idx: jax.Array  # int32 [G] original row index (clipped; dead when ~has)
    has: jax.Array  # bool [G] group produced a state


def _masked(vals, mask, fill):
    return jnp.where(mask, vals, fill)


_VAR_FUNCS = frozenset({"stddev_pop", "stddev_samp", "var_pop", "var_samp"})


def _as_f64(a: CompVal):
    """Value lane as float64 (stddev/var are always DOUBLE in MySQL)."""
    if a.eval_type == "real":
        return a.value
    if a.eval_type == "decimal":
        return a.value.astype(jnp.float64) / float(10 ** max(a.ft.decimal, 0))
    return a.value.astype(jnp.float64)


_BIT_OPS = {
    "bit_and": (jnp.bitwise_and, -1),  # identity all-ones (MySQL empty BIT_AND = 2^64-1)
    "bit_or": (jnp.bitwise_or, 0),
    "bit_xor": (jnp.bitwise_xor, 0),
}


def _agg_states_raw(desc: AggDesc, args: list[CompVal], valid, ctx: SegCtx):
    """Per-group partial states from raw rows."""
    name = desc.name
    nseg = ctx.nseg
    if name == "count":
        mask = valid
        for a in args:
            mask = mask & ~a.null
        return [(seg_sum(ctx, mask.astype(jnp.int64)), jnp.zeros(nseg, bool))]
    a = args[0]
    mask = valid & ~a.null
    cnt = seg_sum(ctx, mask.astype(jnp.int64))
    empty = cnt == 0
    if name in ("sum", "avg"):
        if a.eval_type == "real":
            s = seg_sum(ctx, _masked(a.value, mask, 0.0))
        else:
            s = seg_sum(ctx, _masked(a.value.astype(jnp.int64), mask, jnp.int64(0)))
        if name == "sum":
            return [(s, empty)]
        return [(cnt, jnp.zeros(nseg, bool)), (s, empty)]
    if name in ("min", "max"):
        op = seg_min if name == "min" else seg_max
        if a.eval_type == "real":
            fill = jnp.inf if name == "min" else -jnp.inf
            v = op(ctx, _masked(a.value, mask, fill))
        elif a.value.ndim == 2:
            raise AssertionError("string min/max is routed via GatherState")
        elif a.ft.is_unsigned() and a.eval_type == "int":
            flip = jnp.int64(-0x8000000000000000)
            av = a.value.astype(jnp.int64) ^ flip
            fill = I64_MAX if name == "min" else I64_MIN_
            v = op(ctx, _masked(av, mask, fill)) ^ flip
        else:
            av = a.value.astype(jnp.int64)
            fill = I64_MAX if name == "min" else I64_MIN_
            v = op(ctx, _masked(av, mask, fill))
        return [(v, empty)]
    if name == "first_row":
        raise AssertionError("first_row is routed via GatherState")
    if name in _VAR_FUNCS:
        # moment states [count, sum, sum_sq] — additive, mesh-mergeable
        # (ref: executor/aggfuncs/func_varpop.go partial results)
        v = _as_f64(a)
        s = seg_sum(ctx, _masked(v, mask, 0.0))
        q = seg_sum(ctx, _masked(v * v, mask, 0.0))
        nn = cnt == 0
        return [(cnt, jnp.zeros(nseg, bool)), (s, nn), (q, nn)]
    if name == "group_concat":
        raise NotImplementedError("group_concat on device (root-only, oracle-evaluated)")
    if name in _BIT_OPS:
        red, fill = _BIT_OPS[name]
        v = seg_bitreduce(ctx, red, _masked(a.value.astype(jnp.int64), mask, jnp.int64(fill)), fill)
        # MySQL BIT_* never return NULL: empty set yields the identity
        return [(v, jnp.zeros(nseg, bool))]
    raise NotImplementedError(f"aggregate {name} on device")


def _first_match_idx(mask_s, orig_s, ctx: SegCtx, n):
    """Per-segment earliest ORIGINAL row index among mask rows.

    mask_s/orig_s are in sorted order (orig_s = perm, the original index of
    each sorted position). sort_by_word is stable, so the first masked
    sorted position IS the earliest original row — one cumsum+searchsorted
    (seg_first_match), no segmented scan. Returns (idx[nseg], has[nseg])."""
    pos, has = seg_first_match(ctx, mask_s)
    idx = orig_s[pos].astype(jnp.int32)
    return jnp.clip(idx, 0, n - 1), has


def _arg_extreme_mask(words_s, cand, ctx: SegCtx, maximize: bool):
    """Narrow `cand` (sorted order) to rows holding the per-segment
    lexicographic extreme of `words_s` ([n, K] int64, most significant word
    first — the packed-string key layout). Word-by-word radix arg-extreme:
    K static segment reduces, no data-dependent shapes."""
    for k in range(words_s.shape[1]):
        w = words_s[:, k]
        if maximize:
            best = seg_max(ctx, jnp.where(cand, w, I64_MIN_))
        else:
            best = seg_min(ctx, jnp.where(cand, w, I64_MAX))
        cand = cand & (w == best[ctx.seg])
    return cand


def _distinct_states(desc: AggDesc, args: list, row_valid, hp, nseg: int, salt: int):
    """COUNT/SUM/AVG(DISTINCT ...) states via a secondary sort by
    (group hash, arg hash): the first row of each distinct (group, args)
    combination contributes exactly once (ref: aggfuncs distinct set
    semantics, executor/aggfuncs/func_count_distinct.go — the sort replaces
    the hash set).

    Group numbering matches the main sort's: both cluster by the same group
    hash word, so segment ids depend only on hash ranks. The value lane and
    NULL-arg mask ride the sort as payload operands (no [N] gathers).
    Returns (states, collision_flag) — arg-hash collisions are detected by
    a neighbor compare on a second arg hash and clear on the salted retry."""
    argkeys: list = []
    amask = row_valid
    for a in args:
        amask = amask & ~a.null
        argkeys.extend(sort_key_arrays(a))
    ah = hash_words(argkeys, salt + 1)
    ah2 = hash_words(argkeys, salt + 2)
    need_val = desc.name != "count"
    a0 = args[0]
    if need_val and a0.value.ndim != 1:
        raise NotImplementedError(f"DISTINCT {desc.name} over string values")
    operands = [hp, ah, ah2, amask] + ([a0.value] if need_val else [])
    sorted_ops = jax.lax.sort(tuple(operands), num_keys=2)
    hps, ahs, ah2s = sorted_ops[0], sorted_ops[1], sorted_ops[2]
    amask_s = sorted_ops[3]
    valid2 = hps != I64_MAX
    seg2, _ = segments_from_sorted([hps], valid2)
    seg2 = jnp.minimum(seg2, nseg - 1)
    ctx2 = make_segctx(seg2, nseg)
    fal = jnp.zeros(1, bool)
    same_run = jnp.concatenate([fal, (hps[1:] == hps[:-1]) & (ahs[1:] == ahs[:-1])])
    mism = jnp.concatenate([fal, ah2s[1:] != ah2s[:-1]])
    pair_valid = valid2 & jnp.concatenate([fal, valid2[:-1]])
    collision = jnp.any(same_run & mism & pair_valid)
    diff = ~same_run
    uniq = diff & valid2 & amask_s
    cnt = seg_sum(ctx2, uniq.astype(jnp.int64))
    if desc.name == "count":
        return [(cnt, jnp.zeros(nseg, bool))], collision
    a2 = sorted_ops[4]
    empty = cnt == 0
    if desc.name in _VAR_FUNCS:
        v2 = _as_f64(CompVal(a2, jnp.zeros_like(amask_s), a0.ft))
        s = seg_sum(ctx2, jnp.where(uniq, v2, 0.0))
        q = seg_sum(ctx2, jnp.where(uniq, v2 * v2, 0.0))
        return [(cnt, jnp.zeros(nseg, bool)), (s, empty), (q, empty)], collision
    if a0.eval_type == "real":
        s = seg_sum(ctx2, jnp.where(uniq, a2, 0.0))
    else:
        s = seg_sum(ctx2, jnp.where(uniq, a2.astype(jnp.int64), jnp.int64(0)))
    if desc.name == "sum":
        return [(s, empty)], collision
    return [(cnt, jnp.zeros(nseg, bool)), (s, empty)], collision


def _agg_states_merge(desc: AggDesc, args: list[CompVal], valid, ctx: SegCtx):
    """Merge partial-state columns (Partial2/Final): args are state cols."""
    name = desc.name
    nseg = ctx.nseg
    if name == "count":
        a = args[0]
        return [(seg_sum(ctx, _masked(a.value, valid, 0)), jnp.zeros(nseg, bool))]
    if name in ("sum", "avg"):
        out = []
        for a in args:  # count then sum for avg; sum only for sum
            mask = valid & ~a.null
            present = seg_sum(ctx, mask.astype(jnp.int64)) > 0
            if a.eval_type == "real":
                s = seg_sum(ctx, _masked(a.value, mask, 0.0))
            else:
                s = seg_sum(ctx, _masked(a.value.astype(jnp.int64), mask, jnp.int64(0)))
            out.append((s, ~present))
        if name == "avg":
            # count state never null
            out[0] = (out[0][0], jnp.zeros(nseg, bool))
        return out
    if name in ("min", "max"):
        return _agg_states_raw(desc, args, valid, ctx)
    if name in _VAR_FUNCS:
        # additive moment states: sum each of [count, sum, sum_sq]
        cnt_a, s_a, q_a = args
        mask = valid & ~s_a.null
        cnt = seg_sum(ctx, _masked(cnt_a.value.astype(jnp.int64), valid, jnp.int64(0)))
        s = seg_sum(ctx, _masked(s_a.value, mask, 0.0))
        q = seg_sum(ctx, _masked(q_a.value, mask, 0.0))
        nn = cnt == 0
        return [(cnt, jnp.zeros(nseg, bool)), (s, nn), (q, nn)]
    if name == "first_row":
        raise AssertionError("first_row merge is routed via GatherState")
    if name in _BIT_OPS:
        # reduce of reduces — same segmented bitwise kernel over state cols
        return _agg_states_raw(desc, args, valid, ctx)
    raise NotImplementedError(f"merge of {name} on device")


def finalize_agg(desc: AggDesc, states: list, group_valid) -> tuple:
    """State columns -> final (value, null) result column."""
    name = desc.name
    if name == "avg":
        cnt, (s, snull) = states[0][0], states[1]
        if desc.ft.eval_type() == "real":
            out = s / jnp.where(cnt == 0, 1.0, cnt).astype(jnp.float64)
            return out, snull | (cnt == 0)
        # decimal: scale(avg) = scale(sum) + 4 (div frac incr)
        sum_scale = _scale(desc.partial_fts()[1])
        tgt = _scale(desc.ft)
        num = s * jnp.int64(10 ** (tgt - sum_scale))
        out = _round_div(num, jnp.where(cnt == 0, jnp.int64(1), cnt))
        return out, snull | (cnt == 0)
    if name == "first_row":
        has = states[0][0]
        v, nl = states[1]
        return v, nl | (has == 0)
    if name in _VAR_FUNCS:
        cnt = states[0][0]
        s, q = states[1][0], states[2][0]
        n = jnp.maximum(cnt, 1).astype(jnp.float64)
        mean = s / n
        if name.endswith("samp"):
            var = jnp.maximum(q - n * mean * mean, 0.0) / jnp.maximum(n - 1.0, 1.0)
            null = cnt < 2  # sample stats undefined for n < 2 (MySQL NULL)
        else:
            var = jnp.maximum(q / n - mean * mean, 0.0)
            null = cnt == 0
        out = jnp.sqrt(var) if name.startswith("stddev") else var
        return out, null
    # identity finalize
    v, nl = states[0][0], states[0][1]
    return v, nl


def _gather_state_sorted(desc, sorted_avs, valid_s, ctx: SegCtx, perm, n, merge):
    """GatherState for first_row / string min-max, from SORTED args."""
    name = desc.name
    if name == "first_row":
        mask = valid_s
        if merge:
            # merge input states are [has, value]: earliest state with has>0
            mask = mask & (sorted_avs[0].value > 0)
        idx, has = _first_match_idx(mask, perm, ctx, n)
        return GatherState(idx, has)
    a = sorted_avs[-1]  # merge-mode state col == value col, same kernel
    mask = valid_s & ~a.null
    cand = _arg_extreme_mask(a.value, mask, ctx, name == "max")
    idx, has = _first_match_idx(cand, perm, ctx, n)
    return GatherState(idx, has)


def _needs_gather_state(desc, arg_vals) -> bool:
    if desc.name == "first_row":
        return True
    return desc.name in ("min", "max") and bool(arg_vals) and arg_vals[-1].value.ndim == 2


def _is_distinct_special(desc, arg_vals, merge) -> bool:
    if desc.distinct and desc.name in ({"count", "sum", "avg"} | _VAR_FUNCS) and arg_vals:
        if merge:
            raise NotImplementedError(
                "DISTINCT aggregates are not decomposable into mergeable partials; "
                "plan them in Complete mode (ref: AggregationPushDownSolver skips distinct)"
            )
        return True
    return False


def _dense_eligible(aggs, merge) -> bool:
    """The dense small-G kernel handles everything except DISTINCT and
    string-valued gather aggregates (their word-matrix machinery assumes
    the sorted layout)."""
    for desc, avs in aggs:
        if desc.distinct:
            return False
        if desc.name in ("min", "max") and avs and avs[-1].value.ndim == 2:
            return False
        if desc.name == "group_concat":
            return False
    return True


def _group_aggregate_dense(group_bys, aggs, row_valid, g_cap: int, merge: bool):
    """Sort-free small-G aggregation (see seg.DenseCtx).

    The distinct-hash table is extracted from a strided SAMPLE (serial
    min-extraction over 4M rows costs 2*g_cap full passes; over a 4K sample
    it is free), then two single-pass checks make the result exact:
    every valid row's hash must be IN the table (catches groups the sample
    missed) and the secondary hash must be constant within a slot (catches
    true hash collisions). Either failure, or more distinct hashes than
    g_cap, raises the overflow flag and the driver falls back to the sort
    kernel — the same contract a wrong NDV hint always had."""
    n = row_valid.shape[0]
    keys: list[jax.Array] = []
    for g in group_bys:
        keys.extend(sort_key_arrays(g))
    hp = group_hash(keys, row_valid, salt=g_cap)
    hv = hash_words(keys, g_cap + 0x9E3779B9)

    stride = max(n // 4096, 1)
    cur = hp[::stride]
    tbl = []
    for _ in range(g_cap):
        m = jnp.min(cur)
        tbl.append(m)
        cur = jnp.where(cur == m, I64_MAX, cur)
    overflow = jnp.min(cur) != I64_MAX
    tbl_arr = jnp.stack(tbl)
    n_groups = (tbl_arr != I64_MAX).sum().astype(jnp.int32)

    gid = jnp.sum((hp[:, None] > tbl_arr[None, :]).astype(jnp.int32), axis=1)
    nseg = g_cap + 1
    ctx = DenseCtx(gid=gid, nseg=nseg)

    # exactness check 1: every valid row's hash is a table entry (a group
    # the sample missed would otherwise silently merge into a neighbor slot
    # or vanish in the invalid slot)
    in_tbl = jnp.any(hp[:, None] == tbl_arr[None, :], axis=1)
    overflow = overflow | jnp.any(row_valid & ~in_tbl)
    # exactness check 2: the secondary hash is constant within each slot
    # (different keys, equal primary hash)
    from .seg import _dense_mask

    vm = _dense_mask(ctx) & row_valid[:, None]
    mx = jnp.max(jnp.where(vm, hv[:, None], I64_MIN_), axis=0)
    mn = jnp.min(jnp.where(vm, hv[:, None], I64_MAX), axis=0)
    overflow = overflow | jnp.any((mx != mn) & (mx != I64_MIN_))

    group_rep_full, _ = seg_first_match(ctx, row_valid)
    group_rep = group_rep_full[:g_cap]
    gids = jnp.arange(g_cap, dtype=jnp.int32)
    group_valid = gids < n_groups

    # batch every integer per-group sum into ONE MXU matmul (record pass
    # -> resolve -> replay; see seg.DenseSumBatch)
    from .seg import DenseSumBatch

    ctx.sums = DenseSumBatch(ctx)
    for desc, arg_vals in aggs:
        if _needs_gather_state(desc, arg_vals):
            continue
        fn = _agg_states_merge if merge else _agg_states_raw
        fn(desc, arg_vals, row_valid, ctx)
    ctx.sums.resolve()

    states = []
    for desc, arg_vals in aggs:
        if _needs_gather_state(desc, arg_vals):
            st = _gather_state_sorted(
                desc, arg_vals, row_valid, ctx, jnp.arange(n, dtype=jnp.int32), n, merge
            )
        else:
            fn = _agg_states_merge if merge else _agg_states_raw
            st = fn(desc, arg_vals, row_valid, ctx)
        if isinstance(st, GatherState):
            states.append(GatherState(st.idx[:g_cap], st.has[:g_cap] & group_valid))
            continue
        st = [(v[:g_cap], nl[:g_cap]) for v, nl in st]
        st = [(v, nl | ~group_valid) for v, nl in st]
        states.append(st)

    order = jnp.argsort(jnp.where(group_valid, group_rep, jnp.int32(n)))
    group_rep = group_rep[order]
    out_states: list = []
    for st in states:
        if isinstance(st, GatherState):
            out_states.append(GatherState(st.idx[order], st.has[order]))
        else:
            out_states.append([(v[order], nl[order]) for v, nl in st])
    return GroupAggResult(group_rep, group_valid, jnp.minimum(n_groups, g_cap), overflow, out_states)


def _group_aggregate_stream(group_bys, aggs, row_valid, group_capacity: int, merge: bool, compact: bool = True):
    """StreamAgg kernel (ref: pkg/executor/aggregate/agg_stream_executor.go,
    cophandler's sorted-input aggregation): the input arrives ALREADY sorted
    on the group keys (index order, or below a Sort), so group boundaries
    are plain neighbor compares over the key words — no sort, no hash, no
    collision risk. Rows keep their original order (seg is monotone), so
    the whole segment machinery applies directly; filtered rows stay inside
    their key run and are masked by the states, and key runs whose rows are
    ALL filtered compact away through the first-encounter reorder."""
    n = row_valid.shape[0]
    keys: list[jax.Array] = []
    for g in group_bys:
        keys.extend(sort_key_arrays(g))
    one = jnp.ones(1, bool)
    diff = one
    for k in keys:
        d = jnp.concatenate([one, k[1:] != k[:-1]])
        diff = d if diff is one else (diff | d)
    if diff is one:
        diff = jnp.ones(n, bool)
    seg = jnp.cumsum(diff.astype(jnp.int32)) - 1
    # overflow only when a SURVIVING row lands past the capacity: key runs
    # whose rows are all filtered may overflow the raw run count without
    # affecting any output (ops/joinagg.py feeds build∪probe key runs here,
    # where most runs contribute nothing)
    overflow = jnp.any(row_valid & (seg >= group_capacity))
    nseg = group_capacity + 1
    seg = jnp.minimum(seg, nseg - 1)
    ctx = make_segctx(seg, nseg)
    perm = jnp.arange(n, dtype=jnp.int32)

    group_rep_full, has_rep = _first_match_idx(row_valid, perm, ctx, n)
    group_rep = group_rep_full[:group_capacity]
    has_g = has_rep[:group_capacity]
    n_groups = has_g.sum().astype(jnp.int32)

    states = []
    for desc, arg_vals in aggs:
        if _is_distinct_special(desc, arg_vals, merge):
            # DISTINCT needs the hash machinery's group-id alignment;
            # the planner never sets stream for distinct aggs (guard)
            raise NotImplementedError("DISTINCT aggregates in stream mode")
        if _needs_gather_state(desc, arg_vals):
            st = _gather_state_sorted(desc, arg_vals, row_valid, ctx, perm, n, merge)
        else:
            fn = _agg_states_merge if merge else _agg_states_raw
            st = fn(desc, arg_vals, row_valid, ctx)
        if isinstance(st, GatherState):
            states.append(GatherState(st.idx[:group_capacity], st.has[:group_capacity] & has_g))
            continue
        st = [(v[:group_capacity], nl[:group_capacity]) for v, nl in st]
        st = [(v, nl | ~has_g) for v, nl in st]
        states.append(st)

    if not compact:
        # caller reorders/compacts itself (ops/joinagg.py rides its own
        # original-row argsort) — group_valid is the raw has-flags here
        return GroupAggResult(group_rep, has_g, n_groups, overflow, states)

    # compact: runs with >=1 surviving row first, in first-encounter order
    order = jnp.argsort(jnp.where(has_g, group_rep, jnp.int32(n)))
    group_rep = group_rep[order]
    gids = jnp.arange(group_capacity, dtype=jnp.int32)
    group_valid = gids < n_groups
    out_states: list = []
    for st in states:
        if isinstance(st, GatherState):
            out_states.append(GatherState(st.idx[order], st.has[order]))
        else:
            out_states.append([(v[order], nl[order]) for v, nl in st])
    return GroupAggResult(group_rep, group_valid, n_groups, overflow, out_states)


def group_aggregate(
    group_bys: list[CompVal],
    aggs: list,
    row_valid: jax.Array,
    group_capacity: int,
    merge: bool = False,
    small_groups: int | None = None,
    stream: bool = False,
):
    """Hash-cluster group aggregation.

    aggs: list of (AggDesc, [arg CompVals]). Returns GroupAggResult with one
    extra hidden overflow segment dropped; groups in first-encounter order.
    small_groups: statistics-driven hint (planner NDV product) — when set
    and the agg mix allows it, the sort-free dense kernel runs instead; its
    overflow flag routes the driver back here.
    stream: input is pre-sorted on the group keys (planner-proven): the
    boundary-scan StreamAgg kernel runs — no sort, no hash at all.
    """
    if stream and group_bys and not any(d.distinct for d, _ in aggs):
        return _group_aggregate_stream(group_bys, aggs, row_valid, group_capacity, merge)
    if small_groups and group_bys and small_groups <= 32:
        from .dense_pallas import (
            dense_pallas_eligible,
            group_aggregate_dense_pallas,
            pallas_mode,
        )

        mode = pallas_mode()
        if mode and dense_pallas_eligible(group_bys, aggs, merge):
            return group_aggregate_dense_pallas(
                group_bys, aggs, row_valid, small_groups, mode
            )
    if small_groups and group_bys and _dense_eligible(aggs, merge):
        return _group_aggregate_dense(group_bys, aggs, row_valid, small_groups, merge)
    n = row_valid.shape[0]
    keys: list[jax.Array] = []
    for g in group_bys:
        keys.extend(sort_key_arrays(g))
    # ONE sortable word: salted 62-bit hash, invalid rows pinned to the tail;
    # a second independently-salted hash rides along purely for collision
    # detection (neighbor compare — no gathers)
    hp = group_hash(keys, row_valid, salt=group_capacity)
    hv = hash_words(keys, group_capacity + 0x9E3779B9)

    # payload plan: every array needed after the sort rides the sort itself
    # (variadic operands) — a random [N] gather costs more than an extra
    # sort operand by an order of magnitude on TPU. Null masks bit-pack
    # eight-to-a-byte into shared uint8 operands.
    payload: list = []
    slot_of: dict = {}
    bool_arrs: list = []
    bool_ix: dict = {}

    def carry(arr) -> int:
        key = id(arr)
        if key not in slot_of:
            slot_of[key] = len(payload)
            payload.append(arr)
        return slot_of[key]

    def carry_bool(arr) -> int:
        key = id(arr)
        if key not in bool_ix:
            bool_ix[key] = len(bool_arrs)
            bool_arrs.append(arr)
        return bool_ix[key]

    plans = []  # per agg: "distinct" | list[(vslots, null_bit)] per arg
    for desc, avs in aggs:
        if _is_distinct_special(desc, avs, merge):
            plans.append("distinct")
            continue
        slots = []
        for a in avs:
            if a.value.ndim == 2:
                vslots = [carry(a.value[:, i]) for i in range(a.value.shape[1])]
            else:
                vslots = carry(a.value)
            slots.append((vslots, carry_bool(a.null)))
        plans.append(slots)

    nwords = []
    for w0 in range(0, len(bool_arrs), 8):
        grp = bool_arrs[w0 : w0 + 8]
        word = grp[0].astype(jnp.uint8)
        for k, a in enumerate(grp[1:], start=1):
            word = word | (a.astype(jnp.uint8) << k)
        nwords.append(word)

    iota = jnp.arange(n, dtype=jnp.int32)
    sorted_ops = jax.lax.sort(tuple([hp, iota, hv] + payload + nwords), num_keys=2)
    h_s, perm, hv_s = sorted_ops[0], sorted_ops[1], sorted_ops[2]
    pay_s = list(sorted_ops[3 : 3 + len(payload)])
    nw_s = list(sorted_ops[3 + len(payload) :])
    valid_s = h_s != I64_MAX  # validity is IN the sort word — no gather
    seg, n_groups = segments_from_sorted([h_s], valid_s)
    overflow = n_groups > group_capacity
    nseg = group_capacity + 1
    seg = jnp.minimum(seg, nseg - 1)
    ctx = make_segctx(seg, nseg)

    # exact-grouping check: equal primary hash but different secondary hash
    # anywhere inside a cluster => collision => overflow (salted retry)
    fal = jnp.zeros(1, bool)
    same_prev = jnp.concatenate([fal, h_s[1:] == h_s[:-1]])
    mism = jnp.concatenate([fal, hv_s[1:] != hv_s[:-1]])
    pair_valid = valid_s & jnp.concatenate([fal, valid_s[:-1]])
    overflow = overflow | jnp.any(same_prev & mism & pair_valid)

    # earliest original row per group (deterministic oracle parity)
    group_rep_full, _ = _first_match_idx(valid_s, perm, ctx, n)
    group_rep = group_rep_full[:group_capacity]
    gids = jnp.arange(group_capacity, dtype=jnp.int32)
    group_valid = gids < n_groups

    def resort(a: CompVal, slots) -> CompVal:
        vslots, nbit = slots
        if isinstance(vslots, list):
            v = jnp.stack([pay_s[i] for i in vslots], axis=1)
        else:
            v = pay_s[vslots]
        null = ((nw_s[nbit // 8] >> (nbit % 8)) & 1).astype(bool)
        return CompVal(v, null, a.ft, raw=None)

    # dry pass records every seg_sum request; resolve() batches them into
    # one [A, N] cumsum; the replay pass below gets the real results
    ctx.sums = SumBatch(ctx)
    for (desc, arg_vals), plan in zip(aggs, plans):
        if plan == "distinct" or _needs_gather_state(desc, arg_vals):
            continue
        av_s = [resort(a, sl) for a, sl in zip(arg_vals, plan)]
        fn = _agg_states_merge if merge else _agg_states_raw
        fn(desc, av_s, valid_s, ctx)
    ctx.sums.resolve()

    states = []
    for (desc, arg_vals), plan in zip(aggs, plans):
        if plan == "distinct":
            st, coll_flag = _distinct_states(
                desc, arg_vals, row_valid, hp, nseg, group_capacity
            )
            overflow = overflow | coll_flag
        else:
            av_s = [resort(a, sl) for a, sl in zip(arg_vals, plan)]
            if _needs_gather_state(desc, arg_vals):
                st = _gather_state_sorted(desc, av_s, valid_s, ctx, perm, n, merge)
            else:
                fn = _agg_states_merge if merge else _agg_states_raw
                st = fn(desc, av_s, valid_s, ctx)
        if isinstance(st, GatherState):
            states.append(GatherState(st.idx[:group_capacity], st.has[:group_capacity] & group_valid))
            continue
        st = [(v[:group_capacity], nl[:group_capacity]) for v, nl in st]
        st = [(v, nl | ~group_valid) for v, nl in st]
        states.append(st)
    ctx.sums = None

    # groups come out hash-ordered; reorder by earliest contributing row so
    # the output order matches the oracle's first-encounter insertion order
    order = jnp.argsort(jnp.where(group_valid, group_rep, jnp.int32(n)))
    group_rep = group_rep[order]
    out_states: list = []
    for st in states:
        if isinstance(st, GatherState):
            out_states.append(GatherState(st.idx[order], st.has[order]))
        else:
            out_states.append([(v[order], nl[order]) for v, nl in st])

    return GroupAggResult(group_rep, group_valid, jnp.minimum(n_groups, group_capacity), overflow, out_states,
                          need=n_groups.astype(jnp.int64))


def scalar_aggregate(aggs: list, row_valid: jax.Array, merge: bool = False, salt: int = 1):
    """Aggregation without GROUP BY: always exactly one output row
    (ref: SELECT count(*) over empty set returns 0).

    No sort at all — one segment spanning the batch. States come back
    [1]-shaped; first_row / string min/max come back as a GatherState
    ([1]-shaped idx/has) for the caller to gather. Returns (states,
    overflow) — overflow only from DISTINCT hash collisions, cleared by
    the salted retry."""
    n = row_valid.shape[0]
    ctx = SegCtx(
        seg=jnp.zeros(n, jnp.int32),
        nseg=1,
        starts=jnp.zeros(1, jnp.int32),
        ends=jnp.full(1, n - 1, jnp.int32),
        counts=jnp.full(1, n, jnp.int64),
    )
    perm = jnp.arange(n, dtype=jnp.int32)
    hp = jnp.where(row_valid, jnp.int64(0), I64_MAX)
    overflow = jnp.bool_(False)
    states = []
    for desc, arg_vals in aggs:
        if _is_distinct_special(desc, arg_vals, merge):
            st, coll_flag = _distinct_states(desc, arg_vals, row_valid, hp, 2, salt)
            overflow = overflow | coll_flag
            states.append([(v[:1], nl[:1]) for v, nl in st])
        elif _needs_gather_state(desc, arg_vals):
            st = _gather_state_sorted(desc, arg_vals, row_valid, ctx, perm, n, merge)
            states.append(GatherState(st.idx[:1], st.has[:1]))
        else:
            fn = _agg_states_merge if merge else _agg_states_raw
            states.append(fn(desc, arg_vals, row_valid, ctx))
    return states, overflow
