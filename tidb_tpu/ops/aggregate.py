"""Aggregation kernels (ref: unistore/cophandler/mpp_exec.go:999 aggExec,
pkg/executor/aggregate/agg_hash_executor.go, pkg/executor/aggfuncs).

TPU-native shape: the reference keys a hash table on encoded group datums
and updates per-row (pointer chasing — hostile to the VPU). Here group-by
is hash-cluster based: normalize keys to int64 words (ops/keys.py), mix
them into ONE 63-bit hash word (ops/seg.py), sort by that single word, and
reduce each contiguous hash cluster with scatter-free segment passes
(cumsum / segmented scan + boundary gathers). Hash collisions are detected
exactly (row-vs-segment-head word compare) and surface as the overflow
flag; the retry driver's larger capacity re-salts the hash. Dynamic group
counts live behind a static `group_capacity` plus that flag (SURVEY.md §7
"hard parts": dynamic cardinality).

Two phases mirror the reference's partial/final split
(ref: pkg/expression/aggregation modes):
  raw phase    (Complete/Partial1)  raw rows in
  merge phase  (Partial2/Final)     partial-state columns in, reduced by
                                    state-specific merge (+, +, min, max...)

Partial states (expr/agg.py): count=[n], sum=[s], avg=[n,s], min/max=[v].
The psum across regions of these states is exactly the ICI-mesh merge of the
north star (BASELINE.json): count/sum/avg states add elementwise.

Output groups are ordered by first encounter (earliest contributing input
row), matching the row-at-a-time oracle's insertion order.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..expr.agg import AggDesc
from ..expr.compile import CompVal, _round_div, _scale
from ..types import FieldType, TypeCode
from .keys import segments_from_sorted, sort_key_arrays
from .seg import (
    I64_MAX,
    SegCtx,
    group_hash,
    hash_words,
    make_segctx,
    run_head_pos,
    seg_bitreduce,
    seg_head_pos,
    seg_max,
    seg_min,
    seg_sum,
    sort_by_word,
)

I64_MIN_ = jnp.int64(-0x8000000000000000)


@dataclass
class GroupAggResult:
    """Fixed-capacity aggregation output.

    group_rep: int32 [G] earliest original input-row index per group (gather
    group-by output columns from the original batch with it; earliest matches
    the row-at-a-time oracle's first-encountered semantics).
    states: per agg, either a list of (value[G], null[G]) state/result
    columns or a GatherState (the caller gathers the agg's value column —
    and its raw string bytes — from the original batch).
    """

    group_rep: jax.Array
    group_valid: jax.Array
    n_groups: jax.Array
    overflow: jax.Array
    states: list


@dataclass
class GatherState:
    """Per-group 'fetch this original row' aggregate state.

    Serves first_row (any mode: the earliest original row of the group — in
    merge mode the earliest partial state with has>0) and min/max over
    strings (segmented lexicographic arg-extreme). Gathering from the
    *original* batch lets string aggregates carry their raw bytes, which the
    packed compare words alone cannot (ref: aggfuncs/func_first_row.go,
    func_max_min.go — the reference keeps whole datums in its partial
    results; here the row index plays that role)."""

    idx: jax.Array  # int32 [G] original row index (clipped; dead when ~has)
    has: jax.Array  # bool [G] group produced a state


def _masked(vals, mask, fill):
    return jnp.where(mask, vals, fill)


_VAR_FUNCS = frozenset({"stddev_pop", "stddev_samp", "var_pop", "var_samp"})


def _as_f64(a: CompVal):
    """Value lane as float64 (stddev/var are always DOUBLE in MySQL)."""
    if a.eval_type == "real":
        return a.value
    if a.eval_type == "decimal":
        return a.value.astype(jnp.float64) / float(10 ** max(a.ft.decimal, 0))
    return a.value.astype(jnp.float64)


_BIT_OPS = {
    "bit_and": (jnp.bitwise_and, -1),  # identity all-ones (MySQL empty BIT_AND = 2^64-1)
    "bit_or": (jnp.bitwise_or, 0),
    "bit_xor": (jnp.bitwise_xor, 0),
}


def _agg_states_raw(desc: AggDesc, args: list[CompVal], valid, ctx: SegCtx):
    """Per-group partial states from raw rows."""
    name = desc.name
    nseg = ctx.nseg
    if name == "count":
        mask = valid
        for a in args:
            mask = mask & ~a.null
        return [(seg_sum(ctx, mask.astype(jnp.int64)), jnp.zeros(nseg, bool))]
    a = args[0]
    mask = valid & ~a.null
    cnt = seg_sum(ctx, mask.astype(jnp.int64))
    empty = cnt == 0
    if name in ("sum", "avg"):
        if a.eval_type == "real":
            s = seg_sum(ctx, _masked(a.value, mask, 0.0))
        else:
            s = seg_sum(ctx, _masked(a.value.astype(jnp.int64), mask, jnp.int64(0)))
        if name == "sum":
            return [(s, empty)]
        return [(cnt, jnp.zeros(nseg, bool)), (s, empty)]
    if name in ("min", "max"):
        op = seg_min if name == "min" else seg_max
        if a.eval_type == "real":
            fill = jnp.inf if name == "min" else -jnp.inf
            v = op(ctx, _masked(a.value, mask, fill))
        elif a.value.ndim == 2:
            raise AssertionError("string min/max is routed via GatherState")
        elif a.ft.is_unsigned() and a.eval_type == "int":
            flip = jnp.int64(-0x8000000000000000)
            av = a.value.astype(jnp.int64) ^ flip
            fill = I64_MAX if name == "min" else I64_MIN_
            v = op(ctx, _masked(av, mask, fill)) ^ flip
        else:
            av = a.value.astype(jnp.int64)
            fill = I64_MAX if name == "min" else I64_MIN_
            v = op(ctx, _masked(av, mask, fill))
        return [(v, empty)]
    if name == "first_row":
        raise AssertionError("first_row is routed via GatherState")
    if name in _VAR_FUNCS:
        # moment states [count, sum, sum_sq] — additive, mesh-mergeable
        # (ref: executor/aggfuncs/func_varpop.go partial results)
        v = _as_f64(a)
        s = seg_sum(ctx, _masked(v, mask, 0.0))
        q = seg_sum(ctx, _masked(v * v, mask, 0.0))
        nn = cnt == 0
        return [(cnt, jnp.zeros(nseg, bool)), (s, nn), (q, nn)]
    if name == "group_concat":
        raise NotImplementedError("group_concat on device (root-only, oracle-evaluated)")
    if name in _BIT_OPS:
        red, fill = _BIT_OPS[name]
        v = seg_bitreduce(ctx, red, _masked(a.value.astype(jnp.int64), mask, jnp.int64(fill)), fill)
        # MySQL BIT_* never return NULL: empty set yields the identity
        return [(v, jnp.zeros(nseg, bool))]
    raise NotImplementedError(f"aggregate {name} on device")


def _first_match_idx(mask_s, orig_s, ctx: SegCtx, n):
    """Per-segment earliest ORIGINAL row index among mask rows.

    mask_s/orig_s are in sorted order (orig_s = perm, the original index of
    each sorted position). Returns (idx[nseg] clipped, has[nseg])."""
    fi = seg_min(ctx, jnp.where(mask_s, orig_s.astype(jnp.int32), jnp.int32(n)))
    has = fi < n
    return jnp.clip(fi, 0, n - 1), has


def _arg_extreme_mask(words_s, cand, ctx: SegCtx, maximize: bool):
    """Narrow `cand` (sorted order) to rows holding the per-segment
    lexicographic extreme of `words_s` ([n, K] int64, most significant word
    first — the packed-string key layout). Word-by-word radix arg-extreme:
    K static segment reduces, no data-dependent shapes."""
    for k in range(words_s.shape[1]):
        w = words_s[:, k]
        if maximize:
            best = seg_max(ctx, jnp.where(cand, w, I64_MIN_))
        else:
            best = seg_min(ctx, jnp.where(cand, w, I64_MAX))
        cand = cand & (w == best[ctx.seg])
    return cand


def _distinct_states(desc: AggDesc, args: list, row_valid, hp, nseg: int, salt: int):
    """COUNT/SUM/AVG(DISTINCT ...) states via a secondary sort by
    (group hash, arg hash): the first row of each distinct (group, args)
    combination contributes exactly once (ref: aggfuncs distinct set
    semantics, executor/aggfuncs/func_count_distinct.go — the sort replaces
    the hash set).

    Group numbering matches the main sort's: both cluster by the same group
    hash word, so segment ids depend only on hash ranks. Returns
    (states, collision_flag) — arg-hash collisions are detected by the
    run-head word compare and clear on the salted retry."""
    argkeys: list = []
    amask = row_valid
    for a in args:
        amask = amask & ~a.null
        argkeys.extend(sort_key_arrays(a))
    ah = hash_words(argkeys, salt + 1)
    n = row_valid.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    hp2, ah2, perm2 = jax.lax.sort((hp, ah, iota), num_keys=2)
    valid2 = row_valid[perm2]
    seg2, _ = segments_from_sorted([hp2], valid2)
    seg2 = jnp.minimum(seg2, nseg - 1)
    ctx2 = make_segctx(seg2, nseg)
    one = jnp.ones(1, bool)
    diff = jnp.concatenate([one, (hp2[1:] != hp2[:-1]) | (ah2[1:] != ah2[:-1])])
    head = run_head_pos(diff)
    amask2 = amask[perm2]
    coll = jnp.zeros(n, bool)
    for k in argkeys:
        k2 = k[perm2]
        coll = coll | (k2 != k2[head])
    collision = jnp.any(coll & valid2 & amask2)
    uniq = diff & valid2 & amask2
    cnt = seg_sum(ctx2, uniq.astype(jnp.int64))
    if desc.name == "count":
        return [(cnt, jnp.zeros(nseg, bool))], collision
    a0 = args[0]
    empty = cnt == 0
    if desc.name in _VAR_FUNCS:
        v2 = _as_f64(a0)[perm2]
        s = seg_sum(ctx2, jnp.where(uniq, v2, 0.0))
        q = seg_sum(ctx2, jnp.where(uniq, v2 * v2, 0.0))
        return [(cnt, jnp.zeros(nseg, bool)), (s, empty), (q, empty)], collision
    a2 = a0.value[perm2]
    if a0.eval_type == "real":
        s = seg_sum(ctx2, jnp.where(uniq, a2, 0.0))
    else:
        s = seg_sum(ctx2, jnp.where(uniq, a2.astype(jnp.int64), jnp.int64(0)))
    if desc.name == "sum":
        return [(s, empty)], collision
    return [(cnt, jnp.zeros(nseg, bool)), (s, empty)], collision


def _agg_states_merge(desc: AggDesc, args: list[CompVal], valid, ctx: SegCtx):
    """Merge partial-state columns (Partial2/Final): args are state cols."""
    name = desc.name
    nseg = ctx.nseg
    if name == "count":
        a = args[0]
        return [(seg_sum(ctx, _masked(a.value, valid, 0)), jnp.zeros(nseg, bool))]
    if name in ("sum", "avg"):
        out = []
        for a in args:  # count then sum for avg; sum only for sum
            mask = valid & ~a.null
            present = seg_sum(ctx, mask.astype(jnp.int64)) > 0
            if a.eval_type == "real":
                s = seg_sum(ctx, _masked(a.value, mask, 0.0))
            else:
                s = seg_sum(ctx, _masked(a.value.astype(jnp.int64), mask, jnp.int64(0)))
            out.append((s, ~present))
        if name == "avg":
            # count state never null
            out[0] = (out[0][0], jnp.zeros(nseg, bool))
        return out
    if name in ("min", "max"):
        return _agg_states_raw(desc, args, valid, ctx)
    if name in _VAR_FUNCS:
        # additive moment states: sum each of [count, sum, sum_sq]
        cnt_a, s_a, q_a = args
        mask = valid & ~s_a.null
        cnt = seg_sum(ctx, _masked(cnt_a.value.astype(jnp.int64), valid, jnp.int64(0)))
        s = seg_sum(ctx, _masked(s_a.value, mask, 0.0))
        q = seg_sum(ctx, _masked(q_a.value, mask, 0.0))
        nn = cnt == 0
        return [(cnt, jnp.zeros(nseg, bool)), (s, nn), (q, nn)]
    if name == "first_row":
        raise AssertionError("first_row merge is routed via GatherState")
    if name in _BIT_OPS:
        # reduce of reduces — same segmented bitwise kernel over state cols
        return _agg_states_raw(desc, args, valid, ctx)
    raise NotImplementedError(f"merge of {name} on device")


def finalize_agg(desc: AggDesc, states: list, group_valid) -> tuple:
    """State columns -> final (value, null) result column."""
    name = desc.name
    if name == "avg":
        cnt, (s, snull) = states[0][0], states[1]
        if desc.ft.eval_type() == "real":
            out = s / jnp.where(cnt == 0, 1.0, cnt).astype(jnp.float64)
            return out, snull | (cnt == 0)
        # decimal: scale(avg) = scale(sum) + 4 (div frac incr)
        sum_scale = _scale(desc.partial_fts()[1])
        tgt = _scale(desc.ft)
        num = s * jnp.int64(10 ** (tgt - sum_scale))
        out = _round_div(num, jnp.where(cnt == 0, jnp.int64(1), cnt))
        return out, snull | (cnt == 0)
    if name == "first_row":
        has = states[0][0]
        v, nl = states[1]
        return v, nl | (has == 0)
    if name in _VAR_FUNCS:
        cnt = states[0][0]
        s, q = states[1][0], states[2][0]
        n = jnp.maximum(cnt, 1).astype(jnp.float64)
        mean = s / n
        if name.endswith("samp"):
            var = jnp.maximum(q - n * mean * mean, 0.0) / jnp.maximum(n - 1.0, 1.0)
            null = cnt < 2  # sample stats undefined for n < 2 (MySQL NULL)
        else:
            var = jnp.maximum(q / n - mean * mean, 0.0)
            null = cnt == 0
        out = jnp.sqrt(var) if name.startswith("stddev") else var
        return out, null
    # identity finalize
    v, nl = states[0][0], states[0][1]
    return v, nl


def _gather_or_distinct_state(desc, arg_vals, row_valid, merge, hp, ctx: SegCtx, perm, n, salt):
    """(GatherState | distinct states | None, collision_flag | None) for the
    aggs that need special routing.

    first_row (all modes) and string min/max resolve to a per-group original
    row index; DISTINCT count/sum/avg resolve via a secondary hash sort."""
    name = desc.name
    orig_s = perm.astype(jnp.int32)
    if name == "first_row":
        mask = row_valid
        if merge:
            # merge input states are [has, value]: earliest state with has>0
            mask = mask & (arg_vals[0].value > 0)
        idx, has = _first_match_idx(mask[perm], orig_s, ctx, n)
        return GatherState(idx, has), None
    if name in ("min", "max") and arg_vals and arg_vals[-1].value.ndim == 2:
        a = arg_vals[-1]  # merge-mode state col == value col, same kernel
        mask = (row_valid & ~a.null)[perm]
        cand = _arg_extreme_mask(a.value[perm, :], mask, ctx, name == "max")
        idx, has = _first_match_idx(cand, orig_s, ctx, n)
        return GatherState(idx, has), None
    if desc.distinct and name in ({"count", "sum", "avg"} | _VAR_FUNCS) and arg_vals:
        if merge:
            raise NotImplementedError(
                "DISTINCT aggregates are not decomposable into mergeable partials; "
                "plan them in Complete mode (ref: AggregationPushDownSolver skips distinct)"
            )
        nseg = max(ctx.nseg, 2)  # scalar path: one group + the invalid slot
        return _distinct_states(desc, arg_vals, row_valid, hp, nseg, salt)
    return None, None


def group_aggregate(
    group_bys: list[CompVal],
    aggs: list,
    row_valid: jax.Array,
    group_capacity: int,
    merge: bool = False,
):
    """Hash-cluster group aggregation.

    aggs: list of (AggDesc, [arg CompVals]). Returns GroupAggResult with one
    extra hidden overflow segment dropped; groups in first-encounter order.
    """
    n = row_valid.shape[0]
    keys: list[jax.Array] = []
    for g in group_bys:
        keys.extend(sort_key_arrays(g))
    # ONE sortable word: 63-bit salted hash, invalid rows pinned to the tail
    hp = group_hash(keys, row_valid, salt=group_capacity)
    h_s, perm = sort_by_word(hp)
    valid_s = row_valid[perm]
    seg, n_groups = segments_from_sorted([h_s], valid_s)
    overflow = n_groups > group_capacity
    nseg = group_capacity + 1
    seg = jnp.minimum(seg, nseg - 1)
    ctx = make_segctx(seg, nseg)

    # exact-grouping check: a cluster mixing two distinct keys (hash
    # collision, or the clamped overflow cluster) trips the overflow flag;
    # the retry's larger capacity re-salts the hash and clears it
    head = seg_head_pos(ctx)
    coll = jnp.zeros(n, bool)
    for k in keys:
        k_s = k[perm]
        coll = coll | (k_s != k_s[head])
    overflow = overflow | jnp.any(coll & valid_s)

    # earliest original row per group (deterministic oracle parity)
    group_rep_full, _ = _first_match_idx(valid_s, perm, ctx, n)
    group_rep = group_rep_full[:group_capacity]
    gids = jnp.arange(group_capacity, dtype=jnp.int32)
    group_valid = gids < n_groups

    states = []
    for desc, arg_vals in aggs:
        st, coll_flag = _gather_or_distinct_state(
            desc, arg_vals, row_valid, merge, hp, ctx, perm, n, group_capacity
        )
        if coll_flag is not None:
            overflow = overflow | coll_flag
        if isinstance(st, GatherState):
            states.append(GatherState(st.idx[:group_capacity], st.has[:group_capacity] & group_valid))
            continue
        if st is None:
            av_s = [CompVal(a.value[perm] if a.value.ndim == 1 else a.value[perm, :], a.null[perm], a.ft, raw=None) for a in arg_vals]
            fn = _agg_states_merge if merge else _agg_states_raw
            st = fn(desc, av_s, valid_s, ctx)
        st = [(v[:group_capacity], nl[:group_capacity]) for v, nl in st]
        st = [(v, nl | ~group_valid) for v, nl in st]
        states.append(st)

    # groups come out hash-ordered; reorder by earliest contributing row so
    # the output order matches the oracle's first-encounter insertion order
    order = jnp.argsort(jnp.where(group_valid, group_rep, jnp.int32(n)))
    group_rep = group_rep[order]
    out_states: list = []
    for st in states:
        if isinstance(st, GatherState):
            out_states.append(GatherState(st.idx[order], st.has[order]))
        else:
            out_states.append([(v[order], nl[order]) for v, nl in st])

    return GroupAggResult(group_rep, group_valid, jnp.minimum(n_groups, group_capacity), overflow, out_states)


def scalar_aggregate(aggs: list, row_valid: jax.Array, merge: bool = False):
    """Aggregation without GROUP BY: always exactly one output row
    (ref: SELECT count(*) over empty set returns 0).

    States come back [1]-shaped; first_row / string min/max come back as a
    GatherState ([1]-shaped idx/has) for the caller to gather. Returns
    (states, overflow) — overflow only from DISTINCT hash collisions,
    cleared by the salted retry."""
    n = row_valid.shape[0]
    ctx = SegCtx(
        seg=jnp.zeros(n, jnp.int32),
        nseg=1,
        starts=jnp.zeros(1, jnp.int32),
        ends=jnp.full(1, n - 1, jnp.int32),
        counts=jnp.full(1, n, jnp.int64),
    )
    perm = jnp.arange(n, dtype=jnp.int32)
    hp = jnp.where(row_valid, jnp.int64(0), I64_MAX)
    overflow = jnp.bool_(False)
    states = []
    for desc, arg_vals in aggs:
        st, coll_flag = _gather_or_distinct_state(
            desc, arg_vals, row_valid, merge, hp, ctx, perm, n, 1
        )
        if coll_flag is not None:
            overflow = overflow | coll_flag
        if isinstance(st, GatherState):
            states.append(GatherState(st.idx[:1], st.has[:1]))
        elif st is not None:  # distinct states came back [2]-shaped
            states.append([(v[:1], nl[:1]) for v, nl in st])
        else:
            fn = _agg_states_merge if merge else _agg_states_raw
            states.append(fn(desc, arg_vals, row_valid, ctx))
    return states, overflow
