"""Aggregation kernels (ref: unistore/cophandler/mpp_exec.go:999 aggExec,
pkg/executor/aggregate/agg_hash_executor.go, pkg/executor/aggfuncs).

TPU-native shape: instead of a hash table (pointer chasing — hostile to the
VPU), group-by is sort-based: normalize keys to int64 arrays, lexsort, detect
segment boundaries, then scatter-reduce into a fixed `group_capacity` table
with `jax.ops.segment_*`. Dynamic group counts live behind a static capacity
plus an overflow flag (SURVEY.md §7 "hard parts": dynamic cardinality).

Two phases mirror the reference's partial/final split
(ref: pkg/expression/aggregation modes):
  raw phase    (Complete/Partial1)  raw rows in
  merge phase  (Partial2/Final)     partial-state columns in, reduced by
                                    state-specific merge (+, +, min, max...)

Partial states (expr/agg.py): count=[n], sum=[s], avg=[n,s], min/max=[v].
The psum across regions of these states is exactly the ICI-mesh merge of the
north star (BASELINE.json): count/sum/avg states add elementwise.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..expr.agg import AggDesc
from ..expr.compile import CompVal, _round_div, _scale
from ..types import FieldType, TypeCode
from .keys import lexsort, segments_from_sorted, sort_key_arrays

I64_MAX = jnp.int64(0x7FFFFFFFFFFFFFFF)
I64_MIN_ = jnp.int64(-0x8000000000000000)


@dataclass
class GroupAggResult:
    """Fixed-capacity aggregation output.

    group_rep: int32 [G] earliest original input-row index per group (gather
    group-by output columns from the original batch with it; earliest matches
    the row-at-a-time oracle's first-encountered semantics).
    states: per agg, either a list of (value[G], null[G]) state/result
    columns or a GatherState (the caller gathers the agg's value column —
    and its raw string bytes — from the original batch).
    """

    group_rep: jax.Array
    group_valid: jax.Array
    n_groups: jax.Array
    overflow: jax.Array
    states: list


@dataclass
class GatherState:
    """Per-group 'fetch this original row' aggregate state.

    Serves first_row (any mode: the earliest original row of the group — in
    merge mode the earliest partial state with has>0) and min/max over
    strings (segmented lexicographic arg-extreme). Gathering from the
    *original* batch lets string aggregates carry their raw bytes, which the
    packed compare words alone cannot (ref: aggfuncs/func_first_row.go,
    func_max_min.go — the reference keeps whole datums in its partial
    results; here the row index plays that role)."""

    idx: jax.Array  # int32 [G] original row index (clipped; dead when ~has)
    has: jax.Array  # bool [G] group produced a state


def _seg_sum(vals, seg, n, dtype=None):
    """Segment sum tuned for TPU: a single segment is a plain reduction
    (segment_* lowers to scatter, which serializes on TPU), and the general
    case promises sorted ids — every caller sorts rows by group key first,
    and XLA's sorted-scatter path is far cheaper than the generic one."""
    v = vals if dtype is None else vals.astype(dtype)
    if n == 1:
        return jnp.sum(v, axis=0, keepdims=True)
    return jax.ops.segment_sum(v, seg, num_segments=n, indices_are_sorted=True)


def _seg_min(vals, seg, n):
    if n == 1:
        return jnp.min(vals, axis=0, keepdims=True)
    return jax.ops.segment_min(vals, seg, num_segments=n, indices_are_sorted=True)


def _seg_max(vals, seg, n):
    if n == 1:
        return jnp.max(vals, axis=0, keepdims=True)
    return jax.ops.segment_max(vals, seg, num_segments=n, indices_are_sorted=True)


def _masked(vals, mask, fill):
    return jnp.where(mask, vals, fill)


_VAR_FUNCS = frozenset({"stddev_pop", "stddev_samp", "var_pop", "var_samp"})


def _as_f64(a: CompVal):
    """Value lane as float64 (stddev/var are always DOUBLE in MySQL)."""
    if a.eval_type == "real":
        return a.value
    if a.eval_type == "decimal":
        return a.value.astype(jnp.float64) / float(10 ** max(a.ft.decimal, 0))
    return a.value.astype(jnp.float64)


_BIT_OPS = {
    "bit_and": (jnp.bitwise_and, -1),  # identity all-ones (MySQL empty BIT_AND = 2^64-1)
    "bit_or": (jnp.bitwise_or, 0),
    "bit_xor": (jnp.bitwise_xor, 0),
}


def _seg_bitreduce(red, vals, seg, nseg, fill):
    """Segmented bitwise reduce via associative scan (rows sorted by seg —
    group_aggregate sorts, scalar_aggregate has one segment). There is no
    jax.ops.segment_{and,or,xor}; the standard segmented-scan combine is
    associative over sorted segment ids, then the last row of each segment
    holds the segment's reduction."""
    n = vals.shape[0]

    def combine(c1, c2):
        v1, s1 = c1
        v2, s2 = c2
        return jnp.where(s1 == s2, red(v1, v2), v2), s2

    sv, _ = jax.lax.associative_scan(combine, (vals, seg))
    pos = jnp.arange(n, dtype=jnp.int32)
    last = _seg_max(pos, seg, nseg)
    out = sv[jnp.clip(last, 0, n - 1)]
    cnt = _seg_sum(jnp.ones_like(seg), seg, nseg)
    return jnp.where(cnt > 0, out, jnp.int64(fill))


def _agg_states_raw(desc: AggDesc, args: list[CompVal], valid, seg, nseg):
    """Per-group partial states from raw rows."""
    name = desc.name
    if name == "count":
        mask = valid
        for a in args:
            mask = mask & ~a.null
        return [(_seg_sum(mask.astype(jnp.int64), seg, nseg), jnp.zeros(nseg, bool))]
    a = args[0]
    mask = valid & ~a.null
    cnt = _seg_sum(mask.astype(jnp.int64), seg, nseg)
    empty = cnt == 0
    if name in ("sum", "avg"):
        if a.eval_type == "real":
            s = _seg_sum(_masked(a.value, mask, 0.0), seg, nseg)
        else:
            s = _seg_sum(_masked(a.value.astype(jnp.int64), mask, jnp.int64(0)), seg, nseg)
        if name == "sum":
            return [(s, empty)]
        return [(cnt, jnp.zeros(nseg, bool)), (s, empty)]
    if name in ("min", "max"):
        op = _seg_min if name == "min" else _seg_max
        if a.eval_type == "real":
            fill = jnp.inf if name == "min" else -jnp.inf
            v = op(_masked(a.value, mask, fill), seg, nseg)
        elif a.value.ndim == 2:
            raise AssertionError("string min/max is routed via GatherState")
        elif a.ft.is_unsigned() and a.eval_type == "int":
            flip = jnp.int64(-0x8000000000000000)
            av = a.value.astype(jnp.int64) ^ flip
            fill = I64_MAX if name == "min" else I64_MIN_
            v = op(_masked(av, mask, fill), seg, nseg) ^ flip
        else:
            av = a.value.astype(jnp.int64)
            fill = I64_MAX if name == "min" else I64_MIN_
            v = op(_masked(av, mask, fill), seg, nseg)
        return [(v, empty)]
    if name == "first_row":
        raise AssertionError("first_row is routed via GatherState")
    if name in _VAR_FUNCS:
        # moment states [count, sum, sum_sq] — additive, mesh-mergeable
        # (ref: executor/aggfuncs/func_varpop.go partial results)
        v = _as_f64(a)
        cnt = _seg_sum(mask.astype(jnp.int64), seg, nseg)
        s = _seg_sum(_masked(v, mask, 0.0), seg, nseg)
        q = _seg_sum(_masked(v * v, mask, 0.0), seg, nseg)
        nn = cnt == 0
        return [(cnt, jnp.zeros(nseg, bool)), (s, nn), (q, nn)]
    if name == "group_concat":
        raise NotImplementedError("group_concat on device (root-only, oracle-evaluated)")
    if name in _BIT_OPS:
        red, fill = _BIT_OPS[name]
        v = _seg_bitreduce(red, _masked(a.value.astype(jnp.int64), mask, jnp.int64(fill)), seg, nseg, fill)
        # MySQL BIT_* never return NULL: empty set yields the identity
        return [(v, jnp.zeros(nseg, bool))]
    raise NotImplementedError(f"aggregate {name} on device")


def _first_match_idx(mask_s, orig_s, seg, nseg, n):
    """Per-segment earliest ORIGINAL row index among mask rows.

    mask_s/orig_s are in sorted order (orig_s = perm, the original index of
    each sorted position). Returns (idx[nseg] clipped, has[nseg])."""
    fi = _seg_min(jnp.where(mask_s, orig_s, jnp.int32(n)), seg, nseg)
    has = fi < n
    return jnp.clip(fi, 0, n - 1), has


def _arg_extreme_mask(words_s, cand, seg, nseg, maximize: bool):
    """Narrow `cand` (sorted order) to rows holding the per-segment
    lexicographic extreme of `words_s` ([n, K] int64, most significant word
    first — the packed-string key layout). Word-by-word radix arg-extreme:
    K static segment reduces, no data-dependent shapes."""
    for k in range(words_s.shape[1]):
        w = words_s[:, k]
        if maximize:
            best = _seg_max(jnp.where(cand, w, I64_MIN_), seg, nseg)
        else:
            best = _seg_min(jnp.where(cand, w, I64_MAX), seg, nseg)
        cand = cand & (w == best[seg])
    return cand


def _distinct_states(desc: AggDesc, args: list, row_valid, gkeys: list, invalid_first, nseg):
    """COUNT/SUM/AVG(DISTINCT ...) states via a secondary sort by
    (validity, group keys, arg keys): the first row of each distinct
    (group, args) combination contributes exactly once (ref: aggfuncs
    distinct set semantics, executor/aggfuncs/func_count_distinct.go —
    the sort replaces the hash set).

    Group numbering matches the main sort's: both order valid-first by the
    same group-key words, so segment ids depend only on distinct key ranks.
    With no group keys (scalar agg) callers pass nseg=2 (slot 1 = invalid).
    """
    argkeys: list = []
    amask = row_valid
    for a in args:
        amask = amask & ~a.null
        argkeys.extend(sort_key_arrays(a))
    perm2 = lexsort([invalid_first] + gkeys + argkeys)
    valid2 = row_valid[perm2]
    gkeys2 = [k[perm2] for k in gkeys]
    if gkeys:
        seg2, _ = segments_from_sorted(gkeys2, valid2)
        seg2 = jnp.minimum(seg2, nseg - 1)
    else:
        seg2 = jnp.where(valid2, 0, 1).astype(jnp.int32)
    allkeys2 = gkeys2 + [k[perm2] for k in argkeys]
    diff = jnp.zeros(valid2.shape[0], bool)
    for k in allkeys2:
        diff = diff | jnp.concatenate([jnp.ones(1, bool), k[1:] != k[:-1]])
    uniq = diff & valid2 & amask[perm2]
    cnt = _seg_sum(uniq.astype(jnp.int64), seg2, nseg)
    if desc.name == "count":
        return [(cnt, jnp.zeros(nseg, bool))]
    a0 = args[0]
    empty = cnt == 0
    if desc.name in _VAR_FUNCS:
        v2 = _as_f64(a0)[perm2]
        s = _seg_sum(jnp.where(uniq, v2, 0.0), seg2, nseg)
        q = _seg_sum(jnp.where(uniq, v2 * v2, 0.0), seg2, nseg)
        return [(cnt, jnp.zeros(nseg, bool)), (s, empty), (q, empty)]
    a2 = a0.value[perm2]
    if a0.eval_type == "real":
        s = _seg_sum(jnp.where(uniq, a2, 0.0), seg2, nseg)
    else:
        s = _seg_sum(jnp.where(uniq, a2.astype(jnp.int64), jnp.int64(0)), seg2, nseg)
    if desc.name == "sum":
        return [(s, empty)]
    return [(cnt, jnp.zeros(nseg, bool)), (s, empty)]


def _agg_states_merge(desc: AggDesc, args: list[CompVal], valid, seg, nseg):
    """Merge partial-state columns (Partial2/Final): args are state cols."""
    name = desc.name
    if name == "count":
        a = args[0]
        return [(_seg_sum(_masked(a.value, valid, 0), seg, nseg), jnp.zeros(nseg, bool))]
    if name in ("sum", "avg"):
        out = []
        for a in args:  # count then sum for avg; sum only for sum
            mask = valid & ~a.null
            present = _seg_sum(mask.astype(jnp.int64), seg, nseg) > 0
            if a.eval_type == "real":
                s = _seg_sum(_masked(a.value, mask, 0.0), seg, nseg)
            else:
                s = _seg_sum(_masked(a.value.astype(jnp.int64), mask, jnp.int64(0)), seg, nseg)
            out.append((s, ~present))
        if name == "avg":
            # count state never null
            out[0] = (out[0][0], jnp.zeros(nseg, bool))
        return out
    if name in ("min", "max"):
        return _agg_states_raw(desc, args, valid, seg, nseg)
    if name in _VAR_FUNCS:
        # additive moment states: sum each of [count, sum, sum_sq]
        cnt_a, s_a, q_a = args
        mask = valid & ~s_a.null
        cnt = _seg_sum(_masked(cnt_a.value.astype(jnp.int64), valid, jnp.int64(0)), seg, nseg)
        s = _seg_sum(_masked(s_a.value, mask, 0.0), seg, nseg)
        q = _seg_sum(_masked(q_a.value, mask, 0.0), seg, nseg)
        nn = cnt == 0
        return [(cnt, jnp.zeros(nseg, bool)), (s, nn), (q, nn)]
    if name == "first_row":
        raise AssertionError("first_row merge is routed via GatherState")
    if name in _BIT_OPS:
        # reduce of reduces — same segmented bitwise kernel over state cols
        return _agg_states_raw(desc, args, valid, seg, nseg)
    raise NotImplementedError(f"merge of {name} on device")


def finalize_agg(desc: AggDesc, states: list, group_valid) -> tuple:
    """State columns -> final (value, null) result column."""
    name = desc.name
    if name == "avg":
        cnt, (s, snull) = states[0][0], states[1]
        if desc.ft.eval_type() == "real":
            out = s / jnp.where(cnt == 0, 1.0, cnt).astype(jnp.float64)
            return out, snull | (cnt == 0)
        # decimal: scale(avg) = scale(sum) + 4 (div frac incr)
        sum_scale = _scale(desc.partial_fts()[1])
        tgt = _scale(desc.ft)
        num = s * jnp.int64(10 ** (tgt - sum_scale))
        out = _round_div(num, jnp.where(cnt == 0, jnp.int64(1), cnt))
        return out, snull | (cnt == 0)
    if name == "first_row":
        has = states[0][0]
        v, nl = states[1]
        return v, nl | (has == 0)
    if name in _VAR_FUNCS:
        cnt = states[0][0]
        s, q = states[1][0], states[2][0]
        n = jnp.maximum(cnt, 1).astype(jnp.float64)
        mean = s / n
        if name.endswith("samp"):
            var = jnp.maximum(q - n * mean * mean, 0.0) / jnp.maximum(n - 1.0, 1.0)
            null = cnt < 2  # sample stats undefined for n < 2 (MySQL NULL)
        else:
            var = jnp.maximum(q / n - mean * mean, 0.0)
            null = cnt == 0
        out = jnp.sqrt(var) if name.startswith("stddev") else var
        return out, null
    # identity finalize
    v, nl = states[0][0], states[0][1]
    return v, nl


def _gather_or_distinct_state(desc, arg_vals, row_valid, merge, gkeys, invalid_first, nseg, seg, perm, n):
    """GatherState / distinct states for the aggs that need them, else None.

    first_row (all modes) and string min/max resolve to a per-group original
    row index; DISTINCT count/sum/avg resolve via a secondary sort."""
    name = desc.name
    orig_s = perm.astype(jnp.int32)
    if name == "first_row":
        mask = row_valid
        if merge:
            # merge input states are [has, value]: earliest state with has>0
            mask = mask & (arg_vals[0].value > 0)
        idx, has = _first_match_idx(mask[perm], orig_s, seg, nseg, n)
        return GatherState(idx, has)
    if name in ("min", "max") and arg_vals and arg_vals[-1].value.ndim == 2:
        a = arg_vals[-1]  # merge-mode state col == value col, same kernel
        mask = (row_valid & ~a.null)[perm]
        cand = _arg_extreme_mask(a.value[perm, :], mask, seg, nseg, name == "max")
        idx, has = _first_match_idx(cand, orig_s, seg, nseg, n)
        return GatherState(idx, has)
    if desc.distinct and name in ({"count", "sum", "avg"} | _VAR_FUNCS) and arg_vals:
        if merge:
            raise NotImplementedError(
                "DISTINCT aggregates are not decomposable into mergeable partials; "
                "plan them in Complete mode (ref: AggregationPushDownSolver skips distinct)"
            )
        return _distinct_states(desc, arg_vals, row_valid, gkeys, invalid_first, nseg)
    return None


def group_aggregate(
    group_bys: list[CompVal],
    aggs: list,
    row_valid: jax.Array,
    group_capacity: int,
    merge: bool = False,
):
    """Sort-based group aggregation.

    aggs: list of (AggDesc, [arg CompVals]). Returns GroupAggResult with one
    extra hidden overflow segment dropped.
    """
    n = row_valid.shape[0]
    keys: list[jax.Array] = []
    for g in group_bys:
        keys.extend(sort_key_arrays(g))
    invalid_first_key = jnp.where(row_valid, jnp.int64(0), jnp.int64(1))
    perm = lexsort([invalid_first_key] + keys)
    valid_s = row_valid[perm]
    keys_s = [k[perm] for k in keys]
    seg, n_groups = segments_from_sorted(keys_s, valid_s)
    overflow = n_groups > group_capacity
    nseg = group_capacity + 1
    seg = jnp.minimum(seg, nseg - 1)

    # earliest original row per group (deterministic oracle parity)
    group_rep_full, _ = _first_match_idx(valid_s, perm.astype(jnp.int32), seg, nseg, n)
    group_rep = group_rep_full[:group_capacity]
    gids = jnp.arange(group_capacity, dtype=jnp.int32)
    group_valid = gids < n_groups

    states = []
    for desc, arg_vals in aggs:
        st = _gather_or_distinct_state(
            desc, arg_vals, row_valid, merge, keys, invalid_first_key, nseg, seg, perm, n
        )
        if isinstance(st, GatherState):
            states.append(GatherState(st.idx[:group_capacity], st.has[:group_capacity] & group_valid))
            continue
        if st is None:
            av_s = [CompVal(a.value[perm] if a.value.ndim == 1 else a.value[perm, :], a.null[perm], a.ft, raw=None) for a in arg_vals]
            fn = _agg_states_merge if merge else _agg_states_raw
            st = fn(desc, av_s, valid_s, seg, nseg)
        st = [(v[:group_capacity], nl[:group_capacity]) for v, nl in st]
        st = [(v, nl | ~group_valid) for v, nl in st]
        states.append(st)

    return GroupAggResult(group_rep, group_valid, jnp.minimum(n_groups, group_capacity), overflow, states)


def scalar_aggregate(aggs: list, row_valid: jax.Array, merge: bool = False):
    """Aggregation without GROUP BY: always exactly one output row
    (ref: SELECT count(*) over empty set returns 0).

    States come back [1]-shaped; first_row / string min/max come back as a
    GatherState ([1]-shaped idx/has) for the caller to gather."""
    n = row_valid.shape[0]
    seg = jnp.zeros(n, jnp.int32)
    perm = jnp.arange(n, dtype=jnp.int32)
    invalid_first = jnp.where(row_valid, jnp.int64(0), jnp.int64(1))
    states = []
    for desc, arg_vals in aggs:
        st = _gather_or_distinct_state(
            desc, arg_vals, row_valid, merge, [], invalid_first, 2, seg, perm, n
        )
        if isinstance(st, GatherState):
            states.append(GatherState(st.idx[:1], st.has[:1]))
        elif st is not None:  # distinct states came back [2]-shaped
            states.append([(v[:1], nl[:1]) for v, nl in st])
        else:
            fn = _agg_states_merge if merge else _agg_states_raw
            states.append(fn(desc, arg_vals, row_valid, seg, 1))
    return states
