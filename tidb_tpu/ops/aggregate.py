"""Aggregation kernels (ref: unistore/cophandler/mpp_exec.go:999 aggExec,
pkg/executor/aggregate/agg_hash_executor.go, pkg/executor/aggfuncs).

TPU-native shape: instead of a hash table (pointer chasing — hostile to the
VPU), group-by is sort-based: normalize keys to int64 arrays, lexsort, detect
segment boundaries, then scatter-reduce into a fixed `group_capacity` table
with `jax.ops.segment_*`. Dynamic group counts live behind a static capacity
plus an overflow flag (SURVEY.md §7 "hard parts": dynamic cardinality).

Two phases mirror the reference's partial/final split
(ref: pkg/expression/aggregation modes):
  raw phase    (Complete/Partial1)  raw rows in
  merge phase  (Partial2/Final)     partial-state columns in, reduced by
                                    state-specific merge (+, +, min, max...)

Partial states (expr/agg.py): count=[n], sum=[s], avg=[n,s], min/max=[v].
The psum across regions of these states is exactly the ICI-mesh merge of the
north star (BASELINE.json): count/sum/avg states add elementwise.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..expr.agg import AggDesc
from ..expr.compile import CompVal, _round_div, _scale
from ..types import FieldType, TypeCode
from .keys import lexsort, segments_from_sorted, sort_key_arrays

I64_MAX = jnp.int64(0x7FFFFFFFFFFFFFFF)
I64_MIN_ = jnp.int64(-0x8000000000000000)


@dataclass
class GroupAggResult:
    """Fixed-capacity aggregation output.

    group_rep: int32 [G] representative input-row index per group (gather
    group-by output columns from the original batch with it).
    states: per agg, list of (value[G], null[G]) state/result columns.
    """

    group_rep: jax.Array
    group_valid: jax.Array
    n_groups: jax.Array
    overflow: jax.Array
    states: list


def _seg_sum(vals, seg, n, dtype=None):
    return jax.ops.segment_sum(vals if dtype is None else vals.astype(dtype), seg, num_segments=n)


def _masked(vals, mask, fill):
    return jnp.where(mask, vals, fill)


_BIT_OPS = {
    "bit_and": (jnp.bitwise_and, -1),  # identity all-ones (MySQL empty BIT_AND = 2^64-1)
    "bit_or": (jnp.bitwise_or, 0),
    "bit_xor": (jnp.bitwise_xor, 0),
}


def _seg_bitreduce(red, vals, seg, nseg, fill):
    """Segmented bitwise reduce via associative scan (rows sorted by seg —
    group_aggregate sorts, scalar_aggregate has one segment). There is no
    jax.ops.segment_{and,or,xor}; the standard segmented-scan combine is
    associative over sorted segment ids, then the last row of each segment
    holds the segment's reduction."""
    n = vals.shape[0]

    def combine(c1, c2):
        v1, s1 = c1
        v2, s2 = c2
        return jnp.where(s1 == s2, red(v1, v2), v2), s2

    sv, _ = jax.lax.associative_scan(combine, (vals, seg))
    pos = jnp.arange(n, dtype=jnp.int32)
    last = jax.ops.segment_max(pos, seg, num_segments=nseg)
    out = sv[jnp.clip(last, 0, n - 1)]
    cnt = jax.ops.segment_sum(jnp.ones_like(seg), seg, num_segments=nseg)
    return jnp.where(cnt > 0, out, jnp.int64(fill))


def _agg_states_raw(desc: AggDesc, args: list[CompVal], valid, seg, nseg):
    """Per-group partial states from raw rows."""
    name = desc.name
    if name == "count":
        mask = valid
        for a in args:
            mask = mask & ~a.null
        return [(_seg_sum(mask.astype(jnp.int64), seg, nseg), jnp.zeros(nseg, bool))]
    a = args[0]
    mask = valid & ~a.null
    cnt = _seg_sum(mask.astype(jnp.int64), seg, nseg)
    empty = cnt == 0
    if name in ("sum", "avg"):
        if a.eval_type == "real":
            s = _seg_sum(_masked(a.value, mask, 0.0), seg, nseg)
        else:
            s = _seg_sum(_masked(a.value.astype(jnp.int64), mask, jnp.int64(0)), seg, nseg)
        if name == "sum":
            return [(s, empty)]
        return [(cnt, jnp.zeros(nseg, bool)), (s, empty)]
    if name in ("min", "max"):
        op = jax.ops.segment_min if name == "min" else jax.ops.segment_max
        if a.eval_type == "real":
            fill = jnp.inf if name == "min" else -jnp.inf
            v = op(_masked(a.value, mask, fill), seg, num_segments=nseg)
        elif a.value.ndim == 2:
            # strings: packed words are sign-adjusted but per-word reduction
            # is not lexicographic; handled via a per-segment arg-extreme on
            # the first word only when strings fit one word (W+1 == 2).
            raise NotImplementedError("min/max over strings on device TODO")
        elif a.ft.is_unsigned() and a.eval_type == "int":
            flip = jnp.int64(-0x8000000000000000)
            av = a.value.astype(jnp.int64) ^ flip
            fill = I64_MAX if name == "min" else I64_MIN_
            v = op(_masked(av, mask, fill), seg, num_segments=nseg) ^ flip
        else:
            av = a.value.astype(jnp.int64)
            fill = I64_MAX if name == "min" else I64_MIN_
            v = op(_masked(av, mask, fill), seg, num_segments=nseg)
        return [(v, empty)]
    if name == "first_row":
        return _first_row_state(a, valid, seg, nseg)
    if name in _BIT_OPS:
        red, fill = _BIT_OPS[name]
        v = _seg_bitreduce(red, _masked(a.value.astype(jnp.int64), mask, jnp.int64(fill)), seg, nseg, fill)
        # MySQL BIT_* never return NULL: empty set yields the identity
        return [(v, jnp.zeros(nseg, bool))]
    raise NotImplementedError(f"aggregate {name} on device")


def _first_row_state(a: CompVal, inseg, seg, nseg):
    """first_row partial state: [has, value]. `has` = segment saw >=1 row;
    the value is the literal first in-segment row's (value, null) — NULL
    values are kept, matching the reference's first_row which takes the
    first row verbatim (ref: aggfuncs/func_first_row.go). `has` lets the
    cross-region merge skip empty/filtered-out regions without conflating
    them with a legitimately-NULL first value."""
    if a.value.ndim == 2:
        # grouped first_row over strings is served by the rep-row gather
        # in exec/builder.py; this state path has no raw bytes to carry
        raise NotImplementedError("first_row over string needs rep-row gather")
    n = seg.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    sentinel = jnp.int32(2**31 - 1)
    first = jax.ops.segment_min(jnp.where(inseg, pos, sentinel), seg, num_segments=nseg)
    has = first < n
    first_c = jnp.clip(first, 0, n - 1)
    val = jnp.where(has, a.value[first_c], jnp.zeros((), a.value.dtype))
    null = jnp.where(has, a.null[first_c], True)
    return [(has.astype(jnp.int64), jnp.zeros(nseg, bool)), (val, null)]


def _agg_states_merge(desc: AggDesc, args: list[CompVal], valid, seg, nseg):
    """Merge partial-state columns (Partial2/Final): args are state cols."""
    name = desc.name
    if name == "count":
        a = args[0]
        return [(_seg_sum(_masked(a.value, valid, 0), seg, nseg), jnp.zeros(nseg, bool))]
    if name in ("sum", "avg"):
        out = []
        for a in args:  # count then sum for avg; sum only for sum
            mask = valid & ~a.null
            present = _seg_sum(mask.astype(jnp.int64), seg, nseg) > 0
            if a.eval_type == "real":
                s = _seg_sum(_masked(a.value, mask, 0.0), seg, nseg)
            else:
                s = _seg_sum(_masked(a.value.astype(jnp.int64), mask, jnp.int64(0)), seg, nseg)
            out.append((s, ~present))
        if name == "avg":
            # count state never null
            out[0] = (out[0][0], jnp.zeros(nseg, bool))
        return out
    if name in ("min", "max"):
        return _agg_states_raw(desc, args, valid, seg, nseg)
    if name == "first_row":
        # merge phase: states are [has, value]; take the first state whose
        # region saw rows (has>0), keeping that state's value/null verbatim
        has, val = args[0], args[1]
        return _first_row_state(val, valid & (has.value > 0), seg, nseg)
    if name in _BIT_OPS:
        # reduce of reduces — same segmented bitwise kernel over state cols
        return _agg_states_raw(desc, args, valid, seg, nseg)
    raise NotImplementedError(f"merge of {name} on device")


def finalize_agg(desc: AggDesc, states: list, group_valid) -> tuple:
    """State columns -> final (value, null) result column."""
    name = desc.name
    if name == "avg":
        cnt, (s, snull) = states[0][0], states[1]
        if desc.ft.eval_type() == "real":
            out = s / jnp.where(cnt == 0, 1.0, cnt).astype(jnp.float64)
            return out, snull | (cnt == 0)
        # decimal: scale(avg) = scale(sum) + 4 (div frac incr)
        sum_scale = _scale(desc.partial_fts()[1])
        tgt = _scale(desc.ft)
        num = s * jnp.int64(10 ** (tgt - sum_scale))
        out = _round_div(num, jnp.where(cnt == 0, jnp.int64(1), cnt))
        return out, snull | (cnt == 0)
    if name == "first_row":
        has = states[0][0]
        v, nl = states[1]
        return v, nl | (has == 0)
    # identity finalize
    v, nl = states[0][0], states[0][1]
    return v, nl


def group_aggregate(
    group_bys: list[CompVal],
    aggs: list,
    row_valid: jax.Array,
    group_capacity: int,
    merge: bool = False,
):
    """Sort-based group aggregation.

    aggs: list of (AggDesc, [arg CompVals]). Returns GroupAggResult with one
    extra hidden overflow segment dropped.
    """
    n = row_valid.shape[0]
    keys: list[jax.Array] = []
    for g in group_bys:
        keys.extend(sort_key_arrays(g))
    invalid_first_key = jnp.where(row_valid, jnp.int64(0), jnp.int64(1))
    perm = lexsort([invalid_first_key] + keys)
    valid_s = row_valid[perm]
    keys_s = [k[perm] for k in keys]
    seg, n_groups = segments_from_sorted(keys_s, valid_s)
    overflow = n_groups > group_capacity
    nseg = group_capacity + 1
    seg = jnp.minimum(seg, nseg - 1)

    # representative original row per group
    pos = jnp.arange(n, dtype=jnp.int32)
    first_pos = jax.ops.segment_min(jnp.where(valid_s, pos, jnp.int32(n)), seg, num_segments=nseg)
    first_pos = jnp.clip(first_pos, 0, n - 1)
    group_rep = perm[first_pos][:group_capacity].astype(jnp.int32)
    gids = jnp.arange(group_capacity, dtype=jnp.int32)
    group_valid = gids < n_groups

    states = []
    for desc, arg_vals in aggs:
        av_s = [CompVal(a.value[perm] if a.value.ndim == 1 else a.value[perm, :], a.null[perm], a.ft, raw=None) for a in arg_vals]
        fn = _agg_states_merge if merge else _agg_states_raw
        st = fn(desc, av_s, valid_s, seg, nseg)
        st = [(v[:group_capacity], nl[:group_capacity]) for v, nl in st]
        st = [(v, nl | ~group_valid) for v, nl in st]
        states.append(st)

    return GroupAggResult(group_rep, group_valid, jnp.minimum(n_groups, group_capacity), overflow, states)


def scalar_aggregate(aggs: list, row_valid: jax.Array, merge: bool = False):
    """Aggregation without GROUP BY: always exactly one output row
    (ref: SELECT count(*) over empty set returns 0)."""
    n = row_valid.shape[0]
    seg = jnp.zeros(n, jnp.int32)
    fn = _agg_states_merge if merge else _agg_states_raw
    states = []
    for desc, arg_vals in aggs:
        st = fn(desc, arg_vals, row_valid, seg, 1)
        states.append(st)
    return states
