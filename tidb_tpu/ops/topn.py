"""TopN kernel (ref: unistore/cophandler/mpp_exec.go:526 topNExec,
pkg/executor/sortexec/topn.go:38).

The reference keeps a heap over evaluated sort keys. A full lexsort of the
batch is correct but wastes ~40x (sorting N rows to keep k=100), and on TPU
even `lax.top_k` lowers to a sort. TPU shape — no large sort at all:

  1. fold (row validity, first-key null flag) into one word s0; strided-
     sample S pairs (s0, w1) and sort just the SAMPLE (tiny);
  2. pick the j-th sample pair as a threshold, j sized so the expected
     candidate count lands in [k, CAP];
  3. candidates = rows lexicographically <= threshold on (s0, w1). VERIFY:
     if count >= min(k, n_valid) the candidate set provably contains the
     true top k (any non-candidate is beaten by >= k candidates); if the
     count is also <= CAP the fast path is EXACT;
  4. compact the candidate positions with cumsum + searchsorted (CAP
     queries — no scatter, no sort), then a CAP-sized stable lexsort over
     ALL key words breaks the remaining ties.

If verification fails (tie-heavy first word, adversarial distribution, or
fewer valid rows than the sample can see), the overflow flag fires and the
retry driver recompiles with full_sort=True — the exact full lexsort, same
stable result, just slower. Compiling the full sort INSIDE a lax.cond would
pay its (size-proportional) compile cost on every TopN plan, so the slow
variant is a separate cached program. Large k (>2048) goes straight to the
full sort (TopN at that size is a sort anyway)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..expr.compile import CompVal
from .keys import lexsort, sort_key_arrays
from .seg import I64_MAX

FAST_K_LIMIT = 2048  # beyond this, full sort is the right kernel
SAMPLE = 16384  # threshold sample size


def _pow2(x: int) -> int:
    c = 1
    while c < x:
        c *= 2
    return c


def topn(by: list, row_valid, k: int, full_sort: bool = False):
    """by: list of (CompVal, desc: bool). Returns (row_indices[k],
    out_valid[k], overflow).

    Invalid rows sort last; out_valid marks slots < min(k, n_valid_rows).
    Ties keep input order (stable), like the reference's heap-pop order.
    On overflow=True the indices are unusable; the caller recompiles with
    full_sort=True (exact, no overflow possible)."""
    keys, invalid_last = _order_keys(by, row_valid)
    n = row_valid.shape[0]
    k = min(k, n)
    n_valid = row_valid.sum()
    out_valid = jnp.arange(k) < n_valid

    def full_sort_idx():
        return _stable_sort_idx(keys, invalid_last)[:k]

    stride = max(1, n // SAMPLE)
    s_count = n // stride  # sampled pairs
    # expected candidates per sample rank is n/s_count; margin past the
    # k-quantile scales with the Poisson deviation of the sample count so
    # candidate underflow (a spurious full-sort recompile) stays a tail
    # event for every k, not just small ones
    base = (k * s_count) // n
    j = min(base + 4 + 2 * int(base ** 0.5), s_count - 1)
    # cap needs slack ABOVE the expected candidate count (~(j+1) sample
    # gaps) or benign uniform data overflows into the full-sort recompile
    expected = (j + 1) * max(1, n // s_count)
    cap = _pow2(max(2 * k + 2 * expected, 256))
    if full_sort or k < 1 or k > FAST_K_LIMIT or cap >= n or len(keys) < 2:
        return full_sort_idx(), out_valid, jnp.bool_(False)

    # s0: first key's null-flag word with invalid rows pinned to +max —
    # <=3 distinct values, so the real selection happens on w1
    s0 = jnp.where(row_valid, keys[0], I64_MAX)
    w1 = keys[1]
    w1f = jnp.issubdtype(w1.dtype, jnp.floating)
    w1_top = jnp.asarray(jnp.inf if w1f else jnp.iinfo(w1.dtype).max, w1.dtype)
    w1m = jnp.where(row_valid, w1, w1_top)

    s0_s, w1_s = jax.lax.sort((s0[::stride][:s_count], w1m[::stride][:s_count]), num_keys=2)
    ts0, tw1 = s0_s[j], w1_s[j]
    cand = row_valid & ((s0 < ts0) | ((s0 == ts0) & (w1m <= tw1)))
    cnt = cand.sum().astype(jnp.int32)
    overflow = (cnt < jnp.minimum(jnp.int32(k), n_valid.astype(jnp.int32))) | (cnt > cap)

    # compact first `cap` candidate positions (ascending by construction —
    # stability preserved)
    cpos = _first_set_positions(cand, cap)
    cvalid = jnp.arange(cap, dtype=jnp.int32) < cnt
    cpos_c = jnp.clip(cpos, 0, n - 1)
    small_keys = [jnp.where(cvalid, jnp.int64(0), jnp.int64(1))] + [kk[cpos_c] for kk in keys]
    perm_s = lexsort(small_keys, extra_key=cpos_c.astype(jnp.int64))
    fast_idx = cpos_c[perm_s[:k]].astype(jnp.int32)
    return fast_idx, out_valid, overflow


def _first_set_positions(cand, cap: int, block: int = 256):
    """Positions of the first `cap` set bits of cand [N], ascending.

    Two-level: per-block counts locate each rank's block (binary search
    over a tiny VMEM-resident haystack), then a [cap, block] contiguous
    row-gather + intra-block cumsum finds the bit. ~2x the flat
    cumsum+searchsorted formulation on TPU (the flat variant's binary
    search runs ~log2(N) serial gather rounds over an HBM haystack;
    measured 1.8ms vs 0.9ms at N=4M, cap=4096)."""
    n = cand.shape[0]
    if n % block or n <= block:
        c = jnp.cumsum(cand.astype(jnp.int32))
        return jnp.searchsorted(c, jnp.arange(1, cap + 1, dtype=jnp.int32), side="left").astype(jnp.int32)
    nb = n // block
    blocks = cand.reshape(nb, block)
    cum_b = jnp.cumsum(blocks.sum(axis=1, dtype=jnp.int32))
    ranks = jnp.arange(1, cap + 1, dtype=jnp.int32)
    blk = jnp.minimum(
        jnp.searchsorted(cum_b, ranks, side="left").astype(jnp.int32), nb - 1
    )
    rows = blocks[blk]  # [cap, block] contiguous row gather
    prev = jnp.where(blk > 0, cum_b[jnp.maximum(blk - 1, 0)], 0)
    need = (ranks - prev).astype(jnp.int32)
    ccum = jnp.cumsum(rows.astype(jnp.int32), axis=1)
    intra = jnp.argmax((ccum >= need[:, None]) & rows, axis=1).astype(jnp.int32)
    return blk * block + intra


def _order_keys(by: list, row_valid):
    """ORDER BY -> (normalized key words, invalid-last word) — the ONE
    place the ordering/validity key construction lives (topn and sort_all
    share it)."""
    keys = []
    for v, desc in by:
        keys.extend(sort_key_arrays(v, desc=desc))
    invalid_last = jnp.where(row_valid, jnp.int64(0), jnp.int64(1))
    return keys, invalid_last


def _stable_sort_idx(keys: list, invalid_last):
    """Stable full-sort permutation with invalid rows compacted to the
    tail (topn's exact fallback and the Sort executor both use it)."""
    return lexsort([invalid_last] + keys).astype(jnp.int32)


def sort_all(by: list, row_valid):
    """Full stable sort of the batch (the Sort executor's kernel): every
    valid row, in ORDER BY order, invalid rows compacted to the tail.
    Returns (row_indices[n], out_valid[n])."""
    keys, invalid_last = _order_keys(by, row_valid)
    n = row_valid.shape[0]
    idx = _stable_sort_idx(keys, invalid_last)
    out_valid = jnp.arange(n) < row_valid.sum()
    return idx, out_valid
