"""TopN kernel (ref: unistore/cophandler/mpp_exec.go:526 topNExec,
pkg/executor/sortexec/topn.go:38).

The reference keeps a heap over evaluated sort keys. A full lexsort of the
batch is correct but wastes ~40x: sorting N rows to keep k=100. TPU shape:
`lax.top_k` threshold refinement —

  1. fold (row validity, first-key null flag) into one word s0, find the
     k-th smallest s0 (top_k over the bit-inverted word);
  2. among rows at that s0, find the k-th smallest first value word w1;
  3. candidates = rows strictly better than (s0kth) plus rows at s0kth with
     w1 <= w1kth — a guaranteed superset of the true top k;
  4. compact the first CAP candidate positions with one more top_k, then a
     CAP-sized stable lexsort over ALL key words breaks the remaining ties.

If candidates overflow CAP (massive ties on the first value word), the
overflow flag fires and the retry driver recompiles with full_sort=True —
the exact full lexsort, same stable result, just slower. Compiling the full
sort INSIDE a lax.cond would pay its (size-proportional) compile cost on
every TopN plan, so the slow variant is a separate cached program. Large k
(>2048) goes straight to the full sort (TopN at that size is a sort
anyway)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..expr.compile import CompVal
from .keys import lexsort, sort_key_arrays

I64_MAX = jnp.int64(0x7FFFFFFFFFFFFFFF)

FAST_K_LIMIT = 2048  # beyond this, full sort is the right kernel
CAND_FACTOR = 4  # candidate capacity = next pow2 of CAND_FACTOR*k


def _pow2(x: int) -> int:
    c = 1
    while c < x:
        c *= 2
    return c


def _kth_smallest(x, mask, k: int):
    """k-th smallest value of x over mask rows (dtype max if fewer)."""
    if jnp.issubdtype(x.dtype, jnp.floating):
        v = jnp.where(mask, x, jnp.inf)
        return -jax.lax.top_k(-v, k)[0][k - 1]
    v = jnp.where(mask, x, jnp.asarray(jnp.iinfo(x.dtype).max, x.dtype))
    return ~jax.lax.top_k(~v, k)[0][k - 1]


def topn(by: list, row_valid, k: int, full_sort: bool = False):
    """by: list of (CompVal, desc: bool). Returns (row_indices[k],
    out_valid[k], overflow).

    Invalid rows sort last; out_valid marks slots < min(k, n_valid_rows).
    Ties keep input order (stable), like the reference's heap-pop order.
    On overflow=True the indices are unusable; the caller recompiles with
    full_sort=True (exact, no overflow possible)."""
    keys = []
    for v, desc in by:
        keys.extend(sort_key_arrays(v, desc=desc))
    n = row_valid.shape[0]
    invalid_last = jnp.where(row_valid, jnp.int64(0), jnp.int64(1))
    k = min(k, n)
    n_valid = row_valid.sum()
    out_valid = jnp.arange(k) < n_valid

    def full_sort_idx():
        perm = lexsort([invalid_last] + keys)
        return perm[:k].astype(jnp.int32)

    cap = _pow2(CAND_FACTOR * k)
    if full_sort or k < 1 or k > FAST_K_LIMIT or cap >= n or len(keys) < 2:
        return full_sort_idx(), out_valid, jnp.bool_(False)

    # s0: first key's null-flag word with invalid rows pinned to +max —
    # <=3 distinct values, so the real selection happens on w1
    s0 = jnp.where(row_valid, keys[0], I64_MAX)
    w1 = keys[1]
    s0kth = _kth_smallest(s0, row_valid, k)
    at_kth = row_valid & (s0 == s0kth)
    w1kth = _kth_smallest(w1, at_kth, k)
    cand = row_valid & ((s0 < s0kth) | (at_kth & (w1 <= w1kth)))
    cnt = cand.sum()

    # first `cap` candidate positions, ascending (top_k of inverted pos)
    pos = jnp.arange(n, dtype=jnp.int32)
    cpos = ~jax.lax.top_k(~jnp.where(cand, pos, jnp.int32(n)), cap)[0]
    cvalid = cpos < n
    cpos_c = jnp.clip(cpos, 0, n - 1)
    small_keys = [jnp.where(cvalid, jnp.int64(0), jnp.int64(1))] + [kk[cpos_c] for kk in keys]
    perm_s = lexsort(small_keys, extra_key=cpos_c.astype(jnp.int64))
    fast_idx = cpos_c[perm_s[:k]].astype(jnp.int32)
    return fast_idx, out_valid, cnt > cap
