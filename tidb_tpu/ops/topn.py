"""TopN kernel (ref: unistore/cophandler/mpp_exec.go:526 topNExec,
pkg/executor/sortexec/topn.go:38).

The reference keeps a heap over evaluated sort keys; on TPU the batch is
resident, so TopN = normalize keys -> lexsort (stable, so ties keep input
order like the reference's stable heap-pop order) -> take first k row
indices. Single-key numeric cases could use lax.top_k, but full sort keeps
multi-key and NULL ordering uniform and XLA's sort is fast on VPU.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..expr.compile import CompVal
from .keys import lexsort, sort_key_arrays


def topn(by: list, row_valid, k: int):
    """by: list of (CompVal, desc: bool). Returns (row_indices[k], out_valid[k]).

    Invalid rows sort last; out_valid marks slots < min(k, n_valid_rows).
    """
    keys = []
    for v, desc in by:
        keys.extend(sort_key_arrays(v, desc=desc))
    n = row_valid.shape[0]
    invalid_last = jnp.where(row_valid, jnp.int64(0), jnp.int64(1))
    perm = lexsort([invalid_last] + keys)
    k = min(k, n)
    idx = perm[:k]
    n_valid = row_valid.sum()
    out_valid = jnp.arange(k) < n_valid
    return idx.astype(jnp.int32), out_valid
