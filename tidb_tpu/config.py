"""Instance configuration (ref: pkg/config — TOML file + flags, bridged to
sysvars at boot; cmd/tidb-server/main.go:654 setGlobalVars)."""

from __future__ import annotations

import tomllib
from dataclasses import dataclass


@dataclass
class Config:
    # store / execution
    region_split_rows: int = 1 << 20  # rows per region before auto-split
    group_capacity: int = 4096  # initial group table capacity
    join_capacity: int | None = None  # default: probe batch capacity
    distsql_scan_concurrency: int = 4
    paging_size: int | None = None
    # memory
    mem_quota_query: int = 1 << 30
    # observability
    enable_metrics: bool = True
    slow_query_threshold_ms: int = 300

    @classmethod
    def from_toml(cls, path: str) -> "Config":
        with open(path, "rb") as f:
            data = tomllib.load(f)
        return cls.from_dict(data)

    @classmethod
    def from_dict(cls, data: dict) -> "Config":
        known = {f_ for f_ in cls.__dataclass_fields__}
        flat = {}
        for k, v in data.items():
            if isinstance(v, dict):  # one level of TOML tables
                for k2, v2 in v.items():
                    if k2 in known:
                        flat[k2] = v2
            elif k in known:
                flat[k] = v
        return cls(**flat)


DEFAULT = Config()
