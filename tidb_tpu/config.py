"""Instance configuration (ref: pkg/config — TOML file + flags, bridged to
sysvars at boot; cmd/tidb-server/main.go:654 setGlobalVars)."""

from __future__ import annotations

try:
    import tomllib  # 3.11+
except ModuleNotFoundError:  # gated: from_toml degrades, everything else works
    tomllib = None
from dataclasses import dataclass


@dataclass
class Config:
    # store / execution
    region_split_rows: int = 1 << 20  # rows per region before auto-split
    group_capacity: int = 4096  # initial group table capacity
    join_capacity: int | None = None  # default: probe batch capacity
    distsql_scan_concurrency: int = 4
    paging_size: int | None = None
    # memory
    mem_quota_query: int = 1 << 30
    mem_quota_session: int = 0  # 0 = unlimited; parents every query tracker
    # admission control (ISSUE 15; ref: the server-side token limits) —
    # bridged onto the store's AdmissionGate at boot; 0 = unlimited
    admission_max_inflight: int = 0
    admission_session_queue: int = 4
    admission_queue_wait_ms: float = 50.0
    admission_shed_backoff_ms: int = 5
    admission_max_dispatch: int = 0
    # measured-cost admission (ISSUE 17): weigh in-flight statements by
    # their Top SQL cost class — heavy digests saturate (and shed) at a
    # fraction of the budget while point-gets keep their full count
    admission_cost_classed: bool = False
    # cross-session fused execution (ISSUE 19) — bridged onto session
    # sysvars at boot: coalesce concurrent point gets into one batched
    # launch and autocommit writes into group commits
    coalesce_enabled: bool = False
    coalesce_wait_us: int = 300
    coalesce_max_lanes: int = 64
    # observability
    enable_metrics: bool = True
    slow_query_threshold_ms: int = 300
    # placement driver (tidb_tpu/pd; ref: pd ScheduleConfig) — bridged
    # onto the store's PlacementDriver by the session at boot
    pd_tick_interval: float = 10.0
    pd_max_region_size: int = 1 << 22  # bytes; split-checker threshold
    pd_max_region_keys: int = 1 << 16  # keys; split-checker threshold

    @classmethod
    def from_toml(cls, path: str) -> "Config":
        if tomllib is not None:
            with open(path, "rb") as f:
                data = tomllib.load(f)
        else:
            data = _parse_flat_toml(open(path, encoding="utf-8").read())
        return cls.from_dict(data)

    @classmethod
    def from_dict(cls, data: dict) -> "Config":
        known = {f_ for f_ in cls.__dataclass_fields__}
        flat = {}
        for k, v in data.items():
            if isinstance(v, dict):  # one level of TOML tables
                for k2, v2 in v.items():
                    if k2 in known:
                        flat[k2] = v2
            elif k in known:
                flat[k] = v
        return cls(**flat)


def _strip_comment(raw: str) -> str:
    """Drop a trailing # comment, but not a # inside a quoted value."""
    quote = None
    for j, ch in enumerate(raw):
        if quote:
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
        elif ch == "#":
            return raw[:j]
    return raw


def _parse_flat_toml(text: str) -> dict:
    """Pre-3.11 fallback: the [section] / key = scalar subset the config
    files actually use (ints, bools, quoted strings). Not a general parser."""
    data: dict = {}
    cur = data
    for raw in text.splitlines():
        line = _strip_comment(raw).strip()
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            cur = data.setdefault(line[1:-1].strip(), {})
            continue
        if "=" not in line:
            continue
        k, _, v = line.partition("=")
        v = v.strip()
        if v.lower() in ("true", "false"):
            val: object = v.lower() == "true"
        elif (v.startswith('"') and v.endswith('"')) or (v.startswith("'") and v.endswith("'")):
            val = v[1:-1]
        else:
            try:
                val = int(v)
            except ValueError:
                try:
                    val = float(v)
                except ValueError:
                    val = v
        cur[k.strip()] = val
    return data


DEFAULT = Config()
