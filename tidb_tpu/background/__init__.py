"""Background frameworks (ref: pkg/timer, pkg/ttl, pkg/disttask,
pkg/statistics/handle auto-analyze) — the domain's always-on workers,
collapsed to thread-based runtimes over the embedded engine:

  Timer        periodic callbacks with jittered ticks (pkg/timer runtime);
               also drives the placement driver's scheduling tick
               (tidb_tpu/pd PlacementDriver.timer) and GC below
  TTLWorker    scans TTL-attached tables and deletes expired rows in
               bounded batches (pkg/ttl/ttlworker scan+delete workers)
  DistTask     task -> subtask split, N executor workers pulling from a
               queue with states/retry (pkg/disttask/framework scheduler +
               taskexecutor; subtask states proto/subtask.go:102)
  AutoAnalyzer ANALYZE tables whose modify ratio exceeds the threshold
               (statistics/handle auto-analyze loop)
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


class Timer:
    """(ref: pkg/timer/runtime). Fires `fn` every `interval` seconds on a
    daemon thread until stop(); errors are caught and counted, never fatal
    (a background tick must not kill the process)."""

    def __init__(self, name: str, interval: float, fn):
        self.name = name
        self.interval = interval
        self.fn = fn
        self.fire_count = 0
        self.error_count = 0
        self.last_error: str | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, name=f"timer-{self.name}", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.fn()
                self.fire_count += 1
            except Exception as exc:  # noqa: BLE001 — ticks survive errors
                self.error_count += 1
                self.last_error = str(exc)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def fire_once(self):
        """Synchronous tick (tests and manual triggers)."""
        self.fn()
        self.fire_count += 1


# ---------------------------------------------------------------- TTL

@dataclass
class TTLRule:
    table: str
    column: str  # DATETIME column
    expire_after_days: float


class TTLWorker:
    """(ref: pkg/ttl/ttlworker — scan tasks find expired rows, delete
    workers remove them in bounded batches). `now_fn` is injectable so
    tests control the clock."""

    def __init__(self, session, batch: int = 256, now_fn=None):
        self.session = session
        self.rules: list[TTLRule] = []
        self.batch = batch
        self.now_fn = now_fn or (lambda: time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime()))
        self.deleted_total = 0

    def attach(self, table: str, column: str, expire_after_days: float):
        self.session.catalog.table(table).col(column)  # validates
        self.rules.append(TTLRule(table, column, expire_after_days))

    def run_once(self) -> int:
        """One TTL pass over every rule; returns rows deleted."""
        import datetime as dt

        deleted = 0
        now = dt.datetime.strptime(self.now_fn(), "%Y-%m-%d %H:%M:%S")
        for rule in self.rules:
            cutoff = now - dt.timedelta(days=rule.expire_after_days)
            cutoff_s = cutoff.strftime("%Y-%m-%d %H:%M:%S")
            while True:
                res = self.session.execute(
                    f"DELETE FROM {rule.table} WHERE {rule.column} < '{cutoff_s}' LIMIT {self.batch}"
                )
                deleted += res.affected
                if res.affected < self.batch:
                    break
        self.deleted_total += deleted
        return deleted

    def timer(self, interval: float) -> Timer:
        return Timer("ttl", interval, self.run_once)


# ---------------------------------------------------------------- disttask

@dataclass
class Subtask:
    """(ref: disttask/framework/proto/subtask.go:102 states)."""

    subtask_id: int
    payload: object
    state: str = "pending"  # pending -> running -> (succeed | failed)
    result: object = None
    error: str = ""
    attempts: int = 0


@dataclass
class Task:
    """(ref: disttask/framework/proto/task.go:147)."""

    task_id: int
    task_type: str
    state: str = "pending"  # pending -> running -> (succeed | reverted)
    subtasks: list = field(default_factory=list)


class DistTaskScheduler:
    """Split a task into subtasks, run them on N workers, collect results
    (ref: disttask framework scheduler + per-node taskexecutor; a failed
    subtask retries up to `max_retries`, then reverts the whole task —
    framework/scheduler/balancer.go's rebalance collapses to the shared
    queue: an idle worker simply pulls the next subtask)."""

    def __init__(self, n_workers: int = 4, max_retries: int = 2):
        self.n_workers = n_workers
        self.max_retries = max_retries
        self._next_id = 1
        self.history: list[Task] = []

    def run(self, task_type: str, payloads: list, execute_fn) -> Task:
        """execute_fn(payload) -> result; raises to fail the subtask."""
        task = Task(self._next_id, task_type)
        self._next_id += 1
        task.subtasks = [Subtask(i + 1, p) for i, p in enumerate(payloads)]
        self.history.append(task)
        task.state = "running"
        queue = list(task.subtasks)
        qlock = threading.Lock()
        failed = threading.Event()

        def worker():
            while not failed.is_set():
                with qlock:
                    if not queue:
                        return
                    st = queue.pop(0)
                st.state = "running"
                while True:
                    st.attempts += 1
                    try:
                        st.result = execute_fn(st.payload)
                        st.state = "succeed"
                        break
                    except Exception as exc:  # noqa: BLE001
                        st.error = str(exc)
                        if st.attempts > self.max_retries:
                            st.state = "failed"
                            failed.set()
                            return

        threads = [threading.Thread(target=worker, daemon=True) for _ in range(self.n_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        task.state = "reverted" if failed.is_set() else "succeed"
        return task


# ---------------------------------------------------------------- auto-analyze

class AutoAnalyzer:
    """(ref: statistics/handle autoAnalyze loop): tables whose modified-row
    ratio since the last ANALYZE exceeds `ratio` (default matches
    tidb_auto_analyze_ratio 0.5) get re-analyzed."""

    def __init__(self, session, ratio: float = 0.5):
        self.session = session
        self.ratio = ratio
        self.analyzed: list[str] = []

    def run_once(self) -> list:
        ran = []
        cat = self.session.catalog
        for name in cat.tables():
            meta = cat.table(name)
            st = cat.stats.get(meta.table_id)
            if st is None:
                if meta.row_count > 0:
                    self.session.execute(f"ANALYZE TABLE {name}")
                    ran.append(name)
                continue
            base = max(st.row_count, 1)
            drift = abs(meta.row_count - st.row_count) / base
            if drift > self.ratio:
                self.session.execute(f"ANALYZE TABLE {name}")
                ran.append(name)
        self.analyzed.extend(ran)
        return ran

    def timer(self, interval: float) -> Timer:
        return Timer("auto-analyze", interval, self.run_once)


class GCWorker:
    """Safepoint-driven MVCC garbage collection on a timer (ref:
    pkg/store/gcworker/gc_worker.go — leader-elected there, a plain
    periodic worker in one process). Each tick garbage-collects versions
    older than the current TSO, clamped below active transactions by
    TPUStore.run_gc."""

    def __init__(self, store, interval: float = 30.0):
        self.store = store
        self.removed_total = 0
        self.runs = 0

        def tick():
            self.removed_total += self.store.run_gc()
            self.runs += 1

        self.timer = Timer("gc", interval, tick)

    def start(self):
        self.timer.start()
        return self

    def stop(self):
        self.timer.stop()
