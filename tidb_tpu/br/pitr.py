"""Point-in-time recovery (ISSUE 20; ref: br/pkg/stream — log backup as
a persistent change stream — and br/pkg/restore's PiTR path: full
snapshot + log replay to an exact ts).

Log backup
----------
`BACKUP LOG TO 'file://dir'` attaches a RAW changefeed (no mounting —
the sink receives undecoded RawKVEvents, index entries and schema
entries included) whose `LogBackupSink` commits each flush as ONE atomic
segment (cdc/sink.py's SegmentWriter: write-temp + fsync + rename) under
`<dir>/log/`, ending in a resolved mark. `manifest.json` (also written
atomically) chains the segments: each entry carries `base_ts` (the
previous resolved point) and `resolved_ts`, so ANY prefix of verified
segments is a transactionally consistent cut and a missing link is
DETECTABLE, never a silently-short restore. The feed's emitted
checkpoint doubles as a sliding GC service safepoint (the changefeed hub
registers it), so MVCC GC can never collect a version the backup still
has to stream.

Replay-to-ts restore
--------------------
`RESTORE FROM 'file://dir' UNTIL TS = <ts>` picks the newest full backup
at or below <ts> (`<dir>` itself or `<dir>/full/*/`), restores it, then
replays the log segments IN ORDER at their SOURCE commit timestamps —
raw bytes back into the target's KV through `bulk_ingest`, schema
entries as catalog DDL. Every discontinuity is a typed `LogGapError`:
no full backup under <ts>, a segment whose `base_ts` overshoots the
covered point, a missing/corrupt segment file, or a log that ends before
<ts>. A per-segment checkpoint file makes a mid-replay crash
(`restore/replay-crash`) resumable: the re-run skips already-applied
segments (idempotent — replay at fixed source ts makes re-application a
no-op anyway, the checkpoint just makes the resume observable and
cheap). `br/log-gap` drops one manifest link to drill the gap detector.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading

from ..cdc.schema import decode_payload, is_schema_key
from ..cdc.sink import SegmentWriter, Sink


class LogGapError(RuntimeError):
    """The log cannot prove continuous coverage up to the requested ts —
    a restore MUST fail typed rather than return a silently-short
    cluster (ref: BR's PiTR erroring on a checkpoint gap)."""

    def __init__(self, msg: str, covered_ts: int = 0, target_ts: int = 0):
        super().__init__(msg)
        self.covered_ts = covered_ts
        self.target_ts = target_ts


class ReplayInterrupted(RuntimeError):
    """The replay loop died mid-restore (the `restore/replay-crash`
    drill): the per-segment checkpoint survives, and a re-run of the
    same `restore_until` resumes past every already-applied segment."""


def _atomic_json(path: str, obj) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class LogBackupSink(Sink):
    """The log backup's sink: buffers raw KV records, commits each flush
    as one atomic segment + an atomic manifest rewrite. Records at or
    below the manifest checkpoint are dropped on arrival — a redelivered
    batch (sink failure, re-attach) can never duplicate an event in the
    durable log (the manifest IS the dedupe floor)."""

    def __init__(self, directory: str):
        self.directory = directory
        self.writer = SegmentWriter(directory)
        self._mu = threading.Lock()
        self._buf: list = []  # [(ts, record dict)]; guarded_by: _mu
        self._manifest_path = os.path.join(directory, "manifest.json")
        self.manifest = self._load_manifest()  # guarded_by: _mu

    def _load_manifest(self) -> dict:
        if os.path.exists(self._manifest_path):
            try:
                m = json.load(open(self._manifest_path, encoding="utf-8"))
                m.setdefault("start_ts", 0)
                m.setdefault("checkpoint_ts", 0)
                m.setdefault("segments", [])
                return m
            except (ValueError, KeyError):
                pass  # unreadable manifest: start a fresh chain
        return {"start_ts": 0, "checkpoint_ts": 0, "segments": []}

    @property
    def checkpoint_ts(self) -> int:
        with self._mu:
            return self.manifest["checkpoint_ts"]

    def segment_count(self) -> int:
        with self._mu:
            return len(self.manifest["segments"])

    def event_count(self) -> int:
        with self._mu:
            return sum(s["events"] for s in self.manifest["segments"])

    def write(self, events: list) -> None:
        with self._mu:
            floor = self.manifest["checkpoint_ts"]
            for ev in events:
                if ev.commit_ts <= floor:
                    continue  # redelivery below the durable checkpoint
                self._buf.append((ev.commit_ts, {
                    "t": "kv",
                    "k": ev.key.hex(),
                    "v": None if ev.value is None else ev.value.decode("latin1"),
                    "ts": ev.commit_ts,
                }))

    def flush(self, resolved_ts: int) -> None:
        """Commit the buffered window: one atomic segment ending in a
        resolved mark, then the manifest rewrite that links it into the
        chain. The manifest only advances AFTER the segment is durable —
        a crash between the two re-sends the window (the write()-side
        dedupe floor is the OLD checkpoint, so re-buffered events land
        in the next segment exactly once). An empty window advances the
        manifest checkpoint alone — the implicit trailing resolved mark
        a quiet log still extends."""
        from ..util import metrics

        with self._mu:
            if resolved_ts <= self.manifest["checkpoint_ts"]:
                return
            # a failed write_segment DROPS the window (the buffer stays
            # swapped out): the feed re-queues the batch below its held
            # checkpoint and REDELIVERS it through write() — the dedupe
            # floor is still the old checkpoint, so exactly one durable
            # copy ever lands (same contract as FileSink)
            take, self._buf = self._buf, []
            # the chain links segment to segment, NOT to the checkpoint:
            # an empty flush advances the checkpoint without a segment,
            # which PROVES no events landed in between — so the next
            # segment still covers continuously from the last segment's
            # resolved point (the dedupe floor above stays the
            # checkpoint; only the recorded chain base differs)
            segs = self.manifest["segments"]
            base_ts = segs[-1]["resolved_ts"] if segs else 0
            if take:
                take.sort(key=lambda p: p[0])
                lines = [json.dumps(rec) for _ts, rec in take]
                lines.append(json.dumps({"t": "resolved", "ts": resolved_ts}))
                body = "".join(line + "\n" for line in lines).encode()
                fname = self.writer.write_segment(lines)
                self.manifest["segments"].append({
                    "file": fname,
                    "sha256": hashlib.sha256(body).hexdigest(),
                    "base_ts": base_ts,
                    "resolved_ts": resolved_ts,
                    "min_ts": take[0][0],
                    "max_ts": take[-1][0],
                    "events": len(take),
                })
                metrics.LOG_BACKUP_SEGMENTS.inc()
                metrics.LOG_BACKUP_EVENTS.inc(len(take))
            self.manifest["checkpoint_ts"] = resolved_ts
            _atomic_json(self._manifest_path, self.manifest)

    def describe(self) -> str:
        return f"log-backup://{self.directory}"


class LogBackup:
    """One attached log backup: the destination, its raw changefeed and
    its sink (registered in `store.log_backups`, surfaced by SHOW BACKUP
    LOGS and refreshed by the pd.pitr tick)."""

    def __init__(self, uri: str, directory: str, feed_name: str,
                 sink: LogBackupSink, start_ts: int):
        self.uri = uri
        self.directory = directory
        self.feed_name = feed_name
        self.sink = sink
        self.start_ts = start_ts


def _log_dir(uri: str) -> str:
    """`file://<dir>` or a bare path (the plain BACKUP/RESTORE SQL takes
    bare paths; the uri form matches the changefeed sink scheme)."""
    scheme, sep, rest = uri.partition("://")
    if not sep:
        return uri
    if scheme.lower() != "file" or not rest:
        raise ValueError(f"log backup destination must be file://<dir>, got {uri!r}")
    return rest


def start_log_backup(store, catalog, uri: str) -> LogBackup:
    """Attach a durable log backup at `uri` (idempotent re-attach: an
    existing `<dir>/log/manifest.json` resumes the chain from its
    checkpoint — the raw feed's initial incremental scan backfills
    (checkpoint, now] and the sink's dedupe floor drops the overlap)."""
    root = _log_dir(uri)
    if uri in store.log_backups:
        raise ValueError(f"log backup to {uri!r} already running")
    sink = LogBackupSink(os.path.join(root, "log"))
    start_ts = sink.checkpoint_ts
    name = f"log-backup:{hashlib.sha256(root.encode()).hexdigest()[:8]}"
    store.cdc.create(name, sink, catalog, table_ids=None,
                     start_ts=start_ts, raw=True)
    lb = LogBackup(uri, root, name, sink, start_ts)
    store.log_backups[uri] = lb
    return lb


def stop_log_backup(store, uri: str) -> None:
    lb = store.log_backups.pop(uri, None)
    if lb is None:
        raise ValueError(f"no log backup to {uri!r}")
    store.cdc.drop(lb.feed_name)


def log_backup_views(store) -> list:
    """One row per attached log backup (SHOW BACKUP LOGS)."""
    from ..cdc import ChangefeedError

    out = []
    for uri, lb in sorted(store.log_backups.items()):
        try:
            state = store.cdc.get(lb.feed_name).view(store)["state"]
        except ChangefeedError:
            state = "removed"
        ckpt = lb.sink.checkpoint_ts
        out.append({
            "destination": uri,
            "changefeed": lb.feed_name,
            "state": state,
            "start_ts": lb.sink.manifest.get("start_ts", 0),
            "checkpoint_ts": ckpt,
            "resolved_lag": max(store.kv.max_committed() - ckpt, 0),
            "segments": lb.sink.segment_count(),
            "events": lb.sink.event_count(),
        })
    return out


def pitr_tick(store) -> None:
    """The `pd.pitr` phase body: refresh the log-backup freshness gauges
    and trim the schema journal below the floor every live feed has
    passed (a feed only ever injects (checkpoint, cand], and feeds born
    later snapshot the live catalog, so nothing can still need the
    trimmed window)."""
    from ..util import metrics

    backups = getattr(store, "log_backups", None)
    hub = getattr(store, "cdc", None)
    if backups is None or hub is None:
        return  # a bare store without the CDC/PITR surfaces
    top = store.kv.max_committed()
    for lb in list(backups.values()):
        ckpt = lb.sink.checkpoint_ts
        metrics.LOG_BACKUP_CHECKPOINT_TS.labels(lb.feed_name).set(ckpt)
        metrics.LOG_BACKUP_LAG.labels(lb.feed_name).set(max(top - ckpt, 0))
    feeds = hub.feeds()
    if feeds:
        floor = min(f.view(store)["checkpoint_ts"] for f in feeds)
        store.schema_journal.trim(floor)


# --------------------------------------------------------------- restore

def _full_backup_candidates(root: str) -> list:
    """(snapshot_ts, dir) of every full backup under the PITR root:
    `<root>` itself and `<root>/full/<anything>/`."""
    dirs = [root]
    full = os.path.join(root, "full")
    if os.path.isdir(full):
        dirs += [os.path.join(full, d) for d in sorted(os.listdir(full))]
    out = []
    for d in dirs:
        mpath = os.path.join(d, "manifest.json")
        if not os.path.exists(mpath):
            continue
        try:
            m = json.load(open(mpath, encoding="utf-8"))
            out.append((int(m["snapshot_ts"]), d))
        except (ValueError, KeyError):
            continue  # not a full-backup manifest (e.g. the log's own)
    return out


def _apply_schema_record(catalog, payload: dict) -> bool:
    """One replayed schema entry onto the target catalog (idempotent by
    schema version; the table is matched by its IMMUTABLE id — the full
    restore recreated it with original ids)."""
    from ..sql.catalog import ColumnMeta
    from ..tools.br import _datum_from_dict, _ft_from_dict

    meta = None
    for name in catalog.tables():
        m = catalog.table(name)
        if m.table_id == payload["table_id"]:
            meta = m
            break
    if meta is None or meta.schema_version >= payload["schema_version"]:
        return False
    meta.columns = [
        ColumnMeta(c["name"], c["col_id"], _ft_from_dict(c["ft"]),
                   origin_default=_datum_from_dict(c.get("origin_default")))
        for c in payload["columns"]
    ]
    if payload.get("handle_col"):
        meta.handle_col = payload["handle_col"]
    meta.next_col_id = max(meta.next_col_id, payload.get("next_col_id", 0))
    meta.schema_version = payload["schema_version"]
    catalog.version += 1
    return True


def _ckpt_path(root: str, until_ts: int) -> str:
    return os.path.join(root, f"restore-ckpt-{until_ts}.json")


def restore_until(store, catalog, uri: str, until_ts: int) -> dict:
    """PITR restore: newest full backup at or below `until_ts`, then log
    replay to exactly `until_ts` at source commit timestamps. Resumable
    and idempotent after a mid-replay crash (per-segment checkpoint);
    every coverage break is a typed LogGapError."""
    from ..util import failpoint, metrics
    from ..tools import br as full_br

    root = _log_dir(uri)
    log_dir = os.path.join(root, "log")
    manifest_path = os.path.join(log_dir, "manifest.json")

    candidates = [(ts, d) for ts, d in _full_backup_candidates(root)
                  if ts <= until_ts]
    if not candidates:
        metrics.PITR_LOG_GAPS.inc()
        raise LogGapError(
            f"no full backup at or below ts {until_ts} under {root!r}",
            covered_ts=0, target_ts=until_ts)
    full_ts, full_dir = max(candidates)

    ckpt_path = _ckpt_path(root, until_ts)
    ckpt = {"full_done": False, "replayed": [], "covered_ts": full_ts}
    resumed = False
    if os.path.exists(ckpt_path):
        try:
            ckpt = json.load(open(ckpt_path, encoding="utf-8"))
            resumed = True
            metrics.PITR_REPLAY_RESUMES.inc()
        except (ValueError, KeyError):
            pass  # torn checkpoint: restart from the full backup

    if not ckpt.get("full_done"):
        full_br.restore(store, catalog, full_dir)
        ckpt["full_done"] = True
        _atomic_json(ckpt_path, ckpt)

    segments = []
    log_checkpoint = full_ts
    if os.path.exists(manifest_path):
        log_manifest = json.load(open(manifest_path, encoding="utf-8"))
        segments = list(log_manifest.get("segments", []))
        log_checkpoint = max(log_checkpoint, log_manifest.get("checkpoint_ts", 0))
    if failpoint.eval("br/log-gap") and len(segments) > 1:
        # chaos drill: drop one mid-chain link — the base_ts/covered
        # check below must refuse, typed, never restore short
        segments.pop(len(segments) // 2)

    covered = ckpt.get("covered_ts", full_ts)
    replayed = set(ckpt.get("replayed", []))
    events_applied = 0
    segments_replayed = 0
    for seg in segments:
        if seg["resolved_ts"] <= covered and seg["file"] in replayed:
            continue
        if seg["resolved_ts"] <= full_ts:
            # wholly below the full snapshot: the snapshot already holds
            # every effect; the chain stays continuous through it
            covered = max(covered, seg["resolved_ts"])
            continue
        if covered >= until_ts:
            break  # target reached: later segments are beyond the cut
        if seg["base_ts"] > covered:
            metrics.PITR_LOG_GAPS.inc()
            raise LogGapError(
                f"log gap: segment {seg['file']} starts at base_ts "
                f"{seg['base_ts']} but coverage ends at {covered}",
                covered_ts=covered, target_ts=until_ts)
        fpath = os.path.join(log_dir, seg["file"])
        if not os.path.exists(fpath):
            metrics.PITR_LOG_GAPS.inc()
            raise LogGapError(
                f"log gap: segment {seg['file']} missing from {log_dir!r}",
                covered_ts=covered, target_ts=until_ts)
        body = open(fpath, "rb").read()
        if hashlib.sha256(body).hexdigest() != seg["sha256"]:
            metrics.PITR_LOG_GAPS.inc()
            raise LogGapError(
                f"log gap: segment {seg['file']} fails its checksum",
                covered_ts=covered, target_ts=until_ts)
        if seg["file"] not in replayed:
            by_ts: dict = {}
            for line in body.decode("utf-8").splitlines():
                if not line.strip():
                    continue
                rec = json.loads(line)
                if rec.get("t") != "kv":
                    continue  # resolved mark
                ts = rec["ts"]
                if ts <= full_ts or ts > until_ts:
                    continue  # below the snapshot / beyond the cut
                by_ts.setdefault(ts, []).append(rec)
            for ts in sorted(by_ts):
                batch = []
                for rec in by_ts[ts]:
                    key = bytes.fromhex(rec["k"])
                    val = None if rec["v"] is None else rec["v"].encode("latin1")
                    if is_schema_key(key):
                        if val is not None and _apply_schema_record(
                                catalog, decode_payload(val)):
                            events_applied += 1
                        continue
                    batch.append((key, val))
                if batch:
                    # replay at the SOURCE commit ts: versions land
                    # byte-identical and in the original order, so a
                    # re-run after a crash re-puts the same (key, ts)
                    # versions — idempotent by construction
                    store.txn.bulk_ingest(batch, ts)
                    events_applied += len(batch)
            replayed.add(seg["file"])
            segments_replayed += 1
            metrics.PITR_SEGMENTS_REPLAYED.inc()
        covered = max(covered, min(seg["resolved_ts"], until_ts))
        ckpt["covered_ts"] = covered
        ckpt["replayed"] = sorted(replayed)
        _atomic_json(ckpt_path, ckpt)
        if failpoint.eval("restore/replay-crash"):
            raise ReplayInterrupted(
                "restore/replay-crash: killed mid-replay after "
                f"{seg['file']} (re-run resumes from the checkpoint)")
    # the manifest checkpoint is the implicit trailing resolved mark: a
    # quiet log still proves coverage up to it
    if covered < until_ts and log_checkpoint >= until_ts:
        covered = until_ts
    if covered < until_ts:
        metrics.PITR_LOG_GAPS.inc()
        raise LogGapError(
            f"log ends at ts {covered}, cannot restore to {until_ts}",
            covered_ts=covered, target_ts=until_ts)
    store.advance_tso(until_ts)
    store._bump_write_ver()
    metrics.PITR_RESTORES.inc()
    if events_applied:
        metrics.PITR_REPLAYED_EVENTS.inc(events_applied)
    try:
        os.unlink(ckpt_path)  # done: a fresh run must start clean
    except OSError:
        pass
    return {
        "full_backup_ts": full_ts,
        "until_ts": until_ts,
        "segments_replayed": segments_replayed,
        "events_applied": events_applied,
        "resumed": resumed,
    }
