"""Point-in-time recovery (ISSUE 20; ref: br/pkg/stream + br/pkg/task
PiTR): log backup riding the CDC stream as a raw changefeed, replay-to-ts
RESTORE over the latest full backup, and the pd.pitr tick phase."""

from .pitr import (
    LogBackup,
    LogBackupSink,
    LogGapError,
    ReplayInterrupted,
    log_backup_views,
    pitr_tick,
    restore_until,
    start_log_backup,
    stop_log_backup,
)

__all__ = [
    "LogBackup", "LogBackupSink", "LogGapError", "ReplayInterrupted",
    "log_backup_views", "pitr_tick", "restore_until", "start_log_backup",
    "stop_log_backup",
]
