"""Region replication — the raft-lite overlay that gives every region a
peer set (one leader + followers), quorum-acked writes, and per-peer
`safe_ts` watermarks that gate replica reads (ISSUE 8)."""

from .raftlite import QUORUM_SAFE_TS_MAX, ReplicaManager, ReplicationGroup

__all__ = ["ReplicaManager", "ReplicationGroup", "QUORUM_SAFE_TS_MAX"]
