"""Raft-lite region replication (ref: TiKV's raftstore, scaled to the
in-process store: every region is a raft group of peers — one leader, N-1
followers — kvproto metapb.Peer + raft_serverpb; the resolved-ts worker
advances a per-peer `safe_ts` that gates follower/stale reads, and
client-go's `tidb_replica_read` rides it).

What is REAL here and what is simulated, stated plainly:

  * There is ONE physical MVCC KV (`MemKV`) shared by every logical
    placement store — replication does not copy bytes. What the subsystem
    maintains is the *visibility contract*: a follower peer may serve a
    read at `start_ts` only when its `safe_ts >= start_ts`, exactly the
    check TiKV's replica read performs against the resolved-ts
    (components/resolved_ts). Because the KV is shared, a gated read is
    byte-identical to the leader's — the gate itself is what the chaos
    and stale-read tests verify.
  * Writes PROPOSE to the leader's per-region log: each commit appends an
    entry (the commit ts), followers ack it, and the entry commits on
    quorum (len(peers)//2 + 1). The `replica/drop-ack` failpoint drops a
    follower's ack (a partitioned peer); losing quorum is surfaced on the
    `tidb_tpu_replica_quorum_fail_total` counter and flips the group's
    `quorum_ok` — the PD's failover consults liveness for the same
    decision (leader transfer among live peers vs placement move).
  * Followers apply asynchronously: an acked entry advances the
    follower's `applied_ts` (== its safe_ts) unless `replica/apply-lag`
    is armed for its store — a lagging apply loop. The PD tick's
    replication phase is the catch-up driver (the resolved-ts worker
    analog): unarmed followers advance to the leader's committed
    watermark there, and per-store lag lands on the
    `tidb_tpu_replica_safe_ts_lag{store=}` gauge.

Lock order: Cluster._mu -> ReplicaManager._mu (split/merge/transfer
notify under the cluster lock). ReplicaManager therefore NEVER calls back
into Cluster while holding _mu — peer sets are snapshotted first.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

# a leader always serves its own reads: its safe_ts is the group's
# committed watermark by definition, representable as "no gate"
QUORUM_SAFE_TS_MAX = 1 << 62


@dataclass
class ReplicationGroup:
    """One region's replication state (ref: raftstore PeerFsm + the
    resolved-ts region state). `applied_ts` carries FOLLOWER stores only;
    the leader's watermark is `committed_ts` itself."""

    region_id: int
    committed_ts: int = 0
    applied_ts: dict[int, int] = field(default_factory=dict)
    quorum_ok: bool = True
    log_len: int = 0  # committed entries proposed through this group


class ReplicaManager:
    """Replication state for every region of one TPUStore. The cluster
    owns the TOPOLOGY (who the peers are); this owns the DYNAMICS (what
    each peer has applied). `cluster.replica` points back here so
    split/merge/transfer propagate state like `pd.flow` does for stats."""

    def __init__(self, store):
        self.store = store
        self.cluster = store.cluster
        self._mu = threading.Lock()
        self._groups: dict[int, ReplicationGroup] = {}  # guarded_by: _mu
        self._reads: dict[int, int] = {}  # per-store routed reads; guarded_by: _mu
        self.cluster.replica = self

    # -- failpoint arming (non-consuming probes: a storm stays armed) -------
    def _lagging(self, store_id: int) -> bool:
        """True when `store_id`'s apply loop is wedged by failpoint
        (`replica/apply-lag`) — its safe_ts must not advance."""
        from ..store.store import _fault_matches
        from ..util import failpoint

        return _fault_matches(failpoint.peek("replica/apply-lag"), store_id)

    def _ack_dropped(self, store_id: int) -> bool:
        """True when `store_id`'s ack is dropped by failpoint
        (`replica/drop-ack`) — a partitioned follower for quorum math."""
        from ..store.store import _fault_matches
        from ..util import failpoint

        return _fault_matches(failpoint.peek("replica/drop-ack"), store_id)

    # -- group state --------------------------------------------------------
    def _group(self, region_id: int, followers: list[int]) -> ReplicationGroup:  # requires: _mu
        """Lazily bootstrap a group as FULLY replicated at the store's
        current commit watermark (snapshot replication: a fresh peer set
        starts from a snapshot, not an empty log). A follower this group
        has not MATERIALIZED yet has been replicating since the peer set
        formed — it joins caught up; real lag accrues only from proposals
        made while its apply loop is wedged."""
        g = self._groups.get(region_id)
        if g is None:
            now = self.store.kv.max_committed()
            g = self._groups[region_id] = ReplicationGroup(
                region_id, committed_ts=now,
                applied_ts={f: now for f in followers},
            )
        else:
            for f in followers:
                g.applied_ts.setdefault(f, g.committed_ts)
        return g

    def propose(self, region_id: int, ts: int,
                placement: tuple | None = None,
                entries: list | None = None) -> bool:
        """One committed write batch against `region_id` at `ts`: append
        to the leader's log, collect follower acks, commit on quorum, and
        advance every non-lagging follower's applied watermark (the
        common case applies synchronously — healthy raft on a fast LAN).
        `placement` is an optional pre-fetched (leader, peers) snapshot
        (the per-key write path already looked it up — don't take the
        cluster lock again). `entries` is the batch's change payload —
        [(key, value|None)] — handed to the CDC hub AFTER the group state
        settles (the changefeed puller rides this log exactly like TiCDC
        rides the raft log). Returns False when quorum was NOT reached
        (the write is still durable on the shared KV; the flag is what
        failover consults)."""
        return self.propose_group(region_id, [(ts, entries)],
                                  placement=placement)

    def propose_group(self, region_id: int, groups: list,
                      placement: tuple | None = None) -> bool:
        """Group commit (ISSUE 19): ONE log append / ack round / quorum
        decision covering several commits against `region_id`, each at its
        OWN timestamp — N coalesced sessions cost one raft-lite round
        instead of N. `groups` is [(commit_ts, entries|None)]; entries are
        delivered to the CDC hub per commit in ascending ts order, so the
        changefeed sees exactly the per-key event sequence N separate
        proposals would have produced."""
        from ..util import metrics

        if not groups:
            return True
        groups = sorted(groups, key=lambda g: g[0])
        first_ts = groups[0][0]
        last_ts = groups[-1][0]
        if placement is not None:
            leader, peers = placement
        else:
            leader = self.cluster.leader_of(region_id)
            peers = self.cluster.peers_of(region_id)
        followers = [p for p in peers if p != leader]
        quorum = len(peers) // 2 + 1
        with self._mu:
            g = self._group(region_id, followers)
            prev_committed = g.committed_ts
            g.committed_ts = max(g.committed_ts, last_ts)
            g.log_len += 1
            acks = 1  # the leader's own append
            for f in followers:
                dropped = self._ack_dropped(f)
                if not dropped:
                    acks += 1
                if not dropped and not self._lagging(f):
                    g.applied_ts[f] = g.committed_ts
                    continue
                # wedged follower: if it held the FULL log before this
                # entry, everything strictly below the new entry's ts
                # stays servable — but it must NEVER be credited with the
                # entry itself, so its watermark pins at ts - 1 (raft:
                # safe_ts = first-unapplied-entry's ts - 1). For a grouped
                # append the pin sits below the EARLIEST commit in the
                # batch — crediting any later lane would let a wedged
                # follower serve reads it never applied. The pin also
                # clamps the lazy-bootstrap over-credit when this very
                # proposal materialized the group (kv.max_committed()
                # already included the write).
                have = g.applied_ts.get(f, 0)
                if have >= prev_committed or have >= first_ts:
                    g.applied_ts[f] = first_ts - 1
            g.quorum_ok = acks >= quorum
            if not g.quorum_ok:
                metrics.REPLICA_QUORUM_FAILS.inc()
            ok = g.quorum_ok
        # CDC delivery OUTSIDE _mu (lock order: the hub's feed locks are
        # leaves; a subscriber must never nest inside replication state),
        # one on_proposal per lane so every event wears its own commit ts
        hub = getattr(self.store, "cdc", None)
        if hub is not None:
            for ts, entries in groups:
                if entries:
                    hub.on_proposal(region_id, ts, entries)
        return ok

    def check_write_quorum(self, region_id: int,
                           placement: tuple | None = None) -> None:
        """Live quorum roll call BEFORE a write applies (ROADMAP PR-8
        follow-on: a write against a quorum-lost region must be REFUSED,
        not silently durable on the shared KV). Same roll call the PD
        tick's catch-up takes: the leader always acks its own append; a
        follower whose ack the `replica/drop-ack` failpoint drops is a
        partitioned peer. Raises the typed QuorumLostError (MySQL 9005 at
        the session boundary) and keeps the quorum-fail counter honest —
        a refused write is still a failed proposal attempt."""
        from ..store.errors import QuorumLostError
        from ..util import metrics

        if placement is not None:
            leader, peers = placement
        else:
            leader, peers = self.cluster.placement_of(region_id)
        followers = [p for p in peers if p != leader]
        quorum = len(peers) // 2 + 1
        acks = 1 + sum(1 for f in followers if not self._ack_dropped(f))
        if acks >= quorum:
            return
        metrics.REPLICA_QUORUM_FAILS.inc()
        with self._mu:
            g = self._groups.get(region_id)
            if g is not None:
                g.quorum_ok = False  # failover consults the latched flag
        raise QuorumLostError(region_id, acks, quorum)

    def safe_ts(self, region_id: int, store_id: int) -> int:
        """The watermark `store_id` may serve reads at for `region_id`
        (ref: resolved-ts; the store-side replica-read gate compares this
        against the request's start_ts). The leader always serves. A
        FULLY-APPLIED follower also serves any snapshot — it holds every
        committed version of the region, the reference's resolved-ts
        advancing with the clock between writes; only a follower whose
        apply trails the leader's committed watermark is pinned to what
        it has actually applied."""
        leader, peers = self.cluster.placement_of(region_id)
        if leader == store_id:
            return QUORUM_SAFE_TS_MAX
        if store_id not in peers:
            # not a peer (e.g. an in-flight request raced a re_place that
            # evicted this store): it holds nothing it may serve, and it
            # must not materialize a phantom watermark entry
            return 0
        with self._mu:
            g = self._groups.get(region_id)
            if g is None:
                # no proposals ever: the bootstrap snapshot covers all
                return QUORUM_SAFE_TS_MAX
            have = g.applied_ts.get(store_id)
            if have is None:
                # first sight of this peer: it has been replicating since
                # the peer set formed and has missed no tracked proposal
                have = g.applied_ts[store_id] = g.committed_ts
            return QUORUM_SAFE_TS_MAX if have >= g.committed_ts else have

    def quorum_ok(self, region_id: int) -> bool:
        with self._mu:
            g = self._groups.get(region_id)
            return g.quorum_ok if g is not None else True

    def best_transfer_target(self, region_id: int, candidates: list[int],
                             loads: dict | None = None) -> int:
        """Pick the leadership-transfer target among `candidates` (raft:
        only an up-to-date peer may win the election): fully-applied
        peers first, least-loaded among them; with none fully applied,
        the MOST-applied candidate (the reference's most-up-to-date-wins
        vote)."""
        loads = loads or {}
        up = [p for p in candidates
              if self.safe_ts(region_id, p) == QUORUM_SAFE_TS_MAX]
        if up:
            return min(up, key=lambda p: (loads.get(p, 0), p))
        return max(candidates, key=lambda p: (self.safe_ts(region_id, p), -p))

    # -- catch-up + observability (the PD tick's replication phase) ---------
    def catch_up(self) -> int:
        """Advance every unwedged follower to its leader's committed
        watermark (the resolved-ts worker's periodic advance) and refresh
        the per-store lag gauges. Returns the number of followers that
        moved."""
        from ..util import metrics

        regions = [r.region_id for r in self.cluster.regions()]
        topo = {rid: (self.cluster.leader_of(rid), self.cluster.peers_of(rid))
                for rid in regions}
        moved = 0
        lag_by_store: dict[int, int] = {s: 0 for s in range(self.cluster.n_stores)}
        with self._mu:
            # NO pruning against `topo` here: the snapshot above was read
            # outside _mu, so a region split concurrently with this tick
            # could look absent and lose its group — erasing a wedged
            # follower's watermark pin (review finding). Absorbed regions
            # are popped by on_merge under the cluster lock instead.
            for rid, (leader, peers) in topo.items():
                g = self._groups.get(rid)
                if g is None:
                    continue
                followers = [p for p in peers if p != leader]
                for f in followers:
                    have = g.applied_ts.get(f)
                    if have is None:
                        have = g.applied_ts[f] = g.committed_ts
                    if have < g.committed_ts and not self._lagging(f) \
                            and not self._ack_dropped(f):
                        g.applied_ts[f] = g.committed_ts
                        moved += 1
                    lag = max(g.committed_ts - g.applied_ts[f], 0)
                    lag_by_store[f] = max(lag_by_store.get(f, 0), lag)
                # re-take the quorum roll call: quorum_ok latched by the
                # LAST proposal would otherwise stay False forever on a
                # read-only workload after the ack-dropping storm clears,
                # degrading a healthy group's failover to a placement move
                g.quorum_ok = 1 + sum(
                    1 for f in followers if not self._ack_dropped(f)
                ) >= len(peers) // 2 + 1
        for sid, lag in lag_by_store.items():
            metrics.REPLICA_SAFE_TS_LAG.labels(str(sid)).set(lag)
        return moved

    def lag_view(self) -> dict[int, int]:
        """store_id -> worst follower safe_ts lag (ts units), for
        /pd/api/v1/stores and SHOW PLACEMENT."""
        out: dict[int, int] = {s: 0 for s in range(self.cluster.n_stores)}
        with self._mu:
            for g in self._groups.values():
                for f, have in g.applied_ts.items():
                    out[f] = max(out.get(f, 0), max(g.committed_ts - have, 0))
        return out

    # -- read routing load (closest-replica's tiebreak) ---------------------
    def note_read(self, store_id: int) -> None:
        with self._mu:
            self._reads[store_id] = self._reads.get(store_id, 0) + 1

    def read_counts(self) -> dict[int, int]:
        with self._mu:
            return dict(self._reads)

    # -- topology-change bookkeeping (called UNDER Cluster._mu) -------------
    def on_assign(self, region_id: int, peers: list[int], leader: int) -> None:
        """The peer set was (re)assigned (scatter, placement miss, move):
        materialize the new followers caught up at the committed
        watermark and drop state for peers that left the set."""
        with self._mu:
            g = self._groups.get(region_id)
            if g is None:
                return  # lazy bootstrap covers a group with no history
            for f in [p for p in peers if p != leader]:
                g.applied_ts.setdefault(f, g.committed_ts)
            for f in [f for f in list(g.applied_ts) if f not in peers or f == leader]:
                del g.applied_ts[f]

    def on_split(self, parent_id: int, child_id: int) -> None:
        """The child region inherits the parent's replication watermarks —
        peers stay put on a split, so what a follower had applied of the
        parent covers the child's keyspace too."""
        with self._mu:
            p = self._groups.get(parent_id)
            if p is None:
                return
            self._groups[child_id] = ReplicationGroup(
                child_id, committed_ts=p.committed_ts,
                applied_ts=dict(p.applied_ts), quorum_ok=p.quorum_ok,
                log_len=p.log_len,
            )

    def on_merge(self, left_id: int, right_id: int,
                 peers: list[int] | None = None, leader: int = -1) -> None:
        """The survivor's watermark must cover BOTH inputs: a follower
        serves the merged range only at ts it has applied for each half.
        A follower one side never tracked has no gap on that side — it
        counts as applied at that side's committed watermark, NOT at 0
        (review finding: the 0 default manufactured phantom lag). The
        merged group keeps only the SURVIVOR's peer set."""
        with self._mu:
            right = self._groups.pop(right_id, None)
            left = self._groups.get(left_id)
            if right is None or left is None:
                return
            lc, rc = left.committed_ts, right.committed_ts
            for f in set(left.applied_ts) | set(right.applied_ts):
                left.applied_ts[f] = min(left.applied_ts.get(f, lc),
                                         right.applied_ts.get(f, rc))
            left.committed_ts = max(lc, rc)
            left.quorum_ok = left.quorum_ok and right.quorum_ok
            if peers is not None:
                for f in [f for f in list(left.applied_ts)
                          if f not in peers or f == leader]:
                    del left.applied_ts[f]

    def on_transfer(self, region_id: int, old_leader: int, new_leader: int) -> None:
        """Leadership moved (ref: raft TransferLeader — only an up-to-date
        peer may win): the new leader serves from the committed watermark
        by construction; the old leader becomes a fully-applied follower
        (it WAS the leader — it has everything)."""
        with self._mu:
            g = self._groups.get(region_id)
            if g is None:
                return
            g.applied_ts.pop(new_leader, None)
            g.applied_ts[old_leader] = g.committed_ts

    def on_replace(self, region_id: int, peers: list[int], leader: int) -> None:
        """The peer set was rebuilt (quorum-loss placement move): state
        restarts from a fresh snapshot on the new peers."""
        with self._mu:
            now = self.store.kv.max_committed()
            self._groups[region_id] = ReplicationGroup(
                region_id, committed_ts=now,
                applied_ts={p: now for p in peers if p != leader},
            )
