"""HTAP columnar replica tier (ref: TiDB VLDB'20's TiFlash — a
log-replicated columnar replica serving analytics without disturbing
OLTP, layered delta/stable like DeltaTree). Fed by the changefeed
(tidb_tpu/cdc), compacted by the `pd.columnar` tick phase, routed to by
`tidb_isolation_read_engines`."""

from .replica import ColumnarNotReady, ColumnarReplica, ColumnarTable
from .route import columnar_would_serve, try_columnar_select
from .sink import ColumnarSink

__all__ = [
    "ColumnarNotReady",
    "ColumnarReplica",
    "ColumnarSink",
    "ColumnarTable",
    "columnar_would_serve",
    "try_columnar_select",
]
