"""The columnar apply sink — a sibling of `SessionReplaySink`
(cdc/sink.py) that applies mounted TYPED rows into the columnar replica's
delta layer instead of replaying them through a second cluster's write
path (ref: TiFlash learner apply: raft log entries decode once and land
in the DeltaTree's delta; TiDB VLDB'20 §3.2).

No rowcodec anywhere: the changefeed's mounter already produced typed
column datums, and the delta stores them as-is — the whole analytical
read path is codec-free by design.

The sink honors the standard contract (`write` receives rows in
(commit_ts, key) order at or below the NEXT `flush(resolved_ts)`), so
`flush` advancing the tables' applied frontier is exactly the
transactionally-complete-prefix promise the scan-readiness gate relies
on. Delivery is AT-LEAST-ONCE across sink failures (the feed re-queues on
error); the delta fold is idempotent by (commit_ts, handle)."""

from __future__ import annotations

from ..cdc.sink import Sink, SinkError
from .replica import _schema_sig


class ColumnarSink(Sink):
    def __init__(self, replica, catalog, meta):
        self.replica = replica
        self.catalog = catalog
        self.meta = meta
        self.pids = tuple(meta.physical_ids())

    @property
    def table_name(self) -> str:
        return self.meta.name  # follows RENAME TABLE (meta mutates in place)

    def write(self, events: list) -> None:
        from ..sql.catalog import CatalogError
        from ..types import Datum
        from ..util import failpoint, metrics

        if failpoint.eval("columnar/apply-stall"):
            # the apply loop wedges: the feed parks in `error`, the
            # backlog re-queues below the held checkpoint, and RESUME
            # (ColumnarReplica.resume_all) replays it — at-least-once,
            # absorbed by the idempotent delta fold
            raise SinkError("columnar/apply-stall: replica apply loop stalled")
        applied = 0
        for ev in events:
            try:
                meta = self.catalog.table(ev.table)
            except CatalogError:
                continue  # table dropped under the feed: nothing to apply to
            if ev.op == "delete":
                # deletes carry no values, so the partition is unknown:
                # tombstone the handle in every physical table (absent
                # handles fold to nothing — over-deleting is sound).
                # ONE event counts once no matter how many pids the
                # tombstone fans to (review finding: an 8-partition
                # table over-reported deletes 8x)
                hit = False
                for pid in self.pids:
                    t = self.replica.table_for(pid)
                    if t is not None:
                        t.apply(ev.commit_ts, ev.handle, None)
                        hit = True
                if hit:
                    applied += 1
                continue
            by_name = dict(ev.columns)
            datums = [by_name.get(c.name, Datum.NULL) for c in meta.columns]
            pid = meta.pid_for_row(datums)
            t = self.replica.table_for(pid)
            if t is None:
                continue  # a partition added after enable: not replicated
            if _schema_sig(meta.columns) != t.schema_sig:
                # the replica's layers are frozen at the enable-time row
                # shape; a post-ALTER RESUME would otherwise apply rows
                # of the NEW shape into OLD-schema columns (misaligned
                # datums, or an fts/row length mismatch crashing the
                # fold). Park with the rebuild instruction instead —
                # scans already decline on the same signature and fall
                # back to the row store (review finding)
                raise SinkError(
                    f"columnar replica for {ev.table!r} holds the pre-ALTER "
                    f"row shape: rebuild it (ALTER TABLE {ev.table} SET "
                    f"COLUMNAR REPLICA 0, then 1)")
            t.apply(ev.commit_ts, ev.handle, datums)
            applied += 1
        if applied:
            metrics.COLUMNAR_APPLIED.inc(applied)

    def flush(self, resolved_ts: int) -> None:
        from ..util import metrics

        for pid in self.pids:
            t = self.replica.table_for(pid)
            if t is not None:
                t.set_applied(resolved_ts)
        top = self.replica.store.kv.max_committed()
        metrics.COLUMNAR_RESOLVED_LAG.labels(self.table_name).set(
            max(top - resolved_ts, 0))

    def describe(self) -> str:
        return f"columnar://{self.table_name}"
