"""The columnar apply sink — a sibling of `SessionReplaySink`
(cdc/sink.py) that applies mounted TYPED rows into the columnar replica's
delta layer instead of replaying them through a second cluster's write
path (ref: TiFlash learner apply: raft log entries decode once and land
in the DeltaTree's delta; TiDB VLDB'20 §3.2).

No rowcodec anywhere: the changefeed's mounter already produced typed
column datums, and the delta stores them as-is — the whole analytical
read path is codec-free by design.

The sink honors the standard contract (`write` receives rows in
(commit_ts, key) order at or below the NEXT `flush(resolved_ts)`), so
`flush` advancing the tables' applied frontier is exactly the
transactionally-complete-prefix promise the scan-readiness gate relies
on. Delivery is AT-LEAST-ONCE across sink failures (the feed re-queues on
error); the delta fold is idempotent by (commit_ts, handle)."""

from __future__ import annotations

from ..cdc.sink import Sink, SinkError


class ColumnarSink(Sink):
    def __init__(self, replica, catalog, meta):
        self.replica = replica
        self.catalog = catalog
        self.meta = meta
        self.pids = tuple(meta.physical_ids())

    @property
    def table_name(self) -> str:
        return self.meta.name  # follows RENAME TABLE (meta mutates in place)

    def write(self, events: list) -> None:
        from ..cdc.events import SchemaEvent
        from ..cdc.schema import snapshot_from_payload
        from ..sql.catalog import CatalogError
        from ..types import Datum
        from ..util import failpoint, metrics

        if failpoint.eval("columnar/apply-stall"):
            # the apply loop wedges: the feed parks in `error`, the
            # backlog re-queues below the held checkpoint, and RESUME
            # (ColumnarReplica.resume_all) replays it — at-least-once,
            # absorbed by the idempotent delta fold
            raise SinkError("columnar/apply-stall: replica apply loop stalled")
        applied = 0
        for ev in events:
            if isinstance(ev, SchemaEvent):
                # a mid-feed ALTER, ordered between the rows committed
                # before and after it: remap the replica's layers to the
                # new shape and KEEP consuming (ISSUE 20 — the pre-20
                # behavior parked the feed here with a rebuild message)
                snap = snapshot_from_payload(ev.payload)
                reshaped = False
                for pid in self.pids:
                    t = self.replica.table_for(pid)
                    if t is not None and t.reshape(snap.version, snap.columns):
                        reshaped = True
                if reshaped:
                    metrics.COLUMNAR_RESHAPES.inc()
                continue
            try:
                meta = self.catalog.table(ev.table)
            except CatalogError:
                continue  # table dropped under the feed: nothing to apply to
            if ev.op == "delete":
                # deletes carry no values, so the partition is unknown:
                # tombstone the handle in every physical table (absent
                # handles fold to nothing — over-deleting is sound).
                # ONE event counts once no matter how many pids the
                # tombstone fans to (review finding: an 8-partition
                # table over-reported deletes 8x)
                hit = False
                for pid in self.pids:
                    t = self.replica.table_for(pid)
                    if t is not None:
                        t.apply(ev.commit_ts, ev.handle, None)
                        hit = True
                if hit:
                    applied += 1
                continue
            by_name = dict(ev.columns)
            # live-meta name alignment is used ONLY to route the row to
            # its partition; the applied row maps by col_id below
            route = [by_name.get(c.name, Datum.NULL) for c in meta.columns]
            pid = meta.pid_for_row(route)
            t = self.replica.table_for(pid)
            if t is None:
                continue  # a partition added after enable: not replicated
            # remap by col_id against the TABLE's tracked shape (which a
            # schema event earlier in this same ordered stream may have
            # reshaped): a row mounted under the pre-ALTER snapshot still
            # lands in the right columns, missing ones fill from the
            # column's origin default. Only this feed thread reshapes, so
            # the unlocked col_ids/defaults reads cannot race.
            if ev.col_ids:
                by_id = dict(zip(ev.col_ids, (d for _n, d in ev.columns)))
                row = [by_id.get(cid, dflt if dflt is not None else Datum.NULL)
                       for cid, dflt in zip(t.col_ids, t.defaults)]
            else:  # a legacy event with no ids: trust live-name order
                row = route
            t.apply(ev.commit_ts, ev.handle, row)
            applied += 1
        if applied:
            metrics.COLUMNAR_APPLIED.inc(applied)

    def flush(self, resolved_ts: int) -> None:
        from ..util import metrics

        for pid in self.pids:
            t = self.replica.table_for(pid)
            if t is not None:
                t.set_applied(resolved_ts)
        top = self.replica.store.kv.max_committed()
        metrics.COLUMNAR_RESOLVED_LAG.labels(self.table_name).set(
            max(top - resolved_ts, 0))

    def describe(self) -> str:
        return f"columnar://{self.table_name}"
