"""The columnar replica store — this framework's TiFlash (ref: TiDB: A
Raft-based HTAP Database, VLDB'20 §3: a log-replicated columnar replica
that serves analytics without disturbing OLTP; the delta/stable layering
follows TiFlash's DeltaTree design, where fresh log entries land in a
row-versioned DELTA layer and a background pass folds them into sorted,
deduplicated STABLE column chunks).

One `ColumnarReplica` per TPUStore. Each replicated table (one
`ColumnarTable` per PHYSICAL table id, like the row keyspace) holds:

  delta    a row-versioned append buffer — `(commit_ts, handle, row|None)`
           entries exactly as the changefeed's mounter produced them
           (typed datums, NO rowcodec anywhere in this tier: the mounter
           decoded once when the event entered the feed)
  stable   the folded form: one live row per handle, sorted by handle,
           held as a host `Chunk` AND a device-resident `DeviceBatch`
           (chunk/device.py) so analytical scans ship zero bytes and
           decode nothing — the fused program reads HBM directly
  applied  the feed's flushed resolved-ts: every commit at or below it
           has been applied (the scan-readiness gate)
  floor    `stable_ts`, the compaction watermark: versions at or below it
           were folded, so a snapshot OLDER than the floor cannot be
           reconstructed here and falls back to the row store

Consistency contract (the chaos storm's oracle): a scan served at
`start_ts` requires `stable_ts <= start_ts <= applied_ts` and is then
byte-identical to a row-store scan at the same snapshot — stable rows all
predate the floor, and the delta overlay replays exactly the versions in
`(stable_ts, start_ts]`.

Lock order: replica._mu and each table._mu are leaves — nothing else is
acquired under them (the device upload in compact() runs under table._mu
but touches only JAX, never another subsystem lock).
"""

from __future__ import annotations

import threading

from ..chunk import Chunk

I64_MIN = -(1 << 63)
I64_MAX = (1 << 63) - 1


class ColumnarNotReady(RuntimeError):
    """DataIsNotReady's columnar shape (ref: TiKV's replica read answering
    errorpb.DataIsNotReady when `safe_ts < start_ts`): the replica cannot
    serve this snapshot — the resolved frontier trails it (`applied_ts <
    start_ts`) or compaction folded past it (`start_ts < stable_ts`). The
    route layer waits once on the data_not_ready backoff budget, then
    falls back to the row store."""

    def __init__(self, table: str, start_ts: int, applied_ts: int, stable_ts: int):
        super().__init__(
            f"columnar data_is_not_ready: table {table!r} start_ts={start_ts} "
            f"applied_ts={applied_ts} stable_ts={stable_ts}")
        self.table = table
        self.start_ts = start_ts
        self.applied_ts = applied_ts
        self.stable_ts = stable_ts


def _fold_newest(entries: list) -> dict:
    """Latest version per handle, with PUT beating DELETE on a commit-ts
    tie. The tie is real: an UPDATE that moves a row across partitions
    emits delete(old pid) + put(new pid) at the SAME commit ts, and the
    apply sink fans the value-less delete to EVERY pid — without the
    tie-break, the tombstone could erase the new partition's live row
    (the replay sink's `latest_ts(key) < commit_ts` skip, folded into
    the delta semantics; within ONE pid a txn never commits both a put
    and a delete of the same key at one ts, so the tie-break only ever
    fires on the cross-pid fan-out)."""
    newest: dict = {}
    for ts, h, row in sorted(entries, key=lambda e: (e[0], e[2] is not None)):
        newest[h] = row
    return newest


def _schema_sig(columns) -> tuple:
    """Stable identity of a scan schema: (col_id, eval type, charset) per
    column. The route layer declines when a DAG's scan no longer matches
    the replica's snapshot of the table (a mid-feed ALTER parked the feed;
    the replica keeps serving OLD-schema snapshots, never mixed ones)."""
    return tuple((c.col_id, c.ft.eval_type(), c.ft.charset or "") for c in columns)


class ColumnarTable:
    """Delta + stable layers of one physical table (ref: TiFlash's
    DeltaTree segment: delta appends, stable folded)."""

    def __init__(self, pid: int, meta):
        self.pid = pid
        self.meta = meta  # identity/current-name only — the row SHAPE
        # below snapshots at enable time (a live meta.columns read would
        # silently drift under DDL) and advances ONLY through
        # `reshape()`, driven by the feed's ordered SchemaEvents
        self.table_id = meta.table_id
        self.fts = [c.ft for c in meta.columns]
        self.schema_sig = _schema_sig(meta.columns)
        self.schema_version = meta.schema_version
        self.col_ids = [c.col_id for c in meta.columns]
        self.defaults = [c.origin_default for c in meta.columns]
        self._mu = threading.Lock()
        self.delta: list = []  # [(commit_ts, handle, row|None)]; guarded_by: _mu
        self.applied_ts = 0  # flushed resolved frontier; guarded_by: _mu
        self.stable_ts = 0  # compaction watermark (the floor); guarded_by: _mu
        self._stable_rows: dict = {}  # handle -> row datums; guarded_by: _mu
        self._stable_chunk: Chunk | None = None  # sorted by handle; guarded_by: _mu
        self._stable_handles: list = []  # sorted handles; guarded_by: _mu
        self._stable_batch = None  # device-resident stable; guarded_by: _mu
        self.applied_events = 0  # guarded_by: _mu
        self.compactions = 0  # guarded_by: _mu
        self.last_error = ""  # last compaction failure (GIL-atomic str swap)

    @property
    def name(self) -> str:
        """The table's CURRENT name — RENAME TABLE mutates meta in
        place, and views/routing must follow it (review finding: a
        name-keyed registry orphaned the feed across a rename)."""
        return self.meta.name

    # ------------------------------------------------------------ delta
    def apply(self, commit_ts: int, handle: int, row: list | None) -> None:
        """One mounted change into the delta layer (row None = delete).
        At-least-once delivery is fine: the fold is by max commit_ts per
        handle, so a redelivered (ts, handle) pair is idempotent."""
        with self._mu:
            self.delta.append((commit_ts, handle, row))
            self.applied_events += 1

    def set_applied(self, resolved_ts: int) -> None:
        """The feed's flush: every commit <= resolved_ts is in the delta."""
        with self._mu:
            if resolved_ts > self.applied_ts:
                self.applied_ts = resolved_ts

    # ---------------------------------------------------------- reshape
    def reshape(self, schema_version: int, columns) -> bool:
        """Remap every held row to a NEW column shape by col_id (ISSUE
        20: a mid-feed ALTER arrives as an ordered SchemaEvent and the
        replica follows it instead of parking). Columns the old shape
        lacked fill from the column's origin default (NULL when none) —
        the same backfill the mounter applies to old row bytes.
        Idempotent by schema version (redelivered events no-op); returns
        True when the shape moved. `columns` is a sequence of
        ColumnSnap-shaped objects (.name/.col_id/.ft/.origin_default)."""
        from ..types import Datum

        with self._mu:
            if schema_version <= self.schema_version:
                return False
            old_idx = {cid: i for i, cid in enumerate(self.col_ids)}

            def remap(row):
                return [
                    row[old_idx[c.col_id]] if c.col_id in old_idx
                    else (c.origin_default if c.origin_default is not None
                          else Datum.NULL)
                    for c in columns
                ]

            self._stable_rows = {h: remap(r) for h, r in self._stable_rows.items()}
            self.delta = [(ts, h, None if r is None else remap(r))
                          for ts, h, r in self.delta]
            self.fts = [c.ft for c in columns]
            self.schema_sig = _schema_sig(columns)
            self.col_ids = [c.col_id for c in columns]
            self.defaults = [c.origin_default for c in columns]
            self.schema_version = schema_version
            self._stable_chunk = Chunk.from_rows(
                self.fts, [self._stable_rows[h] for h in self._stable_handles])
            # the host chunk serves until the next compact re-uploads;
            # a stale-shape device batch must never outlive the remap
            self._stable_batch = None
            return True

    # ------------------------------------------------------- compaction
    def compact(self) -> int:
        """Fold every delta entry at or below the applied frontier into
        the stable layer: latest version per handle wins, deletes remove
        the row, the result sorts by handle and re-uploads to device.
        Returns entries folded. The floor (`stable_ts`) advances to the
        frontier the fold ran at — snapshots older than that can no
        longer be served here (their overwritten versions are gone)."""
        from ..chunk.device import to_device_batch
        from ..exec.executor import _pow2

        with self._mu:
            fold_ts = self.applied_ts
            take = [e for e in self.delta if e[0] <= fold_ts]
            if not take:
                # nothing to fold: the floor must NOT creep to the
                # frontier — an unchanged stable layer still serves every
                # snapshot down to the floor it was folded at (floor
                # creep would decline stale reads for no reason)
                if self._stable_chunk is None:
                    # first pass over a never-written table: materialize
                    # the empty stable chunk so the scan fast path
                    # exists (floor stays 0 — empty at every snapshot)
                    self._stable_chunk = Chunk.from_rows(self.fts, [])
                return 0
            self.delta = [e for e in self.delta if e[0] > fold_ts]
            newest = _fold_newest(take)
            for h, row in newest.items():
                if row is None:
                    self._stable_rows.pop(h, None)
                else:
                    self._stable_rows[h] = row
            handles = sorted(self._stable_rows)
            chunk = Chunk.from_rows(self.fts, [self._stable_rows[h] for h in handles])
            batch = None
            try:
                # device-resident stable: scans drive the fused program
                # straight from HBM (non-ASCII CI columns can't ride the
                # device CI kernels — chunk-only, the scan's oracle
                # fallback serves)
                batch = to_device_batch(chunk, capacity=_pow2(max(chunk.num_rows(), 1)))
            except NotImplementedError:
                batch = None
            self._stable_chunk = chunk
            self._stable_handles = handles
            self._stable_batch = batch
            self.stable_ts = fold_ts
            self.compactions += 1
            return len(take)

    # ------------------------------------------------------------ scans
    def frontier(self) -> tuple:
        """(applied_ts, stable_ts) snapshot for the readiness gate."""
        with self._mu:
            return self.applied_ts, self.stable_ts

    def scan(self, start_ts: int, intervals: list | None):
        """Rows visible at `start_ts` as (chunk, device_batch|None).
        `intervals` is a list of inclusive (lo, hi) handle bounds (None =
        the whole table). The fast path — no unfolded delta at this
        snapshot, full-range scan — returns the cached stable chunk and
        its device-resident batch untouched; otherwise the delta overlay
        merges on the host (still typed datums, never rowcodec)."""
        with self._mu:
            if start_ts < self.stable_ts or start_ts > self.applied_ts:
                raise ColumnarNotReady(self.name, start_ts, self.applied_ts, self.stable_ts)
            overlay = [e for e in self.delta if e[0] <= start_ts]
            full = intervals is None or any(
                lo <= I64_MIN and hi >= I64_MAX for lo, hi in intervals)
            if not overlay and full and self._stable_chunk is not None:
                return self._stable_chunk, self._stable_batch
            merged = dict(self._stable_rows)
            newest = _fold_newest(overlay)
            for h, row in newest.items():
                if row is None:
                    merged.pop(h, None)
                else:
                    merged[h] = row
            handles = sorted(merged)
            if intervals is not None and not full:
                handles = [
                    h for h in handles
                    if any(lo <= h <= hi for lo, hi in intervals)
                ]
            return Chunk.from_rows(self.fts, [merged[h] for h in handles]), None

    def view(self) -> dict:
        with self._mu:
            return {
                "pid": self.pid,
                "delta_rows": len(self.delta),
                "stable_rows": len(self._stable_handles),
                "stable_chunk": self._stable_chunk is not None,
                "on_device": self._stable_batch is not None,
                "applied_ts": self.applied_ts,
                "stable_ts": self.stable_ts,
                "applied_events": self.applied_events,
                "compactions": self.compactions,
                "error": self.last_error,
            }


class ColumnarReplica:
    """All columnar tables of one store + their feeding changefeeds.
    `enable_table` creates one changefeed per logical table (sink =
    ColumnarSink) whose birth incremental scan backfills full history;
    `compact_tick` is the `pd.columnar` phase body."""

    def __init__(self, store):
        self.store = store
        self._mu = threading.Lock()
        self._by_pid: dict = {}  # pid -> ColumnarTable; guarded_by: _mu
        # keyed by the IMMUTABLE logical table id, not the name — RENAME
        # TABLE mutates meta.name in place, and a name-keyed registry
        # would orphan the feeding changefeed (a live GC safepoint) on
        # the disable under the new name (review finding)
        self._feeds: dict = {}  # table_id -> changefeed name; guarded_by: _mu
        self._gauge_names: dict = {}  # table_id -> last gauge label; guarded_by: _mu

    # -------------------------------------------------------- lifecycle
    def enable_table(self, catalog, meta) -> None:
        """Attach a columnar replica to `meta`: register its physical
        tables and create the feeding changefeed (idempotent). The
        tables register BEFORE the feed exists — `cdc.create` makes the
        feed tickable immediately, and a background PD tick landing in
        the gap would hand the whole birth backfill to a sink whose
        `table_for` lookups miss (silently dropping every pre-existing
        row forever; review finding)."""
        from ..cdc import ChangefeedError
        from .sink import ColumnarSink

        tables = {pid: ColumnarTable(pid, meta) for pid in meta.physical_ids()}
        feed_name = f"columnar:{meta.name}"
        with self._mu:
            if meta.table_id in self._feeds:
                return
            self._feeds[meta.table_id] = feed_name  # reservation: a racing
            # enable sees it and returns; rolled back if create fails
            self._by_pid.update(tables)
        sink = ColumnarSink(self, catalog, meta)
        try:
            self.store.cdc.create(
                feed_name, sink, catalog,
                table_ids=set(meta.physical_ids()) | {meta.table_id}, start_ts=0)
        except ChangefeedError:
            with self._mu:
                self._feeds.pop(meta.table_id, None)
                for pid in tables:
                    self._by_pid.pop(pid, None)
            raise

    def disable_table(self, meta) -> None:
        from ..cdc import ChangefeedError
        from ..util import metrics

        with self._mu:
            feed_name = self._feeds.pop(meta.table_id, None)
            last_label = self._gauge_names.pop(meta.table_id, None)
            for pid in meta.physical_ids():
                self._by_pid.pop(pid, None)
        if last_label is not None and last_label != meta.name:
            from ..util import metrics

            metrics.COLUMNAR_RESOLVED_LAG.labels(last_label).set(0)
        if feed_name is not None:
            try:
                self.store.cdc.drop(feed_name)
            except ChangefeedError:
                pass  # the feed was dropped out from under us
            metrics.COLUMNAR_RESOLVED_LAG.labels(meta.name).set(0)

    def enabled(self, table_id: int) -> bool:
        with self._mu:
            return table_id in self._feeds

    def resume_all(self) -> None:
        """RESUME every columnar feed parked in `error` (the storm's
        recovery action after a columnar/apply-stall window)."""
        from ..cdc import ChangefeedError

        with self._mu:
            names = list(self._feeds.values())
        for n in names:
            try:
                self.store.cdc.get(n).resume()
            except ChangefeedError:
                pass

    # ----------------------------------------------------------- lookup
    def table_for(self, pid: int) -> ColumnarTable | None:
        with self._mu:
            return self._by_pid.get(pid)

    def tables(self) -> list:
        with self._mu:
            return list(self._by_pid.values())

    def has_tables(self) -> bool:
        with self._mu:
            return bool(self._by_pid)

    def feed_state(self, table_id: int) -> str:
        """Lifecycle state of the feed replicating one logical table."""
        from ..cdc import ChangefeedError

        with self._mu:
            feed_name = self._feeds.get(table_id)
        if feed_name is None:
            return "disabled"
        try:
            feed = self.store.cdc.get(feed_name)
        except ChangefeedError:
            return "removed"
        with feed._mu:
            return feed.state

    # ------------------------------------------------------- compaction
    def compact_tick(self) -> int:
        """One background compaction round (the `pd.columnar` tick phase
        body, riding the same Timer the pd/cdc ticks do): fold every
        table's delta into its stable layer and refresh the freshness
        gauges. `columnar/compact-stall` skips the fold — delta grows,
        scans keep serving (the floor just stops advancing)."""
        from ..util import failpoint, metrics, tracing

        if failpoint.eval("columnar/compact-stall"):
            return 0
        folded = 0
        for t in self.tables():
            with tracing.span("columnar.compact", table=t.name, pid=t.pid) as sp:
                try:
                    n = t.compact()
                except Exception as exc:  # noqa: BLE001 — one poisoned
                    # table must not abort the PD tick's remaining
                    # phases (schedule/dispatch run after pd.columnar);
                    # the error surfaces in the table view and the scan
                    # path keeps falling back safely
                    t.last_error = f"{type(exc).__name__}: {exc}"
                    if sp is not None:
                        sp.set("error", t.last_error)
                    continue
                if sp is not None:
                    sp.set("rows_folded", n)
            if n:
                metrics.COLUMNAR_COMPACTIONS.inc()
            folded += n
        self._refresh_gauges()
        return folded

    def _refresh_gauges(self) -> None:
        from ..util import metrics

        top = self.store.kv.max_committed()
        for tid, (name, applied) in self._applied_by_id().items():
            with self._mu:
                old = self._gauge_names.get(tid)
                self._gauge_names[tid] = name
            if old is not None and old != name:
                # RENAME TABLE moved the label: zero the stranded series
                # or its last lag value alerts forever (review finding)
                metrics.COLUMNAR_RESOLVED_LAG.labels(old).set(0)
            metrics.COLUMNAR_RESOLVED_LAG.labels(name).set(max(top - applied, 0))

    def _applied_by_id(self) -> dict:
        """table_id -> (current name, min applied across its pids)."""
        out: dict = {}
        for t in self.tables():
            a, _f = t.frontier()
            prev = out.get(t.table_id)
            out[t.table_id] = (t.name, a if prev is None else min(prev[1], a))
        return out

    # ------------------------------------------------------------ views
    def views(self) -> list:
        """One row per logical table (SHOW COLUMNAR TABLES and the
        /columnar/api/v1/tables HTTP view)."""
        top = self.store.kv.max_committed()
        by_name: dict = {}
        for t in self.tables():
            v = t.view()
            agg = by_name.setdefault(t.name, {
                "table": t.name, "state": self.feed_state(t.table_id),
                "pids": 0, "delta_rows": 0, "stable_rows": 0,
                "stable_chunks": 0, "applied_events": 0, "compactions": 0,
                "applied_ts": v["applied_ts"], "stable_ts": v["stable_ts"],
            })
            agg["pids"] += 1
            agg["delta_rows"] += v["delta_rows"]
            agg["stable_rows"] += v["stable_rows"]
            agg["stable_chunks"] += 1 if v["stable_chunk"] else 0
            agg["applied_events"] += v["applied_events"]
            agg["compactions"] += v["compactions"]
            agg["applied_ts"] = min(agg["applied_ts"], v["applied_ts"])
            agg["stable_ts"] = max(agg["stable_ts"], v["stable_ts"])
        for agg in by_name.values():
            agg["resolved_ts_lag"] = max(top - agg["applied_ts"], 0)
        return [by_name[k] for k in sorted(by_name)]
