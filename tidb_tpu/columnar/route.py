"""Engine routing for the columnar replica (ref: TiDB's
`tidb_isolation_read_engines` + planner engine selection — `kv.StoreType
{TiKV, TiFlash}` picking which store kind may serve each read;
planner/core/find_best_task.go's isolation-read engine filter).

`execute_root` consults this module before splitting a plan for the row
store: when the session's engine list includes `columnar` and the plan is
an ELIGIBLE analytical shape, the whole logical DAG runs over the
replica's device-resident column chunks instead of dispatching per-region
cop tasks — one program over all rows, no rowcodec, no region fan-out.

Eligibility (the TiFlash routing rules, scaled to this engine):
  * the probe is a TABLE scan (index scans/lookups describe row-store
    access paths), every range parses to exact handle bounds, and every
    physical table the ranges touch is replicated with a matching schema
  * the plan is analytical: an Aggregation or TopN appears in the DAG
    (point gets never reach execute_root; plain row-local scans stay on
    the row store, which answers them from its caches)
  * in-txn reads and EXPLAIN ANALYZE runs never route (the session strips
    `columnar` from the engine list for those)

Staleness: a scan at `start_ts` needs the replica frontier to cover it
(`applied_ts >= start_ts`) and compaction not to have folded past it
(`stable_ts <= start_ts`). A lagging frontier answers the typed
DataIsNotReady shape: one wait on the `data_not_ready` backoff budget
(PR 8's replication budget — a background tick may advance the frontier),
one re-check, then a counted fallback to the row store. Never a torn
prefix."""

from __future__ import annotations

from ..codec import tablecodec
from .replica import I64_MAX, I64_MIN, ColumnarNotReady, _schema_sig

_ROW_KEY_LEN = 1 + 8 + 2 + 8  # 't' + tid + '_r' + handle


def _range_handles(kr) -> tuple | None:
    """KeyRange -> (pid, lo, hi) INCLUSIVE handle bounds, or None when the
    bytes are not exact row-key bounds (index keyspace, partial prefixes —
    anything ambiguous declines to the row store, never guesses)."""
    start, end = kr.start, kr.end
    if len(start) != _ROW_KEY_LEN:
        return None
    try:
        pid, lo = tablecodec.decode_row_key(start)
    except ValueError:
        return None
    if len(end) == _ROW_KEY_LEN:
        try:
            pid2, h = tablecodec.decode_row_key(end)
        except ValueError:
            return None
        if pid2 != pid or h == I64_MIN:
            return None
        hi = h - 1
    elif len(end) == _ROW_KEY_LEN + 1 and end[-1:] == b"\x00":
        try:
            pid2, hi = tablecodec.decode_row_key(end[:-1])
        except ValueError:
            return None
        if pid2 != pid:
            return None
    else:
        return None
    return pid, lo, hi


def _plan_intervals(dag, ranges) -> dict | None:
    """ranges -> {pid: [(lo, hi)]} in first-seen pid order, or None when
    any range is not an exact row-key interval."""
    out: dict = {}
    for kr in ranges:
        hit = _range_handles(kr)
        if hit is None:
            return None
        pid, lo, hi = hit
        out.setdefault(pid, []).append((lo, hi))
    return out


def _analytical(dag) -> bool:
    from ..exec.dag import Aggregation, TableScan, TopN

    if not isinstance(dag.executors[0], TableScan):
        return False
    return any(isinstance(e, (Aggregation, TopN)) for e in dag.executors)


def columnar_would_serve(store, dag, ranges, engines) -> bool:
    """Cheap routing predicate (no execution, no waiting): is this plan
    the columnar replica's to serve? The session uses it to keep the
    whole-plan mesh shortcut from preempting engine routing; readiness is
    NOT checked here — a lagging frontier is `try_columnar_select`'s
    fallback decision, made at execution time."""
    if "columnar" not in engines:
        return False
    rep = getattr(store, "columnar", None)
    if rep is None or not rep.has_tables() or not _analytical(dag):
        return False
    plan = _plan_intervals(dag, ranges)
    if not plan:
        return False
    sig = _schema_sig(dag.scan().columns)
    return all(
        (t := rep.table_for(pid)) is not None and t.schema_sig == sig
        for pid in plan
    )


def try_columnar_select(store, dag, ranges, start_ts: int, aux_chunks: list,
                        cache=None, group_capacity: int | None = None,
                        small_groups: int | None = None,
                        backoff_weight: int = 2, checker=None):
    """Serve the whole logical DAG from the columnar replica. Returns the
    result Chunk, or None when the plan is not the replica's to serve
    (ineligible shape / unreplicated table) or the frontier could not
    cover the snapshot after one data_not_ready wait (a counted fallback —
    the caller dispatches to the row store as if routing never happened)."""
    from ..exec.builder import DEFAULT_GROUP_CAPACITY
    from ..util import metrics, tracing

    rep = getattr(store, "columnar", None)
    if rep is None or not rep.has_tables() or not _analytical(dag):
        return None
    plan = _plan_intervals(dag, ranges)
    if not plan:
        return None
    sig = _schema_sig(dag.scan().columns)
    tables = []
    for pid in plan:
        t = rep.table_for(pid)
        if t is None:
            return None  # an unreplicated physical table: not ours
        if t.schema_sig != sig:
            # schema drift (a mid-feed ALTER parked the feed): the replica
            # holds the OLD shape — this is a routed-then-declined read
            metrics.COLUMNAR_FALLBACKS.inc()
            return None
        tables.append(t)
    ts_eff = _wait_ready(store, tables, start_ts, backoff_weight, checker)
    if ts_eff is None:
        metrics.COLUMNAR_FALLBACKS.inc()
        return None
    group_capacity = group_capacity or DEFAULT_GROUP_CAPACITY
    with tracing.span("columnar.scan", table=tables[0].name,
                      start_ts=start_ts, snapshot_ts=ts_eff,
                      pids=len(tables)) as sp:
        try:
            out = _run(store, dag, plan, tables, ts_eff, aux_chunks,
                       cache, group_capacity, small_groups)
        except ColumnarNotReady:
            # a compaction advanced the floor between the gate and the
            # scan: fall back rather than serve a torn snapshot
            metrics.COLUMNAR_FALLBACKS.inc()
            return None
        except Exception:  # noqa: BLE001 — degrade, never fail the query:
            # the row store still owns the authoritative answer
            metrics.COLUMNAR_FALLBACKS.inc()
            return None
        if sp is not None:
            sp.set("rows", out.num_rows())
    metrics.COLUMNAR_SCANS.inc()
    return out


def _wait_ready(store, tables, start_ts: int, backoff_weight: int, checker):
    """The staleness gate. Returns the snapshot the replica serves at —
    `min(start_ts, applied_ts)` — or None for a counted row-store
    fallback. The served snapshot is provably EQUIVALENT to `start_ts`:
    it is either `start_ts` itself (the frontier covers it), or the
    frontier with `applied_ts >= kv.max_committed()` proven under a
    quiescent WriteGuard double-sample — no commit exists (or is in
    flight) in `(applied_ts, start_ts]`, so the two snapshots see
    identical data. A frontier trailing a real commit answers the
    DataIsNotReady shape: one wait on the replication error's
    data_not_ready budget (PR 8 — a background pd tick may advance the
    frontier under us), one re-check, then None. A snapshot OLDER than
    the compaction floor (a stale read whose overwritten versions were
    folded away) can never become servable and returns None fast."""
    from ..util.backoff import Backoffer, BackoffExhausted

    def gate():
        applied = min(t.frontier()[0] for t in tables)
        floor = max(t.frontier()[1] for t in tables)
        if applied >= start_ts:
            return start_ts if start_ts >= floor else None
        # frontier behind the snapshot: serving at `applied` is only
        # equivalent when NO commit exists in (applied, start_ts] — and
        # comparing against kv.max_committed alone cannot prove that: a
        # writer inside its [commit-ts draw .. apply] window has a ts
        # drawn but nothing in kv yet (review finding). The CDC
        # WriteGuard's quiescent double-sample closes exactly that
        # window (hub._safe_candidate's proof): no write in flight
        # across the max_committed read and none completed between the
        # samples means every drawn commit ts is applied and <=
        # max_committed <= applied; any later writer draws > start_ts.
        guard = getattr(store.cdc, "guard", None)
        if guard is None:
            return None
        inflight, seq = guard.sample()
        if inflight:
            return None
        top = store.kv.max_committed()
        inflight2, seq2 = guard.sample()
        if applied >= top and inflight2 == 0 and seq2 == seq:
            return applied if applied >= floor else None
        return None

    ts = gate()
    if ts is not None:
        return ts
    if start_ts < max(t.frontier()[1] for t in tables):
        # below the compaction floor: floors only advance, so waiting
        # can never make this snapshot servable — fail fast
        return None
    applied = min(t.frontier()[0] for t in tables)
    boff = Backoffer(weight=backoff_weight, checker=checker)
    try:
        boff.backoff(
            "data_not_ready",
            f"columnar data_is_not_ready: applied_ts={applied} start_ts={start_ts}")
    except BackoffExhausted:
        return None
    return gate()


def _run(store, dag, plan: dict, tables: list, start_ts: int, aux_chunks,
         cache, group_capacity: int, small_groups):
    """Execute the DAG over the replica's chunks. Single-table full scans
    with a folded delta ride the DEVICE-RESIDENT stable batch straight
    into the fused program (zero upload, zero decode); everything else
    merges the delta overlay on the host and takes the standard
    chunk-execution path (spill + oracle fallbacks included)."""
    from ..chunk import Chunk
    from ..exec.executor import (
        OverflowRetryError,
        drive_program_info,
        run_dag_on_chunks,
    )

    scans = []
    for pid, t in zip(plan, tables):
        scans.append(t.scan(start_ts, plan[pid]))
    if len(scans) == 1 and scans[0][1] is not None:
        batch = scans[0][1]
        try:
            batches = [batch] + [store._aux_batch(c) for c in aux_chunks]
            chunk, _rows, _info = drive_program_info(
                store.programs, dag, batches, group_capacity,
                small_groups=small_groups)
            return chunk
        except (OverflowRetryError, NotImplementedError):
            pass  # the chunk path below owns the retry/oracle ladder
    merged = scans[0][0] if len(scans) == 1 else Chunk.concat([c for c, _b in scans])
    return run_dag_on_chunks(dag, [merged] + list(aux_chunks),
                             cache=cache or store.programs,
                             group_capacity=group_capacity,
                             small_groups=small_groups)
