"""Bulk import — the Lightning analog (ref: pkg/lightning local backend:
parse -> encode KV -> ingest, bypassing the SQL executor; checkpoints
pkg/lightning/checkpoints keep imports resumable).

`load_data` serves `LOAD DATA INFILE` (session routes LoadDataStmt here):
CSV-ish lines are parsed, coerced to column types, encoded with rowcodec,
and written in batches directly to the store (rows + index entries) — each
batch commits at its own TSO tick and advances a sidecar checkpoint file
(`<path>.ckpt`), so a crashed import resumes at the last durable batch."""

from __future__ import annotations

import os

from ..codec import tablecodec
from ..sql.planner import _coerce_datum
from ..types import Datum

BATCH = 1024


def _parse_line(line: str, sep: str, enclosed: str) -> list:
    """Split one data line (supports the enclosure char and \\N nulls)."""
    fields = []
    cur = []
    i, n = 0, len(line)
    in_enc = False
    while i < n:
        ch = line[i]
        if in_enc:
            if ch == enclosed:
                if i + 1 < n and line[i + 1] == enclosed:
                    cur.append(enclosed)
                    i += 1
                else:
                    in_enc = False
            else:
                cur.append(ch)
        elif enclosed and ch == enclosed and not cur:
            in_enc = True
        elif line.startswith(sep, i):
            fields.append("".join(cur))
            cur = []
            i += len(sep) - 1
        elif ch == "\\" and i + 1 < n:
            nxt = line[i + 1]
            if (nxt == "N" and not cur
                    and (i + 2 >= n or line.startswith(sep, i + 2))):
                # \N is NULL only when it constitutes the whole field
                cur.append("\x00NULL")
            else:
                cur.append({"n": "\n", "t": "\t"}.get(nxt, nxt))
            i += 1
        else:
            cur.append(ch)
        i += 1
    fields.append("".join(cur))
    return fields


def load_data(session, stmt) -> int:
    """Execute a LoadDataStmt; returns imported row count (resumed rows
    excluded). Duplicate primary keys fail the batch loudly."""
    from ..sql.session import SQLError

    meta = session.catalog.table(stmt.table.name)
    path = stmt.path
    if not os.path.exists(path):
        raise SQLError(f"LOAD DATA: file not found: {path!r}")
    col_names = [c.lower() for c in stmt.columns] or [c.name for c in meta.columns]
    positions = []
    for cn in col_names:
        positions.append(meta.col(cn))
    ckpt_path = path + ".ckpt"
    done = 0
    if os.path.exists(ckpt_path):
        try:
            done = int(open(ckpt_path).read().strip() or 0)
        except ValueError:
            done = 0

    sep = stmt.fields_terminated or "\t"
    enc = stmt.fields_enclosed or ""
    imported = 0
    batch_rows: list = []

    pos = {c.name: i for i, c in enumerate(meta.columns)}
    uniq_idxs = [i for i in meta.indices if i.unique]

    def flush():
        nonlocal imported
        if not batch_rows:
            return
        # the WHOLE batch — timestamp draw, duplicate checks, lock check,
        # writes — runs in one engine critical section, so no concurrent
        # commit can land between the unique scan and the apply (ADVICE r2;
        # review r3: the read_ts-before-lock window allowed duplicates)
        # the CDC WriteGuard brackets [ts draw .. record_applied_writes]
        # so a changefeed's resolved-ts sampler counts the batch as in
        # flight until its change events are delivered
        with session.store.cdc.guard.writing():
            with session.store.txn.ingest_guard():
                ts = session.store.next_ts()
                read_ts = session.store.next_ts()
                # ALL conflict checks before ANY write: a mid-batch duplicate
                # must not leave half a batch durable below the checkpoint
                # (re-running would then collide with the crashed run's rows)
                seen_pk: set = set()
                seen_uk: set = set()
                for handle, datums in batch_rows:
                    if handle in seen_pk:
                        raise SQLError(f"LOAD DATA: duplicate primary key {handle} within the file")
                    seen_pk.add(handle)
                    key = tablecodec.encode_row_key(meta.pid_for_row(datums), handle)
                    if session.store.kv.get(key, read_ts) is not None:
                        raise SQLError(f"LOAD DATA: duplicate primary key {handle}")
                    for idx in uniq_idxs:
                        vals = [datums[pos[cn]] for cn in idx.col_names]
                        if any(d.is_null() for d in vals):
                            continue
                        prefix = tablecodec.encode_index_key(meta.table_id, idx.index_id, vals)
                        if (idx.index_id, prefix) in seen_uk:
                            raise SQLError(f"LOAD DATA: duplicate entry for unique key {idx.name!r} within the file")
                        seen_uk.add((idx.index_id, prefix))
                        if next(iter(session.store.kv.scan(prefix, prefix + b"\xff", read_ts)), None) is not None:
                            raise SQLError(f"LOAD DATA: duplicate entry for unique key {idx.name!r}")
                items = []
                for handle, datums in batch_rows:
                    items.append((
                        # partition-aware key routing (partitioned tables store
                        # rows under their PartitionDef pid)
                        tablecodec.encode_row_key(meta.pid_for_row(datums), handle),
                        session.store._row_encoder.encode(meta.col_ids(), datums),
                    ))
                    for idx in meta.indices:
                        vals = [datums[pos[cn]] for cn in idx.col_names] + [Datum.i64(handle)]
                        items.append((tablecodec.encode_index_key(meta.table_id, idx.index_id, vals), b"\x00"))
                # raises KeyIsLocked on a conflict with a live 2PC; the
                # session's LOAD DATA branch maps it to a SQLError (vet
                # dataflow-error-escape: it used to escape the boundary raw)
                session.store.txn.check_unlocked([k for k, _ in items])
                # quorum-lost regions refuse bulk writes too (PR-8 follow-on);
                # raises BEFORE anything turns durable
                session.store._check_write_quorum([k for k, _ in items])
                applied = [(k, v, session.store.kv.put(k, v, ts)) for k, v in items]
            # PD write flow AFTER the engine guard (bulk-loaded regions
            # must report their size/keys or the merge-checker sees them
            # as empty) but INSIDE the write window: the replication
            # proposal carries this batch's change events at its real ts
            session.store.record_applied_writes(applied, ts)
        session.store._bump_write_ver()
        # stats track per durable batch (a later failed batch must not
        # leave committed rows uncounted)
        meta.row_count += len(batch_rows)
        imported += len(batch_rows)
        batch_rows.clear()
        # durable progress marker AFTER the batch lands (resume skips it)
        with open(ckpt_path, "w") as f:
            f.write(str(done + imported))

    with open(path) as f:
        lineno = 0
        data_lineno = 0
        for raw in f:
            lineno += 1
            if lineno <= stmt.ignore_lines:
                continue
            line = raw.rstrip("\n").rstrip("\r")
            if not line:
                continue
            data_lineno += 1
            if data_lineno <= done:
                continue  # resumed past the checkpoint
            fields = _parse_line(line, sep, enc)
            if len(fields) != len(positions):
                raise SQLError(
                    f"LOAD DATA: line {lineno} has {len(fields)} fields, expected {len(positions)}"
                )
            datums = [Datum.NULL] * len(meta.columns)
            name_to_i = {c.name: i for i, c in enumerate(meta.columns)}
            handle = None
            for cm, text in zip(positions, fields):
                if text == "\x00NULL" or text == "\\N":
                    d = Datum.NULL
                else:
                    d = _coerce_datum(Datum.string(text), cm.ft)
                datums[name_to_i[cm.name]] = d
                if meta.handle_col == cm.name and not d.is_null():
                    handle = int(d.val)
                    meta.observe_handle(handle)
            if handle is None:
                handle = meta.alloc_handle()
                if meta.handle_col is not None:
                    i = name_to_i[meta.handle_col]
                    datums[i] = Datum.i64(handle)
            batch_rows.append((handle, datums))
            if len(batch_rows) >= BATCH:
                flush()
    flush()
    if os.path.exists(ckpt_path):
        os.remove(ckpt_path)  # complete: clear the resume marker
    return imported
