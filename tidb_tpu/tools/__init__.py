"""Ecosystem tools (ref: dumpling/, pkg/lightning, br/):

  dump.py       logical export to CSV/SQL at one consistent snapshot
  lightning.py  bulk import (LOAD DATA) writing KV directly with a
                resumable checkpoint file
  br.py         physical backup/restore of the KV snapshot + schema with
                per-segment checksums and resume
"""

from .br import backup, restore
from .dump import dump_all, dump_table
from .lightning import load_data

__all__ = ["backup", "restore", "dump_all", "dump_table", "load_data"]
