"""Physical backup/restore — the BR analog (ref: br/pkg/backup snapshot
SST export, br/pkg/restore ingest, br/pkg/checkpoint resumable progress).

Backup walks the whole KV space at one snapshot ts and writes fixed-size
segments of length-prefixed (key, value) records, each with a SHA-256
recorded in `manifest.json` alongside the full schema (table ids, columns,
indices, autoid cursors) and the snapshot ts. A crashed backup resumes:
segments already on disk with matching checksums are skipped. Restore
recreates the schema with the ORIGINAL ids (keys embed them) and ingests
the segments at a fresh commit ts, verifying each checksum first."""

from __future__ import annotations

import hashlib
import json
import os
import struct

from ..sql.catalog import ColumnMeta, IndexMeta, TableMeta
from ..types import Collation, Datum, DatumKind, FieldType, Flag, MyDecimal, MyTime, TypeCode

SEGMENT_KEYS = 4096


def _ft_to_dict(ft: FieldType) -> dict:
    return {
        "tp": int(ft.tp), "flag": int(ft.flag), "flen": ft.flen,
        "decimal": ft.decimal, "charset": ft.charset, "collate": int(ft.collate),
    }


def _ft_from_dict(d: dict) -> FieldType:
    return FieldType(
        TypeCode(d["tp"]), Flag(d["flag"]), d["flen"], d["decimal"],
        d["charset"], Collation(d["collate"]),
    )


def _datum_to_dict(d) -> dict | None:
    if d is None:
        return None
    if d.is_null():
        return {"k": "null"}
    if d.kind == DatumKind.MysqlDecimal:
        return {"k": "dec", "v": str(d.val), "s": d.val.scale}
    if d.kind == DatumKind.MysqlTime:
        return {"k": "time", "v": d.val.packed, "fsp": d.val.fsp}
    if d.kind == DatumKind.Bytes:
        return {"k": "bytes", "v": d.val.decode("latin1")}
    if d.kind == DatumKind.Uint64:
        return {"k": "u64", "v": d.val}
    if d.kind in (DatumKind.Float32, DatumKind.Float64):
        return {"k": "f64", "v": float(d.val)}
    if d.kind == DatumKind.String:
        return {"k": "str", "v": d.val}
    return {"k": "i64", "v": int(d.val)}


def _datum_from_dict(d: dict | None):
    if d is None:
        return None
    k = d["k"]
    if k == "null":
        return Datum.NULL
    if k == "dec":
        return Datum.dec(MyDecimal(d["v"], d["s"]))
    if k == "time":
        return Datum.time(MyTime(d["v"], d.get("fsp", 0)))
    if k == "bytes":
        return Datum.bytes_(d["v"].encode("latin1"))
    if k == "u64":
        return Datum.u64(d["v"])
    if k == "f64":
        return Datum.f64(d["v"])
    if k == "str":
        return Datum.string(d["v"])
    return Datum.i64(d["v"])


def _schema_dict(catalog) -> list:
    out = []
    for name in catalog.tables():
        if name.startswith("mysql."):
            continue  # system schema excluded, like BR's default filter
        m = catalog.table(name)
        out.append({
            "name": m.name,
            "table_id": m.table_id,
            "handle_col": m.handle_col,
            "row_count": m.row_count,
            "next_handle": m.peek_handle(),  # cursor survives the round trip
            "next_col_id": m.next_col_id,
            "columns": [
                {"name": c.name, "col_id": c.col_id, "ft": _ft_to_dict(c.ft),
                 "origin_default": _datum_to_dict(c.origin_default),
                 "auto_increment": c.auto_increment}
                for c in m.columns
            ],
            "indices": [
                {"name": i.name, "index_id": i.index_id, "col_names": i.col_names,
                 "unique": i.unique, "state": i.state}
                for i in m.indices
            ],
            "partition": None if m.partition is None else {
                "method": m.partition.method,
                "col": m.partition.col,
                "parts": [{"name": p.name, "pid": p.pid, "upper": p.upper}
                          for p in m.partition.parts],
            },
        })
    return out


def _views_dict(catalog) -> dict:
    return {
        v.name: {"columns": v.columns, "select": v.select_sql}
        for v in catalog.view_snapshot()
    }


def backup(store, catalog, dest_dir: str) -> dict:
    """Full backup; returns the manifest. Resumable: re-running skips
    segments whose files already verify."""
    os.makedirs(dest_dir, exist_ok=True)
    ts = store.next_ts()
    manifest_path = os.path.join(dest_dir, "manifest.json")
    prior = {}
    if os.path.exists(manifest_path):
        try:
            prior = {s["file"]: s["sha256"] for s in json.load(open(manifest_path)).get("segments", [])}
        except (ValueError, KeyError):
            prior = {}
    segments = []
    seg_idx = 0
    buf = bytearray()
    count = 0
    n_keys = 0

    def flush():
        nonlocal seg_idx, buf, count
        if not count:
            return
        fname = f"seg-{seg_idx:06d}.bak"
        digest = hashlib.sha256(bytes(buf)).hexdigest()
        fpath = os.path.join(dest_dir, fname)
        if prior.get(fname) == digest and os.path.exists(fpath):
            pass  # resume: identical segment already durable
        else:
            with open(fpath + ".tmp", "wb") as f:
                f.write(bytes(buf))
            os.replace(fpath + ".tmp", fpath)
        segments.append({"file": fname, "sha256": digest, "keys": count})
        seg_idx += 1
        buf = bytearray()
        count = 0

    # pin the snapshot while copying: a concurrent GC pass must not
    # collect versions the backup's read view still needs (ISSUE 20
    # satellite — the unpinned ts let run_gc race the scan)
    store.register_snapshot(ts)
    try:
        for key, val in store.kv.scan(b"", b"\xff" * 40, ts):
            # live values only: kv.scan filters tombstones, so the format
            # has no delete representation (a full backup needs none)
            buf += struct.pack("<I", len(key)) + key
            buf += struct.pack("<I", len(val)) + val
            count += 1
            n_keys += 1
            if count >= SEGMENT_KEYS:
                flush()
        flush()
    finally:
        store.unregister_snapshot(ts)
    manifest = {
        "snapshot_ts": ts,
        "total_keys": n_keys,
        "schema": _schema_dict(catalog),
        "views": _views_dict(catalog),
        "segments": segments,
    }
    with open(manifest_path + ".tmp", "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(manifest_path + ".tmp", manifest_path)
    return manifest


def restore(store, catalog, src_dir: str) -> dict:
    """Restore a backup into an (empty-enough) store/catalog. Table names
    already present in the catalog are an error — no silent merges."""
    manifest = json.load(open(os.path.join(src_dir, "manifest.json")))
    existing = set(catalog.tables())
    for t in manifest["schema"]:
        if t["name"] in existing:
            raise ValueError(f"restore: table {t['name']!r} already exists")
    # schema first (original ids — the KV bytes embed them)
    for t in manifest["schema"]:
        cols = [
            ColumnMeta(
                c["name"], c["col_id"], _ft_from_dict(c["ft"]),
                auto_increment=c.get("auto_increment", False),
                origin_default=_datum_from_dict(c.get("origin_default")),
            )
            for c in t["columns"]
        ]
        idxs = [IndexMeta(i["name"], i["index_id"], list(i["col_names"]), i["unique"],
                          i.get("state", "public")) for i in t["indices"]]
        meta = TableMeta(t["name"], t["table_id"], cols, idxs, t["handle_col"])
        pd = t.get("partition")
        if pd is not None:
            from ..sql.catalog import PartitionDef, PartitionInfo

            meta.partition = PartitionInfo(
                pd["method"], pd["col"],
                [PartitionDef(p["name"], p["pid"], p["upper"]) for p in pd["parts"]],
            )
        meta.row_count = t["row_count"]
        meta._next_handle = t["next_handle"]
        if t.get("next_col_id"):
            meta.next_col_id = t["next_col_id"]
        with catalog._lock:
            catalog._tables[t["name"]] = meta
            catalog.version += 1
    from ..sql.catalog import ViewMeta

    for vn in manifest.get("views", {}):
        if vn in existing or catalog.view_of(vn) is not None:
            raise ValueError(f"restore: view {vn!r} already exists")
    for vn, vd in manifest.get("views", {}).items():
        with catalog._lock:
            catalog.views[vn] = ViewMeta(vn, vd["columns"], vd["select"])
            catalog.version += 1
    max_id = 0
    for t in manifest["schema"]:
        ids = [t["table_id"]] + [i["index_id"] for i in t["indices"]]
        ids += [p["pid"] for p in (t.get("partition") or {}).get("parts", [])]
        max_id = max(max_id, *ids)
    catalog.ensure_id_above(max_id)
    n = 0
    # the restore ts is drawn INSIDE the CDC WriteGuard window so the
    # resolved-ts sampler counts the whole restore as an in-flight write:
    # a frontier candidate can never pass the restore ts before its
    # change events are delivered (the guard nests fine around
    # bulk_ingest's own writing() bracket — it is a plain counter)
    with store.cdc.guard.writing():
        ts = store.next_ts()
        # pin the ingest ts while copying (released on completion OR
        # failure): a GC pass racing a half-done restore must not collect
        # at or above the versions still being written (ISSUE 20
        # satellite)
        store.register_snapshot(ts)
        try:
            for seg in manifest["segments"]:
                data = open(os.path.join(src_dir, seg["file"]), "rb").read()
                if hashlib.sha256(data).hexdigest() != seg["sha256"]:
                    raise ValueError(f"restore: checksum mismatch in {seg['file']}")
                pos = 0
                batch = []
                for _ in range(seg["keys"]):
                    (klen,) = struct.unpack_from("<I", data, pos)
                    pos += 4
                    key = data[pos : pos + klen]
                    pos += klen
                    (vlen,) = struct.unpack_from("<I", data, pos)
                    pos += 4
                    val = data[pos : pos + vlen]
                    pos += vlen
                    batch.append((bytes(key), bytes(val)))
                # restore must not overwrite keys locked by an in-flight
                # 2PC: lock-check + apply in one engine critical section
                # (ADVICE r2)
                store.txn.bulk_ingest(batch, ts)
                n += len(batch)
        finally:
            store.unregister_snapshot(ts)
    store._bump_write_ver()
    return {"tables": len(manifest["schema"]), "keys": n, "snapshot_ts": manifest["snapshot_ts"]}
