"""Logical export — the Dumpling analog (ref: dumpling/export/dump.go:
one snapshot ts for every table gives a consistent dump; writer.go file
formats). Produces `<table>-schema.sql` plus `<table>.csv` or `<table>.sql`
per table."""

from __future__ import annotations

import os

from ..types import Datum, DatumKind, TypeCode


def _type_sql(ft) -> str:
    if ft.is_int():
        return "BIGINT UNSIGNED" if ft.is_unsigned() else "BIGINT"
    if ft.tp == TypeCode.Double:
        return "DOUBLE"
    if ft.tp == TypeCode.Float:
        return "FLOAT"
    if ft.is_decimal():
        return f"DECIMAL({ft.flen if ft.flen > 0 else 20},{max(ft.decimal, 0)})"
    if ft.is_time():
        return "DATETIME" if max(ft.decimal, 0) == 0 else f"DATETIME({ft.decimal})"
    if ft.is_string():
        return f"VARCHAR({ft.flen if ft.flen > 0 else 255})"
    return "BIGINT"


def schema_sql(meta) -> str:
    cols = []
    for c in meta.columns:
        line = f"  `{c.name}` {_type_sql(c.ft)}"
        if c.name == meta.handle_col:
            line += " PRIMARY KEY"
        elif c.ft.flag & 1:  # NotNull
            line += " NOT NULL"
        cols.append(line)
    for idx in meta.indices:
        kind = "UNIQUE KEY" if idx.unique else "KEY"
        cols.append(f"  {kind} `{idx.name}` ({', '.join('`' + c + '`' for c in idx.col_names)})")
    return f"CREATE TABLE `{meta.name}` (\n" + ",\n".join(cols) + "\n);\n"


def _cell_csv(d: Datum) -> str:
    if d.is_null():
        return "\\N"
    s = str(d.val)
    if any(ch in s for ch in ',"\n\\'):
        return '"' + s.replace('"', '""') + '"'
    return s


def _cell_sql(d: Datum) -> str:
    if d.is_null():
        return "NULL"
    if d.kind in (DatumKind.Int64, DatumKind.Uint64, DatumKind.Float64, DatumKind.Float32):
        return str(d.val)
    if d.kind == DatumKind.MysqlDecimal:
        return str(d.val)
    s = str(d.val).replace("\\", "\\\\").replace("'", "''")
    return f"'{s}'"


def dump_table(session, table: str, out_dir: str, fmt: str = "csv",
               snapshot_ts: int | None = None, batch: int = 256) -> dict:
    """Dump one table at a snapshot. Returns {rows, schema_path, data_path}."""
    os.makedirs(out_dir, exist_ok=True)
    meta = session.catalog.table(table)
    ts = snapshot_ts if snapshot_ts is not None else session.store.next_ts()
    rows = [r for _, r in session._scan_rows_with_handles(meta, None, ts)]
    schema_path = os.path.join(out_dir, f"{meta.name}-schema.sql")
    with open(schema_path, "w") as f:
        f.write(schema_sql(meta))
    data_path = os.path.join(out_dir, f"{meta.name}.{'csv' if fmt == 'csv' else 'sql'}")
    with open(data_path, "w") as f:
        if fmt == "csv":
            f.write(",".join(c.name for c in meta.columns) + "\n")
            for r in rows:
                f.write(",".join(_cell_csv(d) for d in r) + "\n")
        else:
            for i in range(0, len(rows), batch):
                part = rows[i : i + batch]
                vals = ",".join("(" + ",".join(_cell_sql(d) for d in r) + ")" for r in part)
                f.write(f"INSERT INTO `{meta.name}` VALUES {vals};\n")
    return {"rows": len(rows), "schema_path": schema_path, "data_path": data_path}


def dump_all(session, out_dir: str, fmt: str = "csv") -> dict:
    """Every table at ONE snapshot ts (Dumpling's consistency contract)."""
    ts = session.store.next_ts()
    out = {}
    for name in session.catalog.tables():
        if name.startswith("mysql."):
            continue  # system schema excluded (Dumpling's default filter)
        out[name] = dump_table(session, name, out_dir, fmt, snapshot_ts=ts)
    return out
