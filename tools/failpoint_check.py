"""Failpoint cross-reference checker + catalog generator — standalone
entrypoint.

Since ISSUE 7 the analysis itself lives in `tidb_tpu/analysis/failpoints.py`
as one tidb-vet pass among peers (`python tools/vet.py --only failpoints`
runs the same check); this shim keeps the historical CLI and module API
(`check()`, `write_catalog()`, `DESCRIPTIONS`, the `_SITE`/`_USE`
patterns) stable for tests and FAILPOINTS.md generation. The pass module
is loaded by FILE PATH — like tools/scrape_check.py does for promparse —
so this tool stays runnable without the engine's jax import.

Usage: `python tools/failpoint_check.py [--catalog [path]]`;
exit 0 clean, exit 1 with one error per line otherwise.
"""

from __future__ import annotations

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "_ttvet_failpoints",
    os.path.join(REPO, "tidb_tpu", "analysis", "failpoints.py"))
_fp = importlib.util.module_from_spec(_spec)
sys.modules["_ttvet_failpoints"] = _fp  # dataclasses resolve __module__
_spec.loader.exec_module(_fp)

# the public API tests import from this module
DESCRIPTIONS = _fp.DESCRIPTIONS
_SITE = _fp._SITE
_USE = _fp._USE
_py_files = _fp._py_files
_scan = _fp._scan
check = _fp.check
write_catalog = _fp.write_catalog


def main(argv: list[str]) -> int:
    errors, sites = check()
    if "--catalog" in argv:
        i = argv.index("--catalog")
        path = argv[i + 1] if i + 1 < len(argv) else os.path.join(REPO, "FAILPOINTS.md")
        write_catalog(sites, path)
        print(f"catalog: {path} ({len(sites)} failpoints)")
    for e in errors:
        print(e, file=sys.stderr)
    if not errors and "--catalog" not in argv:
        print(f"ok: {len(sites)} failpoints defined, all uses resolve")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
