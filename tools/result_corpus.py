"""Result-corpus harness: EXECUTE the reference's integration-test SQL and
diff the output against the recorded golden results
(ref: /root/reference/tests/integrationtest/run-tests.sh feeding t/*.test to
a real tidb-server and diffing r/*.result; VERDICT r3 missing #3 — the
parser-only replay said nothing about result correctness).

For each .test file: statements execute in order through a fresh Session
(oracle evaluation path — tidb_enable_tpu_coprocessor=OFF, so 47k tiny
statements don't each compile an XLA program; kernel-vs-oracle parity is the
device harness's job), results render mysqltest-style (tab-separated, NULL
literal), and each statement is classified:

  match        executed, output block equals the recorded one
  mismatch     executed, output differs (the real parity debt)
  explain_diff executed EXPLAIN/DESC whose plan rendering differs (this
               engine prints its own plan format, not the reference's
               cost-model tree — tracked separately so the data-parity
               rate is not drowned by plan-format noise)
  error_ok     statement under --error failed as the recording expects
  unsupported  raised a parse/plan/SQL "not supported" class error
  exec_error   raised anything else (engine bug surface)
  desync       the runner lost alignment with the .result echo stream
               (remaining statements in the file are skipped, counted here)

Usage:  python tools/result_corpus.py [--dir PATH] [--files a,b,...] [--per-file]
Prints one JSON line with aggregate counts; per-file detail on stderr.
tests/test_result_corpus.py ratchets the match rate over a pinned file set.
"""

from __future__ import annotations

import json
import os
import re
import sys

# hermetic CPU: the environment registers the axon TPU plugin in every
# interpreter and its register() overrides JAX_PLATFORMS=cpu — without the
# factory pop, every Session.execute round-trips the single-client TPU
# tunnel (~174 ms per array fetch; a full sweep took >1h instead of ~2 min)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax  # noqa: E402

try:  # noqa: SIM105
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")  # axon register() overrides the env
jax.config.update("jax_enable_x64", True)

TEST_DIR = "/root/reference/tests/integrationtest/t"
RESULT_DIR = "/root/reference/tests/integrationtest/r"

# control directives that carry no SQL and no result lines
_IGNORED_DIRECTIVES = (
    "disable_warnings", "enable_warnings", "disable_info", "enable_info",
    "replace_regex", "replace_column", "begin_concurrent", "end_concurrent",
    "sleep", "real_sleep", "reap", "send",
)


def parse_test(text: str):
    """mysqltest .test -> ordered items.

    ("stmt", [lines], {"sorted": bool, "error": bool}) | ("echo", text)
    Query/result logging directives are tracked via the flags dict returned
    alongside (per-statement snapshot)."""
    items = []
    sorted_next = False
    error_next = False
    qlog = rlog = True
    buf: list[str] = []
    for raw in text.splitlines():
        line = raw.strip()
        if buf:
            buf.append(raw)
            if line.endswith(";"):
                items.append(("stmt", buf, {"sorted": sorted_next, "error": error_next,
                                            "qlog": qlog, "rlog": rlog}))
                buf, sorted_next, error_next = [], False, False
            continue
        if not line:
            continue
        if line.startswith("--"):
            d = line[2:].strip()
            dl = d.lower()
            if dl.startswith("echo"):
                items.append(("echo", d[4:].lstrip()))
            elif dl.startswith("sorted_result"):
                sorted_next = True
            elif dl.startswith("error"):
                error_next = True
            elif dl.startswith("disable_query_log"):
                qlog = False
            elif dl.startswith("enable_query_log"):
                qlog = True
            elif dl.startswith("disable_result_log"):
                rlog = False
            elif dl.startswith("enable_result_log"):
                rlog = True
            # other directives: ignored
            continue
        if line.startswith("#"):
            continue
        low = line.lower()
        if low.startswith(("connect", "connection", "disconnect", "let ", "eval ",
                           "exec ", "source ", "delimiter", "while", "}", "{",
                           "sleep", "vertical_results", "horizontal_results",
                           "inc ", "dec ")):
            continue
        buf.append(raw)
        if line.endswith(";"):
            items.append(("stmt", buf, {"sorted": sorted_next, "error": error_next,
                                        "qlog": qlog, "rlog": rlog}))
            buf, sorted_next, error_next = [], False, False
    return items


def _strip_leading_comments(sql: str) -> str:
    """tpch.test prefixes every query with a /* Qn ... */ block comment."""
    s = sql.lstrip()
    while s.startswith("/*"):
        end = s.find("*/")
        if end < 0:
            break
        s = s[end + 2 :].lstrip()
    return s


def _norm(line: str) -> str:
    return line.rstrip("\r\n")


def _datum_text(d) -> str:
    """Render one datum the way the MySQL client (and mysqltest) prints it."""
    if d.is_null():
        return "NULL"
    v = d.val
    from tidb_tpu.types import DatumKind, MyDecimal

    if d.kind == DatumKind.MysqlJSON:
        from tidb_tpu.types import json_binary as jb

        return jb.to_text(bytes(v)) if hasattr(jb, "to_text") else str(jb.decode(bytes(v)))
    if isinstance(v, (bytes, bytearray)):
        return bytes(v).decode("utf-8", "replace")
    if isinstance(v, float):
        # MySQL prints DOUBLE shortest-roundtrip-ish; repr matches for the
        # common cases, integers drop the .0, exponents drop the '+'
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v).replace("e+", "e")
    if isinstance(v, MyDecimal):
        return str(v)
    return str(v)


def _results_recode(text: str, session) -> str:
    """Model character_set_results: the server would encode result text into
    the client charset; mysqltest recorded those BYTES into the .result file,
    which this runner reads back as UTF-8-with-replacement. Reproducing the
    same transform makes gbk-session recordings comparable."""
    try:
        cs = session.sysvars.get("character_set_results").lower()
    except Exception:
        return text
    if cs in ("", "utf8", "utf8mb4", "binary"):
        return text
    codec = {"gbk": "gbk", "gb2312": "gb2312", "gb18030": "gb18030",
             "latin1": "latin-1", "ascii": "ascii", "big5": "big5"}.get(cs)
    if codec is None:
        return text
    return text.encode(codec, "replace").decode("utf-8", "replace")


def execute_one(session, sql: str):
    """-> (header_line, row_lines) or raises."""
    res = session.execute(sql)
    if res is None or not getattr(res, "columns", None):
        return None, []
    header = "\t".join(res.columns)
    rows = []
    for r in res.rows:
        text = _results_recode("\t".join(_datum_text(d) for d in r), session)
        # cells may embed newlines (SHOW CREATE TABLE): mysqltest prints
        # them literally, so the recording has them as separate lines
        rows.extend(text.split("\n"))
    return header, rows


UNSUPPORTED_PAT = re.compile(
    r"not supported|unsupported|unknown system variable|no such|not implemented",
    re.I,
)


SAMPLES_CAP = 8


def run_file(name: str, test_dir: str = TEST_DIR, result_dir: str = RESULT_DIR):
    """Execute one corpus file; returns per-class counts + mismatch samples."""
    from tidb_tpu.sql import Session

    test_path = os.path.join(test_dir, name + ".test")
    res_path = os.path.join(result_dir, name + ".result")
    items = parse_test(open(test_path, encoding="utf-8", errors="replace").read())
    rlines = [_norm(x) for x in open(res_path, encoding="utf-8", errors="replace").read().splitlines()]

    s = Session()
    # oracle path: semantics-parity run, no per-shape XLA compiles
    s.sysvars.set("tidb_enable_tpu_coprocessor", "OFF")
    # the reference harness runs each file in a database named after it
    # (run-tests.sh creates DATABASE `$file` and connects to it)
    s.execute(f"create database if not exists `{name}`")
    s.execute(f"use `{name}`")

    counts = {"match": 0, "mismatch": 0, "explain_diff": 0, "error_ok": 0,
              "unsupported": 0, "exec_error": 0, "desync": 0}
    samples: list = []
    cap = SAMPLES_CAP
    cur = 0  # cursor into rlines

    def find_echo(stmt_lines):
        """Locate the echo of this statement at/near the cursor; returns the
        index AFTER the echo, or None. mysqltest may re-wrap long
        statements across lines (tpch.result wraps each CREATE TABLE at
        column boundaries), so an exact line-by-line match is followed by
        a whitespace-normalized multi-line fallback."""
        first = stmt_lines[0].strip()
        want_norm = " ".join(" ".join(stmt_lines).split())
        first_tok = want_norm.split(" ", 1)[0]
        for i in range(cur, min(cur + 200, len(rlines))):
            if rlines[i].strip() == first:
                # multi-line statements echo line by line
                j = i
                ok = True
                for sl in stmt_lines:
                    if j >= len(rlines) or rlines[j].strip() != sl.strip():
                        ok = False
                        break
                    j += 1
                if ok:
                    return j
            # wrapped echo: join result lines until the normalized texts
            # agree (or diverge)
            if rlines[i].strip().startswith(first_tok):
                acc = ""
                for j in range(i, min(i + 80, len(rlines))):
                    acc = (acc + " " + rlines[j].strip()).strip()
                    accn = " ".join(acc.split())
                    if accn == want_norm:
                        return j + 1
                    if not want_norm.startswith(accn):
                        break
        return None

    n_stmt = sum(1 for it in items if it[0] == "stmt")
    stmts = [it for it in items if it[0] == "stmt"]
    seen = 0
    si = -1
    for item_i, it in enumerate(items):
        if it[0] == "echo":
            # the echo may sit past a mismatched statement's recorded block
            # (cur parks at the block start): scan a bounded window so echo
            # lines are consumed instead of polluting the next want-block
            for i in range(cur, min(cur + 400, len(rlines))):
                if rlines[i].strip() == it[1].strip():
                    cur = i + 1
                    break
            continue
        _, stmt_lines, mods = it
        si += 1
        seen += 1
        if not mods["qlog"]:
            counts["desync"] += 1  # unecho'd statements can't be aligned
            continue
        after = find_echo(stmt_lines)
        if after is None:
            # lost alignment: count the rest of the file as desync
            counts["desync"] += n_stmt - seen + 1
            break
        cur = after
        # the recorded output block is EVERYTHING up to the next
        # statement's echo (or EOF) — comparing the full block means a
        # strict-prefix engine result (missing rows) is a MISMATCH, not a
        # match (code-review r4: length-sliced compare inflated the rate)
        # the recorded block ends at the next statement echo OR the next
        # --echo emission, whichever comes first (echo text counted as part
        # of a want-block was the '///// SUBQUERY' phantom-mismatch class)
        block_end = len(rlines)
        nxt_firsts = []
        if si + 1 < len(stmts):
            nxt_firsts.append(stmts[si + 1][1][0].strip())
        for later in items[item_i + 1:]:
            if later[0] == "echo":
                nxt_firsts.append(later[1].strip())
                break
        if nxt_firsts:
            for j in range(cur, min(cur + 400, len(rlines))):
                if rlines[j].strip() in nxt_firsts:
                    block_end = j
                    break
        sql = "\n".join(stmt_lines).strip().rstrip(";")
        expect_error = mods["error"]
        try:
            header, rows = execute_one(s, sql)
            if expect_error:
                # recording expects an error message line(s); resync will
                # handle the echoed error text — classify leniently
                counts["mismatch"] += 1
                continue
            got = ([] if header is None else [header] + rows)
            # ALWAYS compare the full recorded block (to the next echo or
            # EOF): a truncated `want` would count missing trailing rows
            # as a match (code-review r4, twice)
            want = rlines[cur:block_end]
            if mods["sorted"] and header is not None and want:
                got = [got[0]] + sorted(got[1:])
                want = [want[0]] + sorted(want[1:])
            if got == want:
                counts["match"] += 1
                cur += len(got)
            elif _strip_leading_comments(sql).lower().startswith(("explain", "desc")):
                counts["explain_diff"] += 1
            else:
                counts["mismatch"] += 1
                if len(samples) < cap:
                    samples.append({"sql": sql[:120], "got": got[:3], "want": want[:3]})
                # leave `cur` at the echo point; the next find_echo scans
                # forward past this statement's recorded output
        except Exception as exc:  # noqa: BLE001
            from tidb_tpu.parser.parser import ParseError

            if expect_error:
                counts["error_ok"] += 1
                # skip the recorded error-message lines via forward resync
            elif isinstance(exc, ParseError) or UNSUPPORTED_PAT.search(str(exc)):
                # grammar-surface gaps are "unsupported", not engine crashes
                counts["unsupported"] += 1
            else:
                counts["exec_error"] += 1
                if len(samples) < cap:
                    samples.append({"sql": sql[:120], "error": str(exc)[:160]})
    return counts, samples


def run_corpus(files=None, test_dir: str = TEST_DIR, result_dir: str = RESULT_DIR,
               per_file: bool = False):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    if files is None:
        files = sorted(
            fn[:-5] for fn in os.listdir(test_dir)
            if fn.endswith(".test") and os.path.exists(os.path.join(result_dir, fn[:-5] + ".result"))
        )
    total = {"match": 0, "mismatch": 0, "explain_diff": 0, "error_ok": 0,
             "unsupported": 0, "exec_error": 0, "desync": 0}
    details = {}
    for name in files:
        try:
            counts, samples = run_file(name, test_dir, result_dir)
        except Exception as exc:  # noqa: BLE001 — a broken file must not kill the run
            counts, samples = {k: 0 for k in total}, [{"file_error": str(exc)[:200]}]
        for k, v in counts.items():
            total[k] += v
        details[name] = {"counts": counts, "samples": samples}
    executed = sum(total.values()) - total["desync"]
    matched = total["match"] + total["error_ok"]
    rate = matched / executed if executed else 0.0
    non_explain = executed - total["explain_diff"]
    return {
        "files": len(files),
        **total,
        "executed": executed,
        "match_rate": round(rate, 4),
        "data_match_rate": round(matched / non_explain, 4) if non_explain else 0.0,
        "details": details if per_file else None,
    }


def main():
    args = sys.argv[1:]
    files = None
    per_file = False
    test_dir = TEST_DIR
    while args:
        a = args.pop(0)
        if a == "--files":
            files = args.pop(0).split(",")
        elif a == "--per-file":
            per_file = True
        elif a == "--dir":
            test_dir = args.pop(0)
    r = run_corpus(files, test_dir=test_dir, per_file=per_file)
    d = r.pop("details", None)
    print(json.dumps(r))
    if d:
        for name, info in sorted(d.items(), key=lambda kv: -kv[1]["counts"]["mismatch"]):
            c = info["counts"]
            print(f"  {name:40s} match={c['match']:4d} mismatch={c['mismatch']:4d} "
                  f"explain={c['explain_diff']:4d} "
                  f"unsup={c['unsupported']:4d} err={c['exec_error']:4d} desync={c['desync']:4d}",
                  file=sys.stderr)
            for smp in info["samples"][:2]:
                print(f"      {smp}", file=sys.stderr)


if __name__ == "__main__":
    main()
