"""Zero-dependency Prometheus text-exposition (v0.0.4) validator.

The test suite runs `validate()` against `Registry.dump()` so an exposition
regression — a missing `# TYPE`, a non-cumulative `_bucket` series, a
`+Inf` bucket that disagrees with `_count` — fails tier-1 instead of
silently breaking every scraper pointed at `GET /metrics`.

Checks (the subset of the format spec an in-process registry can violate):
  * line grammar: `# HELP`/`# TYPE` comments, `name{labels} value` samples
  * metric/label name charsets, label value quoting
  * `# TYPE` precedes its samples and appears at most once per family
  * counter samples are finite and non-negative
  * histogram families expose `_bucket`/`_sum`/`_count`; bucket counts are
    cumulative (non-decreasing in `le` order) per label group; the `+Inf`
    bucket exists and equals `_count`

The metric-name / label grammar is shared with the `metrics` vet pass via
`tidb_tpu/analysis/promparse.py` — ONE parser for both the lint-time and
scrape-time halves of the exposition contract, so they cannot drift.

Usage: `python tools/scrape_check.py [file]` (stdin when no file);
exit 0 clean, exit 1 with one error per line otherwise.
"""

from __future__ import annotations

import importlib.util
import math
import os
import re
import sys

# load the shared grammar by path (not `import tidb_tpu...`) so this tool
# stays runnable without the engine's jax import
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "_tt_promparse", os.path.join(_REPO, "tidb_tpu", "analysis", "promparse.py"))
_promparse = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_promparse)

_NAME = _promparse.METRIC_NAME
_LABEL = _promparse.LABEL_NAME
_TYPES = _promparse.EXPOSITION_TYPES
_parse_labels = _promparse.parse_labels


def _split_sample(line: str, errs: list, ln: int):
    """-> (name, labels-dict, value) or None."""
    if "{" in line:
        name, rest = line.split("{", 1)
        if "}" not in rest:
            errs.append(f"line {ln}: missing closing brace")
            return None
        labels_s, _, tail = rest.rpartition("}")
        labels = _parse_labels(labels_s, errs, ln)
    else:
        parts = line.split(None, 1)
        if len(parts) != 2:
            errs.append(f"line {ln}: sample needs a name and a value: {line!r}")
            return None
        name, tail = parts
        labels = {}
    name = name.strip()
    fields = tail.split()
    if not fields or len(fields) > 2:  # optional timestamp rides after value
        errs.append(f"line {ln}: expected 'value [timestamp]' after name: {line!r}")
        return None
    if not _NAME.match(name):
        errs.append(f"line {ln}: invalid metric name {name!r}")
        return None
    for k in labels:
        if not _LABEL.match(k):
            errs.append(f"line {ln}: invalid label name {k!r}")
    try:
        value = float(fields[0])
    except ValueError:
        errs.append(f"line {ln}: unparseable value {fields[0]!r}")
        return None
    return name, labels, value


def validate(text: str) -> list[str]:
    """All format violations found, [] when the exposition is clean."""
    errs: list[str] = []
    types: dict[str, str] = {}
    seen_samples: set = set()
    series_keys: set = set()
    samples: list[tuple[str, dict, float, int]] = []
    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                continue  # arbitrary comments are legal
            name = parts[2]
            if not _NAME.match(name):
                errs.append(f"line {ln}: invalid metric name in comment: {name!r}")
                continue
            if parts[1] == "TYPE":
                typ = parts[3].strip() if len(parts) > 3 else ""
                if typ not in _TYPES:
                    errs.append(f"line {ln}: unknown TYPE {typ!r} for {name}")
                if name in types:
                    errs.append(f"line {ln}: duplicate # TYPE for {name}")
                if name in seen_samples:
                    errs.append(f"line {ln}: # TYPE {name} after its samples")
                types[name] = typ
            continue
        parsed = _split_sample(line, errs, ln)
        if parsed is None:
            continue
        name, labels, value = parsed
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        seen_samples.add(name)
        seen_samples.add(base)
        samples.append((name, labels, value, ln))
        key = (name, tuple(sorted(labels.items())))
        if key in series_keys:
            errs.append(f"line {ln}: duplicate series {name}{labels}")
        series_keys.add(key)
        typ = types.get(name) or types.get(base)
        if typ == "counter" and (value < 0 or math.isnan(value)):
            errs.append(f"line {ln}: counter {name} has invalid value {value}")
    _check_histograms(types, samples, errs)
    return errs


def _check_histograms(types: dict, samples: list, errs: list) -> None:
    for base, typ in types.items():
        if typ != "histogram":
            continue
        # group the family's series by their non-le label set
        groups: dict[tuple, dict] = {}
        for name, labels, value, ln in samples:
            if name not in (f"{base}_bucket", f"{base}_sum", f"{base}_count"):
                continue
            rest = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
            g = groups.setdefault(rest, {"buckets": [], "sum": None, "count": None})
            if name.endswith("_bucket"):
                if "le" not in labels:
                    errs.append(f"line {ln}: {base}_bucket without an le label")
                    continue
                try:
                    ub = float(labels["le"])
                except ValueError:
                    errs.append(f"line {ln}: bad le value {labels['le']!r}")
                    continue
                g["buckets"].append((ub, value, ln))
            elif name.endswith("_sum"):
                g["sum"] = value
            else:
                g["count"] = value
        if not groups:
            errs.append(f"histogram {base} declared but exposes no samples")
        for rest, g in groups.items():
            where = f"{base}{{{','.join(f'{k}={v}' for k, v in rest)}}}"
            if g["count"] is None or g["sum"] is None:
                errs.append(f"{where}: histogram missing _sum or _count")
            buckets = sorted(g["buckets"])
            if not buckets:
                errs.append(f"{where}: histogram has no _bucket samples")
                continue
            prev = -1.0
            for ub, v, ln in buckets:
                if v < prev:
                    errs.append(
                        f"line {ln}: {where} bucket le={ub} count {v} < previous {prev} (not cumulative)"
                    )
                prev = v
            inf = [v for ub, v, _ in buckets if math.isinf(ub)]
            if not inf:
                errs.append(f"{where}: histogram missing the +Inf bucket")
            elif g["count"] is not None and inf[0] != g["count"]:
                errs.append(
                    f"{where}: +Inf bucket {inf[0]} != _count {g['count']}"
                )


def main(argv: list[str]) -> int:
    text = open(argv[1], encoding="utf-8").read() if len(argv) > 1 else sys.stdin.read()
    errors = validate(text)
    for e in errors:
        print(e, file=sys.stderr)
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
