"""Parser corpus harness: replay the reference's integration-test SQL
through parse_one and report the pass rate
(ref: /root/reference/tests/integrationtest/t/*.test — the golden-file
corpus run-tests.sh feeds to a real tidb-server; VERDICT r2 weak #8: the
parser must be validated against it, not only self-authored tests).

Usage:  python tools/parser_corpus.py [--top N] [--dir PATH]
Prints one JSON line: {"total", "ok", "rate", "failures": {class: count}}.
tests/test_parser_corpus.py runs this in-process and ratchets the rate.
"""

from __future__ import annotations

import json
import os
import re
import sys

DEFAULT_DIR = "/root/reference/tests/integrationtest/t"

# mysqltest directives and CLIENT commands — not SQL the server parses
# (run-tests.sh intercepts these; ref: mysqltest command reference)
_SKIP_PREFIXES = (
    "--",  # echo/error/enable_warnings/replace_regex/sorted_result...
    "#",
    "delimiter",
    "connect",  # connect (conn1,...)
    "connection",
    "disconnect",
    "sleep",
    "let ",
    "eval ",
    "exec ",
    "source ",
    "vertical_results",
    "horizontal_results",
)


def extract_statements(text: str) -> list[str]:
    """Pull SQL statements out of a mysqltest .test file: strip directive
    and comment lines, join continuation lines until the trailing `;`."""
    stmts: list[str] = []
    buf: list[str] = []
    for raw in text.splitlines():
        line = raw.strip()
        if not buf:
            if not line or line.lower().startswith(_SKIP_PREFIXES):
                continue
        buf.append(raw)
        if line.endswith(";"):
            stmt = "\n".join(buf).strip().rstrip(";").strip()
            buf = []
            if stmt:
                stmts.append(stmt)
    return stmts


def classify_failure(stmt: str, exc: Exception) -> str:
    """Bucket failures by leading keyword(s) — the fix-priority signal."""
    words = re.findall(r"[A-Za-z_]+", stmt.upper())
    head = " ".join(words[:2]) if words else "<empty>"
    return head


def run_corpus(corpus_dir: str = DEFAULT_DIR, per_file: bool = False):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tidb_tpu.parser.parser import parse

    total = ok = 0
    failures: dict[str, int] = {}
    examples: dict[str, str] = {}
    file_stats: dict[str, tuple[int, int]] = {}
    for root, _dirs, files in os.walk(corpus_dir):
        for fn in sorted(files):
            if not fn.endswith(".test"):
                continue
            path = os.path.join(root, fn)
            try:
                text = open(path, encoding="utf-8", errors="replace").read()
            except OSError:
                continue
            f_total = f_ok = 0
            for stmt in extract_statements(text):
                total += 1
                f_total += 1
                try:
                    parse(stmt)  # a chunk may hold several ;-separated stmts
                    ok += 1
                    f_ok += 1
                except Exception as exc:  # noqa: BLE001 — tally, don't die
                    key = classify_failure(stmt, exc)
                    failures[key] = failures.get(key, 0) + 1
                    examples.setdefault(key, stmt[:160])
            file_stats[os.path.relpath(path, corpus_dir)] = (f_ok, f_total)
    rate = ok / total if total else 0.0
    return {
        "total": total,
        "ok": ok,
        "rate": round(rate, 4),
        "failures": dict(sorted(failures.items(), key=lambda kv: -kv[1])),
        "examples": examples,
        "files": file_stats if per_file else None,
    }


def main():
    top = 25
    corpus_dir = DEFAULT_DIR
    args = sys.argv[1:]
    while args:
        a = args.pop(0)
        if a == "--top":
            top = int(args.pop(0))
        elif a == "--dir":
            corpus_dir = args.pop(0)
    r = run_corpus(corpus_dir)
    print(json.dumps({"total": r["total"], "ok": r["ok"], "rate": r["rate"]}))
    print(f"\npass rate: {r['ok']}/{r['total']} = {r['rate']*100:.1f}%", file=sys.stderr)
    print(f"top {top} failure classes:", file=sys.stderr)
    for k, n in list(r["failures"].items())[:top]:
        print(f"  {n:6d}  {k:30s}  e.g. {r['examples'][k][:90]!r}", file=sys.stderr)


if __name__ == "__main__":
    main()
