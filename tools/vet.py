"""tidb-vet driver — run the repo's static-analysis suite and fail CI on
any finding (ISSUE 7; the `go vet` / nogo analog for this codebase).

Usage:
    python tools/vet.py              # human output, exit 1 on findings
    python tools/vet.py --json       # machine output (diffable across
                                     # commits: stable path/line/pass keys)
    python tools/vet.py --only PASS  # one pass (repeatable)
    python tools/vet.py --files F..  # run every pass over exactly these
                                     # files (fixture corpora; failpoints
                                     # checks their arms vs live sites)
    python tools/vet.py --list       # pass catalog

Passes live in tidb_tpu/analysis/ (one module per pass; ANALYZERS.md is
the human catalog). tools/failpoint_check.py remains the standalone
entrypoint for the failpoints pass + FAILPOINTS.md generation.
Suppress a finding with `# vet: ignore[<pass>]` on (or just above) the
flagged line.

Run by tier-1 via tests/test_tools.py and tests/test_vet.py.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv: list[str]) -> int:
    from tidb_tpu import analysis

    if "--list" in argv:
        for name, (mod, roots) in analysis.PASSES.items():
            scope = ", ".join(roots) if roots else "(self-scoped)"
            print(f"{name:16s} {scope}")
        return 0
    only = [argv[i + 1] for i, a in enumerate(argv)
            if a == "--only" and i + 1 < len(argv)]
    unknown = [p for p in only if p not in analysis.PASSES]
    if unknown:
        print(f"unknown pass(es): {', '.join(unknown)} — see --list", file=sys.stderr)
        return 2
    if "--files" in argv:
        from tidb_tpu.analysis.common import load_files

        paths = [a for a in argv[argv.index("--files") + 1:] if not a.startswith("--")]
        files = load_files(os.path.abspath(p) for p in paths)
        findings = []
        for p in (only or list(analysis.PASSES)):
            findings.extend(analysis.run_pass(p, files))
        findings.sort(key=lambda f: (f.path, f.line, f.passname))
    elif only:
        findings: list = []
        for p in only:
            findings.extend(analysis.run_pass(p))
        findings.sort(key=lambda f: (f.path, f.line, f.passname))
    else:
        findings = analysis.run_all()
    if "--json" in argv:
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render(), file=sys.stderr)
        if not findings:
            ran = ", ".join(only) if only else ", ".join(analysis.PASSES)
            print(f"ok: 0 findings ({ran})")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
