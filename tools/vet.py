"""tidb-vet driver — run the repo's static-analysis suite and fail CI on
any finding (ISSUE 7 seeded it; ISSUE 9 added the interprocedural
dataflow passes, the jaxpr auditor, result caching and baseline diffing;
the `go vet` / nogo analog for this codebase).

Usage:
    python tools/vet.py                  # human output, exit 1 on findings
    python tools/vet.py --json           # machine output (stable, sorted —
                                         # diffable across commits)
    python tools/vet.py --only PASS      # one pass (repeatable; globs ok:
                                         # --only 'dataflow-*')
    python tools/vet.py --files F..      # run every pass over exactly these
                                         # files (fixture corpora)
    python tools/vet.py --baseline FILE  # write current findings to FILE
                                         # (stable sorted JSON), exit 0
    python tools/vet.py --diff FILE      # compare against a baseline: print
                                         # {"new": [...], "fixed": [...]},
                                         # exit 1 only on NEW findings
    python tools/vet.py --list           # pass catalog

Passes live in tidb_tpu/analysis/ (ANALYZERS.md is the human catalog).
Results cache per file revision in .vet_cache.json; suppress a finding
with `# vet: ignore[<pass>]` on (or just above) the flagged line — the
`suppressions` pass flags markers that no longer suppress anything.

Run by tier-1 via tests/test_tools.py and tests/test_vet.py.
"""

from __future__ import annotations

import fnmatch
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _flag_value(argv: list[str], flag: str) -> str | None:
    if flag in argv:
        i = argv.index(flag)
        if i + 1 < len(argv):
            return argv[i + 1]
    return None


def _expand_only(argv: list[str], names) -> tuple[list[str], list[str]]:
    """--only values (repeatable, glob-capable) -> (matched, unknown)."""
    pats = [argv[i + 1] for i, a in enumerate(argv)
            if a == "--only" and i + 1 < len(argv)]
    matched: list[str] = []
    unknown: list[str] = []
    for p in pats:
        hits = [n for n in names if fnmatch.fnmatch(n, p)]
        if hits:
            matched.extend(h for h in hits if h not in matched)
        else:
            unknown.append(p)
    return matched, unknown


def _diff_key(d: dict) -> tuple:
    # line-agnostic: pure line drift between commits is not a new finding
    return (d["path"], d["pass"], d["message"])


def _diff_sets(base: list, cur: list) -> tuple[list, list]:
    """Multiset comparison: a SECOND instance of an identical defect in
    the same file is a new finding even though its key already exists
    (a plain set-diff would wave it through the CI gate)."""
    from collections import Counter

    base_n = Counter(_diff_key(d) for d in base)
    cur_n = Counter(_diff_key(d) for d in cur)
    new: list = []
    seen: Counter = Counter()
    for d in cur:
        k = _diff_key(d)
        seen[k] += 1
        if seen[k] > base_n.get(k, 0):
            new.append(d)
    fixed: list = []
    seen = Counter()
    for d in base:
        k = _diff_key(d)
        seen[k] += 1
        if seen[k] > cur_n.get(k, 0):
            fixed.append(d)
    return sorted(new, key=_diff_key), sorted(fixed, key=_diff_key)


def main(argv: list[str]) -> int:
    from tidb_tpu import analysis

    if "--list" in argv:
        for name, spec in analysis.PASSES.items():
            scope = ", ".join(spec.roots) if spec.roots else "(self-scoped)"
            print(f"{name:22s} {scope}")
        print(f"{analysis.SUPPRESSIONS:22s} (stale-marker audit; --only runs the full suite)")
        return 0
    only, unknown = _expand_only(
        argv, list(analysis.PASSES) + [analysis.SUPPRESSIONS])
    if unknown:
        print(f"unknown pass(es): {', '.join(unknown)} — see --list", file=sys.stderr)
        return 2
    if "--files" in argv:
        from tidb_tpu.analysis.common import load_files

        # value flags and their arguments are NOT input files — without
        # this, `--files a.py --baseline out.json` would analyze the
        # baseline JSON as source
        consumed: set = set()
        for flag in ("--baseline", "--diff", "--only"):
            for i, a in enumerate(argv):
                if a == flag:
                    consumed.add(i)
                    consumed.add(i + 1)
        paths = [a for i, a in enumerate(argv[argv.index("--files") + 1:],
                                         argv.index("--files") + 1)
                 if not a.startswith("--") and i not in consumed]
        files = load_files(os.path.abspath(p) for p in paths)
        findings = []
        for p in (only or list(analysis.PASSES)):
            findings.extend(analysis.run_pass(p, files))
        findings.sort(key=lambda f: (f.path, f.line, f.passname))
    elif only and analysis.SUPPRESSIONS in only:
        # the stale-marker audit needs every other pass's verdict: run
        # the full suite and keep the selected passes' findings
        keep = set(only)
        findings = [f for f in analysis.run_all() if f.passname in keep]
    elif only:
        findings = analysis.run_only(only)
    else:
        findings = analysis.run_all()

    dicts = [f.to_dict() for f in findings]
    baseline_path = _flag_value(argv, "--baseline")
    if baseline_path is not None:
        with open(baseline_path, "w", encoding="utf-8") as fh:
            json.dump(dicts, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"baseline: {len(dicts)} finding(s) -> {baseline_path}")
        return 0
    diff_path = _flag_value(argv, "--diff")
    if diff_path is not None:
        try:
            base = json.load(open(diff_path, encoding="utf-8"))
            if not isinstance(base, list):
                raise ValueError("baseline must be a JSON array of findings")
        except (OSError, ValueError) as exc:
            # a missing/corrupt baseline must be distinguishable from
            # "new findings found" (exit 1) — CI consumers branch on it
            print(f"unusable baseline {diff_path!r}: {exc}", file=sys.stderr)
            return 2
        new, fixed = _diff_sets(base, dicts)
        print(json.dumps({"new": new, "fixed": fixed}, indent=2, sort_keys=True))
        return 1 if new else 0
    if "--json" in argv:
        print(json.dumps(dicts, indent=2))
    else:
        for f in findings:
            print(f.render(), file=sys.stderr)
        if not findings:
            ran = ", ".join(only) if only else ", ".join(analysis.ALL_PASS_NAMES)
            print(f"ok: 0 findings ({ran})")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
